"""Multi-tenant adapter serving (ISSUE 20 tentpole): paged LoRA store
with tiered spill, batched gather-LoRA in the unified window, and live
base-weight hot-swap.

The load-bearing contracts:
- paged gather-LoRA output == offline ``merge_lora`` weights
  token-for-token (the jnp reference path), per tenant, INCLUDING the
  int8 KV cache, prefix cache on, speculative decoding, and across
  preemption/resume with the adapter demoted to a cold tier in between;
- adapter-less rows skip the LoRA pass exactly (base trace unchanged);
- prefix-cache block hashes are salted by ``adapter_id`` — tenants
  sharing a prompt can never hit each other's cached KV;
- an unknown ``adapter_id`` fails TYPED (4xx + counter), never a 500;
- ``adapter.load`` chaos (deny/corrupt) fails only the targeted
  tenant's requests (or degrades them to base per
  ``serving.adapters.fallback_to_base``) — other tenants stay
  token-identical;
- ``Router.swap_weights`` rolls the fleet one replica at a time with
  zero failed requests and a ``weights_version`` label on /metrics.
"""
import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.resilience.faults import FaultInjector
from deepspeed_tpu.runtime.config import ServingConfig
from deepspeed_tpu.runtime.lora import init_lora_params, merge_lora
from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                   RequestState, SamplingParams)
from deepspeed_tpu.serving.adapters import (AdapterRegistry,
                                            adapters_enabled,
                                            load_adapter_file,
                                            save_adapter)
from deepspeed_tpu.serving.request import UnknownAdapterError
from tests.util import tiny_gpt2


@pytest.fixture(autouse=True)
def _debug_invariant(monkeypatch):
    """DS_SERVE_DEBUG stays armed across this suite: every step asserts
    the block-pool invariant AND the AdapterStore invariants (slot
    bijection, pin census vs live requests, single-tier residency)."""
    monkeypatch.setenv("DS_SERVE_DEBUG", "1")


@pytest.fixture(scope="module")
def served():
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    return m, eng


def _mixed_prompts(n=3, seed=0, lo=4, hi=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 128, (int(L),)).astype(np.int32)
            for L in rng.integers(lo, hi, n)]


def _mk_lora(params, seed, rank=4):
    """A fresh adapter with a RANDOMIZED B (init_lora_params zeros B so
    merged == base — useless for distinguishing tenants)."""
    lora = init_lora_params(params, rank=rank, rng=jax.random.PRNGKey(seed))
    r2 = np.random.default_rng(seed)
    return {p: {"a": np.asarray(ab["a"]),
                "b": r2.normal(0, 0.05, ab["b"].shape).astype(np.float32)}
            for p, ab in lora.items()}


def _merged_reference(m, params, lora, prompt, max_new, scale=1.0,
                      cfg=None, kv_cache_dtype=None):
    """The offline-merge parity oracle: a base-only scheduler over
    ``merge_lora``-ed weights."""
    mp = (merge_lora(params, lora, scale, freeze_base=False)
          if lora else params)
    cfg = cfg or ServingConfig(block_size=8, num_blocks=64, max_num_seqs=4)
    s = ContinuousBatchingScheduler(m, mp, cfg,
                                    kv_cache_dtype=kv_cache_dtype)
    r = s.submit(prompt, SamplingParams(max_new_tokens=max_new))
    s.run_until_idle()
    assert r.state == RequestState.FINISHED
    return list(r.output_ids)


def _adapter_cfg(**kw):
    ad = kw.pop("adapters", {})
    ad.setdefault("enabled", True)
    ad.setdefault("max_hbm_adapters", 2)
    base = dict(block_size=8, num_blocks=64, max_num_seqs=4,
                adapters=ad)
    base.update(kw)
    return ServingConfig(**base)


# ----------------------------------------------------------- registry unit
def test_adapter_registry_validation(served):
    m, eng = served
    reg = AdapterRegistry(max_rank=4)
    lora = _mk_lora(eng.params, 1, rank=4)
    man = reg.register("A", lora)
    assert man.rank == 4 and man.scale == 1.0
    assert set(man.targets) == {"qkv_w", "proj_w"}
    assert man.crc32 and man.nbytes > 0
    assert "A" in reg and reg.get("A") is man
    with pytest.raises(ValueError, match="already registered"):
        reg.register("A", lora)
    with pytest.raises(ValueError, match="rank"):
        reg.register("big", _mk_lora(eng.params, 2, rank=6))
    with pytest.raises(ValueError, match="no target arrays"):
        reg.register("empty", {})
    # alpha rescales: scale = alpha / rank
    man2 = reg.register("B", _mk_lora(eng.params, 3), alpha=8.0)
    assert man2.scale == 2.0
    # take_arrays pops exactly once (paging owns the bytes after)
    assert reg.take_arrays("A") is not None
    assert reg.take_arrays("A") is None
    reg.unregister("A")
    assert "A" not in reg


def test_adapter_file_roundtrip(served, tmp_path):
    m, eng = served
    lora = _mk_lora(eng.params, 5)
    path = save_adapter(str(tmp_path / "t.npz"), lora, alpha=8.0)
    tree, alpha = load_adapter_file(path)
    assert alpha == 8.0
    for p, ab in lora.items():
        t = p.split("/")[-1]
        np.testing.assert_array_equal(tree[t]["a"], ab["a"])
        np.testing.assert_array_equal(tree[t]["b"], ab["b"])
    reg = AdapterRegistry(max_rank=8)
    man = reg.register_file("T", path)
    assert man.rank == 4 and man.scale == 2.0 and man.source == path


# ----------------------------------------------------------- config plumbing
def test_adapters_config_roundtrip(tmp_path):
    cfg = ServingConfig(adapters={"enabled": True, "max_hbm_adapters": 3,
                                  "max_rank": 16,
                                  "adapters": {"a": "/x/a.npz"},
                                  "slo_class_map": {"a": "strict"},
                                  "fallback_to_base": True,
                                  "max_host_adapters": 5,
                                  "nvme_dir": str(tmp_path)})
    ad = cfg.adapters
    assert ad.enabled and ad.max_hbm_adapters == 3 and ad.max_rank == 16
    assert ad.adapters == {"a": "/x/a.npz"}
    assert ad.slo_class_map == {"a": "strict"}
    assert ad.fallback_to_base and ad.max_host_adapters == 5
    assert not ServingConfig().adapters.enabled       # off by default
    with pytest.raises(ValueError, match="max_hbm_adapters"):
        ServingConfig(adapters={"max_hbm_adapters": 0})
    with pytest.raises(ValueError, match="max_rank"):
        ServingConfig(adapters={"max_rank": 0})
    with pytest.raises(ValueError, match="slo_class_map"):
        ServingConfig(adapters={"slo_class_map": ["a"]})
    with pytest.raises(ValueError, match="adapters.adapters"):
        ServingConfig(adapters={"adapters": ["a"]})


def test_adapters_env_override(monkeypatch):
    cfg = ServingConfig(adapters={"enabled": True}).adapters
    assert adapters_enabled(cfg)
    monkeypatch.setenv("DS_ADAPTERS", "0")
    assert not adapters_enabled(cfg)
    monkeypatch.setenv("DS_ADAPTERS", "1")
    assert adapters_enabled(ServingConfig().adapters)


# ----------------------------------------------------- store paging + tiers
def test_adapter_store_paging_and_spill(served, tmp_path):
    """Direct store drive: ingest -> host, host-cap overflow spills
    oldest to NVMe, swap-in demotes the LRU refcount-0 resident, a
    pinned adapter is never a victim — and the invariant checker signs
    off after every transition."""
    from deepspeed_tpu.runtime.config import AdaptersConfig
    from deepspeed_tpu.serving.adapters import AdapterStore
    m, eng = served
    reg = AdapterRegistry(max_rank=4)
    cfg = AdaptersConfig(enabled=True, max_hbm_adapters=1, max_rank=4,
                         max_host_adapters=1, nvme_dir=str(tmp_path))
    # block shapes straight off the tiny model's stacked params
    shapes = {t: tuple(np.shape(eng.params["blocks"][t]))
              for t in ("qkv_w", "proj_w")}
    st = AdapterStore(reg, cfg, shapes)
    try:
        for i, aid in enumerate(("A", "B", "C")):
            reg.register(aid, _mk_lora(eng.params, 10 + i))
            assert st.ingest(aid)
            st.check_invariant()
        s = st.summary()
        # host cap 1: A and B spilled onward to NVMe oldest-first
        assert s["host_adapters"] == 1 and s["nvme_adapters"] == 2
        assert s["spills"] == 2
        assert st.residency_digest() == {"A": "nvme", "B": "nvme",
                                         "C": "host"}
        # swap A in from NVMe
        assert st.schedule_swapin("A")
        assert st.swap_in("A") == ("ok", 0)
        assert st.resident("A") and st.slot_of("A") == 0
        st.check_invariant()
        # pinned A blocks the only slot: B must wait, not demote it
        st.acquire("A")
        assert st.swap_in("B") == ("wait", None)
        assert st.summary()["slot_waits"] == 1
        # released -> refcount-0 A is the LRU victim for B's swap-in
        st.release("A")
        status, slot = st.swap_in("B")
        assert status == "ok" and slot == 0
        assert not st.resident("A")
        st.check_invariant()
        s = st.summary()
        assert s["demotions"] == 1 and s["swap_ins"] == 2
        assert st.residency_digest()["A"] in ("host", "nvme")
        # round-trip integrity: A re-materializes bit-exact
        st.release("B")
        assert st.swap_in("A")[0] == "ok"
        st.check_invariant()
    finally:
        st.close()


# ------------------------------------------------------------------ parity
def test_adapter_parity_paged_vs_merged(served):
    """Acceptance: batched gather-LoRA (mixed tenants + a base row in
    ONE window program) == per-tenant offline-merged weights,
    token-for-token, prefix cache on."""
    m, eng = served
    loraA, loraB = _mk_lora(eng.params, 1), _mk_lora(eng.params, 2)
    cfg = _adapter_cfg(prefix_cache={"enabled": True})
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    s.register_adapter("A", lora_tree=loraA)
    s.register_adapter("B", lora_tree=loraB)
    prompts = _mixed_prompts(3, seed=1)
    aids = [None, "A", "B"]
    reqs = [s.submit(p, SamplingParams(max_new_tokens=6), adapter_id=a)
            for p, a in zip(prompts, aids)]
    s.run_until_idle()
    ref_cfg = ServingConfig(block_size=8, num_blocks=64, max_num_seqs=4,
                            prefix_cache={"enabled": True})
    for p, a, r in zip(prompts, aids, reqs):
        assert r.state == RequestState.FINISHED
        lora = {"A": loraA, "B": loraB}.get(a)
        assert list(r.output_ids) == _merged_reference(
            m, eng.params, lora, p, 6, cfg=ref_cfg)
    # both adapters came up through the paging tiers (ingest -> host ->
    # demand swap-in), not via some side door
    assert s.adapter_store.summary()["swap_ins"] == 2
    assert 'weights_version="v1"' in s.render_metrics()


def test_adapter_parity_int8_kv(served):
    """Same parity with the quantized KV-cache pool: both sides see the
    same activations, so the int8 round-trip stays token-identical."""
    m, _ = served
    eng8 = deepspeed_tpu.init_inference(
        model=m, config={"dtype": "float32", "kv_cache_dtype": "int8"})
    loraA = _mk_lora(eng8.params, 3)
    cfg = _adapter_cfg()
    s = ContinuousBatchingScheduler(m, eng8.params, cfg,
                                    kv_cache_dtype="int8")
    s.register_adapter("A", lora_tree=loraA)
    prompts = _mixed_prompts(2, seed=4)
    reqs = [s.submit(p, SamplingParams(max_new_tokens=5), adapter_id=a)
            for p, a in zip(prompts, [None, "A"])]
    s.run_until_idle()
    ref_cfg = ServingConfig(block_size=8, num_blocks=64, max_num_seqs=4)
    for p, a, r in zip(prompts, [None, "A"], reqs):
        assert list(r.output_ids) == _merged_reference(
            m, eng8.params, loraA if a else None, p, 5, cfg=ref_cfg,
            kv_cache_dtype="int8")


def test_adapter_batch_invariance_int8_weights(served):
    """int8 WEIGHTS x adapters: the fp32 LoRA delta rides on the
    fused-dequant base matmul, so the merged-weights oracle doesn't
    apply (quantization isn't linear) — the contract here is batch
    invariance: a mixed multi-tenant window == the same requests run
    solo, token-for-token."""
    m, eng = served
    engq = deepspeed_tpu.init_inference(
        model=m, config={"dtype": "float32", "quant": {"enabled": True}})
    # quantized leaves are QuantizedTensors — derive the adapter from
    # the fp32 tree (same logical shapes)
    loraA = _mk_lora(eng.params, 6)
    prompts = _mixed_prompts(2, seed=5)
    aids = [None, "A"]

    def run(batched):
        s = ContinuousBatchingScheduler(m, engq.params, _adapter_cfg())
        s.register_adapter("A", lora_tree=loraA)
        outs = []
        if batched:
            reqs = [s.submit(p, SamplingParams(max_new_tokens=5),
                             adapter_id=a)
                    for p, a in zip(prompts, aids)]
            s.run_until_idle()
            outs = [list(r.output_ids) for r in reqs]
        else:
            for p, a in zip(prompts, aids):
                r = s.submit(p, SamplingParams(max_new_tokens=5),
                             adapter_id=a)
                s.run_until_idle()
                outs.append(list(r.output_ids))
        return outs

    assert run(batched=True) == run(batched=False)


def test_adapter_parity_spec_decode(served):
    """Speculative decoding x adapters: greedy spec parity holds per
    tenant against the merged-weights oracle (draft/verify both see the
    gather-LoRA pass)."""
    m, eng = served
    loraA = _mk_lora(eng.params, 7)
    cfg = _adapter_cfg(spec={"mode": "ngram", "max_draft_tokens": 4},
                       max_num_batched_tokens=256)
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    s.register_adapter("A", lora_tree=loraA)
    prompts = _mixed_prompts(2, seed=8, lo=6, hi=10)
    reqs = [s.submit(p, SamplingParams(max_new_tokens=8), adapter_id=a)
            for p, a in zip(prompts, [None, "A"])]
    s.run_until_idle()
    for p, a, r in zip(prompts, [None, "A"], reqs):
        assert r.state == RequestState.FINISHED
        assert list(r.output_ids) == _merged_reference(
            m, eng.params, loraA if a else None, p, 8)


def test_adapter_preempt_resume_with_cold_tier(served, tmp_path):
    """Preempt/resume x paging: pool pressure preempts the low-priority
    tenant, its adapter demotes through host toward NVMe while it sits
    queued, and the resumed stream still matches the merged oracle —
    recompute-on-resume swap-ins the adapter back from the cold tier."""
    m, eng = served
    loraA, loraB = _mk_lora(eng.params, 11), _mk_lora(eng.params, 12)
    cfg = ServingConfig(
        block_size=4, num_blocks=8, max_num_seqs=2,
        max_num_batched_tokens=64,
        adapters={"enabled": True, "max_hbm_adapters": 2,
                  "max_host_adapters": 1, "nvme_dir": str(tmp_path)})
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    s.register_adapter("A", lora_tree=loraA)
    s.register_adapter("B", lora_tree=loraB)
    # host cap 1: B's ingest already pushed A onward to NVMe
    assert s.adapter_store.summary()["nvme_adapters"] >= 1
    pa, pb = _mixed_prompts(2, seed=6, lo=6, hi=7)
    ra = s.submit(pa, SamplingParams(max_new_tokens=10), priority=1,
                  adapter_id="A")
    rb = s.submit(pb, SamplingParams(max_new_tokens=10), priority=0,
                  adapter_id="B")
    s.run_until_idle()
    assert s.metrics.counters["preemptions"] >= 1
    assert rb.num_preemptions >= 1            # lower priority = victim
    for p, lora, r in ((pa, loraA, ra), (pb, loraB, rb)):
        assert r.state == RequestState.FINISHED
        assert list(r.output_ids) == _merged_reference(
            m, eng.params, lora, p, 10)
    st = s.adapter_store.summary()
    assert st["swap_ins"] >= 2                # both tenants materialized
    assert s.block_mgr.num_allocated_blocks == 0
    # eviction released every pin
    assert s.adapter_store.refcounts() == {}


# --------------------------------------------------- cross-tenant isolation
def test_prefix_salt_prevents_cross_tenant_hits(served):
    """Regression: UNSALTED chain hashes for two tenants sharing a
    prompt are identical (they WOULD collide — one tenant would serve
    from the other's KV); the adapter_id salt separates them, and the
    end-to-end outputs match each tenant's own oracle even when tenant
    B replays tenant A's exact prompt against a warm cache."""
    from deepspeed_tpu.serving.block_manager import BlockManager
    tokens = (1, 2, 3, 4)
    unsalted = BlockManager._chain_hash(None, tokens)
    assert unsalted == BlockManager._chain_hash(None, tokens)
    a = BlockManager._chain_hash(None, tokens, salt="A")
    b = BlockManager._chain_hash(None, tokens, salt="B")
    assert len({unsalted, a, b}) == 3

    m, eng = served
    loraA, loraB = _mk_lora(eng.params, 21), _mk_lora(eng.params, 22)
    cfg = _adapter_cfg(prefix_cache={"enabled": True})
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    s.register_adapter("A", lora_tree=loraA)
    s.register_adapter("B", lora_tree=loraB)
    prompt = _mixed_prompts(1, seed=9, lo=10, hi=11)[0]
    # wave 1: tenant A commits its blocks into the cache
    r1 = s.submit(prompt, SamplingParams(max_new_tokens=5),
                  adapter_id="A")
    s.run_until_idle()
    # wave 2: same prompt as B, as base, and as A again
    r2 = s.submit(prompt, SamplingParams(max_new_tokens=5),
                  adapter_id="B")
    r3 = s.submit(prompt, SamplingParams(max_new_tokens=5))
    r4 = s.submit(prompt, SamplingParams(max_new_tokens=5),
                  adapter_id="A")
    s.run_until_idle()
    for lora, r in ((loraA, r1), (loraB, r2), (None, r3), (loraA, r4)):
        assert list(r.output_ids) == _merged_reference(
            m, eng.params, lora, prompt, 5)
    # A's replay hit its own salted prefix; B/base could not
    assert s.metrics.counters["prefix_cache_hit"] >= 1


# ------------------------------------------------------- typed failure paths
def test_unknown_adapter_rejects_typed(served):
    m, eng = served
    s = ContinuousBatchingScheduler(m, eng.params, _adapter_cfg())
    prompt = _mixed_prompts(1, seed=3)[0]
    with pytest.raises(UnknownAdapterError):
        s.submit(prompt, SamplingParams(max_new_tokens=2),
                 adapter_id="nope")
    assert s.metrics.counters["adapter_unknown"] == 1
    # adapters disabled entirely: same typed error, never a crash
    s2 = ContinuousBatchingScheduler(
        m, eng.params, ServingConfig(block_size=8, num_blocks=32))
    with pytest.raises(UnknownAdapterError):
        s2.submit(prompt, SamplingParams(max_new_tokens=2),
                  adapter_id="anything")


def test_adapter_chaos_deny_and_corrupt(served):
    """adapter.load chaos during swap-in: the targeted tenant fails
    TYPED (reject + counters at /debug); corruption quarantines the key
    through the PR 18 integrity contract; the OTHER tenant's stream is
    token-identical throughout."""
    m, eng = served
    loraA, loraB = _mk_lora(eng.params, 31), _mk_lora(eng.params, 32)
    s = ContinuousBatchingScheduler(m, eng.params, _adapter_cfg())
    s.register_adapter("A", lora_tree=loraA)
    s.register_adapter("B", lora_tree=loraB)
    pa, pb = _mixed_prompts(2, seed=13)
    # let tenant A materialize cleanly, THEN arm the deny storm so it
    # gates only B's swap-in
    ra = s.submit(pa, SamplingParams(max_new_tokens=5), adapter_id="A")
    while not s.adapter_store.resident("A"):
        s.step()
    s.adapter_store.injector = FaultInjector("adapter.load:deny@*")
    rb = s.submit(pb, SamplingParams(max_new_tokens=5), adapter_id="B")
    s.run_until_idle()
    s.adapter_store.injector = FaultInjector([])
    assert ra.state == RequestState.FINISHED
    assert list(ra.output_ids) == _merged_reference(
        m, eng.params, loraA, pa, 5)
    assert rb.state == RequestState.REJECTED
    assert "failed to load" in rb.reject_reason
    assert s.metrics.counters["adapter_rejects"] >= 1
    dbg = s.debug_scheduler()["adapters"]
    assert dbg["load_failures"] >= 1

    # corruption at ingest -> integrity failure + quarantine at swap-in
    s.adapter_store.injector = FaultInjector("adapter.load:corrupt=4@*")
    s.register_adapter("C", lora_tree=_mk_lora(eng.params, 33))
    s.adapter_store.injector = FaultInjector([])
    rc = s.submit(pa, SamplingParams(max_new_tokens=3), adapter_id="C")
    s.run_until_idle()
    assert rc.state == RequestState.REJECTED
    dbg = s.debug_scheduler()["adapters"]
    assert dbg["integrity_failures"] >= 1 and dbg["quarantined"] >= 1


def test_adapter_chaos_fallback_to_base(served):
    """serving.adapters.fallback_to_base: the failed tenant degrades to
    the BASE model (flagged on the response) instead of rejecting."""
    m, eng = served
    loraA = _mk_lora(eng.params, 41)
    cfg = _adapter_cfg(adapters={"fallback_to_base": True})
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    s.register_adapter("A", lora_tree=loraA)
    p = _mixed_prompts(1, seed=14)[0]
    s.adapter_store.injector = FaultInjector("adapter.load:deny@*")
    r = s.submit(p, SamplingParams(max_new_tokens=5), adapter_id="A")
    s.run_until_idle()
    s.adapter_store.injector = FaultInjector([])
    assert r.state == RequestState.FINISHED
    assert r.adapter_fallback and r.adapter_id is None
    assert list(r.output_ids) == _merged_reference(
        m, eng.params, None, p, 5)
    assert s.metrics.counters["adapter_fallbacks"] == 1
    assert r.to_response()["adapter_fallback"] is True


# ------------------------------------------------------------- QoS mapping
def test_adapter_slo_class_mapping(served):
    """Per-tenant SLO classes: adapter_id maps onto the ISSUE 9 QoS
    ladder when the request doesn't name a class itself."""
    m, eng = served
    cfg = _adapter_cfg(
        adapters={"slo_class_map": {"A": "strict"}},
        slo={"classes": {"strict": {"ttft_ms": 50, "weight": 4.0}}})
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    s.register_adapter("A", lora_tree=_mk_lora(eng.params, 51))
    s.register_adapter("B", lora_tree=_mk_lora(eng.params, 52),
                       slo_class="strict")   # manifest-registered class
    p = _mixed_prompts(1, seed=15)[0]
    ra = s.submit(p, SamplingParams(max_new_tokens=2), adapter_id="A")
    rb = s.submit(p, SamplingParams(max_new_tokens=2), adapter_id="B")
    rc = s.submit(p, SamplingParams(max_new_tokens=2), adapter_id="A",
                  slo_class="default")
    assert ra.slo_class == "strict" and rb.slo_class == "strict"
    assert rc.slo_class == "strict"
    s.run_until_idle()
    text = s.render_metrics()
    assert 'weights_version="v1"' in text
    # per-tenant completion counter, labeled by adapter
    assert 'adapter="A"' in text and 'adapter="B"' in text


# ------------------------------------------------------------- HTTP surface
def test_http_generate_adapter_end_to_end(served):
    """/generate carries adapter_id; unknown ids are a typed 400 with
    the serving/adapter_unknown counter bumped — never a 500."""
    from deepspeed_tpu.serving.server import make_server
    m, eng = served
    loraA = _mk_lora(eng.params, 61)
    s = ContinuousBatchingScheduler(m, eng.params, _adapter_cfg())
    s.register_adapter("A", lora_tree=loraA)
    httpd, loop = make_server(s, port=0)
    loop.start()
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        prompt = _mixed_prompts(1, seed=16)[0]
        body = json.dumps({"input_ids": prompt.tolist(),
                           "max_new_tokens": 4,
                           "adapter_id": "A"}).encode()
        req = urllib.request.Request(
            base + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            out = json.loads(resp.read())
        assert out["adapter_id"] == "A"
        assert out["output_ids"] == _merged_reference(
            m, eng.params, loraA, prompt, 4)
        bad = json.dumps({"input_ids": prompt.tolist(),
                          "max_new_tokens": 4,
                          "adapter_id": "ghost"}).encode()
        req = urllib.request.Request(
            base + "/generate", data=bad,
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 400
        payload = json.loads(ei.value.read())
        assert payload["unknown_adapter"] is True
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert 'serving_adapter_unknown{weights_version="v1"} 1' in text
    finally:
        httpd.shutdown()
        loop.shutdown()
        httpd.server_close()


def test_ds_serve_adapters_flag(served, tmp_path):
    """--adapters name=path,... lands in serving.adapters and the
    npz round-trips through scheduler construction."""
    import subprocess
    import sys
    m, eng = served
    path = save_adapter(str(tmp_path / "a.npz"),
                        _mk_lora(eng.params, 71))
    r = subprocess.run([sys.executable, "bin/ds_serve", "--help"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0 and "--adapters" in r.stdout
    # the construction path: config names the file, scheduler registers
    # + ingests at build time
    cfg = _adapter_cfg(adapters={"adapters": {"T": path}})
    s = ContinuousBatchingScheduler(m, eng.params, cfg)
    assert "T" in s.adapter_registry.ids()
    assert s.adapter_store.residency_digest()["T"] == "host"


# -------------------------------------------------------- fleet: hot swap
def test_fleet_hot_swap_weights(served):
    """Acceptance: a 2-replica rolling base-weight swap completes with
    ZERO failed requests — in-flight streams extract, resubmit, and
    finish token-identically; every replica lands on the new version
    and /metrics carries the weights_version label."""
    from deepspeed_tpu.serving.fleet.replica import Replica
    from deepspeed_tpu.serving.fleet.router import Router
    from deepspeed_tpu.telemetry.flight_recorder import FlightRecorder
    m, eng = served
    rec = FlightRecorder(4096)
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=2,
                        max_fused_steps=1,
                        adapters={"enabled": True},
                        fleet={"num_replicas": 2, "digest_refresh_s": 0})
    reps = [Replica(i, m, eng.params, cfg, flightrec=rec)
            for i in range(2)]
    router = Router(reps, cfg.fleet, flightrec=rec)
    loraA = _mk_lora(eng.params, 81)
    for rep in reps:
        rep.scheduler.register_adapter("A", lora_tree=loraA)
    prompts = _mixed_prompts(4, seed=17)
    aids = [None, "A", None, "A"]
    handles = [router.submit(p, SamplingParams(max_new_tokens=10),
                             adapter_id=a)
               for p, a in zip(prompts, aids)]
    # commit a few tokens so the roll catches streams mid-flight
    for _ in range(3):
        for rep in reps:
            if rep.scheduler.has_work():
                rep.scheduler.step()
    # value-identical new tree: proves zero-loss mechanics while keeping
    # the token-identity oracle exact for resubmitted streams
    new_params = jax.tree_util.tree_map(lambda x: x, eng.params)
    out = router.swap_weights(new_params, "v2")
    assert out["version"] == "v2"
    assert len(out["replicas"]) == 2
    router.run_until_idle()
    for p, a, h in zip(prompts, aids, handles):
        assert h.state == "finished", h.reject_reason
        assert list(h.output_ids) == _merged_reference(
            m, eng.params, loraA if a else None, p, 10)
    assert router.registry.get_counter("fleet/weight_swaps") == 2
    for rep in reps:
        assert rep.scheduler.weights_version == "v2"
        assert rep.is_accepting()
        assert rep.summary()["weights_version"] == "v2"
    assert 'weights_version="v2"' in router.render_metrics()
    swaps = [e for e in rec.events(corr="swap-v2")
             if e["kind"] == "route/weights_swap"]
    assert len(swaps) == 2
    assert {e["replica"] for e in swaps} == {0, 1}
    # post-roll requests serve on the new version
    h2 = router.submit(prompts[0], SamplingParams(max_new_tokens=3))
    router.run_until_idle()
    assert h2.state == "finished"
    dbg = router.debug_fleet()
    assert dbg["weight_swaps"] == 2
    assert set(dbg["weights_versions"].values()) == {"v2"}


def test_install_params_validates_structure(served):
    """install_params is double-buffered behind a structure check: a
    tree that would recompile (or silently misload) is refused."""
    m, eng = served
    s = ContinuousBatchingScheduler(
        m, eng.params, ServingConfig(block_size=8, num_blocks=32))
    assert s.weights_version == "v1"
    new = jax.tree_util.tree_map(lambda x: x, eng.params)
    s.install_params(new, "v2")
    assert s.weights_version == "v2"
    assert s.metrics.counters["weights_swaps"] == 1
    with pytest.raises(ValueError):
        s.install_params({"not": "a matching tree"}, "v3")
    assert s.weights_version == "v2"


# ---------------------------------------------------------- router digest
def test_router_prefers_adapter_resident_replica(served):
    """Routing digest awareness: with loads tied, the replica already
    holding the tenant's adapter in a hotter tier wins the dispatch."""
    from deepspeed_tpu.serving.fleet.replica import Replica
    from deepspeed_tpu.serving.fleet.router import Router
    m, eng = served
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=2,
                        adapters={"enabled": True},
                        fleet={"num_replicas": 2, "digest_refresh_s": 0})
    reps = [Replica(i, m, eng.params, cfg) for i in range(2)]
    router = Router(reps, cfg.fleet)
    loraA = _mk_lora(eng.params, 91)
    for rep in reps:
        rep.scheduler.register_adapter("A", lora_tree=loraA)
    p = _mixed_prompts(1, seed=18)[0]
    # replica 1 serves tenant A once: its adapter is HBM-resident there
    r = reps[1].scheduler.submit(p, SamplingParams(max_new_tokens=2),
                                 adapter_id="A")
    reps[1].scheduler.run_until_idle()
    assert r.state == RequestState.FINISHED
    assert reps[1].adapter_residency()["A"] == "hbm"
    assert reps[0].adapter_residency()["A"] == "host"
    h = router.submit(p, SamplingParams(max_new_tokens=2),
                      adapter_id="A")
    assert h.replica_id == 1
    router.run_until_idle()
    assert h.state == "finished"
