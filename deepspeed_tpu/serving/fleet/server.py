"""Stdlib HTTP front-end for the replica fleet (``bin/ds_router``,
``ds_serve --replicas N`` — ISSUE 11).

Endpoints:

  POST /generate      same body as the single-replica server plus an
                      optional ``session_id`` (affinity key); proxied
                      through the Router to an in-process replica
                      -> 200 with merged output (+ replica_history),
                      429 (+ Retry-After) on queue-full/shed, 400 on
                      malformed bodies, 503 when no replica is READY
  GET  /healthz       aggregate member states: 200 while ANY replica
                      accepts work, 503 otherwise; per-replica rows
  GET  /metrics       ONE merged Prometheus exposition: the router's
                      fleet/* registry + every replica's registry under
                      a ``replica="<id>"`` label
  GET  /debug/fleet   router + per-replica live state
  GET  /debug/stacks  all-thread stack dump (lock-free, as ever)
  GET  /debug/flightrec  shared flight-recorder ring (?n=/?corr=/?kind=)

Replicas run their own ServingLoops; handler threads dispatch through
the Router and supervise it (``await_result`` polls) while they wait —
the Router needs no thread of its own.
"""
import json
import signal
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from deepspeed_tpu.serving.fleet.replica import Replica
from deepspeed_tpu.serving.fleet.router import (FleetUnavailableError,
                                                Router)
from deepspeed_tpu.serving.request import (AdmissionError, QueueFullError,
                                           RequestShedError,
                                           UnknownAdapterError)
from deepspeed_tpu.serving.server import (parse_generate_body,
                                          send_json_response)
from deepspeed_tpu.utils.logging import logger


def build_fleet(model, params, serving_cfg, num_replicas=None,
                kv_cache_dtype=None, injector=None, flightrec=None,
                monitor=None) -> Router:
    """N replicas over ONE shared model+params (weights are never
    duplicated — each replica owns only its scheduler, KV pool, health,
    and registry), routed by a Router configured from
    ``serving.fleet``."""
    n = int(num_replicas if num_replicas is not None
            else serving_cfg.fleet.num_replicas)
    replicas = [Replica(i, model, params, serving_cfg,
                        kv_cache_dtype=kv_cache_dtype, injector=injector,
                        flightrec=flightrec, monitor=monitor)
                for i in range(n)]
    return Router(replicas, serving_cfg.fleet, injector=injector,
                  flightrec=flightrec)


class _FleetHandler(BaseHTTPRequestHandler):
    # injected by make_fleet_server
    router: Router = None
    default_timeout_s = 0.0

    def log_message(self, fmt, *args):
        logger.debug("ds_router: " + fmt % args)

    def _send_json(self, code: int, payload: dict,
                   retry_after_s: float = None):
        # serving/server.py owns the shape + Retry-After clamp for both
        # front doors
        send_json_response(self, code, payload,
                           retry_after_s=retry_after_s)

    def _send_text(self, code: int, text: str):
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------- routes
    def do_GET(self):
        from deepspeed_tpu.telemetry.debug import (flightrec_payload,
                                                   format_thread_stacks,
                                                   parse_debug_query)
        router = self.router
        if self.path == "/healthz":
            rows = [r.summary() for r in router.replicas]
            accepting = sum(r["accepting"] for r in rows)
            self._send_json(
                200 if accepting else 503,
                {"status": "ok" if accepting else "unavailable",
                 "accepting": accepting, "replicas": rows})
            return
        if self.path == "/metrics":
            self._send_text(200, router.render_metrics())
            return
        route, query = parse_debug_query(self.path)
        if route == "/debug/fleet":
            self._send_json(200, router.debug_fleet())
            return
        if route == "/debug/stacks":
            self._send_text(200, format_thread_stacks())
            return
        if route == "/debug/flightrec":
            self._send_json(200, flightrec_payload(router.flightrec,
                                                   query))
            return
        self._send_json(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        if self.path != "/generate":
            self._send_json(404, {"error": f"no route {self.path}"})
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n) or b"{}")
            parsed = parse_generate_body(body, self.default_timeout_s)
        except (KeyError, TypeError, ValueError, json.JSONDecodeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        router = self.router
        try:
            handle = router.submit(
                parsed["input_ids"], parsed["sampling"],
                priority=parsed["priority"],
                timeout_s=parsed["timeout_s"],
                slo_class=parsed["slo_class"],
                session_id=parsed["session_id"],
                adapter_id=parsed["adapter_id"])
        except FleetUnavailableError as e:
            self._send_json(503, {"error": str(e)})
            return
        except UnknownAdapterError as e:
            # typed 400 (ISSUE 20), same contract as the single-replica
            # front door — never a 500
            self._send_json(400, {"error": str(e),
                                  "unknown_adapter": True})
            return
        except RequestShedError as e:
            self._send_json(429, {"error": str(e), "shed": True},
                            retry_after_s=e.retry_after_s)
            return
        except QueueFullError as e:
            # every replica queue-full: the same Retry-After contract
            # (serving.slo.retry_after_s) as the single-replica server
            self._send_json(
                429, {"error": str(e)},
                retry_after_s=router.replicas[0].scheduler
                .slo.retry_after_s)
            return
        except AdmissionError as e:
            self._send_json(400, {"error": str(e)})
            return
        except (ValueError, TypeError) as e:
            self._send_json(400, {"error": f"bad request: {e}"})
            return
        router.await_result(handle)
        resp = handle.to_response()
        if handle.reject_reason is not None:
            self._send_json(429, resp)
            return
        self._send_json(200, resp)


def make_fleet_server(router: Router, host: str = "127.0.0.1",
                      port: int = 8000, default_timeout_s: float = 0.0):
    """ThreadingHTTPServer over a Router — the caller starts the
    replicas (``router.start()``) and serves.  ``port=0`` binds an
    ephemeral port (tests)."""
    handler = type("FleetHandler", (_FleetHandler,),
                   {"router": router,
                    "default_timeout_s": default_timeout_s})
    return ThreadingHTTPServer((host, port), handler)


def serve_fleet_forever(router: Router, host: str = "127.0.0.1",
                        port: int = 8000, default_timeout_s: float = 0.0,
                        install_signal_handlers: bool = True
                        ):  # pragma: no cover
    """Start every replica's loop and serve HTTP until a drain
    completes.  SIGTERM/SIGINT = whole-fleet drain: every replica
    finishes its admitted work in place (with the whole fleet going
    away there is no healthy member to resubmit to), then the server
    exits.  A second signal stops immediately."""
    router.start()
    httpd = make_fleet_server(router, host, port, default_timeout_s)

    draining = threading.Event()

    def _on_signal(signum, frame):
        if draining.is_set():
            logger.warning(f"ds_router: second signal {signum}; "
                           "stopping now")
            threading.Thread(target=httpd.shutdown, daemon=True).start()
            return
        draining.set()
        router.drain_all(f"signal {signum}")

        def _await_drain():
            for rep in router.replicas:
                rep.join()
            httpd.shutdown()

        threading.Thread(target=_await_drain, daemon=True).start()

    if install_signal_handlers:
        for sig in (signal.SIGTERM, signal.SIGINT):
            signal.signal(sig, _on_signal)
    n = len(router.replicas)
    cfg = router.replicas[0].scheduler.cfg
    logger.info(
        f"ds_router: listening on http://{host}:{httpd.server_port} "
        f"({n} replicas x {cfg.num_blocks}x{cfg.block_size}-token pools, "
        f"policy={router.cfg.policy})")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        router.drain_all("KeyboardInterrupt")
    finally:
        router.shutdown()
        httpd.server_close()
