"""Async I/O handle (reference: deepspeed/ops/aio over csrc/aio — the
``aio_handle`` pybind object with async pread/pwrite + wait)."""
import ctypes
import os
from typing import Optional

import numpy as np

from op_builder import AsyncIOBuilder, load_op


class AsyncIOHandle:
    """Thread-pool async file reader/writer for numpy buffers.

    Mirrors the reference handle API: ``async_pread``/``async_pwrite`` submit
    and return immediately; ``wait()`` blocks until all in-flight requests
    complete and returns the number of failures.
    """

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 single_submit: bool = False, overlap_events: bool = True,
                 thread_count: int = 4):
        self._lib = load_op(AsyncIOBuilder())
        self._lib.ds_aio_handle_new.restype = ctypes.c_void_p
        self._lib.ds_aio_wait.restype = ctypes.c_long
        self._lib.ds_aio_inflight.restype = ctypes.c_long
        self._lib.ds_aio_pread.restype = ctypes.c_int
        self._lib.ds_aio_pwrite.restype = ctypes.c_int
        self._h = ctypes.c_void_p(
            self._lib.ds_aio_handle_new(ctypes.c_int(thread_count)))
        self.block_size = block_size
        self.thread_count = thread_count
        # keep submitted buffers alive until wait()
        self._pinned = []

    def _buf_ptr(self, arr: np.ndarray):
        assert arr.flags.c_contiguous
        return arr.ctypes.data_as(ctypes.c_char_p)

    def async_pread(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self._lib.ds_aio_pread(
            self._h, filename.encode(), self._buf_ptr(buffer),
            ctypes.c_size_t(buffer.nbytes), ctypes.c_size_t(offset))
        if rc == 0:
            self._pinned.append(buffer)
        return rc

    def async_pwrite(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self._lib.ds_aio_pwrite(
            self._h, filename.encode(), self._buf_ptr(buffer),
            ctypes.c_size_t(buffer.nbytes), ctypes.c_size_t(offset))
        if rc == 0:
            self._pinned.append(buffer)
        return rc

    def sync_pread(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self.async_pread(buffer, filename, offset)
        if rc == 0:
            rc = -self.wait()
        return rc

    def sync_pwrite(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self.async_pwrite(buffer, filename, offset)
        if rc == 0:
            rc = -self.wait()
        return rc

    def wait(self) -> int:
        errors = self._lib.ds_aio_wait(self._h)
        self._pinned.clear()
        return int(errors)

    def inflight(self) -> int:
        return int(self._lib.ds_aio_inflight(self._h))

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.ds_aio_handle_free(h)
            except Exception:
                pass
            self._h = None
