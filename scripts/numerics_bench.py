"""Numerics-observatory overhead micro-bench (ISSUE 15 satellite).

A/B of the fused train step with the in-graph numerics stats ON
(per-leaf-group norms + non-finite bitmap + update ratio) vs OFF, on
the ckpt_bench model shapes.  The contract is <2% step-time overhead at
bench shapes on-chip; ``NUMERICS_BENCH_STRICT=1`` enforces it (the
on-chip queue entry — CPU wall-clock at smoke shapes is dominated by
dispatch noise and is reported, not gated).

``NUMERICS_SMOKE=1`` runs tiny shapes/loops — the tier-1 subprocess
smoke.  With ``DS_BENCH_LEDGER=1`` the overhead fraction lands in the
BENCH/ ledger for ``bench_compare --history``.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SMOKE = bool(int(os.environ.get("NUMERICS_SMOKE", "0")))
STRICT = bool(int(os.environ.get("NUMERICS_BENCH_STRICT", "0")))


def build(numerics_on: bool):
    import numpy as np
    import deepspeed_tpu
    from deepspeed_tpu.models.gpt2 import gpt2_model
    import jax
    if SMOKE:
        model = gpt2_model("custom", vocab_size=256, num_layers=2,
                           num_heads=4, d_model=32, max_seq_len=64)
        mbs, seq, warm, meas = max(2, len(jax.devices())), 32, 2, 8
    else:
        model = gpt2_model("350m", max_seq_len=1024, dtype="bfloat16",
                           remat=True)
        mbs, seq, warm, meas = 12, 1024, 3, 10
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": mbs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": not SMOKE},
        "steps_per_print": 0,
        "telemetry": {"numerics": {"enabled": numerics_on}}})
    rng = np.random.default_rng(0)

    def batch():
        return {"input_ids": rng.integers(
            0, model.config.vocab_size,
            size=(1, mbs, seq), dtype=np.int32)}
    return engine, batch, warm, meas


def time_steps(numerics_on: bool) -> float:
    engine, batch, warm, meas = build(numerics_on)
    for _ in range(warm):
        loss = engine.train_batch(batch=batch())
    float(loss)                       # close the warmup window
    t0 = time.time()
    for _ in range(meas):
        loss = engine.train_batch(batch=batch())
    float(loss)
    return (time.time() - t0) / meas


def main() -> int:
    if os.environ.get("DS_NUMERICS", "").strip():
        print("numerics_bench: unset DS_NUMERICS — the env wins over "
              "the per-engine config this A/B flips", file=sys.stderr)
        return 2
    off_s = time_steps(False)
    on_s = time_steps(True)
    overhead = (on_s - off_s) / off_s if off_s > 0 else 0.0
    record = {"metric": "numerics_overhead_fraction",
              "value": round(overhead, 5), "unit": "fraction",
              "direction": "lower_better",
              "detail": {"model": "gpt2:smoke" if SMOKE else "gpt2:350m",
                         "step_s_numerics_off": round(off_s, 5),
                         "step_s_numerics_on": round(on_s, 5),
                         "strict": STRICT}}
    from scripts.bench_util import emit_ledger
    emit_ledger(record)
    print(json.dumps(record))
    if STRICT and overhead >= 0.02:
        print(f"numerics_bench: overhead {overhead:.2%} >= 2% contract",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
