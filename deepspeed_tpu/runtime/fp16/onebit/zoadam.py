"""ZeroOneAdam — the real 0/1 Adam algorithm (reference:
deepspeed/runtime/fp16/onebit/zoadam.py:14, paper arXiv:2202.06009).

Unlike 1-bit Adam's single freeze point, 0/1 Adam runs TWO adaptive
policies:

- **Variance update policy**: the second moment updates only at steps where
  ``step % var_interval == 0``; ``var_interval`` doubles after every
  ``var_update_scaler`` such updates (the kappa rule), until
  ``var_freeze_step`` freezes the variance for good.  At variance-update
  steps the gradient exchange is full-precision (the reference toggles
  ``enable_backward_allreduce``, zoadam.py:273-281); at every other step the
  wire is the 1-bit error-feedback compressed all-reduce.
- **Local step policy** (reference zoadam.py:243-258): after the variance
  freeze the reference lets parameters drift locally between exponentially
  spaced compressed syncs of the accumulated momentum.  Per-device parameter
  drift is not representable in a replicated-SPMD train step (every device
  executes one logical program), so this port keeps the 1-bit exchange
  *every* step after the freeze — the wire stays 1 byte/element and the
  update is communication-exact where the reference's drifts between syncs.
  ``local_step_scaler``/``local_step_clipper`` are accepted for config
  parity and drive the same interval bookkeeping, but no drift occurs.

The update itself follows the reference faithfully: no bias correction
(zoadam.py:237 ``update = exp_avg / (exp_avg_sq.sqrt() + eps)``), decoupled
weight decay added to the update, momentum updated every step with whatever
(dense or compressed) reduced gradient arrived.

The engine's quantized-exchange tier (runtime/engine.py `_qgz_grad_fn`)
mirrors the dense-vs-compressed schedule on the wire; this transform mirrors
it in the moment updates.  Both derive the schedule from the same
(count, var_interval, var_counter) recurrence so they stay in lock-step.
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp
import optax


class ZeroOneAdamState(NamedTuple):
    count: jnp.ndarray          # optimizer steps taken (1-based after update)
    m: optax.Updates
    v: optax.Updates
    var_interval: jnp.ndarray   # current variance-update interval
    var_counter: jnp.ndarray    # updates seen at this interval
    local_interval: jnp.ndarray  # local-step interval bookkeeping (parity)
    local_counter: jnp.ndarray


def var_schedule_step(count, var_interval, var_counter,
                      var_freeze_step: int, var_update_scaler: int):
    """One step of the variance-update policy recurrence.

    Returns (update_var_now, new_interval, new_counter) for 1-based step
    ``count``.  Shared by this transform and the engine's exchange tier so
    the wire format and the moment updates agree step-by-step."""
    frozen = count > var_freeze_step
    update_now = jnp.logical_and(count % var_interval == 0,
                                 jnp.logical_not(frozen))
    bumped = var_counter + jnp.where(update_now, 1, 0)
    roll = bumped >= var_update_scaler
    new_counter = jnp.where(roll, 0, bumped)
    new_interval = jnp.where(roll, var_interval * 2, var_interval)
    return update_now, new_interval, new_counter


def zero_one_adam(learning_rate=1e-3, b1: float = 0.9, b2: float = 0.999,
                  eps: float = 1e-8, weight_decay: float = 0.0,
                  var_freeze_step: int = 100000,
                  var_update_scaler: int = 16,
                  local_step_scaler: int = 32678,
                  local_step_clipper: int = 16):
    """0/1 Adam as an optax GradientTransformation.

    Callers hand in already-reduced gradients; the engine's exchange tier
    decides per step (same recurrence) whether the reduction ran dense or
    1-bit compressed."""

    def init_fn(params):
        z = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                                 params)
        one = jnp.ones((), jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        return ZeroOneAdamState(zero, z(), z(), one, zero, one, zero)

    def update_fn(grads, state, params=None):
        count = state.count + 1
        update_var, new_interval, new_counter = var_schedule_step(
            count, state.var_interval, state.var_counter,
            var_freeze_step, var_update_scaler)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        m = jax.tree.map(lambda mm, g: b1 * mm + (1 - b1) * g, state.m, g32)
        v = jax.tree.map(
            lambda vv, g: jnp.where(update_var,
                                    b2 * vv + (1 - b2) * g * g, vv),
            state.v, g32)
        lr = (learning_rate(count) if callable(learning_rate)
              else learning_rate)
        # reference zoadam.py:237: NO bias correction on either moment
        if weight_decay > 0 and params is not None:
            updates = jax.tree.map(
                lambda mm, vv, p: -lr * (mm / (jnp.sqrt(vv) + eps)
                                         + weight_decay * p),
                m, v, params)
        else:
            updates = jax.tree.map(
                lambda mm, vv: -lr * mm / (jnp.sqrt(vv) + eps), m, v)
        # local-step interval bookkeeping (config parity; see module doc)
        frozen = count > var_freeze_step
        lbump = state.local_counter + jnp.where(frozen, 1, 0)
        lroll = lbump >= local_step_scaler
        new_lcounter = jnp.where(lroll, 0, lbump)
        new_linterval = jnp.where(
            lroll, jnp.minimum(state.local_interval * 2, local_step_clipper),
            state.local_interval)
        return updates, ZeroOneAdamState(count, m, v, new_interval,
                                         new_counter, new_linterval,
                                         new_lcounter)

    return optax.GradientTransformation(init_fn, update_fn)


class ZeroOneAdam:
    """Class shim with the reference's constructor surface."""

    def __init__(self, params=None, deepspeed=None, lr: float = 1e-3,
                 betas=(0.9, 0.999), eps: float = 1e-8,
                 weight_decay: float = 0.0, var_freeze_step: int = 100000,
                 var_update_scaler: int = 16, local_step_scaler: int = 32678,
                 local_step_clipper: int = 16, cuda_aware: bool = False,
                 comm_backend_name: str = "jax", **kw):
        self.transform = zero_one_adam(
            learning_rate=lr, b1=betas[0], b2=betas[1], eps=eps,
            weight_decay=weight_decay, var_freeze_step=var_freeze_step,
            var_update_scaler=var_update_scaler,
            local_step_scaler=local_step_scaler,
            local_step_clipper=local_step_clipper)
