"""Numerics observatory (ISSUE 15): training-health telemetry, NaN
provenance, MoE router health, and determinism fingerprints.

Acceptance (tier-1):

- the in-graph stats are banked LAZILY: a training loop adds zero
  ``jax.device_get`` calls and zero bank resolutions on the hot path
  (the overflow-banking contract, asserted directly);
- an injected ``train.nonfinite`` fault at a known leaf group is
  attributed to exactly that group in ``/debug/numerics`` over live
  HTTP, in the flight recorder, and in the post-mortem bundle's
  ``numerics.json``, and the trace validates with ``anomaly/num_*``
  instants carrying the step corr id;
- restore-from-checkpoint reproduces the save-time fingerprint
  (audited at load), a deliberately perturbed restore is flagged, and
  a save→resume run reproduces the uninterrupted run's fingerprint
  stream bitwise (subprocess, cache-less per the documented jaxlib
  restore-then-train hazard);
- einsum and grouped MoE dispatch publish bitwise-identical router
  health through the opt-in registry tap.
"""
import json
import os
import subprocess
import sys
import urllib.request

import jax
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.config import NumericsConfig, TelemetryConfig
from deepspeed_tpu.telemetry import (MetricsRegistry, get_registry,
                                     numerics_payload, peek_numerics,
                                     reset_numerics, reset_tracer)
from deepspeed_tpu.telemetry.numerics import (NumericsState, group_stats,
                                              leaf_groups,
                                              numerics_enabled,
                                              resolve_fingerprint_interval,
                                              state_fingerprint)
from tests.util import base_config, random_batch, tiny_gpt2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _numerics_isolation():
    reset_numerics()
    yield
    reset_numerics()


def _batch(seed=0):
    # leading gas=1; inner batch 8 divides the virtual 8-device mesh
    return {"input_ids": random_batch(seed=seed)["input_ids"][None]}


def _engine(tmp_path=None, **cfg_overrides):
    cfg = base_config(**cfg_overrides)
    if tmp_path is not None:
        cfg.setdefault("resilience", {})["postmortem_dir"] = str(tmp_path)
    eng, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    return eng


# ---------------------------------------------------------------- units
def test_leaf_groups_names_and_index():
    tree = {"blocks": {"attn_w": np.zeros((2, 3)),
                       "mlp_w": np.zeros((4,))},
            "wte": np.zeros((5,))}
    names, index = leaf_groups(tree, depth=2)
    assert names == ["blocks/attn_w", "blocks/mlp_w", "wte"]
    assert index == [0, 1, 2]
    names1, index1 = leaf_groups(tree, depth=1)
    assert names1 == ["blocks", "wte"]
    assert index1 == [0, 0, 1]


def test_group_stats_norms_and_nonfinite_bitmap():
    import jax.numpy as jnp
    grads = {"a": jnp.asarray([3.0, 4.0]),
             "b": jnp.asarray([[jnp.nan, 1.0], [jnp.inf, 2.0]])}
    names, index = leaf_groups(grads, depth=1)
    norms, counts = group_stats(grads, index, len(names))
    norms, counts = np.asarray(norms), np.asarray(counts)
    assert norms[0] == pytest.approx(5.0)
    assert not np.isfinite(norms[1])           # NaN/Inf poison the norm
    assert counts.tolist() == [0, 2]           # provenance bitmap
    # structure mismatch degrades to None, never a wrong attribution
    assert group_stats(grads, [0], 1) is None


def test_state_fingerprint_sensitivity():
    params = {"w": np.arange(12, dtype=np.float32).reshape(3, 4),
              "b": np.ones((4,), np.float32)}
    rng = np.asarray([1, 2], np.uint32)
    d0 = state_fingerprint(params, rng, step=5)
    assert d0 == state_fingerprint(params, rng, step=5)   # deterministic
    p2 = {"w": params["w"].copy(), "b": params["b"]}
    p2["w"][1, 2] += 1e-6                    # any sampled element flips it
    assert state_fingerprint(p2, rng, step=5) != d0
    assert state_fingerprint(params, np.asarray([1, 3], np.uint32),
                             step=5) != d0   # rng chain is digested
    assert state_fingerprint(params, rng, step=6) != d0   # step too
    assert state_fingerprint(params, rng, step=5, loss=1.0) != d0


def test_numerics_config_roundtrip_and_env_wins(monkeypatch):
    t = TelemetryConfig(numerics={"fingerprint_interval": 8,
                                  "group_depth": 3, "history": 64})
    assert t.numerics.enabled and t.numerics.fingerprint_interval == 8
    assert t.numerics.group_depth == 3 and t.numerics.history == 64
    # bool shorthand matches telemetry.memory's spelling
    assert TelemetryConfig(numerics=False).numerics.enabled is False
    with pytest.raises(ValueError):
        NumericsConfig(fingerprint_interval=-1)
    with pytest.raises(ValueError):
        NumericsConfig(group_depth=0)
    with pytest.raises(ValueError):
        NumericsConfig(history=4)
    monkeypatch.setenv("DS_NUMERICS", "0")
    assert numerics_enabled(True) is False
    monkeypatch.setenv("DS_NUMERICS", "1")
    assert numerics_enabled(False) is True
    monkeypatch.delenv("DS_NUMERICS")
    assert numerics_enabled(None) is True
    monkeypatch.setenv("DS_FINGERPRINT_INTERVAL", "16")
    assert resolve_fingerprint_interval(4) == 16
    monkeypatch.delenv("DS_FINGERPRINT_INTERVAL")
    assert resolve_fingerprint_interval(4) == 4


def test_overflow_handled_provenance_no_postmortem():
    fired = []
    st = NumericsState(["g0", "g1"], registry=MetricsRegistry(),
                       on_nonfinite=fired.append)
    st.bank(1, grad_norm=np.float32(0.0), overflow=np.bool_(True),
            loss=np.float32(2.0), loss_scale=np.float32(1024.0),
            group_norms=np.asarray([0.0, np.inf], np.float32),
            nonfinite=np.asarray([0, 3], np.int32),
            update_ratio=np.float32(0.0))
    st.resolve()
    # handled (overflow) records ride their own rolling tail — they
    # must never consume the first-N unexpected-incident ring
    assert st.nonfinite_records() == []
    handled = st.handled_nonfinite_records()
    assert len(handled) == 1 and handled[0]["handled"] is True
    assert handled[0]["first_group"] == "g1"
    assert st.nonfinite_overflow_steps == 1 and st.nonfinite_steps == 0
    assert fired == []        # loss-scaler skips never trigger a bundle
    # unexpected flavor: counted separately, callback fires
    st.bank(2, grad_norm=np.float32(np.nan), overflow=np.bool_(False),
            nonfinite=np.asarray([2, 0], np.int32),
            group_norms=np.asarray([np.nan, 1.0], np.float32))
    st.resolve()
    assert st.nonfinite_steps == 1
    assert fired and fired[0]["first_group"] == "g0"
    assert st.nonfinite_records()[0]["first_group"] == "g0"
    # non-finite floats never reach the JSON-bound surfaces (spec-
    # invalid NaN tokens would break jq/strict parsers mid-incident)
    snap = st.snapshot()
    json.dumps(snap, allow_nan=False)
    bad = next(e for e in snap["history"] if e["step"] == 2)
    assert bad["nonfinite"] is True
    assert bad["grad_norm"] is None
    assert bad["group_norms"][0] is None
    assert st.registry.get_counter("num/nonfinite_steps",
                                   handled="unexpected") == 1
    assert st.registry.get_counter("num/nonfinite_steps",
                                   handled="overflow") == 1


def test_numerics_payload_unarmed_and_filters():
    assert numerics_payload()["armed"] is False
    from deepspeed_tpu.telemetry.numerics import configure_numerics
    st = configure_numerics(["a/x", "a/y", "b"])
    for step in range(1, 6):
        st.bank(step, grad_norm=np.float32(step), loss=np.float32(1.0),
                group_norms=np.asarray([1.0, 2.0, 3.0], np.float32),
                nonfinite=np.zeros((3,), np.int32),
                update_ratio=np.float32(0.01))
    payload = numerics_payload({"n": "2", "group": "a/"})
    assert payload["armed"] is True
    assert payload["groups"] == ["a/x", "a/y"]
    assert len(payload["history"]) == 2
    assert payload["history"][-1]["group_norms"] == [1.0, 2.0]


# ------------------------------------------------- lazy banking contract
def test_bank_is_lazy_and_resolves_in_one_fetch():
    eng = _engine()
    # warm the compiled step + the one-time cost/memory reports before
    # instrumenting: the acceptance is about the steady-state hot path
    for i in range(2):
        eng.train_batch(batch=_batch(seed=i))
    st = eng.numerics
    st.resolve()
    base_resolves = st.resolves
    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    jax.device_get = counting
    try:
        for i in range(8):
            eng.train_batch(batch=_batch(seed=10 + i))
        hot_path_fetches = calls["n"]
        assert st.pending_count() == 8       # banked, not fetched
        assert st.resolves == base_resolves  # nothing resolved mid-loop
        assert hot_path_fetches == 0         # zero added host syncs
        entries = st.resolve()
        assert calls["n"] == 1               # the WHOLE backlog: one fetch
    finally:
        jax.device_get = real
    assert [e["step"] for e in entries] == list(range(3, 11))
    last = entries[-1]
    assert np.isfinite(last["grad_norm"]) and np.isfinite(last["loss"])
    assert last["update_ratio"] > 0
    assert len(last["group_norms"]) == len(eng._num_groups)
    reg = eng.telemetry_registry
    assert reg.get_gauge("num/grad_norm") == pytest.approx(
        last["grad_norm"])
    assert reg.get_gauge("num/update_ratio") == pytest.approx(
        last["update_ratio"])
    assert reg.get_gauge("num/group_grad_norm",
                         group=eng._num_groups[0]) is not None


def test_numerics_disabled_restores_bare_metrics(monkeypatch):
    monkeypatch.setenv("DS_NUMERICS", "0")
    eng = _engine()
    assert eng.numerics is None and not eng._num_on
    eng.train_batch(batch=_batch())
    assert "grad_norm" in eng.last_metrics
    assert "num_group_norms" not in eng.last_metrics
    assert peek_numerics() is None


# --------------------------------------------- chaos acceptance (HTTP)
def test_chaos_nonfinite_http_trace_and_bundle(tmp_path, monkeypatch):
    """ISSUE 15 acceptance: a ``train.nonfinite`` NaN at a known leaf
    group under DS_TRACE is attributed to that group over live HTTP
    (/debug/numerics), in the flight recorder, and in the bundle's
    numerics.json — while the training loop itself banked lazily (no
    resolves, no extra host syncs) and the trace validates with
    ``anomaly/num_*`` instants carrying the step corr."""
    from deepspeed_tpu.resilience.postmortem import reset_rate_limit
    reset_rate_limit()
    trace_path = str(tmp_path / "numerics_trace.json")
    monkeypatch.setenv("DS_TRACE", trace_path)
    reset_tracer()
    inject_group = 5
    eng = _engine(
        tmp_path=tmp_path / "pm",
        telemetry={"metrics_port": 0},
        resilience={"faults": f"train.nonfinite:deny={inject_group}@4",
                    "postmortem_dir": str(tmp_path / "pm")})
    try:
        for i in range(10):
            eng.train_batch(batch=_batch(seed=i))
        st = eng.numerics
        expect = eng._num_groups[inject_group]
        # lazy banking preserved: the injected step changed nothing on
        # the hot path — detection happens at resolution, not per step
        assert st.resolves == 0
        assert st.pending_count() == 10
        port = eng.metrics_server.port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/numerics?n=16",
                timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["armed"] is True
        recs = payload["nonfinite"]["records"]
        assert recs and recs[0]["first_group"] == expect
        assert recs[0]["step"] == 5          # invocation 4 == step 5
        assert list(recs[0]["groups"]) == [expect]
        # flight recorder carries the same attribution
        events = eng.flightrec.events(kind_prefix="num/nonfinite")
        assert any(e.get("first_group") == expect
                   and e.get("corr") == "train-step-5" for e in events)
        # the num/* gauges ride the same /metrics exposition
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            prom = r.read().decode()
        assert "num_grad_norm" in prom
        assert "num_group_grad_norm{" in prom
        assert 'num_nonfinite_steps{handled="unexpected"}' in prom
        # the resolve (triggered by the debug read) wrote the bundle
        pm = tmp_path / "pm"
        bundles = [d for d in os.listdir(pm)
                   if d.startswith("postmortem-")]
        assert bundles, "nonfinite detection wrote no bundle"
        with open(pm / bundles[0] / "numerics.json") as f:
            bundle_payload = json.load(f)
        names = [r["first_group"]
                 for r in bundle_payload["nonfinite"]["records"]]
        assert expect in names
    finally:
        eng.metrics_server.stop()
    # flush + validate the trace: anomaly/num_* instants must carry the
    # step corr and detector fields
    eng.tracer.flush()
    reset_tracer()
    from scripts.trace_validate import load_events, validate_anomalies
    events = load_events(trace_path)
    anomalies = [e for e in events
                 if str(e.get("name", "")).startswith("anomaly/num_")]
    assert anomalies, "no anomaly/num_* instants in the trace"
    assert validate_anomalies(events, require_present=True) == []
    nf = [e for e in anomalies if e["name"] == "anomaly/num_nonfinite"]
    assert nf and nf[0]["args"]["corr"] == "train-step-5"
    assert nf[0]["args"]["first_group"] == expect


def test_sanitize_branch_names_group_and_writes_terminal_bundle(
        tmp_path):
    from deepspeed_tpu.resilience.postmortem import reset_rate_limit
    reset_rate_limit()
    eng = _engine(
        tmp_path=tmp_path,
        debug={"sanitize_gradients": True},
        resilience={"faults": "train.nonfinite:deny=3@1",
                    "postmortem_dir": str(tmp_path)})
    eng.train_batch(batch=_batch(seed=0))
    expect = eng._num_groups[3]
    with pytest.raises(FloatingPointError, match=expect.replace("/", "/")):
        eng.train_batch(batch=_batch(seed=1))
    bundles = [d for d in os.listdir(tmp_path)
               if d.startswith("postmortem-")]
    assert bundles, "terminal raise wrote no bundle"


# --------------------------------------------------- fingerprint audit
def test_restore_fingerprint_audit_ok_then_perturbed_flags(tmp_path):
    from deepspeed_tpu.runtime.checkpoint_engine.engine import (
        NpzCheckpointEngine, STATE_DIR)
    save_dir = str(tmp_path / "ckpt")
    eng = _engine()
    eng.checkpoint_engine = NpzCheckpointEngine()
    for i in range(2):
        eng.train_batch(batch=_batch(seed=i))
    assert eng.save_checkpoint(save_dir, tag="t0")
    saved_digest = None
    with open(os.path.join(save_dir, "t0", "ds_metadata.json")) as f:
        saved_digest = json.load(f)["numerics_fingerprint"]["digest"]
    assert saved_digest
    # clean restore: recomputed fingerprint matches the manifest stamp
    # (no training after restore — the documented jaxlib hazard; the
    # continued-stream acceptance runs cache-less in a subprocess)
    e2 = _engine()
    e2.checkpoint_engine = NpzCheckpointEngine()
    path, _ = e2.load_checkpoint(save_dir)
    assert path is not None
    audit = e2.numerics.restore_audits[-1]
    assert audit["ok"] is True and audit["actual"] == saved_digest
    # perturb one param element on disk: structural (manifest)
    # verification passes, the fingerprint audit flags it
    state_path = os.path.join(save_dir, "t0", STATE_DIR + ".npz")
    data = dict(np.load(state_path))
    key = next(k for k in data
               if k.startswith("params/") and data[k].size > 4
               and np.issubdtype(data[k].dtype, np.floating))
    data[key] = data[key].copy()
    data[key].flat[0] += 1.0
    np.savez(state_path.removesuffix(".npz"), **data)
    before = get_registry().get_counter("num/fingerprint_mismatch")
    e3 = _engine(resilience={"verify_checkpoint": "off"})
    e3.checkpoint_engine = NpzCheckpointEngine()
    path, _ = e3.load_checkpoint(save_dir, tag="t0")
    assert path is not None
    audit = e3.numerics.restore_audits[-1]
    assert audit["ok"] is False
    assert audit["expected"] == saved_digest
    assert get_registry().get_counter("num/fingerprint_mismatch") \
        == (before or 0.0) + 1
    # the audit also lands as a num/fingerprint flight event
    evs = e3.flightrec.events(kind_prefix="num/fingerprint")
    assert any(e.get("source") == "restore" and e.get("ok") is False
               for e in evs)


_RESUME_CHILD = """
import os, sys, json
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
sys.path.insert(0, {root!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import gpt2_model

rng = np.random.default_rng(7)
batches = [{{"input_ids": rng.integers(0, 128, size=(1, 4, 16),
                                       dtype=np.int32)}}
           for _ in range(6)]

def make_engine():
    model = gpt2_model(size="custom", vocab_size=128, max_seq_len=64,
                       num_layers=2, num_heads=4, d_model=32,
                       dtype="float32", attention_impl="xla")
    eng, *_ = deepspeed_tpu.initialize(model=model, config={{
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": 1,
        "optimizer": {{"type": "Adam", "params": {{"lr": 1e-3}}}},
        "steps_per_print": 0,
        "telemetry": {{"numerics": {{"fingerprint_interval": 2}}}}}})
    return eng

def interval_stream(eng):
    return {{e["step"]: e["digest"]
             for e in eng.numerics.fingerprint_stream()
             if e["source"] == "interval"}}

# run A: uninterrupted 6 steps
eA = make_engine()
for b in batches:
    eA.train_batch(batch=b)
stream_a = interval_stream(eA)

# run B: 2 steps -> save -> fresh engine restores -> 4 more steps
save_dir = sys.argv[1]
eB = make_engine()
for b in batches[:2]:
    eB.train_batch(batch=b)
eB.save_checkpoint(save_dir, tag="t")
stream_b = interval_stream(eB)
eC = make_engine()
path, _ = eC.load_checkpoint(save_dir)
assert path is not None, "restore failed"
for b in batches[2:]:
    eC.train_batch(batch=b)
stream_b.update(interval_stream(eC))
audits = eC.numerics.restore_audits
print(json.dumps({{"a": stream_a, "b": stream_b,
                   "audit_ok": bool(audits and audits[-1]["ok"])}}))
"""


def test_fingerprint_resume_reproduces_stream_bitwise(tmp_path):
    """Save -> (process boundary) -> resume reproduces the
    uninterrupted run's fingerprint stream bitwise; the restore audit
    passes.  Runs cache-less in a child: on this container's jaxlib a
    donated train step over restored state under the warm persistent
    cache corrupts the heap (test_resilience's documented pattern)."""
    out = subprocess.run(
        [sys.executable, "-c", _RESUME_CHILD.format(root=REPO),
         str(tmp_path / "ckpt")],
        capture_output=True, text=True, timeout=540, cwd=REPO)
    assert out.returncode == 0, out.stderr[-3000:]
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["audit_ok"] is True
    a = {int(k): v for k, v in doc["a"].items()}
    b = {int(k): v for k, v in doc["b"].items()}
    assert set(a) == {2, 4, 6} and set(b) == {2, 4, 6}
    assert a == b, f"fingerprint streams diverged: {a} vs {b}"
    # and the report tool agrees: identical -> 0, perturbed -> 1
    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    pbad = tmp_path / "bad.json"

    def payload(stream):
        return {"history": [], "fingerprints": [
            {"step": s, "digest": d, "source": "interval"}
            for s, d in sorted(stream.items())]}
    pa.write_text(json.dumps(payload(a)))
    pb.write_text(json.dumps(payload(b)))
    bad = dict(b)
    bad[4] = "0" * 32
    pbad.write_text(json.dumps(payload(bad)))
    from scripts.numerics_report import main as report_main
    assert report_main(["--diff", str(pa), str(pb)]) == 0
    assert report_main(["--diff", str(pa), str(pbad)]) == 1


# ------------------------------------------------------ MoE router health
def test_moe_router_health_parity_einsum_vs_grouped():
    from deepspeed_tpu.moe.layer import (MoEConfig, dispatch_scope,
                                         init_moe_params, moe_layer,
                                         set_moe_metrics_registry)
    cfg = MoEConfig(d_model=16, d_ff=32, num_experts=4, top_k=2,
                    z_loss_coef=1e-3)
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
    vals = {}
    try:
        for mode in ("einsum", "grouped"):
            reg = MetricsRegistry()
            set_moe_metrics_registry(reg)
            with dispatch_scope(mode):
                out, _ = moe_layer(params, x, cfg, train=False)
            jax.block_until_ready(out)
            vals[mode] = {
                "entropy": reg.get_gauge("moe/router_entropy"),
                "max_frac": reg.get_gauge(
                    "moe/expert_load_max_fraction"),
                "dead": reg.get_counter("moe/dead_experts"),
                "aux": reg.get_gauge("moe/aux_loss"),
                "z": reg.get_gauge("moe/z_loss"),
                "load": [reg.get_gauge("moe/expert_load_fraction",
                                       expert=str(i))
                         for i in range(cfg.num_experts)],
            }
    finally:
        set_moe_metrics_registry(None)
    assert vals["einsum"] == vals["grouped"]
    e = vals["einsum"]
    assert e["entropy"] is not None and 0.0 < e["entropy"] <= np.log(4) + 1e-6
    assert 0.25 <= e["max_frac"] <= 1.0
    assert e["z"] is not None and e["z"] > 0.0      # z_loss armed
    assert sum(e["load"]) == pytest.approx(1.0)


def test_moe_router_health_dead_experts_and_disarmed():
    import jax.numpy as jnp
    from deepspeed_tpu.moe.layer import (MoEConfig, init_moe_params,
                                         moe_layer,
                                         set_moe_metrics_registry)
    cfg = MoEConfig(d_model=8, d_ff=16, num_experts=4, top_k=1)
    params = init_moe_params(cfg, jax.random.PRNGKey(0))
    # bias the router so every token picks expert 0: 3 dead experts
    # (non-negative tokens keep every logit's column-0 dot positive)
    params = dict(params)
    params["router"] = jnp.zeros_like(params["router"]).at[:, 0].set(50.0)
    x = jnp.abs(jax.random.normal(jax.random.PRNGKey(2), (1, 8, 8)))
    reg = MetricsRegistry()
    set_moe_metrics_registry(reg)
    try:
        out, _ = moe_layer(params, x, cfg, train=False)
        jax.block_until_ready(out)
    finally:
        set_moe_metrics_registry(None)
    assert reg.get_counter("moe/dead_experts") == 3
    assert reg.get_gauge("moe/expert_load_max_fraction") == 1.0
    assert reg.get_gauge("moe/router_entropy") == pytest.approx(
        0.0, abs=1e-4)
    # disarmed tap publishes nothing (the opt-in contract)
    reg2 = MetricsRegistry()
    out, _ = moe_layer(params, x, cfg, train=False)
    jax.block_until_ready(out)
    assert reg2.get_gauge("moe/router_entropy") is None


# ------------------------------------------------------------- tooling
def test_numerics_report_render_and_errors(tmp_path, capsys):
    from scripts.numerics_report import main as report_main
    payload = {
        "armed": True, "groups": ["a", "b"],
        "history": [{"step": 1, "loss": 2.0, "grad_norm": 1.0,
                     "update_ratio": 0.01, "loss_scale": 1.0,
                     "overflow": False, "group_norms": [0.5, 0.8]}],
        "nonfinite": {"unexpected_steps": 1, "overflow_steps": 0,
                      "records": [{"step": 1, "first_group": "b",
                                   "groups": {"b": 3}, "loss": None}]},
        "fingerprints": [{"step": 1, "digest": "ab", "source":
                          "interval"}],
        "restore_audits": [{"step": 1, "ok": False, "expected": "x",
                            "actual": "y"}],
    }
    p = tmp_path / "numerics.json"
    p.write_text(json.dumps(payload))
    assert report_main([str(p)]) == 0
    out = capsys.readouterr().out
    assert "first group 'b'" in out and "MISMATCH" in out
    bad = tmp_path / "bad.json"
    bad.write_text("{}")
    assert report_main([str(bad)]) == 2
    assert report_main([str(tmp_path / "missing.json")]) == 2
    assert report_main(["--diff", str(p)]) == 2   # needs two sources


def test_numerics_bench_smoke_subprocess():
    env = dict(os.environ, NUMERICS_SMOKE="1", JAX_PLATFORMS="cpu")
    env.pop("DS_NUMERICS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "numerics_bench.py")],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "numerics_overhead_fraction"
    assert rec["detail"]["step_s_numerics_off"] > 0


def test_ckpt_bench_detail_gains_convergence_fields():
    from scripts.bench_compare import lower_is_better
    # convergence detail fields gate like latency ones
    assert lower_is_better("ckpt_bench_sync.final_loss")
    assert lower_is_better("ckpt_bench_sync.mean_grad_norm")
    env = dict(os.environ, CKPT_SMOKE="1", ASYNC="0",
               JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "ckpt_bench.py")],
        capture_output=True, text=True, timeout=540, cwd=REPO, env=env)
    assert out.returncode == 0, out.stderr[-3000:]
    detail = json.loads(out.stdout.strip().splitlines()[-1])
    assert np.isfinite(detail["final_loss"])
    assert np.isfinite(detail["mean_grad_norm"])
