"""Reference-format checkpoint ingest (VERDICT r4 missing-item 1).

Builds synthetic DeepSpeed/Megatron-layout checkpoints WITH the real torch
(cpu torch is in the image — the fixtures are genuine ``torch.save`` zips)
and reads them back through the torch-free ingest path, asserting exact
tensor recovery and end-to-end logits parity through the Megatron
converter.
"""
import collections

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from deepspeed_tpu.checkpoint import (DeepSpeedCheckpoint, load_pt,
                                      load_reference_checkpoint,
                                      megatron_gpt_from_ds_dir)

H, HD, L, V, S = 4, 8, 2, 64, 16
D = H * HD
FFN = 4 * D


def _megatron_sd(seed=0):
    """Full (unsharded) Megatron-GPT state dict, torch tensors."""
    g = torch.Generator().manual_seed(seed)
    r = lambda *s: torch.randn(*s, generator=g) * 0.02
    sd = collections.OrderedDict()
    sd["embedding.word_embeddings.weight"] = r(V, D)
    sd["embedding.position_embeddings.weight"] = r(S, D)
    for i in range(L):
        p = f"transformer.layers.{i}."
        sd[p + "input_layernorm.weight"] = torch.ones(D) + r(D)
        sd[p + "input_layernorm.bias"] = r(D)
        sd[p + "self_attention.query_key_value.weight"] = r(3 * D, D)
        sd[p + "self_attention.query_key_value.bias"] = r(3 * D)
        sd[p + "self_attention.dense.weight"] = r(D, D)
        sd[p + "self_attention.dense.bias"] = r(D)
        sd[p + "post_attention_layernorm.weight"] = torch.ones(D) + r(D)
        sd[p + "post_attention_layernorm.bias"] = r(D)
        sd[p + "mlp.dense_h_to_4h.weight"] = r(FFN, D)
        sd[p + "mlp.dense_h_to_4h.bias"] = r(FFN)
        sd[p + "mlp.dense_4h_to_h.weight"] = r(D, FFN)
        sd[p + "mlp.dense_4h_to_h.bias"] = r(D)
    sd["transformer.final_layernorm.weight"] = torch.ones(D) + r(D)
    sd["transformer.final_layernorm.bias"] = r(D)
    return sd


def _tp_shard(sd, tp, tp_degree):
    """Shard a full Megatron SD the way Megatron TP does: column-parallel
    rows (qkv per head group, h_to_4h, vocab embedding) split dim 0,
    row-parallel (dense, 4h_to_h) split dim 1, norms/positions replicated."""
    out = collections.OrderedDict()
    for k, v in sd.items():
        if k.endswith(("input_layernorm.weight", "input_layernorm.bias",
                       "post_attention_layernorm.weight",
                       "post_attention_layernorm.bias",
                       "final_layernorm.weight", "final_layernorm.bias",
                       "self_attention.dense.bias", "mlp.dense_4h_to_h.bias",
                       "position_embeddings.weight")):
            out[k] = v
        elif k.endswith(("self_attention.dense.weight",
                         "mlp.dense_4h_to_h.weight")):
            out[k] = v.chunk(tp_degree, dim=1)[tp].contiguous()
        else:
            out[k] = v.chunk(tp_degree, dim=0)[tp].contiguous()
    return out


def _write_mp_checkpoint(tmp_path, sd, tp_degree, iteration=100):
    d = tmp_path / "global_step100"
    d.mkdir(exist_ok=True)
    (tmp_path / "latest").write_text("global_step100")
    shards = []
    for tp in range(tp_degree):
        shard = _tp_shard(sd, tp, tp_degree)
        torch.save({"module": shard, "iteration": iteration,
                    # real DeepSpeed saves torch.Size values here — keep
                    # them as Size to exercise the torch-free reader's
                    # GLOBAL('torch','Size') mapping
                    "param_shapes": [collections.OrderedDict(
                        (k, v.shape) for k, v in shard.items())],
                    "dp_world_size": 1},
                   d / f"mp_rank_{tp:02d}_model_states.pt")
        shards.append(shard)
    return d, shards


def test_mp_rank_tp2_merge_exact(tmp_path):
    sd = _megatron_sd()
    _write_mp_checkpoint(tmp_path, sd, tp_degree=2)
    ck = DeepSpeedCheckpoint(str(tmp_path))
    assert ck.tp_degree == 2
    assert ck.iteration == 100
    merged = ck.merged_state_dict()
    assert set(merged) == set(sd)
    for k, v in sd.items():
        np.testing.assert_array_equal(merged[k], v.numpy(), err_msg=k)


def test_layer_file_layout_merge(tmp_path):
    """Megatron-DeepSpeed pipeline layout: layer_NN-model_TT files."""
    sd = _megatron_sd(seed=3)
    d = tmp_path / "global_step5"
    d.mkdir()
    (tmp_path / "latest").write_text("global_step5")
    tp_degree = 2
    for tp in range(tp_degree):
        shard = _tp_shard(sd, tp, tp_degree)
        emb = {k.split("embedding.")[1]: v for k, v in shard.items()
               if k.startswith("embedding.")}
        torch.save(emb, d / f"layer_00-model_{tp:02d}-model_states.pt")
        for i in range(L):
            lay = {k.split(f"layers.{i}.")[1]: v for k, v in shard.items()
                   if f"layers.{i}." in k}
            torch.save(lay,
                       d / f"layer_{i + 2:02d}-model_{tp:02d}-model_states.pt")
        fin = {k.split("final_layernorm.")[1]: v for k, v in shard.items()
               if "final_layernorm" in k}
        torch.save(fin, d / f"layer_{L + 3:02d}-model_{tp:02d}-model_states.pt")
    merged = load_reference_checkpoint(str(tmp_path))
    for k, v in sd.items():
        np.testing.assert_array_equal(merged[k], v.numpy(), err_msg=k)


def _flat_groups_zero2(shard, dp_degree, align=8):
    """Build the ZeRO-1/2 per-rank flat fp32 partitions the reference
    writes: params concatenated in param_shapes order, padded to a
    multiple of dp_degree*align, split evenly across ranks."""
    flat = torch.cat([v.float().reshape(-1) for v in shard.values()])
    pad = (-flat.numel()) % (dp_degree * align)
    flat = torch.cat([flat, torch.zeros(pad)])
    return list(flat.chunk(dp_degree))


def test_zero2_fp32_reconstruction(tmp_path):
    sd = _megatron_sd(seed=7)
    d, shards = _write_mp_checkpoint(tmp_path, sd, tp_degree=2)
    dp = 2
    for tp, shard in enumerate(shards):
        parts = _flat_groups_zero2(shard, dp)
        for r in range(dp):
            torch.save(
                {"optimizer_state_dict": {
                    "zero_stage": 2,
                    "partition_count": dp,
                    "single_partition_of_fp32_groups": [parts[r]]}},
                d / f"zero_pp_rank_{r}_mp_rank_{tp:02d}_optim_states.pt")
    ck = DeepSpeedCheckpoint(str(tmp_path))
    for tp, shard in enumerate(shards):
        rec = ck.zero_to_fp32(tp)
        assert set(rec) == set(shard)
        for k, v in shard.items():
            np.testing.assert_array_equal(rec[k], v.float().numpy(),
                                          err_msg=f"tp{tp} {k}")
    # and the one-call path prefers the fp32 masters
    merged = load_reference_checkpoint(str(tmp_path))
    for k, v in sd.items():
        np.testing.assert_array_equal(merged[k], v.float().numpy(),
                                      err_msg=k)


def test_zero3_fp32_reconstruction(tmp_path):
    sd = _megatron_sd(seed=11)
    d, shards = _write_mp_checkpoint(tmp_path, sd, tp_degree=1)
    shard = shards[0]
    world = 2
    # stage 3: EVERY param partitions individually in ceil(n/world) slices
    per_rank = [[] for _ in range(world)]
    for v in shard.values():
        flat = v.float().reshape(-1)
        part = -(-flat.numel() // world)
        padded = torch.cat([flat, torch.zeros(part * world - flat.numel())])
        for r in range(world):
            per_rank[r].append(padded[r * part:(r + 1) * part])
    for r in range(world):
        torch.save(
            {"optimizer_state_dict": {
                "zero_stage": 3,
                "partition_count": world,
                "fp32_flat_groups": [torch.cat(per_rank[r])]}},
            d / f"zero_pp_rank_{r}_mp_rank_00_optim_states.pt")
    rec = DeepSpeedCheckpoint(str(tmp_path)).zero_to_fp32(0)
    for k, v in shard.items():
        np.testing.assert_array_equal(rec[k], v.float().numpy(), err_msg=k)


def test_megatron_logits_parity_from_ds_dir(tmp_path):
    """End-to-end: ingest a tp=2 DeepSpeed dir -> Megatron converter ->
    logits match the converter fed the original unsharded SD."""
    import jax
    from deepspeed_tpu.models.hf import megatron_gpt_from_sd
    sd = _megatron_sd(seed=5)
    _write_mp_checkpoint(tmp_path, sd, tp_degree=2)
    model_a, params_a = megatron_gpt_from_ds_dir(str(tmp_path), num_heads=H)
    model_b, params_b = megatron_gpt_from_sd(
        {k: v.numpy() for k, v in sd.items()}, num_heads=H)
    tokens = np.arange(2 * 8, dtype=np.int32).reshape(2, 8) % V
    la = jax.jit(model_a.apply_fn)(params_a, {"input_ids": tokens})
    lb = jax.jit(model_b.apply_fn)(params_b, {"input_ids": tokens})
    np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                               rtol=1e-6, atol=1e-6)


# ------------------------------------------------- restricted unpickler
def test_unpickler_rejects_numpy_executing_callables(tmp_path):
    """ISSUE 1 satellite (ADVICE high): the numpy allowlist must NOT hand
    out executing callables.  numpy.testing._private.utils.runstring
    exec()s an arbitrary string — a module-level ``numpy.*`` wildcard
    resolves it and a crafted checkpoint achieves code execution."""
    import pickle
    import zipfile

    canary = tmp_path / "pwned"

    class Evil:
        def __reduce__(self):
            import numpy.testing._private.utils as u
            return (u.runstring,
                    (f"open(r'{canary}', 'w').write('pwned')", {}))

    path = tmp_path / "evil.pt"
    with zipfile.ZipFile(path, "w") as zf:
        zf.writestr("archive/data.pkl", pickle.dumps({"payload": Evil()}))
        zf.writestr("archive/version", "3")
    out = load_pt(str(path))
    assert not canary.exists(), "checkpoint-controlled code executed!"
    # the global resolved to an inert stub, not the real callable
    assert "runstring" in type(out["payload"]).__name__


def test_unpickler_allowlist_keeps_numpy_data(tmp_path):
    """The flip side: legitimate numpy payloads (arrays, scalars, dtypes)
    still reconstruct through the explicit allowlist."""
    payload = {"arr": np.arange(6, dtype=np.float32).reshape(2, 3),
               "scalar": np.float64(3.5),
               "dt": np.dtype(np.int16)}
    for proto in (2, 5):    # proto>=5 ndarrays ride _frombuffer instead
        path = tmp_path / f"np{proto}.pt"
        torch.save(payload, path, pickle_protocol=proto)
        out = load_pt(str(path))
        np.testing.assert_array_equal(out["arr"], payload["arr"])
        assert float(out["scalar"]) == 3.5
        assert np.dtype(out["dt"]) == np.int16


def test_merge_tp_shards_zero_bias_concats_by_name():
    """ISSUE 1 satellite (ADVICE medium): zero-initialized
    column-parallel bias shards are bit-identical — the old equality
    heuristic replicated (truncated) them.  The reference CAT_DIM name
    rules must win."""
    from deepspeed_tpu.checkpoint.ds_ingest import merge_tp_shards
    qkv = "transformer.layers.0.self_attention.query_key_value.bias"
    h4h = "transformer.layers.0.mlp.dense_h_to_4h.bias"
    row_bias = "transformer.layers.0.self_attention.dense.bias"
    norm = "transformer.layers.0.input_layernorm.weight"
    shards = [
        {qkv: np.zeros(6, np.float32), h4h: np.zeros(8, np.float32),
         row_bias: np.full(4, 0.5, np.float32), norm: np.ones(4)},
        {qkv: np.zeros(6, np.float32), h4h: np.zeros(8, np.float32),
         row_bias: np.full(4, 0.5, np.float32), norm: np.ones(4)},
    ]
    merged = merge_tp_shards(shards)
    assert merged[qkv].shape == (12,)          # concat, despite equality
    assert merged[h4h].shape == (16,)
    assert merged[row_bias].shape == (4,)      # row-parallel: replicated
    assert merged[norm].shape == (4,)
    # unknown-name 1-D biases that DIFFER still concat (equality is only
    # a fallback signal, never an override of the name rules)
    odd = "some_custom.proj.bias"
    m2 = merge_tp_shards([{odd: np.zeros(3, np.float32)},
                          {odd: np.ones(3, np.float32)}])
    assert m2[odd].shape == (6,)
