"""Training-health report + fingerprint-stream audit (ISSUE 15).

Renders the numerics observatory's health timeline — per-step
loss / grad_norm / loss_scale / update_ratio, the per-leaf-group norm
table, NaN provenance records, and the determinism fingerprint
stream — from either a live ``/debug/numerics`` endpoint or a
post-mortem bundle's ``numerics.json``; ``--diff`` compares TWO
fingerprint streams (the restore-vs-uninterrupted / DP-vs-TP audit)::

    python scripts/numerics_report.py http://127.0.0.1:8080/debug/numerics
    python scripts/numerics_report.py postmortems/postmortem-step12/numerics.json
    python scripts/numerics_report.py --diff runA/numerics.json runB/numerics.json
    python scripts/numerics_report.py --diff a/flightrec.jsonl b/flightrec.jsonl

``--diff`` accepts a ``numerics.json`` payload OR a flight-recorder
JSONL dump (it extracts the ``num/fingerprint`` events); streams match
when every step both runs fingerprinted carries the same digest.

Exit codes: 0 report rendered / streams identical, 1 fingerprint
streams diverge, 2 unreadable or not-a-numerics source.
"""
import argparse
import json
import os
import sys
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_payload(source: str):
    """URL / numerics.json path / flightrec.jsonl path -> parsed doc
    (dict for payloads, list of events for JSONL)."""
    if source.startswith(("http://", "https://")):
        with urllib.request.urlopen(source, timeout=10) as r:
            return json.loads(r.read())
    with open(source) as f:
        text = f.read()
    try:
        return json.loads(text)
    except json.JSONDecodeError:
        # flight-recorder JSONL
        return [json.loads(line) for line in text.splitlines() if line]


def fingerprint_stream(doc) -> dict:
    """-> {step: digest} from a numerics payload or a flightrec JSONL
    event list.  ONLY the periodic ``interval`` entries count: a
    checkpoint stamp at the same step digests different inputs (no
    loss term — it must be recomputable at restore time), so mixing
    the sources would report two identical runs as diverged whenever
    only one of them checkpointed; restore audits re-state an existing
    step and are reported separately too."""
    out = {}
    if isinstance(doc, dict):
        for e in doc.get("fingerprints", []):
            if e.get("source") == "interval" and "digest" in e:
                out[int(e["step"])] = e["digest"]
        return out
    for e in doc or []:
        if isinstance(e, dict) and e.get("kind") == "num/fingerprint" \
                and e.get("source") == "interval" and "digest" in e:
            out[int(e["step"])] = e["digest"]
    return out


def diff_streams(a: dict, b: dict):
    """-> (shared steps, list of (step, digest_a, digest_b)
    mismatches)."""
    shared = sorted(set(a) & set(b))
    bad = [(s, a[s], b[s]) for s in shared if a[s] != b[s]]
    return shared, bad


def _fmt(v, nd=4):
    if v is None:
        return "-"
    try:
        return f"{float(v):.{nd}g}"
    except (TypeError, ValueError):
        return str(v)


def render(payload: dict, tail: int = 24) -> str:
    lines = ["# numerics observatory report"]
    if not payload.get("armed", True):
        lines.append("(bank not armed — no training engine in this "
                     "process; run with telemetry.numerics / "
                     "DS_NUMERICS=1)")
        return "\n".join(lines)
    groups = payload.get("groups", [])
    hist = payload.get("history", [])
    lines.append(f"groups: {len(groups)}; resolved steps in window: "
                 f"{len(hist)}; pending banked: "
                 f"{payload.get('banked_pending', 0)}")

    if hist:
        lines.append("\n## health timeline (tail)")
        lines.append(f"{'step':>6}  {'loss':>10}  {'grad_norm':>10}  "
                     f"{'upd/param':>10}  {'loss_scale':>10}  ovf")
        for e in hist[-tail:]:
            lines.append(
                f"{e.get('step', '?'):>6}  {_fmt(e.get('loss')):>10}  "
                f"{_fmt(e.get('grad_norm')):>10}  "
                f"{_fmt(e.get('update_ratio')):>10}  "
                f"{_fmt(e.get('loss_scale')):>10}  "
                f"{'Y' if e.get('overflow') else '.'}")
        last = hist[-1]
        norms = last.get("group_norms")
        if norms and groups:
            lines.append(f"\n## per-group grad norms @ step "
                         f"{last.get('step')}")
            w = max(len(g) for g in groups)
            # None = a non-finite norm (mapped out of the JSON payload);
            # sort those first — they ARE the story
            for g, v in sorted(zip(groups, norms),
                               key=lambda kv: (kv[1] is not None,
                                               -abs(kv[1] or 0.0))):
                lines.append(f"{g:<{w}}  "
                             f"{'non-finite' if v is None else _fmt(v)}")

    nf = payload.get("nonfinite", {})
    lines.append(f"\n## non-finite steps: "
                 f"{nf.get('unexpected_steps', 0)} unexpected, "
                 f"{nf.get('overflow_steps', 0)} loss-scaler-handled")
    for rec in nf.get("records", [])[:8]:
        lines.append(f"- step {rec.get('step')}: first group "
                     f"{rec.get('first_group')!r}"
                     + (" (overflow-handled)" if rec.get("handled")
                        else "")
                     + f", {len(rec.get('groups', {}))} group(s) "
                     f"affected, loss={_fmt(rec.get('loss'))}")

    fps = payload.get("fingerprints", [])
    if fps:
        lines.append(f"\n## fingerprint stream ({len(fps)} entries)")
        for e in fps[-8:]:
            lines.append(f"- step {e.get('step')} [{e.get('source')}] "
                         f"{e.get('digest')}")
    audits = payload.get("restore_audits", [])
    if audits:
        lines.append("\n## restore audits")
        for a in audits:
            lines.append(
                f"- step {a.get('step')}: "
                + ("OK" if a.get("ok") else
                   f"MISMATCH (expected {a.get('expected')}, got "
                   f"{a.get('actual')})"))
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="numerics_report",
        description="render the training-health timeline from "
                    "/debug/numerics or a bundle's numerics.json; "
                    "--diff audits two fingerprint streams")
    p.add_argument("source", help="URL or numerics.json path (with "
                                  "--diff: the first stream)")
    p.add_argument("other", nargs="?", default=None,
                   help="second stream (with --diff)")
    p.add_argument("--diff", action="store_true",
                   help="compare two fingerprint streams (numerics.json "
                        "or flightrec.jsonl); exit 1 on divergence")
    p.add_argument("--json", action="store_true",
                   help="emit the raw JSON payload instead of the table")
    p.add_argument("--tail", type=int, default=24,
                   help="timeline rows to render (default 24)")
    args = p.parse_args(argv)

    if args.diff:
        if not args.other:
            print("numerics_report: --diff needs two sources",
                  file=sys.stderr)
            return 2
        try:
            a = fingerprint_stream(load_payload(args.source))
            b = fingerprint_stream(load_payload(args.other))
        except Exception as e:
            print(f"numerics_report: cannot read streams: {e}",
                  file=sys.stderr)
            return 2
        if not a or not b:
            print("numerics_report: a source has no num/fingerprint "
                  "entries (was the run armed with "
                  "telemetry.numerics.fingerprint_interval / "
                  "DS_FINGERPRINT_INTERVAL?)", file=sys.stderr)
            return 2
        shared, bad = diff_streams(a, b)
        if not shared:
            print("numerics_report: streams share no fingerprinted "
                  "steps", file=sys.stderr)
            return 2
        print(f"shared fingerprinted steps: {len(shared)} "
              f"({shared[0]}..{shared[-1]})")
        if bad:
            print(f"DIVERGED at {len(bad)} step(s):")
            for s, da, db in bad[:16]:
                print(f"- step {s}: {da} != {db}")
            return 1
        print("streams identical over the shared steps")
        return 0

    try:
        payload = load_payload(args.source)
    except Exception as e:
        print(f"numerics_report: cannot read {args.source!r}: {e}",
              file=sys.stderr)
        return 2
    if not isinstance(payload, dict) or "history" not in payload:
        print(f"numerics_report: {args.source!r} is not a "
              "/debug/numerics payload (no 'history' key)",
              file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        print(render(payload, tail=args.tail))
    return 0


if __name__ == "__main__":
    sys.exit(main())
