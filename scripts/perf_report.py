#!/usr/bin/env python3
"""Render the "where did the step go" table from a Chrome trace plus
cost-model reports (ISSUE 13).

Inputs:
- a ``DS_TRACE`` trace file (``{"traceEvents": [...]}``) — B/E span
  pairs are matched per (pid, tid) exactly like
  ``scripts/trace_validate.py``;
- optionally ``--perf perf.json`` — a ``/debug/perf`` body or a
  post-mortem bundle's ``perf.json`` — to join each span family with
  its program's static cost, roofline floor, and achieved-vs-floor.

Output: one row per span name — count, total ms, mean ms, % of the
trace's wall span — then, for rows whose name matches a registered
cost-model program, the floor columns (including, when an interconnect
bandwidth was declared, the program's comm floor — ISSUE 19).  The table PERF.md used to
hand-compute, from artifacts the running system already emits::

    python scripts/perf_report.py trace.json
    python scripts/perf_report.py trace.json --perf perf.json --top 15
    python scripts/perf_report.py trace.json --json   # machine-readable

Exit 0 on success, 2 on unreadable inputs.
"""
import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List, Optional


def load_trace(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    return [e for e in events if isinstance(e, dict)]


def span_stats(events: List[dict]) -> Dict[str, dict]:
    """name -> {count, total_ms, mean_ms} from matched B/E pairs per
    (pid, tid) stack.  Unbalanced tails (a trace cut mid-span) are
    dropped, not fatal — post-mortem traces end mid-incident by
    design."""
    stacks: Dict[tuple, list] = defaultdict(list)
    acc: Dict[str, dict] = {}
    for ev in sorted(events, key=lambda e: e.get("ts", 0)):
        ph = ev.get("ph")
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks[key].append(ev)
        elif ph == "E" and stacks[key]:
            b = stacks[key].pop()
            name = b.get("name", "?")
            dur_ms = (ev.get("ts", 0) - b.get("ts", 0)) / 1e3
            row = acc.setdefault(name, {"count": 0, "total_ms": 0.0})
            row["count"] += 1
            row["total_ms"] += max(dur_ms, 0.0)
    for row in acc.values():
        row["mean_ms"] = row["total_ms"] / max(row["count"], 1)
    return acc


def wall_ms(events: List[dict]) -> float:
    ts = [e.get("ts", 0) for e in events if "ts" in e]
    return (max(ts) - min(ts)) / 1e3 if len(ts) >= 2 else 0.0


def join_cost(stats: Dict[str, dict], perf: Optional[dict]):
    """Attach floor/achieved columns from a /debug/perf payload.  Span
    names and program names share the ``serve/window``-style stems; a
    program ``serve/window:w8`` joins the ``serve/window`` span
    family (the span is the measured side, the program the modeled
    side)."""
    if not perf:
        return
    programs = perf.get("programs", {})
    for name, row in stats.items():
        exact = programs.get(name)
        if exact is None:
            matches = [p for pname, p in programs.items()
                       if pname.split(":", 1)[0] == name]
            if len(matches) > 1:
                # several buckets of one family (serve/window:w2 + :w8
                # after a spec+chunk run): join the LOWEST floor — the
                # conservative bound for a span family that mixes
                # bucket widths (weight streaming dominates, so bucket
                # floors are near-identical anyway)
                matches.sort(key=lambda p: (p.get("floor_ms") is None,
                                            p.get("floor_ms") or 0))
            exact = matches[0] if matches else None
        if exact is None:
            continue
        row["floor_ms"] = exact.get("floor_ms")
        row["bound"] = exact.get("bound")
        row["pallas_launches"] = exact.get("pallas_launches")
        if exact.get("floor_ms"):
            row["mean_vs_floor"] = round(
                row["mean_ms"] / exact["floor_ms"], 2)
        # comm columns (ISSUE 19): only present when the program carries
        # collectives AND an interconnect bandwidth was declared — never
        # invent a comm floor the roofline itself refused to price
        if exact.get("comm_floor_ms") is not None:
            row["comm_floor_ms"] = exact.get("comm_floor_ms")
            row["comm_achieved_vs_floor"] = exact.get(
                "comm_achieved_vs_floor")


def render(stats: Dict[str, dict], wall: float, top: int) -> str:
    rows = sorted(stats.items(), key=lambda kv: -kv[1]["total_ms"])[:top]
    width = max([len(n) for n, _ in rows] + [4])
    lines = [f"{'span':<{width}}  {'count':>7}  {'total ms':>10}  "
             f"{'mean ms':>9}  {'% wall':>6}  {'floor ms':>9}  "
             f"{'x floor':>7}  {'comm ms':>8}  bound"]
    for name, r in rows:
        pct = 100.0 * r["total_ms"] / wall if wall > 0 else 0.0
        floor = r.get("floor_ms")
        floor_cell = f"{floor:>9.4f}" if floor is not None else f"{'-':>9}"
        ratio_cell = f"{r.get('mean_vs_floor', '-'):>7}" \
            if floor is not None else f"{'-':>7}"
        comm = r.get("comm_floor_ms")
        comm_cell = f"{comm:>8.4f}" if comm is not None else f"{'-':>8}"
        bound = (r.get("bound") or "-") if floor is not None else "-"
        lines.append(
            f"{name:<{width}}  {r['count']:>7}  {r['total_ms']:>10.3f}  "
            f"{r['mean_ms']:>9.4f}  {pct:>5.1f}%  {floor_cell}  "
            f"{ratio_cell}  {comm_cell}  {bound}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="perf_report",
        description="per-span time attribution from a DS_TRACE file, "
                    "joined with cost-model floors when --perf is given")
    p.add_argument("trace")
    p.add_argument("--perf", default=None,
                   help="/debug/perf payload or post-mortem perf.json")
    p.add_argument("--top", type=int, default=20,
                   help="rows to print (by total time; default 20)")
    p.add_argument("--json", action="store_true",
                   help="emit the joined stats as JSON instead of a "
                        "table")
    args = p.parse_args(argv)
    try:
        events = load_trace(args.trace)
        perf = None
        if args.perf:
            with open(args.perf) as f:
                perf = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"perf_report: cannot load inputs: {e}", file=sys.stderr)
        return 2
    stats = span_stats(events)
    if not stats:
        print("perf_report: no span pairs in trace", file=sys.stderr)
        return 2
    wall = wall_ms(events)
    join_cost(stats, perf)
    if args.json:
        print(json.dumps({"wall_ms": round(wall, 3), "spans": stats},
                         indent=2))
    else:
        print(f"# trace wall: {wall:.3f} ms, "
              f"{sum(r['count'] for r in stats.values())} spans, "
              f"{len(stats)} families")
        print(render(stats, wall, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
