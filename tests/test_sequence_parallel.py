"""Ulysses sequence-parallel tests (reference capability:
deepspeed/sequence/layer.py + ZeRO over seq-data group)."""
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.mesh import MeshTopology, set_topology
from deepspeed_tpu.ops.attention import xla_causal_attention
from deepspeed_tpu.sequence.layer import distributed_attention
from tests.util import tiny_gpt2, base_config, random_batches


def test_distributed_attention_matches_local(devices8):
    import jax
    set_topology(MeshTopology(sequence_parallel_size=4))
    rng = jax.random.PRNGKey(0)
    q, k, v = (jax.random.normal(r, (2, 32, 8, 16))
               for r in jax.random.split(rng, 3))
    ref = xla_causal_attention(q, k, v)
    out = jax.jit(lambda a, b, c: distributed_attention(
        a, b, c, xla_causal_attention))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("stage", [0, 2])
def test_sp_training_matches_dp(devices8, stage):
    """sp=2 engine must produce the same losses as pure dp (ZeRO over the
    seq-data combined group, reference engine.py:1460)."""
    ref, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": stage}))
    sp, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": stage},
            mesh={"sequence_parallel_size": 2}))
    for i in range(2):
        batches = random_batches(1, batch_size=8, seq_len=16, seed=40 + i)
        l_ref = float(ref.train_batch(
            batch={"input_ids": batches[0]["input_ids"][None]}))
        l_sp = float(sp.train_batch(
            batch={"input_ids": batches[0]["input_ids"][None]}))
        assert abs(l_ref - l_sp) < 2e-4, f"step {i}: {l_ref} vs {l_sp}"


def test_ring_cp_training_matches_dp(devices8):
    """mesh.sequence_parallel_impl="ring": the engine's seq axis runs
    ring-attention context parallelism end-to-end in training (round-4:
    ring CP reachable from config, not just the direct API) and matches
    pure DP."""
    ref, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 2}))
    ring, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 2},
            mesh={"sequence_parallel_size": 2,
                  "sequence_parallel_impl": "ring"}))
    assert ring.topology.sequence_parallel_impl == "ring"
    for i in range(2):
        batches = random_batches(1, batch_size=8, seq_len=16, seed=50 + i)
        l_ref = float(ref.train_batch(
            batch={"input_ids": batches[0]["input_ids"][None]}))
        l_ring = float(ring.train_batch(
            batch={"input_ids": batches[0]["input_ids"][None]}))
        assert abs(l_ref - l_ring) < 2e-4, f"step {i}: {l_ref} vs {l_ring}"
