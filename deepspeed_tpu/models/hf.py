"""Hugging Face interop: convert `transformers` checkpoints to the native
param pytrees (reference capability: DeepSpeed wraps HF modules directly
— init_inference(model=AutoModel...) + AutoTP; in the functional design
the equivalent is a weight conversion into the in-tree models, after
which every engine feature — ZeRO, TP via the hand specs, KV-cache
serving, int8 quantization — applies unchanged).

Converters accept a live `transformers` model OR its ``state_dict()``
(anything indexable by parameter name whose values have ``.numpy()`` or
are array-like).  Logits parity against transformers' own forward is
asserted in tests/test_hf_interop.py.
"""
from typing import Any, Dict, Tuple

import numpy as np


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach()
    if hasattr(t, "cpu"):
        t = t.cpu()
    if hasattr(t, "float"):
        # torch bf16/fp16 tensors refuse .numpy(); widen first (real HF
        # checkpoints load as bf16 with torch_dtype="auto")
        t = t.float()
    if hasattr(t, "numpy"):
        return np.asarray(t.numpy(), dtype=np.float32)
    return np.asarray(t, dtype=np.float32)


def _state_dict(model_or_sd) -> Dict[str, Any]:
    if hasattr(model_or_sd, "state_dict"):
        return model_or_sd.state_dict()
    return model_or_sd


def gpt2_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF GPT2LMHeadModel (or its state_dict) -> (Model, params).

    HF's Conv1D already stores weights [in, out] — the same layout as the
    native blocks — so the mapping is a rename + per-layer stack."""
    from deepspeed_tpu.models.gpt2 import gpt2_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"transformer.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("transformer.h."))
    D = g("wte.weight").shape[1]
    cfg = dict(vocab_size=g("wte.weight").shape[0],
               max_seq_len=g("wpe.weight").shape[0],
               num_layers=n_layers, d_model=D,
               num_heads=overrides.pop("num_heads", None)
               or _gpt2_heads(model_or_sd, D))
    cfg.update(overrides)
    model = gpt2_model("custom", **cfg)

    def stack(fmt):
        return np.stack([g(fmt.format(i)) for i in range(n_layers)])

    params = {
        "wte": g("wte.weight"),
        "wpe": g("wpe.weight"),
        "blocks": {
            "ln1_scale": stack("h.{}.ln_1.weight"),
            "ln1_bias": stack("h.{}.ln_1.bias"),
            "qkv_w": stack("h.{}.attn.c_attn.weight"),
            "qkv_b": stack("h.{}.attn.c_attn.bias"),
            "proj_w": stack("h.{}.attn.c_proj.weight"),
            "proj_b": stack("h.{}.attn.c_proj.bias"),
            "ln2_scale": stack("h.{}.ln_2.weight"),
            "ln2_bias": stack("h.{}.ln_2.bias"),
            "mlp_in_w": stack("h.{}.mlp.c_fc.weight"),
            "mlp_in_b": stack("h.{}.mlp.c_fc.bias"),
            "mlp_out_w": stack("h.{}.mlp.c_proj.weight"),
            "mlp_out_b": stack("h.{}.mlp.c_proj.bias"),
        },
        "lnf_scale": g("ln_f.weight"),
        "lnf_bias": g("ln_f.bias"),
    }
    return model, params


def _gpt2_heads(model_or_sd, d_model: int) -> int:
    cfg = getattr(model_or_sd, "config", None)
    if cfg is not None and getattr(cfg, "n_head", None):
        return int(cfg.n_head)
    # head count is not recoverable from a bare state_dict; GPT-2 family
    # convention is hd=64
    return max(1, d_model // 64)


def llama_from_hf(model_or_sd, **overrides) -> Tuple[Any, dict]:
    """HF LlamaForCausalLM (or its state_dict) -> (Model, params).

    torch Linear stores [out, in]; the native layout is [in, out], so the
    projection weights transpose."""
    from deepspeed_tpu.models.llama import llama_model

    sd = _state_dict(model_or_sd)
    g = lambda k: _to_np(sd[f"model.{k}"])
    n_layers = 1 + max(int(k.split(".")[2]) for k in sd
                       if k.startswith("model.layers."))
    hf_cfg = getattr(model_or_sd, "config", None)
    if hf_cfg is None:
        from deepspeed_tpu.utils.logging import warning_once
        warning_once(
            "llama_from_hf: bare state_dict has no config — guessing "
            "rope_theta=10000, head_dim=64, max_seq_len=4096; pass the "
            "transformers model (or num_heads/rope_theta overrides) for "
            "Llama-3-family checkpoints (rope_theta=500000, hd=128)")
    D = g("embed_tokens.weight").shape[1]
    kv_rows = g("layers.0.self_attn.k_proj.weight").shape[0]
    q_rows = g("layers.0.self_attn.q_proj.weight").shape[0]
    heads = (int(hf_cfg.num_attention_heads) if hf_cfg is not None
             else max(1, q_rows // 64))
    hd = q_rows // heads
    cfg = dict(vocab_size=g("embed_tokens.weight").shape[0],
               num_layers=n_layers, d_model=D, num_heads=heads,
               num_kv_heads=kv_rows // hd,
               d_mlp=g("layers.0.mlp.gate_proj.weight").shape[0])
    if hf_cfg is not None:
        cfg["rope_theta"] = float(getattr(hf_cfg, "rope_theta", 10000.0))
        cfg["rms_norm_eps"] = float(getattr(hf_cfg, "rms_norm_eps", 1e-5))
        cfg["max_seq_len"] = int(getattr(hf_cfg, "max_position_embeddings",
                                         4096))
    cfg.update(overrides)
    model = llama_model("custom", **cfg)

    def stack_t(fmt):
        return np.stack([g(fmt.format(i)).T for i in range(n_layers)])

    def stack(fmt):
        return np.stack([g(fmt.format(i)) for i in range(n_layers)])

    params = {
        "wte": g("embed_tokens.weight"),
        "blocks": {
            "attn_norm": stack("layers.{}.input_layernorm.weight"),
            "wq": stack_t("layers.{}.self_attn.q_proj.weight"),
            "wk": stack_t("layers.{}.self_attn.k_proj.weight"),
            "wv": stack_t("layers.{}.self_attn.v_proj.weight"),
            "wo": stack_t("layers.{}.self_attn.o_proj.weight"),
            "mlp_norm": stack("layers.{}.post_attention_layernorm.weight"),
            "w_gate": stack_t("layers.{}.mlp.gate_proj.weight"),
            "w_up": stack_t("layers.{}.mlp.up_proj.weight"),
            "w_down": stack_t("layers.{}.mlp.down_proj.weight"),
        },
        "final_norm": g("norm.weight"),
        # tied-embedding checkpoints (safetensors drops the shared tensor)
        # reuse the embedding matrix as the head
        "lm_head": _to_np(sd["lm_head.weight"]).T
        if "lm_head.weight" in sd else g("embed_tokens.weight").T,
    }
    return model, params
