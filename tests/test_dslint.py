"""dslint test suite (ISSUE 10).

One known-bad snippet per rule (each must flag) with a known-good twin
(each must pass), the suppression/baseline machinery, the DSL004
inventory extraction, and — marked ``dslint`` — the tier-1 acceptance
pass asserting the live tree lints clean modulo the committed baseline.

Everything here is stdlib-only (no jax): dslint is designed to run in
hooks and collection phases, and these tests hold it to that.
"""
import ast
import copy
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(ROOT, "deepspeed_tpu", "tools")
if TOOLS not in sys.path:
    sys.path.insert(0, TOOLS)

import dslint  # noqa: E402
from dslint.core import (baseline_path, lint_paths,  # noqa: E402
                         lint_source, load_baseline, write_baseline)
from dslint.inventory import (Inventory, SCAN_ROOTS,  # noqa: E402
                              generate_registries_md)


@pytest.fixture(scope="session")
def inv():
    return Inventory.build(ROOT)


def _snippet_inv(inv, source, relpath):
    """A copy of the repo inventory that has also scanned ``source`` —
    DSL004 findings are cross-repo, so snippet uses must enter the
    inventory the checker reads."""
    inv2 = copy.deepcopy(inv)
    inv2.scan_module(ast.parse(source), relpath)
    return inv2


def _rules(findings):
    return sorted({f.rule for f in findings})


# =====================================================================
# DSL001 donation-safety
# =====================================================================

# THE PR 3 pattern (acceptance criterion): a live donated buffer handed
# to the async checkpoint engine while the donating train step reuses it
_DSL001_BAD_ASYNC = '''
import jax

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def train_loop(state, async_engine, batches, tag):
    for b in batches:
        async_engine.save(state, tag)     # live donated buffer escapes
        state = step(state, b)
'''

_DSL001_GOOD_ASYNC = '''
import jax
import numpy as np

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def train_loop(state, async_engine, batches, tag):
    for b in batches:
        snap = jax.tree.map(lambda a: np.array(a, copy=True), state)
        async_engine.save(snap, tag)      # host snapshot — safe
        state = step(state, b)
'''

_DSL001_BAD_READ = '''
import jax

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def f(state, batch):
    new = step(state, batch)
    loss = state["loss"]                  # read after donation
    return new, loss
'''

_DSL001_GOOD_READ = '''
import jax

step = jax.jit(lambda s, b: s, donate_argnums=(0,))

def f(state, batch):
    loss = state["loss"]                  # read BEFORE donation: fine
    state = step(state, batch)
    return state, loss
'''


def test_dsl001_flags_pr3_async_donation_race():
    findings = lint_source(_DSL001_BAD_ASYNC, rules=["DSL001"])
    assert _rules(findings) == ["DSL001"]
    assert any("escapes live" in f.message and "async_engine.save"
               in f.message for f in findings)


def test_dsl001_good_async_snapshot_passes():
    assert lint_source(_DSL001_GOOD_ASYNC, rules=["DSL001"]) == []


def test_dsl001_flags_read_after_donate():
    findings = lint_source(_DSL001_BAD_READ, rules=["DSL001"])
    assert _rules(findings) == ["DSL001"]
    assert any("read after being donated" in f.message for f in findings)


def test_dsl001_good_read_before_donate_passes():
    assert lint_source(_DSL001_GOOD_READ, rules=["DSL001"]) == []


def test_dsl001_thread_escape_and_self_attr_donor():
    src = '''
import jax, threading

class Engine:
    def __init__(self):
        self._fused = jax.jit(lambda s: s, donate_argnums=(0,))

    def run(self, state):
        t = threading.Thread(target=self.save, args=(state,))
        t.start()
        state = self._fused(state)
        return state
'''
    findings = lint_source(src, rules=["DSL001"])
    assert any("threading.Thread" in f.message for f in findings)


# =====================================================================
# DSL002 lock-discipline
# =====================================================================

_DSL002_BAD = '''
import time

class Scheduler:
    def step(self):
        with self._lock:
            time.sleep(0.5)                       # blocking under lock
            with open("/tmp/x", "w") as f:        # I/O under lock
                f.write("state")

    def debug_requests(self):
        with self._lock:                          # lock-free contract
            return list(self._queue)


class ServeWatchdog:
    def _run(self):
        if self.scheduler.has_work():             # locking call
            self.flag()
'''

_DSL002_GOOD = '''
import time

class Scheduler:
    def step(self):
        payload = None
        with self._lock:
            payload = self._render()
        with open("/tmp/x", "w") as f:            # I/O OUTSIDE the lock
            f.write(payload)
        time.sleep(0.5)

    def debug_requests(self):
        return [r for r in list(self._queue) if r is not None]


class ServeWatchdog:
    def _run(self):
        if self.scheduler.has_work_unlocked():    # lock-free variant
            self.flag()
'''


def test_dsl002_flags_blocking_and_contract_violations():
    findings = lint_source(_DSL002_BAD, rules=["DSL002"])
    msgs = "\n".join(f.message for f in findings)
    assert "time.sleep" in msgs
    assert "open" in msgs
    assert "debug_requests" in msgs and "lock-free by contract" in msgs
    assert "has_work" in msgs
    assert len(findings) == 4


def test_dsl002_good_twin_passes():
    assert lint_source(_DSL002_GOOD, rules=["DSL002"]) == []


def test_dsl002_docstring_contract_zone():
    src = '''
class View:
    def snapshot(self):
        """Racy lock-free scheduler view for forensics."""
        with self._sched._lock:
            return dict(self._sched.state)
'''
    findings = lint_source(src, rules=["DSL002"])
    assert len(findings) == 1 and "lock-free by contract" in \
        findings[0].message


# =====================================================================
# DSL003 jit-boundary hygiene
# =====================================================================

_DSL003_BAD = '''
import jax
import numpy as np
from functools import partial

@partial(jax.jit, static_argnums=(2,))
def decode(x, mask, n):
    if mask:                      # Python branch on traced value
        x = x * n
    y = np.asarray(x)             # host sync inside jit
    return y

g = jax.jit(lambda a, cfg: a, static_argnums=(1,))
out = g(1, [1, 2])                # unhashable static arg
'''

_DSL003_GOOD = '''
import jax
import jax.numpy as jnp
from functools import partial

@partial(jax.jit, static_argnums=(2,))
def decode(x, mask, n):
    if n > 4:                     # static arg: fine
        x = x * n
    if mask is None:              # structural: fine
        return x
    if x.ndim == 2:               # shape attr: static under trace
        x = x.sum(-1)
    return jnp.where(mask, x, 0)

g = jax.jit(lambda a, cfg: a, static_argnums=(1,))
out = g(1, (1, 2))                # hashable tuple
'''


def test_dsl003_flags_branch_sync_and_static():
    findings = lint_source(_DSL003_BAD, rules=["DSL003"])
    msgs = "\n".join(f.message for f in findings)
    assert "Python 'if' on traced value(s) ['mask']" in msgs
    assert "np.asarray" in msgs
    assert "unhashable list literal" in msgs
    assert len(findings) == 3


def test_dsl003_good_twin_passes():
    assert lint_source(_DSL003_GOOD, rules=["DSL003"]) == []


def test_dsl003_hot_path_item_sync():
    src = '''
import numpy as np

class Sched:
    def _decode(self, logits, rows):
        toks = []
        for r in rows:
            toks.append(logits[r].item())    # per-row device round-trip
        return toks
'''
    findings = lint_source(src, relpath="deepspeed_tpu/serving/x.py",
                           rules=["DSL003"])
    assert len(findings) == 1 and ".item()" in findings[0].message
    # same code outside a serving hot path is not flagged
    assert lint_source(src, relpath="deepspeed_tpu/other/x.py",
                       rules=["DSL003"]) == []


# =====================================================================
# DSL004 string-registry consistency
# =====================================================================

_DSL004_BAD = '''
import os

def serve_step(self):
    self.injector.check("serve.nonexistent_site")
    self.flightrec.record("req/made_up_kind", corr="req-1")
    self.registry.inc("serving/not_a_documented_metric")
    lvl = os.environ.get("DS_TOTALLY_UNDOCUMENTED", "")
    raise ValueError("serving.not_a_real_key must be >= 1")
'''

_DSL004_GOOD = '''
import os

def serve_step(self):
    self.injector.check("serve.step")
    self.flightrec.record("req/admit", corr="req-1")
    self.registry.inc("serving/generated_tokens")
    lvl = os.environ.get("DS_TRACE", "")
    raise ValueError("serving.max_num_seqs must be >= 1")
'''


def test_dsl004_flags_every_registry_drift(inv):
    rel = "deepspeed_tpu/serving/snippet.py"
    inv2 = _snippet_inv(inv, _DSL004_BAD, rel)
    findings = lint_source(_DSL004_BAD, relpath=rel, rules=["DSL004"],
                           inventory=inv2)
    msgs = "\n".join(f.message for f in findings)
    assert "serve.nonexistent_site" in msgs          # fault site
    assert "req/made_up_kind" in msgs                # flight kind
    assert "serving/not_a_documented_metric" in msgs  # metric
    assert "DS_TOTALLY_UNDOCUMENTED" in msgs         # env var
    assert "serving.not_a_real_key" in msgs          # config key
    assert len(findings) == 5


def test_dsl004_good_twin_passes(inv):
    rel = "deepspeed_tpu/serving/snippet.py"
    inv2 = _snippet_inv(inv, _DSL004_GOOD, rel)
    assert lint_source(_DSL004_GOOD, relpath=rel, rules=["DSL004"],
                       inventory=inv2) == []


def test_dsl004_config_key_resolution(inv):
    assert inv.config_key_exists("serving.block_size")
    assert inv.config_key_exists("serving.spec.max_draft_tokens")
    assert inv.config_key_exists("serving.prefix_cache.max_cached_blocks")
    assert inv.config_key_exists("serving.slo.classes")
    assert inv.config_key_exists(
        "serving.slo.classes.interactive.ttft_ms")
    assert inv.config_key_exists("serving.chunked_prefill.chunk_tokens")
    assert inv.config_key_exists("resilience.retry.deadline_s")
    assert inv.config_key_exists("telemetry.flightrec_events")
    assert not inv.config_key_exists("serving.bogus")
    assert not inv.config_key_exists("serving.spec.bogus")
    assert not inv.config_key_exists("serving.block_size.nested")
    assert not inv.config_key_exists("telemetry.trace.bogus")


def test_dsl004_inventory_extraction_shapes(inv):
    # the whole-tree scan found the registries PRs 1-9 built
    assert "serve.step" in inv.fault_sites_fired
    assert "ckpt.manifest" in inv.fault_sites_fired   # site= kw form
    assert "serve.chunk" in inv.fault_sites_declared
    assert "req/resume" in inv.flight_kinds_recorded  # IfExp arg form
    assert "anomaly/*" in inv.flight_kinds_recorded   # f-string prefix
    assert inv.flight_kind_known("anomaly/train.step")
    assert not inv.flight_kind_known("nonsense/kind")
    assert "DS_FAULTS" in inv.env_reads               # module-const form
    assert "DS_SERVE_DEBUG" in inv.env_reads
    assert "serving/generated_tokens" in inv.metrics_emitted
    assert "serving/goodput" in inv.metrics_emitted   # gauges.update kw
    assert "train/step_latency_s" in inv.metrics_emitted
    assert any(r.value == "serving.block_size" for r in inv.config_refs)


# =====================================================================
# DSL005 resilience hygiene
# =====================================================================

_DSL005_BAD = '''
import os

def save_tag(path, blob):
    try:
        risky()
    except:                       # bare
        cleanup()
    try:
        retry()
    except Exception:             # swallowed broad
        pass
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
    os.replace(tmp, path)         # rename without fsync
'''

_DSL005_GOOD = '''
import os

def save_tag(path, blob):
    try:
        risky()
    except OSError:
        cleanup()
    try:
        retry()
    except ValueError as e:
        log(e)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(blob)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
'''


def test_dsl005_flags_all_three_patterns():
    findings = lint_source(_DSL005_BAD,
                           relpath="deepspeed_tpu/resilience/ckpt.py",
                           rules=["DSL005"])
    msgs = "\n".join(f.message for f in findings)
    assert "bare 'except:'" in msgs
    assert "silently swallowed" in msgs
    assert "without any fsync" in msgs
    assert len(findings) == 3


def test_dsl005_good_twin_passes():
    assert lint_source(_DSL005_GOOD,
                       relpath="deepspeed_tpu/resilience/ckpt.py",
                       rules=["DSL005"]) == []


def test_dsl005_rename_rule_scoped_to_checkpoint_files():
    # same rename-without-fsync outside checkpoint code: not this rule's
    # business (tracing flushes etc. make their own durability calls)
    findings = lint_source(_DSL005_BAD,
                           relpath="deepspeed_tpu/telemetry/x.py",
                           rules=["DSL005"])
    assert all("fsync" not in f.message for f in findings)


_DSL005_WRITE_BAD = '''
class Eng:
    def demote(self, key, buf, path):
        # only the request id survives the call — a terminal write
        # failure has nothing left to revert from
        self._writes[key] = self.aio.submit_pwrite(buf, path)
'''

_DSL005_WRITE_RETAINS = '''
class Eng:
    def demote(self, key, buf, path):
        self._writes[key] = self.aio.submit_pwrite(buf, path)
        self._pending[key] = buf          # source retained until reap
'''

_DSL005_WRITE_REAPS = '''
class Eng:
    def swap_out(self, key, buf, path):
        rid = self.aio.submit_pwrite(buf, path)
        if self.aio.wait_req(rid) != 0:   # reaped in-scope
            raise IOError(key)
'''


def test_dsl005_flags_release_before_reap_write():
    findings = lint_source(_DSL005_WRITE_BAD,
                           relpath="deepspeed_tpu/offload/x.py",
                           rules=["DSL005"])
    assert len(findings) == 1
    assert "retains the source buffer" in findings[0].message


def test_dsl005_write_retention_good_twins_pass():
    # retaining the bytes on self OR reaping in-scope both satisfy the
    # durability-ordering contract
    for src in (_DSL005_WRITE_RETAINS, _DSL005_WRITE_REAPS):
        assert lint_source(src, relpath="deepspeed_tpu/offload/x.py",
                           rules=["DSL005"]) == []


# =====================================================================
# suppressions + baseline machinery
# =====================================================================

def test_suppression_with_justification_silences():
    src = '''
def f():
    try:
        g()
    # dslint: disable=DSL005 -- deliberate: teardown best-effort
    except Exception:
        pass
'''
    assert lint_source(src, rules=["DSL005"]) == []


def test_suppression_same_line_and_header_scope():
    src = '''
import time

def f(lock):
    with lock._lock:  # dslint: disable=DSL002 -- test double, no loop
        time.sleep(0.1)
        time.sleep(0.2)
'''
    assert lint_source(src, rules=["DSL002"]) == []


def test_unjustified_suppression_is_a_finding():
    src = '''
def f():
    try:
        g()
    # dslint: disable=DSL005
    except Exception:
        pass
'''
    findings = lint_source(src)
    assert any(f.rule == "DSL000" and "justification" in f.message
               for f in findings)
    # the suppression still applies — DSL005 itself is silenced
    assert all(f.rule != "DSL005" for f in findings)


def test_unknown_rule_suppression_is_a_finding():
    src = "x = 1  # dslint: disable=DSL999 -- no such rule\n"
    findings = lint_source(src)
    assert any(f.rule == "DSL000" and "unknown rule" in f.message
               for f in findings)


def test_docstring_mentioning_syntax_is_not_a_suppression():
    src = '''
def f():
    """Docs may say '# dslint: disable=DSL005 -- why' freely."""
    try:
        g()
    except Exception:
        pass
'''
    findings = lint_source(src)
    assert any(f.rule == "DSL005" for f in findings)
    assert all(f.rule != "DSL000" for f in findings)


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    bad = tmp_path / "deepspeed_tpu"
    bad.mkdir()
    f = bad / "victim.py"
    f.write_text("def g():\n    try:\n        h()\n    except Exception:"
                 "\n        pass\n")
    # no baseline: finding reported
    res = lint_paths([str(f)], str(tmp_path), rules=["DSL005"],
                     baseline=[])
    assert len(res.findings) == 1 and not res.ok
    entry = res.findings[0]
    baseline = [{"rule": entry.rule, "path": entry.path,
                 "message": entry.message},
                {"rule": "DSL005", "path": entry.path,
                 "message": "this one was fixed long ago"}]
    res2 = lint_paths([str(f)], str(tmp_path), rules=["DSL005"],
                      baseline=baseline)
    assert res2.ok and len(res2.baselined) == 1
    assert len(res2.stale_baseline) == 1
    assert "fixed long ago" in res2.stale_baseline[0]["message"]
    # line drift doesn't resurrect: shift the finding down two lines
    f.write_text("X = 1\nY = 2\ndef g():\n    try:\n        h()\n"
                 "    except Exception:\n        pass\n")
    res3 = lint_paths([str(f)], str(tmp_path), rules=["DSL005"],
                      baseline=baseline[:1])
    assert res3.ok and len(res3.baselined) == 1


def test_standalone_suppression_is_line_scoped():
    # review regression: a standalone comment suppresses only its next
    # code line — it must NOT widen to a following compound statement's
    # whole body (one blessed line covering a whole function)
    src = '''
def f():
    try:
        g()
    # dslint: disable=DSL005 -- only this first one is deliberate
    except Exception:
        pass
    try:
        h()
    except Exception:
        pass
'''
    findings = lint_source(src, rules=["DSL005"])
    assert len(findings) == 1, [f.format() for f in findings]
    assert findings[0].line > 8  # the second handler still flags
    # and a comment above a `def`/`try` header blesses nothing inside
    src2 = '''
# dslint: disable=DSL005 -- misplaced blanket attempt
def f():
    try:
        g()
    except Exception:
        pass
'''
    assert len(lint_source(src2, rules=["DSL005"])) == 1


def test_scoped_write_baseline_keeps_out_of_scope_entries(tmp_path):
    # review regression: --write-baseline on a scoped run must not drop
    # grandfathered entries for files outside the scope
    pkg = tmp_path / "deepspeed_tpu"
    pkg.mkdir()
    bad = "def g():\n    try:\n        h()\n    except Exception:\n" \
          "        pass\n"
    (pkg / "a.py").write_text(bad)
    (pkg / "b.py").write_text(bad)
    bl_dir = pkg / "tools" / "dslint"
    bl_dir.mkdir(parents=True)
    bl_path = baseline_path(str(tmp_path))
    full = lint_paths([str(pkg)], str(tmp_path), rules=["DSL005"],
                      baseline=[])
    write_baseline(bl_path, full.findings)
    assert len(load_baseline(bl_path)) == 2
    # scoped run over a.py only, then rewrite merging out-of-scope
    scoped = lint_paths([str(pkg / "a.py")], str(tmp_path),
                        rules=["DSL005"], baseline=load_baseline(bl_path))
    keep = [e for e in load_baseline(bl_path)
            if e["path"] not in scoped.checked_paths]
    assert len(keep) == 1 and keep[0]["path"].endswith("b.py")
    write_baseline(bl_path, scoped.findings + scoped.baselined,
                   keep=keep)
    assert len(load_baseline(bl_path)) == 2  # b.py's entry survived
    full2 = lint_paths([str(pkg)], str(tmp_path), rules=["DSL005"],
                       baseline=load_baseline(bl_path))
    assert full2.ok and len(full2.baselined) == 2


def test_nonexistent_path_raises_not_clean(tmp_path):
    # review regression: a typo'd path must error, not report 0
    # findings forever
    with pytest.raises(FileNotFoundError):
        lint_paths([str(tmp_path / "no_such_dir")], str(tmp_path),
                   baseline=[])
    script = os.path.join(ROOT, "scripts", "dslint.py")
    r = subprocess.run([sys.executable, script, "no/such/dir"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "no such file" in r.stderr


def test_deferred_callbacks_not_flagged_under_lock():
    # review regression: a nested def/lambda defined under the lock
    # runs LATER, outside it — and nested lock-withs report once
    src = '''
import time

class S:
    def step(self):
        with self._lock:
            self._cb = lambda: open("/tmp/x").read()
            def deferred():
                time.sleep(1)
            self._later = deferred
'''
    assert lint_source(src, rules=["DSL002"]) == []
    nested = '''
import time

class S:
    def step(self):
        with self._lock:
            with self._other_lock:
                time.sleep(1)
'''
    assert len(lint_source(nested, rules=["DSL002"])) == 1


def test_fsync_rule_does_not_conflate_nested_scopes():
    # review regression: an inner def's fsync-less write must not pair
    # with the outer fn's rename of an unrelated file
    src = '''
import os

def publish(path):
    def _scratch():
        with open("/tmp/scratch", "w") as f:
            f.write("x")
    _scratch()
    os.replace(path + ".ready", path)   # renames a file it never wrote
'''
    findings = lint_source(src, relpath="deepspeed_tpu/resilience/ckpt.py",
                           rules=["DSL005"])
    assert all("fsync" not in f.message for f in findings), \
        [f.format() for f in findings]


def test_injector_regex_not_fooled_by_default():
    src = '''
def f(self):
    self.default.check("not.a.fault.site")
'''
    import dslint.inventory as di
    inv2 = Inventory.empty()
    inv2.scan_module(ast.parse(src), "deepspeed_tpu/x.py")
    assert inv2.fault_sites_fired == {}
    assert di._INJECTOR_RE.search("self.fault_injector")
    assert di._INJECTOR_RE.search("inj")
    assert not di._INJECTOR_RE.search("self.default")


def test_select_write_baseline_keeps_other_rules():
    # review regression: --select + --write-baseline must not drop
    # grandfathered entries of non-selected rules on in-scope paths
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_dslint_runner", os.path.join(ROOT, "scripts", "dslint.py"))
    runner = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(runner)
    entry_other_rule = {"rule": "DSL005", "path": "deepspeed_tpu/a.py",
                        "message": "grandfathered other-rule"}
    entry_selected = {"rule": "DSL002", "path": "deepspeed_tpu/a.py",
                      "message": "selected-rule, in scope: regenerated"}
    entry_other_path = {"rule": "DSL002", "path": "deepspeed_tpu/b.py",
                        "message": "out of scope: kept"}
    keep = runner.baseline_entries_to_keep(
        [entry_other_rule, entry_selected, entry_other_path],
        checked_paths={"deepspeed_tpu/a.py"}, select=["DSL002"])
    assert keep == [entry_other_rule, entry_other_path]
    # unscoped rules (select=None): only path scoping applies
    keep2 = runner.baseline_entries_to_keep(
        [entry_other_rule, entry_other_path],
        checked_paths={"deepspeed_tpu/a.py"}, select=None)
    assert keep2 == [entry_other_path]


def test_parse_error_is_reported_not_fatal(tmp_path):
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    res = lint_paths([str(bad)], str(tmp_path), baseline=[])
    assert len(res.findings) == 1
    assert res.findings[0].rule == "DSL000"
    assert "syntax error" in res.findings[0].message


# =====================================================================
# tier-1 acceptance: the live tree lints clean (modulo baseline)
# =====================================================================

@pytest.mark.dslint
def test_live_tree_lints_clean(inv):
    """ISSUE 10 acceptance: `python scripts/dslint.py deepspeed_tpu/`
    exits 0 on the final tree with an empty-or-justified baseline."""
    result = lint_paths(list(SCAN_ROOTS), ROOT, inventory=inv)
    assert result.files_checked > 150
    formatted = "\n".join(f.format() for f in result.findings)
    assert result.ok, f"dslint found new violations:\n{formatted}"
    # the baseline may grandfather, but it must not rot
    assert result.stale_baseline == [], (
        "baseline entries no longer match any finding — prune them: "
        f"{result.stale_baseline}")
    # committed baseline is empty-or-justified (acceptance wording)
    entries = load_baseline(baseline_path(ROOT))
    assert entries == [], "baseline must stay empty on this tree"


@pytest.mark.dslint
def test_registries_doc_in_sync(inv):
    path = os.path.join(ROOT, "docs", "reference", "registries.md")
    with open(path, encoding="utf-8") as f:
        actual = f.read()
    assert actual == generate_registries_md(inv), (
        "docs/reference/registries.md drifted — regenerate with "
        "'python scripts/dslint.py --write-registries'")


@pytest.mark.dslint
def test_runner_cli(tmp_path):
    """scripts/dslint.py end-to-end: rule catalog, JSON output + exit
    codes on a known-bad file, --changed smoke."""
    script = os.path.join(ROOT, "scripts", "dslint.py")
    r = subprocess.run([sys.executable, script, "--rules"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0
    for rule in ("DSL001", "DSL002", "DSL003", "DSL004", "DSL005"):
        assert rule in r.stdout
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    f()\nexcept:\n    pass\n")
    r = subprocess.run([sys.executable, script, "--json", str(bad)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 1
    doc = json.loads(r.stdout)
    assert doc["ok"] is False
    assert any(f["rule"] == "DSL005" for f in doc["findings"])
    r = subprocess.run([sys.executable, script, "--changed"],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode in (0, 1), r.stdout + r.stderr


# =====================================================================
# importability satellite: scripts analyze as modules, no side effects
# =====================================================================

def _import_script(name):
    import importlib.util
    path = os.path.join(ROOT, "scripts", f"{name}.py")
    spec = importlib.util.spec_from_file_location(f"_dslint_test_{name}",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_chaos_smoke_imports_without_side_effects():
    env_before = dict(os.environ)
    path_before = list(sys.path)
    mod = _import_script("chaos_smoke")
    assert dict(os.environ) == env_before, \
        "importing chaos_smoke mutated os.environ"
    assert sys.path == path_before, \
        "importing chaos_smoke mutated sys.path"
    assert callable(mod.main)


def test_trace_validate_imports_and_validates(tmp_path):
    env_before = dict(os.environ)
    mod = _import_script("trace_validate")
    assert dict(os.environ) == env_before
    trace = tmp_path / "t.json"
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "s", "ph": "B", "ts": 1, "pid": 1, "tid": 1},
        {"name": "s", "ph": "E", "ts": 2, "pid": 1, "tid": 1},
    ]}))
    assert mod.validate(str(trace)) == []
    trace.write_text(json.dumps({"traceEvents": [
        {"name": "s", "ph": "B", "ts": 5, "pid": 1, "tid": 1},
        {"name": "s", "ph": "E", "ts": 2, "pid": 1, "tid": 1},
    ]}))
    assert mod.validate(str(trace)) != []


def test_scripts_are_in_lint_scope(inv):
    # chaos_smoke/trace_validate are analyzed as modules by the same
    # pass that covers deepspeed_tpu/ (the ISSUE 10 satellite)
    result = lint_paths(["scripts/chaos_smoke.py",
                         "scripts/trace_validate.py"], ROOT,
                        inventory=inv)
    assert result.files_checked == 2
    assert result.ok, "\n".join(f.format() for f in result.findings)
