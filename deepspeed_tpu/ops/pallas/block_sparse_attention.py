"""Block-sparse attention kernel (Pallas/TPU, from scratch).

The TPU-native equivalent of the reference's Triton block-sparse attention
(deepspeed/ops/sparse_attention/matmul.py ``_sparse_matmul`` SDD/DSD modes +
softmax.py, driven by the `SparsityConfig` block layouts).  The reference
compiles a per-layout Triton lookup table; here the static layout becomes
**scalar-prefetched active-block index lists**, and the kernel runs a
flash-style online-softmax sweep that only ever DMAs and multiplies the
live KV blocks — masked blocks cost zero FLOPs and zero HBM traffic, so
compute scales with layout density, not S².

Layout semantics match ops/sparse_attention.py's dense block-masked path
(NEG_INF = -1e30 additive masking) — the two implementations are
numerically interchangeable, which the tests assert.

Grid: (B, H, n_q_blocks, max_active) with the KV step innermost; the KV
BlockSpec's index map reads the prefetched index list, so inactive steps
clamp to the last live block (DMA'd but skipped by ``pl.when``).
"""
import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _plan(layout: np.ndarray, causal: bool):
    """[H, nq, nk] 0/1 block layout -> (kv_idx [H, nq, max_active] int32,
    kv_cnt [H, nq] int32).  Static (numpy) — the layout is config, not data."""
    if causal:
        layout = np.tril(layout)
    H, nq, nk = layout.shape
    cnt = layout.sum(-1).astype(np.int32)                    # [H, nq]
    max_active = max(int(cnt.max()), 1)
    idx = np.zeros((H, nq, max_active), np.int32)
    for h in range(H):
        for q in range(nq):
            active = np.nonzero(layout[h, q])[0]
            idx[h, q, :len(active)] = active
            if len(active):                                   # clamp target
                idx[h, q, len(active):] = active[-1]
    return idx, cnt, max_active


def _kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref,
            acc_ref, m_ref, l_ref, *, scale, causal, block, max_active,
            out_dtype):
    import jax.experimental.pallas as pl

    h = pl.program_id(1)
    qi = pl.program_id(2)
    s = pl.program_id(3)

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(s < cnt_ref[h, qi])
    def _step():
        kb = idx_ref[h, qi, s]
        qv = q_ref[0, 0].astype(jnp.float32)                  # [BQ, hd]
        kv = k_ref[0, 0].astype(jnp.float32)                  # [BK, hd]
        scores = jax.lax.dot_general(
            qv, kv, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [BQ, BK]
        if causal:
            q_pos = qi * block + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 0)
            k_pos = kb * block + jax.lax.broadcasted_iota(
                jnp.int32, scores.shape, 1)
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, scores.max(-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_ref[:] = l_prev * alpha + p.sum(-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(s == max_active - 1)
    def _emit():
        # rows with no live blocks (fully masked) emit 0 — the flash
        # convention, shared with the dense path's row_any guard
        l = l_ref[:]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[:] / jnp.maximum(l, 1e-30),
            0.0).astype(out_dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block", "sm_scale",
                                    "interpret"))
def _call(q, k, v, kv_idx, kv_cnt, causal, block, sm_scale, interpret):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    nq = S // block
    max_active = kv_idx.shape[-1]
    scale = sm_scale if sm_scale is not None else hd ** -0.5

    # _plan pads every idx row to max_active with its last live block (or 0
    # for empty rows), so the raw entry is always a safe DMA target
    kv_spec = pl.BlockSpec(
        (1, 1, block, hd),
        lambda b, h, qi, s, idx, cnt: (b, h, idx[h, qi, s], 0))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nq, max_active),
        in_specs=[
            pl.BlockSpec((1, 1, block, hd),
                         lambda b, h, qi, s, idx, cnt: (b, h, qi, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=pl.BlockSpec((1, 1, block, hd),
                               lambda b, h, qi, s, idx, cnt: (b, h, qi, 0)),
        scratch_shapes=[
            pltpu.VMEM((block, hd), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block=block,
        max_active=max_active, out_dtype=q.dtype)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        interpret=interpret,
    )(kv_idx, kv_cnt, q, k, v)


def block_sparse_attention_trainable(q, k, v, layout: np.ndarray,
                                     causal: bool = False,
                                     sm_scale: Optional[float] = None):
    """Differentiable wrapper: forward runs the block-skipping kernel,
    backward differentiates the numerically-identical dense block-masked
    path (ops/sparse_attention.py) — correct gradients today; the fused
    Pallas backward is the remaining upgrade.  Backward recomputes the
    [S, S] scores (flash-style no-residuals trade)."""
    from deepspeed_tpu.ops import sparse_attention as sa

    def dense(q, k, v):
        cfg = _LayoutShim(layout)
        return sa.sparse_self_attention(q, k, v, cfg, causal=causal,
                                        sm_scale=sm_scale)

    @jax.custom_vjp
    def f(q, k, v):
        return block_sparse_attention(q, k, v, layout, causal=causal,
                                      sm_scale=sm_scale)

    def fwd(q, k, v):
        return f(q, k, v), (q, k, v)

    def bwd(res, g):
        _, vjp = jax.vjp(dense, *res)
        return vjp(g)

    f.defvjp(fwd, bwd)
    return f(q, k, v)


class _LayoutShim:
    """Adapts a raw [H, n, n] layout to the SparsityConfig interface."""

    def __init__(self, layout):
        self._layout = np.asarray(layout)

    def make_layout(self, seq_len):
        return self._layout


def block_sparse_attention(q, k, v, layout: np.ndarray, causal: bool = False,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """q/k/v [B, S, H, hd], layout [H, S//block, S//block] (0/1 numpy) ->
    [B, S, H, hd].  Skipped blocks are never loaded or multiplied.

    ``interpret`` defaults to True off-TPU (CPU tests run the kernel through
    the Pallas interpreter).
    """
    B, S, H, hd = q.shape
    nq = layout.shape[1]
    block = S // nq
    assert S % nq == 0, (S, nq)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    kv_idx, kv_cnt, _ = _plan(np.asarray(layout), causal)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _call(qt, kt, vt, jnp.asarray(kv_idx), jnp.asarray(kv_cnt),
                causal=causal, block=block, sm_scale=sm_scale,
                interpret=bool(interpret))
    return out.transpose(0, 2, 1, 3)
