"""Mesh topology tests (reference: tests/unit/runtime/pipe/test_topology.py +
groups algebra)."""
import pytest

from deepspeed_tpu.comm.mesh import MeshTopology


def test_default_topology_all_data(devices8):
    t = MeshTopology()
    assert t.world_size == 8
    assert t.dp_world_size == 8
    assert t.zero_world_size == 8
    assert dict(t.mesh.shape) == {"pipe": 1, "expert": 1, "data": 8, "hpz": 1,
                                  "seq": 1, "model": 1}


def test_tp_dp_split(devices8):
    t = MeshTopology(model_parallel_size=2)
    assert t.dp_world_size == 4
    assert t.axis_size("model") == 2


def test_full_5d(devices8):
    t = MeshTopology(model_parallel_size=2, pipe_parallel_size=2,
                     sequence_parallel_size=2)
    assert t.dp_world_size == 1
    assert dict(t.mesh.shape) == {"pipe": 2, "expert": 1, "data": 1, "hpz": 1,
                                  "seq": 2, "model": 2}


def test_expert_carved_from_data(devices8):
    t = MeshTopology(expert_parallel_size=4)
    assert t.dp_world_size == 8          # ep x data = 4 x 2
    assert t.axis_size(t.expert_parallel_axes) == 4
    assert t.axis_size(t.expert_data_parallel_axes) == 2


def test_zero_includes_seq(devices8):
    t = MeshTopology(sequence_parallel_size=2)
    assert t.dp_world_size == 4
    assert t.zero_world_size == 8        # seq x data combined group


def test_invalid_sizes(devices8):
    with pytest.raises(ValueError):
        MeshTopology(model_parallel_size=3)
    with pytest.raises(ValueError):
        MeshTopology(expert_parallel_size=3)
    with pytest.raises(ValueError):
        MeshTopology(data_parallel_size=4, model_parallel_size=1)
