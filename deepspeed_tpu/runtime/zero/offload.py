"""ZeRO-Offload / ZeRO-Infinity host optimizer tiers (reference:
stage_1_and_2.py:1102 CPU grad offload + csrc/adam cpu_adam for device=cpu;
runtime/swap_tensor/* + csrc/aio for device=nvme).

The jitted step ends at gradients; this module owns the fp32 master weights and
Adam moments in host DRAM (or on NVMe, streamed through the async I/O op),
updates them with the C++ SIMD optimizer, and returns the compute-dtype working
parameters for upload.  HBM then holds only working params + grads — the same
memory shape as the reference's offload tiers.
"""
from typing import Callable, Dict, Optional

import numpy as np
import jax

from deepspeed_tpu.ops.adam.cpu_adam import (DeepSpeedCPUAdam,
                                             DeepSpeedCPUAdagrad,
                                             DeepSpeedCPULamb)
from deepspeed_tpu.runtime import constants as C
from deepspeed_tpu.utils.logging import log_dist


def _flatten_with_paths(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(str(getattr(k, "key", k)) for k in path), leaf)
            for path, leaf in flat]


class HostOffloadOptimizer:
    """Owns master fp32 params + optimizer moments on host; steps via C++."""

    def __init__(self, params_tree, optimizer_name: str, optimizer_params: dict,
                 gradient_clipping: float = 0.0,
                 lr_schedule: Optional[Callable] = None,
                 nvme_swapper=None, masters_on_nvme: bool = False):
        optimizer_params = dict(optimizer_params or {})
        self.base_lr = float(optimizer_params.get("lr", 1e-3))
        self.lr_schedule = lr_schedule
        self.gradient_clipping = gradient_clipping
        self.nvme = nvme_swapper
        name = (optimizer_name or C.ADAM_OPTIMIZER).lower()
        betas = optimizer_params.get("betas", (0.9, 0.999))
        wd = float(optimizer_params.get("weight_decay", 0.0))
        eps = float(optimizer_params.get("eps", 1e-8))
        if name in (C.ADAM_OPTIMIZER, C.ADAMW_OPTIMIZER, C.FUSED_ADAM,
                    C.CPU_ADAM):
            adamw = (name == C.ADAMW_OPTIMIZER
                     or optimizer_params.get("adam_w_mode", True))
            self.opt = DeepSpeedCPUAdam(lr=self.base_lr, betas=betas, eps=eps,
                                        weight_decay=wd,
                                        adamw_mode=bool(adamw and wd > 0))
            self.n_moments = 2
        elif name in (C.LAMB_OPTIMIZER, C.FUSED_LAMB):
            self.opt = DeepSpeedCPULamb(lr=self.base_lr, betas=betas, eps=eps,
                                        weight_decay=wd)
            self.n_moments = 2
        elif name == C.ADAGRAD_OPTIMIZER:
            self.opt = DeepSpeedCPUAdagrad(lr=self.base_lr, eps=eps,
                                           weight_decay=wd)
            self.n_moments = 1
        else:
            raise ValueError(f"host offload does not support optimizer {name}")

        # host master copies (flat fp32 per leaf); with masters_on_nvme the
        # fp32 master tier streams through the aio op like the moments
        # (reference: NVMe optimizer offload swaps fp32 params + moments,
        # partitioned_optimizer_swapper.py)
        self.masters_on_nvme = bool(masters_on_nvme and nvme_swapper is not None)
        self.master: Dict[str, Optional[np.ndarray]] = {}
        self.shapes: Dict[str, tuple] = {}
        self.treedef = jax.tree_util.tree_structure(params_tree)
        self.paths = []
        for path, leaf in _flatten_with_paths(params_tree):
            host_leaf = jax.device_get(leaf)
            arr = np.ascontiguousarray(
                np.asarray(host_leaf).astype(np.float32).ravel())
            self.paths.append(path)
            self.shapes[path] = tuple(np.shape(host_leaf))
            if self.masters_on_nvme:
                self.nvme.swap_out(f"{path}.w", arr)
                self.master[path] = None
            else:
                self.master[path] = arr
        if self.masters_on_nvme:
            self.nvme.drain()
        self.moments: Dict[str, list] = {}
        for path in self.paths:
            numel = int(np.prod(self.shapes[path])) if self.shapes[path] else 1
            bufs = [np.zeros(numel, np.float32)
                    for _ in range(self.n_moments)]
            if self.nvme is not None:
                for j, b in enumerate(bufs):
                    self.nvme.swap_out(f"{path}.m{j}", b)
                self.moments[path] = None
            else:
                self.moments[path] = bufs
        if self.nvme is not None:
            # one drain for the whole moment tier: each path's buffers are
            # freshly allocated and never touched again here, so the writes
            # can all ride the same queue-depth window instead of init
            # running at single-request depth (ISSUE 17 small fix)
            self.nvme.drain()
        master_bytes = sum(4 * int(np.prod(s) if s else 1)
                           for s in self.shapes.values())
        dram_copies = ((0 if self.masters_on_nvme else 1) +
                       (0 if self.nvme is not None else self.n_moments))
        #: memory-ledger attribution (ISSUE 14): fp32 state resident in
        #: host DRAM vs streamed through the NVMe swap files (the
        #: swapper accounts the nvme tier itself, per swap dir)
        self.host_dram_bytes = master_bytes * dram_copies
        self.nvme_bytes = master_bytes * (
            (1 if self.masters_on_nvme else 0)
            + (self.n_moments if self.nvme is not None else 0))
        log_dist(f"HostOffloadOptimizer: {len(self.paths)} tensors, "
                 f"{master_bytes * dram_copies / 1e9:.2f} GB host DRAM"
                 + (", masters+moments on NVMe" if self.masters_on_nvme
                    else (", moments on NVMe" if self.nvme is not None
                          else "")),
                 ranks=[0])

    # ------------------------------------------------------------------ step
    def current_lr(self, step: int) -> float:
        if self.lr_schedule is not None:
            return float(self.lr_schedule(step))
        return self.base_lr

    def step(self, grads_tree, step_index: int, compute_dtype,
             sink=None) -> tuple:
        """grads_tree: device (or host) pytree of fp32 grads.
        Returns (new_params_tree as numpy in compute_dtype, grad_norm,
        overflow: bool).

        ``sink(path, arr) -> bool`` optionally consumes updated leaves as
        they are produced (the streamed-param tier hands block leaves to
        the ParamStore instead of materializing the full tree); a consumed
        leaf becomes ``None`` in the returned tree.  On overflow with a
        sink armed the tree is ``None`` — the caller keeps its current
        params rather than paying a full master rebuild."""
        grads = [np.asarray(jax.device_get(g)).astype(np.float32).ravel()
                 for g in jax.tree_util.tree_leaves(grads_tree)]
        # overflow check (reference has_overflow_serial)
        overflow = any(not np.all(np.isfinite(g)) for g in grads)
        gn_sq = sum(float(np.dot(g, g)) for g in grads) if not overflow else 0.0
        grad_norm = float(np.sqrt(gn_sq))
        if overflow:
            if sink is not None:
                return (None, grad_norm, True)
            new_leaves = [self._get_master(p).reshape(self.shapes[p])
                          .astype(compute_dtype) for p in self.paths]
            return (jax.tree_util.tree_unflatten(self.treedef, new_leaves),
                    grad_norm, True)
        if self.gradient_clipping > 0 and grad_norm > self.gradient_clipping:
            scale = self.gradient_clipping / (grad_norm + 1e-6)
            for g in grads:
                g *= scale
        lr = self.current_lr(step_index)
        opt_step = getattr(self.opt, "step_count", 0) + 1
        new_leaves = []
        nvme_names = [[f"{p}.m{j}" for j in range(self.n_moments)]
                      for p in self.paths]
        if self.nvme is not None and self.paths:
            # double-buffered swap pipeline (reference
            # pipelined_optimizer_swapper.py): tensor i+1's reads are
            # submitted before blocking on tensor i's, and tensor i-1's
            # write-backs stay in flight underneath — the per-request aio
            # completions make all three overlap for real
            for nm in nvme_names[0]:
                self.nvme.prefetch(nm)
            if self.masters_on_nvme:
                self.nvme.prefetch(f"{self.paths[0]}.w")
        for i, (path, g) in enumerate(zip(self.paths, grads)):
            if self.nvme is not None:
                if i + 1 < len(self.paths):
                    for nm in nvme_names[i + 1]:
                        self.nvme.prefetch(nm)
                    if self.masters_on_nvme:
                        self.nvme.prefetch(f"{self.paths[i + 1]}.w")
                moments = [self.nvme.swap_in(nm) for nm in nvme_names[i]]
                p = (self.nvme.swap_in(f"{path}.w") if self.masters_on_nvme
                     else self.master[path])
            else:
                moments = self.moments[path]
                p = self.master[path]
            g = np.ascontiguousarray(g)
            if self.n_moments == 2:
                self.opt.step(p, g, moments[0], moments[1], lr=lr,
                              step=opt_step)
            else:
                self.opt.step(p, g, moments[0], lr=lr)
            if self.nvme is not None:
                for nm, mbuf in zip(nvme_names[i], moments):
                    self.nvme.swap_out(nm, mbuf)
                if self.masters_on_nvme:
                    self.nvme.swap_out(f"{path}.w", p)
            new_leaf = p.reshape(self.shapes[path]).astype(compute_dtype)
            if sink is not None and sink(path, new_leaf):
                new_leaf = None
            new_leaves.append(new_leaf)
        if self.nvme is not None:
            self.nvme.drain()
        return (jax.tree_util.tree_unflatten(self.treedef, new_leaves),
                grad_norm, False)

    def _get_master(self, path: str) -> np.ndarray:
        """Master fp32 buffer for `path`, reading through NVMe if needed
        (read-only access: the buffer is written straight back)."""
        if self.masters_on_nvme:
            return self.nvme.swap_in(f"{path}.w")   # file stays valid on disk
        return self.master[path]

    # ------------------------------------------------------------------ ckpt
    def state_dict(self) -> dict:
        moments = {}
        for i, path in enumerate(self.paths):
            if self.nvme is not None:
                moments[path] = [self.nvme.swap_in(f"{path}.m{j}")
                                 for j in range(self.n_moments)]
                for j in range(self.n_moments):
                    self.nvme.swap_out(f"{path}.m{j}", moments[path][j])
            else:
                moments[path] = self.moments[path]
        if self.nvme is not None:
            self.nvme.drain()
        master = {p: np.array(self._get_master(p)) for p in self.paths}
        return {
            "master": master,
            "moments": {p: list(m) for p, m in moments.items()},
            "step_count": getattr(self.opt, "step_count", 0),
        }

    def load_state_dict(self, sd: dict):
        for path in self.paths:
            loaded_master = np.ascontiguousarray(
                np.asarray(sd["master"][path], dtype=np.float32).ravel())
            if self.masters_on_nvme:
                self.nvme.swap_out(f"{path}.w", loaded_master)
            else:
                self.master[path][:] = loaded_master
            loaded = sd["moments"][path]
            if self.nvme is not None:
                for j in range(self.n_moments):
                    self.nvme.swap_out(
                        f"{path}.m{j}",
                        np.asarray(loaded[j], np.float32).ravel())
                self.nvme.drain()
            else:
                for j in range(self.n_moments):
                    self.moments[path][j][:] = np.asarray(
                        loaded[j], np.float32).ravel()
        if hasattr(self.opt, "step_count"):
            self.opt.step_count = int(sd.get("step_count", 0))

    def params_in_compute_dtype(self, compute_dtype):
        leaves = [self._get_master(p).reshape(self.shapes[p])
                  .astype(compute_dtype) for p in self.paths]
        return jax.tree_util.tree_unflatten(self.treedef, leaves)
