"""ZeRO-Offload / ZeRO-Infinity tests (reference capability: offload_optimizer
device=cpu/nvme; tests/unit/runtime/zero compare offload vs plain paths)."""
import numpy as np
import pytest

import jax

import deepspeed_tpu
from tests.util import tiny_gpt2, base_config, random_batches


def _has_pinned_host() -> bool:
    return any(m.kind == "pinned_host"
               for m in jax.local_devices()[0].addressable_memories())


#: environment-blocked (ROADMAP hygiene item 6): offload_param places
#: block params with memory_kind="pinned_host", which this container's
#: jaxlib CPU backend does not implement (its CPU devices address only
#: unpinned_host — engine init dies in jax sharding_impls with
#: "Could not find memory addressable by device cpu ... Got memory
#: kind: pinned_host").  Repro: any jax.device_put to
#: jax.local_devices()[0].memory("pinned_host") raises the same error;
#: the tests pass wherever the backend advertises pinned_host (newer
#: jaxlib CPU, any TPU).
requires_pinned_host = pytest.mark.skipif(
    not _has_pinned_host(),
    reason="jaxlib CPU backend lacks the pinned_host memory kind "
           "offload_param shards into (env-blocked; see module note)")


def _train(engine, steps=3, seed=0):
    losses = []
    for i in range(steps):
        b = random_batches(1, batch_size=8, seed=seed + i)[0]
        losses.append(float(engine.train_batch(
            batch={"input_ids": b["input_ids"][None]})))
    return losses


def test_cpu_offload_matches_device_adam(devices8):
    """offload_optimizer device=cpu must track the on-device optax Adam.

    Tolerance note: the host and fused-on-device paths place jit/fusion
    boundaries differently; near-zero grads under Adam's eps make step-1
    updates sign-sensitive, so trajectories agree only loosely (the exact
    per-op equivalence is pinned by test_native_ops).
    """
    ref, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=base_config())
    off, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 2,
                               "offload_optimizer": {"device": "cpu"}}))
    l_ref = _train(ref, steps=4, seed=21)
    l_off = _train(off, steps=4, seed=21)
    np.testing.assert_allclose(l_off, l_ref, rtol=2e-3, atol=2e-3)


def test_cpu_offload_no_device_opt_state(devices8):
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 2,
                               "offload_optimizer": {"device": "cpu"}}))
    assert engine.state["opt_state"] == ()
    assert engine.host_optimizer is not None


def test_nvme_offload_trains(devices8, tmp_path):
    """ZeRO-Infinity tier: optimizer moments streamed through the aio op."""
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 2,
                               "offload_optimizer": {
                                   "device": "nvme",
                                   "nvme_path": str(tmp_path)}}))
    losses = _train(engine, steps=3, seed=5)
    assert np.isfinite(losses).all()
    swap_files = list((tmp_path / "zero_stage_offload").glob("*.pay"))
    assert len(swap_files) > 0


def test_nvme_matches_cpu_offload(devices8, tmp_path):
    cpu, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 0,
                               "offload_optimizer": {"device": "cpu"}}))
    nvme, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            zero_optimization={"stage": 0,
                               "offload_optimizer": {
                                   "device": "nvme",
                                   "nvme_path": str(tmp_path)}}))
    l_cpu = _train(cpu, steps=3, seed=9)
    l_nvme = _train(nvme, steps=3, seed=9)
    np.testing.assert_allclose(l_nvme, l_cpu, rtol=1e-5, atol=1e-6)


def test_offload_checkpoint_roundtrip(devices8, tmp_path):
    cfg = base_config(zero_optimization={
        "stage": 2, "offload_optimizer": {"device": "cpu"}})
    e1, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    _train(e1, steps=2, seed=1)
    e1.save_checkpoint(str(tmp_path / "ck"))
    l_next = _train(e1, steps=1, seed=33)[0]

    e2, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    e2.load_checkpoint(str(tmp_path / "ck"))
    assert e2.host_optimizer.opt.step_count == e1.host_optimizer.opt.step_count - 1
    l_resume = _train(e2, steps=1, seed=33)[0]
    assert abs(l_next - l_resume) < 1e-5


def test_offload_async_checkpoint_roundtrip(devices8, tmp_path):
    """Async save with the host-optimizer tier: the aux npz snapshot is
    taken at save time and serialized on the background thread; training
    continues and the restore sees the save-time optimizer state."""
    cfg = base_config(zero_optimization={
        "stage": 2, "offload_optimizer": {"device": "cpu"}},
        checkpoint={"async_save": True})
    e1, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    _train(e1, steps=2, seed=1)
    e1.save_checkpoint(str(tmp_path / "ck"))
    l_next = _train(e1, steps=1, seed=33)[0]      # mutates host buffers
    e1.wait_pending_checkpoint()

    e2, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    e2.load_checkpoint(str(tmp_path / "ck"))
    assert (e2.host_optimizer.opt.step_count
            == e1.host_optimizer.opt.step_count - 1)
    l_resume = _train(e2, steps=1, seed=33)[0]
    assert abs(l_next - l_resume) < 1e-5


def test_offload_gradient_clipping(devices8):
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config(
            gradient_clipping=0.001,
            optimizer={"type": "SGD", "params": {"lr": 1.0}},
            zero_optimization={"offload_optimizer": {"device": "cpu"}})
    ) if False else (None,) * 4
    # SGD unsupported on host: expect the informative error instead
    with pytest.raises(ValueError, match="host offload"):
        deepspeed_tpu.initialize(
            model=tiny_gpt2(), config=base_config(
                optimizer={"type": "SGD", "params": {"lr": 1.0}},
                zero_optimization={"offload_optimizer": {"device": "cpu"}}))


def test_offload_micro_step_api(devices8):
    cfg = base_config(gradient_accumulation_steps=2,
                      zero_optimization={"offload_optimizer": {"device": "cpu"}})
    engine, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    for mb in random_batches(2, batch_size=8, seed=2):
        loss = engine.forward(mb)
        engine.backward(loss)
        engine.step()
    assert engine.global_steps == 1
    assert np.isfinite(float(loss))


# ----------------------------------------------------- ZeRO-Infinity param tier

@pytest.fixture
def mesh1():
    """Single-device mesh: param streaming is the one-chip memory-extension
    tier (the reference's 13B-on-one-V100 scenario)."""
    import jax
    return jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))


def test_offload_param_requires_offload_optimizer(mesh1):
    with pytest.raises(ValueError, match="offload_param requires"):
        deepspeed_tpu.initialize(
            model=tiny_gpt2(), mesh=mesh1, config=base_config(
                zero_optimization={"stage": 2,
                                   "offload_param": {"device": "cpu"}}))


def test_offload_param_multidevice_requires_stage3(devices8):
    """Multi-device ZeRO-Infinity needs the param shards to exist: stage
    < 3 is rejected (round-2 VERDICT item 2 replaced the blanket
    single-device restriction)."""
    with pytest.raises(ValueError, match="stage 3"):
        deepspeed_tpu.initialize(
            model=tiny_gpt2(remat=True), config=base_config(
                zero_optimization={
                    "stage": 2,
                    "offload_optimizer": {"device": "cpu"},
                    "offload_param": {"device": "cpu"}}))


@requires_pinned_host
def test_offload_param_multidevice_trains_to_parity(devices8):
    """offload_param on an 8-device mesh (full ZeRO-Infinity: per-device
    pinned-host shards of the layer stack, per-layer stream doubling as
    the stage-3 gather) matches plain stage-3 training."""
    def run(offload):
        from deepspeed_tpu.comm import reset_topology
        reset_topology()
        zo = {"stage": 3, "stage3_param_persistence_threshold": 0}
        if offload:
            zo.update(offload_optimizer={"device": "cpu"},
                      offload_param={"device": "cpu"})
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_gpt2(remat=True), config=base_config(
                gradient_accumulation_steps=2,
                zero_optimization=zo))
        # storage is sharded: the stacked blocks must NOT shard dim 0
        # (per-layer slice must stay device-local)
        spec = tuple(engine.param_specs["blocks"]["qkv_w"])
        assert spec[0] is None, spec
        rng = np.random.default_rng(7)
        losses = []
        for _ in range(3):
            batch = {"input_ids": rng.integers(
                0, 128, size=(2, 8, 16), dtype=np.int32)}
            losses.append(float(engine.train_batch(batch=batch)))
        return losses

    ref = run(offload=False)
    off = run(offload=True)
    np.testing.assert_allclose(off, ref, rtol=2e-4, atol=2e-4)


@requires_pinned_host
def test_offload_param_params_live_on_host(mesh1):
    """offload_param stores block params in pinned host memory —
    HBM holds O(1 layer), the ZeRO-Infinity memory shape (reference
    parameter_offload.py:201)."""
    import jax
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(remat=True), mesh=mesh1, config=base_config(
            zero_optimization={
                "stage": 0,
                "offload_optimizer": {"device": "cpu"},
                "offload_param": {"device": "cpu"}}))
    blocks = engine.state["params"]["blocks"]
    # matrix-shaped (>=3-dim stacked) leaves offload; tiny biases/norm leaves
    # stay device-resident (persistent-small rule + libtpu cannot
    # dynamic-slice packed bf16 2-D host buffers)
    for name in ("qkv_w", "proj_w", "mlp_in_w", "mlp_out_w"):
        assert blocks[name].sharding.memory_kind == "pinned_host", name
    assert blocks["ln1_scale"].sharding.memory_kind == "device"
    # block grads stream to host as the backward scan produces them (TPU
    # backends only: the CPU runtime cannot execute host-placed jit outputs)
    if jax.devices()[0].platform == "tpu":
        for leaf in jax.tree.leaves(engine.grad_shardings["blocks"]):
            assert leaf.memory_kind == "pinned_host"
    # non-block params stay on device
    assert engine.state["params"]["wte"].sharding.memory_kind == "device"


@requires_pinned_host
def test_offload_param_matches_no_offload(mesh1):
    """Training with the param-offload streaming path must match the plain
    host-offload path step for step (same optimizer, same grads)."""
    ref, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(remat=True), mesh=mesh1, config=base_config(
            zero_optimization={"stage": 0,
                               "offload_optimizer": {"device": "cpu"}}))
    inf, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(remat=True), mesh=mesh1, config=base_config(
            zero_optimization={"stage": 0,
                               "offload_optimizer": {"device": "cpu"},
                               "offload_param": {"device": "cpu"}}))
    l_ref = _train(ref, steps=3, seed=11)
    l_inf = _train(inf, steps=3, seed=11)
    np.testing.assert_allclose(l_inf, l_ref, rtol=1e-5, atol=1e-5)


@requires_pinned_host
def test_offload_param_with_gas(mesh1):
    """gas>1 exercises the python-level host grad accumulation."""
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(remat=True), mesh=mesh1, config=base_config(
            gradient_accumulation_steps=2,
            zero_optimization={"stage": 0,
                               "offload_optimizer": {"device": "cpu"},
                               "offload_param": {"device": "cpu"}}))
    for i in range(2):
        b1, b2 = random_batches(2, batch_size=8, seed=40 + i)
        stacked = {"input_ids": np.stack([b1["input_ids"], b2["input_ids"]])}
        loss = float(engine.train_batch(batch=stacked))
        assert np.isfinite(loss)


def _param_nvme_cfg(tmp_path, opt_device="nvme", **overrides):
    zo = {"stage": 0,
          "offload_optimizer": {"device": opt_device,
                                **({"nvme_path": str(tmp_path)}
                                   if opt_device == "nvme" else {})},
          "offload_param": {"device": "nvme",
                            "nvme_path": str(tmp_path)}}
    zo["offload_param"].update(overrides.pop("offload_param", {}))
    return base_config(zero_optimization=zo, **overrides)


def test_offload_param_nvme_masters(mesh1, tmp_path):
    """device=nvme for BOTH tiers: fp32 masters, moments AND the
    per-layer param shards all stream through one shared SwapEngine
    (ISSUE 17 — no pinned_host needed: blocks never touch the device,
    the streamed weight pass materializes a K-layer working set)."""
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), mesh=mesh1, config=_param_nvme_cfg(tmp_path))
    ho = engine.host_optimizer
    assert ho.masters_on_nvme
    assert all(v is None for v in ho.master.values())
    assert engine.param_store is not None
    # nonblock-only device params: the stacked blocks are never resident
    assert "blocks" not in engine.state["params"]
    losses = _train(engine, steps=3, seed=3)
    assert np.isfinite(losses).all()
    names = {f.name for f in (tmp_path / "zero_stage_offload").glob("*.pay")}
    assert any(n.endswith(".w.pay") for n in names), names   # masters on disk
    assert any(".m0" in n for n in names), names             # moments on disk
    assert any(n.startswith("param_L") for n in names), names  # layer shards


@requires_pinned_host
def test_offload_param_checkpoint_roundtrip(mesh1, tmp_path):
    cfg = base_config(
        zero_optimization={"stage": 0,
                           "offload_optimizer": {"device": "cpu"},
                           "offload_param": {"device": "cpu"}})
    e1, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(remat=True), mesh=mesh1,
                                      config=cfg)
    _train(e1, steps=2, seed=9)
    e1.save_checkpoint(str(tmp_path / "ck"))
    e2, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(remat=True), mesh=mesh1,
                                      config=cfg)
    e2.load_checkpoint(str(tmp_path / "ck"))
    l1 = _train(e1, steps=2, seed=13)
    l2 = _train(e2, steps=2, seed=13)
    np.testing.assert_allclose(l2, l1, rtol=1e-5, atol=1e-5)


# ------------------------------------------- ISSUE 17: NVMe-streamed params

def test_offload_param_nvme_matches_resident_bitwise(mesh1, tmp_path):
    """THE acceptance bar: a model whose full param stack exceeds the
    resident budget (4 layers, K=1) trains with losses BITWISE-identical
    to the all-resident host-offload baseline (same C++ Adam, same grad
    math — the streamed VJP chain is the same op sequence), and the
    tiered ledger prices the shard bytes under the params_nvme owner."""
    ref, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(num_layers=4), mesh=mesh1, config=base_config(
            zero_optimization={"stage": 0,
                               "offload_optimizer": {"device": "cpu"}}))
    nv, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(num_layers=4), mesh=mesh1, config=_param_nvme_cfg(
            tmp_path, opt_device="cpu",
            offload_param={"resident_layers": 1}))
    l_ref = _train(ref, steps=4, seed=17)
    l_nv = _train(nv, steps=4, seed=17)
    np.testing.assert_array_equal(np.float32(l_nv), np.float32(l_ref))
    # the working set really is smaller than the model
    assert nv.param_store.resident_layers == 1
    assert nv.param_store.sync_misses + nv.param_store.prefetch_hits > 0
    # overlap is MEASURED, never asserted — just a well-formed fraction
    assert 0.0 <= nv.param_store.overlap_fraction() <= 1.0
    from deepspeed_tpu.telemetry.memory import get_memory_ledger
    assert get_memory_ledger().owner_bytes("nvme", "params_nvme") > 0
    assert nv.param_store.failures == 0 and nv.param_store.degraded == 0


@pytest.mark.parametrize("spec", [
    "param.swap:stall=0.01@2",     # delayed I/O: pipeline absorbs it
    "param.swap:truncate@6+",      # torn shards: every read degrades to
                                   # the synchronous fp32-master rebuild
    "param.swap:deny@*",           # failed I/O on BOTH directions
    "param.swap:corrupt@6+",       # flipped shards: the checksum catches
                                   # them and masters rebuild + heal back
    "param.swap:corrupt=32@p0.4s18",   # seeded corruption storm
    "swap.io:corrupt=8@p0.4s18",   # media-level damage inside the engine
])
def test_offload_param_nvme_faults_never_corrupt(mesh1, tmp_path, spec):
    """param.swap/swap.io stall/truncate/deny/corrupt mid-step must
    degrade to a synchronous re-read (fp32 masters are authoritative) —
    the loss trajectory stays bitwise-identical to the fault-free run; a
    torn or flipped shard never reaches a matmul."""
    clean, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(num_layers=3), mesh=mesh1, config=_param_nvme_cfg(
            tmp_path / "clean", opt_device="cpu",
            offload_param={"resident_layers": 1}))
    faulty, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(num_layers=3), mesh=mesh1, config=_param_nvme_cfg(
            tmp_path / "fault", opt_device="cpu",
            offload_param={"resident_layers": 1},
            resilience={"faults": spec}))
    l_clean = _train(clean, steps=3, seed=23)
    l_fault = _train(faulty, steps=3, seed=23)
    np.testing.assert_array_equal(np.float32(l_fault), np.float32(l_clean))
    site = spec.split(":", 1)[0]
    assert faulty.fault_injector.fired.get(site, 0) > 0
    if "truncate" in spec or "corrupt" in spec:
        assert faulty.param_store.degraded > 0
    if "corrupt" in spec:
        assert faulty.param_store.engine.integrity_failures > 0


def test_offload_param_nvme_deny_without_masters_is_loud(tmp_path):
    """A failed shard read with NO rebuild source must raise, never
    step against missing weights (ParamStore without reload_fn)."""
    import os
    from deepspeed_tpu.offload import ParamStore, SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path))
    store = ParamStore(eng, num_layers=2, resident_layers=1)
    store.put_layer(0, {"w": np.ones((4, 4), np.float32)})
    store.put_layer(1, {"w": np.zeros((4, 4), np.float32)})  # evicts L0
    store.flush()
    os.remove(eng._path("param/L0000"))      # the shard is gone
    with pytest.raises(IOError, match="no reload source"):
        store.get_layer(0)


def test_offload_param_nvme_checkpoint_roundtrip(mesh1, tmp_path):
    cfg = _param_nvme_cfg(tmp_path / "swap")
    e1, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), mesh=mesh1,
                                      config=cfg)
    _train(e1, steps=2, seed=9)
    e1.save_checkpoint(str(tmp_path / "ck"))
    e2, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), mesh=mesh1,
        config=_param_nvme_cfg(tmp_path / "swap2"))
    e2.load_checkpoint(str(tmp_path / "ck"))
    l1 = _train(e1, steps=2, seed=13)
    l2 = _train(e2, steps=2, seed=13)
    np.testing.assert_array_equal(np.float32(l2), np.float32(l1))


def test_offload_param_nvme_eval_batch(mesh1, tmp_path):
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), mesh=mesh1, config=_param_nvme_cfg(tmp_path))
    b = random_batches(1, batch_size=4, seed=50)[0]
    loss = float(engine.eval_batch(b))
    assert np.isfinite(loss)


def test_offload_param_nvme_rejects_multidevice_and_fp16(devices8, tmp_path):
    with pytest.raises(ValueError, match="single"):
        deepspeed_tpu.initialize(
            model=tiny_gpt2(), config=_param_nvme_cfg(tmp_path))
    mesh1 = jax.sharding.Mesh(np.array(jax.devices()[:1]), ("data",))
    with pytest.raises(ValueError, match="fp16"):
        deepspeed_tpu.initialize(
            model=tiny_gpt2(), mesh=mesh1,
            config=_param_nvme_cfg(tmp_path, fp16={"enabled": True}))


def test_cold_param_source_serving_logits(mesh1, tmp_path):
    """Serving-side cold layers (ColdParamSource): streamed logits match
    the all-resident forward bitwise at CPU-suite shapes."""
    import jax as _jax
    from deepspeed_tpu.serving import ColdParamSource
    from deepspeed_tpu.offload import SwapEngine
    model = tiny_gpt2(num_layers=3)
    params = model.init(_jax.random.PRNGKey(0))
    batch = random_batches(1, batch_size=2, seed=77)[0]
    ref = np.asarray(model.apply(params, batch, None))
    eng = SwapEngine(nvme_dir=str(tmp_path))
    src = ColdParamSource.from_params(model, params, eng,
                                      resident_layers=1)
    got = np.asarray(src.forward_logits(batch))
    np.testing.assert_array_equal(got, ref)
    assert eng.count("nvme") == 3          # every layer shard went cold
    assert 0.0 <= src.overlap_fraction() <= 1.0


# ------------------------------------------ SwapEngine edge cases (ISSUE 17)

def test_swap_engine_prefetch_host_tier_noop(tmp_path):
    """prefetch() of a host-tier key is a no-op — no read ring entry,
    and fetch still returns the payload."""
    from deepspeed_tpu.offload import SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path))
    arr = np.arange(16, dtype=np.float32)
    eng.put("k", [arr], tier="host")
    eng.prefetch("k")
    assert eng.inflight_reads() == set()
    out = eng.fetch("k")
    np.testing.assert_array_equal(out[0], arr)


def test_swap_engine_discard_with_inflight_read(tmp_path):
    """discard() while a prefetch read is in flight reaps the request
    and drops the key — a later fetch is a clean KeyError, and the
    engine's rings stay consistent (drain sees nothing pending)."""
    from deepspeed_tpu.offload import SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path))
    eng.put("k", [np.arange(1 << 16, dtype=np.float32)], tier="nvme")
    eng.prefetch("k")
    assert "k" in eng.inflight_reads()
    eng.discard("k")
    assert eng.inflight_reads() == set()
    assert eng.tier_of("k") is None
    with pytest.raises(KeyError):
        eng.fetch("k")
    eng.drain()                              # nothing left to fail


def test_swap_engine_failed_read_sentinel_surfaces(tmp_path):
    """A read reaped as failed by the queue-depth window gate leaves the
    -1 sentinel; fetch must surface IOError — never the junk buffer.
    (The file is truncated BEHIND the engine, so its torn-payload
    bookkeeping can't catch it first: this exercises the backend
    short-read failure path.)"""
    import os
    from deepspeed_tpu.offload import SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path), queue_depth=1)
    a = np.arange(1 << 14, dtype=np.float32)
    eng.put("a", [a], tier="nvme")
    eng.put("b", [a], tier="nvme")
    eng.drain()
    os.truncate(eng._path("a"), a.nbytes // 2)   # fail behind its back
    eng.prefetch("a")
    # queue_depth=1: submitting b's read forces the gate to reap a's
    eng.prefetch("b")
    rid, buf = eng._inflight_reads["a"]
    assert rid == -1 and buf is None             # the sentinel
    with pytest.raises(IOError, match="read failed"):
        eng.fetch("a")
    out = eng.fetch("b")                         # neighbor unaffected
    np.testing.assert_array_equal(out[0], a)


# ---------------------------------------- ISSUE 18: storage integrity

def _storm_payload(seed=0):
    rng = np.random.default_rng(seed)
    return [rng.standard_normal((32, 8)).astype(np.float32),
            rng.integers(-128, 127, (64,), dtype=np.int8)]


def test_swap_engine_checksum_roundtrip_both_tiers(tmp_path):
    """Checksums are computed at swap-out and verified on fetch across
    BOTH tiers; clean payloads round-trip bit-exact with zero
    integrity noise."""
    from deepspeed_tpu.offload import SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path))
    arrs = _storm_payload(1)
    eng.put("h", arrs, tier="host")
    eng.put("n", arrs, tier="nvme")
    assert eng._entries["h"].crc is not None
    assert eng._entries["h"].crc == eng._entries["n"].crc
    for key in ("h", "n"):
        back = eng.fetch(key)
        for a, b in zip(arrs, back):
            np.testing.assert_array_equal(a, b)
    assert eng.integrity_failures == 0 and eng.quarantined() == {}
    eng.close()


def test_swap_engine_on_disk_flip_detected_and_quarantined(tmp_path):
    """THE gap this PR closes: a size-preserving bit-flip on the NVMe
    payload (flipped behind the engine's back — byte count unchanged,
    so the torn check at fetch cannot see it) raises the typed
    CorruptPayloadError, quarantines the key, and a fresh put of the
    key (the heal-back contract) clears the quarantine."""
    import os
    from deepspeed_tpu.offload import CorruptPayloadError, SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path))
    arrs = _storm_payload(2)
    nbytes = eng.put("k", arrs, tier="nvme")
    eng.drain()
    path = eng._path("k")
    assert os.path.getsize(path) == nbytes
    with open(path, "r+b") as f:                 # media damage, same size
        f.seek(7)
        orig = f.read(1)[0]
        f.seek(7)
        f.write(bytes([orig ^ 0xFF]))
    assert os.path.getsize(path) == nbytes       # size-preserving
    with pytest.raises(CorruptPayloadError) as ei:
        eng.fetch("k")
    assert ei.value.key == "k" and ei.value.tier == "nvme"
    assert eng.tier_of("k") is None              # never re-attached
    assert "k" in eng.quarantined()
    assert eng.integrity_failures == 1
    with pytest.raises(KeyError):
        eng.fetch("k")                           # gone, not resurrected
    eng.put("k", arrs, tier="nvme")              # heal-back re-put
    assert "k" not in eng.quarantined()          # quarantine cleared
    back = eng.fetch("k")
    np.testing.assert_array_equal(arrs[0], back[0])
    eng.close()


def test_swap_engine_verify_off_reproduces_pre_pr_silent_corruption(tmp_path):
    """The documented pre-PR repro (acceptance criterion): with fetch
    verification disabled — exactly the pre-ISSUE-18 engine behavior —
    the same on-disk bit-flip sails through fetch and the flipped
    float reaches the consumer (a matmul, in a real step) silently.
    The default config catches it (previous test)."""
    import os
    import types
    from deepspeed_tpu.offload import SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path),
                     integrity=types.SimpleNamespace(verify_fetch=False))
    arrs = [np.ones((16,), np.float32)]
    eng.put("k", arrs, tier="nvme")
    eng.drain()
    with open(eng._path("k"), "r+b") as f:
        f.seek(3)
        orig = f.read(1)[0]
        f.seek(3)
        f.write(bytes([orig ^ 0xFF]))            # flip inside float 0
    back = eng.fetch("k")                        # attaches silently
    assert not np.array_equal(back[0], arrs[0])  # wrong bytes, no error
    assert eng.integrity_failures == 0           # nothing noticed
    eng.close()


def test_swap_engine_swap_io_corrupt_storm_detected(tmp_path):
    """swap.io:corrupt flips payload bytes between checksum and disk
    inside the engine's own write path; every fetch detects it —
    corruption degrades, it is never absorbed."""
    from deepspeed_tpu.offload import CorruptPayloadError, SwapEngine
    from deepspeed_tpu.resilience.faults import FaultInjector
    eng = SwapEngine(nvme_dir=str(tmp_path),
                     injector=FaultInjector("swap.io:corrupt=4@*"))
    arrs = _storm_payload(3)
    eng.put("k", arrs, tier="nvme")
    with pytest.raises(CorruptPayloadError):
        eng.fetch("k")
    assert eng.integrity_failures == 1 and "k" in eng.quarantined()
    assert eng.injector.fired.get("swap.io", 0) > 0
    eng.close()


def test_swap_engine_host_tier_corrupt_detected(tmp_path):
    """The corrupt= injection hook on put() damages the HOST-tier copy
    post-checksum; the host-side fetch verify catches it — integrity
    is not an NVMe-only property."""
    from deepspeed_tpu.offload import CorruptPayloadError, SwapEngine
    eng = SwapEngine(nvme_dir=str(tmp_path))
    eng.put("k", _storm_payload(4), tier="host", corrupt=4)
    with pytest.raises(CorruptPayloadError) as ei:
        eng.fetch("k")
    assert ei.value.tier == "host"
    assert "k" in eng.quarantined()
    eng.close()


def test_swap_engine_transient_deny_retries_to_success(tmp_path):
    """A single transient backend failure at the write reap resubmits
    synchronously through retry_call and succeeds — no terminal
    failure, no breaker movement, bytes intact."""
    from deepspeed_tpu.offload import SwapEngine
    from deepspeed_tpu.resilience.faults import FaultInjector
    # swap.io invocation 0 is the write-path corrupt probe; invocation 1
    # is the write-reap deny — exactly one transient failure
    eng = SwapEngine(nvme_dir=str(tmp_path),
                     injector=FaultInjector("swap.io:deny@1"))
    arrs = _storm_payload(5)
    eng.put("k", arrs, tier="nvme")
    eng.drain()                                  # reap retries + succeeds
    assert eng.io_failures == 0 and eng.write_reverts == 0
    assert eng.breaker().state == "closed"
    back = eng.fetch("k")
    np.testing.assert_array_equal(arrs[0], back[0])
    eng.close()


def test_swap_engine_write_failure_reverts_to_host(tmp_path):
    """THE lost-only-copy regression (ISSUE 18 satellite): a
    fire-and-forget NVMe write that fails terminally must NOT have
    consumed the only copy — the retained pristine source rebuilds the
    entry on the host tier, bit-exact, and the failure feeds the
    breaker instead of raising into the caller's put()."""
    from deepspeed_tpu.offload import SwapEngine
    from deepspeed_tpu.resilience.faults import FaultInjector
    eng = SwapEngine(nvme_dir=str(tmp_path),
                     injector=FaultInjector("swap.io:deny@*"))
    arrs = _storm_payload(6)
    eng.put("k", arrs, tier="nvme")              # submit looks fine
    eng.drain()                                  # reap fails terminally
    assert eng.tier_of("k") == "host"            # survived, demotion undone
    assert eng.write_reverts == 1 and eng.io_failures == 1
    eng.injector = FaultInjector([])             # tier heals
    back = eng.fetch("k")                        # host fetch: no swap.io
    for a, b in zip(arrs, back):
        np.testing.assert_array_equal(a, b)      # pristine, not the torn
    eng.close()


def test_swap_engine_breaker_lifecycle(tmp_path):
    """CLOSED -> OPEN (sustained terminal read failures) -> refused
    fast-fail with the entry RETAINED -> HALF_OPEN after cooldown ->
    CLOSED on a successful real-traffic probe; transitions are
    observable in the snapshot and the flight recorder."""
    from deepspeed_tpu.offload import SwapEngine
    from deepspeed_tpu.resilience.faults import FaultInjector
    from deepspeed_tpu.telemetry.flight_recorder import get_flight_recorder
    import types
    clock = [0.0]
    eng = SwapEngine(
        nvme_dir=str(tmp_path),
        integrity=types.SimpleNamespace(breaker_window=4,
                                        breaker_min_ops=2,
                                        breaker_cooldown_s=10.0))
    eng._breaker._now = lambda: clock[0]
    arrs = _storm_payload(7)
    for k in ("a", "b", "c"):
        eng.put(k, arrs, tier="nvme")
    eng.drain()
    eng.injector = FaultInjector("swap.io:deny@*")   # the drive goes bad
    for k in ("a", "b"):
        with pytest.raises(IOError):
            eng.fetch(k)                         # terminal after retries
    assert eng.breaker().state == "open"
    with pytest.raises(IOError, match="circuit open"):
        eng.fetch("c")                           # fast-fail, no submit
    assert eng.tier_of("c") == "nvme"            # RETAINED: media may heal
    assert eng.breaker().snapshot()["refused"] >= 1
    eng.prefetch("c")                            # OPEN: peek, no submit
    assert eng.inflight_reads() == set()
    clock[0] += 11.0                             # cooldown elapses
    eng.injector = FaultInjector([])             # ...and the tier healed
    back = eng.fetch("c")                        # the HALF_OPEN probe
    np.testing.assert_array_equal(arrs[0], back[0])
    snap = eng.breaker().snapshot()
    assert snap["state"] == "closed"
    assert snap["opens"] == 1 and snap["closes"] == 1
    kinds = [e["kind"] for e in get_flight_recorder().events(
        kind_prefix="offload/breaker")]
    assert len(kinds) >= 3                       # open, half_open, closed
    eng.close()


def test_swap_engine_snapshot_and_debug_payload(tmp_path):
    """/debug/offload: the weakref live-engine registry serves each
    engine's integrity + occupancy snapshot, filterable by owner."""
    from deepspeed_tpu.offload import SwapEngine, live_engines
    from deepspeed_tpu.telemetry.debug import offload_payload
    eng = SwapEngine(nvme_dir=str(tmp_path), owner="snap_test")
    eng.put("k", _storm_payload(8), tier="nvme")
    assert eng in live_engines()
    payload = offload_payload({"owner": "snap_test"})
    assert payload["count"] >= 1
    snap = [s for s in payload["engines"] if s["owner"] == "snap_test"][0]
    assert snap["tiers"]["nvme"]["entries"] == 1
    assert snap["breaker"]["state"] == "closed"
    assert snap["checksums"] and snap["verify_fetch"]
    assert snap["retained_write_sources"] == 1   # write not yet reaped
    eng.drain()
    assert eng.snapshot()["retained_write_sources"] == 0
    eng.close()
    assert eng not in live_engines()
