"""LoRA adapters over any engine Model (reference capability:
deepspeed/runtime/hybrid_engine.py:138-158 — the LoRA fuse/unfuse the RLHF
hybrid engine performs around generate; adapter maths per Hu et al. 2021).

TPU-native design: instead of the reference's in-place module surgery, the
wrapped Model's params tree is ``{"base": <frozen base>, "lora": {path:
{"a": A, "b": B}}}`` and every forward runs against ``merge(params)`` —
``W' = W + (alpha/r)·A@B`` computed inside jit, where XLA fuses the
rank-r outer product into the surrounding layout (no materialised weight
copy survives the fusion for the scanned stacked blocks).  The base
subtree is ``stop_gradient``-ed, so the backward pass never computes base
weight gradients, and ``trainable_mask`` excludes base from the optimizer
(zero update, zero moment memory).  A/B inherit the base leaf's logical
PartitionSpec on their preserved dimension, so TP/ZeRO shard adapters
exactly like the weights they decorate.

``fuse_fn`` materialises the merged base-shaped tree once — the hybrid
engine calls it at generate-rebind time so the KV-cache decode path runs
fused weights at full speed (one merge per policy update, not per token).
"""
from dataclasses import replace
from typing import Any, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

DEFAULT_TARGETS: Tuple[str, ...] = ("qkv_w", "proj_w")


def _path_str(kp) -> str:
    return "/".join(str(getattr(k, "key", getattr(k, "idx", k)))
                    for k in kp)


def _target_leaves(base_tree, targets):
    """[(path_str, leaf)] for every >=2-D leaf whose last path key is in
    ``targets``."""
    out = []
    for kp, leaf in jax.tree_util.tree_flatten_with_path(base_tree)[0]:
        name = str(getattr(kp[-1], "key", kp[-1]))
        if name in targets and getattr(leaf, "ndim", 0) >= 2:
            out.append((_path_str(kp), leaf))
    return out


def init_lora_params(base_params, rank: int, targets=DEFAULT_TARGETS,
                     rng=None, dtype=None):
    """Fresh adapters for ``base_params``: A ~ N(0, 1/in_dim) (so the
    rank-r product starts variance-bounded), B = 0 — merged == base at
    step 0, the LoRA paper's init."""
    rng = jax.random.PRNGKey(0) if rng is None else rng
    lora = {}
    for path, leaf in _target_leaves(base_params, targets):
        *lead, d_in, d_out = leaf.shape
        dt = dtype or leaf.dtype
        rng, k = jax.random.split(rng)
        lora[path] = {
            "a": (jax.random.normal(k, (*lead, d_in, rank), dt)
                  * (d_in ** -0.5)),
            "b": jnp.zeros((*lead, rank, d_out), dt),
        }
    if not lora:
        raise ValueError(
            f"wrap_lora: no >=2-D param leaf named in {targets!r}")
    return lora


def merge_lora(base_params, lora_params, scale: float,
               freeze_base: bool = True):
    """Base-shaped tree with ``W + scale·A@B`` at adapter sites.  With
    ``freeze_base`` the base leaves are stop_gradient-ed (training);
    fuse_fn passes False so the merge is a pure function of the params."""
    def visit(kp, leaf):
        w = jax.lax.stop_gradient(leaf) if freeze_base else leaf
        ab = lora_params.get(_path_str(kp))
        if ab is None:
            return w
        prod = jnp.einsum("...ir,...ro->...io", ab["a"], ab["b"])
        return w + scale * prod.astype(w.dtype)

    return jax.tree_util.tree_map_with_path(visit, base_params)


def _map_paths(tree):
    return [(_path_str(kp), leaf)
            for kp, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]]


def _ab_spec(spec, ndim) -> Tuple[P, P]:
    """Adapter specs from the decorated leaf's spec: A keeps the input
    dim's sharding, B the output dim's — rank stays replicated.  P() (the
    engine's replicated convention — None is an empty pytree to the spec
    machinery) when the leaf carries no spec."""
    if spec is None:
        return P(), P()
    t = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    lead, s_in, s_out = t[:-2], t[-2], t[-1]
    return P(*lead, s_in, None), P(*lead, None, s_out)


def wrap_lora(model, rank: int, alpha: Optional[float] = None,
              targets: Sequence[str] = DEFAULT_TARGETS):
    """Model -> Model whose params are ``{"base", "lora"}`` and whose
    forward/loss run merged weights with a frozen base.

    The wrapped model keeps the engine contract: ``init`` builds base +
    adapters, ``logical_specs``/``trainable_mask`` mirror the new tree,
    ``fuse_fn`` materialises merged weights for the inference view.  The
    pipeline decomposition (embed/block/head) is dropped — PP slices raw
    block params, which would bypass the merge; LoRA+PP is rejected
    loudly rather than silently unfused.
    """
    targets = tuple(targets)
    scale = (alpha if alpha is not None else float(rank)) / float(rank)

    def init_fn(rng):
        r1, r2 = jax.random.split(rng)
        base = model.init(r1)
        return {"base": base,
                "lora": init_lora_params(base, rank, targets, r2)}

    def merged(params):
        return merge_lora(params["base"], params["lora"], scale)

    def apply_fn(params, batch, rng=None):
        return model.apply_fn(merged(params), batch, rng)

    def loss_fn(params, batch, rng=None):
        return model.loss_fn(merged(params), batch, rng)

    def fuse(params):
        """Merged base-shaped tree (reference _fuse_lora) — feed to the
        inference engine together with the UNWRAPPED model."""
        return merge_lora(params["base"], params["lora"], scale,
                          freeze_base=False)

    def specs_and_mask():
        base_specs = model.logical_specs
        shapes = jax.eval_shape(model.init, jax.random.PRNGKey(0))
        spec_of = dict(_map_paths(base_specs)) if base_specs else {}
        lora_specs, lora_mask = {}, {}
        for path, leaf in _target_leaves(shapes, targets):
            a_spec, b_spec = _ab_spec(spec_of.get(path), leaf.ndim)
            lora_specs[path] = {"a": a_spec, "b": b_spec}
            lora_mask[path] = {"a": True, "b": True}
        base_mask = jax.tree.map(lambda _: False, shapes)
        if base_specs is None:
            # spec-less (pure-DP) base: replicate it explicitly — a None
            # subtree is an EMPTY pytree to the spec machinery
            base_specs = jax.tree.map(lambda _: P(), shapes)
        specs = {"base": base_specs, "lora": lora_specs}
        mask = {"base": base_mask, "lora": lora_mask}
        return specs, mask

    specs, mask = specs_and_mask()
    wrapped = replace(
        model,
        init_fn=init_fn,
        numpy_init_fn=None, layer_init_fn=None, nonblock_init_fn=None,
        apply_fn=apply_fn, loss_fn=loss_fn,
        logical_specs=specs,
        trainable_mask=mask,
        fuse_fn=fuse,
        embed_fn=None, block_fn=None, head_fn=None,
        init_cache_fn=None, prefill_fn=None, decode_fn=None,
        meta={**model.meta, "lora": {"rank": rank, "alpha": alpha,
                                     "scale": scale, "targets": targets},
              "base_model": model},
    )
    return wrapped


def attach_lora_params(wrapped_model, base_params, rng=None):
    """Full params tree for a *pretrained* base: fresh adapters around the
    given base weights (the RLHF flow — policy starts from the SFT model)."""
    cfg = wrapped_model.meta["lora"]
    return {"base": base_params,
            "lora": init_lora_params(base_params, cfg["rank"],
                                     cfg["targets"], rng)}
