"""DSL002 — lock discipline.

Two contracts, both paid for in incidents:

1. **No blocking operations inside a lock body.**  ``with self._lock:``
   in the serving scheduler/server guards the step loop; a file write,
   socket call, or ``time.sleep`` inside it stalls every submitter and
   the /metrics scrape.  Flagged calls: ``open``, ``time.sleep``,
   ``os.fsync/replace/rename/remove/unlink/makedirs``, ``subprocess.*``,
   ``socket.*``, ``urllib``/``requests``, ``.block_until_ready()``,
   ``.wait_until_finished()``.  (Jit *dispatch* under the scheduler
   lock is by design — a fresh bucket legitimately compiles for
   minutes, which is exactly why the watchdog below must stay
   lock-free.)

2. **No lock acquisition in lock-free-by-contract read paths.**  The
   watchdog (`resilience/health.py SchedulerWatchdog`), the /debug
   views, and ``*_unlocked`` helpers exist to observe a scheduler whose
   wedged ``step()`` is *holding* the lock; if they acquire it (or call
   a locking scheduler method like ``has_work()``), they join the
   deadlock they were built to report.  Zones: functions named
   ``*_unlocked`` or ``debug_*``, everything in ``telemetry/debug.py``,
   methods of ``*Watchdog`` classes, and any function whose docstring
   contains ``lock-free``.
"""
import ast
import re
from typing import Iterable, List, Optional

from ..astutil import dotted as _dotted
from ..core import Checker, Finding, ModuleFile, register

_LOCK_NAME_RE = re.compile(r"(^|[._])(_?lock)$", re.IGNORECASE)

#: dotted-call blocklist inside lock bodies
_BLOCKING_DOTTED = {
    "time.sleep", "os.fsync", "os.replace", "os.rename", "os.remove",
    "os.unlink", "os.makedirs", "os.rmdir", "shutil.rmtree",
    "shutil.copy", "shutil.copytree", "shutil.move",
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output",
    "socket.socket", "socket.create_connection",
    "urllib.request.urlopen", "requests.get", "requests.post",
}
_BLOCKING_BARE = {"open", "input"}
_BLOCKING_METHODS = {"block_until_ready", "wait_until_finished"}

#: scheduler methods that take the scheduler lock — calling them from a
#: lock-free zone deadlocks against a wedged step()
_LOCKING_SCHED_METHODS = {"has_work", "queue_depth", "active_requests",
                          "metrics_snapshot", "render_metrics", "submit",
                          "step", "run_until_idle"}

_ZONE_FILE_RES = (re.compile(r"telemetry/debug\.py$"),)
_ZONE_FN_RE = re.compile(r"(_unlocked$|^debug_)")
_WATCHDOG_CLASS_RE = re.compile(r"Watchdog$")
_DOCSTRING_MARK = "lock-free"


def _is_lock_expr(node) -> bool:
    name = _dotted(node)
    return bool(name and _LOCK_NAME_RE.search(name))


def _blocking_reason(call: ast.Call) -> Optional[str]:
    key = _dotted(call.func)
    if key in _BLOCKING_DOTTED:
        return key
    if isinstance(call.func, ast.Name) and call.func.id in _BLOCKING_BARE:
        return call.func.id
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in _BLOCKING_METHODS:
        return f".{call.func.attr}()"
    return None


@register
class LockDisciplineChecker(Checker):
    rule = "DSL002"
    name = "lock-discipline"
    doc = ("no blocking I/O inside lock bodies; no lock acquisition in "
           "watchdog//debug/lock-free-by-contract read paths")

    def check(self, mod: ModuleFile, inv) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._check_lock_bodies(mod, findings)
        self._check_lockfree_zones(mod, findings)
        return findings

    # ----------------------------------------------- blocking under lock
    def _check_lock_bodies(self, mod: ModuleFile,
                           findings: List[Finding]):
        for node in ast.walk(mod.tree):
            if not isinstance(node, (ast.With, ast.AsyncWith)):
                continue
            if not any(_is_lock_expr(item.context_expr)
                       for item in node.items):
                continue
            lock_name = next(
                (_dotted(i.context_expr) for i in node.items
                 if _is_lock_expr(i.context_expr)), "_lock")
            # scope-bounded walk: a deferred callback (nested def /
            # lambda) defined under the lock runs later, outside it;
            # a nested lock-with reports its own body once, not per
            # enclosing with
            stack = [s for s in node.body]
            while stack:
                inner = stack.pop()
                if isinstance(inner, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda,
                                      ast.ClassDef)):
                    continue
                if isinstance(inner, (ast.With, ast.AsyncWith)) and any(
                        _is_lock_expr(i.context_expr)
                        for i in inner.items):
                    continue
                if isinstance(inner, ast.Call):
                    reason = _blocking_reason(inner)
                    if reason is not None:
                        findings.append(self.finding(
                            mod, inner,
                            f"blocking call {reason} inside "
                            f"'with {lock_name}:' — I/O and sleeps "
                            "under the lock stall every submitter and "
                            "scrape; move it outside the critical "
                            "section"))
                stack.extend(ast.iter_child_nodes(inner))

    # -------------------------------------------------- lock-free zones
    def _check_lockfree_zones(self, mod: ModuleFile,
                              findings: List[Finding]):
        file_zone = any(r.search(mod.relpath) for r in _ZONE_FILE_RES)
        for cls, fn in self._functions_with_class(mod.tree):
            zone = (file_zone
                    or _ZONE_FN_RE.search(fn.name) is not None
                    or (cls is not None
                        and _WATCHDOG_CLASS_RE.search(cls.name))
                    or _DOCSTRING_MARK in (ast.get_docstring(fn) or ""))
            if not zone:
                continue
            for inner in ast.walk(fn):
                if isinstance(inner, (ast.With, ast.AsyncWith)):
                    for item in inner.items:
                        if _is_lock_expr(item.context_expr):
                            findings.append(self.finding(
                                mod, inner,
                                f"'{fn.name}' is lock-free by contract "
                                "(watchdog//debug/flight-recorder read "
                                "path) but acquires "
                                f"'{_dotted(item.context_expr)}' — a "
                                "wedged step() holding the lock makes "
                                "this join the deadlock"))
                elif isinstance(inner, ast.Call):
                    key = _dotted(inner.func)
                    if key and key.endswith("._lock.acquire"):
                        findings.append(self.finding(
                            mod, inner,
                            f"'{fn.name}' is lock-free by contract but "
                            f"calls {key}()"))
                    elif isinstance(inner.func, ast.Attribute) and \
                            inner.func.attr in _LOCKING_SCHED_METHODS:
                        recv = _dotted(inner.func.value) or ""
                        if re.search(r"sched", recv, re.IGNORECASE):
                            findings.append(self.finding(
                                mod, inner,
                                f"'{fn.name}' is lock-free by contract "
                                f"but calls {recv}.{inner.func.attr}(), "
                                "which acquires the scheduler lock — "
                                "use the *_unlocked variant or a "
                                "GIL-atomic attribute read"))

    @staticmethod
    def _functions_with_class(tree: ast.AST):
        owner = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for child in node.body:
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        owner[id(child)] = node
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield owner.get(id(node)), node
