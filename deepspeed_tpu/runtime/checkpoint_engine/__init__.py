from deepspeed_tpu.runtime.checkpoint_engine.engine import save_state, load_state
