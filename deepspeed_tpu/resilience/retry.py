"""Shared retry policy for checkpoint / storage I/O (ISSUE 3 tentpole).

One helper, one policy shape: exponential backoff with full jitter and a
wall-clock deadline.  Checkpoint writes on preemptible pods see 429s and
transient NFS/GCS hiccups routinely; unbounded retries wedge the drain
path, zero retries tear checkpoints — this is the middle ground every
checkpoint I/O call goes through.
"""
import random
import time
from typing import Callable, Optional, Tuple, Type

from deepspeed_tpu.utils.logging import logger


class RetryDeadlineExceeded(RuntimeError):
    """Deadline elapsed before an attempt succeeded; chains the last
    underlying error via ``__cause__``."""


def retry_call(fn: Callable, *args,
               attempts: int = 4,
               base_delay_s: float = 0.05,
               max_delay_s: float = 2.0,
               deadline_s: Optional[float] = None,
               retry_on: Tuple[Type[BaseException], ...] = (OSError,),
               rng: Optional[random.Random] = None,
               describe: str = "",
               _sleep: Callable[[float], None] = time.sleep,
               **kwargs):
    """Call ``fn(*args, **kwargs)``; on ``retry_on`` errors back off
    exponentially (full jitter: U(0, min(max_delay, base*2^k))) and retry
    up to ``attempts`` total tries or until ``deadline_s`` of wall clock
    has elapsed, whichever is sooner.  Non-matching exceptions propagate
    immediately."""
    if attempts < 1:
        raise ValueError(f"attempts={attempts}: must be >= 1")
    rng = rng if rng is not None else random.Random()
    t0 = time.monotonic()
    what = describe or getattr(fn, "__name__", repr(fn))
    last = None
    for k in range(attempts):
        try:
            return fn(*args, **kwargs)
        except retry_on as e:
            last = e
            elapsed = time.monotonic() - t0
            if k + 1 >= attempts:
                raise
            if deadline_s is not None and elapsed >= deadline_s:
                raise RetryDeadlineExceeded(
                    f"{what}: deadline {deadline_s}s exceeded after "
                    f"{k + 1} attempts") from e
            delay = rng.uniform(0.0, min(max_delay_s,
                                         base_delay_s * (2 ** k)))
            if deadline_s is not None:
                delay = min(delay, max(0.0, deadline_s - elapsed))
            logger.warning(f"retry_call: {what} failed "
                           f"(attempt {k + 1}/{attempts}: {e}); "
                           f"retrying in {delay:.3f}s")
            # observability (ISSUE 4): every retry counts in the
            # process-wide registry and marks the trace timeline
            from deepspeed_tpu.telemetry import get_registry, get_tracer
            get_registry().inc("retry/retries", op=what)
            get_tracer().instant("retry", cat="resilience",
                                 args={"op": what, "attempt": k + 1,
                                       "error": str(e)})
            _sleep(delay)
    raise last  # unreachable; satisfies type checkers
