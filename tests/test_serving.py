"""Continuous-batching serving subsystem (ISSUE 1 tentpole):
block-granular KV-cache pool, iteration-level scheduler, HTTP front-end.

The load-bearing contracts:
- greedy continuous-batching output == static ``InferenceEngine.generate``
  token-for-token (same prompts/seeds), INCLUDING the int8 KV cache and
  across preemption/resume;
- iteration-level behavior: a finished sequence's blocks recycle and a
  queued request is admitted while the rest of the batch still decodes;
- pool exhaustion preempts the lowest-priority request, which later
  resumes (recompute) and completes correctly;
- admission control rejects 429-style (queue full / too long / timeout)
  instead of crashing.
"""
import json
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.config import ServingConfig
from deepspeed_tpu.serving import (BlockManager, ContinuousBatchingScheduler,
                                   QueueFullError, RequestState,
                                   RequestTooLongError, SamplingParams)
from tests.util import tiny_gpt2


@pytest.fixture(scope="module")
def served():
    """One tiny model + engine pair shared by the parity tests (module
    scope: params/jit cache reuse keeps the file fast)."""
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    return m, eng


def _mixed_prompts(n=3, seed=0, lo=3, hi=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 128, (int(L),)).astype(np.int32)
            for L in rng.integers(lo, hi, n)]


def _static_reference(eng, prompt, max_new):
    return np.asarray(eng.generate(prompt[None], max_new_tokens=max_new,
                                   do_sample=False))[0, prompt.size:]


# --------------------------------------------------------------- block mgr
def test_block_manager_allocate_free_exhaust():
    bm = BlockManager(num_blocks=5, block_size=4)
    assert bm.num_usable_blocks == 4          # block 0 reserved (trash)
    got = bm.allocate(1, 3)
    assert got is not None and len(got) == 3
    assert BlockManager.TRASH_BLOCK not in got
    assert bm.num_free_blocks == 1
    assert bm.allocate(2, 2) is None          # no partial allocation
    assert bm.num_free_blocks == 1
    bm.free(1)
    assert bm.num_free_blocks == 4
    assert bm.block_table(1) == []
    # position addressing walks the table
    bm.allocate(3, 2)
    t = bm.block_table(3)
    assert bm.position_index(3, 0) == t[0] * 4
    assert bm.position_index(3, 5) == t[1] * 4 + 1


def test_block_manager_validation():
    with pytest.raises(ValueError, match="num_blocks"):
        BlockManager(num_blocks=1, block_size=4)
    with pytest.raises(ValueError, match="block_size"):
        BlockManager(num_blocks=4, block_size=0)


def test_serving_config_validation():
    cfg = ServingConfig(block_size=8, num_blocks=64)
    assert cfg.max_num_seqs == 8
    with pytest.raises(ValueError, match="block_size"):
        ServingConfig(block_size=0)
    with pytest.raises(ValueError, match="num_blocks"):
        ServingConfig(num_blocks=1)
    with pytest.raises(ValueError, match="max_num_seqs"):
        ServingConfig(max_num_seqs=0)


# ----------------------------------------------------------------- parity
def test_continuous_batching_matches_static_generate(served):
    """Acceptance: greedy continuous-batching == static generate
    token-for-token for mixed-length prompts."""
    m, eng = served
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=4,
                        max_num_batched_tokens=256)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    prompts = _mixed_prompts(5, seed=1)
    max_new = [6, 3, 8, 5, 4]
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=mn))
            for p, mn in zip(prompts, max_new)]
    sched.run_until_idle()
    for p, mn, r in zip(prompts, max_new, reqs):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, mn))


def test_continuous_batching_matches_static_int8_kv(served):
    """Same parity with the quantized KV-cache pool (int8 payload +
    per-vector scales ride the same block tables)."""
    m, _ = served
    eng8 = deepspeed_tpu.init_inference(
        model=m, config={"dtype": "float32", "kv_cache_dtype": "int8"})
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=3,
                        max_num_batched_tokens=256)
    sched = ContinuousBatchingScheduler(m, eng8.params, cfg,
                                        kv_cache_dtype="int8")
    prompts = _mixed_prompts(3, seed=2)
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=5))
            for p in prompts]
    sched.run_until_idle()
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng8, p, 5))


def test_continuous_batching_matches_static_int8_weights(served):
    """ISSUE 2 satellite: int8 WEIGHTS × continuous batching — the cb
    scheduler over a quantized-weight engine (the SERVE_INT8_WEIGHTS
    serve_bench path, decoding through the fused-dequant qgemm route)
    matches static int8 generate token-for-token."""
    m, _ = served
    import jax
    engq = deepspeed_tpu.init_inference(
        model=m, config={"dtype": "float32", "quant": {"enabled": True}})
    from deepspeed_tpu.models.model import QuantizedTensor
    is_q = lambda x: isinstance(x, QuantizedTensor)
    assert any(map(is_q, jax.tree_util.tree_leaves(engq.params["blocks"],
                                                   is_leaf=is_q)))
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=3,
                        max_num_batched_tokens=256)
    prompts = _mixed_prompts(4, seed=11)
    max_new = [5, 7, 3, 6]
    # force the qgemm route (CPU default is the dequant fallback) so cb
    # and the static reference both trace the new path
    from deepspeed_tpu.models.serving import qgemm_scope
    with qgemm_scope(True):
        sched = ContinuousBatchingScheduler(m, engq.params, cfg)
        reqs = [sched.submit(p, SamplingParams(max_new_tokens=mn))
                for p, mn in zip(prompts, max_new)]
        sched.run_until_idle()
        refs = [_static_reference(engq, p, mn)
                for p, mn in zip(prompts, max_new)]
    for r, ref in zip(reqs, refs):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(np.asarray(r.output_ids), ref)


def test_eos_stops_early(served):
    """EOS retirement: pick the model's first greedy token as "EOS" so the
    request finishes after one token and its blocks free immediately."""
    m, eng = served
    prompt = _mixed_prompts(1, seed=3)[0]
    first = int(_static_reference(eng, prompt, 1)[0])
    cfg = ServingConfig(block_size=8, num_blocks=16, max_num_seqs=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    r = sched.submit(prompt, SamplingParams(max_new_tokens=8,
                                            eos_token_id=first))
    sched.run_until_idle()
    assert r.output_ids == [first]
    assert sched.block_mgr.num_allocated_blocks == 0


def test_sampling_per_request_params(served):
    """Per-request sampling: a sampled request is deterministic in its
    seed, differs across seeds, and respects top_k=1 (== greedy)."""
    m, eng = served
    cfg = ServingConfig(block_size=8, num_blocks=64, max_num_seqs=4)
    prompt = _mixed_prompts(1, seed=4)[0]

    def run(seed, **kw):
        sched = ContinuousBatchingScheduler(m, eng.params, cfg)
        r = sched.submit(prompt, SamplingParams(
            max_new_tokens=8, do_sample=True, seed=seed, **kw))
        sched.run_until_idle()
        return list(r.output_ids)

    a = run(seed=7, temperature=1.5)
    assert a == run(seed=7, temperature=1.5)          # seed-deterministic
    outs = {tuple(run(seed=s, temperature=1.5)) for s in (7, 8, 9, 10)}
    assert len(outs) > 1                              # seeds differ
    np.testing.assert_array_equal(
        run(seed=3, top_k=1), _static_reference(eng, prompt, 8))


# ------------------------------------------------------- iteration-level
def test_finished_blocks_recycle_midbatch(served):
    """Acceptance: with a full decode batch, a newly finished sequence's
    blocks recycle and a queued request is admitted BEFORE the other
    sequence finishes."""
    m, eng = served
    cfg = ServingConfig(block_size=4, num_blocks=16, max_num_seqs=2,
                        max_num_batched_tokens=64)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    prompts = _mixed_prompts(3, seed=5, lo=4, hi=8)
    r_short = sched.submit(prompts[0], SamplingParams(max_new_tokens=4))
    r_long = sched.submit(prompts[1], SamplingParams(max_new_tokens=12))
    r_queued = sched.submit(prompts[2], SamplingParams(max_new_tokens=3))
    # both slots fill; r_queued must wait
    sched.step()
    assert r_short.state == RequestState.DECODE
    assert r_long.state == RequestState.DECODE
    assert r_queued.state == RequestState.QUEUED
    admitted_at = None
    for i in range(30):
        sched.step()
        if admitted_at is None and r_queued.state != RequestState.QUEUED:
            admitted_at = i
            assert r_short.state == RequestState.FINISHED
            assert r_long.state == RequestState.DECODE   # mid-batch admit
        if not sched.has_work():
            break
    assert admitted_at is not None
    for p, mn, r in ((prompts[0], 4, r_short), (prompts[1], 12, r_long),
                     (prompts[2], 3, r_queued)):
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, mn))


def test_preemption_evicts_and_resumes(served):
    """Acceptance: pool exhaustion evicts the lowest-priority request
    (recompute-on-resume) and it still completes with exact greedy
    parity."""
    m, eng = served
    # 7 usable blocks x 4 = 28 positions; two requests need 2x(6+10)=32
    cfg = ServingConfig(block_size=4, num_blocks=8, max_num_seqs=2,
                        max_num_batched_tokens=64)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    pa, pb = _mixed_prompts(2, seed=6, lo=6, hi=7)
    ra = sched.submit(pa, SamplingParams(max_new_tokens=10), priority=1)
    rb = sched.submit(pb, SamplingParams(max_new_tokens=10), priority=0)
    sched.run_until_idle()
    assert sched.metrics.counters["preemptions"] >= 1
    assert sched.metrics.counters["resumed"] >= 1
    assert rb.num_preemptions >= 1            # lower priority = the victim
    assert ra.num_preemptions == 0
    for p, r in ((pa, ra), (pb, rb)):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, 10))
    assert sched.block_mgr.num_allocated_blocks == 0


# ------------------------------------------------------ admission control
def test_admission_rejections(served):
    m, eng = served
    cfg = ServingConfig(block_size=4, num_blocks=8, max_num_seqs=1,
                        max_queued=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    prompt = _mixed_prompts(1, seed=7)[0]
    with pytest.raises(RequestTooLongError):
        sched.submit(np.arange(1, 20, dtype=np.int32),
                     SamplingParams(max_new_tokens=30))
    sched.submit(prompt, SamplingParams(max_new_tokens=2))
    sched.submit(prompt, SamplingParams(max_new_tokens=2))
    with pytest.raises(QueueFullError):       # 429, not a crash
        sched.submit(prompt, SamplingParams(max_new_tokens=2))
    assert sched.metrics.counters["rejected_queue_full"] == 1
    assert sched.metrics.counters["rejected_too_long"] == 1


def test_queued_timeout_rejects(served):
    m, eng = served
    cfg = ServingConfig(block_size=4, num_blocks=16, max_num_seqs=1)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    prompt = _mixed_prompts(1, seed=8)[0]
    blocker = sched.submit(prompt, SamplingParams(max_new_tokens=6))
    doomed = sched.submit(prompt, SamplingParams(max_new_tokens=2),
                          timeout_s=0.01)
    sched.step()                               # blocker takes the only slot
    time.sleep(0.05)
    sched.run_until_idle()
    assert blocker.state == RequestState.FINISHED
    assert doomed.state == RequestState.REJECTED
    assert "timed out" in doomed.reject_reason
    assert sched.metrics.counters["rejected_timeout"] == 1


# ---------------------------------------------------------- observability
def test_metrics_flow_through_monitor(served):
    from deepspeed_tpu.monitor.monitor import InMemoryMonitor
    m, eng = served
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=2,
                        monitor_interval=1)
    sink = InMemoryMonitor()
    sched = ContinuousBatchingScheduler(m, eng.params, cfg, monitor=sink)
    r = sched.submit(_mixed_prompts(1, seed=9)[0],
                     SamplingParams(max_new_tokens=4))
    sched.run_until_idle()
    assert r.ttft_s is not None and r.latency_s is not None
    assert sink.latest["serving/completed"][0] == 1.0
    assert "serving/ttft_p50_ms" in sink.latest
    assert "serving/block_pool_utilization" in sink.latest
    snap = sched.metrics.snapshot()
    assert snap["serving/generated_tokens"] == 4.0


# ------------------------------------------------------------ HTTP layer
def test_ds_serve_help_smoke():
    """tier-1 CLI smoke: bin/ds_serve --help exits 0."""
    out = subprocess.run([sys.executable, "bin/ds_serve", "--help"],
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0
    assert "continuous-batching" in out.stdout


@pytest.mark.slow
def test_http_server_end_to_end(served):
    """Full front-end: /generate, /healthz, /metrics over real HTTP."""
    from deepspeed_tpu.serving.server import make_server
    m, eng = served
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    httpd, loop = make_server(sched, port=0)
    loop.start()
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        prompt = _mixed_prompts(1, seed=10)[0]
        body = json.dumps({"input_ids": prompt.tolist(),
                           "max_new_tokens": 4}).encode()
        req = urllib.request.Request(base + "/generate", data=body,
                                     headers={"Content-Type":
                                              "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200
            out = json.loads(resp.read())
        np.testing.assert_array_equal(
            np.asarray(out["output_ids"]),
            _static_reference(eng, prompt, 4))
        assert out["ttft_ms"] > 0
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            health = json.loads(resp.read())
            assert health["status"] == "ready"   # ISSUE 3 health machine
        with urllib.request.urlopen(base + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
            assert "serving_completed 1" in text
            # ISSUE 4: /metrics is Prometheus text with latency
            # histogram buckets + quantile gauges
            assert "# TYPE serving_ttft_s histogram" in text
            assert 'serving_ttft_s_bucket{le="+Inf"} 1' in text
            assert "serving_ttft_p50_ms" in text
    finally:
        httpd.shutdown()
        loop.shutdown()
        httpd.server_close()
