"""Model tests (reference pattern: tests/unit/ops numeric checks vs reference
implementations)."""
import jax
import jax.numpy as jnp
import numpy as np

from tests.util import tiny_gpt2, random_batch
from deepspeed_tpu.ops.attention import xla_causal_attention


def test_gpt2_forward_shape():
    m = tiny_gpt2()
    params = m.init(jax.random.PRNGKey(0))
    batch = random_batch(batch_size=2, seq_len=16)
    logits = m.apply(params, batch)
    assert logits.shape == (2, 16, 128)
    assert np.isfinite(np.asarray(logits)).all()


def test_gpt2_loss_near_uniform_at_init():
    m = tiny_gpt2()
    params = m.init(jax.random.PRNGKey(0))
    loss = float(m.loss(params, random_batch(batch_size=4, seq_len=32)))
    assert abs(loss - np.log(128)) < 0.5


def test_causality():
    """Changing a future token must not affect earlier logits."""
    m = tiny_gpt2()
    params = m.init(jax.random.PRNGKey(0))
    b1 = random_batch(batch_size=1, seq_len=16, seed=0)
    b2 = {"input_ids": b1["input_ids"].copy()}
    b2["input_ids"][0, -1] = (b2["input_ids"][0, -1] + 1) % 128
    l1 = np.asarray(m.apply(params, b1))
    l2 = np.asarray(m.apply(params, b2))
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)
    assert not np.allclose(l1[0, -1], l2[0, -1])


def test_attention_causal_mask():
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 8, 2, 4))
    out = xla_causal_attention(q, q, q)
    assert out.shape == (1, 8, 2, 4)
    # first position can only attend to itself -> output == v[0]
    np.testing.assert_allclose(np.asarray(out[0, 0]), np.asarray(q[0, 0]),
                               rtol=1e-5, atol=1e-6)


def test_param_count():
    from deepspeed_tpu.models.gpt2 import GPT2Config, count_params, init_params
    cfg = GPT2Config(vocab_size=128, max_seq_len=64, num_layers=2,
                     num_heads=4, d_model=32)
    params = init_params(cfg, jax.random.PRNGKey(0))
    actual = sum(p.size for p in jax.tree.leaves(params))
    assert actual == count_params(cfg)


def test_remat_matches():
    m1 = tiny_gpt2(remat=False)
    m2 = tiny_gpt2(remat=True)
    params = m1.init(jax.random.PRNGKey(0))
    b = random_batch(batch_size=2, seq_len=16)
    l1 = float(m1.loss(params, b))
    l2 = float(m2.loss(params, b))
    assert abs(l1 - l2) < 1e-6


def test_numpy_init_matches_jax_init_distributions():
    """The host-side numpy initializer mirrors init_params: same tree
    structure/shapes/dtypes and matching per-leaf std within sampling
    error (it is the offload tier's fast init for billion-param models)."""
    import jax
    from deepspeed_tpu.models.gpt2 import (gpt2_model, numpy_init_params)
    model = gpt2_model("custom", vocab_size=512, max_seq_len=64,
                       num_layers=3, num_heads=4, d_model=64,
                       dtype="float32")
    jp = model.init(jax.random.PRNGKey(0))
    npp = numpy_init_params(model.config, seed=0)
    assert jax.tree.structure(jp) == jax.tree.structure(npp)
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_flatten_with_path(jp)[0],
            jax.tree_util.tree_flatten_with_path(npp)[0]):
        assert a.shape == b.shape, path
        sa, sb = float(np.std(np.asarray(a))), float(np.std(b))
        assert abs(sa - sb) <= 0.1 * max(sa, sb, 1e-3), (path, sa, sb)


def test_neox_and_bloom_native_models_train(devices8):
    """The new native architectures (neox partial-rotary parallel-residual,
    bloom ALiBi) train through the engine like every other model."""
    import deepspeed_tpu
    from deepspeed_tpu.models import neox_model, bloom_model
    from tests.util import base_config
    rng = np.random.default_rng(0)
    from deepspeed_tpu.models.gptneo import gptneo_model
    for factory in (lambda: neox_model("tiny", attention_impl="xla"),
                    lambda: bloom_model("tiny"),
                    lambda: gptneo_model("tiny"),
                    lambda: neox_model("tiny", attention_impl="xla",
                                       rotary_interleaved=True,
                                       head_bias=True)):   # gpt-j form
        from deepspeed_tpu.comm import reset_topology
        reset_topology()
        engine, *_ = deepspeed_tpu.initialize(
            model=factory(), config=base_config(
                zero_optimization={"stage": 2}))
        losses = []
        for i in range(3):
            batch = {"input_ids": rng.integers(
                0, 256, size=(1, 8, 16), dtype=np.int32)}
            losses.append(float(engine.train_batch(batch=batch)))
        assert all(np.isfinite(losses))
