from deepspeed_tpu.moe.layer import (MoE, MoEConfig, dispatch_scope,
                                     moe_layer, init_moe_params,
                                     moe_logical_specs,
                                     resolve_dispatch_mode,
                                     set_dispatch_override,
                                     set_moe_metrics_registry)
from deepspeed_tpu.moe.sharded_moe import (top1gating, top2gating, topkgating,
                                           topk_routing, GateOutput,
                                           TopKRouting)
