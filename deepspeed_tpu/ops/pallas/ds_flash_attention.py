"""From-scratch Pallas flash attention, forward AND backward, with
segment-id (sequence-packing) support.

Reference capability: the fused training transformer kernel
(csrc/transformer/softmax_kernels.cu + ds_transformer_cuda.cpp) — rebuilt
as a TPU kernel rather than translated.  Algorithm: FlashAttention-2
(online softmax forward saving per-row logsumexp; recompute-based
backward in two passes — dK/dV blocks looping over query tiles, dQ blocks
looping over key tiles).

Layouts: q [B, S, H, hd], k/v [B, S, KV, hd] (grouped-query attention:
KV may divide H — each group of H/KV query heads reads one KV head, so
GQA models stream KV at 1/group the HBM traffic instead of repeating
heads).  ``segment_ids`` [B, S] int32 restricts attention to same-segment
pairs — packed-sequence training the stock wrapper lacked (pass None for
a single segment).  The [S, S] score matrix never materialises in HBM;
VMEM holds one [block_q, block_k] tile.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _causal_kblocks(iq, block_q, block_k, seq_len):
    """#key-blocks a causal q-block row needs (whole blocks; block_q is a
    multiple of block_k by construction)."""
    return jnp.minimum((iq + 1) * block_q // block_k, seq_len // block_k)


def _fwd_kernel(q_ref, k_ref, v_ref, segq_ref, segk_ref, o_ref, lse_ref, *,
                sm_scale, causal, block_q, block_k, seq_len):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32) * sm_scale       # [Bq, hd]
    q_pos = iq * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_base = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    segq = segq_ref[0]                                   # [Bq, 1]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    n_kblocks = (_causal_kblocks(iq, block_q, block_k, seq_len)
                 if causal else seq_len // block_k)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(j * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(j * block_k, block_k)].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        segk = segk_ref[0, :, pl.dslice(j * block_k, block_k)]   # [1, Bk]
        mask = segq == segk
        if causal:
            mask &= q_pos >= (j * block_k + k_base)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        p = jnp.where(mask, p, 0.0)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, n_kblocks, body, (m0, l0, acc0))
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0, 0] = (acc / l_safe).astype(o_ref.dtype)
    lse_ref[0, 0] = jnp.where(l > 0, m + jnp.log(l_safe), NEG_INF)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                segq_ref, segk_ref, dk_ref, dv_ref, *,
                sm_scale, causal, block_q, block_k, seq_len, rep):
    """Grid (B, S//block_k, H) with the Q-head dim INNERMOST: consecutive
    grid steps within one rep-group revisit the same dk/dv output block
    (index h//rep), which persists in VMEM — the kernel accumulates into
    it, so VMEM holds one head's tiles regardless of the GQA group size.
    dk/dv outputs are fp32 (exact accumulation across the group)."""
    ik = pl.program_id(1)
    ih = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)                  # [Bk, hd]
    v = v_ref[0, 0].astype(jnp.float32)
    k_pos = ik * block_k + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    q_base = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    segk = segk_ref[0, :, pl.dslice(ik * block_k, block_k)]  # [1, Bk]

    dk0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    dv0 = jnp.zeros((block_k, k.shape[-1]), jnp.float32)
    start = (ik * block_k) // block_q if causal else 0

    def body(j, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(j * block_q, block_q)].astype(jnp.float32)
        do = do_ref[0, 0, pl.dslice(j * block_q, block_q)].astype(
            jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(j * block_q, block_q)]     # [Bq, 1]
        delta = delta_ref[0, 0, pl.dslice(j * block_q, block_q)]
        s = lax.dot_general(q * sm_scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        segq = segq_ref[0, pl.dslice(j * block_q, block_q)]      # [Bq, 1]
        mask = segq == segk
        if causal:
            mask &= (j * block_q + q_base) >= k_pos
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dv_new = dv + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dk_new = dk + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_new, dv_new

    dk, dv = lax.fori_loop(start, seq_len // block_q, body, (dk0, dv0))

    @pl.when(ih % rep == 0)
    def _init():
        dk_ref[0, 0] = dk
        dv_ref[0, 0] = dv

    @pl.when(ih % rep != 0)
    def _accum():
        dk_ref[0, 0] = dk_ref[0, 0] + dk
        dv_ref[0, 0] = dv_ref[0, 0] + dv


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               segq_ref, segk_ref, dq_ref, *,
               sm_scale, causal, block_q, block_k, seq_len):
    iq = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0]                                  # [Bq, 1]
    delta = delta_ref[0, 0]
    q_pos = iq * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_base = lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    segq = segq_ref[0]                                   # [Bq, 1]

    dq0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)
    n_kblocks = (_causal_kblocks(iq, block_q, block_k, seq_len)
                 if causal else seq_len // block_k)

    def body(j, dq):
        k = k_ref[0, 0, pl.dslice(j * block_k, block_k)].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(j * block_k, block_k)].astype(jnp.float32)
        s = lax.dot_general(q * sm_scale, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        segk = segk_ref[0, :, pl.dslice(j * block_k, block_k)]   # [1, Bk]
        mask = segq == segk
        if causal:
            mask &= q_pos >= (j * block_k + k_base)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        return dq + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, n_kblocks, body, dq0)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _to_bhsd(x):
    return jnp.transpose(x, (0, 2, 1, 3))


def _choose_blocks(seq_len, block_q, block_k):
    bq = min(block_q, seq_len)
    bk = min(block_k, seq_len)
    while bq > 1 and seq_len % bq:
        bq //= 2
    while bk > 1 and seq_len % bk:
        bk //= 2
    # the causal loop bounds assume block_q is a multiple of block_k
    while bq % bk and bk > 1:
        bk //= 2
    if seq_len % bq or seq_len % bk or bq % bk or bq < 8 or bk < 8:
        raise ValueError(
            f"ds_flash_attention: seq_len {seq_len} does not decompose "
            f"into >=8-sized blocks (got block_q={bq}, block_k={bk}); pad "
            "the sequence to a multiple of 8")
    return bq, bk


def vmem_fits(seq_len, head_dim, itemsize, block_q=512, block_k=512,
              budget_bytes=None):
    """Whether one (batch, head) grid step's VMEM working set fits on-core.

    The kernels stage the full-sequence K/V (forward/dq) or Q/dO (dk/dv
    pass) per grid step via whole-S BlockSpecs, so the dominant term is
    2*S*hd*itemsize; Pallas double-buffers the pipelined blocks, hence the
    factor 2 on top, plus per-row fp32 lse/delta/segments and the
    [block_q, hd] tiles.  The dispatch layer calls this before selecting
    the kernel — ``jax.eval_shape`` probes only shapes and would pass a
    16k-fp32 sequence that Mosaic then rejects at compile time (advisor
    round 3).  Budget defaults to 12 MiB of the ~16 MiB/core VMEM;
    override with DS_FLASH_VMEM_MB."""
    import os
    if budget_bytes is None:
        budget_bytes = int(os.environ.get("DS_FLASH_VMEM_MB", "12")) << 20
    try:
        bq, bk = _choose_blocks(seq_len, block_q, block_k)
    except ValueError:
        return False
    full_kv = 2 * seq_len * head_dim * itemsize      # K+V (or Q+dO) whole-S
    rows = 16 * seq_len                              # lse/delta/2×segments
    tiles = (bq + bk) * head_dim * (itemsize + 2 * 4)  # in tiles + fp32 acc
    return 2 * (full_kv + rows) + tiles <= budget_bytes


def ds_flash_attention(q, k, v, segment_ids=None, causal=True,
                       sm_scale=None, block_q=512, block_k=512):
    """q [B, S, H, hd], k/v [B, S, KV, hd] -> [B, S, H, hd].  KV may
    divide H (grouped-query attention — KV streams once per group).
    ``segment_ids``: None or a [B, S] int array; packed sequences attend
    only within their own segment (non-differentiable — it rides the VJP
    closure)."""

    @jax.custom_vjp
    def f(q, k, v):
        o, _ = _fwd(q, k, v, segment_ids, causal, sm_scale, block_q,
                    block_k)
        return o

    def fwd(q, k, v):
        return _fwd(q, k, v, segment_ids, causal, sm_scale, block_q,
                    block_k)

    def bwd(res, do):
        return _bwd_rule(segment_ids, causal, sm_scale, block_q, block_k,
                         res, do)

    f.defvjp(fwd, bwd)
    return f(q, k, v)


def _fwd(q, k, v, segment_ids, causal, sm_scale, block_q, block_k,
         interpret=None):
    # interpret=None leaves the pallas default (and any test monkeypatch)
    # in force; True forces interpret mode (ring path off-TPU)
    _ikw = {} if interpret is None else {"interpret": interpret}
    B, S, H, hd = q.shape
    KV = k.shape[2]
    if H % KV:
        raise ValueError(f"ds_flash_attention: q heads {H} not a multiple "
                         f"of kv heads {KV}")
    rep = H // KV
    sm = sm_scale if sm_scale is not None else hd ** -0.5
    bq, bk = _choose_blocks(S, block_q, block_k)
    qT, kT, vT = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    seg = (segment_ids.astype(jnp.int32) if segment_ids is not None
           else jnp.zeros((B, S), jnp.int32))
    # TPU-legal layouts for per-row operands: segment ids travel twice —
    # as a [B, S, 1] column (q side) and a [B, 1, S] row (k side) — so the
    # in-kernel mask is a plain (Bq,1)==(1,Bk) broadcast; lse rides a
    # trailing singleton dim (Mosaic requires the last two block dims to
    # divide (8, 128) or equal the array dims — a bare [B, S] block fails)
    seg_col, seg_row = seg[:, :, None], seg[:, None, :]
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm, causal=causal, block_q=bq, block_k=bk,
        seq_len=S)
    oT, lse = pl.pallas_call(
        kernel, grid=(B, H, S // bq), **_ikw,
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, hd),
                         lambda b, h, i: (b, h // rep, 0, 0)),
            pl.BlockSpec((1, 1, S, hd),
                         lambda b, h, i: (b, h // rep, 0, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, h, i: (b, i, 0)),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ])(qT, kT, vT, seg_col, seg_row)
    o = jnp.transpose(oT, (0, 2, 1, 3))
    return o, (q, k, v, o, lse[..., 0])


def _bwd_rule(segment_ids, causal, sm_scale, block_q, block_k, res, do):
    q, k, v, o, lse = res
    doT, oT = _to_bhsd(do), _to_bhsd(o)
    delta = jnp.sum(doT.astype(jnp.float32) * oT.astype(jnp.float32),
                    axis=-1)                              # [B, H, S]
    return _bwd_calls(q, k, v, do, lse, delta, segment_ids, causal,
                      sm_scale, block_q, block_k)


def _bwd_calls(q, k, v, do, lse, delta, segment_ids, causal, sm_scale,
               block_q, block_k, interpret=None, keep_fp32=False):
    """The two backward pallas calls, driven by EXPLICIT lse/delta — the
    ring-attention composition feeds the GLOBAL logsumexp and delta here
    so each K/V chunk's contribution is the exact global-softmax term.
    ``keep_fp32`` returns dq/dk/dv unrounded (fp32) so a caller that sums
    chunk contributions (the ring) accumulates exactly and casts once."""
    _ikw = {} if interpret is None else {"interpret": interpret}
    B, S, H, hd = q.shape
    KV = k.shape[2]
    rep = H // KV
    sm = sm_scale if sm_scale is not None else hd ** -0.5
    bq, bk = _choose_blocks(S, block_q, block_k)
    qT, kT, vT = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    doT = _to_bhsd(do)
    seg = (segment_ids.astype(jnp.int32) if segment_ids is not None
           else jnp.zeros((B, S), jnp.int32))
    # same TPU-legal layout scheme as the forward (see _fwd)
    seg_col, seg_row = seg[:, :, None], seg[:, None, :]
    lse4, delta4 = lse[..., None], delta[..., None]      # [B, H, S, 1]

    # dK/dV: Q-head-innermost grid; rep-group steps accumulate into the
    # shared (b, h//rep, i) fp32 output block
    dkv_kernel = functools.partial(
        _dkv_kernel, sm_scale=sm, causal=causal, block_q=bq, block_k=bk,
        seq_len=S, rep=rep)
    dkT, dvT = pl.pallas_call(
        dkv_kernel, grid=(B, S // bk, H), **_ikw,
        in_specs=[
            pl.BlockSpec((1, 1, S, hd), lambda b, i, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, hd),
                         lambda b, i, h: (b, h // rep, i, 0)),
            pl.BlockSpec((1, 1, bk, hd),
                         lambda b, i, h: (b, h // rep, i, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, i, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, 1), lambda b, i, h: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, 1), lambda b, i, h: (b, h, 0, 0)),
            pl.BlockSpec((1, S, 1), lambda b, i, h: (b, 0, 0)),
            pl.BlockSpec((1, 1, S), lambda b, i, h: (b, 0, 0))],
        out_specs=[
            pl.BlockSpec((1, 1, bk, hd),
                         lambda b, i, h: (b, h // rep, i, 0)),
            pl.BlockSpec((1, 1, bk, hd),
                         lambda b, i, h: (b, h // rep, i, 0))],
        out_shape=[jax.ShapeDtypeStruct((B, KV, S, hd), jnp.float32),
                   jax.ShapeDtypeStruct((B, KV, S, hd), jnp.float32)],
    )(qT, kT, vT, doT, lse4, delta4, seg_col, seg_row)

    dq_kernel = functools.partial(
        _dq_kernel, sm_scale=sm, causal=causal, block_q=bq, block_k=bk,
        seq_len=S)
    dqT = pl.pallas_call(
        dq_kernel, grid=(B, H, S // bq), **_ikw,
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, hd),
                         lambda b, h, i: (b, h // rep, 0, 0)),
            pl.BlockSpec((1, 1, S, hd),
                         lambda b, h, i: (b, h // rep, 0, 0)),
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, bq, 1), lambda b, h, i: (b, i, 0)),
            pl.BlockSpec((1, 1, S), lambda b, h, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct(
            (B, H, S, hd), jnp.float32 if keep_fp32 else q.dtype),
    )(qT, kT, vT, doT, lse4, delta4, seg_col, seg_row)

    dq = jnp.transpose(dqT, (0, 2, 1, 3))
    dk = jnp.transpose(dkT, (0, 2, 1, 3))
    dv = jnp.transpose(dvT, (0, 2, 1, 3))
    if not keep_fp32:
        dk, dv = dk.astype(k.dtype), dv.astype(v.dtype)
    return dq, dk, dv


# -------------------------------------------------------- ring composition
# Chunk-level entry points for blockwise context parallelism
# (sequence/ring_attention.py): the ring merges per-chunk (o, lse) pairs
# online in the forward and replays each chunk's backward against the
# GLOBAL lse/delta — exactly the flash decomposition, spread over the
# seq-axis ring instead of the in-kernel key loop.

def chunk_fwd(q, k, v, causal, sm_scale=None, block_q=512, block_k=512,
              interpret=None):
    """One K/V chunk's attention: -> (o [B,S,H,hd], lse [B,H,S]).
    Not differentiable on its own — the ring owns the VJP."""
    o, (_, _, _, _, lse) = _fwd(q, k, v, None, causal, sm_scale, block_q,
                                block_k, interpret=interpret)
    return o, lse


def chunk_bwd(q, k, v, do, lse, delta, causal, sm_scale=None, block_q=512,
              block_k=512, interpret=None):
    """One K/V chunk's gradient contributions given the GLOBAL softmax
    stats: -> (dq, dk, dv), all fp32 — the ring sums sp of these, so
    per-chunk rounding would defeat its fp32 travel accumulators."""
    return _bwd_calls(q, k, v, do, lse, delta, None, causal, sm_scale,
                      block_q, block_k, interpret=interpret, keep_fp32=True)

