"""Inference engine tests (reference: tests/unit/inference coverage of
init_inference + generate)."""
import jax
import numpy as np
import pytest

import deepspeed_tpu
from tests.util import tiny_gpt2, random_batch


def test_init_inference_forward(devices8):
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    logits = eng(random_batch(batch_size=2, seq_len=16))
    assert logits.shape == (2, 16, 128)


def test_generate_greedy_deterministic(devices8):
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    prompt = np.arange(8, dtype=np.int32)[None] % 128
    out1 = eng.generate(prompt, max_new_tokens=8)
    out2 = eng.generate(prompt, max_new_tokens=8)
    assert out1.shape == (1, 16)
    np.testing.assert_array_equal(out1, out2)
    np.testing.assert_array_equal(out1[0, :8], prompt[0])


def test_generate_matches_stepwise_forward(devices8):
    """Greedy generate must equal repeated argmax over full forwards."""
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    prompt = (np.arange(6, dtype=np.int32)[None] * 7) % 128
    out = eng.generate(prompt, max_new_tokens=4)
    toks = prompt.copy()
    for _ in range(4):
        logits = np.asarray(eng({"input_ids": toks}))
        nxt = logits[0, -1].argmax().astype(np.int32)
        toks = np.concatenate([toks, [[nxt]]], axis=1)
    np.testing.assert_array_equal(out, toks)


def test_generate_tp(devices8):
    m = tiny_gpt2()
    ref = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    tp = deepspeed_tpu.init_inference(
        model=tiny_gpt2(), config={"dtype": "float32",
                                   "tensor_parallel": {"tp_size": 2}})
    # same init seed -> same params -> same greedy output
    prompt = np.arange(5, dtype=np.int32)[None]
    np.testing.assert_array_equal(ref.generate(prompt, max_new_tokens=5),
                                  tp.generate(prompt, max_new_tokens=5))


def test_generate_context_overflow_raises(devices8):
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    with pytest.raises(ValueError, match="context"):
        eng.generate(np.zeros((1, 60), dtype=np.int32), max_new_tokens=10)


def test_mp_size_deprecated_alias(devices8):
    cfg = deepspeed_tpu.inference.DeepSpeedInferenceConfig(mp_size=2)
    assert cfg.tensor_parallel.tp_size == 2
