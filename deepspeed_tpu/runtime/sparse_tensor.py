"""Sparse embedding gradients (reference: deepspeed/runtime/sparse_tensor.py
``SparseTensor`` + engine.py sparse allreduce path, config key
``sparse_gradients``).

The reference wraps torch's sparse COO embedding grads so DP all-reduce
moves (indices, values) instead of the dense [V, D] table.  TPU-native
formulation: under jit shapes are static, so the exchange keys off the
*batch token ids* (exactly the rows a lookup-only embedding grad can
touch).  Each device normalises its dense local grad rows by their local
occurrence count, all-gathers (ids, rows) — O(tokens·D) wire traffic — and
scatter-adds into the [V, D] table, reproducing the dense mean exactly.

Only correct for params whose gradient comes *solely* from gather-style
lookups of the ids.  Models declare them via
``meta["sparse_grad_params"]`` — a mapping ``{param_key: batch_ids_key}``
naming which batch field feeds the lookup (a list is accepted as shorthand
for ``input_ids``).  A tied embedding+head like GPT-2's wte gets dense head
contributions on every row and must NOT be declared.
"""
import jax.numpy as jnp
from jax import lax


def sparse_embedding_allreduce(g, ids, axis_name, n: int, mean: bool = True):
    """Reduce a lookup-embedding gradient over DP axes by exchanging only
    the touched rows.

    **Collective — call inside a shard_map body.**

    Args:
        g: [V, D] this device's local dense embedding gradient (rows
           non-zero only at ``ids``).
        ids: [T] int32 token ids of this device's batch window (with
           duplicates; every id whose row is non-zero must appear).
        axis_name: DP mesh axis name, or a tuple of names — a tuple runs
           the exchange hierarchically (axis by axis), the touched-id set
           widening per hop, matching the multi-axis manual meshes of the
           generalized qgZ tier.
        n: total size across the named axes.
        mean: divide the reduced rows by ``n`` (set False when the caller
           pre-scaled the loss by 1/n so the sum is already the mean).
    Returns:
        [V, D] the exact mean (or sum) gradient over the axes.
    """
    ids = ids.reshape(-1)
    # counts in f32 regardless of g.dtype: a bf16 accumulator saturates its
    # integer range at 256 and high-frequency tokens would mis-normalise
    counts = jnp.zeros((g.shape[0],), jnp.float32).at[ids].add(1.0)
    # each occurrence carries row/count so duplicates sum back to the row
    rows = (g[ids].astype(jnp.float32)
            / jnp.maximum(counts, 1.0)[ids][:, None])           # [T, D]
    axes = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
    for a in axes:
        # each hop widens the (ids, rows) set to the whole group's
        ids = lax.all_gather(ids, a, tiled=True)                # [na*T]
        rows = lax.all_gather(rows, a, tiled=True)              # [na*T, D]
    out = jnp.zeros(g.shape, jnp.float32).at[ids].add(rows)
    if mean:
        out = out / n
    return out.astype(g.dtype)
