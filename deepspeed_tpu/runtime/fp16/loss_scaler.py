"""Static/dynamic loss scaling (reference: deepspeed/runtime/fp16/loss_scaler.py).

Pure-functional: the mutable scaler state (:class:`LossScaleState`) is an
arrays-only pytree threaded through the jitted train step; the static knobs live
in :class:`LossScalerConfig` and are closed over at trace time.  Overflow skip is
a select on the update, matching the reference's skip-step-and-shrink-scale
semantics.
"""
from dataclasses import dataclass
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


class LossScaleState(NamedTuple):
    cur_scale: jnp.ndarray           # f32 scalar
    cur_iter: jnp.ndarray            # i32 scalar
    last_overflow_iter: jnp.ndarray  # i32 scalar
    cur_hysteresis: jnp.ndarray      # i32 scalar


@dataclass(frozen=True)
class LossScalerConfig:
    dynamic: bool = True
    scale_window: int = 1000
    scale_factor: float = 2.0
    min_scale: float = 1.0
    delayed_shift: int = 2           # hysteresis
    consecutive_hysteresis: bool = False  # refill on every good step


def create_loss_scaler(enabled: bool,
                       loss_scale: float = 0.0,
                       initial_scale_power: int = 16,
                       loss_scale_window: int = 1000,
                       hysteresis: int = 2,
                       min_loss_scale: float = 1.0,
                       consecutive_hysteresis: bool = False
                       ) -> Tuple[LossScaleState, LossScalerConfig]:
    if not enabled:
        state = LossScaleState(jnp.float32(1.0), jnp.int32(0), jnp.int32(-1),
                               jnp.int32(1))
        return state, LossScalerConfig(dynamic=False)
    dynamic = loss_scale == 0.0
    init = float(2.0 ** initial_scale_power) if dynamic else float(loss_scale)
    state = LossScaleState(jnp.float32(init), jnp.int32(0), jnp.int32(-1),
                           jnp.int32(hysteresis))
    cfg = LossScalerConfig(dynamic=dynamic, scale_window=int(loss_scale_window),
                           min_scale=float(min_loss_scale),
                           delayed_shift=int(hysteresis),
                           consecutive_hysteresis=bool(consecutive_hysteresis))
    return state, cfg


def has_overflow(grads) -> jnp.ndarray:
    """Global NaN/Inf scan over a gradient pytree (reference
    ``has_overflow_serial`` / ``_has_inf_or_nan``, stage3.py:2039)."""
    leaves = jax.tree.leaves(grads)
    flags = [jnp.logical_not(jnp.all(jnp.isfinite(l.astype(jnp.float32))))
             for l in leaves]
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_or(out, f)
    return out


def update_scale(state: LossScaleState, overflow: jnp.ndarray,
                 cfg: LossScalerConfig) -> LossScaleState:
    """Dynamic loss-scale update (reference LossScaler.update_scale)."""
    if not cfg.dynamic:
        return state._replace(cur_iter=state.cur_iter + 1)
    hysteresis_exhausted = state.cur_hysteresis <= 1
    shrink = jnp.logical_and(overflow, hysteresis_exhausted)
    new_hysteresis = jnp.where(
        overflow, jnp.maximum(state.cur_hysteresis - 1, 0), state.cur_hysteresis)
    shrunk = jnp.maximum(state.cur_scale / cfg.scale_factor, cfg.min_scale)
    # growth fires on the scale_window-th consecutive good step:
    # (cur_iter - last_overflow_iter) reaches a multiple of scale_window
    # (last_overflow_iter starts at -1, updates are evaluated pre-increment)
    stable = (state.cur_iter - state.last_overflow_iter) % cfg.scale_window == 0
    grow = jnp.logical_and(jnp.logical_not(overflow), stable)
    new_scale = jnp.where(shrink, shrunk,
                          jnp.where(grow, state.cur_scale * cfg.scale_factor,
                                    state.cur_scale))
    new_last = jnp.where(overflow, state.cur_iter, state.last_overflow_iter)
    if cfg.consecutive_hysteresis:
        # reference fused_optimizer.py: with consecutive_hysteresis the budget
        # refills on every non-overflow step, so only *consecutive* overflows
        # can exhaust it and shrink the scale
        new_hysteresis = jnp.where(jnp.logical_not(overflow),
                                   jnp.int32(cfg.delayed_shift),
                                   new_hysteresis)
    else:
        # hysteresis refills on growth, not on shrink (once exhausted, every
        # further overflow shrinks immediately until a stable window passes)
        new_hysteresis = jnp.where(grow, jnp.int32(cfg.delayed_shift),
                                   new_hysteresis)
    return LossScaleState(new_scale, state.cur_iter + 1, new_last,
                          new_hysteresis)
