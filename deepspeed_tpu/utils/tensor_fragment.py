"""Tensor-fragment API (reference: deepspeed/utils/tensor_fragment.py:92-125 —
``safe_get_full_fp32_param`` / ``safe_get_full_grad`` /
``safe_get_full_optimizer_state`` and the set_ variants).

The reference needs this machinery because ZeRO scatters flat fragments across
ranks; in JAX a sharded array already knows how to gather itself, so "safe get"
is a device_get through the addressable shards, and "safe set" is a device_put
with the original sharding.  Paths address the params pytree
("blocks/qkv_w"-style, matching HostOffloadOptimizer path naming).
"""
from typing import Optional

import numpy as np
import jax


def _resolve(tree, path: str):
    node = tree
    for part in path.split("/"):
        if isinstance(node, dict):
            node = node[part]
        else:
            node = getattr(node, part)
    return node


def _set(tree, path: str, value):
    parts = path.split("/")
    node = tree
    for part in parts[:-1]:
        node = node[part]
    node[parts[-1]] = value


def safe_get_full_fp32_param(engine, path: str) -> Optional[np.ndarray]:
    """Gather the full fp32 master value of a parameter."""
    if engine.host_optimizer is not None:
        m = engine.host_optimizer
        if path in m.master:
            return m.master[path].reshape(m.shapes[path]).copy()
        return None
    leaf = _resolve(engine.state["params"], path)
    return np.asarray(jax.device_get(leaf), dtype=np.float32)


def safe_set_full_fp32_param(engine, path: str, value) -> bool:
    value = np.asarray(value, dtype=np.float32)
    if engine.host_optimizer is not None:
        m = engine.host_optimizer
        if path not in m.master:
            return False
        m.master[path][:] = value.ravel()
        # refresh the device working copy
        engine.state["params"] = jax.device_put(
            m.params_in_compute_dtype(engine.compute_dtype),
            engine.param_shardings)
        return True
    leaf = _resolve(engine.state["params"], path)
    sharding = _resolve(engine.param_shardings, path)
    _set(engine.state["params"], path,
         jax.device_put(value.astype(np.asarray(leaf).dtype), sharding))
    return True


def safe_get_full_grad(engine, path: str) -> Optional[np.ndarray]:
    """Full gradient of the last backward (micro-step API accumulator)."""
    grads = engine._micro_grads if engine._micro_grads is not None \
        else engine._pending_grads
    if grads is None:
        return None
    return np.asarray(jax.device_get(_resolve(grads, path)))


def safe_get_full_optimizer_state(engine, path: str,
                                  optim_state_key: str) -> Optional[np.ndarray]:
    """optim_state_key: 'exp_avg' | 'exp_avg_sq' (reference key names)."""
    key_to_idx = {"exp_avg": 0, "exp_avg_sq": 1}
    if engine.host_optimizer is not None:
        m = engine.host_optimizer
        idx = key_to_idx.get(optim_state_key)
        if idx is None or path not in m.master or m.moments.get(path) is None:
            return None
        return m.moments[path][idx].reshape(m.shapes[path]).copy()
    # optax: find mu/nu subtrees inside the chained state
    import optax
    for s in jax.tree_util.tree_leaves(
            engine.state["opt_state"],
            is_leaf=lambda x: isinstance(x, optax.ScaleByAdamState)):
        if isinstance(s, optax.ScaleByAdamState):
            tree = s.mu if optim_state_key == "exp_avg" else s.nu
            return np.asarray(jax.device_get(_resolve(tree, path)))
    return None


def safe_set_full_optimizer_state(engine, path: str, value,
                                  optim_state_key: str) -> bool:
    key_to_idx = {"exp_avg": 0, "exp_avg_sq": 1}
    idx = key_to_idx.get(optim_state_key)
    if idx is None:
        return False
    if engine.host_optimizer is not None:
        m = engine.host_optimizer
        if path not in m.master or m.moments.get(path) is None:
            return False
        m.moments[path][idx][:] = np.asarray(value, np.float32).ravel()
        return True
    return False
