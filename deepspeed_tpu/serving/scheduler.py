"""Iteration-level continuous-batching scheduler (Orca OSDI '22 scheduling
over a vLLM-style paged KV pool).

Each ``step()`` is one engine iteration:

1. expire queued requests past their timeout (graceful 429, never a crash);
2. admit queued prefills — highest SLO class, then priority, first — up
   to the ``max_num_batched_tokens`` budget and the free-slot/free-block
   supply; with ``serving.prefix_cache`` on, each prompt is first matched
   block-by-block against the cross-request prefix cache and only the
   uncached suffix prefills (ISSUE 6); with ``serving.chunked_prefill``
   on (ISSUE 9), a prefill larger than the per-iteration chunk allowance
   admits into a persistent PREFILLING state instead of running whole;
2b. service PREFILLING rows: each iteration runs at most
   ``chunk_tokens`` of pending prefill — highest class first — from
   each request's committed cursor, riding the SAME batched-window
   program as the decode rows (``_window_step``, ISSUE 12), so one
   32k-token prompt can never spike every active stream's TPOT and a
   chunk's layer weight pass is shared with decode instead of paid
   separately;
3. grow each active row's block table for the token it is about to write
   (allocate-on-decode); under pool exhaustion the lowest-priority active
   request is preempted (blocks freed, request requeued; it resumes later
   by recomputing prompt+generated — no swap tier in v1);
4. run ONE jitted decode step over the packed active set.  The physical
   cache is a position-flat pool ``[L, num_blocks*block_size, ...]``
   (the `models/serving.py` cache layout with batch collapsed into the
   pool); block tables expand to per-position gather indices, the pool is
   gathered into the dense ``[L, B, S_pad, ...]`` view the existing
   `decode_fn` expects, and the one new KV vector per row scatters back.
   Finished rows retire immediately — their blocks recycle and a queued
   request can take the slot on the very next iteration, mid-batch.

The decode program compiles ONCE per (max_num_seqs, S_pad, sampling?)
— padding rows point at the reserved trash block and are ignored.

Greedy decoding is token-for-token identical to the static
``InferenceEngine.generate`` path: same prefill, same decode kernel, same
cache values (tested, including the int8 KV cache and across preemption).
Sampled requests draw per-row keys from ``fold_in(PRNGKey(seed),
position)`` — preemption-stable, but deliberately NOT the static engine's
batch-coupled rng chain.
"""
import collections
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from deepspeed_tpu.serving.block_manager import BlockManager
from deepspeed_tpu.serving.request import (AdmissionError, QueueFullError,
                                           RequestState, RequestTooLongError,
                                           ServeRequest)
from deepspeed_tpu.utils.logging import logger


def _round_up(n: int, q: int) -> int:
    return -(-n // q) * q


def _jit_device_local(fn):
    """``jax.jit`` with the body TRACED under
    ``sharding_pin_scope(False)`` (comm/mesh.py): the scheduler's
    compiled programs are single-device by design (ROADMAP item 1 — the
    fleet router / sharded-serving tier is the multi-device path), so
    the training-mesh layout pins model code carries (e.g.
    ``moe_layer``'s token-major constraint over the zero-shard axes)
    must not engage inside them.  On a multi-device host a pin engages
    whenever the token count divides the data axis — and this jaxlib's
    SPMD partitioner miscompiles the scheduler's gather/scatter-heavy
    programs under it (reproduced: mixtral spec verify at window width
    8 on the 8-device CPU harness returns zero logits; width 5 —
    non-divisible, pin skipped — is correct)."""
    def traced(*args):
        from deepspeed_tpu.comm.mesh import sharding_pin_scope
        with sharding_pin_scope(False):
            return fn(*args)
    return jax.jit(traced)


def _sample_rows(logits, seeds, positions, temps, top_ks, top_ps, do_flags,
                 any_sampling: bool):
    """Per-row sampling with traced per-request params.  ``positions``
    keys the rng per (seed, absolute token index) so an evicted-and-
    resumed request reproduces its stream exactly.  The temperature /
    top-k / top-p pipeline lives in ``spec/verifier.py`` so speculative
    rejection sampling draws from the SAME distribution."""
    from deepspeed_tpu.serving.spec.verifier import process_sampling_logits
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if not any_sampling:                    # static: all-greedy steps skip
        return greedy                       # the sort entirely
    x = process_sampling_logits(logits, temps, top_ks, top_ps)
    keys = jax.vmap(lambda s, p: jax.random.fold_in(jax.random.PRNGKey(s), p)
                    )(seeds, positions)
    sampled = jax.vmap(jax.random.categorical)(keys, x).astype(jnp.int32)
    return jnp.where(do_flags, sampled, greedy)


class ServingMetrics:
    """Serving observability (ISSUE 4): counters + registry-backed
    latency histograms (TTFT, per-token decode latency, queue wait, e2e
    latency) and occupancy histograms, rendered three ways from ONE
    store — monitor events (monitor/monitor.py sinks), the flat
    ``snapshot()`` dict, and Prometheus text for ``/metrics``
    (``render_prometheus``, the telemetry registry's shared exposition
    function)."""

    _QUANTILES = ((50, "p50"), (90, "p90"), (99, "p99"))
    #: histogram name -> snapshot/monitor key stem
    _LATENCY_HISTS = (("serving/ttft_s", "ttft"),
                      ("serving/token_latency_s", "token_latency"),
                      ("serving/latency_s", "latency"),
                      ("serving/queue_wait_s", "queue_wait"))

    def __init__(self, registry=None, max_accept_len: int = 17):
        from deepspeed_tpu.telemetry import (COUNT_BUCKETS, MetricsRegistry,
                                             OCCUPANCY_BUCKETS)
        #: isolated per scheduler by default; ds_serve passes the
        #: process-wide registry so train+serve share one exposition
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        self.counters = collections.Counter()
        self.gauges: Dict[str, float] = {}
        reg = self.registry
        self.ttft_s = reg.histogram("serving/ttft_s")
        self.token_latency_s = reg.histogram("serving/token_latency_s")
        self.latency_s = reg.histogram("serving/latency_s")
        self.queue_wait_s = reg.histogram("serving/queue_wait_s")
        self.decode_occupancy = reg.histogram("serving/decode_occupancy",
                                              buckets=OCCUPANCY_BUCKETS)
        self.prefill_batch_tokens = reg.histogram(
            "serving/prefill_batch_tokens", buckets=COUNT_BUCKETS)
        # tokens emitted per verify pass per speculating request
        # (accepted drafts + the bonus token) — ISSUE 5; unit-granular
        # buckets sized to the configured cap (max_draft_tokens + 1) so
        # high-k workloads never collapse into +Inf
        self.spec_accept_len = reg.histogram(
            "serve/spec_accept_len",
            buckets=tuple(range(1, max(max_accept_len, 2) + 1)))

    def observe_finished(self, req: ServeRequest):
        self.counters["completed"] += 1
        if req.ttft_s is not None:
            self.ttft_s.observe(req.ttft_s)
        if req.latency_s is not None:
            self.latency_s.observe(req.latency_s)
        times = req.token_times
        for a, b in zip(times, times[1:]):
            self.token_latency_s.observe(b - a)

    def observe_queue_wait(self, wait_s: float):
        self.queue_wait_s.observe(wait_s)

    def _hist(self, name: str):
        return self.registry.histogram(name)

    def _spec_accept_gauges(self) -> Dict[str, float]:
        """serve/spec_accept_len quantiles + mean, in raw token units
        (ISSUE 5: the /metrics surface the adaptive-k dashboards read)."""
        h = self.spec_accept_len
        out: Dict[str, float] = {}
        vals = h.quantiles(tuple(q for q, _tag in self._QUANTILES))
        if vals is None:
            return out
        for (_q, tag), v in zip(self._QUANTILES, vals):
            out[f"serve/spec_accept_len_{tag}"] = round(v, 3)
        if h.count:
            out["serve/spec_accept_len_mean"] = round(h.sum / h.count, 3)
        return out

    def snapshot(self) -> Dict[str, float]:
        out = {f"serving/{k}": float(v) for k, v in self.counters.items()}
        out.update({f"serving/{k}": float(v)
                    for k, v in self.gauges.items()})
        for hist_name, stem in self._LATENCY_HISTS:
            vals = self._hist(hist_name).quantiles(
                tuple(q for q, _tag in self._QUANTILES))
            if vals is None:
                continue
            for (_q, tag), v in zip(self._QUANTILES, vals):
                out[f"serving/{stem}_{tag}_ms"] = round(v * 1e3, 3)
        out.update(self._spec_accept_gauges())
        return out

    def to_events(self, step: int):
        return [(name, value, step)
                for name, value in sorted(self.snapshot().items())]

    def render_prometheus(self, extra_labels=None) -> str:
        """Single exposition path: mirror the counters/gauges (and the
        quantile gauges the dashboards want pre-computed) into the
        registry, then render its text format — histogram buckets
        included.  ``extra_labels`` ride every sample line (the fleet
        front-end's per-``replica`` label, ISSUE 11)."""
        for k, v in self.counters.items():
            self.registry.set_counter(f"serving/{k}", float(v))
        for k, v in self.gauges.items():
            self.registry.set_gauge(f"serving/{k}", float(v))
        for hist_name, stem in self._LATENCY_HISTS:
            vals = self._hist(hist_name).quantiles(
                tuple(q for q, _tag in self._QUANTILES))
            if vals is None:
                continue
            for (_q, tag), v in zip(self._QUANTILES, vals):
                self.registry.set_gauge(
                    f"serving/{stem}_{tag}_ms", round(v * 1e3, 3))
        for name, value in self._spec_accept_gauges().items():
            self.registry.set_gauge(name, value)
        return self.registry.render_prometheus(extra_labels=extra_labels)


class ContinuousBatchingScheduler:
    """Drives a Model's existing prefill/decode fns as a serving loop.

    ``model`` must provide ``init_cache_fn/prefill_fn/decode_fn`` (every
    in-tree decoder does); ``params`` are the placed inference params
    (e.g. ``InferenceEngine.params``).  ``monitor`` is any
    ``monitor/monitor.py`` sink; gauge+counter events flow to it each
    ``monitor_interval`` steps.
    """

    PROMPT_BUCKET = 16          # prefill compile count = distinct buckets

    def __init__(self, model, params, config, kv_cache_dtype=None,
                 monitor=None, injector=None, registry=None,
                 proposer=None, flightrec=None, anomaly=None):
        if (model.init_cache_fn is None or model.prefill_fn is None
                or model.decode_fn is None):
            raise ValueError("model does not expose the KV-cache serving "
                             "surface (init_cache_fn/prefill_fn/decode_fn)")
        from deepspeed_tpu.resilience.faults import resolve_injector
        self.model = model
        self.params = params
        self.cfg = config
        self.kv_cache_dtype = kv_cache_dtype
        self.monitor = monitor
        self.injector = (injector if injector is not None
                         else resolve_injector())
        self._telemetry_registry = registry
        # cross-request prefix cache (ISSUE 6): released full blocks are
        # hash-addressed and retained; _admit matches prompts against
        # them and prefills only the uncached suffix
        pc = config.prefix_cache
        self._prefix_cache_on = bool(pc.enabled)
        self._prefix_min_blocks = pc.min_prefix_blocks
        self.block_mgr = BlockManager(config.num_blocks, config.block_size,
                                      injector=self.injector,
                                      cache_enabled=pc.enabled,
                                      max_cached_blocks=pc.max_cached_blocks)
        # int8-weights decode dispatch: install this config's threshold so
        # the model-side use_scan_decode sees it (env override still wins
        # inside get_quant_scan_threshold).  Only an EXPLICITLY supplied
        # key installs — a defaulted config leaves the module default (and
        # any test monkeypatch of it) in force
        if "quant_scan_threshold_mb" in config.model_fields_set:
            from deepspeed_tpu.models import serving as _serving
            _serving.set_quant_scan_threshold(
                int(config.quant_scan_threshold_mb) << 20)
        # MoE expert dispatch (ISSUE 8): an explicit serving.moe_dispatch
        # installs the process override so every model-side
        # resolve_dispatch_mode — decode, verify, suffix prefill — sees
        # it (DS_MOE_DISPATCH env still wins at trace time)
        if config.moe_dispatch is not None:
            from deepspeed_tpu.moe.layer import set_dispatch_override
            set_dispatch_override(config.moe_dispatch)

        bs = config.block_size
        model_ctx = int(getattr(model.config, "max_seq_len", 1 << 30))
        per_seq_cap = (config.max_blocks_per_seq * bs
                       if config.max_blocks_per_seq else model_ctx)
        #: hard per-request length ceiling (prompt + generated)
        self.max_model_len = min(model_ctx, per_seq_cap,
                                 self.block_mgr.num_usable_blocks * bs)
        # dense gather width: fixed for the whole session so the decode
        # program compiles once; 64-multiple for the decode kernel's
        # S-block alignment (engine.py cache_size does the same)
        self.s_pad = _round_up(self.max_model_len, 64)
        self.blocks_per_table = -(-self.s_pad // bs)
        # table→flat-pool expansion, shared by every dense gather
        # (_pos_idx_row): logical position p lives at
        # table[p // bs] * bs + p % bs
        self._pos_offs = np.arange(self.s_pad) % bs
        self._pos_blk = np.arange(self.s_pad) // bs

        #: per-step block-accounting invariant check (O(num_blocks) under
        #: the scheduler lock — a debug aid, not a production default);
        #: the spec test suite arms it for every scheduler it builds
        self._debug_invariant = bool(int(
            os.environ.get("DS_SERVE_DEBUG", "0") or 0))
        self._lock = threading.RLock()
        self._queue: List[ServeRequest] = []
        self._slots: List[Optional[ServeRequest]] = \
            [None] * config.max_num_seqs
        self._next_id = 0
        self._step_count = 0
        self.metrics = ServingMetrics(
            registry=self._telemetry_registry,
            max_accept_len=getattr(getattr(config, "spec", None),
                                   "max_draft_tokens", 16) + 1)
        # MoE routing-health telemetry (ISSUE 8 satellite): an
        # explicitly-passed registry (the ds_serve /metrics path) arms
        # the moe_layer host-callback tap; a registry-less scheduler
        # DISARMS it (last-constructed wins — a retired server's dead
        # registry must not keep receiving per-step callbacks from
        # programs a later scheduler traces)
        from deepspeed_tpu.moe.layer import set_moe_metrics_registry
        set_moe_metrics_registry(self._telemetry_registry)
        # black-box layer (ISSUE 7): flight recorder for per-request
        # lifecycle events, rolling step-latency anomaly detection, and
        # per-class SLO burn accounting — all writing into the SAME
        # registry/trace/correlation-id space as the PR 4 telemetry
        from deepspeed_tpu.telemetry.anomaly import (AnomalyMonitor,
                                                     SLOTracker)
        from deepspeed_tpu.telemetry.flight_recorder import \
            get_flight_recorder
        self.flightrec = (flightrec if flightrec is not None
                          else get_flight_recorder())
        self.anomaly = (anomaly if anomaly is not None
                        else AnomalyMonitor(registry=self.metrics.registry,
                                            flightrec=self.flightrec))
        self.slo = SLOTracker(getattr(config, "slo", None),
                              self.metrics.registry)
        # chunked prefill (ISSUE 9): prefill becomes a per-iteration
        # resource — admissions larger than the chunk allowance persist
        # in PREFILLING state and the batched-window step services
        # them, highest SLO class first, within the shared token budget
        cp = getattr(config, "chunked_prefill", None)
        self._chunked_on = bool(getattr(cp, "enabled", False))
        self._chunk_tokens = int(getattr(cp, "chunk_tokens", 256) or 256)
        self._prefill_spent = 0         # prefill tokens executed this step
        self._serve_t0 = time.monotonic()   # tokens/s accounting window
        self._prefill_fns = {}
        self._decode_fns = {}
        self._sample1_fns = {}
        self._window_fns = {}
        self._suffix_prefill_fns = {}
        # fused decode megakernel (ISSUE 12): an explicit
        # serving.fused_decode installs the process override so every
        # model-side fused_decode_active resolution — decode, verify,
        # suffix prefill — sees it (DS_FUSED_DECODE env wins at trace
        # time; None leaves auto-on-TPU in force)
        if config.fused_decode is not None:
            from deepspeed_tpu.ops.pallas.fused_decode import \
                set_fused_decode_override
            set_fused_decode_override(bool(config.fused_decode))
        self._copy_fn = None            # COW-fork block copy (lazy jit)
        self._finished_this_step: List[ServeRequest] = []
        # --- speculative decoding (ISSUE 5): resolve the proposer from
        # serving.spec.mode; an explicit proposer wins (and implies spec
        # on even when the config section says off — test/bench intent)
        self.proposer = self._resolve_proposer(proposer)
        # perf observatory (ISSUE 13): one dtype-aware weight-stream
        # model per scheduler (split_quantized_bytes library math) — the
        # HBM-byte term every compiled program family reports against
        from deepspeed_tpu.telemetry.costmodel import (costmodel_enabled,
                                                       param_stream_bytes)
        self._costmodel_on = costmodel_enabled()
        self._cost_stream = None
        if self._costmodel_on:
            try:
                mcfg = getattr(self.model, "config", None)
                self._cost_stream = param_stream_bytes(
                    self.params, batch=self.cfg.max_num_seqs,
                    top_k=getattr(mcfg, "top_k", None),
                    num_experts=getattr(mcfg, "num_experts", None))
            except Exception:       # cost accounting must never block serving
                self._costmodel_on = False
        # comm observatory (ISSUE 19): attach the process-wide CommStat
        # to THIS scheduler's telemetry spine so serve-side collective
        # windows (barriers, eager collectives) publish into the same
        # registry /debug/comm renders
        from deepspeed_tpu.telemetry.commstat import (commstat_enabled,
                                                      get_commstat)
        if commstat_enabled():
            get_commstat().attach(registry=self.metrics.registry,
                                  anomaly=self.anomaly,
                                  flightrec=self.flightrec,
                                  injector=self.injector)
        self.pool = self._init_pool()
        # memory observatory (ISSUE 14): per-step byte attribution of
        # the KV pool (allocated / prefix-cache retained / free), the
        # params, and the spec draft pool into the process-wide tiered
        # ledger — mem/* gauges, /debug/memory, OOM forensics
        from deepspeed_tpu.telemetry.memory import (get_memory_ledger,
                                                    memory_enabled,
                                                    tree_bytes)
        self._mem_on = memory_enabled(getattr(
            getattr(config, "telemetry", None), "memory", None))
        self._mem_ledger = get_memory_ledger() if self._mem_on else None
        self._pool_bytes = 0
        self._bytes_per_block = 0.0
        if self._mem_on:
            try:
                self._pool_bytes = tree_bytes(self.pool)
                self._bytes_per_block = (self._pool_bytes
                                         / self.cfg.num_blocks)
                from deepspeed_tpu.telemetry.memory import attribute_params
                attribute_params(self._mem_ledger, self.params,
                                 stream=self._cost_stream)
                draft_pool = getattr(self.proposer, "pool", None)
                if draft_pool is not None:
                    self._mem_ledger.set_bytes(
                        "device", "spec_draft", tree_bytes(draft_pool))
            except Exception:   # byte accounting must never block serving
                self._mem_on = False
        # tiered KV spill (ISSUE 16): LRU pressure demotes refcount-0
        # hashed blocks HBM→host→NVMe through the offload engine
        # instead of evicting; cold prefix hits swap back in async
        # (overlapped with the decode iteration) and preemption parks
        # committed KV on NVMe.  Needs the prefix cache — cold tiers
        # are keyed by its chain hashes.
        from deepspeed_tpu.serving.kv_tiering import tiering_enabled
        kt = getattr(config, "kv_tiering", None)
        self._tier_store = None
        self._park_on_preempt = bool(getattr(kt, "park_on_preempt", True))
        #: request_id -> cold chain hashes whose swap-in is in flight
        #: (the request sits out admission until they materialize)
        self._swap_pending = collections.OrderedDict()
        self._swapin_fn = None          # tier swap-in scatter (lazy jit)
        self._pool_treedef = jax.tree_util.tree_structure(self.pool)
        if tiering_enabled(kt) and self._prefix_cache_on:
            from deepspeed_tpu.serving.kv_tiering import KvTierStore
            self._tier_store = KvTierStore(
                kt, injector=self.injector, flightrec=self.flightrec)
            self.block_mgr.attach_tiering(self._tier_store,
                                          self._extract_block)
        # multi-tenant LoRA adapters (ISSUE 20): paged AdapterStore over
        # the same offload engine — requests carry adapter_id, admission
        # pins a resident HBM slot (swap-in overlapped with the running
        # decode like cold-tier prefix hits), and every program family
        # takes an optional trailing gather-LoRA operand
        from deepspeed_tpu.serving.adapters import adapters_enabled
        ac = getattr(config, "adapters", None)
        self._adapters_cfg = ac
        self.adapter_store = None
        self.adapter_registry = None
        #: request_id -> adapter_id whose swap-in is in flight (the
        #: request sits out admission until it materializes)
        self._adapter_pending: Dict[int, str] = {}
        #: rolling base-weight version label (ISSUE 20 live hot-swap);
        #: stamped on /metrics and every admit/retire/step flight event
        self.weights_version = "v1"
        self._weights_swapped = False
        if ac is not None and adapters_enabled(ac):
            if not model.meta.get("lora_serving"):
                raise ValueError(
                    f"serving.adapters.enabled: model "
                    f"{model.meta.get('name')!r} does not implement the "
                    "gather-LoRA serving pass (meta['lora_serving'])")
            from deepspeed_tpu.serving.adapters import (AdapterRegistry,
                                                        AdapterStore)
            self.adapter_registry = AdapterRegistry(
                max_rank=ac.max_rank,
                allowed_targets=ac.targets or None)
            shapes = self._lora_block_shapes()
            self.adapter_store = AdapterStore(
                self.adapter_registry, ac, shapes,
                injector=self.injector, flightrec=self.flightrec)
            for aid, path in sorted(ac.adapters.items()):
                self.register_adapter(aid, path=path)

    def _resolve_proposer(self, proposer):
        spec = getattr(self.cfg, "spec", None)
        mode = getattr(spec, "mode", "off") if spec is not None else "off"
        if proposer is not None:
            return proposer
        if mode == "off":
            return None
        if mode == "ngram":
            from deepspeed_tpu.serving.spec import NgramProposer
            return NgramProposer(ngram_max=spec.ngram_max,
                                 ngram_min=spec.ngram_min)
        # draft mode needs a model+params pair the scheduler cannot
        # conjure — bin/ds_serve builds the DraftModelProposer from
        # serving.spec.draft_model
        raise ValueError(
            "serving.spec.mode='draft' needs a DraftModelProposer passed "
            "as ContinuousBatchingScheduler(..., proposer=...)")

    # -------------------------------------------- adapter serving (20)
    def _lora_block_shapes(self) -> Dict[str, tuple]:
        """Stackable gather-LoRA targets from the base params: every
        3-D ``blocks`` leaf (stacked [L, d_in, d_out] projection;
        biases/norms are 2-D and skip), optionally restricted to
        ``serving.adapters.targets``.  Quantized leaves report their
        LOGICAL shape — the LoRA delta applies in float on the qdot
        output, never inside the int8 payload."""
        blocks = (self.params.get("blocks", {})
                  if isinstance(self.params, dict) else {})
        want = set(self._adapters_cfg.targets or ())
        shapes: Dict[str, tuple] = {}
        for t, leaf in blocks.items():
            if want and t not in want:
                continue
            shp = tuple(getattr(leaf, "shape", ()) or ())
            if not shp and hasattr(leaf, "q"):
                # QuantizedTensor: the int8 payload carries the logical
                # [L, d_in, d_out] shape
                shp = tuple(getattr(leaf.q, "shape", ()) or ())
            if len(shp) == 3:
                shapes[t] = (int(shp[0]), int(shp[1]), int(shp[2]))
        if not shapes:
            raise ValueError(
                "serving.adapters: no stackable [L, d_in, d_out] block "
                "weights found in the model params"
                + (f" for targets {sorted(want)}" if want else ""))
        return shapes

    def register_adapter(self, adapter_id: str, lora_tree=None, path=None,
                         alpha=None, slo_class=None):
        """Register + ingest one LoRA adapter (the ``ds_serve
        --adapters`` startup path and the test/tooling surface).
        Validation failure raises ValueError and leaves the registry
        unchanged; on success the payload enters the host paging tier
        and the first request swap-ins it to HBM."""
        if self.adapter_registry is None:
            raise ValueError("serving.adapters is not enabled")
        with self._lock:
            if path is not None:
                m = self.adapter_registry.register_file(
                    adapter_id, path, slo_class=slo_class)
            else:
                m = self.adapter_registry.register(
                    adapter_id, lora_tree, alpha=alpha,
                    slo_class=slo_class)
            try:
                ok = self.adapter_store.ingest(adapter_id)
            except ValueError:
                self.adapter_registry.unregister(adapter_id)
                raise
            if not ok:
                # fault-denied ingest: registered but in no tier — the
                # typed failure surfaces per-request at swap-in time
                self.metrics.counters["adapter_load_failures"] += 1
            return m

    def _adapter_slot(self, req: ServeRequest) -> int:
        """This request's HBM adapter slot for program packing
        (-1 = base model / no adapter)."""
        if self.adapter_store is None or req.adapter_id is None:
            return -1
        s = self.adapter_store.slot_of(req.adapter_id)
        return -1 if s is None else s

    def _lora_arg(self, groups) -> tuple:
        """Trailing gather-LoRA operand for one program execution: ()
        when no packed row carries an adapter — the program runs its
        unchanged base trace, so adapter-less steps pay exactly
        nothing — else the one pytree the model-side pass consumes
        (per-row slot groups + the store's slot stacks; each distinct
        adapter's factors stream once per execution)."""
        g = np.asarray(groups, np.int32)
        if self.adapter_store is None or not (g >= 0).any():
            return ()
        st = self.adapter_store
        return ({"groups": jnp.asarray(g), "scale": st.scale,
                 "stacks": st.stacks},)

    def _schedule_adapter_swapin(self, req: ServeRequest) -> bool:
        """Kick (or piggyback on) the async swap-in for a cold adapter;
        the request sits out admission until it materializes.  False =
        the adapter is in no tier (quarantined / dropped) — the caller
        runs the failure path."""
        aid = req.adapter_id
        if aid not in self._adapter_pending.values():
            if not self.adapter_store.schedule_swapin(
                    aid, corr=f"req-{req.request_id}"):
                return False
        if req.request_id not in self._adapter_pending:
            self.flightrec.record("req/adapter_swap_in",
                                  corr=f"req-{req.request_id}",
                                  adapter=aid)
        self._adapter_pending[req.request_id] = aid
        return True

    def _materialize_adapter_swapins(self):
        """Complete adapter swap-ins scheduled on an earlier step (the
        I/O already overlapped at least one decode iteration): install
        each into an HBM slot; waiters re-enter this step's admission
        line.  ``wait`` (every slot pinned) stays pending and retries
        as requests retire; ``fail`` runs the per-request failure path
        (typed reject, or base-model fallback per config)."""
        if self.adapter_store is None or not self._adapter_pending:
            return
        queued = {r.request_id: r for r in self._queue}
        status_of: Dict[str, str] = {}
        for rid in list(self._adapter_pending):
            aid = self._adapter_pending[rid]
            req = queued.get(rid)
            if req is None:         # expired / extracted while pending
                self._adapter_pending.pop(rid)
                continue
            st = status_of.get(aid)
            if st is None:
                st, _slot = self.adapter_store.swap_in(
                    aid, corr=f"req-{rid}")
                status_of[aid] = st
            if st == "ok":
                self._adapter_pending.pop(rid)
            elif st == "fail":
                self._adapter_pending.pop(rid)
                self._adapter_failure(req)

    def _adapter_failure(self, req: ServeRequest) -> bool:
        """One request's adapter could not materialize (fault / IO /
        integrity / quarantine).  With ``fallback_to_base`` the request
        degrades to the base model (flagged on its response) and True
        returns; otherwise it fails TYPED — rejected with a reason,
        never a crash — and every other tenant's stream is untouched."""
        aid = req.adapter_id
        ac = self._adapters_cfg
        if ac is not None and getattr(ac, "fallback_to_base", False):
            req.adapter_id = None
            req.adapter_fallback = True
            self.metrics.counters["adapter_fallbacks"] += 1
            self.flightrec.record("req/adapter_fallback",
                                  corr=f"req-{req.request_id}",
                                  adapter=aid)
            return True
        if req in self._queue:
            self._queue.remove(req)
        req.state = RequestState.REJECTED
        req.reject_reason = (f"adapter {aid!r} failed to load "
                             "(fault/IO/integrity)")
        self.metrics.counters["adapter_rejects"] += 1
        self.flightrec.record("req/adapter_fail",
                              corr=f"req-{req.request_id}", adapter=aid)
        req.done.set()
        return False

    def install_params(self, new_params, version: str):
        """Live base-weight hot-swap (ISSUE 20): install a new params
        pytree under the scheduler lock and roll the version label.
        Structure/shapes/dtypes must match the old tree — params is a
        TRACED argument of every compiled program family, so an
        identical-structure install triggers zero recompiles.  Call on
        a drained replica (fleet ``Router.swap_weights``) for token-
        identical streams; an undrained install changes weights
        mid-stream."""
        old = jax.tree_util.tree_structure(self.params)
        new = jax.tree_util.tree_structure(new_params)
        if old != new:
            raise ValueError(
                "install_params: new params tree does not match the "
                "serving tree (hot-swap requires identical structure)")
        with self._lock:
            self.params = new_params
            self.weights_version = str(version)
            self._weights_swapped = True
            self.flightrec.record("route/weights_swap",
                                  corr=f"serve-step-{self._step_count}",
                                  version=self.weights_version,
                                  step=self._step_count)
            self.metrics.counters["weights_swaps"] += 1

    # ------------------------------------------------------------- pool
    def _init_pool(self):
        """Position-flat physical cache: [L, num_blocks*block_size, ...]
        (init_cache layout with the batch dim collapsed into the pool)."""
        n_pos = self.cfg.num_blocks * self.cfg.block_size
        cache = self.model.init_cache_fn(1, n_pos, self.kv_cache_dtype)
        return jax.tree.map(lambda a: a[:, 0], cache)

    # ------------------------------------------------------- jitted fns
    def _instrument(self, name: str, fn, variant=None):
        """``_jit_device_local`` plus the ISSUE 13 cost model: the first
        invocation of each program variant traces ``fn`` once more (no
        compile) and registers a CostReport — dot FLOPs, weight-stream
        HBM bytes, pallas launch sites, collective bytes — publishing
        ``perf/*`` gauges into this scheduler's registry.

        ``variant(args) -> (suffix, weight_passes)`` resolves per-call
        program variants: the k-step fused decode scans k FULL weight
        passes per execution and jit compiles one program per k, so
        each k is its own cost family (``serve/decode:k8``) with a
        k-scaled byte model — one shared report would understate the
        floor by k.  Analysis failure (or DS_PERF_COSTMODEL=0) degrades
        to plain jit; it never blocks a step."""
        jitted = _jit_device_local(fn)
        if not self._costmodel_on:
            return jitted
        analyzed = set()
        stream = self._cost_stream or {}

        def wrapper(*args):
            vname, passes = name, 1
            if variant is not None:
                try:
                    suffix, passes = variant(args)
                    vname = name + suffix
                except Exception:           # malformed packing: keep base
                    vname, passes = name, 1
            if vname not in analyzed:
                analyzed.add(vname)
                try:
                    from deepspeed_tpu.telemetry.costmodel import analyze_fn
                    from deepspeed_tpu.telemetry.roofline import \
                        publish_report
                    base = stream.get("weights_floor_bytes")
                    report = analyze_fn(
                        fn, *args, name=vname,
                        hbm_bytes=None if base is None else base * passes,
                        detail=dict(
                            {k: v for k, v in stream.items()
                             if isinstance(v, int)},
                            weight_passes=passes))
                    publish_report(self.metrics.registry, report)
                except Exception as e:      # noqa: BLE001 — best-effort
                    logger.debug(f"costmodel: {vname} analysis "
                                 f"failed: {e}")
            return jitted(*args)

        return wrapper

    def _prefill_fn(self, sp: int):
        if sp not in self._prefill_fns:
            model, kv_dtype = self.model, self.kv_cache_dtype
            cache_len = _round_up(sp, 64)

            def fn(params, pool, tokens, length, dest_idx, lora=None):
                cache = model.init_cache_fn(1, cache_len, kv_dtype)
                if lora is None:
                    logits, cache = model.prefill_fn(
                        params, {"input_ids": tokens}, cache)
                else:
                    logits, cache = model.prefill_fn(
                        params, {"input_ids": tokens}, cache, lora=lora)
                pool = jax.tree.map(
                    lambda p, c: p.at[:, dest_idx].set(c[:, 0, :sp]),
                    pool, cache)
                return logits[0, length[0] - 1][None], pool

            self._prefill_fns[sp] = self._instrument(
                f"serve/prefill:sp{sp}", fn)
        return self._prefill_fns[sp]

    def _sample1_fn(self, any_sampling: bool):
        if any_sampling not in self._sample1_fns:
            self._sample1_fns[any_sampling] = _jit_device_local(
                lambda lg, s, pos, t, k, p, d: _sample_rows(
                    lg, s, pos, t, k, p, d, any_sampling))
        return self._sample1_fns[any_sampling]

    def _decode_fn(self, any_sampling: bool):
        """Multi-step decode program: ``dest_steps [k, B]`` carries the
        pre-allocated pool destination per fused iteration; a lax.scan
        runs k gather→decode→scatter→sample iterations on device,
        amortizing per-step dispatch (k=1 is plain single-step)."""
        key = any_sampling
        if key not in self._decode_fns:
            model = self.model

            def fn(params, pool, ints, floats, do_flags, pos_idx,
                   lora=None):
                # ints [4+k, B]: tokens, lengths, seeds, top_ks,
                # dest_steps[k]; floats [2, B]: temps, top_ps.  One packed
                # array per dtype — per-call device_put overhead measured
                # ~40% of toy-scale serving wall time with 11 loose args
                tokens, lengths, seeds, top_ks = ints[0], ints[1], \
                    ints[2], ints[3]
                dest_steps = ints[4:]
                temps, top_ps = floats[0], floats[1]
                B = tokens.shape[0]
                rows = jnp.arange(B)

                def body(carry, dest_idx):
                    pool, toks, lens = carry
                    dense = jax.tree.map(lambda p: p[:, pos_idx], pool)
                    if lora is None:
                        logits, new_cache = model.decode_fn(
                            params, toks, dense, lens)
                    else:
                        logits, new_cache = model.decode_fn(
                            params, toks, dense, lens, lora=lora)
                    # the ONE vector decode wrote per row, back to the pool
                    new_vecs = jax.tree.map(
                        lambda c: c[:, rows, lens], new_cache)
                    pool = jax.tree.map(
                        lambda p, nv: p.at[:, dest_idx].set(nv),
                        pool, new_vecs)
                    nxt = _sample_rows(logits, seeds, lens + 1, temps,
                                       top_ks, top_ps, do_flags,
                                       any_sampling)
                    return (pool, nxt, lens + 1), nxt

                (pool, _, _), toks = jax.lax.scan(
                    body, (pool, tokens, lengths), dest_steps)
                return toks, pool               # toks [k, B]

            # ints [4+k, B]: the scan length k IS the weight-pass
            # count of one execution (see _instrument docstring)
            self._decode_fns[key] = self._instrument(
                "serve/decode", fn,
                variant=lambda args: (f":k{args[2].shape[0] - 4}",
                                      args[2].shape[0] - 4))
        return self._decode_fns[key]

    def _window_fn(self, W: int, any_sampling: bool):
        """THE batched-window program (ISSUE 12): one compiled family —
        keyed only by (window bucket, sampling?) — through which plain
        decode rows (window width 1), speculative-verify windows
        (ISSUE 5), and chunked-prefill chunks (ISSUE 9) all ride the
        SAME per-layer weight pass: one dense pool gather, the model's
        ``verify_fn`` (the fused megakernel path when enabled — ONE
        Pallas call per layer), ONE windowed scatter back, and the
        accept/emit math on device.  This replaces the PR 5 verify
        family and the PR 9 per-request chunk programs: a prefill chunk
        now amortizes the decode batch's weight stream instead of
        paying its own (Sarathi-style piggybacking).

        Packing: ints [4 + 2W, B] — rows 0..W-1 window tokens (decode
        rows: col 0 = last committed token then padded drafts; chunk
        rows: the prompt slice at the cursor), W: first window position
        (decode: seq-1; chunk: cursor), W+1: draft_len (chunk rows:
        take-1 so the bonus column lands on the chunk's last real
        position), W+2: seeds, W+3: top_ks, W+4..: per-window-position
        pool destinations (pads point at the trash block); floats
        [2, B]: temps, top_ps."""
        key = (W, any_sampling)
        if key not in self._window_fns:
            from deepspeed_tpu.serving.spec.verifier import (accept_tokens,
                                                             scan_verify_fn)
            model = self.model
            vf = model.verify_fn
            if vf is None or os.environ.get("DS_SPEC_VERIFY") == "scan":
                vf = scan_verify_fn(model.decode_fn)

            def fn(params, pool, ints, floats, do_flags, pos_idx,
                   lora=None):
                tokens = ints[:W].T                     # [B, W]
                lengths = ints[W]
                draft_len = ints[W + 1]
                seeds, top_ks = ints[W + 2], ints[W + 3]
                dests = ints[W + 4:]                    # [W, B]
                temps, top_ps = floats[0], floats[1]
                B = tokens.shape[0]
                rows = jnp.arange(B)
                dense = jax.tree.map(lambda p: p[:, pos_idx], pool)
                if lora is None:
                    logits, new_cache = vf(params, tokens, dense, lengths)
                else:
                    # adapters need the model's real verify surface (the
                    # scan-of-decode fallback has no lora plumbing);
                    # lora_serving models always expose verify_fn
                    logits, new_cache = model.verify_fn(
                        params, tokens, dense, lengths, lora=lora)
                # ONE windowed scatter for the whole batch: clamped
                # GATHER of each row's window from the dense view (the
                # _suffix_prefill_fn clamp reasoning — pad rows whose
                # window overruns s_pad read clamped positions but their
                # dests point at the trash block), then one flat .set
                win_pos = lengths[:, None] + jnp.arange(W)[None, :]
                flat = dests.T.reshape(-1)              # [B*W]
                pool = jax.tree.map(
                    lambda p, c: p.at[:, flat].set(
                        c[:, rows[:, None],
                          jnp.minimum(win_pos, c.shape[2] - 1)].reshape(
                            (c.shape[0], B * W) + c.shape[3:])),
                    pool, new_cache)
                acc, out = accept_tokens(
                    logits, tokens, draft_len, seeds, lengths + 1,
                    temps, top_ks, top_ps, do_flags, any_sampling)
                return acc, out, pool

            self._window_fns[key] = self._instrument(
                f"serve/window:w{W}", fn)
        return self._window_fns[key]

    def _window_bucket(self, need: int) -> int:
        """Window widths compile per bucket: 1 and 2 exactly (the plain
        and minimal-draft steps), then SUFFIX_BUCKET multiples — one
        family covers spec verify AND chunk service up to SUFFIX_CHUNK
        (wider drafts keep rounding up, so program count stays
        bounded)."""
        if need <= 2:
            return need
        return _round_up(need, self.SUFFIX_BUCKET)

    #: suffix-prefill chunk width (ISSUE 6): cached-prefix admissions
    #: prefill only the uncached tail, riding the verify-window path in
    #: chunks of at most this many tokens — one weight pass per chunk,
    #: and a bounded compiled-program set (W ∈ SUFFIX_BUCKET-multiples up
    #: to 64) instead of one W-unrolled program per suffix length
    SUFFIX_CHUNK = 64
    #: finer than PROMPT_BUCKET: the window unrolls per-position
    #: attention, so rounding a 5-token tail up to 16 doubles its cost
    SUFFIX_BUCKET = 8

    def _suffix_prefill_fn(self, W: int):
        """Prefix-cache suffix prefill (ISSUE 6): score ``W`` prompt-tail
        tokens at positions ``length..length+W-1`` against the request's
        pool-gathered cache — the cached prefix supplies positions below
        ``length`` — and scatter the window's KV vectors back (pad
        positions land in the trash block).  This IS the speculative
        verify surface (`models/serving.py verify_window`, or the
        scan-of-decode fallback for families without it): one weight
        pass scores the whole window with per-position causal attention,
        exactly what a resume-style re-prefill of the suffix needs."""
        if W not in self._suffix_prefill_fns:
            from deepspeed_tpu.serving.spec.verifier import scan_verify_fn
            model = self.model
            vf = model.verify_fn
            if vf is None or os.environ.get("DS_SPEC_VERIFY") == "scan":
                vf = scan_verify_fn(model.decode_fn)

            def fn(params, pool, tokens, length, dests, pos_idx,
                   lora=None):
                # tokens [1, W]; length [1] = first suffix position;
                # dests [W] flat pool destinations; pos_idx [1, S_pad]
                dense = jax.tree.map(lambda p: p[:, pos_idx], pool)
                if lora is None:
                    logits, new_cache = vf(params, tokens, dense, length)
                else:
                    logits, new_cache = model.verify_fn(
                        params, tokens, dense, length, lora=lora)
                # ONE gather+scatter for the whole window (a per-position
                # .set loop would copy the full pool W times on backends
                # that don't fuse the chain).  Clamped GATHER, not a
                # dynamic_slice: when the padded window overruns the
                # dense width (a prompt ending within W of s_pad) a
                # dynamic_slice would clamp its START and silently
                # misalign every row against dests — the clamp here only
                # affects pad rows, whose dests point at the trash block
                win = jax.tree.map(
                    lambda c: c[:, 0][:, jnp.minimum(
                        length[0] + jnp.arange(W), c.shape[2] - 1)],
                    new_cache)
                pool = jax.tree.map(
                    lambda p, w: p.at[:, dests].set(w), pool, win)
                return logits, pool             # logits [1, W, V]

            self._suffix_prefill_fns[W] = _jit_device_local(fn)
        return self._suffix_prefill_fns[W]

    def _cow_copy(self, pair):
        """Execute a copy-on-write fork's physical KV move: duplicate the
        shared source block's pool positions into the request's private
        destination block (the BlockManager already swapped the table
        entry).  One jitted gather/scatter, reused for every fork."""
        if self._copy_fn is None:
            self._copy_fn = _jit_device_local(lambda pool, src, dst: jax.tree.map(
                lambda p: p.at[:, dst].set(p[:, src]), pool))
        src, dst = pair
        bs = self.block_mgr.block_size
        self.pool = self._copy_fn(
            self.pool,
            jnp.arange(src * bs, (src + 1) * bs, dtype=jnp.int32),
            jnp.arange(dst * bs, (dst + 1) * bs, dtype=jnp.int32))

    # ----------------------------------------------------- tiered KV (16)
    def _extract_block(self, block: int):
        """Snapshot one block's physical payload as host numpy leaves
        (the BlockManager's demotion extractor).  device_get of a pool
        slice per leaf — bit-exact, dtype-preserving (int8 KV
        included), so a later swap-in reproduces the block verbatim
        and tier hits stay token-identical."""
        bs = self.block_mgr.block_size
        lo, hi = block * bs, (block + 1) * bs
        return [np.asarray(leaf[:, lo:hi])
                for leaf in jax.tree_util.tree_leaves(self.pool)]

    def _write_block(self, block: int, arrays):
        """Scatter one swapped-in payload into its promoted pool block
        (the inverse of _extract_block): one jitted scatter, compiled
        once — same shape every time, the _cow_copy discipline."""
        if self._swapin_fn is None:
            self._swapin_fn = _jit_device_local(
                lambda pool, dst, vals: jax.tree.map(
                    lambda p, v: p.at[:, dst].set(v), pool, vals))
        bs = self.block_mgr.block_size
        vals = jax.tree_util.tree_unflatten(
            self._pool_treedef, [jnp.asarray(a) for a in arrays])
        self.pool = self._swapin_fn(
            self.pool,
            jnp.arange(block * bs, (block + 1) * bs, dtype=jnp.int32),
            vals)

    def _schedule_swapins(self, req, entries) -> bool:
        """Queue the async swap-in for a tier-matched prompt's cold
        entries; the request sits out admission (still QUEUED) until
        the next step materializes them — the reads overlap THIS
        step's decode instead of blocking it.  True = scheduled."""
        cold = [h for tier, _, h in entries if tier != "hbm"]
        if not cold or req.request_id in self._swap_pending:
            return False
        for h in cold:
            self._tier_store.prefetch(h, corr=f"req-{req.request_id}")
        # pend the WHOLE chain, hot entries included: materialization
        # must pin the already-hot blocks against its own promote-cap
        # trim, or a small max_cached_blocks demotes block k while
        # promoting block k+1 of the same prefix and the request
        # re-matches cold forever (swap-in livelock)
        self._swap_pending[req.request_id] = [h for _, _, h in entries]
        return True

    def _materialize_swapins(self):
        """Complete pending swap-ins (scheduled on an earlier step, so
        the I/O has already overlapped at least one decode iteration):
        fetch each payload, re-register its hash as an HBM cache entry
        (BlockManager.promote), and scatter the bytes into the promoted
        block — the normal prefix-cache admission path then attaches it
        like any hot hit.  A failed fetch (kv.swap fault, torn NVMe
        payload, I/O error) drops the rest of the chain: those blocks
        simply re-prefill — degraded, never corrupt."""
        if self._tier_store is None or not self._swap_pending:
            return
        queued = {r.request_id for r in self._queue}
        c = self.metrics.counters
        promoted = set()        # this pass's blocks: cap-trim exempt
        for rid in list(self._swap_pending):
            hashes = self._swap_pending.pop(rid)
            if rid not in queued:
                continue        # expired/extracted; entries stay cached
            for h in hashes:
                hot = self.block_mgr._by_hash.get(h)
                if hot is not None:
                    promoted.add(hot)   # pin the chain's hot prefix
                    continue
                got = self._tier_store.fetch(h, corr=f"req-{rid}")
                if got is None:
                    break       # degrade: the remainder re-prefills
                tier, arrays = got
                b = self.block_mgr.promote(h, protect=promoted)
                if b is None:   # pool exhausted mid-promotion
                    break
                promoted.add(b)
                self._write_block(b, arrays)
                if tier == "host":
                    c["kv_tier_hit_host"] += 1
                else:
                    c["kv_tier_hit_nvme"] += 1

    # ----------------------------------------------------------- submit
    def submit(self, prompt_ids, sampling=None, priority: int = 0,
               timeout_s: float = 0.0, slo_class: str = "default",
               adapter_id: Optional[str] = None) -> ServeRequest:
        """Enqueue a request; raises AdmissionError (429-style) instead of
        crashing or wedging the loop.  ``slo_class`` names the request's
        ``serving.slo`` class for burn accounting AND admission control
        (unknown classes fall back to ``default``): with
        ``serving.slo.shed_enabled``, a saturated system sheds the
        lowest-priority classes here with a RequestShedError carrying
        the Retry-After hint (ISSUE 9).  ``adapter_id`` selects the
        tenant's LoRA adapter (ISSUE 20): unknown ids raise the typed
        UnknownAdapterError (a 4xx at the front door, never a 500), and
        a request submitted with the DEFAULT class inherits its
        tenant's ``serving.adapters.slo_class_map`` class."""
        from deepspeed_tpu.serving.request import (RequestShedError,
                                                   SamplingParams,
                                                   UnknownAdapterError)
        with self._lock:
            req = ServeRequest(
                request_id=self._next_id,
                prompt_ids=prompt_ids,
                sampling=sampling or SamplingParams(),
                priority=priority, timeout_s=timeout_s,
                slo_class=slo_class, adapter_id=adapter_id)
            # consume the id for REJECTED requests too: a reject's
            # flight-recorder event must never share its req-<id> corr
            # with a later accepted request's timeline
            self._next_id += 1
            if adapter_id is not None:
                if (self.adapter_registry is None
                        or adapter_id not in self.adapter_registry):
                    req.state = RequestState.REJECTED
                    req.reject_reason = (
                        f"unknown adapter {adapter_id!r}"
                        if self.adapter_registry is not None else
                        f"adapter {adapter_id!r} requested but "
                        "serving.adapters is not enabled")
                    self.metrics.counters["adapter_unknown"] += 1
                    self.flightrec.record(
                        "req/reject", corr=f"req-{req.request_id}",
                        reason="adapter_unknown", adapter=adapter_id)
                    req.done.set()
                    raise UnknownAdapterError(req.reject_reason)
                if slo_class == "default":
                    # per-tenant QoS (ISSUE 9 ladder): the tenant's
                    # mapped class drives shedding, admission order,
                    # chunk service, and preemption below
                    mapped = self.adapter_store.slo_class_for(adapter_id)
                    if mapped:
                        slo_class = mapped
                        req.slo_class = mapped
            total = req.prompt_len + req.sampling.max_new_tokens
            if total > self.max_model_len \
                    or not self.block_mgr.fits_ever(total):
                req.state = RequestState.REJECTED
                req.reject_reason = (
                    f"prompt+max_new_tokens={total} exceeds serving "
                    f"capacity {self.max_model_len}")
                self.metrics.counters["rejected_too_long"] += 1
                self.flightrec.record("req/reject",
                                      corr=f"req-{req.request_id}",
                                      reason="too_long", tokens=total)
                req.done.set()
                raise RequestTooLongError(req.reject_reason)
            # SLO admission control (ISSUE 9): under saturation (burn
            # rates over threshold / queue pressure), classes below the
            # shed cutoff 429 here — BEFORE the queue-full check, so
            # low-class traffic can't fill the queue against the
            # classes the system is still meeting targets for
            cut = self.slo.shed_cutoff(len(self._queue),
                                       self.cfg.max_queued)
            if cut is not None and \
                    self.slo.class_priority(slo_class) < cut["priority"]:
                req.state = RequestState.REJECTED
                req.reject_reason = (
                    f"shed class {self.slo.resolve_class(slo_class)!r} "
                    f"under overload ({cut['reason']}); retry after "
                    f"{self.slo.retry_after_s:g}s")
                self.metrics.counters["rejected_shed"] += 1
                self.flightrec.record(
                    "req/reject", corr=f"req-{req.request_id}",
                    reason="shed",
                    slo_class=self.slo.resolve_class(slo_class))
                req.done.set()
                raise RequestShedError(req.reject_reason,
                                       self.slo.retry_after_s)
            if len(self._queue) >= self.cfg.max_queued:
                req.state = RequestState.REJECTED
                req.reject_reason = (
                    f"queue full ({self.cfg.max_queued} waiting)")
                self.metrics.counters["rejected_queue_full"] += 1
                self.flightrec.record("req/reject",
                                      corr=f"req-{req.request_id}",
                                      reason="queue_full")
                req.done.set()
                raise QueueFullError(req.reject_reason)
            self.metrics.counters["received"] += 1
            self._queue.append(req)
            self.flightrec.record("req/queue", corr=f"req-{req.request_id}",
                                  prompt_tokens=req.prompt_len,
                                  max_new=req.sampling.max_new_tokens,
                                  priority=priority, slo_class=slo_class,
                                  adapter=adapter_id)
            return req

    # ------------------------------------------------------------ state
    def active_requests(self) -> List[ServeRequest]:
        with self._lock:
            return [r for r in self._slots if r is not None]

    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def has_work(self) -> bool:
        with self._lock:
            return bool(self._queue) or any(
                r is not None for r in self._slots)

    def has_work_unlocked(self) -> bool:
        """Lock-free (racy) variant for the watchdog: a wedged step()
        holds the scheduler lock for its whole duration — exactly the
        condition the watchdog must be able to observe without joining
        the deadlock.  GIL-atomic list reads are plenty for a stall
        heuristic."""
        return bool(self._queue) or any(
            r is not None for r in self._slots)

    def outstanding_tokens_unlocked(self) -> int:
        """Lock-free outstanding-work estimate for the fleet router's
        least-loaded policy (ISSUE 11): prefill tokens still owed plus
        decode tokens still to emit, over queued AND active requests.
        Same GIL-atomic-snapshot reasoning as ``has_work_unlocked`` — a
        dispatch decision must not queue behind a long step, and an
        estimate a few tokens stale routes just as well."""
        total = 0
        for r in list(self._queue):
            total += r.prompt_len + max(r.remaining_new_tokens, 0)
        for r in list(self._slots):
            if r is None:
                continue
            total += max(r.remaining_new_tokens, 0)
            inputs = r.prefill_inputs
            if inputs is not None:
                total += max(int(inputs.size) - r.prefill_pos, 0)
        return total

    def extract_for_resubmit(self, include_active: bool = True
                             ) -> List[ServeRequest]:
        """Fleet drain support (ISSUE 11): remove every queued request
        and — with ``include_active`` — evict every active row through
        the standard eviction path (blocks released into the prefix
        cache, committed generated tail preserved on the request), then
        hand them ALL back without completing them.  The caller (the
        fleet Router) resubmits each as a fresh request — prompt plus
        the generated-so-far tail — on a healthy replica; recompute-on-
        resume semantics make the continued stream token-identical to
        the uninterrupted one.  ``done`` is never set here: the original
        request objects are abandoned carriers, not completions."""
        with self._lock:
            extracted = list(self._queue)
            self._queue.clear()
            if include_active:
                for req in list(self._slots):
                    if req is None:
                        continue
                    # the standard eviction frees blocks (publishing
                    # committed full blocks to the cache) and requeues —
                    # reclaim it from the queue it just joined
                    self._evict(req)
                    self._queue.remove(req)
                    extracted.append(req)
            return extracted

    @property
    def step_count(self) -> int:
        return self._step_count

    def metrics_snapshot(self) -> Dict[str, float]:
        """Locked snapshot for readers outside the scheduler loop (the
        /metrics endpoint) — the loop thread mutates the counter dict
        and histograms mid-step."""
        with self._lock:
            return self.metrics.snapshot()

    def render_metrics(self, extra_labels=None) -> str:
        """Prometheus text for the /metrics endpoint (locked, same
        exposition function as the training-side metrics server).  The
        fleet front-end passes ``extra_labels={"replica": "<id>"}`` so
        N replicas merge into one labeled exposition (ISSUE 11).  On a
        multi-tenant server (serving.adapters) or once install_params
        has ever hot-swapped the base weights, every series additionally
        carries ``weights_version`` (ISSUE 20) so the live roll is
        attributable in dashboards."""
        labels = dict(extra_labels or {})
        if self.adapter_store is not None or self._weights_swapped:
            labels.setdefault("weights_version", self.weights_version)
        with self._lock:
            return self.metrics.render_prometheus(extra_labels=labels)

    # ------------------------------------------------- debug introspection
    # Both views below are deliberately LOCK-FREE (ISSUE 7): they exist
    # to answer "what is the scheduler doing" while a wedged step()
    # holds the scheduler lock — the same reasoning as the watchdog's
    # has_work_unlocked.  Reads are GIL-atomic snapshots of plain
    # attributes; a view racing a live step may be internally slightly
    # inconsistent (a request mid-retire, say), which is acceptable for
    # forensics and unacceptable to deadlock on.

    @staticmethod
    def _debug_request(req: ServeRequest, now: float) -> Dict:
        return {
            "request_id": req.request_id,
            "state": req.state.value,
            "slot": req.slot,
            "priority": req.priority,
            "slo_class": req.slo_class,
            "adapter_id": req.adapter_id,
            "prompt_tokens": req.prompt_len,
            "generated": req.num_generated,
            "max_new_tokens": req.sampling.max_new_tokens,
            "cached_tokens": req.num_cached_tokens,
            "preemptions": req.num_preemptions,
            "age_s": round(now - req.arrival_time, 3),
            "ttft_ms": (round(req.ttft_s * 1e3, 3)
                        if req.ttft_s is not None else None),
            "spec_k": req.spec_k,
            "spec_disabled": req.spec_disabled,
            "prefill_cursor": req.prefill_pos,
            "prefill_total": (int(req.prefill_inputs.size)
                              if req.prefill_inputs is not None else None),
        }

    def debug_requests(self) -> Dict:
        """The ``/debug/requests`` body: every queued + active request's
        live state (lock-free snapshot)."""
        now = time.monotonic()
        active = [self._debug_request(r, now)
                  for r in list(self._slots) if r is not None]
        queued = [self._debug_request(r, now) for r in list(self._queue)]
        return {"step_count": self._step_count,
                "active": active, "queued": queued}

    def debug_scheduler(self) -> Dict:
        """The ``/debug/scheduler`` body: scheduler + block-pool +
        prefix-cache + spec + SLO state (lock-free snapshot)."""
        bm = self.block_mgr
        slots = [r.request_id if r is not None else None
                 for r in list(self._slots)]
        out = {
            "step_count": self._step_count,
            "queue_depth": len(self._queue),
            "max_num_seqs": self.cfg.max_num_seqs,
            "max_model_len": self.max_model_len,
            "slots": slots,
            "block_pool": {
                "num_blocks": self.cfg.num_blocks,
                "block_size": bm.block_size,
                "free": bm.num_free_blocks,
                "cached": bm.num_cached_blocks,
                "allocated": bm.num_allocated_blocks,
                "utilization": round(bm.utilization(), 4),
                "cache_evictions": bm.cache_evictions,
            },
            "prefix_cache": {
                "enabled": self._prefix_cache_on,
                "min_prefix_blocks": self._prefix_min_blocks,
                "hits": int(self.metrics.counters["prefix_cache_hit"]),
                "misses": int(self.metrics.counters["prefix_cache_miss"]),
                "cow_forks": int(
                    self.metrics.counters["prefix_cache_cow_forks"]),
            },
            "spec": {
                "proposer": (type(self.proposer).__name__
                             if self.proposer is not None else None),
                "verify_steps": int(
                    self.metrics.counters["spec_verify_steps"]),
                "drafted": int(
                    self.metrics.counters["spec_drafted_tokens"]),
                "accepted": int(
                    self.metrics.counters["spec_accepted_tokens"]),
            },
            "slo": {
                "enabled": self.slo.enabled,
                "classes": sorted(self.slo.classes),
                "priorities": dict(self.slo.priorities),
                "shed_enabled": self.slo.shed_enabled,
                "burn_rates": self.slo.burn_rates(),
                "violations": int(self.metrics.counters["slo_violations"]),
                "shed": int(self.metrics.counters["rejected_shed"]),
            },
            "chunked_prefill": {
                "enabled": self._chunked_on,
                "chunk_tokens": self._chunk_tokens,
                "chunks_deferred": int(
                    self.metrics.counters["chunks_deferred"]),
                "prefilling": [
                    {"request_id": r.request_id,
                     "cursor": r.prefill_pos,
                     "total": (int(r.prefill_inputs.size)
                               if r.prefill_inputs is not None else None)}
                    for r in list(self._slots) if r is not None
                    and r.state == RequestState.PREFILLING],
            },
            "kv_tiering": ({"enabled": False}
                           if self._tier_store is None else dict(
                               {"enabled": True,
                                "park_on_preempt": self._park_on_preempt,
                                "demoted_not_evicted": bm.cache_demotions,
                                "pending_swapins": len(self._swap_pending)},
                               **self._tier_store.summary())),
            "adapters": ({"enabled": False}
                         if self.adapter_store is None else dict(
                             {"enabled": True,
                              "registered": sorted(
                                  self.adapter_registry.ids()),
                              "pending_swapins": len(self._adapter_pending),
                              "weights_version": self.weights_version},
                             **self.adapter_store.summary())),
        }
        return out

    # -------------------------------------------------------- lifecycle
    def _committed_tokens(self, req: ServeRequest) -> Optional[int]:
        """KV-materialized token count for cache publication: a
        PREFILLING request has KV only up to its committed chunk cursor
        (ISSUE 9); everything else uses register_committed's default
        (all but the newest sampled token)."""
        if req.state == RequestState.PREFILLING:
            return req.prefill_pos
        return None

    def _retire(self, req: ServeRequest, state: RequestState,
                reason: Optional[str] = None):
        if self.proposer is not None:
            self.proposer.release(req.request_id)
        # release INTO the cache (ISSUE 6): hash any last full blocks,
        # then free — hashed blocks park on the LRU for the next request
        self.block_mgr.register_committed(
            req.request_id, req.all_token_ids,
            materialized=self._committed_tokens(req),
            salt=req.adapter_id)
        self.block_mgr.free(req.request_id)
        if req.adapter_pinned:
            self.adapter_store.release(req.adapter_id)
            req.adapter_pinned = False
        req.prefill_inputs = None
        req.prefill_pos = 0
        if req.slot >= 0:
            self._slots[req.slot] = None
            req.slot = -1
        req.state = state
        if reason is not None:
            req.reject_reason = reason
        if state == RequestState.FINISHED:
            req.t_finish = time.monotonic()
            self.metrics.observe_finished(req)
            if self.adapter_store is not None:
                # per-tenant label (ISSUE 20): one series per adapter
                self.metrics.registry.inc("serving/tenant_completed",
                                          adapter=req.adapter_id or "base")
            self._finished_this_step.append(req)
            # SLO burn accounting (ISSUE 7): score the finished request
            # against its class targets; TPOT = mean inter-token gap
            times = req.token_times
            tpot = ((times[-1] - times[0]) / (len(times) - 1)
                    if len(times) > 1 else None)
            viol = self.slo.observe(req.slo_class, req.ttft_s, tpot)
            if viol:
                self.metrics.counters["slo_violations"] += 1
                self.flightrec.record(
                    "req/slo_violation", corr=f"req-{req.request_id}",
                    slo_class=self.slo.resolve_class(req.slo_class),
                    **{k: True for k in viol})
        self.flightrec.record(
            "req/retire", corr=f"req-{req.request_id}",
            state=state.value, generated=req.num_generated,
            ttft_ms=(round(req.ttft_s * 1e3, 3)
                     if req.ttft_s is not None else None),
            reason=reason, adapter=req.adapter_id,
            version=self.weights_version)
        req.done.set()

    def _evict(self, victim: ServeRequest):
        """Preempt: free blocks+slot, requeue for recompute-on-resume.
        With the prefix cache on, the victim's full blocks are hashed
        first — resume re-matches them and re-prefills (close to)
        nothing instead of the whole prompt+generated tail.  A victim
        caught MID-PREFILL (PREFILLING, ISSUE 9) publishes only up to
        its committed chunk cursor — re-admission resumes from the last
        committed chunk, never from half-written KV."""
        if self.proposer is not None:
            self.proposer.release(victim.request_id)
        self.block_mgr.register_committed(
            victim.request_id, victim.all_token_ids,
            materialized=self._committed_tokens(victim),
            salt=victim.adapter_id)
        victim_table = list(self.block_mgr.block_table(victim.request_id))
        self.block_mgr.free(victim.request_id)
        if self._tier_store is not None and self._park_on_preempt:
            # park the victim's whole committed KV on NVMe NOW (ISSUE
            # 16): preemption means pool pressure, so freeing the HBM
            # beats LRU retention — and resume becomes a swap-in, not a
            # re-prefill.  Only exclusively-owned hashed blocks move;
            # shared ones stay hot for their other owners.
            parked = self.block_mgr.park_blocks(victim_table)
            if parked:
                self.flightrec.record("kv/park",
                                      corr=f"req-{victim.request_id}",
                                      blocks=parked)
        victim.prefill_inputs = None
        victim.prefill_pos = 0
        if victim.slot >= 0:
            self._slots[victim.slot] = None
            victim.slot = -1
        if victim.adapter_pinned:
            # unpin: a preempted tenant's adapter becomes an ordinary
            # LRU citizen — it may demote to host/NVMe before resume,
            # and re-admission pays a swap-in, not a failure
            self.adapter_store.release(victim.adapter_id)
            victim.adapter_pinned = False
        victim.state = RequestState.EVICTED
        victim.num_preemptions += 1
        victim.queued_at = time.monotonic()    # timeout clock restarts
        self.metrics.counters["preemptions"] += 1
        self.flightrec.record("req/preempt",
                              corr=f"req-{victim.request_id}",
                              generated=victim.num_generated,
                              priority=victim.priority)
        self._queue.append(victim)
        logger.info(f"serving: preempted request {victim.request_id} "
                    f"(priority {victim.priority}, "
                    f"{victim.num_generated} tokens generated)")

    def _expire_queued(self):
        now = time.monotonic()
        for req in list(self._queue):
            if req.timeout_s > 0 and now - req.queued_at > req.timeout_s:
                self._queue.remove(req)
                self.metrics.counters["rejected_timeout"] += 1
                req.state = RequestState.REJECTED
                req.reject_reason = f"timed out after {req.timeout_s}s queued"
                # terminal flight event: without it a timed-out request's
                # timeline ends at req/queue and reads as still in flight
                self.flightrec.record("req/reject",
                                      corr=f"req-{req.request_id}",
                                      reason="timeout",
                                      queued_s=round(now - req.queued_at, 3))
                req.done.set()

    # -------------------------------------------------------- admission
    def _qos_key(self, req: ServeRequest):
        """Scheduling order (ISSUE 9): SLO class priority first, then
        per-request priority, then eviction count (aging — a request
        preempted N times stops being the perpetual victim among its
        peers and re-admits ahead of them), then arrival (oldest wins).
        ``max`` over this key picks the front of the admission line and
        the next chunk to service; ``min`` picks the preemption victim —
        so the lowest class yields pool and compute first.  Without the
        aging term, equal-priority traffic under recurring pool pressure
        could re-elect the same PREFILLING row every cycle and (with the
        prefix cache off, where committed chunks don't persist) restart
        its prefill from zero forever."""
        return (self.slo.class_priority(req.slo_class), req.priority,
                req.num_preemptions, -req.arrival_time)

    def _prefill_allowance(self) -> int:
        """Per-iteration prefill token allowance under chunked prefill:
        at most ``chunk_tokens``, shrunk when active decode rows claim
        their share of ``max_num_batched_tokens`` (one budget, shared),
        floored at one SUFFIX_BUCKET so prefill always progresses — a
        saturated decode batch slows chunking down, never starves it."""
        decode_rows = sum(1 for r in self._slots if r is not None
                          and r.state == RequestState.DECODE)
        allow = min(self._chunk_tokens,
                    self.cfg.max_num_batched_tokens - decode_rows)
        return max(allow, self.SUFFIX_BUCKET)

    def _admit(self):
        """Admit queued prefills (highest SLO class, then priority, then
        oldest, first) into free slots, bounded by the step token budget
        and the pool.

        With the prefix cache on (ISSUE 6), each prompt is first matched
        block-by-block against the cache: matched blocks attach to the
        request's table with a ref bump and prefill starts at the first
        uncached token — a fully cached prompt re-scores only its last
        token, into a copy-on-write fork of the final shared block.  A
        failed attach (pool pressure mid-admission, or an injected
        ``kv.cache`` fault) degrades to a plain full prefill, never to a
        corrupted table.

        With chunked prefill on (ISSUE 9) the token budget is a REAL
        per-iteration cap: an admission whose uncached prefill fits the
        remaining chunk allowance still runs the one-shot prefill
        program here; anything larger enters PREFILLING with a progress
        cursor and is serviced chunk-by-chunk by ``_window_step`` —
        the old first-admission escape (one 32k prompt monopolizing an
        iteration, spiking every active stream's TPOT) is gone."""
        budget = self.cfg.max_num_batched_tokens
        chunked = self._chunked_on
        allow = self._prefill_allowance() if chunked else budget
        bm = self.block_mgr
        spent = 0
        # tiered KV (ISSUE 16): swap-ins scheduled on an earlier step
        # materialize first — their hashes re-enter the HBM cache and
        # the owning requests re-enter the admission line below
        self._materialize_swapins()
        self._materialize_adapter_swapins()
        while self._queue:
            free_slots = [i for i, r in enumerate(self._slots) if r is None]
            if not free_slots:
                break
            # a request waiting on an in-flight swap-in (KV tier or
            # adapter) sits out this round; others admit
            waiting = self._swap_pending or self._adapter_pending
            cands = ([r for r in self._queue
                      if r.request_id not in self._swap_pending
                      and r.request_id not in self._adapter_pending]
                     if waiting else self._queue)
            if not cands:
                break
            req = max(cands, key=self._qos_key)
            # multi-tenant LoRA (ISSUE 20): an admission whose adapter is
            # cold schedules the swap-in and sits out this round — the
            # swap overlaps the running decode, exactly like a cold-tier
            # prefix hit.  A swap that cannot even start (no tier holds
            # the payload) degrades per serving.adapters.fallback_to_base
            # or rejects typed.
            if (self.adapter_store is not None
                    and req.adapter_id is not None
                    and not self.adapter_store.resident(req.adapter_id)):
                if self._schedule_adapter_swapin(req):
                    continue
                if not self._adapter_failure(req):
                    continue
            resumed = req.state == RequestState.EVICTED
            tokens = req.all_token_ids
            # resume re-prefills everything but the last generated token —
            # decode recomputes that one's KV as it proceeds.  A request
            # evicted MID-PREFILL has generated nothing: its whole prompt
            # is the input and the first token is still owed (ISSUE 9)
            inputs = tokens[:-1] if resumed and req.num_generated \
                else tokens
            n_in = int(inputs.size)
            matched, start = ([], 0)
            if self._prefix_cache_on:
                matched, start = self._match_prefix(req, inputs, resumed)
                # tiered KV (ISSUE 16): a prompt whose prefix extends
                # into a cold tier schedules the async swap-in and sits
                # out this round — next step the promoted blocks are
                # ordinary HBM hits and the request pays a swap-in
                # instead of a re-prefill
                if self._tier_store is not None:
                    entries = bm.match_prefix_tiered(
                        inputs, salt=req.adapter_id)
                    if (len(entries) > len(matched)
                            and len(entries) >= self._prefix_min_blocks
                            and self._schedule_swapins(req, entries)):
                        continue
            # the budget meters PREFILL COMPUTE: cached tokens are free
            need = n_in - start
            if not chunked and spent and spent + need > budget:
                break
            # chunked: a prefill the remaining allowance can't absorb
            # defers into PREFILLING — it is still admitted (slot +
            # blocks) so chunk service can start next phase/iteration
            defer = chunked and need > allow - spent
            # blocks covering positions [0, n_in] — prefill fill plus the
            # first decode write — so admission never instantly preempts
            total = bm.blocks_for_tokens(n_in + 1)
            n_full = n_in // bm.block_size
            fork_pair = None
            c = self.metrics.counters
            if matched:
                # prefill writing INTO the matched region (the fully
                # cached prompt's last token) forks that block COW
                fork = start < len(matched) * bm.block_size
                n_fresh = total - len(matched) + (1 if fork else 0)
                got = bm.acquire_prefix(req.request_id, matched,
                                        n_fresh, fork)
                if got is None:
                    # degrade: full prefill — the whole prompt is now
                    # prefill compute, so the budget check re-runs
                    matched, start = ([], 0)
                    need = n_in
                    if not chunked and spent and spent + n_in > budget:
                        break
                    defer = chunked and need > allow - spent
                else:
                    fork_pair = got[1]
            if not matched:
                if not bm.can_allocate(total):
                    break
                # allocate BEFORE dequeueing: a denied allocation
                # (injected fault or free-list race) must leave the
                # request queued, not admit it blockless.  The failure
                # is an OOM-shaped event: snapshot the byte ledger
                # (ISSUE 14 forensics) so the post-mortem answers
                # "what held the pool when admission starved"
                if bm.allocate(req.request_id, total) is None:
                    self._record_alloc_failure(
                        "kv.alloc", request_id=req.request_id,
                        needed_blocks=total,
                        free_blocks=bm.num_free_blocks,
                        cached_blocks=bm.num_cached_blocks)
                    break
            self._queue.remove(req)
            if self._prefix_cache_on:
                # hits count at ATTACH on the admission that sticks, not
                # at lookup: a discarded match (below min_prefix_blocks,
                # attach denied) served nothing and must not inflate the
                # hit-rate gauge, and a request left queued by pool
                # pressure must not re-count its misses every retry
                c["prefix_cache_hit"] += len(matched)
                c["prefix_cache_miss"] += n_full - len(matched)
            req.state = RequestState.PREFILL
            req.slot = free_slots[0]
            self._slots[req.slot] = req
            req.num_cached_tokens = start
            if self.adapter_store is not None \
                    and req.adapter_id is not None:
                # pin the adapter for the request's whole residency —
                # refcount > 0 keeps the LRU from demoting it mid-decode
                self.adapter_store.acquire(req.adapter_id)
                req.adapter_pinned = True
                self.flightrec.record(
                    "req/adapter_attach", corr=f"req-{req.request_id}",
                    adapter=req.adapter_id,
                    adapter_slot=self.adapter_store.slot_of(req.adapter_id))
            self.flightrec.record(
                "req/resume" if resumed else "req/admit",
                corr=f"req-{req.request_id}", slot=req.slot,
                step=self._step_count, cached_tokens=start,
                prompt_tokens=n_in, deferred=bool(defer and need > 0),
                adapter=req.adapter_id, version=self.weights_version)
            if matched:
                self.flightrec.record(
                    "req/prefix_hit", corr=f"req-{req.request_id}",
                    blocks=len(matched), cached_tokens=start,
                    cow_fork=fork_pair is not None)
            self.metrics.observe_queue_wait(
                time.monotonic() - req.queued_at)
            if resumed:
                # goodput accounting: the generated tail re-prefilled
                # here is work the pool preemption threw away — a
                # cache re-hit of the request's own blocks shrinks it
                self.metrics.counters["recomputed_tokens"] += max(
                    0, n_in - max(start, req.prompt_len))
            if fork_pair is not None:
                self._cow_copy(fork_pair)
                self.metrics.counters["prefix_cache_cow_forks"] += 1
            if start >= n_in:
                # resumed request fully served from cache: nothing to
                # prefill, the generated tail is already sampled — straight
                # to decode (recomputed_tokens rides at 0)
                req.state = RequestState.DECODE
            elif defer:
                req.state = RequestState.PREFILLING
                req.prefill_inputs = inputs
                req.prefill_pos = start
            else:
                spent += need
                self._run_prefill(req, inputs, resumed, start)
            if resumed:
                self.metrics.counters["resumed"] += 1
        self._prefill_spent += spent

    def _match_prefix(self, req: ServeRequest, inputs: np.ndarray,
                      resumed: bool):
        """Cache lookup for one admission: returns (matched blocks,
        prefill-start token).  Fresh requests cap the start at the last
        prompt token — its logits seed sampling, so it must be re-scored
        even when its block is cached (the COW-fork case); resumed
        requests may skip prefill entirely."""
        from deepspeed_tpu.telemetry import get_tracer
        bm = self.block_mgr
        n_in = int(inputs.size)
        with get_tracer().span("serve/prefix_match", cat="serving",
                               corr=f"req-{req.request_id}",
                               args={"request_id": req.request_id,
                                     "prompt_tokens": n_in,
                                     "resumed": bool(resumed)}):
            # salt = adapter_id (ISSUE 20): one tenant's cached blocks
            # can never attach to another tenant's prompt
            blocks = bm.match_prefix(inputs, salt=req.adapter_id)
        # hit/miss accounting happens in _admit once the admission
        # sticks — lookups that don't end in an attach count as misses
        if len(blocks) < self._prefix_min_blocks:
            return [], 0
        start = len(blocks) * bm.block_size
        if not resumed and start >= n_in:
            start = n_in - 1
        return blocks, start

    def _run_prefill(self, req: ServeRequest, inputs: np.ndarray,
                     resumed: bool, start: int = 0):
        from deepspeed_tpu.telemetry import get_tracer
        with get_tracer().span("serve/prefill", cat="serving",
                               corr=f"req-{req.request_id}",
                               args={"request_id": req.request_id,
                                     "tokens": int(inputs.size) - start,
                                     "cached": int(start),
                                     "resumed": bool(resumed)}):
            self._run_prefill_traced(req, inputs, resumed, start)

    def _run_prefill_traced(self, req: ServeRequest, inputs: np.ndarray,
                            resumed: bool, start: int = 0):
        bm = self.block_mgr
        if start > 0:
            # cached-prefix admission: only the uncached suffix runs
            last_logits = self._suffix_prefill(req, inputs, start)
        else:
            sp = min(max(_round_up(inputs.size, self.PROMPT_BUCKET),
                         self.PROMPT_BUCKET), self.s_pad)
            padded = np.zeros((1, sp), np.int32)
            padded[0, :inputs.size] = inputs
            # flat pool destination per prompt position; pads write into
            # the trash block (positions 0..block_size-1), never a live
            # block
            dest = np.arange(sp) % bm.block_size
            pos = np.arange(inputs.size)
            dest[:inputs.size] = [bm.position_index(req.request_id, int(p))
                                  for p in pos]
            last_logits, self.pool = self._prefill_fn(sp)(
                self.params, self.pool, jnp.asarray(padded),
                jnp.asarray([inputs.size], np.int32), jnp.asarray(dest),
                *self._lora_arg([self._adapter_slot(req)]))
        self.metrics.counters["prefill_tokens"] += int(inputs.size) - start
        if start == 0:
            # the cached-suffix path records per chunk; this is the
            # one-shot full-prompt program
            self.flightrec.record("req/prefill_chunk",
                                  corr=f"req-{req.request_id}",
                                  tokens=int(inputs.size), offset=0,
                                  cursor=int(inputs.size))
        self._finish_prefill(req, inputs, last_logits)

    def _finish_prefill(self, req: ServeRequest, inputs: np.ndarray,
                        last_logits, tok: Optional[int] = None):
        """Shared prefill epilogue (one-shot, cached-suffix, and
        batched-window chunked completion): publish the prefilled blocks
        to the prefix cache, flip to DECODE, and emit the first token —
        sampled here from the last real position's logits, or passed in
        as ``tok`` when the window program's bonus column already drew
        it (same rng-position key family, so both forms are
        token-identical).  A request that already carries a generated
        tail (resumed mid-decode) emits nothing — its next token is on
        record and decode continues it."""
        # the prompt's full blocks are cache content from here on —
        # registering BEFORE the first sample lets the next admission in
        # this very step hit them (materialized = exactly the prefilled
        # prefix; the token sampled below has no KV yet)
        self.block_mgr.register_committed(req.request_id, inputs,
                                          materialized=int(inputs.size),
                                          salt=req.adapter_id)
        req.state = RequestState.DECODE
        req.prefill_inputs = None
        req.prefill_pos = 0
        if req.num_generated:
            return                  # generated tail already sampled
        if tok is None:
            s = req.sampling
            tok = int(np.asarray(self._sample1_fn(bool(s.do_sample))(
                last_logits,
                # 31-bit mask: the decode path packs seeds as int32 —
                # both paths must derive the SAME key for one request's
                # stream
                jnp.asarray([s.seed & 0x7FFFFFFF], np.uint32),
                jnp.asarray([req.prompt_len], np.int32),
                jnp.asarray([s.temperature], np.float32),
                jnp.asarray([s.top_k], np.int32),
                jnp.asarray([s.top_p], np.float32),
                jnp.asarray([s.do_sample])))[0])
        req.record_token(tok)
        self.metrics.counters["generated_tokens"] += 1
        if req.finished_by(tok):
            self._retire(req, RequestState.FINISHED)

    def _prefill_window(self, req: ServeRequest, inputs: np.ndarray,
                        pos: int, take: int, pos_idx: np.ndarray):
        """ONE verify-window prefill program execution: score
        ``inputs[pos:pos+take]`` (take <= SUFFIX_CHUNK) at traced offset
        ``pos`` against the request's pool-gathered cache and scatter
        the window's KV back; returns the window's last real position's
        logits ``[1, V]``.  This is the shared chunk program — the
        prefix-cache suffix path and the chunked-prefill cursor path
        reuse the same ``_suffix_prefill_fns`` compiled set."""
        bm = self.block_mgr
        W = min(_round_up(take, self.SUFFIX_BUCKET), self.SUFFIX_CHUNK)
        toks = np.zeros((1, W), np.int32)
        toks[0, :take] = inputs[pos:pos + take]
        # pad window positions keep the trash pattern
        dests = (np.arange(W) % bm.block_size).astype(np.int32)
        for j in range(take):
            dests[j] = bm.position_index(req.request_id, pos + j)
        logits, self.pool = self._suffix_prefill_fn(W)(
            self.params, self.pool, jnp.asarray(toks),
            jnp.asarray([pos], np.int32), jnp.asarray(dests),
            jnp.asarray(pos_idx),
            *self._lora_arg([self._adapter_slot(req)]))
        return logits[0, take - 1][None]

    def _suffix_prefill(self, req: ServeRequest, inputs: np.ndarray,
                        start: int):
        """Prefill tokens ``start..n_in-1`` against the cached prefix,
        in SUFFIX_CHUNK-sized verify windows (see _suffix_prefill_fn);
        returns the last real position's logits ``[1, V]`` for first-
        token sampling."""
        n_in = int(inputs.size)
        # dense gather indices over the request's (fully allocated,
        # possibly shared) table — fixed across chunks
        pos_idx = self._pos_idx_row(req.request_id)[None]
        pos, last = start, None
        while pos < n_in:
            take = min(self.SUFFIX_CHUNK, n_in - pos)
            last = self._prefill_window(req, inputs, pos, take, pos_idx)
            self.flightrec.record("req/prefill_chunk",
                                  corr=f"req-{req.request_id}",
                                  tokens=take, offset=pos,
                                  cursor=pos + take)
            pos += take
        return last

    # --------------------------------------------- chunked prefill phase
    def _chunks_pending(self) -> bool:
        """Any PREFILLING row still owed chunk service (the spec-decode
        throttle and the deferral telemetry both key on this)."""
        return any(r is not None and r.state == RequestState.PREFILLING
                   for r in self._slots)

    def _chunk_takes(self):
        """Plan this iteration's chunked-prefill service (ISSUE 9
        semantics on the ISSUE 12 batched-window surface): split the
        per-iteration prefill allowance across PREFILLING rows —
        highest SLO class / priority first — as request_id -> total
        tokens this iteration.  Rows the allowance can't reach (not
        even one bucket or the tiny remainder) are deferred (counted)
        and keep their cursor.  The ``serve.chunk`` fault site fires
        here, BEFORE any KV write: a ``raise`` propagates out of step()
        (cursor and block table untouched), a ``deny`` defers the row
        this iteration."""
        if not self._chunked_on:
            return {}
        rows = [r for r in self._slots if r is not None
                and r.state == RequestState.PREFILLING]
        if not rows:
            return {}
        allow = self._prefill_allowance()
        rows.sort(key=self._qos_key, reverse=True)
        takes = {}
        for req in rows:
            left = allow - self._prefill_spent - sum(takes.values())
            remaining = int(req.prefill_inputs.size) - req.prefill_pos
            if left < min(self.SUFFIX_BUCKET, remaining):
                self.metrics.counters["chunks_deferred"] += 1
                continue
            if self.injector.deny("serve.chunk"):
                self.metrics.counters["chunks_deferred"] += 1
                continue
            takes[req.request_id] = min(left, remaining)
        return takes

    # ------------------------------------------------- decode iteration
    def _grow_tables(self):
        """Allocate-on-decode: each active row needs a block for the
        position it writes this step; exhaustion preempts the lowest-
        priority active request (possibly the grower itself)."""
        for req in list(self._slots):
            if req is None or req.state != RequestState.DECODE:
                continue
            write_pos = int(req.all_token_ids.size) - 1
            bm = self.block_mgr
            while write_pos // bm.block_size >= len(
                    bm.block_table(req.request_id)):
                if bm.allocate(req.request_id, 1) is not None:
                    continue
                # PREFILLING rows are preemptible too (ISSUE 9): a
                # lowest-class chunking prompt yields its pool to a
                # higher-class decode before any decode row does
                active = [r for r in self._slots if r is not None
                          and r.state in (RequestState.DECODE,
                                          RequestState.PREFILLING)]
                victim = min(active, key=self._qos_key)
                if victim is req:
                    # the grower is about to evict ITSELF: true pool
                    # exhaustion, not pressure rebalancing.  Snapshot
                    # the ledger BEFORE the eviction returns the
                    # victim's blocks — the forensic record must show
                    # who held the bytes at the moment of failure, not
                    # the post-eviction state
                    self._record_alloc_failure(
                        "kv.alloc", request_id=req.request_id,
                        phase="grow", needed_blocks=1,
                        free_blocks=bm.num_free_blocks)
                self._evict(victim)
                if victim is req:
                    break

    def _prepare_window(self, active, k: int) -> bool:
        """Extend every active row's block table to cover ``k`` upcoming
        writes — all or nothing, never preempting (window sizing falls
        back to k=1, whose growth path may preempt)."""
        bm = self.block_mgr
        plan = []
        total = 0
        for req in active:
            last_pos = int(req.all_token_ids.size) - 1 + (k - 1)
            n = last_pos // bm.block_size + 1 \
                - len(bm.block_table(req.request_id))
            if n > 0:
                plan.append((req, n))
                total += n
        if total > bm.num_reclaimable_blocks:
            return False
        for req, n in plan:
            if bm.allocate(req.request_id, n) is None:
                # denied mid-plan (injected fault): blocks already granted
                # stay on their tables — harmless extra coverage — but the
                # window must shrink to one it can fully back
                return False
        return True

    def _choose_window(self, active) -> int:
        """Fused-step count: the largest power of two that (a) respects
        max_fused_steps, (b) cannot outrun the first possible retirement
        (min remaining tokens — so a finishing row's slot frees exactly
        when it would have), and (c) has pool blocks for every write."""
        rem = min(r.remaining_new_tokens for r in active)
        k = 1
        while k * 2 <= min(rem, self.cfg.max_fused_steps):
            k *= 2
        while k > 1 and not self._prepare_window(active, k):
            k //= 2
        return k

    def _decode(self):
        """All-plain decode iteration (no drafts, no pending chunks):
        the k-step fused decode program (``max_fused_steps``) — the
        batched-window step owns every iteration that has window work."""
        active = [r for r in self._slots if r is not None
                  and r.state == RequestState.DECODE]
        if not active:
            return
        B = self.cfg.max_num_seqs
        bm = self.block_mgr
        k = self._choose_window(active)
        # packed args (see _decode_fn): ints [4+k, B], floats [2, B]
        ints = np.zeros((4 + k, B), np.int32)
        ints[4:] = (np.arange(k) % bm.block_size)[:, None]  # trash pattern
        floats = np.ones((2, B), np.float32)
        do_flags = np.zeros((B,), bool)
        pos_idx = np.zeros((B, self.s_pad), np.int32)
        groups = np.full((B,), -1, np.int32)
        for req in active:
            b = req.slot
            seq = req.all_token_ids
            pos_idx[b] = self._pos_idx_row(req.request_id)
            groups[b] = self._adapter_slot(req)
            s = req.sampling
            ints[0, b], ints[1, b] = seq[-1], seq.size - 1
            ints[2, b], ints[3, b] = s.seed & 0x7FFFFFFF, s.top_k
            for j in range(k):
                ints[4 + j, b] = bm.position_index(
                    req.request_id, seq.size - 1 + j)
            floats[0, b], floats[1, b] = s.temperature, s.top_p
            do_flags[b] = s.do_sample
        any_sampling = bool(do_flags.any())
        t0 = time.perf_counter()
        toks, self.pool = self._decode_fn(any_sampling)(
            self.params, self.pool, ints, floats, do_flags, pos_idx,
            *self._lora_arg(groups))
        toks = np.asarray(toks)                  # [k, B]
        if self._costmodel_on:
            from deepspeed_tpu.telemetry.roofline import observe_achieved
            observe_achieved(self.metrics.registry, f"serve/decode:k{k}",
                             time.perf_counter() - t0)
        self.metrics.counters["decode_steps"] += k
        for req in active:
            for j in range(k):
                tok = int(toks[j, req.slot])
                req.record_token(tok)
                self.metrics.counters["generated_tokens"] += 1
                if req.finished_by(tok):
                    # immediate retirement: blocks recycle mid-batch, the
                    # slot is admittable on the very next iteration.  An
                    # EOS inside a fused window discards the window tail
                    # (k never outruns max_new, only EOS cuts early).
                    self._retire(req, RequestState.FINISHED)
                    break

    # --------------------------------------------- speculative decoding
    #: verify passes with a draft before min_accept_rate can trip
    SPEC_MIN_PASSES = 4
    #: draft-length clamp while prefill chunks are pending (ISSUE 9):
    #: verify windows and chunk windows contend for the same iteration —
    #: a wide speculative window would stretch every chunk's wait just
    #: like an unchunked prefill stretched decode's
    SPEC_THROTTLE_K = 2

    def _spec_budget(self, req: ServeRequest) -> int:
        """Adaptive per-request draft length for this round (0 = don't
        speculate: disabled, or too close to max_new for a draft plus
        the bonus token to fit).  Clamped to SPEC_THROTTLE_K while
        PREFILLING rows await chunk service (spec auto-throttle,
        ISSUE 9)."""
        spec = self.cfg.spec
        if req.spec_disabled or req.remaining_new_tokens <= 1:
            return 0
        if req.spec_k <= 0:
            req.spec_k = spec.max_draft_tokens      # start optimistic
        k = min(req.spec_k, spec.max_draft_tokens,
                req.remaining_new_tokens - 1)
        if k > self.SPEC_THROTTLE_K and self._chunks_pending():
            self.metrics.counters["spec_throttled"] += 1
            k = self.SPEC_THROTTLE_K
        return k

    def _propose_drafts(self, active) -> Dict[int, np.ndarray]:
        from deepspeed_tpu.telemetry import get_tracer
        tracer = get_tracer()
        bm = self.block_mgr
        drafts: Dict[int, np.ndarray] = {}
        for req in active:
            k = self._spec_budget(req)
            if k <= 0:
                continue
            with tracer.span("serve/draft", cat="serving",
                             corr=f"req-{req.request_id}",
                             args={"request_id": req.request_id, "k": k}):
                d = np.asarray(self.proposer.propose(req, k),
                               np.int32).reshape(-1)[:k]
            if d.size == 0:
                continue
            # window writes reach position (seq-1)+len(d): all-or-nothing
            # block growth, never preempting — a denied/exhausted pool
            # just drops the draft and the row decodes plain in-window
            last = int(req.all_token_ids.size) - 1 + int(d.size)
            need = last // bm.block_size + 1 \
                - len(bm.block_table(req.request_id))
            if need > 0 and bm.allocate(req.request_id, need) is None:
                continue
            drafts[req.request_id] = d
        return drafts

    def _window_step(self) -> bool:
        """The unified batched-window iteration (ISSUE 12 tentpole):
        decode rows (with their speculative drafts when a proposer is
        armed) AND every PREFILLING row's chunk share ride ONE
        ``_window_fn`` execution — one pool gather, one per-layer
        weight pass (the fused megakernel when enabled), one windowed
        scatter.  When a chunk share exceeds the window cap the step
        loops chunk-only passes until the iteration's allowance is
        spent (same per-iteration boundedness as the PR 9 phase, fewer
        launches — chunk rows batch together instead of running B=1
        programs).  Returns False when there is no window work at all —
        the all-plain k-step fused decode path then runs instead.

        Fault degradation is unchanged: ``serve.spec`` (raise/deny)
        fires before any KV write and drops every draft (the step
        degrades to plain-decode-in-window); ``serve.chunk`` fires in
        the planning walk before any KV write."""
        from deepspeed_tpu.resilience.faults import FaultInjected
        bm = self.block_mgr
        active = [r for r in self._slots if r is not None
                  and r.state == RequestState.DECODE]
        takes = self._chunk_takes()
        drafts = {}
        if self.proposer is not None and active:
            drafts = self._propose_drafts(active)
        if drafts:
            try:
                denied = self.injector.deny("serve.spec")
            except FaultInjected:
                denied = True
            if denied:
                # degrade to plain decode this step; hand back the
                # window blocks the dropped drafts had reserved
                self.metrics.counters["spec_faults"] += 1
                for rid in drafts:
                    req = self._request_in_slot(rid)
                    if req is not None:
                        bm.truncate(rid, int(req.all_token_ids.size))
                drafts = {}
        if not drafts and not takes:
            return False
        # first pass: decode rows + each chunk row's first window
        self._run_window(active, drafts, takes)
        # chunk-only passes spend the rest of the allowance (decode rows
        # already emitted this iteration)
        while takes:
            takes = {rid: t for rid, t in takes.items() if t > 0
                     and self._request_in_slot(rid) is not None}
            if not takes:
                break
            self._run_window([], {}, takes)
        return True

    def _run_window(self, decode_rows, drafts, takes):
        """Execute ONE batched-window program over the given decode rows
        (+drafts) and chunk rows (``takes`` mutates: each serviced row's
        remaining iteration share decrements).  Host epilogue: the spec
        acceptance walk for decode rows, cursor advance / completion
        sampling for chunk rows."""
        bm = self.block_mgr
        B = self.cfg.max_num_seqs
        chunk_rows = []                 # (req, take-this-pass)
        need = 1 if decode_rows else 0
        for d in drafts.values():
            need = max(need, 1 + int(d.size))
        for rid, left in takes.items():
            req = self._request_in_slot(rid)
            if req is None or left <= 0:
                continue
            take = min(self.SUFFIX_CHUNK, left)
            chunk_rows.append((req, take))
            need = max(need, take)
        if need == 0:
            return
        W = self._window_bucket(need)
        ints = np.zeros((4 + 2 * W, B), np.int32)
        ints[W + 4:] = (np.arange(W) % bm.block_size)[:, None]  # trash
        floats = np.ones((2, B), np.float32)
        do_flags = np.zeros((B,), bool)
        pos_idx = np.zeros((B, self.s_pad), np.int32)
        groups = np.full((B,), -1, np.int32)
        for req in decode_rows:
            b = req.slot
            seq = req.all_token_ids
            d = drafts.get(req.request_id)
            nd = 0 if d is None else int(d.size)
            pos_idx[b] = self._pos_idx_row(req.request_id)
            groups[b] = self._adapter_slot(req)
            s = req.sampling
            ints[0, b] = seq[-1]
            if nd:
                ints[1:1 + nd, b] = d
            ints[W, b] = seq.size - 1
            ints[W + 1, b] = nd
            ints[W + 2, b], ints[W + 3, b] = s.seed & 0x7FFFFFFF, s.top_k
            # real pool destinations for the last token + draft writes;
            # pad window positions keep the trash pattern
            for j in range(nd + 1):
                ints[W + 4 + j, b] = bm.position_index(
                    req.request_id, seq.size - 1 + j)
            floats[0, b], floats[1, b] = s.temperature, s.top_p
            do_flags[b] = s.do_sample
        for req, take in chunk_rows:
            b = req.slot
            inputs = req.prefill_inputs
            pos = req.prefill_pos
            pos_idx[b] = self._pos_idx_row(req.request_id)
            groups[b] = self._adapter_slot(req)
            s = req.sampling
            ints[0:take, b] = inputs[pos:pos + take]
            ints[W, b] = pos
            # draft_len = take-1 puts the bonus column on the chunk's
            # last real position — its emitted token IS the first-token
            # sample when this chunk completes the prefill
            ints[W + 1, b] = take - 1
            ints[W + 2, b], ints[W + 3, b] = s.seed & 0x7FFFFFFF, s.top_k
            for j in range(take):
                ints[W + 4 + j, b] = bm.position_index(
                    req.request_id, pos + j)
            floats[0, b], floats[1, b] = s.temperature, s.top_p
            do_flags[b] = s.do_sample
        from deepspeed_tpu.telemetry import get_tracer
        tracer = get_tracer()
        any_sampling = bool(do_flags.any())
        # the serve/window span carries the PASS's device time — the
        # per-row serve/chunk spans below are host bookkeeping only (a
        # batched program has no per-row execution time to attribute)
        # cost annotation (ISSUE 13): once the family's CostReport is
        # registered (first execution analyzed it), the span carries the
        # program's static cost beside its measured device time
        span_args = {"W": W, "decode_rows": len(decode_rows),
                     "drafted_rows": len(drafts),
                     "chunk_rows": len(chunk_rows)}
        if self._costmodel_on:
            from deepspeed_tpu.telemetry.costmodel import get_report
            rep = get_report(f"serve/window:w{W}")
            if rep is not None:
                span_args.update(cost_flops=rep.flops,
                                 cost_hbm_bytes=rep.hbm_bytes,
                                 cost_pallas_launches=rep.pallas_launches)
        t0 = time.perf_counter()
        with tracer.span("serve/window", cat="serving", args=span_args):
            acc, out, self.pool = self._window_fn(W, any_sampling)(
                self.params, self.pool, ints, floats, do_flags, pos_idx,
                *self._lora_arg(groups))
            acc, out = np.asarray(acc), np.asarray(out)
        if self._costmodel_on:
            from deepspeed_tpu.telemetry.roofline import observe_achieved
            observe_achieved(self.metrics.registry, f"serve/window:w{W}",
                             time.perf_counter() - t0)
        self.metrics.counters["window_steps"] += 1
        if drafts:
            self.metrics.counters["spec_verify_steps"] += 1
        if decode_rows:
            self._apply_spec_result(decode_rows, drafts, acc, out)
        for req, take in chunk_rows:
            takes[req.request_id] -= take
            inputs = req.prefill_inputs
            n_in = int(inputs.size)
            with tracer.span(
                    "serve/chunk", cat="serving",
                    corr=f"req-{req.request_id}",
                    args={"request_id": req.request_id,
                          "offset": int(req.prefill_pos),
                          "tokens": int(take),
                          "remaining": int(n_in - req.prefill_pos - take)}):
                req.prefill_pos += take
                self.flightrec.record(
                    "req/prefill_chunk", corr=f"req-{req.request_id}",
                    tokens=take, offset=req.prefill_pos - take,
                    cursor=req.prefill_pos, total=n_in)
            self._prefill_spent += take
            self.metrics.counters["prefill_tokens"] += take
            self.metrics.counters["window_chunk_tokens"] += take
            # committed chunks become prefix-cache content immediately:
            # a same-prefix admission (or this row's own post-eviction
            # resume) attaches them instead of recomputing
            self.block_mgr.register_committed(
                req.request_id, inputs, materialized=req.prefill_pos,
                salt=req.adapter_id)
            if req.prefill_pos >= n_in:
                # completion: the window's bonus column already drew the
                # first token — ONE epilogue serves every prefill form
                takes.pop(req.request_id, None)
                self._finish_prefill(req, inputs, None,
                                     tok=int(out[req.slot, take - 1]))

    def _pos_idx_row(self, request_id: int) -> np.ndarray:
        """One row of dense-gather indices: the flat pool position of
        every logical position 0..s_pad-1 for this request.  Positions
        past the allocated table ride block 0 (the trash block), like
        padding rows — the length masking never reads them."""
        table = np.zeros((self.blocks_per_table,), np.int64)
        t = self.block_mgr.block_table(request_id)
        table[:len(t)] = t
        return (table[self._pos_blk] * self.block_mgr.block_size
                + self._pos_offs).astype(np.int32)

    def _request_in_slot(self, request_id: int) -> Optional[ServeRequest]:
        for r in self._slots:
            if r is not None and r.request_id == request_id:
                return r
        return None

    def _apply_spec_result(self, active, drafts, acc: np.ndarray,
                           out: np.ndarray):
        """Host-side acceptance walk per row: commit the longest accepted
        draft prefix plus the token the verify logits emit at the stop
        position (rejection resample / bonus), then truncate the block
        table back to the committed length — whole now-unused blocks
        return to the pool."""
        from deepspeed_tpu.telemetry import get_tracer
        tracer = get_tracer()
        bm = self.block_mgr
        c = self.metrics.counters
        for req in active:
            b, rid = req.slot, req.request_id
            d = drafts.get(rid)
            nd = 0 if d is None else int(d.size)
            a = 0
            while a < nd and acc[b, a]:
                a += 1
            emitted = [int(t) for t in d[:a]] if nd else []
            emitted.append(int(out[b, a]))
            with tracer.span("serve/verify", cat="serving",
                             corr=f"req-{rid}",
                             args={"request_id": rid, "drafted": nd,
                                   "accepted": a}):
                for tok in emitted:
                    req.record_token(tok)
                    c["generated_tokens"] += 1
                    if req.finished_by(tok):
                        # EOS inside the accepted prefix discards the
                        # rest of the window for this row only
                        self._retire(req, RequestState.FINISHED)
                        break
            if nd:
                c["spec_drafted_tokens"] += nd
                c["spec_accepted_tokens"] += a
                c["spec_rolled_back_tokens"] += nd - a
                self.metrics.spec_accept_len.observe(a + 1)
                self.flightrec.record("req/spec_accept", corr=f"req-{rid}",
                                      drafted=nd, accepted=a)
                self._spec_adapt(req, nd, a)
            if req.slot >= 0:       # still live: paged-KV rollback
                bm.truncate(rid, int(req.all_token_ids.size))

    def _spec_adapt(self, req: ServeRequest, drafted: int, accepted: int):
        """Per-request adaptive draft length: double on full acceptance,
        halve on full rejection; a rolling acceptance-rate EMA below
        ``serving.spec.min_accept_rate`` (after a few passes) disables
        speculation for the request — mixed workloads stop paying verify
        cost for unspeculatable streams."""
        spec = self.cfg.spec
        req.spec_passes += 1
        rate = accepted / drafted
        req.spec_accept_ema = (rate if req.spec_accept_ema < 0
                               else 0.5 * req.spec_accept_ema + 0.5 * rate)
        if accepted == drafted:
            req.spec_k = min(max(req.spec_k, 1) * 2,
                             spec.max_draft_tokens)
        elif accepted == 0:
            req.spec_k = max(1, req.spec_k // 2)
        if (spec.min_accept_rate > 0
                and req.spec_passes >= self.SPEC_MIN_PASSES
                and req.spec_accept_ema < spec.min_accept_rate):
            req.spec_disabled = True
            self.metrics.counters["spec_auto_disabled"] += 1

    # ------------------------------------------------------------- step
    def step(self) -> List[ServeRequest]:
        """One engine iteration; returns requests finished this step.

        The iteration runs inside a ``serve/step`` span (correlation id
        ``serve-step-N``) with admit/grow/decode child spans; per-request
        prefill spans carry ``req-<id>`` so one request's admission,
        decode windows, and any faults line up in the trace."""
        from deepspeed_tpu.telemetry import get_tracer
        tracer = get_tracer()
        step_id = self._step_count
        t0 = time.perf_counter()
        # fault site OUTSIDE the lock: an injected stall models a wedged
        # engine without also wedging the /metrics + submit paths
        with tracer.span("serve/step", cat="serving",
                         corr=f"serve-step-{step_id}",
                         args={"step": step_id}):
            self.injector.check("serve.step")
            with self._lock:
                self._finished_this_step = []
                self._prefill_spent = 0
                gen0 = self.metrics.counters["generated_tokens"]
                self._expire_queued()
                with tracer.span("serve/admit", cat="serving"):
                    self._admit()
                with tracer.span("serve/grow", cat="serving"):
                    self._grow_tables()
                if self._mem_on:
                    # mid-step occupancy tap: per-step pool occupancy
                    # peaks right after growth — the watermark must see
                    # a request that admits AND retires this iteration
                    self._update_memory_ledger(publish=False)
                active = sum(r is not None and
                             r.state == RequestState.DECODE
                             for r in self._slots)
                with tracer.span("serve/decode", cat="serving",
                                 args={"active": active}):
                    # unified batched-window step (ISSUE 12): decode
                    # rows, spec-verify windows, and prefill chunks ride
                    # ONE compiled family; all-plain iterations keep the
                    # k-step fused decode program
                    if not self._window_step():
                        self._decode()
                if self._prefill_spent:
                    self.metrics.prefill_batch_tokens.observe(
                        self._prefill_spent)
                # per-iteration budget split (ISSUE 9 telemetry): how
                # this step's tokens divided between prefill compute and
                # decode/sampled emissions
                self.metrics.gauges["step_prefill_tokens"] = \
                    self._prefill_spent
                self.metrics.gauges["step_decode_tokens"] = int(
                    self.metrics.counters["generated_tokens"] - gen0)
                if self._prefix_cache_on:
                    # newly filled full blocks become cache entries while
                    # their owners still decode — concurrent same-prefix
                    # admissions share them immediately
                    for r in self._slots:
                        if r is not None and r.state == RequestState.DECODE:
                            self.block_mgr.register_committed(
                                r.request_id, r.all_token_ids,
                                salt=r.adapter_id)
                self._step_count += 1
                if self._debug_invariant:
                    # allocation-accounting invariant (ISSUE 5): spec
                    # rollback shrinks tables mid-flight — catch any
                    # double-free/leak at the step that caused it
                    # (DS_SERVE_DEBUG=1; off by default — the scan is
                    # O(num_blocks) inside the scheduler lock)
                    self.block_mgr.check_invariant()
                    if self.adapter_store is not None:
                        # adapter census (ISSUE 20): every pinned row's
                        # refcount must reconcile with the store's table
                        census: Dict[str, int] = {}
                        for r in self._slots:
                            if r is not None and r.adapter_pinned:
                                census[r.adapter_id] = \
                                    census.get(r.adapter_id, 0) + 1
                        self.adapter_store.check_invariant(census)
                if active:
                    self.metrics.decode_occupancy.observe(
                        active / self.cfg.max_num_seqs)
                self._update_gauges()
                if self.monitor is not None and (
                        self._step_count % self.cfg.monitor_interval == 0):
                    self.monitor.write_events(
                        self.metrics.to_events(self._step_count))
                finished = list(self._finished_this_step)
            # black-box step record + rolling anomaly check (ISSUE 7);
            # still inside the serve/step span, so the anomaly instant
            # lands between this step's B/E pair with its corr id
            dur_s = time.perf_counter() - t0
            self.flightrec.record(
                "serve/step", corr=f"serve-step-{step_id}",
                dur_ms=round(dur_s * 1e3, 3), active=active,
                queued=len(self._queue), finished=len(finished),
                version=self.weights_version)
            self.anomaly.observe("serve.step", dur_s,
                                 corr=f"serve-step-{step_id}")
            return finished

    def _update_gauges(self):
        """Occupancy + goodput gauges (ISSUE 4).  Goodput = generated
        tokens that were not later thrown away to preemption recompute;
        tokens/s is the cumulative decode rate since scheduler start."""
        from deepspeed_tpu.telemetry import serving_goodput
        c = self.metrics.counters
        elapsed = time.monotonic() - self._serve_t0
        self.metrics.gauges.update(
            queue_depth=len(self._queue),
            active_seqs=sum(r is not None for r in self._slots),
            block_pool_utilization=round(
                self.block_mgr.utilization(), 4),
            free_blocks=self.block_mgr.num_free_blocks,
            goodput=round(serving_goodput(
                c["generated_tokens"], c["recomputed_tokens"]), 4))
        if self._prefix_cache_on:
            c["prefix_cache_evict"] = self.block_mgr.cache_evictions
            self.metrics.gauges["cached_blocks"] = \
                self.block_mgr.num_cached_blocks
            lookups = c["prefix_cache_hit"] + c["prefix_cache_miss"]
            if lookups:
                self.metrics.gauges["prefix_cache_hit_rate"] = round(
                    c["prefix_cache_hit"] / lookups, 4)
        ts = self._tier_store
        if ts is not None:
            # tiered KV (ISSUE 16): policy counters mirror in as
            # serving/* counters (the cache_evictions idiom above);
            # occupancy + in-flight + hit-rate ride as gauges
            c["kv_demotions"] = ts.demotions
            c["kv_spills"] = ts.spills
            c["kv_parked_blocks"] = ts.parks
            c["kv_swap_in_blocks"] = ts.swapins
            c["kv_swap_failures"] = ts.failures
            counts = ts.counts()
            self.metrics.gauges.update(
                kv_host_blocks=counts["host"],
                kv_nvme_blocks=counts["nvme"],
                kv_inflight_swaps=len(ts.inflight()))
            attempts = ts.swapins + ts.failures
            if attempts:
                self.metrics.gauges["kv_tier_hit_rate"] = round(
                    ts.swapins / attempts, 4)
        st = self.adapter_store
        if st is not None:
            # adapter paging (ISSUE 20): store counters mirror in as
            # serving/adapter_* counters; residency rides as gauges
            s = st.summary()
            c["adapter_swap_ins"] = s["swap_ins"]
            c["adapter_demotions"] = s["demotions"]
            c["adapter_spills"] = s["spills"]
            c["adapter_dropped"] = s["dropped"]
            c["adapter_load_failures"] = max(
                c["adapter_load_failures"], s["load_failures"])
            c["adapter_slot_waits"] = s["slot_waits"]
            c["adapter_integrity_failures"] = s["integrity_failures"]
            self.metrics.gauges.update(
                adapter_resident_hbm=len(s["resident"]),
                adapter_host=s["host_adapters"],
                adapter_nvme=s["nvme_adapters"],
                adapter_pending_swapins=len(self._adapter_pending),
                adapter_quarantined=s["quarantined"])
        if elapsed > 0 and c["generated_tokens"]:
            self.metrics.gauges["tokens_per_s"] = round(
                c["generated_tokens"] / elapsed, 3)
        if c["spec_drafted_tokens"]:
            self.metrics.gauges["spec_accept_rate"] = round(
                c["spec_accepted_tokens"] / c["spec_drafted_tokens"], 4)
        if self._mem_on:
            self._update_memory_ledger()

    def _record_alloc_failure(self, site: str, **detail):
        """OOM forensics (ISSUE 14): a failed pool allocation snapshots
        the byte ledger into the forensics ring + flight recorder
        (``mem/alloc_failure``); the /debug and post-mortem surfaces
        read the snapshot, not the live (already-changed) pool."""
        if not self._mem_on:
            return
        try:
            self._update_memory_ledger()
            self._mem_ledger.record_alloc_failure(
                site, flightrec=self.flightrec,
                step=self._step_count, **detail)
        except Exception as e:  # forensics must never fail the step
            logger.debug(f"memory forensics failed ({e})")

    def _update_memory_ledger(self, publish: bool = True):
        """Memory observatory tap (ISSUE 14): the KV pool's bytes split
        by who holds them — live request tables (``kv_pool``), the
        prefix cache's retained refcount-0 set (``prefix_cache``), the
        free list (``kv_free``), and the reserved trash block
        (``kv_reserved``) — so the four owners sum EXACTLY to the pool
        pytree's leaf bytes (the parity contract the acceptance test
        enforces).  With ``publish`` it also refreshes the ``mem/*``
        gauges and feeds the HBM used fraction into the rolling anomaly
        detector (a leak alerts BEFORE the OOM) where the backend
        reports device stats; the mid-step occupancy tap (after table
        growth — where per-step occupancy PEAKS, so the watermarks see
        requests that admit and retire within one iteration) skips
        that half."""
        led = self._mem_ledger
        bm = self.block_mgr
        bpb = self._bytes_per_block
        led.set_bytes("device", "kv_pool",
                      bm.num_allocated_blocks * bpb,
                      blocks=bm.num_allocated_blocks,
                      block_size=bm.block_size)
        led.set_bytes("device", "prefix_cache",
                      bm.num_cached_blocks * bpb,
                      blocks=bm.num_cached_blocks)
        led.set_bytes("device", "kv_free", bm.num_free_blocks * bpb,
                      blocks=bm.num_free_blocks)
        led.set_bytes("device", "kv_reserved", bpb, blocks=1)
        if not publish:
            return
        led.publish_and_feed(self.metrics.registry, self.anomaly,
                             corr=f"serve-step-{self._step_count}")

    def run_until_idle(self, max_steps: int = 100_000):
        """Drive step() until queue and slots drain (bench/test helper)."""
        steps = 0
        while self.has_work():
            self.step()
            steps += 1
            if steps >= max_steps:
                raise RuntimeError(
                    f"scheduler did not drain in {max_steps} steps")
        return steps
