"""Memory-mapped indexed dataset (reference:
deepspeed/runtime/data_pipeline/data_sampling/indexed_dataset.py
``MMapIndexedDataset`` — the Megatron binary corpus format the offline
DataAnalyzer reads and writes).

Native format: ``<path>.bin`` holds the concatenated sample payloads;
``<path>.idx`` holds a small header (magic ``DSTPUIDX``, dtype code,
sample count) followed by per-sample element counts and byte offsets.
The reader ALSO accepts the reference's ``MMIDIDX`` .idx layout
(9-byte magic, version, dtype code, length, doc count, int32 sizes,
int64 pointers, int64 doc_idx — indexed_dataset.py:372-451), so existing
Megatron/DeepSpeed corpora load unchanged; the builder writes only the
native layout.  Reads go through ``np.memmap`` so a multi-hundred-GB
corpus costs no resident RAM.
"""
import os
import struct
from typing import Sequence

import numpy as np

_MAGIC = b"DSTPUIDX\x01"
_MMIDIDX_MAGIC = b"MMIDIDX\x00\x00"  # reference Megatron wire format
#: native dtype codes (DSTPUIDX files only)
_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
           5: np.int64, 6: np.float32, 7: np.float64, 8: np.uint16}
_CODES = {np.dtype(v): k for k, v in _DTYPES.items()}
#: the reference's code table (indexed_dataset.py:101-112) — NOT the same
#: assignment as the native one (6 is float64 there, float32 here)
_MMIDIDX_DTYPES = {1: np.uint8, 2: np.int8, 3: np.int16, 4: np.int32,
                   5: np.int64, 6: np.float64, 7: np.double, 8: np.uint16,
                   9: np.uint32, 10: np.uint64}


def data_file_path(prefix: str) -> str:
    return prefix + ".bin"


def index_file_path(prefix: str) -> str:
    return prefix + ".idx"


class MMapIndexedDatasetBuilder:
    """Streaming writer: ``add_item`` per sample, then ``finalize``."""

    def __init__(self, prefix: str, dtype=np.int32):
        self.dtype = np.dtype(dtype)
        if self.dtype not in _CODES:
            raise ValueError(f"unsupported dtype {dtype}")
        self._prefix = prefix
        self._bin = open(data_file_path(prefix), "wb")
        self._sizes = []
        self._offsets = [0]

    def add_item(self, array):
        arr = np.ascontiguousarray(np.asarray(array), dtype=self.dtype)
        self._bin.write(arr.tobytes())
        self._sizes.append(arr.size)
        self._offsets.append(self._offsets[-1] + arr.nbytes)
        return len(self._sizes) - 1

    def finalize(self):
        self._bin.close()
        with open(index_file_path(self._prefix), "wb") as f:
            f.write(_MAGIC)
            f.write(struct.pack("<BQ", _CODES[self.dtype],
                                len(self._sizes)))
            f.write(np.asarray(self._sizes, np.int64).tobytes())
            f.write(np.asarray(self._offsets[:-1], np.int64).tobytes())


class MMapIndexedDataset:
    """Random-access reader over the ``.bin``/``.idx`` pair."""

    def __init__(self, prefix: str):
        with open(index_file_path(prefix), "rb") as f:
            magic = f.read(len(_MAGIC))
            if magic == _MAGIC:
                code, n = struct.unpack("<BQ", f.read(9))
                self.dtype = np.dtype(_DTYPES[code])
                self.sizes = np.frombuffer(f.read(8 * n), np.int64)
                self.offsets = np.frombuffer(f.read(8 * n), np.int64)
                self.doc_idx = np.arange(n + 1, dtype=np.int64)
            elif magic == _MMIDIDX_MAGIC:
                (version,) = struct.unpack("<Q", f.read(8))
                if version != 1:
                    raise ValueError(
                        f"{prefix}.idx: MMIDIDX version {version} != 1")
                (code,) = struct.unpack("<B", f.read(1))
                if code not in _MMIDIDX_DTYPES:
                    raise ValueError(
                        f"{prefix}.idx: unknown MMIDIDX dtype code {code}")
                self.dtype = np.dtype(_MMIDIDX_DTYPES[code])
                (n,) = struct.unpack("<Q", f.read(8))
                (doc_count,) = struct.unpack("<Q", f.read(8))
                self.sizes = np.frombuffer(f.read(4 * n),
                                           np.int32).astype(np.int64)
                self.offsets = np.frombuffer(f.read(8 * n), np.int64)
                self.doc_idx = np.frombuffer(f.read(8 * doc_count), np.int64)
            else:
                raise ValueError(f"{prefix}.idx: bad magic {magic!r}")
        self._data = np.memmap(data_file_path(prefix), mode="r",
                               dtype=np.uint8)

    def __len__(self) -> int:
        return len(self.sizes)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        off, size = int(self.offsets[i]), int(self.sizes[i])
        raw = self._data[off:off + size * self.dtype.itemsize]
        return np.frombuffer(raw, self.dtype)

    def close(self):
        self._data = None


def write_dataset(prefix: str, samples: Sequence, dtype=np.int32):
    """Convenience one-shot writer."""
    b = MMapIndexedDatasetBuilder(prefix, dtype)
    for s in samples:
        b.add_item(s)
    b.finalize()
    return prefix
