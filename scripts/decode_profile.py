"""Decode-step variant profiling: where does the per-token millisecond go?

VERDICT round-4 item 1: per-family decode rates sit 4-5x above the
weight-streaming floor.  This script times the gpt2 decode step in
structural variants to attribute the residue:

  scan_scatter   — the shipped round-4 path: lax.scan over layers with the
                   cache in xs/ys (full cache copy per token) and scatter
                   cache writes
  unroll_scatter — python-unrolled layers, cache updated in place on the
                   carried stacked array (static layer index + scatter)
  unroll_mask    — unrolled, cache row written via an iota==length mask
                   select instead of scatter
  weights_floor  — one dummy matmul chain streaming the same weight bytes
                   (the floor decode can never beat)

Timing uses the on-device fori_loop slope discipline from flash_ab.py
(the axon tunnel charges ~100 ms per blocking round trip; only slopes
between step counts are trustworthy).

    python scripts/decode_profile.py            # gpt2 125m, B=4, S=384
    DEC_B=8 DEC_S=512 python scripts/decode_profile.py
    DEC_MOE=1 python scripts/decode_profile.py  # mixtral expert floors

DEC_MOE=1 (ISSUE 8) switches to the Mixtral expert-floor accounting:
``weights_floor_moe`` streams the dense int8 bytes plus only the top-k-
DISTINCT-expert bytes per step (what the grouped int8 kernel's slot
plan fetches), vs ``weights_floor_moe_all`` streaming all E experts
(what einsum dispatch — or any capacity-padded formulation — pays).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


# shared slope-timing helper (scripts/bench_util.py): value-fetch sync —
# the old local copy synced with block_until_ready, which does NOT
# synchronize on the axon tunnel (PERF.md round 4)
from scripts.bench_util import emit_ledger
from scripts.bench_util import timed_chain_ms as timed_chain


def _variant_record(model: str, name: str, step_ms: float) -> dict:
    """Ledger form of one variant row (DS_BENCH_LEDGER=1, ISSUE 13):
    step_ms is the gated value; the model shape rides detail.model so
    bench_compare's cross-model guard engages.  ``mem_peak_*`` fields
    (ISSUE 14) and ``comm_*`` fields (ISSUE 19) ride detail too, so
    the history can gate memory and interconnect regressions beside
    latency ones."""
    from scripts.bench_util import comm_fields, mem_peak_fields
    return {"metric": f"decode_profile_{name}", "value": step_ms,
            "unit": "ms_per_step", "direction": "lower_better",
            "detail": {"model": model, **mem_peak_fields(),
                       **comm_fields()}}


def moe_floor_main():
    """Mixtral expert-floor accounting + dummy-stream timing (ISSUE 8):
    how much of the decode step's weight traffic is experts, and what
    the grouped int8 path's distinct-expert floor buys over streaming
    every expert.  Per layer a decode step with A active rows and top-k
    routing touches at most min(A*k, E) distinct experts — the grouped
    slot kernel fetches exactly the distinct set once; the einsum
    formulation's dense [T,E,C] dispatch computes (and streams) all E."""
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    B = int(os.environ.get("DEC_B", 4))
    size = os.environ.get("DEC_MODEL", "1b-moe" if on_tpu else "tiny")
    steps = int(os.environ.get("DEC_STEPS", 20 if on_tpu else 2))

    from deepspeed_tpu.models.mixtral import mixtral_model
    from deepspeed_tpu.models.model import QuantizedTensor
    from deepspeed_tpu.ops.pallas.quantization import block_quantize_int8
    model = mixtral_model(size, dtype="bfloat16" if on_tpu else "float32",
                          attention_impl="xla")
    cfg = model.config
    dtype = jnp.dtype(cfg.dtype)
    params = jax.jit(model.init_fn)(jax.random.PRNGKey(0))

    def _pack(x):
        if x.ndim >= 3 and jnp.issubdtype(x.dtype, jnp.floating):
            qq, ss = block_quantize_int8(x.astype(dtype))
            return QuantizedTensor(qq, ss, str(dtype))
        return x

    qblocks = jax.tree.map(_pack, params["blocks"])
    is_q = lambda x: isinstance(x, QuantizedTensor)
    expert_mats, dense_mats = [], []
    for leaf in jax.tree_util.tree_leaves(qblocks, is_leaf=is_q):
        if not is_q(leaf):
            continue
        if leaf.q.ndim >= 4:        # [L, E, in, out] stacked experts
            expert_mats.append(leaf)
        else:
            dense_mats.append(leaf)
    E, k, L = cfg.num_experts, cfg.top_k, cfg.num_layers
    # byte accounting shared with serve_bench's weights_floor_moe record
    from deepspeed_tpu.models.serving import split_quantized_bytes
    dense_b, expert_b = split_quantized_bytes(qblocks)
    per_expert = expert_b // E          # all layers, one expert
    distinct = min(B * k, E)
    floor_moe = dense_b + distinct * per_expert
    floor_all = dense_b + expert_b
    print(json.dumps({
        "model": f"mixtral:{size}", "batch": B, "num_experts": E,
        "top_k": k, "layers": L,
        "dense_int8_bytes_mb": round(dense_b / 1e6, 2),
        "expert_int8_bytes_mb": round(expert_b / 1e6, 2),
        "distinct_experts_per_step_bound": distinct,
        "weights_floor_moe_mb": round(floor_moe / 1e6, 2),
        "weights_floor_moe_all_mb": round(floor_all / 1e6, 2),
        "floor_ratio_all_over_distinct": round(floor_all / floor_moe, 3),
        "floor_moe_ms_at_819GBs": round(floor_moe / 819e9 * 1e3, 3),
        "floor_moe_all_ms_at_819GBs": round(floor_all / 819e9 * 1e3, 3),
    }))

    # dummy-stream variants: one int8 matvec chain per streamed matrix —
    # the same idiom as weights_floor_int8, restricted to the bytes each
    # formulation actually touches per step
    def chain(mats_2d):
        def step(state):
            tok, a, b = state
            acc = jnp.zeros((B, 1), jnp.int32)
            for m in mats_2d:
                r, _ = m.shape
                y = jnp.broadcast_to(tok[:, None].astype(jnp.int8), (B, r))
                acc = acc + jnp.sum(lax.dot(
                    y, m, preferred_element_type=jnp.int32),
                    axis=-1, keepdims=True)
            return ((tok + jnp.sum(acc) * 0) % 127, a, b)
        return step

    def flat_dense(leaves):
        return [m.q.reshape(-1, m.q.shape[-1]) for m in leaves]

    def flat_experts(n):
        # first n experts of every layer stand in for the distinct set —
        # same byte count, same access pattern class
        return [m.q[:, :n].reshape(-1, m.q.shape[-1])
                for m in expert_mats]

    tok0 = jnp.zeros((B,), jnp.int32)
    state0 = (tok0, tok0, tok0)
    for name, mats_2d in (
            ("weights_floor_moe", flat_dense(dense_mats)
             + flat_experts(distinct)),
            ("weights_floor_moe_all", flat_dense(dense_mats)
             + flat_experts(E))):
        try:
            ms = timed_chain(chain(mats_2d), state0, steps)
            print(json.dumps({"variant": name, "step_ms": round(ms, 4),
                              "tok_per_s_B": (round(B / (ms * 1e-3))
                                              if ms > 0 else None)}))
            emit_ledger(_variant_record(f"mixtral:{size}:B{B}", name,
                                        round(ms, 4)))
        except Exception as e:
            print(json.dumps({"variant": name, "error": str(e)[:300]}))


def main():
    if os.environ.get("DEC_MOE"):
        return moe_floor_main()
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    B = int(os.environ.get("DEC_B", 4))
    S = int(os.environ.get("DEC_S", 384))
    size = os.environ.get("DEC_MODEL", "125m" if on_tpu else "custom")
    steps = int(os.environ.get("DEC_STEPS", 20 if on_tpu else 2))

    from deepspeed_tpu.models import gpt2 as G
    kwargs = {} if on_tpu else dict(vocab_size=256, num_layers=2,
                                    num_heads=4, d_model=32)
    model = G.gpt2_model(size, dtype="bfloat16" if on_tpu else "float32",
                         max_seq_len=max(1024, S), **kwargs)
    cfg = model.config
    params = jax.jit(model.init_fn)(jax.random.PRNGKey(0))
    params = jax.tree.map(
        lambda x: x.astype(cfg.dtype)
        if jnp.issubdtype(x.dtype, jnp.floating) else x, params)
    L = cfg.num_layers
    dtype = jnp.dtype(cfg.dtype)

    cache = G.init_cache(cfg, B, S)
    # warm cache with realistic fill
    rng = np.random.default_rng(0)
    cache = {k: jnp.asarray(rng.standard_normal(v.shape), v.dtype)
             for k, v in cache.items()}
    lengths0 = jnp.full((B,), S // 2, jnp.int32)
    tok0 = jnp.zeros((B,), jnp.int32)

    from deepspeed_tpu.models.model import maybe_stream
    from deepspeed_tpu.ops.pallas.decode_attention import decode_attention
    rows = jnp.arange(B)

    def embed(tokens, lengths):
        return (params["wte"].astype(dtype)[tokens] +
                params["wpe"].astype(dtype)[lengths])

    def logits_of(x):
        return G.head(params, x[:, None, :], cfg)[:, 0]

    def next_state(logits, cache, lengths):
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        # stay in bounds over long chains while keeping the data dependency
        lengths = jnp.minimum(lengths + 1, S - 1)
        return (tok, cache, lengths)

    # ---------------------------------------------------------- variants
    def scan_scatter(state):
        tok, cache, lengths = state
        logits, cache = G.decode_step(params, tok, cache, lengths, cfg)
        return next_state(logits, cache, lengths)

    def unroll_common(state, write):
        tok, cache, lengths = state
        x = embed(tok, lengths)
        kc, vc = cache["k"], cache["v"]
        for l in range(L):
            layer = maybe_stream(jax.tree.map(lambda a: a[l],
                                              params["blocks"]))
            q, kk, v = G._block_qkv(x[:, None, :], layer, cfg)
            kc = write(kc, l, kk[:, 0], lengths)
            vc = write(vc, l, v[:, 0], lengths)
            attn = decode_attention(q[:, 0], kc[l], vc[l], lengths + 1)
            x = G._block_finish(x[:, None, :],
                                attn.reshape(B, 1, cfg.d_model), layer,
                                cfg)[:, 0]
        return next_state(logits_of(x), {"k": kc, "v": vc}, lengths)

    def scatter_write(c, l, new, lengths):
        return c.at[l, rows, lengths].set(new.astype(c.dtype))

    def mask_write(c, l, new, lengths):
        # [B, S] one-hot row mask -> select; dense-bandwidth on ONE layer
        m = (jnp.arange(c.shape[2])[None, :] ==
             lengths[:, None])[..., None, None]           # [B, S, 1, 1]
        upd = jnp.where(m, new[:, None].astype(c.dtype), c[l])
        return lax.dynamic_update_slice(
            c, upd[None], (l, 0, 0, 0, 0))

    def rowdus_write(c, l, new, lengths):
        # B tiny in-place dynamic_update_slices (one per row)
        new = new.astype(c.dtype)
        for b in range(B):
            c = lax.dynamic_update_slice(
                c, new[b][None, None, None],
                (l, b, lengths[b], 0, 0))
        return c

    def unroll_uniform(state):
        # all rows share one position (the engine's common case: equal
        # right-padded prompts) -> ONE dus writes every row's new vector
        tok, cache, lengths = state
        pos = lengths[0]
        x = embed(tok, lengths)
        kc, vc = cache["k"], cache["v"]
        for l in range(L):
            layer = maybe_stream(jax.tree.map(lambda a: a[l],
                                              params["blocks"]))
            q, kk, v = G._block_qkv(x[:, None, :], layer, cfg)
            kc = lax.dynamic_update_slice(
                kc, kk.astype(kc.dtype)[None], (l, 0, pos, 0, 0))
            vc = lax.dynamic_update_slice(
                vc, v.astype(vc.dtype)[None], (l, 0, pos, 0, 0))
            attn = decode_attention(q[:, 0], kc[l], vc[l], lengths + 1)
            x = G._block_finish(x[:, None, :],
                                attn.reshape(B, 1, cfg.d_model), layer,
                                cfg)[:, 0]
        return next_state(logits_of(x), {"k": kc, "v": vc}, lengths)

    variants = {
        "scan_scatter": scan_scatter,
        "unroll_scatter": lambda s: unroll_common(s, scatter_write),
        "unroll_mask": lambda s: unroll_common(s, mask_write),
        "unroll_rowdus": lambda s: unroll_common(s, rowdus_write),
        "unroll_uniform": unroll_uniform,
    }

    # ------------------------------------------------- component ablations
    def ablate(state, *, attn=True, write=True, mlp=True, layers=True):
        tok, cache, lengths = state
        x = embed(tok, lengths)
        kc, vc = cache["k"], cache["v"]
        if layers:
            for l in range(L):
                layer = maybe_stream(jax.tree.map(lambda a: a[l],
                                                  params["blocks"]))
                q, kk, v = G._block_qkv(x[:, None, :], layer, cfg)
                if write:
                    kc = mask_write(kc, l, kk[:, 0], lengths)
                    vc = mask_write(vc, l, v[:, 0], lengths)
                if attn:
                    a = decode_attention(q[:, 0], kc[l], vc[l], lengths + 1)
                else:
                    a = q[:, 0]
                a = a.reshape(B, 1, cfg.d_model)
                if mlp:
                    x = G._block_finish(x[:, None, :], a, layer, cfg)[:, 0]
                else:
                    x = (x[:, None, :] + a @ layer["proj_w"].astype(x.dtype)
                         )[:, 0]
        return next_state(logits_of(x), {"k": kc, "v": vc}, lengths)

    from deepspeed_tpu.ops.pallas.decode_attention import (
        decode_attention_pallas, decode_attention_xla)

    def ablate_attn_impl(state, attn_fn):
        tok, cache, lengths = state
        x = embed(tok, lengths)
        kc, vc = cache["k"], cache["v"]
        for l in range(L):
            layer = maybe_stream(jax.tree.map(lambda a: a[l],
                                              params["blocks"]))
            q, kk, v = G._block_qkv(x[:, None, :], layer, cfg)
            kc = mask_write(kc, l, kk[:, 0], lengths)
            vc = mask_write(vc, l, v[:, 0], lengths)
            a = attn_fn(q[:, 0], kc[l], vc[l], lengths + 1)
            x = G._block_finish(x[:, None, :],
                                a.reshape(B, 1, cfg.d_model), layer,
                                cfg)[:, 0]
        return next_state(logits_of(x), {"k": kc, "v": vc}, lengths)

    variants.update({
        "ab_attn_block384": lambda s: ablate_attn_impl(
            s, lambda q, k, v, cl: decode_attention_pallas(
                q, k, v, cl, block_s=S)),
        "ab_attn_xla": lambda s: ablate_attn_impl(
            s, decode_attention_xla),
        "ab_full": lambda s: ablate(s),
        "ab_no_attn": lambda s: ablate(s, attn=False),
        "ab_no_write": lambda s: ablate(s, write=False),
        "ab_no_mlp": lambda s: ablate(s, mlp=False),
        "ab_embed_head": lambda s: ablate(s, layers=False),
    })

    # mimic the engine's _build_cached_generate scan exactly (decode_fn is
    # the NEW unrolled path): measures what the generate-loop scaffolding
    # (scan ys, done flags, argmax placement) adds per token
    def engine_scan(state):
        tok, cache, lengths = state

        def body(carry, _):
            cache, tok, lens, done = carry
            logits, cache = G.decode_step(params, tok, cache, lens, cfg)
            new = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return (cache, new, jnp.minimum(lens + 1, S - 1), done), new

        done = jnp.zeros((B,), bool)
        (cache, tok, lengths, _), ys = lax.scan(
            body, (cache, tok, lengths, done), None, length=8)
        return (tok + jnp.sum(ys) * 0, cache, lengths)

    def engine_fori(state):
        # the REJECTED generate-loop alternative (the engine ships the
        # scan form): fori_loop with an in-place token buffer — measured
        # ~0.1 ms/token slower than scan's ys emission.  Carries the
        # same done flag as engine_scan so the A/B isolates the
        # token-emission mechanism alone.
        tok, cache, lengths = state
        out0 = jnp.zeros((B, 8), jnp.int32)
        done0 = jnp.zeros((B,), bool)

        def body(i, carry):
            cache, tok, lens, done, out = carry
            logits, cache = G.decode_step(params, tok, cache, lens, cfg)
            new = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            out = lax.dynamic_update_slice(out, new[:, None], (0, i))
            return (cache, new, jnp.minimum(lens + 1, S - 1), done, out)

        cache, tok, lengths, _, out = lax.fori_loop(
            0, 8, body, (cache, tok, lengths, done0, out0))
        return (tok + out[:, -1] * 0, cache, lengths)

    def engine_scan_steps(n, fn=None):
        # per-token cost inside the mimic loop, from the fori slope over
        # chains of 8-token inner loops
        ms = timed_chain(fn or engine_scan, state0, max(2, n // 8))
        return ms / 8

    variants = dict(variants)

    # ------------------------------------------- int8-weight variants
    # the ISSUE-2 A/B: fused-dequant qgemm unrolled decode vs the
    # maybe_stream dequant form, plus the int8 weight-stream floor the
    # qgemm path is chasing (PERF.md round 5: 1.3B int8 238 tok/s on the
    # scan-dequant path vs an int8 floor several× higher)
    from deepspeed_tpu.models.model import QuantizedTensor
    from deepspeed_tpu.ops.pallas.quantization import block_quantize_int8

    def _pack(x):
        if x.ndim >= 3 and jnp.issubdtype(x.dtype, jnp.floating):
            qq, ss = block_quantize_int8(x.astype(dtype))
            return QuantizedTensor(qq, ss, str(dtype))
        return x

    qblocks = jax.tree.map(_pack, params["blocks"])

    def unroll_int8(state, keep_quantized):
        tok, cache, lengths = state
        x = embed(tok, lengths)
        kc, vc = cache["k"], cache["v"]
        for l in range(L):
            layer = maybe_stream(jax.tree.map(lambda a: a[l], qblocks),
                                 keep_quantized=keep_quantized)
            q, kk, v = G._block_qkv(x[:, None, :], layer, cfg)
            kc = mask_write(kc, l, kk[:, 0], lengths)
            vc = mask_write(vc, l, v[:, 0], lengths)
            attn = decode_attention(q[:, 0], kc[l], vc[l], lengths + 1)
            x = G._block_finish(x[:, None, :],
                                attn.reshape(B, 1, cfg.d_model), layer,
                                cfg)[:, 0]
        return next_state(logits_of(x), {"k": kc, "v": vc}, lengths)

    variants["unroll_int8_qgemm"] = lambda s: unroll_int8(s, True)
    variants["unroll_int8_dequant"] = lambda s: unroll_int8(s, False)

    qmats = [leaf.q.reshape(-1, leaf.q.shape[-1])
             for leaf in jax.tree.leaves(
                 qblocks, is_leaf=lambda x: isinstance(x, QuantizedTensor))
             if isinstance(leaf, QuantizedTensor)]
    qbytes = sum(int(m.size) for m in qmats)

    def weights_floor_int8(state):
        # one int8 [B, r] x [r, c] matmul per quantized matrix: streams
        # every int8 byte once per step with a tok data dependency (the
        # bf16 weights_floor idiom at 1 byte/param)
        tok, cache, lengths = state
        acc = jnp.zeros((B, 1), jnp.int32)
        for m in qmats:
            r, c = m.shape
            y = jnp.broadcast_to(tok[:, None].astype(jnp.int8), (B, r))
            d = lax.dot(y, m, preferred_element_type=jnp.int32)
            acc = acc + jnp.sum(d, axis=-1, keepdims=True)
        tok = (tok + jnp.sum(acc) * 0) % cfg.vocab_size
        return (tok, cache, lengths)

    variants["weights_floor_int8"] = weights_floor_int8

    # weights floor: one [B, r] @ [r, c] matmul per large weight matrix —
    # streams every weight byte once per step with zero overhead ops
    flat = [x for x in jax.tree.leaves(params)
            if jnp.issubdtype(x.dtype, jnp.floating)]
    mats = [x.reshape(-1, x.shape[-1]) for x in flat if x.size >= 1 << 16]
    wbytes = sum(int(x.size) * x.dtype.itemsize for x in flat)

    def weights_floor2(state):
        tok, cache, lengths = state
        acc = jnp.zeros((B, 1), jnp.float32)
        for m in mats:
            r, c = m.shape
            y = jnp.broadcast_to(tok[:, None].astype(dtype), (B, r))
            acc = acc + jnp.sum(y @ m, axis=-1, keepdims=True)
        tok = (tok + jnp.sum(acc).astype(jnp.int32) * 0) % cfg.vocab_size
        return (tok, cache, lengths)

    variants["weights_floor"] = weights_floor2

    # ------------------------------------------- fused megakernel A/B
    # ISSUE 12: the same decode step through the fused per-layer path
    # (ONE Pallas call per layer on chip; the jnp reference composition
    # off-chip — a structural A/B only there).  Token identity between
    # the two paths is asserted up front so the timing rows compare
    # equal programs.
    from deepspeed_tpu.ops.pallas.fused_decode import fused_decode_scope

    def fused_decode(state):
        # scope is a trace-time choice; timed_chain traces step_fn
        # inside this call, so the scope covers the trace
        with fused_decode_scope(True):
            tok, cache, lengths = state
            logits, cache = G.decode_step(params, tok, cache, lengths,
                                          cfg)
            return next_state(logits, cache, lengths)

    def fused_int8w(state):
        with fused_decode_scope(True):
            tok, cache, lengths = state
            qp = dict(params)
            qp["blocks"] = qblocks
            logits, cache = G.decode_step(qp, tok, cache, lengths, cfg)
            return next_state(logits, cache, lengths)

    variants["fused_decode"] = fused_decode
    variants["fused_int8w_decode"] = fused_int8w

    def _argmax_chain(fused, n=4):
        tok, cache, lengths = state0
        with fused_decode_scope(fused):
            f = jax.jit(lambda t, c, l: G.decode_step(params, t, c, l,
                                                      cfg))
            out = []
            for _ in range(n):
                logits, cache = f(tok, cache, lengths)
                tok = jnp.argmax(logits, -1).astype(jnp.int32)
                lengths = lengths + 1
                out.append(np.asarray(tok))
        return np.stack(out)

    state0 = (tok0, cache, lengths0)
    try:
        fused_same = bool((_argmax_chain(False)
                           == _argmax_chain(True)).all())
    except Exception as e:
        fused_same = f"error: {str(e)[:200]}"
    print(json.dumps({"variant": "fused_parity",
                      "token_identical": fused_same}))

    cal = jnp.asarray(rng.standard_normal((2048, 2048)), jnp.bfloat16)
    mm = lambda s: (jnp.tanh(s[0] @ cal), s[1], s[2])
    mm_ms = timed_chain(mm, (cal, 0, 0), steps)
    mm_tf = 2 * 2048 ** 3 / (mm_ms * 1e-3) / 1e12 if mm_ms > 0 else None
    print(json.dumps({"calibration": "matmul2048", "ms": round(mm_ms, 4),
                      "apparent_tflops": round(mm_tf, 1) if mm_tf else None,
                      "weight_bytes_mb": round(wbytes / 1e6, 1),
                      "floor_ms_at_819GBs": round(wbytes / 819e9 * 1e3, 3),
                      "int8_weight_bytes_mb": round(qbytes / 1e6, 1),
                      "int8_floor_ms_at_819GBs": round(
                          qbytes / 819e9 * 1e3, 3)}))

    only = [s for s in os.environ.get("DEC_ONLY", "").split(",") if s]
    if only:
        variants = {k: v for k, v in variants.items() if k in only}

    state0 = (tok0, cache, lengths0)
    for mimic_name, mimic_fn in (("engine_scan_mimic", engine_scan),
                                 ("engine_fori_mimic", engine_fori)):
        try:
            if only and mimic_name not in only:
                continue
            ms8 = engine_scan_steps(steps, mimic_fn)
            print(json.dumps({"variant": mimic_name,
                              "step_ms": round(ms8, 4),
                              "tok_per_s_B": (round(B / (ms8 * 1e-3))
                                              if ms8 > 0 else None)}))
        except Exception as e:
            print(json.dumps({"variant": mimic_name,
                              "error": str(e)[:300]}))
    for name, fn in variants.items():
        try:
            ms = timed_chain(fn, state0, steps)
            print(json.dumps({"variant": name, "step_ms": round(ms, 4),
                              "tok_per_s_B": (round(B / (ms * 1e-3))
                                              if ms > 0 else None)}))
            emit_ledger(_variant_record(f"gpt2:{size}:B{B}:S{S}", name,
                                        round(ms, 4)))
        except Exception as e:  # keep profiling the rest
            print(json.dumps({"variant": name,
                              "error": str(e)[:300]}))


if __name__ == "__main__":
    main()
