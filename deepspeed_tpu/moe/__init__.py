from deepspeed_tpu.moe.layer import (MoE, MoEConfig, moe_layer,
                                     init_moe_params, moe_logical_specs)
from deepspeed_tpu.moe.sharded_moe import (top1gating, top2gating, topkgating,
                                           GateOutput)
