"""Benchmark entry point (driver-run on real TPU hardware).

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

Workload: GPT-2 760M causal-LM training step, ZeRO-2, bf16 compute + fp32 master, on the
available chip(s).  Reports model FLOPs utilisation (MFU) against the chip's
bf16 peak; ``vs_baseline`` is MFU relative to the BASELINE.md acceptance target
of 35% MFU.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import jax

import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import gpt2_model

MODEL_SIZE = os.environ.get("BENCH_MODEL", "760m")
SEQ = int(os.environ.get("BENCH_SEQ", 1024))
MICRO = int(os.environ.get("BENCH_MICRO", 12))
STEPS = int(os.environ.get("BENCH_STEPS", 10))
WARMUP = int(os.environ.get("BENCH_WARMUP", 3))
ZERO_STAGE = int(os.environ.get("BENCH_ZERO", 2))
OFFLOAD = bool(int(os.environ.get("BENCH_OFFLOAD", "0")))
REMAT_POLICY = os.environ.get("BENCH_REMAT_POLICY", "nothing")

# bf16 peak TFLOPS per chip by TPU generation (public specs)
PEAK_TFLOPS = {
    "v4": 275.0, "v5e": 197.0, "v5 lite": 197.0, "v5p": 459.0,
    "v6e": 918.0,
}


def chip_peak_tflops() -> float:
    name = str(jax.devices()[0]).lower()
    for key, peak in PEAK_TFLOPS.items():
        if key in name:
            return peak
    return 197.0


def main():
    n_chips = jax.device_count()
    remat = bool(int(os.environ.get("BENCH_REMAT", "1")))
    if MODEL_SIZE.startswith("bert"):
        # BASELINE row 1 (fastest-BERT): BENCH_MODEL=bert-large BENCH_SEQ=128
        # BENCH_MICRO=128 / BENCH_SEQ=512 BENCH_MICRO=32
        from deepspeed_tpu.models.bert import bert_model
        model = bert_model(MODEL_SIZE.split("-", 1)[1], max_seq_len=SEQ,
                           dtype="bfloat16", remat=remat,
                           remat_policy=REMAT_POLICY)
    elif MODEL_SIZE.startswith("mixtral"):
        # BASELINE config 5's measurable half: BENCH_MODEL=mixtral-1b-moe
        # BENCH_SEQ=1024 BENCH_MICRO=8 (ep=1 single chip)
        from deepspeed_tpu.models.mixtral import mixtral_model
        model = mixtral_model(MODEL_SIZE.split("-", 1)[1], max_seq_len=SEQ,
                              dtype="bfloat16", remat=remat,
                              remat_policy=REMAT_POLICY)
    else:
        model = gpt2_model(MODEL_SIZE, max_seq_len=SEQ, dtype="bfloat16",
                           remat=remat, remat_policy=REMAT_POLICY)
    n_params = model.meta["n_params"]
    cfg = model.config
    # MFU accounting: 6N matmul flops/token (N = ACTIVE params for MoE —
    # model.flops_per_token) + causal attention (12*L*S*D fwd+bwd, halved
    # for causal masking)
    flops_per_token = ((model.flops_per_token or 6.0 * n_params)
                       + 6.0 * cfg.num_layers * SEQ * cfg.d_model)

    config = {
        "train_micro_batch_size_per_gpu": MICRO,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": ZERO_STAGE},
        "steps_per_print": 0,
    }
    # optimizer-phase byte diet (runtime/bf16_optimizer.py): Kahan bf16
    # masters / bf16 moments / bf16 grad accumulation.  DEFAULT since
    # round 5 — the metric name carries "_diet" so rounds stay
    # comparable; BENCH_PRECISION=fp32 restores fp32 optimizer states
    # (the round-4 configuration).  The diet's loss trajectory tracks
    # fp32 masters (PERF.md; tests/test_bf16_optimizer.py).
    precision = os.environ.get("BENCH_PRECISION", "diet")
    if precision == "diet":
        config["bf16"].update(master_weights_dtype="bfloat16",
                              optimizer_states_dtype="bfloat16")
        config["data_types"] = {"grad_accum_dtype": "bf16"}
    if OFFLOAD:
        # ZeRO-Infinity tier: params+optimizer state in pinned host DRAM,
        # streamed per layer (models beyond one chip's HBM, e.g. 1.3B+ fp32
        # state on a 16 GB v5e)
        config["zero_optimization"]["offload_optimizer"] = {"device": "cpu"}
        config["zero_optimization"]["offload_param"] = {"device": "cpu"}
    engine, *_ = deepspeed_tpu.initialize(model=model, config=config)

    rng = np.random.default_rng(0)
    global_batch = MICRO * engine.topology.dp_world_size

    def batch():
        ids = rng.integers(0, cfg.vocab_size, size=(1, global_batch, SEQ),
                           dtype=np.int32)
        if MODEL_SIZE.startswith("bert"):     # 15% MLM objective
            labels = np.where(rng.random(ids.shape) < 0.15, ids,
                              -100).astype(np.int32)
            return {"input_ids": ids, "labels": labels}
        return {"input_ids": ids}

    for _ in range(WARMUP):
        loss = engine.train_batch(batch=batch())
    float(loss)   # true device sync (block_until_ready is not enough on the
                  # axon remote-TPU platform; a host transfer is)

    t0 = time.time()
    for _ in range(STEPS):
        loss = engine.train_batch(batch=batch())
    float(loss)   # chained data dependence -> all steps complete
    dt = (time.time() - t0) / STEPS

    tokens_per_sec = global_batch * SEQ / dt
    tokens_per_sec_chip = tokens_per_sec / n_chips
    mfu = tokens_per_sec_chip * flops_per_token / (chip_peak_tflops() * 1e12)

    print(json.dumps({
        "metric": ((MODEL_SIZE if MODEL_SIZE.startswith(("bert", "mixtral"))
                    else f"gpt2_{MODEL_SIZE}")
                   + f"_bf16_zero{ZERO_STAGE}"
                   + ("_diet" if precision == "diet" else "")
                   + ("_offload" if OFFLOAD else "") + "_mfu"),
        "value": round(mfu, 4),
        "unit": "MFU_fraction",
        "vs_baseline": round(mfu / 0.35, 4),
        "detail": {
            "tokens_per_sec_per_chip": round(tokens_per_sec_chip, 1),
            "step_time_s": round(dt, 4),
            "seq_len": SEQ,
            "micro_batch": MICRO,
            "n_chips": n_chips,
            "n_params": n_params,
            "loss": float(loss),
        },
    }))


if __name__ == "__main__":
    main()
