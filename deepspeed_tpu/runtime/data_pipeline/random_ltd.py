"""Random layerwise token dropping (reference: deepspeed/runtime/data_pipeline/
data_routing/basic_layer.py:14 ``RandomLayerTokenDrop`` + csrc/random_ltd
gather/scatter kernels).

TPU-native: token selection is a jittable argsort-of-random-keys gather; the
reference's CUDA token_sort/gather/scatter kernels are plain XLA take/scatter
(SURVEY.md notes no custom kernel is warranted).  The schedule linearly grows
the kept-token count to the full sequence over ``total_layer_token_steps``.
"""
from typing import Tuple

import jax
import jax.numpy as jnp


def random_token_select(rng, x: jnp.ndarray, keep: int
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x: [B, S, D] -> (kept [B, keep, D] in original order, indices)."""
    B, S, _ = x.shape
    scores = jax.random.uniform(rng, (B, S))
    idx = jnp.argsort(scores, axis=1)[:, :keep]
    idx = jnp.sort(idx, axis=1)            # preserve sequence order
    kept = jnp.take_along_axis(x, idx[:, :, None], axis=1)
    return kept, idx


def scatter_tokens(full: jnp.ndarray, kept: jnp.ndarray,
                   idx: jnp.ndarray) -> jnp.ndarray:
    """Write processed kept tokens back into the full sequence."""
    B = full.shape[0]
    b_idx = jnp.arange(B)[:, None]
    return full.at[b_idx, idx].set(kept)


class RandomLTDScheduler:
    """Token-count schedule (reference data_routing/scheduler.py)."""

    def __init__(self, total_layer_token_steps: int, min_tokens: int,
                 max_tokens: int, step_size: int = 16):
        self.total = total_layer_token_steps
        self.min_tokens = min_tokens
        self.max_tokens = max_tokens
        self.step_size = step_size
        self.current = min_tokens

    def update_seq(self, global_step: int) -> int:
        frac = min(1.0, global_step / max(self.total, 1))
        tokens = self.min_tokens + frac * (self.max_tokens - self.min_tokens)
        tokens = int(tokens / self.step_size) * self.step_size
        self.current = max(min(tokens, self.max_tokens), self.min_tokens)
        return self.current

    def get_current_seq(self) -> int:
        return self.current

    def state_dict(self):
        return {"current": self.current}

    def load_state_dict(self, sd):
        self.current = sd["current"]


# trace-time keep-count scope: the engine sets it per step (one compile per
# distinct value), models' layer scans read it (reference wires
# RandomLayerTokenDrop wrappers around layers, data_routing/basic_layer.py:14)
import contextlib
import contextvars

_LTD_KEEP: contextvars.ContextVar = contextvars.ContextVar(
    "ds_random_ltd_keep", default=None)


@contextlib.contextmanager
def ltd_scope(keep):
    token = _LTD_KEEP.set(keep)
    try:
        yield
    finally:
        _LTD_KEEP.reset(token)


def get_ltd_keep():
    return _LTD_KEEP.get()


def random_ltd_block(block_fn, rng, x, keep: int):
    """Apply ``block_fn`` to a random ``keep``-token subset, pass the rest
    through (the RandomLayerTokenDrop wrapper's forward)."""
    if keep >= x.shape[1]:
        return block_fn(x)
    kept, idx = random_token_select(rng, x, keep)
    processed = block_fn(kept)
    return scatter_tokens(x, processed, idx)
