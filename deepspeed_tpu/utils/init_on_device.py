"""OnDevice — deferred/abstract model initialisation (reference:
deepspeed/utils/init_on_device.py ``OnDevice``: constructs modules on the
meta device so multi-billion-param models never materialise on one host).

JAX already separates shape from storage: ``jax.eval_shape`` runs any init
function abstractly.  ``OnDevice`` packages that as the reference's context
manager; route inits through ``abstract_init`` (a bare ``model.init(rng)``
is eager regardless of the context — JAX cannot intercept it):

    with OnDevice(dtype="bfloat16", device="meta"):
        shapes = abstract_init(model.init, rng)   # ShapeDtypeStructs only

    # or get real sharded params directly (each device allocates only its
    # shard — the zero.Init property):
    params = materialize(model.init, rng, shardings=shardings)
"""
import contextlib
import contextvars
from typing import Optional

import jax
import jax.numpy as jnp

_ON_DEVICE: contextvars.ContextVar = contextvars.ContextVar(
    "ds_on_device", default=None)


class OnDevice:
    """Context manager: inside it, ``abstract_init(fn, *args)`` (and model
    inits routed through it) return ShapeDtypeStructs instead of arrays."""

    def __init__(self, dtype=None, device: str = "meta", enabled: bool = True):
        self.dtype = jnp.dtype(dtype) if dtype is not None else None
        self.device = device
        self.enabled = enabled
        self._token = None

    def __enter__(self):
        self._token = _ON_DEVICE.set(self if self.enabled else None)
        return self

    def __exit__(self, *exc):
        _ON_DEVICE.reset(self._token)
        return False


def current_on_device() -> Optional[OnDevice]:
    return _ON_DEVICE.get()


def abstract_init(init_fn, *args, dtype=None):
    """Shapes-only init (the meta-device construction).  Honours an active
    OnDevice context's dtype override."""
    ctx = current_on_device()
    shapes = jax.eval_shape(init_fn, *args)
    dt = dtype or (ctx.dtype if ctx is not None else None)
    if dt is not None:
        shapes = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, dt)
            if jnp.issubdtype(s.dtype, jnp.floating) else s, shapes)
    return shapes


def materialize(init_fn, *args, shardings=None, dtype=None):
    """Materialise params directly into their (sharded) storage — each
    device only ever allocates its own shard, the zero.Init property."""
    def fn(*a):
        out = init_fn(*a)
        if dtype is not None:
            out = jax.tree.map(
                lambda x: x.astype(dtype)
                if jnp.issubdtype(x.dtype, jnp.floating) else x, out)
        return out

    if shardings is not None:
        return jax.jit(fn, out_shardings=shardings)(*args)
    return jax.jit(fn)(*args)
