"""Async-checkpoint overlap bench: steps/s with an in-flight save vs
sync save.

``CKPT_SMOKE=1`` runs a tiny model with short loops — the CPU-smoke
mode the tier-1 ledger round-trip test drives.  With
``DS_BENCH_LEDGER=1`` the result lands in the BENCH/ ledger as a
BenchRecord (ISSUE 13) so ``bench_compare --history`` can gate
step-time regressions."""
import json, os, shutil, sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import deepspeed_tpu
from deepspeed_tpu.models.gpt2 import gpt2_model

SMOKE = bool(int(os.environ.get("CKPT_SMOKE", "0")))

def run(async_save):
    tag_dir = f"/tmp/ckpt_bench_{'async' if async_save else 'sync'}"
    shutil.rmtree(tag_dir, ignore_errors=True)
    if SMOKE:
        import jax
        model = gpt2_model("custom", vocab_size=256, num_layers=2,
                           num_heads=4, d_model=32, max_seq_len=64)
        # batch divisible by the data axis (the CPU harness forces 8
        # host devices)
        mbs, seq, warm, meas = max(2, len(jax.devices())), 32, 1, 2
    else:
        model = gpt2_model("350m", max_seq_len=1024, dtype="bfloat16",
                           remat=True)
        mbs, seq, warm, meas = 12, 1024, 3, 6
    engine, *_ = deepspeed_tpu.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": mbs,
        "gradient_accumulation_steps": 1,
        "optimizer": {"type": "AdamW", "params": {"lr": 1e-4}},
        "bf16": {"enabled": not SMOKE},
        "zero_optimization": {"stage": 0 if SMOKE else 2},
        "checkpoint": {"async_save": bool(async_save)},
        "steps_per_print": 0})
    rng = np.random.default_rng(0)
    def batch():
        return {"input_ids": rng.integers(
            0, model.config.vocab_size,
            size=(1, mbs, seq), dtype=np.int32)}
    # per-step loss/grad_norm summaries ride the record detail
    # (ISSUE 15 satellite): bench_compare --history gates convergence
    # regressions the same way it gates latency ones
    losses, grad_norms = [], []

    def track():
        losses.append(engine.last_metrics.get("loss"))
        grad_norms.append(engine.last_metrics.get("grad_norm"))
    for _ in range(warm):
        loss = engine.train_batch(batch=batch())
    float(loss)
    # baseline steps/s without a save
    t0 = time.time()
    for _ in range(meas):
        loss = engine.train_batch(batch=batch())
        track()
    float(loss); base = (time.time() - t0) / meas

    # save + train while in flight
    t0 = time.time()
    engine.save_checkpoint(tag_dir, tag="t0")
    t_save_call = time.time() - t0
    t0 = time.time()
    for _ in range(meas):
        loss = engine.train_batch(batch=batch())
        track()
    float(loss)
    during = (time.time() - t0) / meas
    # commit barrier (async waits here; sync already durable)
    t0 = time.time()
    engine.wait_pending_checkpoint()
    barrier = time.time() - t0
    mode = "async" if async_save else "sync"
    from scripts.bench_util import mem_peak_fields
    # one fetch for the whole banked set (the numerics idiom)
    import jax
    host = jax.device_get([losses, grad_norms])
    lvals = [float(v) for v in host[0] if v is not None]
    gvals = [float(v) for v in host[1]
             if v is not None and np.isfinite(np.float64(v))]
    detail = {"mode": mode,
              "model": "gpt2:smoke" if SMOKE else "gpt2:350m",
              "baseline_step_s": round(base, 3),
              "save_call_s": round(t_save_call, 3),
              "step_s_during_save": round(during, 3),
              "commit_barrier_s": round(barrier, 3),
              "final_loss": round(lvals[-1], 5) if lvals else None,
              "mean_grad_norm": round(float(np.mean(gvals)), 5)
              if gvals else None,
              **mem_peak_fields()}
    from scripts.bench_util import emit_ledger
    emit_ledger({"metric": f"ckpt_bench_{mode}",
                 "value": round(during, 4), "unit": "s_per_step",
                 "direction": "lower_better", "detail": detail})
    return detail

print(json.dumps(run(async_save=bool(int(os.environ.get("ASYNC", "1"))))))
