"""Black-box observability layer (ISSUE 7 tentpole): flight recorder,
rolling anomaly detection, SLO burn accounting, live ``/debug/*``
introspection, and crash/stall post-mortem bundles — plus the
satellites (bench_compare, serve_bench --json, trace_validate anomaly
checks, MetricsServer debug surface).

The acceptance test at the bottom runs one chaos session — a serving
loop with an injected ``serve.step`` stall under DS_TRACE — and asserts
the watchdog-triggered post-mortem bundle exists, parses, and its
flight-recorder tail reconstructs the stalled request's timeline; that
the trace is validator-clean INCLUDING anomaly instants carrying step
correlation ids; and that ``/debug/requests`` / ``/debug/scheduler``
answer consistently over live HTTP.  A micro-bench asserts flight-
recorder overhead stays under 5% of a 100-step CPU smoke.
"""
import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.config import (ServingConfig, SLOConfig,
                                          TelemetryConfig)
from deepspeed_tpu.serving import ContinuousBatchingScheduler, SamplingParams
from deepspeed_tpu.serving.server import make_server
from deepspeed_tpu.telemetry import (AnomalyMonitor, FlightRecorder,
                                     MetricsRegistry, MetricsServer,
                                     RollingMadDetector, SLOTracker,
                                     configure_tracer, flightrec_payload,
                                     format_thread_stacks, get_tracer,
                                     parse_debug_query, reset_tracer)
from scripts.trace_validate import load_events, validate, validate_anomalies
from tests.util import tiny_gpt2


@pytest.fixture(autouse=True)
def _tracer_isolation():
    reset_tracer()
    yield
    reset_tracer()


@pytest.fixture(autouse=True)
def _postmortem_rate_limit():
    """Every test may write a bundle immediately."""
    from deepspeed_tpu.resilience.postmortem import reset_rate_limit
    reset_rate_limit()
    yield
    reset_rate_limit()


@pytest.fixture(scope="module")
def served():
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    return m, eng


def _prompts(n, seed=0, lo=3, hi=10):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 128, (int(L),)).astype(np.int32)
            for L in rng.integers(lo, hi, n)]


# ------------------------------------------------------- flight recorder
def test_flight_recorder_ring_filters_and_drain():
    fr = FlightRecorder(capacity=8)
    fr.record("req/queue", corr="req-1", prompt_tokens=5)
    fr.record("req/admit", corr="req-1", slot=0)
    fr.record("req/queue", corr="req-2", prompt_tokens=3)
    for i in range(4):
        fr.record("serve/step", corr=f"serve-step-{i}", dur_ms=1.0)
    evs = fr.events()
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    assert fr.timeline(1) == fr.events(corr="req-1")
    assert [e["kind"] for e in fr.timeline(1)] == ["req/queue",
                                                   "req/admit"]
    assert len(fr.events(kind_prefix="serve/")) == 4
    assert len(fr.events(last_n=2)) == 2
    # ring bound: 8-cap, push it over
    for i in range(10):
        fr.record("x")
    assert len(fr.events()) == 8
    assert fr.dropped == fr.total_recorded - 8 > 0
    # jsonl round-trips
    lines = fr.to_jsonl().splitlines()
    assert len(lines) == 8
    assert all(json.loads(ln)["kind"] for ln in lines)
    drained = fr.drain()
    assert len(drained) == 8 and fr.events() == []


def test_flight_recorder_disabled_and_dump(tmp_path):
    off = FlightRecorder(capacity=0)
    off.record("req/queue", corr="req-1")
    assert not off.enabled and off.events() == []
    fr = FlightRecorder(capacity=4)
    fr.record("a", x=1)
    path = fr.dump_jsonl(str(tmp_path / "sub" / "fr.jsonl"))
    rec = json.loads(open(path).read().splitlines()[0])
    assert rec["kind"] == "a" and rec["x"] == 1


def test_flight_recorder_configure_global():
    from deepspeed_tpu.telemetry import (configure_flight_recorder,
                                         get_flight_recorder,
                                         reset_flight_recorder)
    reset_flight_recorder()
    try:
        fr = configure_flight_recorder(16)
        assert get_flight_recorder() is fr and fr.capacity == 16
        off = configure_flight_recorder(0)
        assert not off.enabled and get_flight_recorder() is off
    finally:
        reset_flight_recorder()


# ------------------------------------------------------ anomaly detector
def test_rolling_mad_detector_flags_and_adapts():
    det = RollingMadDetector(window=16, threshold=5.0, min_samples=8)
    rng = np.random.default_rng(0)
    for _ in range(12):
        assert det.observe(0.01 + rng.uniform(0, 1e-4)) is None
    a = det.observe(0.5)
    assert a is not None and a["score"] > 5.0
    assert a["median"] == pytest.approx(0.01, rel=0.1)
    # below min_samples: never flags
    young = RollingMadDetector(window=16, threshold=5.0, min_samples=8)
    for _ in range(7):
        assert young.observe(0.01) is None
    assert young.observe(99.0) is None      # 8th sample, window too young
    # regime change stops alerting once the window adapts
    shifted = RollingMadDetector(window=8, threshold=5.0, min_samples=4)
    for _ in range(8):
        shifted.observe(0.01)
    assert shifted.observe(1.0) is not None
    for _ in range(8):
        shifted.observe(1.0)
    assert shifted.observe(1.0) is None


def test_anomaly_monitor_three_surfaces(tmp_path):
    trace = str(tmp_path / "t.json")
    os.environ.pop("DS_TRACE", None)
    reset_tracer()
    tracer = configure_tracer(trace)
    reg = MetricsRegistry()
    fr = FlightRecorder(capacity=64)
    mon = AnomalyMonitor(registry=reg, flightrec=fr, window=16,
                         threshold=5.0, min_samples=4)
    for i in range(8):
        assert mon.observe("serve.step", 0.01,
                           corr=f"serve-step-{i}") is None
    a = mon.observe("serve.step", 2.0, corr="serve-step-8")
    assert a is not None
    assert reg.get_counter("anomaly/serve.step") == 1
    assert reg.get_gauge("anomaly/last_score", kind="serve.step") > 5
    evs = fr.events(kind_prefix="anomaly/")
    assert len(evs) == 1 and evs[0]["corr"] == "serve-step-8"
    tracer.flush()
    events = load_events(trace)
    assert validate_anomalies(events, require_present=True) == []
    inst = [e for e in events if e["name"] == "anomaly/serve.step"]
    assert inst and inst[0]["args"]["corr"] == "serve-step-8"
    # disabled monitor (threshold 0) never observes
    off = AnomalyMonitor(registry=reg, threshold=0)
    assert off.observe("k", 1e9) is None


def test_trace_validate_anomaly_checks(tmp_path):
    from scripts.trace_validate import main
    ok = [{"name": "anomaly/serve.step", "ph": "i", "ts": 1, "pid": 1,
           "tid": 1, "s": "p",
           "args": {"corr": "serve-step-3", "value": 2.0, "median": 0.01,
                    "mad": 0.001, "score": 9.0}}]
    assert validate_anomalies(ok) == []
    assert validate_anomalies([], require_present=True) != []
    bad_corr = [dict(ok[0], args={**ok[0]["args"], "corr": "req-3"})]
    assert any("corr" in e for e in validate_anomalies(bad_corr))
    no_fields = [dict(ok[0], args={"corr": "train-step-1"})]
    assert any("detector fields" in e
               for e in validate_anomalies(no_fields))
    bad_ph = [dict(ok[0], ph="B"), dict(ok[0], ph="E", ts=2)]
    assert any("instants" in e for e in validate_anomalies(bad_ph))
    # CLI flag: a trace without anomalies fails --check-anomalies
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        json.dump({"traceEvents": [
            {"name": "a", "ph": "B", "ts": 0, "pid": 1, "tid": 1},
            {"name": "a", "ph": "E", "ts": 1, "pid": 1, "tid": 1}]}, f)
    assert main([path, "-q"]) == 0
    assert main([path, "--check-anomalies", "-q"]) == 1


# ----------------------------------------------------------------- SLO
def test_slo_config_roundtrip_and_validation():
    cfg = ServingConfig(slo={
        "enabled": True, "window": 32,
        "classes": {"interactive": {"ttft_ms": 200, "tpot_ms": 40},
                    "batch": {}}})
    assert cfg.slo.enabled and cfg.slo.window == 32
    # "default" always exists as the fallback class
    assert set(cfg.slo.classes) == {"interactive", "batch", "default"}
    assert cfg.slo.classes["interactive"].ttft_ms == 200
    assert ServingConfig().slo.enabled is False
    with pytest.raises(ValueError, match="window"):
        SLOConfig(window=0)
    with pytest.raises(ValueError, match="ttft_ms"):
        SLOConfig(classes={"x": {"ttft_ms": -1}})
    with pytest.raises(ValueError, match="classes"):
        SLOConfig(classes=[1, 2])


def test_slo_tracker_burn_accounting():
    cfg = SLOConfig(enabled=True, window=4,
                    classes={"fast": {"ttft_ms": 100, "tpot_ms": 10}})
    reg = MetricsRegistry()
    t = SLOTracker(cfg, reg)
    # violation on ttft only
    assert t.observe("fast", ttft_s=0.5, tpot_s=0.005) == {"ttft": True}
    # both within target
    assert t.observe("fast", ttft_s=0.05, tpot_s=0.005) == {}
    # unknown class falls back to default (no targets -> no violation)
    assert t.observe("typo", ttft_s=99.0, tpot_s=99.0) == {}
    assert t.resolve_class("typo") == "default"
    assert reg.get_counter("serving/slo_requests", slo_class="fast") == 2
    assert reg.get_counter("serving/slo_ttft_violations",
                           slo_class="fast") == 1
    assert reg.get_gauge("serving/slo_ttft_burn_rate",
                         slo_class="fast") == 0.5
    rates = t.burn_rates()
    assert rates["fast"]["window_requests"] == 2
    assert rates["fast"]["ttft_burn_rate"] == 0.5
    # rolling window: push violations out
    for _ in range(4):
        t.observe("fast", ttft_s=0.01, tpot_s=0.001)
    assert t.burn_rates()["fast"]["ttft_burn_rate"] == 0.0
    # disabled tracker is inert
    off = SLOTracker(SLOConfig(), MetricsRegistry())
    assert off.observe("fast", 99, 99) == {}


# ---------------------------------------------- scheduler integration
def test_scheduler_flight_recorder_lifecycle_and_slo(served):
    m, eng = served
    fr = FlightRecorder(capacity=2048)
    reg = MetricsRegistry()
    cfg = ServingConfig(
        block_size=8, num_blocks=32, max_num_seqs=2,
        slo={"enabled": True,
             "classes": {"strict": {"ttft_ms": 1e-4},
                         "loose": {"ttft_ms": 60000}}})
    sched = ContinuousBatchingScheduler(m, eng.params, cfg, registry=reg,
                                        flightrec=fr)
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=4),
                         slo_class="strict")
            for p in _prompts(2, seed=1)]
    sched.submit(_prompts(1, seed=2)[0], SamplingParams(max_new_tokens=4),
                 slo_class="loose")
    sched.run_until_idle()
    # every request's timeline reconstructs end-to-end
    for r in reqs:
        kinds = [e["kind"] for e in fr.timeline(r.request_id)]
        assert kinds[0] == "req/queue" and kinds[-1] == "req/retire"
        assert "req/admit" in kinds and "req/prefill_chunk" in kinds
        # the strict 0.1 us TTFT target is unmeetable: violation recorded
        assert "req/slo_violation" in kinds
    # step events carry durations and queue/active occupancy
    steps = fr.events(kind_prefix="serve/step")
    assert steps and all("dur_ms" in e and "active" in e for e in steps)
    # SLO surfaces on /metrics through the shared exposition
    text = sched.render_metrics()
    assert 'serving_slo_requests{slo_class="strict"} 2' in text
    assert 'serving_slo_ttft_violations{slo_class="strict"} 2' in text
    assert 'serving_slo_ttft_burn_rate{slo_class="strict"} 1' in text
    assert 'serving_slo_requests{slo_class="loose"} 1' in text
    assert sched.metrics.counters["slo_violations"] == 2
    # debug views agree with final state
    dbg = sched.debug_scheduler()
    assert dbg["slo"]["enabled"] and dbg["slo"]["violations"] == 2
    assert dbg["queue_depth"] == 0
    assert all(s is None for s in dbg["slots"])
    assert dbg["block_pool"]["allocated"] == 0
    assert sched.debug_requests()["active"] == []


def test_scheduler_preempt_resume_flight_events(served):
    """Eviction under pool pressure leaves req/preempt + req/resume on
    the victim's timeline."""
    m, eng = served
    fr = FlightRecorder(capacity=2048)
    # 8 blocks = 7 usable (one trash); each request needs 4 blocks for
    # its 16 tokens, so the pair cannot coexist at full length
    cfg = ServingConfig(block_size=4, num_blocks=8, max_num_seqs=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        registry=MetricsRegistry(),
                                        flightrec=fr)
    rng = np.random.default_rng(3)
    reqs = [sched.submit(rng.integers(1, 128, (6,)).astype(np.int32),
                         SamplingParams(max_new_tokens=10), priority=pr)
            for pr in (0, 1)]
    sched.run_until_idle()
    assert sched.metrics.counters["preemptions"] > 0
    victims = [r for r in reqs
               if any(e["kind"] == "req/preempt"
                      for e in fr.timeline(r.request_id))]
    assert victims
    for v in victims:
        kinds = [e["kind"] for e in fr.timeline(v.request_id)]
        assert "req/resume" in kinds[kinds.index("req/preempt"):]
        assert kinds[-1] == "req/retire"


def test_rejected_requests_never_share_ids_or_timelines(served):
    """A rejected submit must consume its request id: its req/reject
    flight event may not share a req-<id> corr with the next accepted
    request's timeline (review fix)."""
    from deepspeed_tpu.serving.scheduler import RequestTooLongError
    m, eng = served
    fr = FlightRecorder(capacity=256)
    cfg = ServingConfig(block_size=8, num_blocks=16, max_num_seqs=1)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        registry=MetricsRegistry(),
                                        flightrec=fr)
    huge = np.arange(1, 60, dtype=np.int32)
    with pytest.raises(RequestTooLongError):
        sched.submit(huge, SamplingParams(max_new_tokens=200))
    ok = sched.submit(_prompts(1, seed=21)[0],
                      SamplingParams(max_new_tokens=2))
    sched.run_until_idle()
    kinds = [e["kind"] for e in fr.timeline(ok.request_id)]
    assert "req/reject" not in kinds and kinds[-1] == "req/retire"
    rejects = fr.events(kind_prefix="req/reject")
    assert len(rejects) == 1
    assert rejects[0]["corr"] != f"req-{ok.request_id}"


def test_queue_timeout_records_terminal_flight_event(served):
    """A queued request that times out must close its timeline with a
    req/reject (reason=timeout) — not dangle at req/queue (review
    fix)."""
    m, eng = served
    fr = FlightRecorder(capacity=256)
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=1)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        registry=MetricsRegistry(),
                                        flightrec=fr)
    blocker = sched.submit(_prompts(1, seed=22)[0],
                           SamplingParams(max_new_tokens=8))
    doomed = sched.submit(_prompts(1, seed=23)[0],
                          SamplingParams(max_new_tokens=2),
                          timeout_s=1e-6)
    time.sleep(0.01)
    sched.run_until_idle()
    assert doomed.state.value == "rejected"
    kinds = [e["kind"] for e in fr.timeline(doomed.request_id)]
    assert kinds[0] == "req/queue" and kinds[-1] == "req/reject"
    assert blocker.state.value == "finished"


# ------------------------------------------------------ debug endpoints
def test_serve_debug_endpoints_http(served):
    m, eng = served
    fr = FlightRecorder(capacity=256)
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        registry=MetricsRegistry(),
                                        flightrec=fr)
    for p in _prompts(2, seed=4):
        sched.submit(p, SamplingParams(max_new_tokens=3))
    sched.run_until_idle()
    httpd, _loop = make_server(sched, port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        with urllib.request.urlopen(base + "/debug/requests",
                                    timeout=10) as r:
            body = json.loads(r.read())
        assert body["active"] == [] and body["queued"] == []
        assert body["step_count"] == sched.step_count
        with urllib.request.urlopen(base + "/debug/scheduler",
                                    timeout=10) as r:
            dbg = json.loads(r.read())
        assert dbg["step_count"] == sched.step_count
        assert dbg["block_pool"]["num_blocks"] == 32
        assert len(dbg["slots"]) == cfg.max_num_seqs
        assert dbg["health"]["status"] == "starting"   # loop never started
        with urllib.request.urlopen(base + "/debug/stacks",
                                    timeout=10) as r:
            stacks = r.read().decode()
        assert "thread stack dump" in stacks and "MainThread" in stacks
        url = base + "/debug/flightrec?kind=req/&n=4"
        with urllib.request.urlopen(url, timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] and payload["returned"] == 4
        assert all(e["kind"].startswith("req/")
                   for e in payload["events"])
        corr = payload["events"][0]["corr"]
        with urllib.request.urlopen(
                base + f"/debug/flightrec?corr={corr}", timeout=10) as r:
            scoped = json.loads(r.read())
        assert scoped["events"] and all(e["corr"] == corr
                                        for e in scoped["events"])
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/debug/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_metrics_server_healthz_and_debug(monkeypatch):
    """Satellite: the training MetricsServer answers /healthz with the
    ds_serve-shaped JSON body and carries the /debug surface."""
    from deepspeed_tpu.telemetry import (configure_flight_recorder,
                                         reset_flight_recorder)
    reset_flight_recorder()
    fr = configure_flight_recorder(64)
    fr.record("train/step", corr="train-step-1", dur_ms=5.0)
    reg = MetricsRegistry()
    reg.set_gauge("train/mfu", 0.5)
    srv = MetricsServer(reg, port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/healthz", timeout=10) as r:
            assert r.status == 200
            assert r.headers["Content-Type"] == "application/json"
            assert json.loads(r.read()) == {"status": "ok"}
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            assert "train_mfu 0.5" in r.read().decode()
        with urllib.request.urlopen(base + "/debug/stacks",
                                    timeout=10) as r:
            assert "thread stack dump" in r.read().decode()
        with urllib.request.urlopen(base + "/debug/flightrec?corr="
                                    "train-step-1", timeout=10) as r:
            payload = json.loads(r.read())
        assert payload["returned"] == 1
        assert payload["events"][0]["kind"] == "train/step"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.stop()
        reset_flight_recorder()


def test_debug_helpers_unit():
    route, q = parse_debug_query("/debug/flightrec?n=7&corr=req-2&kind=a")
    assert route == "/debug/flightrec"
    assert q == {"n": "7", "corr": "req-2", "kind": "a"}
    fr = FlightRecorder(capacity=8)
    fr.record("a", corr="c-1")
    payload = flightrec_payload(fr, {"n": "bogus"})
    assert payload["returned"] == 1        # bad n falls back to default
    dump = format_thread_stacks()
    assert "MainThread" in dump and "format_thread_stacks" in dump


# -------------------------------------------------- post-mortem bundles
def _read_bundle(path):
    man = json.load(open(os.path.join(path, "manifest.json")))
    fr_lines = [json.loads(ln) for ln in
                open(os.path.join(path, "flightrec.jsonl"))
                if ln.strip()]
    return man, fr_lines


def test_write_postmortem_contents(tmp_path, served):
    from deepspeed_tpu.resilience.postmortem import write_postmortem
    m, eng = served
    fr = FlightRecorder(capacity=256)
    cfg = ServingConfig(block_size=8, num_blocks=32, max_num_seqs=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        registry=MetricsRegistry(),
                                        flightrec=fr)
    req = sched.submit(_prompts(1, seed=5)[0],
                       SamplingParams(max_new_tokens=3))
    sched.run_until_idle()
    path = write_postmortem(str(tmp_path), "test incident",
                            step=sched.step_count, scheduler=sched)
    assert path and os.path.basename(path).startswith("postmortem-step")
    man, fr_lines = _read_bundle(path)
    assert man["reason"] == "test incident"
    for name in ("stacks.txt", "flightrec.jsonl", "metrics.prom",
                 "metrics.json", "scheduler.json", "config.json"):
        assert man["files"][name] is True, (name, man["files"])
    # the request timeline reconstructs from the bundle alone
    tl = [e for e in fr_lines if e.get("corr") == f"req-{req.request_id}"]
    kinds = [e["kind"] for e in tl]
    assert kinds[0] == "req/queue" and kinds[-1] == "req/retire"
    sj = json.load(open(os.path.join(path, "scheduler.json")))
    assert sj["scheduler"]["block_pool"]["num_blocks"] == 32
    metrics = json.load(open(os.path.join(path, "metrics.json")))
    assert metrics.get("serving/completed") == 1
    assert "serving_ttft_s_bucket" in \
        open(os.path.join(path, "metrics.prom")).read()
    cfg_dump = json.load(open(os.path.join(path, "config.json")))
    assert cfg_dump["num_blocks"] == 32


def test_postmortem_rate_limit_and_disable(tmp_path):
    from deepspeed_tpu.resilience.postmortem import (reset_rate_limit,
                                                     write_postmortem)
    assert write_postmortem("", "disabled") is None
    p1 = write_postmortem(str(tmp_path), "first")
    assert p1 is not None
    # immediately after: suppressed by the rate limit
    assert write_postmortem(str(tmp_path), "second") is None
    reset_rate_limit()
    p2 = write_postmortem(str(tmp_path), "third")
    assert p2 is not None and p2 != p1


def test_postmortem_failed_write_returns_rate_limit(tmp_path):
    """A bundle attempt that cannot even create its directory must not
    consume the rate limit — the next trigger (writable again) still
    gets its bundle (review fix)."""
    from deepspeed_tpu.resilience.postmortem import write_postmortem
    blocked = tmp_path / "blocked"
    blocked.write_text("a file, not a dir")     # makedirs will fail
    assert write_postmortem(str(blocked), "doomed") is None
    # immediately after: a healthy dir must succeed, not be suppressed
    assert write_postmortem(str(tmp_path / "ok"), "real incident") \
        is not None


def test_train_postmortem_dir_resolution(tmp_path):
    """resilience.postmortem_dir semantics on the training path:
    None = next to checkpoints, "" = disabled, path = that path
    (review fix)."""
    from deepspeed_tpu.resilience.preemption import _train_postmortem_dir

    class _Cfg:
        postmortem_dir = None

    class _RC:
        resilience_config = _Cfg()

    class _Eng:
        _config = _RC()

    eng = _Eng()
    assert _train_postmortem_dir(eng, "/ckpts") == "/ckpts"
    _Cfg.postmortem_dir = ""
    assert _train_postmortem_dir(eng, "/ckpts") == ""      # disabled
    _Cfg.postmortem_dir = "/custom"
    assert _train_postmortem_dir(eng, "/ckpts") == "/custom"
    assert _train_postmortem_dir(eng, "/ckpts",
                                 override="/x") == "/x"


def test_list_tags_ignores_postmortem_bundles(tmp_path):
    """A checkpoint root holding only a forensic bundle must resolve to
    'no tags' (fresh start), not CheckpointCorruptError."""
    from deepspeed_tpu.resilience.ckpt import find_valid_tag, list_tags
    from deepspeed_tpu.resilience.postmortem import write_postmortem
    root = str(tmp_path / "ckpts")
    os.makedirs(root)
    assert write_postmortem(root, "crash before first save") is not None
    assert list_tags(root) == []
    assert find_valid_tag(root) is None


def test_drain_and_exit_writes_bundle(tmp_path, served):
    """The fatal-signal path: drain_and_exit leaves a bundle next to the
    emergency checkpoint."""
    from deepspeed_tpu.resilience.preemption import drain_and_exit
    from tests.util import base_config, random_batches
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=tiny_gpt2(), config=base_config())
    engine.train_batch(iter(random_batches(1, seed=0)))
    codes = []
    drain_and_exit(engine, str(tmp_path), _exit=codes.append)
    assert codes == [86]
    bundles = [d for d in os.listdir(tmp_path)
               if d.startswith("postmortem-step")]
    assert len(bundles) == 1
    man, fr_lines = _read_bundle(os.path.join(tmp_path, bundles[0]))
    assert "preemption drain" in man["reason"]
    # the engine's train-step flight events rode into the bundle
    assert any(e["kind"] == "train/step" for e in fr_lines)
    # and the emergency checkpoint is still discoverable next to it
    from deepspeed_tpu.resilience.ckpt import find_valid_tag
    assert find_valid_tag(str(tmp_path)).startswith("emergency_step")


# ------------------------------------------------------- bench tooling
def test_bench_compare_direction_and_exit_codes(tmp_path):
    from scripts.bench_compare import (compare, load_metrics,
                                       lower_is_better, main)
    assert lower_is_better("x.cb_ttft_p99_ms")
    assert lower_is_better("x.prefill_tokens")
    assert lower_is_better("ckpt_save_duration_s")
    assert not lower_is_better("gpt2_serve_cb")          # tokens/s value
    assert not lower_is_better("x.hit_rate")
    assert not lower_is_better("x.spec_tokens_per_weight_pass")
    old = str(tmp_path / "old.json")
    new = str(tmp_path / "new.json")
    json.dump({"metric": "m_serve", "value": 100.0,
               "detail": {"ttft_p99_ms": 50.0, "requests": 8}},
              open(old, "w"))
    json.dump({"metric": "m_serve", "value": 80.0,
               "detail": {"ttft_p99_ms": 40.0, "requests": 8}},
              open(new, "w"))
    assert main([old, old, "-q"]) == 0         # self-compare: clean
    assert main([old, new, "-q"]) == 1         # 20% tok/s drop flagged
    rows = compare(load_metrics(old), load_metrics(new), threshold=0.10)
    by = {r["metric"]: r for r in rows}
    assert by["m_serve"]["regressed"]                    # value down 20%
    assert not by["m_serve.ttft_p99_ms"]["regressed"]    # ttft improved
    assert not by["m_serve.requests"]["regressed"]
    # threshold is respected
    rows = compare(load_metrics(old), load_metrics(new), threshold=0.25)
    assert not any(r["regressed"] for r in rows)
    # metric filter + direction override
    rows = compare(load_metrics(old), load_metrics(new),
                   metrics=["ttft"], force_higher=["ttft"])
    assert len(rows) == 1 and rows[0]["regressed"]
    # malformed input -> exit 2
    bad = str(tmp_path / "bad.json")
    open(bad, "w").write("not json {")
    assert main([bad, new, "-q"]) == 2


def test_bench_compare_jsonl_and_flat_inputs(tmp_path):
    from scripts.bench_compare import load_metrics, main
    jl = str(tmp_path / "a.jsonl")
    with open(jl, "w") as f:
        f.write('{"metric": "a", "value": 1.0}\n')
        f.write('{"metric": "b", "value": 2.0, "detail": {"x_ms": 3}}\n')
    assert load_metrics(jl) == {"a": 1.0, "b": 2.0, "b.x_ms": 3.0}
    flat = str(tmp_path / "flat.json")
    json.dump({"tok_s": 10.0, "note": "text ignored"}, open(flat, "w"))
    assert load_metrics(flat) == {"tok_s": 10.0}
    # disjoint metric sets -> exit 2 (nothing comparable)
    assert main([jl, flat, "-q"]) == 2


def test_serve_bench_emit_writes_json(tmp_path, capsys):
    from scripts.serve_bench import emit
    out = str(tmp_path / "r.json")
    rec = {"metric": "m", "value": 1.5, "detail": {"x": 2}}
    emit(rec, out)
    assert json.load(open(out)) == rec
    assert json.loads(capsys.readouterr().out.strip()) == rec


# ------------------------------------------- acceptance: chaos session
def test_chaos_stall_postmortem_and_debug_acceptance(tmp_path,
                                                     monkeypatch, served):
    """ISSUE 7 acceptance: an injected serve.step stall under DS_TRACE
    drives the watchdog to DEGRADED, which writes a post-mortem bundle
    whose flight-recorder tail contains the stalled request's timeline;
    the trace validates clean WITH anomaly instants carrying step corr
    ids; /debug/requests, /debug/scheduler AND /debug/perf answer over
    live HTTP consistently with scheduler state (the perf observatory's
    lock-free debug contract, ISSUE 13 — DS_HBM_GBPS arms real floors
    so perf/achieved_vs_floor is live during the incident)."""
    from deepspeed_tpu.resilience.faults import FaultInjector
    m, eng = served
    trace_path = str(tmp_path / "chaos_trace.json")
    monkeypatch.setenv("DS_TRACE", trace_path)
    monkeypatch.setenv("DS_HBM_GBPS", "819")
    from deepspeed_tpu.telemetry.costmodel import reset_reports
    reset_reports()
    reset_tracer()
    tracer = configure_tracer()
    fr = FlightRecorder(capacity=4096)
    reg = MetricsRegistry()
    cfg = ServingConfig(block_size=8, num_blocks=64, max_num_seqs=2,
                        max_fused_steps=1,
                        slo={"enabled": True,
                             "classes": {"default": {"ttft_ms": 1e-4}}})
    sched = ContinuousBatchingScheduler(
        m, eng.params, cfg, registry=reg,
        injector=FaultInjector("serve.step:stall=1.5@20"),
        flightrec=fr,
        anomaly=AnomalyMonitor(registry=reg, flightrec=fr,
                               min_samples=6, threshold=5.0))
    # warm every compile path (prefill buckets + decode) BEFORE arming
    # the 0.25 s watchdog: first-step compilation reads as a stall,
    # and the false-positive bundle would rate-limit the real one.
    # These warmup steps consume serve.step injector invocations too,
    # but step_count and the fault site tick in lockstep, so the stall
    # still lands at step_count 20 — just fewer steps into the live run.
    for p in _prompts(3, seed=9, lo=4, hi=9):
        sched.submit(p, SamplingParams(max_new_tokens=3))
    sched.run_until_idle()
    pm_dir = str(tmp_path / "pm")
    httpd, loop = make_server(sched, port=0, stall_timeout_s=0.25,
                              postmortem_dir=pm_dir)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_port}"
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=40))
            for p in _prompts(3, seed=9, lo=4, hi=9)]
    loop.start()
    try:
        # wait for the stall-triggered bundle (stall at step 20 lasts
        # 1.5 s; the watchdog flags after 0.25 s of frozen step_count)
        deadline = time.monotonic() + 60
        bundles = []
        while time.monotonic() < deadline:
            if os.path.isdir(pm_dir):
                bundles = [d for d in os.listdir(pm_dir)
                           if d.startswith("postmortem-")]
                if bundles:
                    break
            time.sleep(0.02)
        assert bundles, "watchdog stall produced no post-mortem bundle"
        # scrape /debug/* over live HTTP while the incident is fresh
        with urllib.request.urlopen(base + "/debug/requests",
                                    timeout=10) as r:
            dbg_reqs = json.loads(r.read())
        with urllib.request.urlopen(base + "/debug/scheduler",
                                    timeout=10) as r:
            dbg_sched = json.loads(r.read())
        with urllib.request.urlopen(base + "/debug/stacks",
                                    timeout=10) as r:
            assert "ds-serve-loop" in r.read().decode()
        # /debug/perf answers while the step is wedged (lock-free
        # contract): the cost table + live achieved-vs-floor are there
        with urllib.request.urlopen(base + "/debug/perf",
                                    timeout=10) as r:
            dbg_perf = json.loads(r.read())
        assert dbg_perf["hbm_gbps"] == 819.0
        perf_programs = dbg_perf["programs"]
        assert any(n.startswith("serve/") for n in perf_programs)
        decode_like = [row for n, row in perf_programs.items()
                       if n.startswith(("serve/decode", "serve/window"))
                       and "achieved_vs_floor" in row]
        assert decode_like, perf_programs
        assert all(row["floor_ms"] > 0 for row in decode_like)
        # and the achieved-vs-floor gauge is on the /metrics exposition
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            prom = r.read().decode()
        assert "perf_achieved_vs_floor{" in prom
        assert "perf_floor_ms{" in prom
        # consistency with live scheduler state (racy by design; the
        # structural facts below are stable)
        assert dbg_sched["block_pool"]["num_blocks"] == cfg.num_blocks
        assert len(dbg_sched["slots"]) == cfg.max_num_seqs
        assert dbg_sched["slo"]["enabled"] is True
        known = {r.request_id for r in reqs}
        seen = {q["request_id"]
                for q in dbg_reqs["active"] + dbg_reqs["queued"]}
        assert seen <= known
        live_slots = {s for s in dbg_sched["slots"] if s is not None}
        assert live_slots <= known
        # every request still finishes once the stall clears (the
        # watchdog un-bricks the replica when step_count advances)
        for r in reqs:
            assert r.done.wait(timeout=120)
            assert len(r.output_ids) == 40
    finally:
        loop.shutdown()
        httpd.shutdown()
        httpd.server_close()

    # ---- the bundle reconstructs the faulted request end-to-end ------
    man, fr_lines = _read_bundle(os.path.join(pm_dir, bundles[0]))
    assert "degraded" in man["reason"] and "stalled" in man["reason"]
    assert man["files"]["flightrec.jsonl"] is True
    assert man["files"]["scheduler.json"] is True
    # the bundle carries the perf snapshot (ISSUE 13): a DEGRADED
    # bundle shows whether the wedge was perf collapse
    assert man["files"]["perf.json"] is True
    bundle_perf = json.load(open(
        os.path.join(pm_dir, bundles[0], "perf.json")))
    assert any(n.startswith("serve/") for n in bundle_perf["programs"])
    # the stall hit at step 20, well into decode: at least one request
    # was admitted before it — its timeline must reconstruct from the
    # bundle's flight-recorder tail alone
    stalled = [rid for rid in (r.request_id for r in reqs)
               if any(e.get("corr") == f"req-{rid}"
                      and e["kind"] == "req/admit" for e in fr_lines)]
    assert stalled, "no admitted request in the bundle's flight tail"
    for rid in stalled:
        kinds = [e["kind"] for e in fr_lines
                 if e.get("corr") == f"req-{rid}"]
        assert kinds[0] == "req/queue"
        assert "req/prefill_chunk" in kinds
    # serve/step events up to the stall are in the tail too
    assert any(e["kind"] == "serve/step" for e in fr_lines)
    bundle_sched = json.load(open(
        os.path.join(pm_dir, bundles[0], "scheduler.json")))
    assert bundle_sched["scheduler"]["block_pool"]["num_blocks"] == 64
    assert bundle_sched["requests"]["step_count"] <= sched.step_count

    # ---- validator-clean trace WITH anomaly instants -----------------
    tracer.flush()
    assert validate(trace_path, require_corr=True,
                    check_anomalies=True) == []
    evs = load_events(trace_path)
    anomalies = [e for e in evs
                 if e["name"].startswith("anomaly/serve.step")]
    assert anomalies
    # the stalled step's anomaly carries ITS corr id (the 1.5 s outlier
    # lands on step 20's timeline entry)
    corrs = {e["args"]["corr"] for e in anomalies}
    assert "serve-step-20" in corrs
    # health transition instants joined the same timeline
    assert any(e["name"] == "health/degraded" for e in evs)
    assert any(e["name"] == "postmortem" for e in evs)


def test_flight_recorder_overhead_under_5pct(served):
    """ISSUE 7 acceptance micro-bench: the cost of recording every
    flight event a 100-step CPU smoke generates must stay under 5% of
    that smoke's wall time.  Measured by isolation (re-recording the
    same event mix into a fresh ring) rather than A/B wall clock —
    jitted-step jitter off-TPU dwarfs a sub-5% effect."""
    m, eng = served
    fr = FlightRecorder(capacity=1 << 16)
    # max_num_seqs=1 runs the two 50-token requests back-to-back: a
    # genuine 100-step smoke (side-by-side they'd share ~50 steps)
    cfg = ServingConfig(block_size=8, num_blocks=128, max_num_seqs=1,
                        max_fused_steps=1)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        registry=MetricsRegistry(),
                                        flightrec=fr)
    # warm the compile caches out of the measurement
    sched.submit(_prompts(1, seed=11)[0], SamplingParams(max_new_tokens=4))
    sched.run_until_idle()
    fr.clear()
    before = fr.total_recorded
    t0 = time.perf_counter()
    for p in _prompts(2, seed=12):
        sched.submit(p, SamplingParams(max_new_tokens=52))
    steps = sched.run_until_idle()
    smoke_s = time.perf_counter() - t0
    assert steps >= 100
    n_events = fr.total_recorded - before
    assert n_events >= steps              # at least one event per step
    # replay the same volume of records into a fresh ring, timed alone
    replay = FlightRecorder(capacity=1 << 16)
    t0 = time.perf_counter()
    for i in range(n_events):
        replay.record("serve/step", corr=f"serve-step-{i}",
                      dur_ms=1.234, active=2, queued=0, finished=0)
    record_s = time.perf_counter() - t0
    overhead = record_s / smoke_s
    assert overhead < 0.05, (
        f"flight recorder overhead {overhead:.2%} "
        f"({n_events} events, smoke {smoke_s:.3f}s)")
