"""Autotuner (reference: deepspeed/autotuning/autotuner.py:42 + scheduler +
tuner/{index_based_tuner,model_based_tuner}.py, entered from
launcher/runner.py:358 ``run_autotuning``).

The reference forks ``deepspeed`` jobs per candidate config and scrapes their
metrics.  On TPU a fresh process per trial would pay a full XLA compile each
time with no isolation benefit (no CUDA context to corrupt), so trials run
in-process: build an engine per candidate {zero stage × micro-batch × remat
policy}, run measured steps, rank by throughput.  OOM/compile failures mark
the candidate infeasible, and micro-batch exploration stops growing once a
size fails (the reference's ``max_train_micro_batch_size_per_gpu`` probe).

Outputs the reference's artifact shape: a ranked ``autotuning_results`` list
plus the best config JSON (``autotuning_exps``-style).
"""
import copy
import itertools
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from deepspeed_tpu.utils.logging import log_dist, logger

DEFAULT_STAGES = (0, 1, 2, 3)
DEFAULT_MICRO_BATCHES = (1, 2, 4, 8, 16, 32)
DEFAULT_REMAT = ("nothing", "save_attn", "dots")


@dataclass
class TrialResult:
    config: Dict[str, Any]
    micro_batch: int
    stage: int
    remat: str
    ok: bool
    samples_per_sec: float = 0.0
    step_time_s: float = 0.0
    error: str = ""

    def row(self):
        return {
            "zero_stage": self.stage, "micro_batch": self.micro_batch,
            "remat": self.remat, "ok": self.ok,
            "samples_per_sec": round(self.samples_per_sec, 2),
            "step_time_s": round(self.step_time_s, 4),
            "error": self.error[:200],
        }


class Autotuner:
    """Grid tuner over {zero stage, micro batch, remat policy}."""

    def __init__(self, base_config: dict, model_factory,
                 stages=DEFAULT_STAGES, micro_batches=DEFAULT_MICRO_BATCHES,
                 remat_policies=DEFAULT_REMAT, steps: int = 3,
                 warmup_steps: int = 1, seq_len: Optional[int] = None,
                 results_dir: str = "autotuning_results",
                 tuner_type: str = "gridsearch",
                 tuner_early_stopping: int = 0,
                 isolation: str = "in_process",
                 model_spec: Optional[str] = None,
                 model_kwargs: Optional[dict] = None,
                 trial_timeout_s: float = 900.0):
        """``isolation="subprocess"`` runs every trial as a child process
        (the reference scheduler's contract, scheduler.py:1): a candidate
        that OOM-kills or hard-crashes its process is recorded as
        infeasible and tuning continues.  Requires ``model_spec`` (the
        string form the child re-resolves — a live factory callable
        cannot cross the process boundary).  In-process remains the
        default: on TPU a fresh process pays a full XLA compile per
        trial, and most infeasibilities surface as catchable allocation
        errors — but only the subprocess mode survives hard crashes."""
        self.base_config = dict(base_config)
        self.model_factory = model_factory
        self.isolation = isolation
        self.model_spec = model_spec
        self.model_kwargs = dict(model_kwargs or {})
        self.trial_timeout_s = float(trial_timeout_s)
        if isolation == "subprocess" and not model_spec:
            raise ValueError(
                "isolation='subprocess' needs model_spec (an 'arch:size' "
                "or 'pkg.module:fn' string the child process can resolve)")
        if isolation not in ("in_process", "subprocess"):
            raise ValueError(f"unknown isolation {isolation!r}")
        self.stages = tuple(stages)
        self.micro_batches = tuple(sorted(micro_batches))
        self.remat_policies = tuple(remat_policies)
        self.steps = steps
        self.warmup_steps = warmup_steps
        self.seq_len = seq_len
        self.results_dir = results_dir
        self.tuner_type = tuner_type
        self.tuner_early_stopping = int(tuner_early_stopping)
        self.results: List[TrialResult] = []

    # ------------------------------------------------------------------ trial
    def _candidate_config(self, stage: int, micro_batch: int) -> dict:
        cfg = copy.deepcopy(self.base_config)
        cfg.pop("train_batch_size", None)
        cfg["train_micro_batch_size_per_gpu"] = micro_batch
        cfg.setdefault("gradient_accumulation_steps", 1)
        zo = dict(cfg.get("zero_optimization", {}))
        zo["stage"] = stage
        cfg["zero_optimization"] = zo
        cfg.setdefault("steps_per_print", 0)
        return cfg

    def _run_trial_subprocess(self, stage: int, micro_batch: int,
                              remat: str) -> TrialResult:
        """Launch the candidate as a child job and parse its result line;
        every failure mode (crash, OOM kill, timeout, garbage output)
        becomes an infeasible TrialResult."""
        import subprocess
        import sys
        cfg = self._candidate_config(stage, micro_batch)
        payload = json.dumps({
            "base_config": cfg, "model": self.model_spec,
            "model_kwargs": self.model_kwargs, "stage": stage,
            "micro_batch": micro_batch, "remat": remat,
            "steps": self.steps, "warmup_steps": self.warmup_steps,
            "seq_len": self.seq_len})
        try:
            proc = subprocess.run(
                [sys.executable, "-m",
                 "deepspeed_tpu.autotuning.trial_worker"],
                input=payload, capture_output=True, text=True,
                timeout=self.trial_timeout_s)
        except subprocess.TimeoutExpired:
            return TrialResult(cfg, micro_batch, stage, remat, False,
                               error=f"trial timed out "
                                     f"({self.trial_timeout_s:.0f}s)")
        for line in reversed(proc.stdout.splitlines()):
            if line.startswith("DS_TRIAL_RESULT "):
                try:
                    row = json.loads(line[len("DS_TRIAL_RESULT "):])
                    return TrialResult(
                        cfg, micro_batch, stage, remat, bool(row["ok"]),
                        samples_per_sec=float(row["samples_per_sec"]),
                        step_time_s=float(row["step_time_s"]),
                        error=row.get("error", ""))
                except (ValueError, KeyError) as e:
                    return TrialResult(cfg, micro_batch, stage, remat,
                                       False, error=f"bad result line: {e}")
        tail = (proc.stderr or proc.stdout or "")[-300:]
        return TrialResult(
            cfg, micro_batch, stage, remat, False,
            error=f"trial process died (exit {proc.returncode}): {tail}")

    def _run_trial(self, stage: int, micro_batch: int, remat: str
                   ) -> TrialResult:
        if self.isolation == "subprocess":
            return self._run_trial_subprocess(stage, micro_batch, remat)
        import jax
        import deepspeed_tpu
        from deepspeed_tpu.comm import reset_topology
        cfg = self._candidate_config(stage, micro_batch)
        try:
            reset_topology()
            model = self.model_factory(remat=remat != "nothing",
                                       remat_policy=remat)
            engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
            seq = self.seq_len or getattr(model.config, "max_seq_len", 128)
            vocab = getattr(model.config, "vocab_size", 1024)
            rng = np.random.default_rng(0)
            dp = engine.topology.dp_world_size
            gas = engine.gradient_accumulation_steps()

            def batch():
                return {"input_ids": rng.integers(
                    0, vocab, (gas, micro_batch * dp, seq), dtype=np.int32)}

            for _ in range(self.warmup_steps):
                engine.train_batch(batch=batch())
            t0 = time.time()
            loss = None
            for _ in range(self.steps):
                loss = engine.train_batch(batch=batch())
            jax.block_until_ready(loss)
            dt = (time.time() - t0) / self.steps
            if not np.isfinite(float(loss)):
                raise FloatingPointError("non-finite loss")
            sps = engine.train_batch_size() / dt
            return TrialResult(cfg, micro_batch, stage, remat, True,
                               samples_per_sec=sps, step_time_s=dt)
        except Exception as e:  # OOM / compile failure => infeasible
            return TrialResult(cfg, micro_batch, stage, remat, False,
                               error=f"{type(e).__name__}: {e}")
        finally:
            # drop the trial engine's params/optimizer buffers before the
            # next candidate, or earlier trials' HBM makes later ones OOM
            import gc
            engine = None
            model = None
            gc.collect()

    # ------------------------------------------------------------------ tune
    def _build_cost_model(self):
        from deepspeed_tpu.autotuning.tuner import CostModel
        try:
            probe = self.model_factory(remat=False, remat_policy="nothing")
        except TypeError:
            probe = self.model_factory()
        cfg = getattr(probe, "config", None)
        # through the accelerator abstraction + memory-ledger probe
        # (ISSUE 14 satellite), NOT a raw jax.devices()[0] poke —
        # CPU-degraded probes must behave identically everywhere (the
        # probe itself swallows backend errors and returns {})
        from deepspeed_tpu.telemetry.memory import device_memory_stats
        hbm = device_memory_stats().get("bytes_limit") or None
        if hbm is None:
            # a backend without memory_stats (CPU) degrades to the
            # unbounded cost model — but say so, silently mis-sized
            # search spaces are hard to debug
            logger.debug("autotuner: no device memory stats; "
                         "HBM ceiling disabled")
        n_dev = 1
        try:
            import jax
            n_dev = len(jax.devices())
        except Exception as e:
            logger.debug(f"autotuner: device count probe failed ({e}); "
                         "assuming 1")
        return CostModel(
            n_params=(probe.meta or {}).get("n_params", 0),
            d_model=getattr(cfg, "d_model", 0),
            num_layers=getattr(cfg, "num_layers", 1),
            seq_len=self.seq_len or getattr(cfg, "max_seq_len", 128),
            dp_world=n_dev, hbm_bytes=hbm)

    def tune(self) -> Optional[TrialResult]:
        """Run the candidate space in the configured tuner's order
        (gridsearch | random | model_based); returns the best feasible
        trial (highest samples/sec) and writes ranked results + best
        config JSON.  ``tuner_early_stopping`` > 0 stops after that many
        consecutive non-improving measurements (reference
        model_based_tuner early stopping)."""
        from deepspeed_tpu.autotuning.tuner import (Candidate,
                                                    order_candidates)
        cands = [Candidate(stage, mb, remat)
                 for stage, remat in itertools.product(self.stages,
                                                       self.remat_policies)
                 for mb in self.micro_batches]
        cost_model = (self._build_cost_model()
                      if self.tuner_type == "model_based" else None)
        to_run, pruned = order_candidates(cands, self.tuner_type, cost_model)
        for c in pruned:
            self.results.append(TrialResult(
                self._candidate_config(c.stage, c.micro_batch),
                c.micro_batch, c.stage, c.remat, False,
                error="pruned: cost model predicts out-of-memory"))
        if pruned:
            log_dist(f"autotune: cost model pruned {len(pruned)} "
                     f"sure-OOM candidates", ranks=[0])
        failed_mb = {}       # (stage, remat) -> smallest failing micro batch
        best_sps = 0.0
        since_best = 0
        for c in to_run:
            key = (c.stage, c.remat)
            if key in failed_mb and c.micro_batch >= failed_mb[key]:
                # larger micro batches only cost more memory: skip
                continue
            r = self._run_trial(c.stage, c.micro_batch, c.remat)
            self.results.append(r)
            log_dist(
                f"autotune: stage={c.stage} micro={c.micro_batch} "
                f"remat={c.remat} -> "
                + (f"{r.samples_per_sec:.1f} samples/s" if r.ok
                   else f"FAIL ({r.error[:80]})"), ranks=[0])
            if not r.ok:
                failed_mb[key] = min(c.micro_batch,
                                     failed_mb.get(key, 1 << 30))
                continue
            if r.samples_per_sec > best_sps:
                best_sps = r.samples_per_sec
                since_best = 0
            else:
                since_best += 1
                # a non-improving streak only means "past the peak" under
                # the cost model's best-first ordering (reference couples
                # early stopping with the model-based tuner)
                if (self.tuner_early_stopping
                        and self.tuner_type == "model_based"
                        and since_best >= self.tuner_early_stopping):
                    log_dist(
                        f"autotune: early stop after {since_best} "
                        f"non-improving trials", ranks=[0])
                    break
        best = self.best()
        self._write_results(best)
        return best

    def best(self) -> Optional[TrialResult]:
        ok = [r for r in self.results if r.ok]
        return max(ok, key=lambda r: r.samples_per_sec) if ok else None

    def _write_results(self, best: Optional[TrialResult]):
        os.makedirs(self.results_dir, exist_ok=True)
        with open(os.path.join(self.results_dir, "results.json"), "w") as f:
            json.dump([r.row() for r in self.results], f, indent=2)
        if best is not None:
            cfg = dict(best.config)
            cfg["zero_optimization"]["stage"] = best.stage
            cfg["_autotuning"] = {"remat_policy": best.remat,
                                  "samples_per_sec": best.samples_per_sec}
            with open(os.path.join(self.results_dir, "best_config.json"),
                      "w") as f:
                json.dump(cfg, f, indent=2)
            log_dist(
                f"autotune: best = stage {best.stage}, micro "
                f"{best.micro_batch}, remat {best.remat} "
                f"({best.samples_per_sec:.1f} samples/s) -> "
                f"{self.results_dir}/best_config.json", ranks=[0])


def resolve_model_factory(spec: str, model_kwargs: Optional[dict] = None):
    """Model spec -> factory(remat=..., remat_policy=...) -> Model.

    Accepted specs (reference autotuning tunes the USER's model; here the
    functional equivalent is a factory the config names):

    - ``"<arch>:<size>"`` — in-tree registry: ``gpt2:125m``, ``llama:7b``,
      ``mixtral:tiny``, ``bert:large`` (+ per-arch **model_kwargs**).
    - ``"pkg.module:fn"`` — an importable entry point returning a Model
      (called with remat/remat_policy plus **model_kwargs**).
    - ``"<size>"`` — bare GPT-2 size (backwards compatible).
    """
    model_kwargs = dict(model_kwargs or {})
    if ":" in spec:
        arch, _, rest = spec.partition(":")
        from deepspeed_tpu import models as _m
        registry = {"gpt2": _m.gpt2_model, "llama": _m.llama_model,
                    "mixtral": _m.mixtral_model, "bert": _m.bert_model,
                    "neox": _m.neox_model, "bloom": _m.bloom_model,
                    "gptneo": _m.gptneo_model}
        if arch in registry:
            fn, size = registry[arch], rest
            return lambda **kw: fn(size, **{**model_kwargs, **kw})
        # entry point "pkg.module:fn"
        import importlib
        mod = importlib.import_module(arch)
        entry = getattr(mod, rest)
        return lambda **kw: entry(**{**model_kwargs, **kw})
    from deepspeed_tpu.models import gpt2_model
    from deepspeed_tpu.models.gpt2 import GPT2_SIZES
    if spec not in GPT2_SIZES:
        raise ValueError(
            f"autotuning model spec {spec!r} is neither a known gpt2 size "
            f"({sorted(GPT2_SIZES)}) nor an 'arch:size'/'pkg.module:fn' "
            "spec")
    return lambda **kw: gpt2_model(spec, **{**model_kwargs, **kw})


def tune_from_config(base: dict) -> Optional[TrialResult]:
    """Tune per the config's ``autotuning`` section (the single path both
    the ``deepspeed --autotuning`` launcher entry and ``ds_autotune``
    use)."""
    base = dict(base)
    tuning = base.pop("autotuning", {})
    factory = resolve_model_factory(tuning.get("model", "125m"),
                                    tuning.get("model_kwargs"))
    tuner = Autotuner(
        base, factory,
        stages=tuning.get("stages", DEFAULT_STAGES),
        micro_batches=tuning.get("micro_batches", DEFAULT_MICRO_BATCHES),
        remat_policies=tuning.get("remat_policies", DEFAULT_REMAT),
        steps=int(tuning.get("steps", 3)),
        seq_len=tuning.get("seq_len"),
        results_dir=tuning.get("results_dir", "autotuning_results"),
        tuner_type=tuning.get("tuner_type", "gridsearch"),
        tuner_early_stopping=int(tuning.get("tuner_early_stopping", 0)),
        isolation=tuning.get("trial_isolation", "in_process"),
        model_spec=tuning.get("model", "125m"),
        model_kwargs=tuning.get("model_kwargs"),
        trial_timeout_s=float(tuning.get("trial_timeout_s", 900)))
    return tuner.tune()


def run_autotuning(args):
    """Launcher entry (reference runner.py:358): tune for the user script's
    config, then print the best config path.  The user script is expected to
    read the emitted best_config.json."""
    config_path = None
    for i, a in enumerate(args.user_args):
        if a in ("--deepspeed_config", "--config") and i + 1 < len(args.user_args):
            config_path = args.user_args[i + 1]
    if config_path is None or not os.path.isfile(config_path):
        raise RuntimeError(
            "autotuning needs --deepspeed_config <file> among the user args")
    with open(config_path) as f:
        base = json.load(f)
    best = tune_from_config(base)
    return 0 if best is not None else 1
