"""Streamed parameter shards over the SwapEngine (ISSUE 17 tentpole).

:class:`ParamStore` is the policy client that completes the
reference's ``zero/partitioned_param_swapper.py`` design on the TPU
stack: the model's per-layer param shards live as SwapEngine keys
(``param/L0007`` — bf16/fp16 payloads, quantized leaves kept
quantized), only a **K-layer working set** stays materialized in host
RAM, and the weight pass runs through a double-buffered prefetch
pipeline — :meth:`get_layer` submits the *next* layer's NVMe read
before completing the current one, in either direction (forward pass
prefetches ``k+1``, the backward sweep prefetches ``k-1``).

Policy contracts owned here (mirroring the KV-tiering client,
``serving/kv_tiering.py``):

- the ``param.swap`` fault site fires on every shard read and
  write-back (deny = failed I/O; stall = delayed I/O; truncate = a
  torn NVMe shard; corrupt = a size-preserving bit-flip only the
  engine's payload checksum can see — ISSUE 18).  A failed, torn, or
  corrupt read NEVER reaches a matmul: it degrades to a synchronous
  rebuild through ``reload_fn`` (the host optimizer's fp32 masters
  are the authoritative copy) and heals the on-disk shard — the heal
  ``put`` clears the engine's quarantine record — or raises loudly
  when no rebuild source exists.
- the engine's NVMe circuit breaker (ISSUE 18) gates write-backs by
  policy: while it refuses traffic the shard stays resident+dirty
  (the same retention deny uses), so training continues host-only
  until the tier heals.
- pin/protect semantics (the KV livelock fixes): the current compute
  layer and the prefetch target are never evicted from the working
  set, and a layer whose write-back was denied stays resident
  (``dirty``) until a later write-back succeeds — capacity pressure
  can overshoot K, it cannot corrupt or lose a shard.
- clean evictions are free: shards are read with
  ``fetch(keep=True)``, so dropping a resident copy needs no
  write-back (the payload file is still valid).
- the tiered ledger prices both sides: the engine attributes shard
  bytes on NVMe/host under the ``params_nvme`` owner row (per-key
  ``owner=``), and the store accounts its resident working-set copies
  under ``params_resident``.  Allocation failures in this path call
  ``record_alloc_failure`` so a too-big model produces a
  ``memory.json`` bundle naming the tier/owner, not a bare traceback.

Flight-recorder kinds (the ``param/`` family): ``param/swap_fail``
(a param.swap fault or I/O error on a shard), ``param/degraded`` (a
shard was rebuilt synchronously from the fp32 masters).

Prefetch overlap is *measured*, not asserted: the store counts reads
satisfied by an already-in-flight prefetch vs synchronous misses and
the wall-clock it spent blocked in ``fetch`` —
:meth:`overlap_fraction` feeds the ``offload/param_prefetch_overlap``
gauge and the ``scripts/offload_bench.py`` ledger record.
"""
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional

import numpy as np

from deepspeed_tpu.resilience.faults import NULL_INJECTOR

__all__ = ["ParamStore", "SwapTensorClient"]


def _ledger_set(tier: str, owner: str, nbytes: int, **detail):
    """Best-effort ledger row update (never fails a param access)."""
    try:
        from deepspeed_tpu.telemetry.memory import (get_memory_ledger,
                                                    memory_enabled)
        if memory_enabled():
            get_memory_ledger().set_bytes(tier, owner, nbytes, **detail)
    except Exception:  # dslint: disable=DSL005 -- best-effort telemetry tap; a ledger hiccup must never fail a param access
        pass


def _record_alloc_failure(site: str, flightrec=None, **detail):
    """OOM forensics tap (ISSUE 17 satellite): a MemoryError in the
    param/offload path snapshots the ledger into the forensics ring so
    the post-mortem ``memory.json`` names the tier/owner at failure."""
    try:
        from deepspeed_tpu.telemetry.memory import get_memory_ledger
        get_memory_ledger().record_alloc_failure(
            site, flightrec=flightrec, **detail)
    except Exception:  # dslint: disable=DSL005 -- forensics are best-effort; the original MemoryError is re-raised by the caller
        pass


class SwapTensorClient:
    """AsyncTensorSwapper-compatible view of a SwapEngine.

    The HostOffloadOptimizer's hand-rolled ``swap_tensor`` prefetch
    loop (``runtime/zero/offload.py``) migrates onto the SwapEngine
    through this duck-typed adapter — same ``swap_out`` / ``prefetch``
    / ``swap_in`` / ``drain`` surface, but the I/O rides the SAME
    read/write rings (and queue-depth window) as the param shards, so
    one budget governs both streams.  ``swap_in`` reads with
    ``keep=True``: the payload file stays valid on disk, preserving
    the optimizer's read-only ``_get_master`` contract."""

    def __init__(self, engine, owner: str = "optim_nvme"):
        self.engine = engine
        self.owner = owner
        self.swap_dir = engine.nvme_dir

    def swap_out(self, name: str, arr: np.ndarray):
        self.engine.put(name, [np.ascontiguousarray(arr)], tier="nvme",
                        owner=self.owner)

    def prefetch(self, name: str):
        self.engine.prefetch(name)

    def swap_in(self, name: str) -> np.ndarray:
        return self.engine.fetch(name, keep=True)[0]

    def drain(self):
        self.engine.drain()


class ParamStore:
    """K-layer resident working set over SwapEngine-held layer shards.

    Single-threaded by contract (the train loop / serving scheduler
    already serializes access), like the engine beneath it."""

    def __init__(self, engine, num_layers: int, resident_layers: int = 2,
                 injector=None, flightrec=None, owner: str = "params_nvme",
                 reload_fn: Optional[Callable] = None):
        self.engine = engine
        self.num_layers = int(num_layers)
        self.resident_layers = max(1, int(resident_layers))
        self.injector = injector or NULL_INJECTOR
        self.flightrec = flightrec
        self.owner = owner
        self.resident_owner = "params_resident"
        #: i -> layer pytree rebuilt from masters when a read fails
        self.reload_fn = reload_fn
        self.treedef = None
        #: working set: layer index -> list of leaf arrays (LRU order)
        self._resident: "OrderedDict[int, List[np.ndarray]]" = OrderedDict()
        self._resident_bytes = 0
        #: layers whose write-back was denied — never evicted until a
        #: later write-back succeeds
        self._dirty = set()
        #: client pins (protect semantics beyond the per-call window)
        self._pinned = set()
        # --- measured pipeline counters (gauges/bench, never asserted)
        self.resident_hits = 0     # get_layer satisfied from the working set
        self.prefetch_hits = 0     # engine read was already in flight
        self.sync_misses = 0       # fetch had to submit + block
        self.failures = 0          # param.swap faults / I/O errors
        self.degraded = 0          # shards rebuilt from the fp32 masters
        self.fetch_block_s = 0.0   # wall-clock blocked inside fetch
        self.put_bytes = 0
        self.fetch_bytes = 0

    # ------------------------------------------------------------ helpers
    def _key(self, i: int) -> str:
        return f"param/L{i:04d}"

    def _flatten(self, tree):
        import jax
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        if self.treedef is None:
            self.treedef = treedef
        return [np.asarray(a) for a in leaves]

    def _unflatten(self, leaves):
        import jax
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def _account_resident(self):
        _ledger_set("host", self.resident_owner, self._resident_bytes,
                    layers=len(self._resident),
                    budget_layers=self.resident_layers)

    def _insert_resident(self, i: int, leaves: List[np.ndarray],
                         protect=()):
        if i in self._resident:
            old = self._resident.pop(i)
            self._resident_bytes -= sum(int(a.nbytes) for a in old)
        self._resident[i] = leaves
        self._resident_bytes += sum(int(a.nbytes) for a in leaves)
        self._evict(protect=set(protect) | {i})
        self._account_resident()

    def _evict(self, protect=frozenset()):
        """Shrink the working set back to K.  Pinned, protected and
        dirty layers are skipped — over-budget beats a lost shard or
        the prefetch-target livelock the KV tier hit."""
        candidates = [j for j in self._resident
                      if j not in protect and j not in self._pinned]
        for j in candidates:
            if len(self._resident) <= self.resident_layers:
                return
            if j in self._dirty and not self._writeback(j):
                continue                      # still dirty: keep resident
            dropped = self._resident.pop(j)
            self._resident_bytes -= sum(int(a.nbytes) for a in dropped)

    def _writeback(self, i: int) -> bool:
        """Fault-gated shard write (put or heal).  False = denied; the
        caller keeps the resident copy dirty."""
        leaves = self._resident[i]
        if self.injector.deny("param.swap"):
            self.failures += 1
            if self.flightrec is not None:
                self.flightrec.record("param/swap_fail", layer=i, dir="out")
            self._dirty.add(i)
            return False
        nbytes = int(sum(a.nbytes for a in leaves))
        keep = self.injector.truncate_bytes("param.swap", nbytes)
        corrupt = self.injector.corrupt_bytes("param.swap", nbytes)
        if not self.engine.nvme_allowed():
            # breaker refuses the tier: retain resident+dirty (the deny
            # retention) — a later write-back probes/heals
            self._dirty.add(i)
            return False
        try:
            self.engine.put(self._key(i), leaves, tier="nvme",
                            truncate=keep, owner=self.owner,
                            corrupt=corrupt)
        except MemoryError:
            _record_alloc_failure("param.swap", flightrec=self.flightrec,
                                  layer=i, owner=self.owner, nbytes=nbytes)
            raise
        self.put_bytes += nbytes
        self._dirty.discard(i)
        return True

    # ------------------------------------------------------------- writes
    def put_layer(self, i: int, tree):
        """Store layer ``i``'s shard: resident copy + fire-and-forget
        engine write on the write ring.  ``tree`` may be a pytree or an
        already-flat leaf list in treedef order (the optimizer sink)."""
        if isinstance(tree, list):
            leaves = [np.asarray(a) for a in tree]
        else:
            leaves = self._flatten(tree)
        try:
            leaves = [np.ascontiguousarray(a) for a in leaves]
        except MemoryError:
            _record_alloc_failure("param.store", flightrec=self.flightrec,
                                  layer=i, owner=self.owner)
            raise
        self._resident.pop(i, None)
        self._insert_resident(i, leaves)
        self._writeback(i)

    # -------------------------------------------------------------- reads
    def prefetch_layer(self, i: int):
        """Submit the async read for layer ``i`` (no-op when resident,
        out of range, or host-tier)."""
        if 0 <= i < self.num_layers and i not in self._resident:
            self.engine.prefetch(self._key(i))

    def get_layer(self, i: int, direction: int = 1):
        """Layer ``i``'s shard as a pytree, double-buffered: the read
        for ``i + direction`` is submitted before this one completes,
        so layer-k compute overlaps the layer-k±1 NVMe read."""
        if not 0 <= i < self.num_layers:
            raise IndexError(f"layer {i} out of range 0..{self.num_layers - 1}")
        nxt = i + direction
        self.prefetch_layer(nxt)
        if i in self._resident:
            self.resident_hits += 1
            self._resident.move_to_end(i)
            return self._unflatten(self._resident[i])
        leaves = self._fetch(i)
        self._insert_resident(i, leaves,
                              protect={nxt} if 0 <= nxt < self.num_layers
                              else ())
        return self._unflatten(leaves)

    def _fetch(self, i: int) -> List[np.ndarray]:
        """One fault-gated shard read; degrades to the synchronous
        master rebuild — torn bytes never reach a matmul."""
        key = self._key(i)
        overlapped = key in self.engine.inflight_reads()
        denied = self.injector.deny("param.swap")
        t0 = time.perf_counter()
        leaves = None
        if not denied:
            try:
                leaves = self.engine.fetch(key, keep=True)
            except MemoryError:
                _record_alloc_failure("param.swap",
                                      flightrec=self.flightrec, layer=i,
                                      owner=self.owner, dir="in")
                raise
            except (IOError, OSError, KeyError) as e:
                self.failures += 1
                if self.flightrec is not None:
                    self.flightrec.record("param/swap_fail", layer=i,
                                          dir="in",
                                          error=f"{type(e).__name__}: {e}")
        else:
            self.failures += 1
            if self.flightrec is not None:
                self.flightrec.record("param/swap_fail", layer=i, dir="in",
                                      error="param.swap deny")
        self.fetch_block_s += time.perf_counter() - t0
        if leaves is not None:
            if overlapped:
                self.prefetch_hits += 1
            else:
                self.sync_misses += 1
            self.fetch_bytes += int(sum(a.nbytes for a in leaves))
            return leaves
        # degrade: rebuild from the authoritative fp32 masters and heal
        # the on-disk shard; loud failure when no rebuild source exists
        if self.reload_fn is None:
            raise IOError(
                f"param shard {key} unreadable and no reload source — "
                "refusing to step against missing/torn weights")
        leaves = self._flatten(self.reload_fn(i))
        self.degraded += 1
        self.sync_misses += 1
        if self.flightrec is not None:
            self.flightrec.record("param/degraded", layer=i)
        self._resident[i] = leaves       # transient; _insert accounts
        self._resident_bytes += sum(int(a.nbytes) for a in leaves)
        self._writeback(i)
        dropped = self._resident.pop(i)
        self._resident_bytes -= sum(int(a.nbytes) for a in dropped)
        return leaves

    # ------------------------------------------------------------ control
    def pin(self, i: int):
        self._pinned.add(i)

    def unpin(self, i: int):
        self._pinned.discard(i)

    def flush(self):
        """Re-attempt dirty write-backs and drain the rings (checkpoint
        / shutdown barrier).  Layers still denied stay resident+dirty."""
        for i in list(self._dirty):
            self._writeback(i)
        self.engine.drain()

    # ------------------------------------------------------------- gauges
    def overlap_fraction(self) -> float:
        """Fraction of I/O reads satisfied by an in-flight prefetch
        (resident hits excluded — they moved no bytes)."""
        io = self.prefetch_hits + self.sync_misses
        return self.prefetch_hits / io if io else 0.0

    def publish(self, registry):
        """Mirror the pipeline counters into the shared metrics
        registry (the engine's per-step gauge pass)."""
        registry.set_gauge("offload/param_prefetch_overlap",
                           self.overlap_fraction())
        registry.set_gauge("offload/param_resident_layers",
                           float(len(self._resident)))
        registry.set_counter("offload/param_swap_failures",
                             float(self.failures))
        registry.set_counter("offload/param_degraded_reads",
                             float(self.degraded))
        registry.set_counter("offload/param_fetch_block_s",
                             float(self.fetch_block_s))
