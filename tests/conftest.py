"""Test harness: simulate an 8-device TPU mesh on CPU (the reference's
DistributedTest multi-process harness, tests/unit/common.py:102, becomes a
virtual multi-device single process under XLA's host-platform device count)."""
import os

# must run before jax initialises its backends (the outer environment pins
# JAX_PLATFORMS to the real TPU platform; tests always run on the virtual
# CPU mesh)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_backend_optimization_level" not in _flags:
    # tests measure correctness, not codegen quality: backend opt level 0
    # cuts CPU compile time ~33% on this suite (compile-bound on 1 core)
    _flags = (_flags + " --xla_backend_optimization_level=0").strip()
if "xla_cpu_use_thunk_runtime" not in _flags:
    # this jaxlib's new CPU thunk runtime corrupts the glibc heap under
    # the engine's donated train steps with torch loaded in-process
    # ("corrupted size vs. prev_size" → SIGSEGV kills the whole pytest
    # run at a random later test); the legacy runtime is stable
    _flags = (_flags + " --xla_cpu_use_thunk_runtime=false").strip()
os.environ["XLA_FLAGS"] = _flags

import jax  # noqa: E402
import pytest  # noqa: E402

# jax may already be imported by a sitecustomize with the platform config frozen
# from the outer env; override it before any backend initialises.
jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: the suite compiles many near-identical
# engine steps on the virtual CPU mesh; caching keeps the full-suite wall
# time inside the driver's budget (and repeat runs mostly free)
jax.config.update("jax_compilation_cache_dir", "/tmp/ds_tpu_test_jax_cache")
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)


@pytest.fixture(autouse=True)
def _reset_topology():
    from deepspeed_tpu.comm import reset_topology
    reset_topology()
    yield
    reset_topology()


@pytest.fixture
def devices8():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 simulated devices, got {len(devs)}"
    return devs
