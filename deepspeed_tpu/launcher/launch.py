"""Per-node launcher (reference: deepspeed/launcher/launch.py:132).

The reference forks one process per local GPU, assigning
RANK/LOCAL_RANK/MASTER_ADDR to each.  On TPU the JAX runtime owns all local
chips from one process, so this launcher starts exactly **one** worker process
per host and exports the JAX coordination triplet
(COORDINATOR_ADDRESS / NPROC / PROCESS_ID) that
``deepspeed_tpu.comm.init_distributed`` consumes for the
``jax.distributed.initialize`` rendezvous over DCN.

Signal handling and child-tree cleanup mirror the reference
(``terminate_process_tree``, launch.py:118): SIGINT/SIGTERM forwarded to the
worker, non-zero worker exit propagates to the launcher's exit code.
"""
import argparse
import os
import signal
import subprocess
import sys
import time

from deepspeed_tpu.utils.logging import logger


def parse_args(args=None):
    parser = argparse.ArgumentParser(
        description="deepspeed_tpu per-node launcher")
    parser.add_argument("--coordinator_address", type=str, required=True,
                        help="host:port of the rank-0 JAX coordinator")
    parser.add_argument("--nnodes", type=str, default="1",
                        help="total number of hosts in the job, or 'auto' to "
                             "read it from the MPI/SLURM environment")
    parser.add_argument("--node_rank", type=str, default="0",
                        help="this host's index in [0, nnodes), or 'auto' to "
                             "read it from the MPI/SLURM environment")
    parser.add_argument("--module", action="store_true",
                        help="run the user script as a python module "
                             "(python -m)")
    parser.add_argument("--no_python", action="store_true",
                        help="exec the user script directly, without the "
                             "python interpreter")
    parser.add_argument("--save_pid", type=str, default="",
                        help="write the launcher pid to this file")
    parser.add_argument("user_script", type=str)
    parser.add_argument("user_args", nargs=argparse.REMAINDER)
    return parser.parse_args(args=args)


_RANK_ENV = ("OMPI_COMM_WORLD_RANK", "PMIX_RANK", "PMI_RANK", "SLURM_PROCID")
_SIZE_ENV = ("OMPI_COMM_WORLD_SIZE", "PMI_SIZE", "SLURM_NTASKS")


def _resolve(value, env, candidates, what):
    """Resolve an int or 'auto' (from the launching MPI/SLURM env)."""
    if value != "auto":
        return int(value)
    for var in candidates:
        if var in env:
            return int(env[var])
    raise RuntimeError(
        f"launch: --{what}=auto but none of {candidates} is set — "
        f"not running under mpirun/srun?")


def build_worker_env(args, base_env=None):
    """The env the single per-host worker runs under."""
    env = dict(os.environ if base_env is None else base_env)
    node_rank = _resolve(args.node_rank, env, _RANK_ENV, "node_rank")
    nnodes = _resolve(args.nnodes, env, _SIZE_ENV, "nnodes")
    env["COORDINATOR_ADDRESS"] = args.coordinator_address
    env["NPROC"] = str(nnodes)
    env["PROCESS_ID"] = str(node_rank)
    # reference-compatible aliases (torch-style naming) so user scripts that
    # read RANK/WORLD_SIZE keep working
    env["RANK"] = str(node_rank)
    env["WORLD_SIZE"] = str(nnodes)
    addr, _, port = args.coordinator_address.partition(":")
    env["MASTER_ADDR"] = addr
    env["MASTER_PORT"] = port or "29500"
    env.setdefault("PYTHONUNBUFFERED", "1")
    return env


def build_worker_cmd(args):
    if args.no_python:
        cmd = [args.user_script]
    elif args.module:
        cmd = [sys.executable, "-u", "-m", args.user_script]
    else:
        cmd = [sys.executable, "-u", args.user_script]
    return cmd + list(args.user_args)


def terminate_process_tree(proc: subprocess.Popen, timeout: float = 30.0):
    """SIGTERM then SIGKILL the worker's process group (reference
    launch.py:118)."""
    if proc.poll() is not None:
        return
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
    except (ProcessLookupError, PermissionError):
        return
    deadline = time.time() + timeout
    while time.time() < deadline:
        if proc.poll() is not None:
            return
        time.sleep(0.1)
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass


def main(args=None):
    args = parse_args(args)
    if args.save_pid:
        with open(args.save_pid, "w") as f:
            f.write(str(os.getpid()))

    env = build_worker_env(args)
    cmd = build_worker_cmd(args)
    logger.info(f"launch: node {args.node_rank}/{args.nnodes} "
                f"coordinator={args.coordinator_address} cmd={cmd}")

    proc = subprocess.Popen(cmd, env=env, start_new_session=True)

    def _forward(signum, frame):
        logger.info(f"launch: forwarding signal {signum} to worker")
        terminate_process_tree(proc)
        sys.exit(128 + signum)

    signal.signal(signal.SIGINT, _forward)
    signal.signal(signal.SIGTERM, _forward)

    rc = proc.wait()
    if rc != 0:
        logger.error(f"launch: worker exited with code {rc}")
    sys.exit(rc)


if __name__ == "__main__":
    main()
