"""Perf observatory: cost model, roofline, bench ledger (ISSUE 13).

The load-bearing contracts:
- the jaxpr cost walk counts dot FLOPs execution-weighted (scan trip
  counts, pallas grids) and pallas launch SITES (the PR 12 recursion as
  a shared API), structurally on CPU via interpret mode;
- costmodel-derived byte floors at the bench shapes match PERF.md's
  hand-computed ``weights_floor_int8`` / ``weights_floor_moe`` values
  within 2% — computed from shape-only abstract trees, no 741 MB of
  params materialized;
- roofline floors resolve ONLY where a device rate is known
  (DS_HBM_GBPS is the CPU test override; no fictitious floors), and
  ``perf/achieved_vs_floor`` lands on /metrics and /debug/perf;
- the bench ledger round-trips: bench script → BENCH/ledger.jsonl
  BenchRecord → history-aware bench_compare, which exits 1 on a >10%
  synthetic regression and 2 on a cross-device or cross-model diff.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax

import deepspeed_tpu
from deepspeed_tpu.telemetry import MetricsRegistry
from deepspeed_tpu.telemetry.costmodel import (abstract_quantized_blocks,
                                               analyze_fn,
                                               costmodel_enabled,
                                               count_pallas_launches,
                                               param_stream_bytes,
                                               register_report,
                                               reset_reports)
from deepspeed_tpu.telemetry import costmodel, roofline
from tests.util import base_config, random_batches, tiny_gpt2

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _fresh_reports():
    reset_reports()
    yield
    reset_reports()


# ------------------------------------------------------------ jaxpr walk
def test_dot_flops_counted():
    def fn(x, w):
        return x @ w

    r = analyze_fn(fn, jnp.ones((4, 8)), jnp.ones((8, 16)), name="dot")
    assert r.flops == 2 * 4 * 16 * 8
    # boundary-byte fallback: inputs + outputs, dtype-aware
    assert r.hbm_bytes == 4 * (4 * 8 + 8 * 16 + 4 * 16)
    assert r.detail["hbm_bytes_source"] == "program_boundary_upper_bound"


def test_scan_multiplies_flops():
    w = jnp.ones((8, 8))

    def step(c, _):
        return c @ w, ()

    def fn(c):
        out, _ = lax.scan(step, c, None, length=5)
        return out

    r = analyze_fn(fn, jnp.ones((4, 8)), name="scan")
    assert r.flops == 5 * 2 * 4 * 8 * 8


def test_explicit_hbm_bytes_and_registry():
    r = analyze_fn(lambda x: x * 2, jnp.ones((4,)), name="prog",
                   hbm_bytes=12345, detail={"model": "m"})
    assert r.hbm_bytes == 12345
    assert r.detail["hbm_bytes_source"] == "param_stream"
    register_report(r)
    assert costmodel.get_report("prog").hbm_bytes == 12345
    assert "prog" in costmodel.get_reports()


def test_costmodel_env_resolution(monkeypatch):
    monkeypatch.delenv("DS_PERF_COSTMODEL", raising=False)
    assert costmodel_enabled()
    assert not costmodel_enabled(False)
    monkeypatch.setenv("DS_PERF_COSTMODEL", "0")
    assert not costmodel_enabled(True)
    monkeypatch.setenv("DS_PERF_COSTMODEL", "1")
    assert costmodel_enabled(False)


# --------------------------------------- structural launch/byte contracts
def test_qgemm_path_counts_launches(monkeypatch):
    """ds_qgemm (interpret) traces as >= 1 pallas launch site; the
    plain composition traces as zero (satellite: the PR 12 counter as a
    shared API over the quantized GEMM path)."""
    monkeypatch.setenv("DS_QGEMM_INTERPRET", "1")
    from deepspeed_tpu.ops.pallas.qgemm import ds_qgemm
    from deepspeed_tpu.ops.pallas.quantization import block_quantize_int8
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    q, s = block_quantize_int8(w, block=16)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda a: ds_qgemm(a, q, s, out_dtype=jnp.float32))(x)
    assert count_pallas_launches(jaxpr) >= 1
    jaxpr_plain = jax.make_jaxpr(lambda a: a @ w)(x)
    assert count_pallas_launches(jaxpr_plain) == 0


def test_grouped_gemm_slot_kernel_launches_and_bytes(monkeypatch):
    """Decode-regime slot kernels: the traced program carries >= 1
    launch site, and the distinct-expert byte floor over the stacked
    int8 expert tree matches the inline min(B·k, E) accounting."""
    monkeypatch.setenv("DS_GGEMM_INTERPRET", "1")
    from deepspeed_tpu.models.model import QuantizedTensor
    from deepspeed_tpu.ops.pallas import grouped_gemm as gg
    from deepspeed_tpu.ops.pallas.quantization import block_quantize_int8
    rng = np.random.default_rng(1)
    E, K, N, B = 4, 32, 16, 2
    w = jnp.asarray(rng.standard_normal((E, K, N)), jnp.float32)
    q, s = block_quantize_int8(w, block=16)
    eids = jnp.asarray(rng.integers(0, E, (B,)), jnp.int32)
    plan = gg.make_slot_plan(eids, E)
    x = jnp.asarray(rng.standard_normal((B, K)), jnp.float32)
    jaxpr = jax.make_jaxpr(
        lambda a: gg.ds_ggemm_slots(a, (q, s), plan, interpret=True))(x)
    assert count_pallas_launches(jaxpr) >= 1
    # byte acceptance: stacked [L, E, in, out] expert tree floors at
    # dense + distinct experts — same math serve_bench prints
    qe = QuantizedTensor(jnp.zeros((2, E, K, N), jnp.int8),
                         jnp.zeros((2, E, K, 1), jnp.float32), "float32")
    qd = QuantizedTensor(jnp.zeros((2, K, N), jnp.int8),
                         jnp.zeros((2, K, 1), jnp.float32), "float32")
    tree = {"experts": qe, "dense": qd}
    top_k = 2
    floors = param_stream_bytes(tree, batch=B, top_k=top_k,
                                num_experts=E)
    dense_b = 2 * K * N + 4 * 2 * K
    expert_b = 2 * E * K * N + 4 * 2 * E * K
    distinct = min(B * top_k, E)
    assert floors["dense_int8_bytes"] == dense_b
    assert floors["expert_int8_bytes"] == expert_b
    assert floors["weights_floor_moe"] == \
        dense_b + distinct * (expert_b // E)
    assert floors["weights_floor_int8"] == dense_b + expert_b


# --------------------------------------------- PERF.md floor parity (2%)
def test_floors_match_perf_md_hand_values():
    """Acceptance: costmodel-derived byte floors for gpt2/llama/mixtral
    decode at bench shapes match the hand-computed
    ``weights_floor_int8``/``weights_floor_moe`` values within 2% —
    from shape-only abstract trees (eval_shape), nothing materialized.

    The mixtral anchors are PERF.md's PR 8 table literals (204.6 /
    741.3 MB); the dense-family anchors are the decode_profile /
    serve_bench inline formulas re-derived here over the same shapes.
    """
    from deepspeed_tpu.models.model import QuantizedTensor

    def inline_hand_bytes(qblocks):
        # the scripts' idiom: q bytes + 4-byte scales per quantized leaf
        is_q = lambda x: isinstance(x, QuantizedTensor)
        total = 0
        for leaf in jax.tree_util.tree_leaves(qblocks, is_leaf=is_q):
            if is_q(leaf):
                total += int(leaf.q.size) + 4 * int(leaf.s.size)
        return total

    # mixtral:1b-moe — PERF.md PR 8 table (DEC_MOE=1 decode_profile)
    from deepspeed_tpu.models.mixtral import mixtral_model
    m = mixtral_model("1b-moe")
    cfg = m.config
    q = abstract_quantized_blocks(m)
    f1 = param_stream_bytes(q, batch=1, top_k=cfg.top_k,
                            num_experts=cfg.num_experts)
    f4 = param_stream_bytes(q, batch=4, top_k=cfg.top_k,
                            num_experts=cfg.num_experts)
    assert abs(f1["weights_floor_moe"] - 204.6e6) / 204.6e6 < 0.02
    assert abs(f4["weights_floor_moe"] - 741.3e6) / 741.3e6 < 0.02
    assert abs(f1["weights_floor_int8"] - 741.3e6) / 741.3e6 < 0.02
    # B=1 streams 3.6x fewer expert bytes than all-E (the PR 8 ratio)
    assert 3.5 < f4["weights_floor_moe"] / f1["weights_floor_moe"] < 3.7

    # gpt2-1.3b / llama-7b — library vs the inline script math
    from deepspeed_tpu.models.gpt2 import gpt2_model
    from deepspeed_tpu.models.llama import llama_model
    for model in (gpt2_model("1.3b"), llama_model("7b")):
        qb = abstract_quantized_blocks(model)
        lib = param_stream_bytes(qb)["weights_floor_int8"]
        hand = inline_hand_bytes(qb)
        assert lib == hand                    # same walk, zero drift
        # decode_profile's measured-stream variant counts q bytes only;
        # the stored-form floor differs by exactly the scale overhead
        qonly = sum(int(leaf.q.size) for leaf in jax.tree_util.tree_leaves(
            qb, is_leaf=lambda x: isinstance(x, QuantizedTensor))
            if isinstance(leaf, QuantizedTensor))
        assert abs(lib - qonly) / qonly < 0.02     # 4/256 = 1.6%
    # PERF.md: gpt2-1.3B int8 weight stream "~1.3 GB/step-batch"
    g = param_stream_bytes(abstract_quantized_blocks(gpt2_model("1.3b")))
    assert 1.2e9 < g["weights_floor_int8"] < 1.4e9


# ------------------------------------------------------------- roofline
def test_hbm_table_and_override(monkeypatch):
    monkeypatch.setenv("DS_HBM_GBPS", "819")
    assert roofline.hbm_bytes_per_s() == 819e9

    class FakeDev:
        device_kind = "TPU v5e"
    assert roofline.hbm_bytes_per_s(FakeDev(), env={}) == 819e9
    assert roofline.hbm_bytes_per_s(
        type("D", (), {"device_kind": "cpu"})(), env={}) is None


def test_floor_and_classification():
    from deepspeed_tpu.telemetry.costmodel import CostReport
    r = CostReport(name="p", flops=2e12, hbm_bytes=819e9)
    # bandwidth term: 1 s at 819 GB/s; compute term: 0.01 s at 200 TF
    assert roofline.floor_seconds(r, 200e12, 819e9) == pytest.approx(1.0)
    assert roofline.classify(r, 200e12, 819e9) == "bandwidth_bound"
    r2 = CostReport(name="p2", flops=400e12, hbm_bytes=1e6)
    assert roofline.classify(r2, 200e12, 819e9) == "compute_bound"
    assert roofline.floor_seconds(r, None, None) is None
    assert roofline.classify(r, None, 819e9) is None
    # one known rate is enough for a floor
    assert roofline.floor_seconds(r, None, 819e9) == pytest.approx(1.0)


def test_publish_and_observe_gauges(monkeypatch):
    monkeypatch.setenv("DS_HBM_GBPS", "100")    # 100 GB/s synthetic
    from deepspeed_tpu.telemetry.costmodel import CostReport
    reg = MetricsRegistry()
    r = CostReport(name="serve/window:w1", flops=1000,
                   hbm_bytes=int(100e9 // 1000), pallas_launches=3)
    roofline.publish_report(reg, r)
    assert reg.get_gauge("perf/pallas_launches",
                         program="serve/window:w1") == 3
    # floor = 1 ms at 100 GB/s for 1e8 bytes... here hbm/bw = 1e-3 s
    assert reg.get_gauge("perf/floor_ms",
                         program="serve/window:w1") == pytest.approx(1.0)
    roofline.observe_achieved(reg, "serve/window:w1", 0.004)
    assert reg.get_gauge("perf/achieved_ms",
                         program="serve/window:w1") == pytest.approx(4.0)
    assert reg.get_gauge("perf/achieved_vs_floor",
                         program="serve/window:w1") == pytest.approx(4.0)
    # and the lock-free payload carries the same rows
    from deepspeed_tpu.telemetry.debug import perf_payload
    p = perf_payload()
    row = p["programs"]["serve/window:w1"]
    assert row["achieved_vs_floor"] == pytest.approx(4.0, rel=1e-3)
    assert row["bound"] == "bandwidth_bound" if p["peak_flops"] else True
    assert perf_payload({"program": "nope"})["programs"] == {}


def test_no_floor_on_cpu_without_override(monkeypatch):
    monkeypatch.delenv("DS_HBM_GBPS", raising=False)
    monkeypatch.delenv("DS_PEAK_FLOPS", raising=False)
    if jax.devices()[0].platform != "cpu":
        pytest.skip("CPU-only contract")
    from deepspeed_tpu.telemetry.costmodel import CostReport
    reg = MetricsRegistry()
    r = CostReport(name="p", flops=10, hbm_bytes=10)
    roofline.publish_report(reg, r)
    assert reg.get_gauge("perf/floor_ms", program="p") is None
    roofline.observe_achieved(reg, "p", 0.1)
    assert reg.get_gauge("perf/achieved_ms", program="p") is not None
    assert reg.get_gauge("perf/achieved_vs_floor", program="p") is None


# -------------------------------------------------- scheduler integration
def test_scheduler_registers_programs_and_gauges(monkeypatch):
    monkeypatch.setenv("DS_HBM_GBPS", "100")
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m,
                                       config={"dtype": "float32"})
    reg = MetricsRegistry()
    cfg = ServingConfig(block_size=8, num_blocks=64, max_num_seqs=2)
    sched = ContinuousBatchingScheduler(m, eng.params, cfg, registry=reg)
    rng = np.random.default_rng(0)
    for _ in range(2):
        sched.submit(rng.integers(1, 120, (6,)).astype(np.int32),
                     SamplingParams(max_new_tokens=3))
    sched.run_until_idle()
    reports = costmodel.get_reports()
    assert any(n.startswith("serve/prefill") for n in reports)
    # decode families are keyed per fused-step count k: a k-step scan
    # streams the weights k times, so each k owns a k-scaled byte model
    decode_names = [n for n in reports if n.startswith("serve/decode:k")]
    assert decode_names, reports
    for name in decode_names:
        k = int(name.rsplit("k", 1)[1])
        dec = reports[name]
        assert dec.flops > 0
        assert dec.hbm_bytes == \
            k * sched._cost_stream["weights_floor_bytes"]
        assert dec.detail["weight_passes"] == k
    observed = [n for n in decode_names
                if reg.get_gauge("perf/achieved_vs_floor",
                                 program=n) is not None]
    assert observed, decode_names
    prom = reg.render_prometheus()
    assert f'perf_achieved_vs_floor{{program="{observed[0]}"}}' in prom
    from deepspeed_tpu.telemetry.debug import perf_payload
    assert observed[0] in perf_payload()["programs"]


def test_scheduler_costmodel_off(monkeypatch):
    monkeypatch.setenv("DS_PERF_COSTMODEL", "0")
    from deepspeed_tpu.runtime.config import ServingConfig
    from deepspeed_tpu.serving import (ContinuousBatchingScheduler,
                                       SamplingParams)
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m,
                                       config={"dtype": "float32"})
    reg = MetricsRegistry()
    sched = ContinuousBatchingScheduler(
        m, eng.params, ServingConfig(block_size=8, num_blocks=64,
                                     max_num_seqs=2), registry=reg)
    sched.submit(np.arange(1, 7, dtype=np.int32),
                 SamplingParams(max_new_tokens=2))
    sched.run_until_idle()
    assert costmodel.get_reports() == {}
    assert "perf_flops" not in reg.render_prometheus()


# ----------------------------------------------------- engine integration
def test_engine_train_step_cost_report():
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_gpt2(),
                                               config=base_config())
    engine.train_batch(iter(random_batches(1, seed=0)))
    rep = costmodel.get_report("train/step")
    assert rep is not None and rep.flops > 0
    assert engine.telemetry_registry.get_gauge(
        "perf/flops", program="train/step") == float(rep.flops)
    assert engine.telemetry_registry.get_gauge(
        "perf/achieved_ms", program="train/step") is not None


def test_postmortem_bundle_has_perf_json(tmp_path):
    from deepspeed_tpu.resilience.postmortem import (reset_rate_limit,
                                                     write_postmortem)
    from deepspeed_tpu.telemetry.costmodel import CostReport
    register_report(CostReport(name="serve/decode", flops=10,
                               hbm_bytes=10))
    reset_rate_limit()
    path = write_postmortem(str(tmp_path), "perf test")
    assert path is not None
    perf = json.load(open(os.path.join(path, "perf.json")))
    assert "serve/decode" in perf["programs"]
    man = json.load(open(os.path.join(path, "manifest.json")))
    assert man["files"]["perf.json"] is True


# ------------------------------------------------------------ perf_report
def test_perf_report_renders_trace_with_floors(tmp_path, capsys):
    from scripts.perf_report import main
    events = []
    t = 0.0
    for _ in range(3):
        events.append({"name": "serve/step", "ph": "B", "ts": t,
                       "pid": 1, "tid": 1})
        events.append({"name": "serve/window", "ph": "B", "ts": t + 100,
                       "pid": 1, "tid": 1})
        events.append({"name": "serve/window", "ph": "E", "ts": t + 900,
                       "pid": 1, "tid": 1})
        events.append({"name": "serve/step", "ph": "E", "ts": t + 1000,
                       "pid": 1, "tid": 1})
        t += 1500
    trace = str(tmp_path / "trace.json")
    json.dump({"traceEvents": events}, open(trace, "w"))
    perf = str(tmp_path / "perf.json")
    json.dump({"programs": {"serve/window:w1": {
        "floor_ms": 0.2, "bound": "bandwidth_bound",
        "pallas_launches": 3}}}, open(perf, "w"))
    assert main([trace, "--perf", perf, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    spans = out["spans"]
    assert spans["serve/step"]["count"] == 3
    assert spans["serve/window"]["mean_ms"] == pytest.approx(0.8)
    # the w1 program joined its span family's stem
    assert spans["serve/window"]["floor_ms"] == 0.2
    assert spans["serve/window"]["mean_vs_floor"] == pytest.approx(4.0)
    assert main([trace, "--top", "5"]) == 0       # table mode renders
    assert main([str(tmp_path / "missing.json")]) == 2
    # several buckets of one family: the join survives and takes the
    # lowest (most conservative) floor
    json.dump({"programs": {
        "serve/window:w2": {"floor_ms": 0.3, "bound": "bandwidth_bound"},
        "serve/window:w8": {"floor_ms": 0.2, "bound": "bandwidth_bound"},
    }}, open(perf, "w"))
    capsys.readouterr()                   # drain the table-mode output
    assert main([trace, "--perf", perf, "--json"]) == 0
    out2 = json.loads(capsys.readouterr().out)
    assert out2["spans"]["serve/window"]["floor_ms"] == 0.2


# ------------------------------------------------------------ bench ledger
def test_bench_record_schema_and_ledger(tmp_path, monkeypatch):
    from scripts.bench_util import (append_ledger, bench_meta,
                                    ledger_enabled, make_record)
    monkeypatch.setenv("DS_BENCH_DIR", str(tmp_path / "B"))
    monkeypatch.delenv("DS_BENCH_LEDGER", raising=False)
    assert not ledger_enabled()
    monkeypatch.setenv("DS_BENCH_LEDGER", "1")
    assert ledger_enabled()
    meta = bench_meta()
    assert meta["schema"] == "ds-bench/1"
    assert meta["device_kind"] and meta["device_count"] >= 1
    rec = make_record("m_tok_s", 100.0, unit="tok/s",
                      direction="higher_better",
                      detail={"model": "gpt2:tiny"})
    path = append_ledger(rec)
    assert path == str(tmp_path / "B" / "ledger.jsonl")
    got = json.loads(open(path).read().strip())
    assert got["metric"] == "m_tok_s" and got["meta"]["schema"]
    with pytest.raises(ValueError):
        make_record("m", 1.0, direction="sideways")


def _ledger_lines(tmp_path, values, kind="cpu", model="gpt2:tiny"):
    path = str(tmp_path / "ledger.jsonl")
    with open(path, "a") as f:
        for v in values:
            f.write(json.dumps({
                "metric": "m_tok_s", "value": v,
                "direction": "higher_better",
                "detail": {"model": model},
                "meta": {"schema": "ds-bench/1", "git_rev": "abc",
                         "device_kind": kind, "device_count": 1}}) + "\n")
    return path


def test_bench_compare_history_gate(tmp_path):
    from scripts.bench_compare import main
    led = _ledger_lines(tmp_path, [100.0, 102.0, 98.0, 101.0, 99.0])
    ok = str(tmp_path / "ok.json")
    json.dump({"metric": "m_tok_s", "value": 97.0,
               "direction": "higher_better",
               "detail": {"model": "gpt2:tiny"},
               "meta": {"schema": "ds-bench/1", "device_kind": "cpu",
                        "device_count": 1}}, open(ok, "w"))
    assert main(["--history", led, ok, "-q"]) == 0
    # synthetic >10% regression against the rolling median (100)
    bad = str(tmp_path / "bad.json")
    json.dump({"metric": "m_tok_s", "value": 85.0,
               "direction": "higher_better",
               "detail": {"model": "gpt2:tiny"},
               "meta": {"schema": "ds-bench/1", "device_kind": "cpu",
                        "device_count": 1}}, open(bad, "w"))
    assert main(["--history", led, bad, "-q"]) == 1
    # declared direction wins over the _s-suffix-free name inference:
    # lower_better means 85 < 100 is an improvement
    low = str(tmp_path / "low.json")
    json.dump({"metric": "m_latency", "value": 120.0,
               "direction": "lower_better",
               "meta": {"schema": "ds-bench/1", "device_kind": "cpu",
                        "device_count": 1}}, open(low, "w"))
    led2 = str(tmp_path / "ledger2.jsonl")
    with open(led2, "w") as f:
        f.write(json.dumps({
            "metric": "m_latency", "value": 100.0,
            "direction": "lower_better",
            "meta": {"schema": "ds-bench/1", "device_kind": "cpu",
                     "device_count": 1}}) + "\n")
    assert main(["--history", led2, low, "-q"]) == 1   # 20% worse


def test_bench_compare_refuses_cross_device(tmp_path):
    """Acceptance: a CPU-smoke record must not gate an on-chip one —
    exit 2 with a diagnostic, both pairwise and against history."""
    from scripts.bench_compare import main
    cpu = str(tmp_path / "cpu.json")
    tpu = str(tmp_path / "tpu.json")
    json.dump({"metric": "m_tok_s", "value": 100.0,
               "meta": {"schema": "ds-bench/1", "device_kind": "cpu",
                        "device_count": 1}}, open(cpu, "w"))
    json.dump({"metric": "m_tok_s", "value": 5000.0,
               "meta": {"schema": "ds-bench/1",
                        "device_kind": "TPU v5e", "device_count": 1}},
              open(tpu, "w"))
    assert main([cpu, tpu, "-q"]) == 2
    # history holds ONLY cpu records; current is on-chip -> refuse
    led = _ledger_lines(tmp_path, [100.0, 101.0], kind="cpu")
    tpu2 = str(tmp_path / "tpu2.json")
    json.dump({"metric": "m_tok_s", "value": 5000.0,
               "detail": {"model": "gpt2:tiny"},
               "meta": {"schema": "ds-bench/1",
                        "device_kind": "TPU v5e", "device_count": 1}},
              open(tpu2, "w"))
    assert main(["--history", led, tpu2, "-q"]) == 2
    # pre-schema records (no meta) keep comparing
    old_style = str(tmp_path / "old.json")
    json.dump({"metric": "m_tok_s", "value": 100.0}, open(old_style, "w"))
    assert main([old_style, old_style, "-q"]) == 0


def test_history_tolerates_mixed_model_ledger(tmp_path):
    """A ledger legitimately holding several model shapes for one
    metric (smoke + full-size runs on one box) must NOT trip the
    cross-model refusal — the rolling baseline is already filtered to
    the current record's shape."""
    from scripts.bench_compare import main
    led = _ledger_lines(tmp_path, [100.0, 101.0], model="gpt2:tiny")
    _ledger_lines(tmp_path, [10.0, 11.0], model="gpt2:350m")
    cur = str(tmp_path / "cur.json")
    json.dump({"metric": "m_tok_s", "value": 99.0,
               "direction": "higher_better",
               "detail": {"model": "gpt2:tiny"},
               "meta": {"schema": "ds-bench/1", "device_kind": "cpu",
                        "device_count": 1}}, open(cur, "w"))
    # baseline comes from the tiny-model records (median 100.5), not
    # the 350m ones — 99 is within threshold
    assert main(["--history", led, cur, "-q"]) == 0


def test_schema_version_mismatch_refused(tmp_path):
    from scripts.bench_compare import main, meta_conflict
    assert meta_conflict({"schema": "ds-bench/1"},
                         {"schema": "ds-bench/2"}) is not None
    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    json.dump({"metric": "m", "value": 1.0,
               "meta": {"schema": "ds-bench/1"}}, open(a, "w"))
    json.dump({"metric": "m", "value": 1.0,
               "meta": {"schema": "ds-bench/2"}}, open(b, "w"))
    assert main([a, b, "-q"]) == 2


def test_achieved_mean_excludes_warmup_sample():
    """The first observation of a program carries compile + the
    analysis trace; the running mean must be over warm executions."""
    costmodel.record_achieved("p", 10.0)         # compile-tainted
    costmodel.record_achieved("p", 0.002)
    costmodel.record_achieved("p", 0.004)
    register_report(costmodel.CostReport(name="p", flops=1, hbm_bytes=1))
    row = roofline.perf_table()["programs"]["p"]
    assert row["achieved_count"] == 3
    assert row["achieved_mean_ms"] == pytest.approx(3.0)   # (2+4)/2
    assert row["achieved_ms"] == pytest.approx(4.0)


def test_bench_compare_refuses_cross_model(tmp_path):
    from scripts.bench_compare import main
    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    json.dump({"metric": "m_tok_s", "value": 100.0,
               "detail": {"model": "gpt2:125m"}}, open(a, "w"))
    json.dump({"metric": "m_tok_s", "value": 50.0,
               "detail": {"model": "gpt2:1.3b"}}, open(b, "w"))
    assert main([a, b, "-q"]) == 2


def test_ledger_round_trip_via_bench_script(tmp_path):
    """Satellite: bench script → BENCH/ record → history gate, in
    CPU-smoke mode (ckpt_bench CKPT_SMOKE=1 writes a real BenchRecord;
    a synthetic regressed record then trips the history gate)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", CKPT_SMOKE="1",
               ASYNC="0", DS_BENCH_LEDGER="1",
               DS_BENCH_DIR=str(tmp_path))
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "ckpt_bench.py")],
        env=env, capture_output=True, text=True, timeout=560, cwd=ROOT)
    assert out.returncode == 0, out.stderr[-2000:]
    led = str(tmp_path / "ledger.jsonl")
    recs = [json.loads(line) for line in open(led) if line.strip()]
    assert recs and recs[-1]["metric"] == "ckpt_bench_sync"
    meta = recs[-1]["meta"]
    assert meta["schema"] == "ds-bench/1" and meta["device_kind"]
    # gate a synthetic 10x step-time regression against the history
    from scripts.bench_compare import main
    bad = dict(recs[-1])
    bad["value"] = recs[-1]["value"] * 10
    cur = str(tmp_path / "cur.json")
    json.dump(bad, open(cur, "w"))
    assert main(["--history", led, cur, "-q",
                 "--metrics", "ckpt_bench_sync"]) == 1
    good = dict(recs[-1])
    cur2 = str(tmp_path / "cur2.json")
    json.dump(good, open(cur2, "w"))
    assert main(["--history", led, cur2, "-q",
                 "--metrics", "ckpt_bench_sync"]) == 0
