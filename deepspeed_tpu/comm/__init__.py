"""deepspeed.comm-compatible collectives facade (reference: deepspeed/comm/comm.py).

Two operating regimes, matching how JAX programs actually communicate:

1. **Inside jit/shard_map** — collectives are ``jax.lax`` primitives keyed by mesh
   axis names.  The reference's "process group" argument becomes a tuple of axis
   names (see :mod:`deepspeed_tpu.comm.mesh`).  These are re-exported here as
   ``psum``/``pmean``/``all_gather``/``psum_scatter``/``all_to_all``/``ppermute``
   thin wrappers so framework code imports one comm module.
2. **Outside jit (host-level)** — cross-host bootstrap and eager collectives:
   ``init_distributed()`` wires ``jax.distributed.initialize`` (the reference's
   ``init_distributed`` + env rendezvous, comm/comm.py:604), and eager ops run a
   tiny jitted psum over the global mesh.

Every op is wrapped with the comms logger when enabled (reference ``@timed_op``,
comm/comm.py:101).
"""
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from deepspeed_tpu.comm.mesh import (  # noqa: F401
    MeshTopology, get_topology, set_topology, reset_topology,
    PIPE_AXIS, EXPERT_AXIS, DATA_AXIS, HPZ_AXIS, SEQ_AXIS, MODEL_AXIS,
    MESH_AXIS_ORDER,
)
from deepspeed_tpu.utils.logging import logger

_INITIALIZED = False
_COMMS_LOGGER = None


def is_initialized() -> bool:
    return _INITIALIZED


def init_distributed(dist_backend: str = "jax",
                     dist_init_required: Optional[bool] = None,
                     timeout=None, init_method=None, rank=-1, world_size=-1,
                     auto_mpi_discovery: bool = True):
    """Multi-host bootstrap (reference: comm/comm.py:604).

    Single-host (or already-initialised) is a no-op.  Multi-host TPU pods are
    detected via the standard JAX coordination env vars or TPU metadata; then
    ``jax.distributed.initialize`` performs the rendezvous over DCN.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    if dist_init_required is False:
        _INITIALIZED = True
        return
    coordinator = os.environ.get("COORDINATOR_ADDRESS") or init_method
    n_procs = int(os.environ.get("NPROC", world_size if world_size > 0 else 0))
    proc_id = int(os.environ.get("PROCESS_ID", rank if rank >= 0 else 0))
    if coordinator and n_procs > 1:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=n_procs, process_id=proc_id)
        logger.info(f"jax.distributed initialised: process {proc_id}/{n_procs}")
    elif _looks_multihost():
        # TPU pods / GKE / SLURM: jax auto-detects the coordinator from the
        # cluster environment (the reference's MPI/AML/SageMaker discovery,
        # comm/comm.py:650-658)
        try:
            jax.distributed.initialize()
            logger.info(
                f"jax.distributed auto-initialised: process "
                f"{jax.process_index()}/{jax.process_count()}")
        except Exception as e:  # single-host or undetectable cluster
            logger.warning(f"jax.distributed auto-init skipped: {e}")
    _INITIALIZED = True


def _looks_multihost() -> bool:
    """Heuristics for environments where jax.distributed auto-detection works."""
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    if "," in hosts:
        return True
    if os.environ.get("MEGASCALE_COORDINATOR_ADDRESS") or \
            os.environ.get("JAX_COORDINATOR_ADDRESS"):
        return True
    # GCE TPU-VM pods: the metadata server provisions TPU_WORKER_ID on
    # every worker; a non-zero id, or an accelerator topology naming more
    # chips than one host carries, means a pod slice (jax auto-discovers
    # the coordinator from the same metadata)
    wid = os.environ.get("TPU_WORKER_ID")
    if wid is not None and wid.strip() not in ("", "0"):
        return True
    acc = os.environ.get("TPU_ACCELERATOR_TYPE", "")
    try:
        if "-" in acc and int(acc.rsplit("-", 1)[1]) > 8:
            return True
    except ValueError:
        pass
    for m in ("SLURM_JOB_NUM_NODES", "OMPI_COMM_WORLD_SIZE"):
        try:
            if int(os.environ.get(m, "1")) > 1:
                return True
        except ValueError:
            pass
    return False


def get_rank(group=None) -> int:
    """Host-level "rank" ≙ process index.  JAX is single-controller per host, so
    rank/world at this facade are *process* counts (consistent pair); device
    counts live on the mesh topology / ``get_device_count``."""
    return jax.process_index()


def get_world_size(group=None) -> int:
    if group is not None:
        return get_topology().axis_size(group)
    return jax.process_count()


def get_device_count() -> int:
    return jax.device_count()


def get_local_rank() -> int:
    return 0


def barrier(group=None, name: str = "ds_barrier"):
    """Cross-process barrier (reference: torch.distributed.barrier).
    Host-timed into the process-wide CommStat (ISSUE 19) — the barrier
    is the one collective the host can always time end-to-end."""
    import time as _time
    from deepspeed_tpu.telemetry.commstat import peek_commstat
    t0 = _time.perf_counter()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
    else:
        # single process: fence locally-dispatched work
        for d in jax.local_devices():
            jax.device_put(0.0, d).block_until_ready()
    cs = peek_commstat()
    if cs is not None:
        cs.observe("barrier", 0, _time.perf_counter() - t0)


def _axis(group):
    """Normalise a group handle to a lax axis_name (str or tuple)."""
    if group is None:
        return get_topology().data_parallel_axes
    return group


def _axis_label(ax) -> str:
    """A mesh-axis key for CommStat rows ("data", "expert+data", ...)."""
    if isinstance(ax, str):
        return ax
    try:
        return "+".join(str(a) for a in ax)
    except TypeError:
        return str(ax)


def _log_op(name, tensor, group):
    ax = None
    if _COMMS_LOGGER is not None and _COMMS_LOGGER.enabled:
        ax = _axis(group)
        _COMMS_LOGGER.append_inside_jit(name, tensor, ax)
    from deepspeed_tpu.telemetry.commstat import peek_commstat
    cs = peek_commstat()
    if cs is not None:
        if ax is None:
            ax = _axis(group)
        try:
            nbytes = int(tensor.size) * tensor.dtype.itemsize
        except (AttributeError, TypeError):
            nbytes = 0
        cs.record_traced(name, _axis_label(ax), nbytes)


# --------------------------------------------------------------------------
# In-jit collectives (the hot path).  These trace to XLA collectives over ICI.
# --------------------------------------------------------------------------
def all_reduce(tensor, op: str = "sum", group=None):
    """lax.psum/pmax/pmin over the group's mesh axes (inside jit/shard_map)."""
    _log_op("all_reduce", tensor, group)
    ax = _axis(group)
    if op in ("sum", "SUM"):
        return lax.psum(tensor, ax)
    if op in ("avg", "AVG", "mean"):
        return lax.pmean(tensor, ax)
    if op in ("max", "MAX"):
        return lax.pmax(tensor, ax)
    if op in ("min", "MIN"):
        return lax.pmin(tensor, ax)
    raise ValueError(f"unsupported reduce op {op}")


def all_gather(tensor, group=None, axis: int = 0, tiled: bool = True):
    """lax.all_gather concatenating along ``axis`` (reference
    all_gather_into_tensor)."""
    _log_op("all_gather", tensor, group)
    return lax.all_gather(tensor, _axis(group), axis=axis, tiled=tiled)


def reduce_scatter(tensor, group=None, axis: int = 0, tiled: bool = True):
    """lax.psum_scatter (reference reduce_scatter_tensor)."""
    _log_op("reduce_scatter", tensor, group)
    return lax.psum_scatter(tensor, _axis(group), scatter_dimension=axis, tiled=tiled)


def all_to_all(tensor, group=None, split_axis: int = 0, concat_axis: int = 0,
               tiled: bool = True):
    """lax.all_to_all (reference all_to_all_single)."""
    _log_op("all_to_all", tensor, group)
    ax = _axis(group)
    return lax.all_to_all(tensor, ax, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=tiled)


def ppermute(tensor, perm, group=None):
    """Point-to-point ring shift (reference send/recv pairs in pipe/p2p.py)."""
    _log_op("ppermute", tensor, group)
    return lax.ppermute(tensor, _axis(group), perm)


def axis_index(group=None):
    from deepspeed_tpu.utils.jax_compat import axis_size
    ax = _axis(group)
    if isinstance(ax, str):
        return lax.axis_index(ax)
    idx = lax.axis_index(ax[0])
    for a in ax[1:]:
        idx = idx * axis_size(a) + lax.axis_index(a)
    return idx


def axis_size_in_jit(group=None):
    from deepspeed_tpu.utils.jax_compat import axis_size
    ax = _axis(group)
    if isinstance(ax, str):
        return axis_size(ax)
    n = 1
    for a in ax:
        n *= axis_size(a)
    return n


# --------------------------------------------------------------------------
# Comms logging hookup (reference utils/comms_logging.py)
# --------------------------------------------------------------------------
def configure(comms_logger=None):
    global _COMMS_LOGGER
    _COMMS_LOGGER = comms_logger


def log_summary(monitor=None, step: int = 0, show_straggler: bool = False):
    """Print the comms summary; with ``monitor`` (any monitor/monitor.py
    sink) the per-op totals also land as ``comms/...`` events at
    ``step`` — engine.log_comms_summary() wires its own monitor in."""
    if _COMMS_LOGGER is not None:
        _COMMS_LOGGER.log_all(monitor=monitor, step=step,
                              show_straggler=show_straggler)
