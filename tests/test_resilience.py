"""Chaos tests for the resilience subsystem (ISSUE 3).

The load-bearing contracts:
- an injected crash or write-failure at ANY point inside
  ``save_checkpoint`` (sync and async engines) never leaves ``latest``
  resolving to a tag that fails manifest verification —
  ``load_checkpoint`` always restores the newest VALID tag (the seeded
  fault matrix below);
- a torn/empty ``latest`` file no longer poisons ``load_checkpoint``;
- ``keep_last_k`` retention never deletes the fallback;
- SIGTERM drains training through an emergency checkpoint + the distinct
  exit code the elastic agent resumes from;
- serving: consecutive step failures and scheduler stalls flip health to
  DEGRADED (metrics surfaced) instead of hanging forever; a drain
  finishes in-flight requests while new ones get 503.

The slow group runs the full kill → elastic-agent → resume → identical
final loss pipeline in subprocesses.
"""
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request
import urllib.error

import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.resilience import (CheckpointCorruptError, FaultInjected,
                                      FaultInjector, FaultSpec, HealthMonitor,
                                      HealthState, NULL_INJECTOR,
                                      PREEMPTED_EXIT_CODE, PreemptionHandler,
                                      RetryDeadlineExceeded, SchedulerWatchdog,
                                      parse_spec, resolve_injector,
                                      retry_call, run_resilient_training,
                                      verify_tag)
from deepspeed_tpu.resilience import ckpt as rckpt
from deepspeed_tpu.runtime.config import ServingConfig
from deepspeed_tpu.serving import ContinuousBatchingScheduler, RequestState, \
    SamplingParams
from deepspeed_tpu.serving.scheduler import ServingMetrics
from tests.util import tiny_gpt2, base_config, random_batches


# ------------------------------------------------------------ fault specs
def test_fault_spec_grammar():
    s = FaultSpec.parse("ckpt.save:raise@1")
    assert (s.site, s.action, s.start, s.repeat) == \
        ("ckpt.save", "raise", 1, False)
    s = FaultSpec.parse("train.step:kill=9@5")
    assert s.action == "kill" and s.param == 9
    s = FaultSpec.parse("serve.step:stall=0.25@3+")
    assert s.param == 0.25 and s.start == 3 and s.repeat
    s = FaultSpec.parse("kv.alloc:deny@*")
    assert s.repeat and s.fires_at(0) and s.fires_at(100)
    s = FaultSpec.parse("train.step:raise@p0.5s42")
    fires = [s.fires_at(i) for i in range(200)]
    assert any(fires) and not all(fires)
    # seeded => deterministic
    assert fires == [FaultSpec.parse("train.step:raise@p0.5s42").fires_at(i)
                     for i in range(200)]
    assert len(parse_spec("a.b:raise@0; c.d:deny@*  e.f:stall=1@2+")) == 3
    assert parse_spec(None) == [] and parse_spec("") == []
    for bad in ("nocolon@1", "a.b:explode@1", "a.b:raise", "a.b:raise@x"):
        with pytest.raises(ValueError):
            FaultSpec.parse(bad)


def test_fault_injector_actions():
    inj = FaultInjector("s.a:raise@1; s.b:deny@0; s.c:truncate=3@0")
    inj.check("s.a")                      # invocation 0: no fire
    with pytest.raises(FaultInjected):
        inj.check("s.a")                  # invocation 1: fires
    inj.check("s.a")                      # one-shot: done firing
    assert inj.deny("s.b") and not inj.deny("s.b")
    assert inj.truncate_bytes("s.c", 10) == 3
    assert inj.truncate_bytes("s.c", 10) is None
    assert inj.fired == {"s.a": 1, "s.b": 1, "s.c": 1}
    assert not NULL_INJECTOR
    NULL_INJECTOR.check("anything")       # no-op, no state explosion


def test_corrupt_action_grammar_and_injector():
    """ISSUE 18: the corrupt action — spec grammar, default/clamped
    byte counts, one-shot firing, and NULL_INJECTOR passthrough."""
    s = FaultSpec.parse("kv.swap:corrupt=16@2")
    assert (s.site, s.action, s.param, s.start) == \
        ("kv.swap", "corrupt", 16, 2)
    inj = FaultInjector("s.k:corrupt@0; s.m:corrupt=4@*")
    assert inj.corrupt_bytes("s.k", 100) == 8      # default: 8 bytes
    assert inj.corrupt_bytes("s.k", 100) is None   # one-shot: done
    assert inj.corrupt_bytes("s.m", 2) == 2        # clamped to payload
    assert inj.corrupt_bytes("s.m", 0) is None     # empty payload
    assert inj.fired == {"s.k": 1, "s.m": 2}
    assert NULL_INJECTOR.corrupt_bytes("s.m", 100) is None
    # raise specs still raise through the corrupt hook
    with pytest.raises(FaultInjected):
        FaultInjector("s.r:raise@0").corrupt_bytes("s.r", 10)


def test_corrupt_seeded_probabilistic():
    """pPsS mode is deterministic per (seed, invocation) for corrupt
    like every other action — a corruption storm is replayable."""
    inj = FaultInjector("s.p:corrupt=2@p0.5s7")
    hits = [inj.corrupt_bytes("s.p", 64) for _ in range(200)]
    fired = [h for h in hits if h]
    assert fired and len(fired) < 200 and all(h == 2 for h in fired)
    inj2 = FaultInjector("s.p:corrupt=2@p0.5s7")
    assert hits == [inj2.corrupt_bytes("s.p", 64) for _ in range(200)]


def test_flip_bytes_size_preserving_involution():
    """The flip itself: size-preserving by construction, exact flip
    count, and an involution (two applications restore the payload)."""
    from deepspeed_tpu.resilience.faults import flip_bytes
    rng = np.random.default_rng(0)
    buf = rng.integers(0, 256, size=257, dtype=np.uint8)
    orig = buf.copy()
    assert flip_bytes(buf, 16) == 16
    assert buf.shape == orig.shape                  # size-preserving
    assert int(np.count_nonzero(buf != orig)) == 16
    flip_bytes(buf, 16)
    assert np.array_equal(buf, orig)                # involution
    assert flip_bytes(buf[:0], 4) == 0              # empty payload
    small = orig[:3].copy()
    assert flip_bytes(small, 100) == 3              # clamped to len


def test_resolve_injector_merges_env(monkeypatch):
    monkeypatch.setenv("DS_FAULTS", "env.site:deny@0")
    inj = resolve_injector("cfg.site:raise@0")
    assert {s.site for s in inj.specs} == {"cfg.site", "env.site"}
    monkeypatch.delenv("DS_FAULTS")
    assert not resolve_injector("")       # nothing armed -> falsy no-op


def test_retry_call_backoff_and_deadline():
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, attempts=4, base_delay_s=0.01,
                      _sleep=sleeps.append) == "ok"
    assert len(calls) == 3 and len(sleeps) == 2

    def always():
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(always, attempts=3, base_delay_s=0.0, _sleep=lambda s: 0)

    with pytest.raises(RetryDeadlineExceeded):
        retry_call(always, attempts=100, base_delay_s=0.0, deadline_s=0.0,
                   _sleep=lambda s: 0)

    def type_err():
        raise TypeError("bug, not weather")

    calls.clear()
    with pytest.raises(TypeError):       # non-retryable: no second call
        retry_call(type_err, attempts=5, _sleep=calls.append)
    assert calls == []


def test_verify_restored_catches_corruption():
    state = {"a": np.arange(8, dtype=np.float32),
             "b": np.ones((2, 3), np.int32)}
    manifest = {"leaves": rckpt.leaf_summary(state, checksums=True)}
    assert rckpt.verify_restored(state, manifest) == []
    state["a"] = state["a"].copy()
    state["a"][3] += 1.0
    assert any("checksum" in m
               for m in rckpt.verify_restored(state, manifest))


# ------------------------------------------------ checkpoint crash-safety
def _make_engine(overrides=None):
    cfg = base_config(**(overrides or {}))
    engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    return engine


def _train(engine, steps=1, seed=0):
    for i in range(steps):
        engine.train_batch(data_iter=iter(random_batches(1, seed=seed + i)))


def _qkv(engine):
    return np.asarray(engine.state["params"]["blocks"]["qkv_w"]).copy()


def test_torn_latest_falls_back(devices8, tmp_path):
    """ISSUE 3 satellite regression: a torn/empty `latest` file no longer
    poisons load_checkpoint — it resolves the newest valid tag anyway."""
    engine = _make_engine()
    _train(engine, 1, seed=3)
    engine.save_checkpoint(str(tmp_path))
    _train(engine, 1, seed=4)
    engine.save_checkpoint(str(tmp_path))
    want = _qkv(engine)
    for torn in (b"", b"global_st"):     # empty and truncated pointers
        with open(tmp_path / "latest", "wb") as f:
            f.write(torn)
        loader = _make_engine()
        path, _ = loader.load_checkpoint(str(tmp_path))
        assert path is not None and loader.global_steps == 2
        np.testing.assert_array_equal(_qkv(loader), want)


def test_latest_pointer_written_atomically(devices8, tmp_path):
    """The publish goes through tmp + os.replace: no window where the
    pointer file exists torn.  A truncate fault models the OLD writer."""
    engine = _make_engine()
    _train(engine, 1, seed=5)
    engine.save_checkpoint(str(tmp_path))
    assert (tmp_path / "latest").read_text() == "global_step1"
    assert not (tmp_path / "latest.tmp").exists()
    ok, reason = verify_tag(str(tmp_path / "global_step1"))
    assert ok, reason


# The seeded fault matrix (acceptance): (spec, second_save_survives).
# second_save_survives=True means the fault cannot prevent the new tag
# from publishing validly, so load must restore step 2; False means the
# new tag must NOT be restorable and load falls back to step 1.
FAULT_MATRIX = [
    ("ckpt.save:raise@0", False),
    ("ckpt.save:stall=0.01@0", True),
    ("ckpt.aux:raise@0", False),
    ("ckpt.manifest:raise@0", False),
    ("ckpt.manifest:truncate@0", False),
    ("ckpt.publish:raise@0", False),     # crash before the tag rename
    ("ckpt.latest:truncate@0", True),    # torn pointer, valid tag
    ("ckpt.latest:raise@0", True),       # pointer never written
]


@pytest.mark.parametrize("async_save", [False, True])
def test_fault_matrix_save_never_poisons_load(devices8, tmp_path,
                                              async_save):
    """Acceptance: injected crash/write-failure at any point during
    save_checkpoint never leaves `latest` resolving to an invalid tag —
    load_checkpoint always restores the newest valid tag."""
    overrides = {"checkpoint": {"async_save": async_save}}
    engine = _make_engine(overrides)
    loader = _make_engine(overrides)
    for i, (spec, second_survives) in enumerate(FAULT_MATRIX):
        if async_save and spec.startswith("ckpt.aux"):
            # no host-optimizer aux payload -> the async path never
            # starts an aux thread and the site is unreachable
            continue
        save_dir = tmp_path / f"case{i}"
        _train(engine, 1, seed=10 + i)
        engine.save_checkpoint(str(save_dir))
        engine.wait_pending_checkpoint()
        step1, snap1 = engine.global_steps, _qkv(engine)
        _train(engine, 1, seed=40 + i)
        step2, snap2 = engine.global_steps, _qkv(engine)
        engine.fault_injector = FaultInjector(spec)
        try:
            engine.save_checkpoint(str(save_dir))
            engine.wait_pending_checkpoint()
        except (FaultInjected, OSError, RetryDeadlineExceeded):
            pass
        finally:
            engine.fault_injector = NULL_INJECTOR
        path, _ = loader.load_checkpoint(str(save_dir))
        assert path is not None, f"{spec}: no tag restorable"
        ok, reason = verify_tag(path)
        assert ok, f"{spec}: restored tag failed verification: {reason}"
        want_step = step2 if second_survives else step1
        want_snap = snap2 if second_survives else snap1
        assert loader.global_steps == want_step, \
            f"{spec}: restored step {loader.global_steps} != {want_step}"
        np.testing.assert_array_equal(_qkv(loader), want_snap,
                                      err_msg=spec)


def test_raise_fault_during_save_leaves_only_staging(devices8, tmp_path):
    """A failed save leaves a .tmp staging dir at most — never a
    published tag, and `latest` still names the previous good one."""
    engine = _make_engine()
    _train(engine, 1, seed=6)
    engine.save_checkpoint(str(tmp_path))
    _train(engine, 1, seed=7)
    engine.fault_injector = FaultInjector("ckpt.save:raise@0")
    with pytest.raises(FaultInjected):
        engine.save_checkpoint(str(tmp_path))
    engine.fault_injector = NULL_INJECTOR
    assert rckpt.list_tags(str(tmp_path)) == ["global_step1"]
    assert rckpt.read_latest(str(tmp_path)) == "global_step1"


def test_same_tag_overwrite_crash_window(devices8, tmp_path):
    """Overwriting a fixed tag is crash-safe: a crash between "move old
    aside" and "move new in" leaves the old checkpoint under
    `<tag>.prev` — a normal, discoverable tag the fallback restores
    (a .tmp staging name would hide BOTH copies)."""
    engine = _make_engine()
    _train(engine, 1, seed=30)
    engine.save_checkpoint(str(tmp_path), tag="ckpt")
    snap1 = _qkv(engine)
    _train(engine, 1, seed=31)
    engine.fault_injector = FaultInjector("ckpt.publish:raise@0")
    with pytest.raises(FaultInjected):
        engine.save_checkpoint(str(tmp_path), tag="ckpt")
    engine.fault_injector = NULL_INJECTOR
    assert rckpt.list_tags(str(tmp_path)) == ["ckpt.prev"]
    loader = _make_engine()
    path, _ = loader.load_checkpoint(str(tmp_path))
    assert path.endswith("ckpt.prev") and loader.global_steps == 1
    np.testing.assert_array_equal(_qkv(loader), snap1)
    # a successful overwrite cleans the .prev staging up again
    engine.save_checkpoint(str(tmp_path), tag="ckpt")
    assert rckpt.list_tags(str(tmp_path)) == ["ckpt"]
    loader2 = _make_engine()
    path, _ = loader2.load_checkpoint(str(tmp_path))
    assert path.endswith("ckpt") and loader2.global_steps == 2


def test_keep_last_k_retention(devices8, tmp_path):
    engine = _make_engine({"resilience": {"keep_last_k": 2}})
    for i in range(4):
        _train(engine, 1, seed=20 + i)
        engine.save_checkpoint(str(tmp_path))
    tags = rckpt.list_tags(str(tmp_path))
    assert tags == ["global_step3", "global_step4"]
    assert rckpt.read_latest(str(tmp_path)) == "global_step4"
    loader = _make_engine()
    path, _ = loader.load_checkpoint(str(tmp_path))
    assert loader.global_steps == 4
    # retention must never delete the fallback: corrupt the newest tag's
    # manifest; the next resolve falls back to the OTHER retained tag
    manifest = tmp_path / "global_step4" / rckpt.MANIFEST_FILE
    manifest.write_text("{torn")
    loader2 = _make_engine()
    path, _ = loader2.load_checkpoint(str(tmp_path))
    assert path.endswith("global_step3") and loader2.global_steps == 3


def test_requested_tag_verification(devices8, tmp_path):
    engine = _make_engine()
    _train(engine, 1, seed=8)
    engine.save_checkpoint(str(tmp_path), tag="good")
    (tmp_path / "good" / rckpt.MANIFEST_FILE).write_text("{torn")
    loader = _make_engine()
    with pytest.raises(CheckpointCorruptError):
        loader.load_checkpoint(str(tmp_path), tag="good")


def test_train_step_fault_site(devices8):
    engine = _make_engine({"resilience": {"faults": "train.step:raise@1"}})
    _train(engine, 1, seed=9)             # invocation 0: clean
    with pytest.raises(FaultInjected):
        _train(engine, 1, seed=9)         # invocation 1: fires


def test_npz_engine_save_is_atomic(tmp_path, monkeypatch):
    from deepspeed_tpu.runtime.checkpoint_engine.engine import \
        NpzCheckpointEngine
    eng = NpzCheckpointEngine()
    state = {"w": np.arange(6, dtype=np.float32)}
    target = tmp_path / "flat.npz"

    real_savez = np.savez

    def torn_savez(path, **kw):
        with open(path, "wb") as f:       # half-written file, then death
            f.write(b"PK\x03\x04garbage")
        raise OSError("disk died mid-write")

    monkeypatch.setattr(np, "savez", torn_savez)
    with pytest.raises(OSError):
        eng.save(state, str(target))
    monkeypatch.setattr(np, "savez", real_savez)
    assert not target.exists()            # no torn file at the real name
    assert list(tmp_path.iterdir()) == []  # staging cleaned up
    eng.save(state, str(target))
    out = eng.load(str(target), template={"w": np.zeros(6, np.float32)})
    np.testing.assert_array_equal(out["w"], state["w"])


# ------------------------------------------------------------- preemption
def test_preemption_handler_latches_sigterm():
    handler = PreemptionHandler(signals=(signal.SIGTERM,))
    before = signal.getsignal(signal.SIGTERM)
    with handler:
        assert not handler.should_stop
        signal.raise_signal(signal.SIGTERM)
        assert handler.should_stop and handler.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) == before


def test_resilient_training_drains_and_resumes(devices8, tmp_path):
    """In-process acceptance: preemption mid-run → emergency checkpoint +
    distinct exit code; the resume path restores the drained step, the
    params, and the rng chain EXACTLY.

    (The resumed engine deliberately does no further training here: on
    this container's jaxlib, training on restored state under the warm
    persistent compile cache corrupts the glibc heap — the documented
    test_universal_checkpoint abort class.  The same-final-loss
    acceptance runs in the slow e2e tests, whose subprocess workers
    disable the persistent cache.)"""
    overrides = {"optimizer": {"type": "Adam", "params": {"lr": 1e-2}}}
    batches = [random_batches(1, seed=100 + s)[0] for s in range(6)]

    def batch_for(step):
        return {"input_ids": batches[step]["input_ids"][None]}

    # interrupted at step 3: the handler latch is set as if SIGTERM
    # arrived mid-step; the loop finishes the step then drains
    exit_codes = []
    handler = PreemptionHandler(signals=())
    eng = _make_engine(overrides)
    run_dir = tmp_path / "run"

    def on_step(step, loss):
        if step == 3:
            handler.requested.set()

    run_resilient_training(eng, batch_for, str(run_dir), num_steps=6,
                           handler=handler, on_step=on_step,
                           _exit=exit_codes.append)
    assert exit_codes == [PREEMPTED_EXIT_CODE]
    assert eng.global_steps == 3
    tags = rckpt.list_tags(str(run_dir))
    assert "emergency_step3" in tags
    ok, reason = verify_tag(str(run_dir / "emergency_step3"))
    assert ok, reason

    # resume exactly where the drain left off (what the elastic agent
    # does via DS_RESUME=latest): run_resilient_training with num_steps
    # == the drained step restores and immediately returns
    eng2 = _make_engine(overrides)
    run_resilient_training(eng2, batch_for, str(run_dir), num_steps=3,
                           resume="latest")
    assert eng2.global_steps == 3
    np.testing.assert_array_equal(_qkv(eng2), _qkv(eng))
    # the rng chain rides the metadata, so step 4 would draw the exact
    # key the uninterrupted run would have drawn
    np.testing.assert_array_equal(np.asarray(eng2._rng),
                                  np.asarray(eng._rng))


# ---------------------------------------------------------- elastic agent
def _run_agent_child(tmp_path, body, **agent_kw):
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    script = tmp_path / "child.py"
    script.write_text(textwrap.dedent(body))
    agent = DSElasticAgent([sys.executable, str(script), str(tmp_path)],
                           **agent_kw)
    return agent


def test_elastic_agent_backoff_sequence(tmp_path):
    """Delays grow exponentially from restart_delay_s, capped at
    backoff_max_s; jitter=0 makes the ladder exact."""
    agent = _run_agent_child(tmp_path, """
        import os, sys
        marker = os.path.join(sys.argv[1], "n")
        n = int(open(marker).read()) if os.path.exists(marker) else 0
        open(marker, "w").write(str(n + 1))
        sys.exit(0 if n >= 4 else 1)
    """, max_restarts=8, restart_delay_s=0.01, backoff_factor=2.0,
        backoff_max_s=0.05, backoff_jitter=0.0, monitor_interval_s=0.001)
    sleeps = []
    real_sleep = time.sleep
    agent._sleep = lambda s: (sleeps.append(s), real_sleep(min(s, 0.01)))
    result = agent.run()
    assert result.success and result.restarts == 4
    backoffs = [s for s in sleeps if s > 0.005]   # monitor polls filtered
    np.testing.assert_allclose(backoffs, [0.01, 0.02, 0.04, 0.05])
    assert [a.backoff_s for a in result.history] == \
        pytest.approx([0.01, 0.02, 0.04, 0.05, 0.0])


def test_elastic_agent_window_budget_exhausts_on_crash_loop(tmp_path):
    """Crash-looping inside the window burns the budget and fails — it
    can never succeed by simply outlasting a naive counter."""
    agent = _run_agent_child(tmp_path, """
        import sys
        sys.exit(3)
    """, max_restarts=2, restart_delay_s=0.01, backoff_jitter=0.0,
        restart_window_s=60.0, monitor_interval_s=0.01)
    result = agent.run()
    assert not result.success and result.restarts == 2
    assert result.return_code == 3 and len(result.history) == 3


def test_elastic_agent_window_budget_refills(tmp_path):
    """Failures spaced wider than the window stop counting against the
    budget: a long-lived job that dies occasionally outlives
    max_restarts total failures."""
    agent = _run_agent_child(tmp_path, """
        import os, sys, time
        marker = os.path.join(sys.argv[1], "n")
        n = int(open(marker).read()) if os.path.exists(marker) else 0
        open(marker, "w").write(str(n + 1))
        time.sleep(0.35)              # outlive the agent's budget window
        sys.exit(0 if n >= 2 else 1)
    """, max_restarts=1, restart_delay_s=0.01, backoff_jitter=0.0,
        restart_window_s=0.25, monitor_interval_s=0.02)
    result = agent.run()
    # two failures total but never two inside one window
    assert result.success and result.restarts == 2


def test_elastic_agent_preemption_resume_env(tmp_path):
    """A worker exiting with the preemption code is restarted with
    DS_RESUME=latest and does not consume the failure budget."""
    agent = _run_agent_child(tmp_path, """
        import os, sys
        sys.exit(0 if os.environ.get("DS_RESUME") == "latest" else 86)
    """, max_restarts=0, restart_delay_s=0.01, monitor_interval_s=0.01)
    result = agent.run()
    assert result.success
    assert result.restarts == 0 and result.preempt_restarts == 1
    assert result.history[0].preempted and result.history[1].resumed


# ---------------------------------------------------------------- serving
class _StubScheduler:
    """Just enough scheduler surface for loop/watchdog tests — no model,
    no compile."""

    def __init__(self, cfg, step_fn=None):
        self.cfg = cfg
        self.metrics = ServingMetrics()
        self._step_fn = step_fn
        self._step_count = 0
        self.monitor = None

    def has_work(self):
        return True

    def has_work_unlocked(self):
        return True

    @property
    def step_count(self):
        return self._step_count

    def step(self):
        if self._step_fn is not None:
            self._step_fn()
        self._step_count += 1

    def metrics_snapshot(self):
        return self.metrics.snapshot()


def _wait_for(pred, timeout=10.0, interval=0.01):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(interval)
    return False


def test_serving_loop_failure_cap_degrades():
    """ISSUE 3 satellite: N consecutive step() failures → DEGRADED +
    serving/loop_failures metric, instead of log-and-sleep forever."""
    from deepspeed_tpu.serving.server import ServingLoop

    def boom():
        raise RuntimeError("injected step failure")

    cfg = ServingConfig(max_loop_failures=3, stall_timeout_s=0)
    sched = _StubScheduler(cfg, step_fn=boom)
    loop = ServingLoop(sched)
    loop.FAILURE_SLEEP_S = 0.001
    loop.start()
    try:
        assert _wait_for(loop.health.is_degraded)
        assert loop.join(timeout=5)        # the loop exits, not spins
        assert sched.metrics.counters["loop_failures"] == 3
        assert "consecutive step failures" in loop.health.reason
        assert sched.metrics.snapshot()["serving/loop_failures"] == 3.0
    finally:
        loop.shutdown()


def test_serving_loop_failures_reset_on_success():
    from deepspeed_tpu.serving.server import ServingLoop
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] % 2:                 # alternate fail/succeed
            raise RuntimeError("transient")

    cfg = ServingConfig(max_loop_failures=3, stall_timeout_s=0)
    sched = _StubScheduler(cfg, step_fn=flaky)
    loop = ServingLoop(sched)
    loop.FAILURE_SLEEP_S = 0.001
    loop.start()
    try:
        assert _wait_for(lambda: sched.step_count >= 8)
        assert not loop.health.is_degraded()
        assert sched.metrics.counters["loop_failures"] >= 4
    finally:
        loop.shutdown()


def test_scheduler_watchdog_marks_stall_degraded_and_recovers():
    """ISSUE 3 tentpole: the watchdog (not per-handler polling) detects a
    frozen step_count and degrades the server, with a metrics counter —
    and clears its own verdict when progress resumes (a minutes-long XLA
    compile must not brick the replica until restart)."""
    cfg = ServingConfig()
    sched = _StubScheduler(cfg)            # step_count never advances
    health = HealthMonitor()
    health.mark_ready()
    dog = SchedulerWatchdog(sched, health, stall_timeout_s=0.15,
                            poll_interval_s=0.03)
    dog.start()
    try:
        assert _wait_for(health.is_degraded, timeout=5)
        assert "stalled" in health.reason
        assert sched.metrics.counters["stalls"] == 1
        sched._step_count += 1             # the wedged step completed
        assert _wait_for(lambda: health.state is HealthState.READY,
                         timeout=5)
        assert "recovered" in health.reason
    finally:
        dog.stop()


def test_scheduler_watchdog_survives_held_scheduler_lock():
    """Regression: a wedged step() holds the scheduler lock; the watchdog
    must detect the stall through lock-free reads instead of blocking on
    has_work() and joining the deadlock."""
    cfg = ServingConfig()
    sched = _StubScheduler(cfg)
    wedged = threading.Event()

    def locked_has_work():                 # what acquiring the real lock
        wedged.wait()                      # under a wedged step becomes
        return True

    sched.has_work = locked_has_work
    health = HealthMonitor()
    health.mark_ready()
    dog = SchedulerWatchdog(sched, health, stall_timeout_s=0.1,
                            poll_interval_s=0.03)
    dog.start()
    try:
        assert _wait_for(health.is_degraded, timeout=5), \
            "watchdog blocked on the scheduler lock"
    finally:
        wedged.set()
        dog.stop()


def test_health_state_machine():
    h = HealthMonitor()
    assert h.state is HealthState.STARTING and h.http_status() == 503
    assert h.mark_ready() and h.http_status() == 200 and h.is_accepting()
    assert h.begin_drain("test") and not h.is_accepting()
    assert h.http_status() == 503 and h.drain_started.is_set()
    assert not h.mark_ready()              # no un-draining
    assert h.mark_stopped()
    assert not h.begin_drain("late")       # terminal


def test_stall_timeout_env_override(monkeypatch):
    cfg = ServingConfig(stall_timeout_s=5.0)
    assert cfg.resolved_stall_timeout_s() == 5.0
    monkeypatch.setenv("DS_SERVE_STALL_TIMEOUT_S", "42.5")
    assert cfg.resolved_stall_timeout_s() == 42.5
    monkeypatch.delenv("DS_SERVE_STALL_TIMEOUT_S")
    assert ServingConfig().stall_timeout_s == 600.0   # legacy 10 x 60 s
    with pytest.raises(ValueError, match="stall_timeout_s"):
        ServingConfig(stall_timeout_s=-1)
    with pytest.raises(ValueError, match="max_loop_failures"):
        ServingConfig(max_loop_failures=-1)


def test_install_drain_handlers_sigterm():
    """SIGTERM → DRAINING; a second SIGTERM → immediate server stop."""
    from deepspeed_tpu.serving.server import install_drain_handlers
    health = HealthMonitor()
    health.mark_ready()
    stopped = threading.Event()

    class FakeHttpd:
        def shutdown(self):
            stopped.set()

    before = signal.getsignal(signal.SIGTERM)
    try:
        install_drain_handlers(health, FakeHttpd(),
                               signals=(signal.SIGTERM,))
        signal.raise_signal(signal.SIGTERM)
        assert health.is_draining()
        assert not stopped.is_set()
        signal.raise_signal(signal.SIGTERM)
        assert stopped.wait(timeout=5)
    finally:
        signal.signal(signal.SIGTERM, before)


@pytest.fixture(scope="module")
def served():
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    return m, eng


def test_kv_alloc_deny_fault_forces_preemption(served):
    """kv.alloc deny faults drive the evict/recompute-on-resume path
    deterministically — no need to actually exhaust the pool."""
    m, eng = served
    # max_fused_steps=1 routes growth through the allocate-on-decode
    # path whose exhaustion handler preempts (a denied window
    # reservation would just shrink the fused window instead)
    cfg = ServingConfig(block_size=4, num_blocks=64, max_num_seqs=2,
                        max_num_batched_tokens=64, max_fused_steps=1)
    inj = FaultInjector("kv.alloc:deny@2")
    sched = ContinuousBatchingScheduler(m, eng.params, cfg, injector=inj)
    rng = np.random.default_rng(0)
    pa = rng.integers(1, 128, (6,)).astype(np.int32)
    pb = rng.integers(1, 128, (6,)).astype(np.int32)
    ra = sched.submit(pa, SamplingParams(max_new_tokens=8), priority=1)
    rb = sched.submit(pb, SamplingParams(max_new_tokens=8), priority=0)
    sched.run_until_idle()
    assert inj.fired.get("kv.alloc") == 1
    assert sched.metrics.counters["preemptions"] >= 1
    assert ra.state == RequestState.FINISHED
    assert rb.state == RequestState.FINISHED
    for p, r in ((pa, ra), (pb, rb)):
        ref = np.asarray(eng.generate(p[None], max_new_tokens=8,
                                      do_sample=False))[0, p.size:]
        np.testing.assert_array_equal(np.asarray(r.output_ids), ref)


def _post(base, payload, timeout=60):
    req = urllib.request.Request(
        base + "/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_drain_finishes_inflight_rejects_new(served):
    """Acceptance: during a drain, in-flight requests complete and new
    /generate calls get 503; the loop then exits cleanly."""
    from deepspeed_tpu.serving.server import make_server
    m, eng = served
    cfg = ServingConfig(block_size=8, num_blocks=64, max_num_seqs=2,
                        stall_timeout_s=120)
    # pace the loop so the in-flight request is still decoding when the
    # drain begins (deterministic via the injector, not sleeps)
    inj = FaultInjector("serve.step:stall=0.02@*")
    sched = ContinuousBatchingScheduler(m, eng.params, cfg, injector=inj)
    httpd, loop = make_server(sched, port=0)
    loop.start()
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_port}"
    try:
        with urllib.request.urlopen(base + "/healthz", timeout=10) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "ready"
        prompt = np.random.default_rng(1).integers(1, 128, (5,))
        result = {}

        def _inflight():
            result["resp"] = _post(base, {"input_ids": prompt.tolist(),
                                          "max_new_tokens": 48})

        worker = threading.Thread(target=_inflight, daemon=True)
        worker.start()
        assert _wait_for(lambda: sched.active_requests(), timeout=30)
        assert loop.health.begin_drain("test drain")
        # healthz flips to 503/draining immediately
        code, body = _post(base, {"input_ids": [1, 2], "max_new_tokens": 2})
        assert code == 503 and "not accepting" in body["error"]
        try:
            with urllib.request.urlopen(base + "/healthz", timeout=10):
                pytest.fail("healthz should be 503 during drain")
        except urllib.error.HTTPError as e:
            assert e.code == 503
            assert json.loads(e.read())["status"] == "draining"
        worker.join(timeout=120)
        code, body = result["resp"]
        assert code == 200 and len(body["output_ids"]) == 48
        assert sched.metrics.counters["rejected_not_accepting"] == 1
        # loop exits on its own once drained; health lands on STOPPED
        assert loop.join(timeout=30)
        assert loop.health.state is HealthState.STOPPED
    finally:
        httpd.shutdown()
        loop.shutdown()
        httpd.server_close()


# -------------------------------------------------------- slow e2e chaos
E2E_TRAIN_SCRIPT = """
import json, os, sys

# lean single-device CPU child (the parent env forces an 8-dev mesh and
# the heap-sensitive thunk flag; neither is needed here).  NOTE: the
# persistent compile cache stays OFF — on this container's jaxlib,
# donated train steps over freshly RESTORED state under a warm
# persistent cache corrupt the glibc heap (the documented
# test_universal_checkpoint abort class), and resume-after-restart is
# this script's whole job.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_backend_optimization_level=0"
sys.path.insert(0, {root!r})

import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import deepspeed_tpu
from deepspeed_tpu.resilience import resume_tag_from_env, \\
    run_resilient_training
from tests.util import tiny_gpt2, base_config

save_dir, out_path, num_steps = sys.argv[1], sys.argv[2], int(sys.argv[3])
resumed = resume_tag_from_env() is not None
if resumed:
    # a resumed run must not replay the injected fault (the preempting
    # host is gone); counters are per-process, so drop the spec entirely
    os.environ.pop("DS_FAULTS", None)

cfg = base_config(**{{"optimizer": {{"type": "Adam",
                                    "params": {{"lr": 1e-2}}}},
                     "resilience": {{"keep_last_k": 3}}}})
engine, _, _, _ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)

def batch_for(step):
    rng = np.random.default_rng(1000 + step)
    return {{"input_ids": rng.integers(0, 128, size=(1, 4, 16),
                                       dtype=np.int32)}}

loss = run_resilient_training(engine, batch_for, save_dir,
                              num_steps=num_steps, save_interval=2)
json.dump({{"loss": float(loss), "steps": int(engine.global_steps),
            "resumed": resumed}}, open(out_path, "w"))
"""


def _write_e2e_script(tmp_path):
    script = tmp_path / "train_child.py"
    script.write_text(E2E_TRAIN_SCRIPT.format(
        root=os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    return script


def _run_reference(script, tmp_path, num_steps=8):
    out = tmp_path / "ref.json"
    env = {k: v for k, v in os.environ.items() if k != "DS_FAULTS"}
    r = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "ref_ckpt"),
         str(out), str(num_steps)],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(out.read_text())


@pytest.mark.slow
def test_e2e_hard_kill_resume_same_loss(tmp_path):
    """Acceptance: a training run hard-killed mid-step by the injector,
    supervised by DSElasticAgent with always_resume, restarts from the
    last periodic checkpoint and reaches the SAME final loss as an
    uninterrupted run."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    script = _write_e2e_script(tmp_path)
    ref = _run_reference(script, tmp_path)
    assert ref["steps"] == 8 and not ref["resumed"]

    out = tmp_path / "killed.json"
    env = dict(os.environ,
               DS_FAULTS="train.step:kill=9@5")   # dies at the 6th step
    agent = DSElasticAgent(
        [sys.executable, str(script), str(tmp_path / "ckpt"),
         str(out), "8"],
        env=env, max_restarts=2, restart_delay_s=0.05,
        monitor_interval_s=0.05, always_resume=True)
    result = agent.run()
    assert result.success and result.restarts == 1
    assert result.return_codes == [9, 0]
    assert result.history[1].resumed
    got = json.loads(out.read_text())
    assert got["steps"] == 8 and got["resumed"]
    np.testing.assert_allclose(got["loss"], ref["loss"], rtol=1e-6)


@pytest.mark.slow
def test_e2e_sigterm_drain_emergency_resume(tmp_path):
    """Acceptance: SIGTERM (self-delivered by the injector) drains
    through an emergency checkpoint + PREEMPTED exit code; the agent
    resumes WITHOUT burning the failure budget and the run converges to
    the uninterrupted loss."""
    from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent
    script = _write_e2e_script(tmp_path)
    ref = _run_reference(script, tmp_path)

    out = tmp_path / "preempted.json"
    env = dict(os.environ, DS_FAULTS="train.step:sigterm@5")
    agent = DSElasticAgent(
        [sys.executable, str(script), str(tmp_path / "ckpt"),
         str(out), "8"],
        env=env, max_restarts=0,          # resume must not need budget
        restart_delay_s=0.05, monitor_interval_s=0.05)
    result = agent.run()
    assert result.success
    assert result.restarts == 0 and result.preempt_restarts == 1
    assert result.return_codes == [PREEMPTED_EXIT_CODE, 0]
    # the drain wrote an emergency tag at the preempted step
    tags = rckpt.list_tags(str(tmp_path / "ckpt"))
    assert any(t.startswith("emergency_step") for t in tags)
    got = json.loads(out.read_text())
    assert got["steps"] == 8 and got["resumed"]
    np.testing.assert_allclose(got["loss"], ref["loss"], rtol=1e-6)


@pytest.mark.slow
def test_e2e_kill_during_save_falls_back(tmp_path):
    """Acceptance (process-kill flavor of the fault matrix): a hard kill
    DURING the checkpoint publish leaves the previous tag restorable."""
    script = _write_e2e_script(tmp_path)
    out = tmp_path / "out.json"
    env = dict(os.environ,
               # step-2 periodic save survives; the step-4 save is killed
               # mid-manifest — the process dies inside save_checkpoint
               DS_FAULTS="ckpt.manifest:kill=9@1")
    r = subprocess.run(
        [sys.executable, str(script), str(tmp_path / "ckpt"),
         str(out), "8"],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 9
    save_dir = str(tmp_path / "ckpt")
    tag = rckpt.find_valid_tag(save_dir)
    assert tag == "global_step2"
    ok, reason = verify_tag(os.path.join(save_dir, tag))
    assert ok, reason
