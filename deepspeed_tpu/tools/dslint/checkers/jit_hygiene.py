"""DSL003 — jit-boundary hygiene.

The traced/host boundary is where JAX code rots silently:

1. **Python branching on traced values** — an ``if``/``while`` on a
   (non-static) parameter of a jitted function either raises a
   ``TracerBoolConversionError`` at trace time or, worse, constant-
   folds on the first trace and silently serves stale control flow.
   Structural tests (``x is None``, ``isinstance``, ``.shape``/
   ``.ndim``/``.dtype``/``.size``/``len()`` — all static under trace)
   are exempt.
2. **Host syncs inside jitted bodies** — ``.item()``, ``.tolist()``,
   ``np.asarray``/``np.array``/``jax.device_get`` inside a jitted
   function either fail to trace or silently bake a constant.
3. **Per-item host syncs in decode/verify hot paths** — ``.item()`` /
   ``.tolist()`` in the serving hot loop (``_decode*``/``_prefill*``/
   ``*verify*`` in ``serving/``/``models/serving.py``) turn one batch
   fetch into per-token device round-trips; fetch once with
   ``np.asarray`` and index on host.
4. **Unhashable static args** — a list/dict/set literal passed at a
   ``static_argnums`` position of a known jitted callable raises
   ``ValueError: unhashable`` at call time; pass a tuple.
"""
import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..astutil import dotted as _dotted
from ..astutil import int_values as _int_values
from ..astutil import str_values as _str_values
from ..core import Checker, Finding, ModuleFile, register

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_HOST_SYNC_DOTTED = {"np.asarray", "np.array", "numpy.asarray",
                     "numpy.array", "jax.device_get", "onp.asarray",
                     "onp.array"}
_HOST_SYNC_METHODS = {"item", "tolist"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}

_HOT_PATH_FILE_RE = re.compile(r"(serving/.*\.py|models/serving\.py)$")
_HOT_PATH_FN_RE = re.compile(
    r"(^_decode|^_spec_decode|^_prefill|^_window|^_run_window|^_chunk"
    r"|verify)")


def _jit_call_info(call: ast.Call) -> Optional[Tuple[Set[int], Set[str]]]:
    """(static_argnums, static_argnames) when ``call`` is jax.jit(...)."""
    if _dotted(call.func) not in _JIT_NAMES:
        # functools.partial(jax.jit, ...) decorator form
        if _dotted(call.func) in ("partial", "functools.partial") \
                and call.args and _dotted(call.args[0]) in _JIT_NAMES:
            pass
        else:
            return None
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            nums |= _int_values(kw.value)
        elif kw.arg == "static_argnames":
            names |= _str_values(kw.value)
    return nums, names


class _JitIndex:
    """Which function defs are jitted, and with what static args."""

    def __init__(self, tree: ast.AST):
        #: id(FunctionDef) -> (static_argnums, static_argnames)
        self.jitted: Dict[int, Tuple[Set[int], Set[str]]] = {}
        #: local binding name -> (static_argnums, static_argnames) for
        #: call-site checks (rule 4)
        self.bindings: Dict[str, Tuple[Set[int], Set[str]]] = {}
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
                info = self._decorated(node)
                if info is not None:
                    self.jitted[id(node)] = info
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            info = _jit_call_info(node)
            if info is None:
                continue
            # jax.jit(fn, ...) — mark the wrapped local def as jitted
            if node.args and isinstance(node.args[0], ast.Name):
                target = defs.get(node.args[0].id)
                if target is not None:
                    self.jitted[id(target)] = info
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                info = _jit_call_info(node.value)
                if info is not None:
                    for t in node.targets:
                        name = _dotted(t)
                        if name:
                            self.bindings[name] = info

    @staticmethod
    def _decorated(fn) -> Optional[Tuple[Set[int], Set[str]]]:
        for dec in fn.decorator_list:
            if _dotted(dec) in _JIT_NAMES:
                return set(), set()
            if isinstance(dec, ast.Call):
                d = _dotted(dec.func)
                if d in _JIT_NAMES:
                    return _jit_call_info(dec) or (set(), set())
                if d in ("partial", "functools.partial") and dec.args \
                        and _dotted(dec.args[0]) in _JIT_NAMES:
                    return _jit_call_info(dec) or (set(), set())
        return None


@register
class JitHygieneChecker(Checker):
    rule = "DSL003"
    name = "jit-boundary-hygiene"
    doc = ("no Python branches on traced values or host syncs in jitted "
           "bodies; no per-item .item() syncs in decode/verify hot "
           "paths; static args must be hashable")

    def check(self, mod: ModuleFile, inv) -> Iterable[Finding]:
        findings: List[Finding] = []
        index = _JitIndex(mod.tree)
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info = index.jitted.get(id(node))
                if info is not None:
                    self._check_jitted_body(mod, node, info, findings)
                elif (_HOT_PATH_FILE_RE.search(mod.relpath)
                        and _HOT_PATH_FN_RE.search(node.name)):
                    self._check_hot_path(mod, node, findings)
            elif isinstance(node, ast.Call):
                self._check_static_call(mod, node, index, findings)
        return findings

    # ------------------------------------------------------- jitted body
    def _check_jitted_body(self, mod, fn, info, findings):
        nums, names = info
        params = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
        static = {p for i, p in enumerate(params) if i in nums}
        static |= {p for p in params if p in names}
        traced = {p for p in params if p not in static and p != "self"}
        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                bad = self._traced_names_in_test(node.test, traced)
                if bad:
                    findings.append(self.finding(
                        mod, node,
                        f"Python '{'if' if isinstance(node, ast.If) else 'while'}'"
                        f" on traced value(s) {sorted(bad)} inside "
                        f"jitted '{fn.name}' — use jnp.where/lax.cond "
                        "or mark the arg static"))
            elif isinstance(node, ast.Call):
                sync = self._host_sync(node)
                if sync is not None:
                    findings.append(self.finding(
                        mod, node,
                        f"host sync {sync} inside jitted '{fn.name}' — "
                        "this either fails to trace or bakes a "
                        "constant; move it outside the jit boundary"))

    @staticmethod
    def _host_sync(call: ast.Call) -> Optional[str]:
        key = _dotted(call.func)
        if key in _HOST_SYNC_DOTTED:
            return key
        if isinstance(call.func, ast.Attribute) and \
                call.func.attr in _HOST_SYNC_METHODS:
            return f".{call.func.attr}()"
        return None

    def _traced_names_in_test(self, test, traced: Set[str]) -> Set[str]:
        """Names of traced params used non-structurally in a test."""
        bad: Set[str] = set()

        def visit(node, benign: bool):
            if isinstance(node, ast.Name):
                if not benign and node.id in traced:
                    bad.add(node.id)
                return
            # x.shape / x.ndim / x.dtype / x.size are static under trace
            if isinstance(node, ast.Attribute) and \
                    node.attr in _STATIC_ATTRS:
                return
            if isinstance(node, ast.Call):
                d = _dotted(node.func)
                if d in ("isinstance", "len", "hasattr", "getattr",
                         "callable", "type"):
                    return
                for a in list(node.args) + [k.value for k in
                                            node.keywords]:
                    visit(a, benign)
                return
            if isinstance(node, ast.Compare):
                ops = node.ops
                # `x is None` / `x is not None` are structural
                if all(isinstance(o, (ast.Is, ast.IsNot)) for o in ops):
                    comparators = [node.left] + node.comparators
                    if any(isinstance(c, ast.Constant)
                           and c.value is None for c in comparators):
                        return
            for child in ast.iter_child_nodes(node):
                visit(child, benign)

        visit(test, False)
        return bad

    # --------------------------------------------------------- hot paths
    def _check_hot_path(self, mod, fn, findings):
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _HOST_SYNC_METHODS:
                findings.append(self.finding(
                    mod, node,
                    f"per-item host sync .{node.func.attr}() in serving "
                    f"hot path '{fn.name}' — each call is a device "
                    "round-trip; fetch the batch once with np.asarray "
                    "and index on host"))

    # ------------------------------------------------------- static args
    def _check_static_call(self, mod, call, index: _JitIndex, findings):
        key = _dotted(call.func)
        info = index.bindings.get(key) if key else None
        if info is None and isinstance(call.func, ast.Call):
            info = _jit_call_info(call.func)
        if info is None:
            return
        nums, names = info
        bad_args = [(i, a) for i, a in enumerate(call.args) if i in nums]
        bad_args += [(kw.arg, kw.value) for kw in call.keywords
                     if kw.arg in names]
        for where, arg in bad_args:
            if isinstance(arg, (ast.List, ast.Dict, ast.Set)):
                kind = type(arg).__name__.lower()
                findings.append(self.finding(
                    mod, arg,
                    f"unhashable {kind} literal passed at static arg "
                    f"{where!r} of jitted '{key}' — static args are "
                    "hashed for the compile cache; pass a tuple"))
