"""MFU / goodput accounting (ISSUE 4 tentpole).

Model FLOPs Utilization in the Megatron-LM sense: the model's useful
FLOPs per second (``flops_per_token × tokens / step_wall_clock`` for
training, or the XLA ``compiled_cost`` of the step) divided by the
hardware peak.  Peak FLOPs resolve per device kind from a small table
(bf16 dense peak per chip), overridable with ``DS_PEAK_FLOPS`` (per
device) for parts the table has not met — on CPU there is no meaningful
peak, so MFU reports only when the env var or the ``telemetry.
peak_flops`` config key supplies one.

Goodput is work that survived: for serving, tokens generated minus
tokens recomputed after preemption (recompute-on-resume re-prefilled
them); for training, steps not lost to a restart.
"""
import os
from typing import Optional

PEAK_FLOPS_ENV = "DS_PEAK_FLOPS"

#: dense bf16 peak FLOPs per chip by device-kind substring (lowercase).
#: Sources: published TPU system specs (per-chip, not per-core).
PEAK_FLOPS_BY_KIND = {
    "v5p": 459e12,
    "v5e": 197e12,
    "v5litepod": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}


def peak_flops_per_device(device=None, env: Optional[dict] = None
                          ) -> Optional[float]:
    """Peak FLOPs for one device: DS_PEAK_FLOPS env wins, then the
    device-kind table; None when unknown (CPU, exotic parts) — callers
    skip the MFU gauge rather than report against a made-up peak."""
    env = os.environ if env is None else env
    override = env.get(PEAK_FLOPS_ENV, "").strip()
    if override:
        return float(override)
    if device is None:
        import jax
        device = jax.local_devices()[0]
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, peak in PEAK_FLOPS_BY_KIND.items():
        if sub in kind:
            return peak
    return None


def total_peak_flops(env: Optional[dict] = None) -> Optional[float]:
    """Aggregate peak across this process's local devices (per-host MFU:
    each host rates its own step against its own chips)."""
    import jax
    devs = jax.local_devices()
    per = peak_flops_per_device(devs[0], env=env)
    if per is None:
        return None
    return per * len(devs)


def mfu(model_flops: float, duration_s: float,
        peak_flops: float) -> Optional[float]:
    """Achieved / peak, as a fraction in [0, ~1].  None on degenerate
    inputs instead of inf/NaN leaking into a gauge."""
    if duration_s <= 0 or peak_flops <= 0 or model_flops < 0:
        return None
    return (model_flops / duration_s) / peak_flops


def tokens_per_second(tokens: float, duration_s: float) -> Optional[float]:
    if duration_s <= 0:
        return None
    return tokens / duration_s


def serving_goodput(useful_tokens: float, wasted_tokens: float) -> float:
    """Fraction of generated-token work that was not thrown away to
    preemption recompute.  1.0 when nothing was wasted (including the
    zero-work case — an idle server has not wasted anything)."""
    total = useful_tokens + wasted_tokens
    if total <= 0:
        return 1.0
    return useful_tokens / total
