from deepspeed_tpu.ops.attention import causal_attention
from deepspeed_tpu.ops.pallas.qgemm import ds_qgemm
