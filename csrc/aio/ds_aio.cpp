// Async file I/O for the ZeRO-Infinity NVMe tier (reference capability:
// csrc/aio/ — the libaio O_DIRECT submit/wait queues behind the pybind
// `aio_handle`, deepspeed_py_aio_handle.cpp + deepspeed_aio_common.cpp).
//
// Two backends, selected at runtime:
//  - io_uring via raw syscalls (__NR_io_uring_setup/enter + the uapi
//    header; this environment has no liburing, but queue-depth async I/O
//    needs nothing beyond the kernel).  A reaper thread drains the CQ and
//    marks completions.
//  - std::thread worker pool issuing positional pread/pwrite, for kernels
//    or sandboxes where io_uring_setup is refused (EPERM/ENOSYS).
//
// Both backends complete PER REQUEST: every submit returns an id and
// `ds_aio_wait_req(id)` blocks on that request alone — a read can complete
// while writes are still in flight, which is what the double-buffered
// optimizer-state swap pipeline (runtime/swap_tensor/swapper.py) needs.
// The round-4 version exposed only a global drain, which serialized the
// swap-in(i+1)/swap-out(i-1)/step(i) loop.
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <unordered_map>
#include <vector>

#if __has_include(<linux/io_uring.h>)
#include <linux/io_uring.h>
#include <sys/mman.h>
#include <sys/syscall.h>
#define DS_HAVE_URING 1
#endif

namespace {

struct Request {
  int op;  // 0 = read, 1 = write
  char* buf;
  size_t count;
  size_t offset;
  int fd;
  long id;
  double t_submit;  // steady-clock seconds at submit (I/O telemetry)
};

static double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

#ifdef DS_HAVE_URING
static int sys_io_uring_setup(unsigned entries, struct io_uring_params* p) {
  return (int)syscall(__NR_io_uring_setup, entries, p);
}
static int sys_io_uring_enter(int fd, unsigned to_submit,
                              unsigned min_complete, unsigned flags) {
  return (int)syscall(__NR_io_uring_enter, fd, to_submit, min_complete,
                      flags, nullptr, 0);
}

struct Uring {
  int fd = -1;
  unsigned sq_entries = 0, cq_entries = 0;
  unsigned *sq_head = nullptr, *sq_tail = nullptr, *sq_mask = nullptr;
  unsigned* sq_array = nullptr;
  io_uring_sqe* sqes = nullptr;
  unsigned *cq_head = nullptr, *cq_tail = nullptr, *cq_mask = nullptr;
  io_uring_cqe* cqes = nullptr;
  void* sq_ring_ptr = nullptr;
  void* cq_ring_ptr = nullptr;
  size_t sq_ring_sz = 0, cq_ring_sz = 0, sqes_sz = 0;

  bool init(unsigned entries) {
    io_uring_params p;
    memset(&p, 0, sizeof(p));
    fd = sys_io_uring_setup(entries, &p);
    if (fd < 0) return false;
    sq_entries = p.sq_entries;
    cq_entries = p.cq_entries;
    sq_ring_sz = p.sq_off.array + p.sq_entries * sizeof(unsigned);
    cq_ring_sz = p.cq_off.cqes + p.cq_entries * sizeof(io_uring_cqe);
    bool single_mmap = p.features & IORING_FEAT_SINGLE_MMAP;
    if (single_mmap && cq_ring_sz > sq_ring_sz) sq_ring_sz = cq_ring_sz;
    sq_ring_ptr = mmap(nullptr, sq_ring_sz, PROT_READ | PROT_WRITE,
                       MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_SQ_RING);
    if (sq_ring_ptr == MAP_FAILED) { close(fd); fd = -1; return false; }
    if (single_mmap) {
      cq_ring_ptr = sq_ring_ptr;
      cq_ring_sz = sq_ring_sz;
    } else {
      cq_ring_ptr = mmap(nullptr, cq_ring_sz, PROT_READ | PROT_WRITE,
                         MAP_SHARED | MAP_POPULATE, fd, IORING_OFF_CQ_RING);
      if (cq_ring_ptr == MAP_FAILED) { close(fd); fd = -1; return false; }
    }
    sqes_sz = p.sq_entries * sizeof(io_uring_sqe);
    sqes = (io_uring_sqe*)mmap(nullptr, sqes_sz, PROT_READ | PROT_WRITE,
                               MAP_SHARED | MAP_POPULATE, fd,
                               IORING_OFF_SQES);
    if (sqes == MAP_FAILED) { close(fd); fd = -1; return false; }
    char* sq = (char*)sq_ring_ptr;
    sq_head = (unsigned*)(sq + p.sq_off.head);
    sq_tail = (unsigned*)(sq + p.sq_off.tail);
    sq_mask = (unsigned*)(sq + p.sq_off.ring_mask);
    sq_array = (unsigned*)(sq + p.sq_off.array);
    char* cq = (char*)cq_ring_ptr;
    cq_head = (unsigned*)(cq + p.cq_off.head);
    cq_tail = (unsigned*)(cq + p.cq_off.tail);
    cq_mask = (unsigned*)(cq + p.cq_off.ring_mask);
    cqes = (io_uring_cqe*)(cq + p.cq_off.cqes);
    return true;
  }

  void destroy() {
    if (fd < 0) return;
    if (sqes && sqes != MAP_FAILED) munmap(sqes, sqes_sz);
    if (cq_ring_ptr && cq_ring_ptr != sq_ring_ptr)
      munmap(cq_ring_ptr, cq_ring_sz);
    if (sq_ring_ptr && sq_ring_ptr != MAP_FAILED)
      munmap(sq_ring_ptr, sq_ring_sz);
    close(fd);
    fd = -1;
  }
};
#endif  // DS_HAVE_URING

struct Handle {
  std::mutex mu;
  std::condition_variable cv_work;   // threadpool: work available
  std::condition_variable cv_done;   // a request completed
  std::unordered_map<long, int> completed;  // id -> 0 ok / -1 failed
  //: id -> submit->completion seconds, measured entirely backend-side —
  //: the Python caller's submit->wait window includes arbitrary caller
  //: delay (fire-and-forget writes reaped a whole step later), which is
  //: NOT device bandwidth
  std::unordered_map<long, double> completed_dur;
  std::unordered_map<long, Request> pending; // id -> request (for resume)
  long next_id = 1;
  std::atomic<long> inflight{0};
  long drain_errors = 0;  // errors seen since last wait-all
  bool stop = false;

  // threadpool backend
  std::deque<Request> queue;
  std::vector<std::thread> workers;

#ifdef DS_HAVE_URING
  Uring ring;
  std::thread reaper;
#endif
  bool use_uring = false;

  explicit Handle(int n_threads) {
#ifdef DS_HAVE_URING
    const char* no_uring = getenv("DS_AIO_NO_URING");
    if (!(no_uring && no_uring[0] == '1') && ring.init(128)) {
      use_uring = true;
      reaper = std::thread([this] { reap(); });
      return;
    }
#endif
    for (int i = 0; i < (n_threads > 0 ? n_threads : 1); ++i)
      workers.emplace_back([this] { run(); });
  }

  ~Handle() {
#ifdef DS_HAVE_URING
    if (use_uring) {
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_done.wait(lk, [this] { return inflight.load() == 0; });
        stop = true;
        submit_nop_locked();  // wake the reaper
      }
      reaper.join();
      ring.destroy();
      return;
    }
#endif
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
  }

  // ---------------------------------------------------------------- submit
  long submit(int op, char* buf, size_t count, size_t offset, int fd) {
    std::unique_lock<std::mutex> lk(mu);
    long id = next_id++;
    Request r{op, buf, count, offset, fd, id, now_s()};
    inflight.fetch_add(1);
    pending[id] = r;
#ifdef DS_HAVE_URING
    if (use_uring) {
      submit_sqe_locked(r);
      return id;
    }
#endif
    queue.push_back(r);
    lk.unlock();
    cv_work.notify_one();
    return id;
  }

  void finish(long id, int err) {  // mu held
    auto it = pending.find(id);
    if (it != pending.end()) {
      close(it->second.fd);
      completed_dur[id] = now_s() - it->second.t_submit;
      pending.erase(it);
    }
    completed[id] = err;
    if (err) drain_errors++;
    inflight.fetch_sub(1);
    cv_done.notify_all();
  }

  // ------------------------------------------------------------ threadpool
  static bool do_io(const Request& r) {
    size_t done = 0;
    while (done < r.count) {
      ssize_t rc = (r.op == 0)
          ? pread(r.fd, r.buf + done, r.count - done, r.offset + done)
          : pwrite(r.fd, r.buf + done, r.count - done, r.offset + done);
      if (rc <= 0) return false;
      done += (size_t)rc;
    }
    return true;
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_work.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        r = queue.front();
        queue.pop_front();
      }
      bool ok = do_io(r);
      std::lock_guard<std::mutex> lk(mu);
      finish(r.id, ok ? 0 : -1);
    }
  }

  // -------------------------------------------------------------- io_uring
#ifdef DS_HAVE_URING
  void submit_sqe_locked(const Request& r) {
    // cap at ring capacity: wait for the reaper to free a slot
    unsigned head = __atomic_load_n(ring.sq_head, __ATOMIC_ACQUIRE);
    unsigned tail = *ring.sq_tail;
    while (tail - head >= ring.sq_entries) {
      // ring full — rare (128 deep); spin briefly off-lock
      mu.unlock();
      std::this_thread::yield();
      mu.lock();
      head = __atomic_load_n(ring.sq_head, __ATOMIC_ACQUIRE);
      tail = *ring.sq_tail;
    }
    unsigned idx = tail & *ring.sq_mask;
    io_uring_sqe* sqe = &ring.sqes[idx];
    memset(sqe, 0, sizeof(*sqe));
    sqe->opcode = (r.id == 0) ? IORING_OP_NOP
                              : (r.op == 0 ? IORING_OP_READ : IORING_OP_WRITE);
    sqe->fd = r.fd;
    sqe->addr = (unsigned long long)r.buf;
    sqe->len = (unsigned)r.count;
    sqe->off = r.offset;
    sqe->user_data = (unsigned long long)r.id;
    ring.sq_array[idx] = idx;
    __atomic_store_n(ring.sq_tail, tail + 1, __ATOMIC_RELEASE);
    sys_io_uring_enter(ring.fd, 1, 0, 0);
  }

  void submit_nop_locked() {
    Request nop{0, nullptr, 0, 0, -1, 0};
    submit_sqe_locked(nop);
  }

  void reap() {
    for (;;) {
      int rc = sys_io_uring_enter(ring.fd, 0, 1, IORING_ENTER_GETEVENTS);
      (void)rc;
      std::unique_lock<std::mutex> lk(mu);
      unsigned head = *ring.cq_head;
      unsigned tail = __atomic_load_n(ring.cq_tail, __ATOMIC_ACQUIRE);
      while (head != tail) {
        io_uring_cqe* cqe = &ring.cqes[head & *ring.cq_mask];
        long id = (long)cqe->user_data;
        int res = cqe->res;
        ++head;
        if (id == 0) continue;  // shutdown NOP
        auto it = pending.find(id);
        if (it == pending.end()) continue;
        Request r = it->second;
        if (res < 0) {
          finish(id, -1);
        } else if ((size_t)res < r.count) {
          // short transfer (regular files: rare) — finish synchronously
          Request rest = r;
          rest.buf += res;
          rest.count -= res;
          rest.offset += res;
          lk.unlock();
          bool ok = do_io(rest);
          lk.lock();
          finish(id, ok ? 0 : -1);
        } else {
          finish(id, 0);
        }
      }
      __atomic_store_n(ring.cq_head, head, __ATOMIC_RELEASE);
      if (stop && pending.empty()) return;
    }
  }
#endif  // DS_HAVE_URING

  // ------------------------------------------------------------------ wait
  int wait_req(long id) { return wait_req_dur(id, nullptr); }

  int wait_req_dur(long id, double* dur) {
    if (dur) *dur = 0.0;
    std::unique_lock<std::mutex> lk(mu);
    for (;;) {
      auto it = completed.find(id);
      if (it != completed.end()) {
        int err = it->second;
        completed.erase(it);
        auto dt = completed_dur.find(id);
        if (dt != completed_dur.end()) {
          if (dur) *dur = dt->second;
          completed_dur.erase(dt);
        }
        if (err) drain_errors--;  // consumed by this per-request wait
        return err;
      }
      // unknown id (already consumed by wait_req or a full drain):
      // return instead of blocking forever.  `pending` covers queued
      // thread-pool requests too (populated at submit, erased at finish).
      if (!pending.count(id)) return -2;
      cv_done.wait(lk);
    }
  }

  long wait_all() {
    std::unique_lock<std::mutex> lk(mu);
    cv_done.wait(lk, [this] { return inflight.load() == 0; });
    long errs = drain_errors;
    drain_errors = 0;
    completed.clear();  // fire-and-forget ids are spent at a full drain
    completed_dur.clear();
    return errs;
  }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int n_threads) { return new Handle(n_threads); }

void ds_aio_handle_free(void* h) { delete (Handle*)h; }

// 1 if the queue-depth io_uring backend is live, 0 for the thread pool
int ds_aio_backend(void* h) { return ((Handle*)h)->use_uring ? 1 : 0; }

// submit: returns a positive request id, or -1 on open failure
long ds_aio_submit_pread(void* h, const char* path, char* buf, size_t count,
                         size_t offset) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  return ((Handle*)h)->submit(0, buf, count, offset, fd);
}

long ds_aio_submit_pwrite(void* h, const char* path, char* buf, size_t count,
                          size_t offset) {
  int fd = open(path, O_WRONLY | O_CREAT, 0644);
  if (fd < 0) return -1;
  return ((Handle*)h)->submit(1, buf, count, offset, fd);
}

// block until ONE request completes; 0 ok, -1 I/O failure
int ds_aio_wait_req(void* h, long id) { return ((Handle*)h)->wait_req(id); }

// wait_req + the request's backend-measured submit->completion seconds
// (0.0 when unknown) — the honest bandwidth window for a request the
// caller reaped long after it completed (ISSUE 14 I/O telemetry)
int ds_aio_wait_req_dur(void* h, long id, double* dur) {
  return ((Handle*)h)->wait_req_dur(id, dur);
}

// legacy submit API (round-4 ABI): 0 on successful submit, -1 on failure
int ds_aio_pread(void* h, const char* path, char* buf, size_t count,
                 size_t offset) {
  return ds_aio_submit_pread(h, path, buf, count, offset) > 0 ? 0 : -1;
}

int ds_aio_pwrite(void* h, const char* path, char* buf, size_t count,
                  size_t offset) {
  return ds_aio_submit_pwrite(h, path, buf, count, offset) > 0 ? 0 : -1;
}

// drain all in-flight requests; returns number of failed requests since the
// previous full drain (per-request waits subtract the errors they consume)
long ds_aio_wait(void* h) { return ((Handle*)h)->wait_all(); }

long ds_aio_inflight(void* h) { return ((Handle*)h)->inflight.load(); }

}  // extern "C"
