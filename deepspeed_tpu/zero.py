"""deepspeed.zero-compatible public API surface (reference:
deepspeed/runtime/zero/partition_parameters.py ``Init`` :707 and
``GatheredParameters`` :1936 — the two context managers user code
imports as ``deepspeed.zero.*``).

TPU-native semantics:

- ``Init`` — the reference patches module construction so every param is
  born partitioned.  Here params are ALWAYS born sharded (the engine
  jits model init with ZeRO out_shardings), so ``Init`` is an alias of
  ``utils.init_on_device.OnDevice``: inside it, ``abstract_init`` builds
  shapes only (meta construction), and ``materialize`` lands real params
  directly in sharded storage.
- ``GatheredParameters`` — the reference gathers partitioned params so
  rank ``modifier_rank`` can read/modify them, re-partitioning on exit.
  Here the context yields MUTABLE host (numpy) copies of the engine's
  param tree; on exit the (possibly edited) tree is device_put back with
  the engine's original shardings and dtypes.  Passing a bare pytree
  yields read-only host copies (nothing to write back to).
"""
import jax
import numpy as np

from deepspeed_tpu.utils.init_on_device import (  # noqa: F401
    OnDevice, abstract_init, materialize)


class Init(OnDevice):
    """reference partition_parameters.py:707 — accepts (and ignores) the
    torch-specific ctor arguments so reference call sites port verbatim."""

    def __init__(self, module=None, data_parallel_group=None,
                 mem_efficient_linear=True, remote_device=None,
                 pin_memory=False, config_dict_or_path=None, config=None,
                 enabled=True, dtype=None, mpu=None, sequence_data_parallel_group=None):
        super().__init__(dtype=dtype, device="meta", enabled=enabled)


class GatheredParameters:
    """reference partition_parameters.py:1936.

    with zero.GatheredParameters(engine) as host_params:
        host_params["wte"][0] = 0.0        # surgical weight edit
    # exit: written back sharded, original dtypes
    """

    def __init__(self, params, modifier_rank=0, fwd_module=None,
                 enabled=True):
        self._engine = params if hasattr(params, "state") else None
        self._tree = None if self._engine is not None else params
        self.enabled = enabled
        self._host = None

    def __enter__(self):
        src = (self._engine.state["params"] if self._engine is not None
               else self._tree)
        if not self.enabled:
            # reference semantics: no gather, no write-back — but the
            # conditional-gather idiom still reads inside the block, so
            # yield (read-only) host copies rather than None
            return jax.tree.map(lambda x: np.array(x), src)
        self._host = jax.tree.map(lambda x: np.array(x), src)
        return self._host

    def __exit__(self, *exc):
        if self.enabled and self._engine is not None and exc[0] is None:
            src = self._engine.state["params"]
            shardings = self._engine.state_shardings["params"]
            new = jax.tree.map(
                lambda h, old: jax.numpy.asarray(h, old.dtype),
                self._host, src)
            self._engine.state["params"] = jax.device_put(new, shardings)
        return False
