"""Speculative decoding subsystem (ISSUE 5 tentpole): proposer/verifier
pipeline with paged-KV rollback over the continuous-batching scheduler.

The load-bearing contracts:
- GREEDY spec decoding (ngram or draft-model proposer, any draft
  quality) is token-for-token identical to plain cb decode — including
  the int8 KV-cache pool, across preemption/resume, and when every
  verify degrades through the ``serve.spec`` fault site;
- SAMPLED spec decoding preserves the target distribution exactly
  (Leviathan rejection sampling against deterministic drafts, verified
  statistically at the acceptance-math layer);
- rejected suffixes roll back through ``BlockManager.truncate`` without
  double-freeing or leaking blocks (invariant asserted every scheduler
  step in these debug runs);
- per-request adaptive draft length grows on acceptance, shrinks on
  rejection, and ``min_accept_rate`` auto-disables speculation for
  unspeculatable requests;
- telemetry: serve/draft + serve/verify spans share the request
  correlation id, and /metrics exposes serve/spec_accept_len quantiles.
"""
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.config import ServingConfig
from deepspeed_tpu.serving import (BlockManager, ContinuousBatchingScheduler,
                                   DraftModelProposer, NgramProposer,
                                   Proposer, RequestState, SamplingParams)
from tests.util import tiny_gpt2


@pytest.fixture(autouse=True)
def _debug_invariant(monkeypatch):
    """Every scheduler built in this file asserts the block-accounting
    invariant after every step (DS_SERVE_DEBUG — off in production, the
    scan is O(num_blocks) inside the scheduler lock)."""
    monkeypatch.setenv("DS_SERVE_DEBUG", "1")


@pytest.fixture(scope="module")
def served():
    m = tiny_gpt2()
    eng = deepspeed_tpu.init_inference(model=m, config={"dtype": "float32"})
    return m, eng


def _mixed_prompts(n=3, seed=0, lo=3, hi=12):
    rng = np.random.default_rng(seed)
    return [rng.integers(1, 128, (int(L),)).astype(np.int32)
            for L in rng.integers(lo, hi, n)]


def _static_reference(eng, prompt, max_new):
    return np.asarray(eng.generate(prompt[None], max_new_tokens=max_new,
                                   do_sample=False))[0, prompt.size:]


def _spec_cfg(mode="ngram", **kw):
    spec = {"mode": mode}
    spec.update(kw.pop("spec", {}))
    base = dict(block_size=8, num_blocks=64, max_num_seqs=4,
                max_num_batched_tokens=256, spec=spec)
    base.update(kw)
    return ServingConfig(**base)


# ------------------------------------------------- block manager rollback
def test_truncate_returns_whole_blocks():
    bm = BlockManager(num_blocks=10, block_size=4)
    bm.allocate(1, 5)                       # covers 20 positions
    assert bm.num_free_blocks == 4
    freed = bm.truncate(1, 9)               # 9 tokens -> 3 blocks
    assert freed == 2
    assert len(bm.block_table(1)) == 3
    assert bm.num_free_blocks == 6
    bm.check_invariant()
    # regrow after the shrink: freshly freed blocks come back cleanly
    assert bm.allocate(1, 3) is not None
    assert len(bm.block_table(1)) == 6
    bm.check_invariant()
    # truncate to fewer tokens than one block keeps the minimum block
    bm2 = BlockManager(num_blocks=5, block_size=4)
    bm2.allocate(7, 3)
    assert bm2.truncate(7, 1) == 2 and len(bm2.block_table(7)) == 1
    bm2.check_invariant()


def test_truncate_free_idempotent_no_double_free():
    bm = BlockManager(num_blocks=8, block_size=4)
    bm.allocate(1, 4)
    assert bm.truncate(1, 100) == 0         # growth request: no-op
    assert bm.truncate(99, 4) == 0          # unknown request: no-op
    bm.free(1)
    assert bm.truncate(1, 4) == 0           # after free: table is gone
    bm.free(1)                              # idempotent, not a double-free
    assert bm.num_free_blocks == bm.num_usable_blocks
    bm.check_invariant()
    # shrink/regrow churn never leaks or double-frees
    for i in range(20):
        bm.allocate(2, 1 + i % 5)
        bm.truncate(2, 1 + (i % 3) * 4)
        bm.check_invariant()
    bm.free(2)
    bm.check_invariant()
    assert bm.num_free_blocks == bm.num_usable_blocks


def test_invariant_detects_corruption():
    bm = BlockManager(num_blocks=8, block_size=4)
    bm.allocate(1, 2)
    bm._free.append(bm.block_table(1)[0])   # simulate a double-free
    with pytest.raises(AssertionError, match="live and free"):
        bm.check_invariant()


# ---------------------------------------------------------- ngram proposer
def test_ngram_proposer_lookup():
    class R:
        def __init__(self, ids):
            self.all_token_ids = np.asarray(ids, np.int32)

    p = NgramProposer(ngram_max=3, ngram_min=1)
    # suffix [7, 8] occurred earlier; continuation = [9, 4]
    d = p.propose(R([1, 7, 8, 9, 4, 2, 7, 8]), 2)
    np.testing.assert_array_equal(d, [9, 4])
    # k clipping
    assert p.propose(R([1, 7, 8, 9, 4, 2, 7, 8]), 1).tolist() == [9]
    # no earlier occurrence of any suffix n-gram -> no proposal
    assert p.propose(R([1, 2, 3, 4, 5]), 4).size == 0
    # period-2 repetition: a full-k draft continues the cycle
    d = p.propose(R([5, 6] * 6), 4)
    np.testing.assert_array_equal(d, [5, 6, 5, 6])
    # min_ngram=2 refuses the 1-gram-only match
    p2 = NgramProposer(ngram_max=3, ngram_min=2)
    assert p2.propose(R([1, 2, 3, 9, 4, 3]), 2).size == 0


# ----------------------------------------------------------- greedy parity
def test_spec_ngram_matches_plain_cb(served):
    """Acceptance: greedy spec-ngram == plain cb == static generate,
    token for token, on mixed-length prompts (repetitive and not)."""
    m, eng = served
    prompts = _mixed_prompts(4, seed=1)
    # add a strongly repetitive prompt (the ngram-friendly regime)
    prompts.append(np.tile(np.asarray([9, 23, 4], np.int32), 5))
    max_new = [16, 9, 20, 12, 24]
    sched = ContinuousBatchingScheduler(m, eng.params, _spec_cfg())
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=mn))
            for p, mn in zip(prompts, max_new)]
    sched.run_until_idle()
    for p, mn, r in zip(prompts, max_new, reqs):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, mn))
    c = sched.metrics.counters
    assert c["spec_verify_steps"] > 0 and c["spec_accepted_tokens"] > 0
    assert sched.block_mgr.num_allocated_blocks == 0


def test_spec_ngram_matches_plain_cb_int8_kv(served):
    """Same parity over the quantized KV pool: drafted KV vectors
    quantize exactly as sequential decode's would."""
    m, _ = served
    eng8 = deepspeed_tpu.init_inference(
        model=m, config={"dtype": "float32", "kv_cache_dtype": "int8"})
    sched = ContinuousBatchingScheduler(m, eng8.params, _spec_cfg(),
                                        kv_cache_dtype="int8")
    prompts = _mixed_prompts(3, seed=2)
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=8))
            for p in prompts]
    sched.run_until_idle()
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng8, p, 8))


def test_spec_parity_across_preemption(served):
    """Pool exhaustion under spec mode: the victim evicts (its draft
    state releases), resumes by recompute, and greedy output still
    matches exactly; block accounting drains to zero."""
    m, eng = served
    # 7 usable blocks x 4 = 28 positions; each request needs 6 of them
    # (6+16=22 positions) while the other always holds >= 2: eviction is
    # unavoidable no matter how spec bursts interleave completions
    cfg = ServingConfig(block_size=4, num_blocks=8, max_num_seqs=2,
                        max_num_batched_tokens=64,
                        spec={"mode": "ngram", "max_draft_tokens": 4})
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    pa, pb = _mixed_prompts(2, seed=6, lo=6, hi=7)
    ra = sched.submit(pa, SamplingParams(max_new_tokens=16), priority=1)
    rb = sched.submit(pb, SamplingParams(max_new_tokens=16), priority=0)
    sched.run_until_idle()
    assert sched.metrics.counters["preemptions"] >= 1
    assert rb.num_preemptions >= 1          # lower priority = the victim
    for p, r in ((pa, ra), (pb, rb)):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, 16))
    assert sched.block_mgr.num_allocated_blocks == 0


def test_spec_eos_in_accepted_prefix(served):
    """An accepted draft token that IS the eos finishes the request
    there; the rest of the window discards and every block frees."""
    m, eng = served
    prompt = np.tile(np.asarray([9, 23, 4], np.int32), 5)
    ref = _static_reference(eng, prompt, 12)
    eos = int(ref[5])
    stop = int(np.nonzero(ref == eos)[0][0])
    sched = ContinuousBatchingScheduler(m, eng.params, _spec_cfg())
    r = sched.submit(prompt, SamplingParams(max_new_tokens=12,
                                            eos_token_id=eos))
    sched.run_until_idle()
    np.testing.assert_array_equal(np.asarray(r.output_ids),
                                  ref[:stop + 1])
    assert sched.block_mgr.num_allocated_blocks == 0


def test_spec_scan_verify_fallback(served, monkeypatch):
    """DS_SPEC_VERIFY=scan routes verification through the
    scan-of-decode_fn fallback (the path families without a native
    verify_fn get) — parity must be bitwise there too."""
    m, eng = served
    monkeypatch.setenv("DS_SPEC_VERIFY", "scan")
    sched = ContinuousBatchingScheduler(m, eng.params, _spec_cfg())
    prompts = _mixed_prompts(3, seed=3)
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=10))
            for p in prompts]
    sched.run_until_idle()
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, 10))
    assert sched.metrics.counters["spec_verify_steps"] > 0


# ------------------------------------------------------ draft-model spec
def test_draft_model_proposer_parity(served):
    """Draft = the target itself (acceptance ~1) and draft = a much
    smaller model (low acceptance): greedy output is exact either way —
    draft quality affects speed only, never correctness."""
    m, eng = served
    prompts = _mixed_prompts(4, seed=2)
    max_new = [12, 9, 15, 8]
    refs = [_static_reference(eng, p, mn)
            for p, mn in zip(prompts, max_new)]

    for draft_m, draft_params in (
            (m, eng.params),
            (tiny_gpt2(num_layers=1, d_model=16, num_heads=2),
             None)):
        if draft_params is None:
            d_eng = deepspeed_tpu.init_inference(
                model=draft_m, config={"dtype": "float32"})
            draft_params = d_eng.params
        prop = DraftModelProposer(draft_m, draft_params,
                                  num_blocks=32, block_size=8)
        sched = ContinuousBatchingScheduler(
            m, eng.params, _spec_cfg(mode="draft"), proposer=prop)
        reqs = [sched.submit(p, SamplingParams(max_new_tokens=mn))
                for p, mn in zip(prompts, max_new)]
        sched.run_until_idle()
        for r, ref in zip(reqs, refs):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(np.asarray(r.output_ids), ref)
        assert sched.metrics.counters["spec_verify_steps"] > 0
        # the draft pool drains with the requests
        assert prop.bm.num_allocated_blocks == 0


def test_draft_pool_rollback_self_heals(served):
    """The draft cache resyncs by prefix-diff after rejections: a
    deliberately tiny draft pool (forcing skipped proposals) still ends
    with exact parity and clean accounting."""
    m, eng = served
    md = tiny_gpt2(num_layers=1, d_model=16, num_heads=2)
    d_eng = deepspeed_tpu.init_inference(model=md,
                                         config={"dtype": "float32"})
    prop = DraftModelProposer(md, d_eng.params, num_blocks=6, block_size=4)
    sched = ContinuousBatchingScheduler(
        m, eng.params, _spec_cfg(mode="draft"), proposer=prop)
    prompts = _mixed_prompts(3, seed=9)
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=10))
            for p in prompts]
    sched.run_until_idle()
    for p, r in zip(prompts, reqs):
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, 10))
    assert prop.bm.num_allocated_blocks == 0
    prop.bm.check_invariant()


# --------------------------------------------- rejection sampling (T > 0)
def test_rejection_sampling_preserves_distribution():
    """ISSUE 5 acceptance math: with a deterministic draft, accept-with-
    prob-p(d) + residual resampling reproduces the target distribution
    exactly (statistical tolerance over many seeded trials, one jitted
    batch call)."""
    import jax
    import jax.numpy as jnp
    from deepspeed_tpu.serving.spec.verifier import (
        accept_tokens, process_sampling_logits)
    rng = np.random.default_rng(0)
    V, N = 16, 20000
    raw = (rng.normal(size=(1, 2, V)) * 2.0).astype(np.float32)
    temps = np.full((N,), 1.3, np.float32)
    top_ks = np.zeros((N,), np.int32)
    top_ps = np.ones((N,), np.float32)
    draft_tok = 3
    x = process_sampling_logits(
        jnp.asarray(raw[:, 0]), jnp.asarray(temps[:1]),
        jnp.asarray(top_ks[:1]), jnp.asarray(top_ps[:1]))
    target = np.asarray(jax.nn.softmax(x, axis=-1))[0]

    logits = jnp.broadcast_to(jnp.asarray(raw), (N, 2, V))
    wt = jnp.broadcast_to(jnp.asarray([[0, draft_tok]], jnp.int32), (N, 2))
    acc, out = jax.jit(accept_tokens, static_argnames="any_sampling")(
        logits, wt, jnp.ones((N,), jnp.int32),
        jnp.arange(N, dtype=jnp.uint32), jnp.full((N,), 5, jnp.int32),
        jnp.asarray(temps), jnp.asarray(top_ks), jnp.asarray(top_ps),
        jnp.ones((N,), bool), True)
    acc, out = np.asarray(acc), np.asarray(out)
    toks = np.where(acc[:, 0], draft_tok, out[:, 0])
    emp = np.bincount(toks, minlength=V) / N
    # acceptance rate equals p(draft)
    assert abs(acc[:, 0].mean() - target[draft_tok]) < 0.02
    assert np.abs(emp - target).max() < 0.02


def test_sampled_spec_runs_and_is_seed_deterministic(served):
    m, eng = served
    prompt = np.tile(np.asarray([9, 23, 4], np.int32), 4)

    def run(seed):
        sched = ContinuousBatchingScheduler(m, eng.params, _spec_cfg())
        r = sched.submit(prompt, SamplingParams(
            max_new_tokens=10, do_sample=True, temperature=1.4, seed=seed))
        sched.run_until_idle()
        return list(r.output_ids)

    a = run(7)
    assert len(a) == 10
    assert a == run(7)                      # position-keyed rng
    assert len({tuple(run(s)) for s in (7, 8, 9)}) > 1


# ---------------------------------------------------- adaptive draft len
class _GarbageProposer(Proposer):
    """Deterministic junk drafts: (last_token + 7) mod V, never what the
    tiny model's greedy chain emits."""
    name = "garbage"

    def propose(self, req, k):
        t = int(req.all_token_ids[-1])
        return np.asarray([(t + 7) % 128] * k, np.int32)


def test_min_accept_rate_auto_disables(served):
    m, eng = served
    cfg = _spec_cfg(spec={"min_accept_rate": 0.9, "max_draft_tokens": 2})
    sched = ContinuousBatchingScheduler(m, eng.params, cfg,
                                        proposer=_GarbageProposer())
    prompt = _mixed_prompts(1, seed=4)[0]
    r = sched.submit(prompt, SamplingParams(max_new_tokens=24))
    sched.run_until_idle()
    np.testing.assert_array_equal(
        np.asarray(r.output_ids), _static_reference(eng, prompt, 24))
    assert r.spec_disabled
    assert sched.metrics.counters["spec_auto_disabled"] >= 1
    # shrink happened before the disable tripped
    assert r.spec_k == 1


def test_adaptive_k_grows_on_acceptance(served):
    m, eng = served
    cfg = _spec_cfg(spec={"max_draft_tokens": 8})
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    prompt = np.tile(np.asarray([9, 23, 4], np.int32), 5)
    r = sched.submit(prompt, SamplingParams(max_new_tokens=32))
    sched.run_until_idle()
    np.testing.assert_array_equal(
        np.asarray(r.output_ids), _static_reference(eng, prompt, 32))
    assert r.spec_passes > 0
    assert r.spec_accept_ema > 0.5          # cyclic output: ngram locks on
    assert not r.spec_disabled


# ------------------------------------------------------------ fault site
def test_serve_spec_fault_degrades_to_plain_decode(served):
    """ISSUE 5 satellite: a raise/deny fault during verify degrades the
    step to plain decode — exact parity, no wedge, no KV corruption, and
    the drafts' reserved window blocks return to the pool."""
    from deepspeed_tpu.resilience.faults import FaultInjector
    m, eng = served
    prompts = _mixed_prompts(2, seed=5)
    refs = [_static_reference(eng, p, 10) for p in prompts]
    for spec_txt in ("serve.spec:raise@*", "serve.spec:deny@*",
                     "serve.spec:raise@1"):
        sched = ContinuousBatchingScheduler(
            m, eng.params, _spec_cfg(),
            injector=FaultInjector(spec_txt))
        reqs = [sched.submit(p, SamplingParams(max_new_tokens=10))
                for p in prompts]
        sched.run_until_idle()
        for r, ref in zip(reqs, refs):
            assert r.state == RequestState.FINISHED
            np.testing.assert_array_equal(np.asarray(r.output_ids), ref)
        assert sched.metrics.counters["spec_faults"] >= 1
        assert sched.block_mgr.num_allocated_blocks == 0


# ------------------------------------------------- prefix cache (ISSUE 6)
def test_spec_with_prefix_cache_parity(served):
    """Speculative decoding over a cache-enabled pool: drafted windows
    roll back through truncate on tables whose prefix blocks are SHARED,
    and greedy output stays exactly plain-cb's — committed tokens never
    roll back, so the cached prefix is never corrupted (the invariant
    fixture checks the ref-counted accounting every step)."""
    m, eng = served
    rng = np.random.default_rng(17)
    shared = np.tile(np.asarray([9, 23, 4], np.int32), 8)   # 24 tokens
    prompts = [np.concatenate(
        [shared, rng.integers(1, 128, (int(t),)).astype(np.int32)])
        for t in (3, 5, 7)]
    prompts.append(shared.copy())     # block-aligned: COW-fork admission
    cfg = _spec_cfg(prefix_cache={"enabled": True})
    sched = ContinuousBatchingScheduler(m, eng.params, cfg)
    reqs = [sched.submit(p, SamplingParams(max_new_tokens=14))
            for p in prompts]
    sched.run_until_idle()
    for p, r in zip(prompts, reqs):
        assert r.state == RequestState.FINISHED
        np.testing.assert_array_equal(
            np.asarray(r.output_ids), _static_reference(eng, p, 14))
    c = sched.metrics.counters
    assert c["spec_verify_steps"] > 0         # speculation really ran
    assert c["prefix_cache_hit"] >= 3         # the cache really hit
    assert c["prefix_cache_cow_forks"] >= 1   # ...including the fork path
    assert sched.block_mgr.num_allocated_blocks == 0
    sched.block_mgr.check_invariant()


# ------------------------------------------------------------- telemetry
def test_spec_metrics_and_correlated_spans(served, tmp_path, monkeypatch):
    """serve/draft + serve/verify spans share each request's correlation
    id (trace_validate.correlated_spans), and /metrics exposes the
    serve/spec_accept_len quantile gauges + spec counters."""
    from deepspeed_tpu.telemetry import configure_tracer, reset_tracer
    from scripts.trace_validate import (correlated_spans, load_events,
                                        validate)
    m, eng = served
    trace_path = str(tmp_path / "spec_trace.json")
    monkeypatch.setenv("DS_TRACE", trace_path)
    reset_tracer()
    tracer = configure_tracer()
    try:
        sched = ContinuousBatchingScheduler(m, eng.params, _spec_cfg())
        prompt = np.tile(np.asarray([9, 23, 4], np.int32), 5)
        for _ in range(2):
            sched.submit(prompt, SamplingParams(max_new_tokens=12))
        sched.run_until_idle()
        tracer.flush()
    finally:
        reset_tracer()
    assert validate(trace_path, require_corr=True) == []
    evs = load_events(trace_path)
    by_corr = correlated_spans(evs, ("serve/draft", "serve/verify"))
    both = {c for c, names in by_corr.items()
            if names == {"serve/draft", "serve/verify"}}
    assert {"req-0", "req-1"} <= both
    text = sched.render_metrics()
    assert "# TYPE serve_spec_accept_len histogram" in text
    assert "serve_spec_accept_len_p50" in text
    assert "serve_spec_accept_len_p99" in text
    assert "serving_spec_drafted_tokens" in text
    assert "serving_spec_accepted_tokens" in text
    assert "serving_spec_rolled_back_tokens" in text
    snap = sched.metrics_snapshot()
    assert snap["serve/spec_accept_len_mean"] >= 1.0
    assert snap["serving/spec_accept_rate"] > 0


# ---------------------------------------------------------------- config
def test_spec_config_validation_and_roundtrip():
    cfg = ServingConfig(spec={"mode": "ngram", "max_draft_tokens": 6,
                              "min_accept_rate": 0.25})
    assert cfg.spec.mode == "ngram" and cfg.spec.max_draft_tokens == 6
    assert ServingConfig().spec.mode == "off"
    with pytest.raises(ValueError, match="spec.mode"):
        ServingConfig(spec={"mode": "turbo"})
    with pytest.raises(ValueError, match="max_draft_tokens"):
        ServingConfig(spec={"max_draft_tokens": 0})
    with pytest.raises(ValueError, match="min_accept_rate"):
        ServingConfig(spec={"min_accept_rate": 1.5})
    with pytest.raises(ValueError, match="ngram"):
        ServingConfig(spec={"ngram_min": 3, "ngram_max": 2})
    # draft mode without a proposer is an eager, explicit error
    from tests.util import tiny_gpt2 as _t
    with pytest.raises(ValueError, match="DraftModelProposer"):
        m = _t()
        eng = deepspeed_tpu.init_inference(model=m,
                                           config={"dtype": "float32"})
        ContinuousBatchingScheduler(m, eng.params,
                                    ServingConfig(spec={"mode": "draft"}))
