"""Block-granular KV-cache accounting: a free-list allocator over a pool
of fixed-size token blocks (vLLM PagedAttention's physical layer, minus
swap — preempted requests recompute on resume).

The physical cache itself lives in the scheduler as a position-flat
pytree ``[L, num_blocks * block_size, ...]`` (the `models/serving.py`
`init_cache` layout with the batch dim collapsed into the pool); this
class owns only the integer bookkeeping.  Block 0 is reserved as the
trash block: padding rows in the packed decode batch point their tables
at it, so their (ignored) cache writes can never land in a live block.
"""
from typing import Dict, List, Optional

from deepspeed_tpu.resilience.faults import FaultInjector, NULL_INJECTOR


class BlockManager:
    TRASH_BLOCK = 0

    def __init__(self, num_blocks: int, block_size: int,
                 injector: FaultInjector = NULL_INJECTOR):
        if num_blocks < 2:
            raise ValueError(f"num_blocks={num_blocks}: need >= 2 "
                             "(block 0 is the reserved trash block)")
        if block_size < 1:
            raise ValueError(f"block_size={block_size}: need >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.injector = injector
        # LIFO free list: recently-freed blocks are re-handed first, so a
        # drained-and-refilled pool stays compact
        self._free: List[int] = list(range(num_blocks - 1, 0, -1))
        self._tables: Dict[int, List[int]] = {}     # request_id -> blocks

    # -------------------------------------------------------------- sizes
    @property
    def num_usable_blocks(self) -> int:
        return self.num_blocks - 1          # minus the trash block

    @property
    def num_free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_allocated_blocks(self) -> int:
        return self.num_usable_blocks - self.num_free_blocks

    def utilization(self) -> float:
        return self.num_allocated_blocks / max(self.num_usable_blocks, 1)

    def blocks_for_tokens(self, num_tokens: int) -> int:
        return max(1, -(-num_tokens // self.block_size))

    def fits_ever(self, num_tokens: int) -> bool:
        """Could a request of this total length run on an EMPTY pool?"""
        return self.blocks_for_tokens(num_tokens) <= self.num_usable_blocks

    # ---------------------------------------------------------- allocate
    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, request_id: int, n: int) -> Optional[List[int]]:
        """Append ``n`` fresh blocks to the request's table; None (and no
        state change) when the pool can't supply them — or when a
        ``kv.alloc`` deny fault fires (exercises the preemption /
        recompute-on-resume path deterministically)."""
        if self.injector.deny("kv.alloc"):
            return None
        if n > len(self._free):
            return None
        got = [self._free.pop() for _ in range(n)]
        self._tables.setdefault(request_id, []).extend(got)
        return got

    def block_table(self, request_id: int) -> List[int]:
        return self._tables.get(request_id, [])

    def free(self, request_id: int):
        """Return every block of the request to the pool (retire/evict)."""
        for b in self._tables.pop(request_id, []):
            self._free.append(b)

    # ---------------------------------------------------------- addressing
    def position_index(self, request_id: int, pos: int) -> int:
        """Flat pool position for the request's logical token ``pos``."""
        table = self._tables[request_id]
        return table[pos // self.block_size] * self.block_size \
            + pos % self.block_size
