"""Fused-dequant int8 GEMM Pallas kernel (``ds_qgemm``).

Reference capability: DeepSpeed's quantized-GEMM inference kernels
(csrc/quantization + the MoQ int8 serving path).  The serving problem it
solves is measured in PERF.md round 5: past ~350M params XLA stops fusing
weight dequantization into the consuming matmuls, so int8 decoding pays
int8-read + bf16-write + bf16-re-read (~6.6 GB/step at gpt2-1.3B → 238
tok/s against an int8 weight-stream floor several× higher).

``ds_qgemm(x, q, scales)`` computes ``x @ W`` where ``W`` stays int8 in
HBM in the ``block_quantize_int8`` layout (ops/pallas/quantization.py):
``q`` int8 ``[K, N]`` with one fp32 scale per ``[1, qblock]`` group of
lanes, ``scales`` ``[K, ceil(N/qblock)]``.  Each grid step DMAs one
``[bk, bn]`` int8 weight tile into VMEM, expands its scale columns with a
tiny select-matmul (the decode-attention blockdiag idiom), dequantizes on
the VPU, and feeds the MXU — **no layer-sized compute-dtype copy of W
ever exists**; the only HBM weight traffic is the int8 bytes.

Grid ``(M/bm, N/bn, K/bk)`` with K innermost: the fp32 accumulator tile
persists in VMEM scratch across the K steps of one output block (the
ds_flash_attention accumulation pattern).  Block shapes are sweepable
(``scripts/qgemm_sweep.py``, slope-timed on chip); TPU-legal defaults
keep int8 tiles on (32, 128) multiples.

A jnp reference path (``_ref_qgemm``) serves CPU meshes; interpret mode
runs the real kernel in the CPU suite (tests/test_qgemm.py).
"""
import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# default tile shapes: bm caps at the MXU row dim, bk/bn sized so the int8
# weight tile (the dominant VMEM tenant: bk*bn bytes, double-buffered)
# stays ~512 KB — override per call or with DS_QGEMM_BLOCKS="bm,bk,bn"
DEFAULT_BLOCK_M = 128
DEFAULT_BLOCK_K = 512
DEFAULT_BLOCK_N = 1024


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _env_blocks():
    env = os.environ.get("DS_QGEMM_BLOCKS")
    if not env:
        return None
    bm, bk, bn = (int(v) for v in env.split(","))
    return bm, bk, bn


def _ref_qgemm(x, q, scales, out_dtype=None):
    """jnp reference: dequantize (per-group scales over the last dim of
    ``q``) and matmul in ``x``'s dtype — numerically identical to the
    pre-qgemm ``maybe_stream`` dequant + dense matmul path."""
    from deepspeed_tpu.ops.pallas.quantization import block_dequantize_int8
    w = block_dequantize_int8(q, scales).astype(x.dtype)
    out = x @ w
    return out.astype(out_dtype) if out_dtype is not None else out


def _qgemm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, qblock, block_n,
                  n_k, precision):
    """One (i, j, k) grid step: dequantize the [bk, bn] int8 tile in VMEM
    and accumulate x_tile @ w_tile into the fp32 scratch.

    Scale expansion: ``s_ref`` stages the k-tile's FULL scale rows
    [bk, nb] (nb is tiny — ceil(N/qblock) — and a full trailing dim is
    always Mosaic-legal where a narrow column-slice block is not).  The
    tile's columns select their group via one [bk, nb] x [nb, bn] matmul
    against a computed 0/1 selector — MXU-cheap next to the main matmul,
    and the dequantized tile never leaves VMEM."""
    j = pl.program_id(1)
    k_idx = pl.program_id(2)

    @pl.when(k_idx == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[:]                                    # [bm, bk]
    qt = q_ref[:]                                   # [bk, bn] int8
    s = s_ref[:]                                    # [bk, nb] fp32
    nb = s.shape[1]
    # selector[g, n] = 1 where global column j*bn+n belongs to scale
    # group g (general: works for bn % qblock != 0 and ragged last group)
    g_iota = jax.lax.broadcasted_iota(jnp.int32, (nb, block_n), 0)
    col = j * block_n + jax.lax.broadcasted_iota(
        jnp.int32, (nb, block_n), 1)
    sel = (g_iota == col // qblock).astype(jnp.float32)
    s_exp = jax.lax.dot(s, sel,
                        preferred_element_type=jnp.float32)  # [bk, bn]
    w = (qt.astype(jnp.float32) * s_exp).astype(x.dtype)
    acc_ref[:] += jax.lax.dot(x, w, preferred_element_type=jnp.float32,
                              precision=precision)

    @pl.when(k_idx == n_k - 1)
    def _finalize():
        o_ref[:] = acc_ref[:].astype(o_ref.dtype)


def _fit_block(dim, requested, quantum=128):
    """Largest quantum-multiple block <= requested that DIVIDES dim, when
    dim is quantum-aligned.  Padding a non-dividing weight dim would
    materialize a padded int8 copy of the whole weight inside the traced
    decode (loop-invariant → XLA hoists it → a second HBM-resident copy,
    exactly the residency this kernel exists to avoid); every real model
    dim is 128-aligned, so shrinking to a divisor costs only tile size.
    Ragged dims (tests, odd adapters) keep the requested block and pad."""
    b = min(requested, _round_up(dim, quantum))
    if dim % quantum == 0:
        # sub-quantum requests bump up to the quantum (always a divisor
        # here) — returning them unchanged would re-introduce the pad
        for cand in range(max(b - b % quantum, quantum), quantum - 1,
                          -quantum):
            if dim % cand == 0:
                return cand
    return b


def _pallas_qgemm(x, q, scales, out_dtype, block_m, block_k, block_n,
                  interpret):
    M, K = x.shape
    K2, N = q.shape
    assert K == K2, (x.shape, q.shape)
    nb = scales.shape[-1]
    qblock = -(-N // nb)        # group width (last group may be ragged)

    # sublane alignment for x/out: bf16 tiles are (16, 128), fp32 (8, 128)
    m_align = 16 if x.dtype == jnp.bfloat16 else 8
    bm = min(block_m, _round_up(M, m_align))
    M_pad = _round_up(M, bm)
    bk = _fit_block(K, block_k)
    K_pad = _round_up(K, bk)
    bn = _fit_block(N, block_n)
    N_pad = _round_up(N, bn)

    if M_pad != M:
        x = jnp.pad(x, ((0, M_pad - M), (0, 0)))
    if K_pad != K:
        # zero x-columns and weight rows: padded K contributes nothing
        x = jnp.pad(x, ((0, 0), (0, K_pad - K)))
        q = jnp.pad(q, ((0, K_pad - K), (0, 0)))
        scales = jnp.pad(scales, ((0, K_pad - K), (0, 0)),
                         constant_values=1.0)
    if N_pad != N:
        # padded columns carry q == 0; their (out-of-range) group index
        # matches no selector row, so the dequantized value is 0 either way
        q = jnp.pad(q, ((0, 0), (0, N_pad - N)))

    n_k = K_pad // bk
    # fp32 x needs full-precision MXU passes (default lowering runs
    # bf16-grade multiplies even for f32 operands — decode_attention.py)
    precision = (jax.lax.Precision.HIGHEST if x.dtype == jnp.float32
                 else None)
    kernel = functools.partial(_qgemm_kernel, qblock=qblock, block_n=bn,
                               n_k=n_k, precision=precision)
    out = pl.pallas_call(
        kernel,
        grid=(M_pad // bm, N_pad // bn, n_k),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((bk, nb), lambda i, j, k: (k, 0)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((M_pad, N_pad), out_dtype),
        scratch_shapes=[
            # fp32 accumulator, persistent across the K steps of one
            # (i, j) output block (K is the innermost grid dim)
            pltpu.VMEM((bm, bn), jnp.float32),
        ],
        interpret=interpret,
    )(x, q, scales.astype(jnp.float32))
    return out[:M, :N]


def ds_qgemm(x, q, scales, out_dtype=None, block_m=None, block_k=None,
             block_n=None, interpret=None):
    """``x [..., K] @ dequant(q [K, N], scales [K, ceil(N/qblock)])``.

    Weights stay int8 end-to-end in HBM; dequantization happens tile-wise
    in VMEM inside the kernel.  Leading dims of ``x`` flatten to the GEMM
    M dim.  ``out_dtype`` defaults to ``x.dtype``.  ``interpret=True``
    forces the Pallas kernel in interpret mode (CPU tests); off-TPU the
    jnp reference runs unless ``DS_QGEMM_INTERPRET=1``.
    """
    *lead, K = x.shape
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    if q.ndim != 2 or scales.ndim != 2:
        raise ValueError(
            f"ds_qgemm expects a 2-D quantized weight (q {q.shape}, "
            f"scales {scales.shape}); stacked weights slice per layer "
            "before the matmul")
    if interpret is None:
        if os.environ.get("DS_QGEMM_INTERPRET") == "1":
            interpret = True
        else:
            from deepspeed_tpu.ops.attention import _on_tpu
            if not _on_tpu():
                return _ref_qgemm(x, q, scales, out_dtype)
            if jax.device_count() > 1:
                # multi-device mesh: GSPMD has no partitioning rule for
                # the pallas custom call (see quantization.py's identical
                # gate), and TP-sharded q/s operands would force a
                # gather.  The jnp reference keeps tp>1 int8 serving
                # correct; a shard_map-wrapped kernel is the follow-up.
                return _ref_qgemm(x, q, scales, out_dtype)
            interpret = False
    env = _env_blocks()
    bm = block_m or (env[0] if env else DEFAULT_BLOCK_M)
    bk = block_k or (env[1] if env else DEFAULT_BLOCK_K)
    bn = block_n or (env[2] if env else DEFAULT_BLOCK_N)
    M = 1
    for d in lead:
        M *= d
    out = _pallas_qgemm(x.reshape(M, K), q, scales, out_dtype, bm, bk, bn,
                        interpret)
    return out.reshape(*lead, q.shape[-1])
