#!/usr/bin/env python3
"""Diff two structured bench outputs and flag regressions (ISSUE 7
satellite; ISSUE 13 grows the gated-ledger mode).

Inputs are the machine-readable records the benches emit — a
``serve_bench --json`` file, a BENCH_*.json record, a JSONL stream of
records (the ``BENCH/ledger.jsonl`` history), a list of records, or a
flat ``{name: value}`` dict.  Each record's ``value`` plus every
numeric ``detail`` field becomes a comparable metric named
``<metric>`` / ``<metric>.<detail_key>``.

A metric regresses when it moves more than ``--threshold`` (default
10%) in its BAD direction.  Direction resolution order: explicit
``--lower-better``/``--higher-better`` > the record's own
``direction`` field (the BenchRecord schema) > name inference
(latencies/durations/counts-of-waste are lower-better; rates and
throughputs higher-better).

**Metadata guard (ISSUE 13):** records carrying a BenchRecord ``meta``
envelope are refused when the two sides were measured on different
device kinds, and per-metric when both sides declare different model
shapes (``detail.model``) — a CPU-smoke record silently gating an
on-chip one is exactly the failure this exists to stop.  Exit 2 with
a diagnostic naming both sides.

**History mode (ISSUE 13):** ``--history BENCH/ledger.jsonl current``
gates ``current`` against a ROLLING baseline — per metric, the median
of the last ``--window`` ledger values measured on the same device
kind (and model shape, when declared).  Ledger entries from other
device kinds are excluded; if the ledger holds records for this metric
but none match the current device, that's the cross-device refusal
(exit 2), not a silent pass.

Usage::

    python scripts/bench_compare.py baseline.json current.json
    python scripts/bench_compare.py old.json new.json --threshold 0.05
    python scripts/bench_compare.py a.json b.json --metrics ttft,tok_s
    python scripts/bench_compare.py --history BENCH/ledger.jsonl new.json

Exit 0 = no regression; 1 = at least one flagged regression; 2 = bad
input or refused comparison (cross-device / cross-model / schema
mismatch).  Improvements and within-threshold drift are reported but
never fail the run.
"""
import argparse
import json
import statistics
import sys
from typing import Dict, List, Optional, Tuple

#: name fragments implying "smaller is better" (substring match)
LOWER_BETTER_HINTS = ("latency", "wait", "duration", "prefill_tokens",
                      "rolled_back", "evict", "miss", "violation",
                      "recomputed", "preemption",
                      # convergence guards (ISSUE 15): a loss or
                      # grad-norm jump in a bench detail is a
                      # regression like a latency jump is
                      "loss", "grad_norm")
#: time-unit suffixes (suffix-only: "_s" mid-name would misfire on
#: every "..._serve..." metric)
LOWER_BETTER_SUFFIXES = ("_ms", "_s", "_sec", "_us")
#: fragments that override a lower-better hint back to higher-better
#: (rates and counts of good work)
HIGHER_BETTER_HINTS = ("per_sec", "per_s", "tok_s", "rate", "speedup",
                       "goodput", "hit", "accept", "useful", "mfu",
                       "requests")


def lower_is_better(name: str) -> bool:
    n = name.lower()
    if any(h in n for h in HIGHER_BETTER_HINTS):
        return False
    return n.endswith(LOWER_BETTER_SUFFIXES) \
        or any(h in n for h in LOWER_BETTER_HINTS)


def _records(doc) -> List[dict]:
    if isinstance(doc, list):
        return [r for r in doc if isinstance(r, dict)]
    if isinstance(doc, dict):
        if "metric" in doc:
            return [doc]
        # flat {name: value} map
        return [{"metric": str(k), "value": v} for k, v in doc.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)]
    return []


def load_records(path: str) -> List[dict]:
    """Every record in a bench file (JSON, JSONL, list, or flat map)."""
    with open(path) as f:
        text = f.read()
    try:
        docs = [json.loads(text)]
    except json.JSONDecodeError:
        # JSONL: one record per non-empty line
        docs = []
        for line in text.splitlines():
            line = line.strip()
            if line:
                docs.append(json.loads(line))
    out: List[dict] = []
    for doc in docs:
        out.extend(_records(doc))
    return out


def _flatten_one(rec: dict, out: Dict[str, float]):
    name = str(rec.get("metric", "metric"))
    val = rec.get("value")
    if isinstance(val, (int, float)) and not isinstance(val, bool):
        out[name] = float(val)
    for k, v in (rec.get("detail") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[f"{name}.{k}"] = float(v)


def flatten_records(records: List[dict]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for rec in records:
        _flatten_one(rec, out)
    return out


def load_metrics(path: str) -> Dict[str, float]:
    """Flatten a bench file into {metric_name: numeric_value}."""
    return flatten_records(load_records(path))


# ------------------------------------------------------- metadata guard
def records_meta(records: List[dict]) -> Optional[dict]:
    """The file's BenchRecord envelope (last record wins — one run, one
    environment); None for pre-schema files."""
    meta = None
    for rec in records:
        m = rec.get("meta")
        if isinstance(m, dict):
            meta = m
    return meta


def records_directions(records: List[dict]) -> Dict[str, str]:
    return {str(r["metric"]): r["direction"] for r in records
            if isinstance(r.get("direction"), str) and "metric" in r}


def records_models(records: List[dict]) -> Dict[str, str]:
    """Per-metric declared model shape (``detail.model``) — the
    cross-model comparison guard key."""
    out = {}
    for r in records:
        model = (r.get("detail") or {}).get("model")
        if model is not None and "metric" in r:
            out[str(r["metric"])] = str(model)
    return out


def meta_conflict(a: Optional[dict], b: Optional[dict]) -> Optional[str]:
    """Why these two record sets must not be diffed (None = fine).
    Only guards what both sides declare — pre-schema records keep
    working."""
    if not a or not b:
        return None
    sa, sb = str(a.get("schema", "")), str(b.get("schema", ""))
    if sa and sb and sa != sb:
        return f"schema mismatch: {sa} vs {sb}"
    ka, kb = a.get("device_kind"), b.get("device_kind")
    if ka and kb and ka != kb:
        return (f"cross-device diff refused: baseline measured on "
                f"{ka!r} ({a.get('device_count')} dev), current on "
                f"{kb!r} ({b.get('device_count')} dev) — bench floors "
                f"and rates are not comparable across device kinds")
    return None


def model_conflicts(models_a: Dict[str, str], models_b: Dict[str, str]
                    ) -> List[str]:
    out = []
    for name in sorted(set(models_a) & set(models_b)):
        if models_a[name] != models_b[name]:
            out.append(f"metric {name!r}: baseline model "
                       f"{models_a[name]!r} vs current {models_b[name]!r}")
    return out


# ------------------------------------------------------------- history
def rolling_baseline(history: List[dict], current_meta: Optional[dict],
                     current_models: Dict[str, str], window: int = 5
                     ) -> Tuple[Dict[str, float], List[str]]:
    """Per-metric rolling baseline from the ledger: the median of the
    last ``window`` values measured on the current device kind (and,
    when both declare one, the current model shape).  Returns (baseline
    metrics, refusal diagnostics for metrics whose ledger entries exist
    ONLY on other device kinds)."""
    kind = (current_meta or {}).get("device_kind")
    series: Dict[str, List[float]] = {}
    skipped_kinds: Dict[str, set] = {}
    for rec in history:
        meta = rec.get("meta") or {}
        rkind = meta.get("device_kind")
        name = str(rec.get("metric", "metric"))
        if kind and rkind and rkind != kind:
            skipped_kinds.setdefault(name, set()).add(rkind)
            continue
        model = (rec.get("detail") or {}).get("model")
        want = current_models.get(name)
        if model is not None and want is not None \
                and str(model) != str(want):
            continue
        flat: Dict[str, float] = {}
        _flatten_one(rec, flat)
        for n, v in flat.items():
            series.setdefault(n, []).append(v)
    baseline = {n: statistics.median(vals[-window:])
                for n, vals in series.items() if vals}
    refusals = [f"metric {n!r}: ledger holds records only for device "
                f"kind(s) {sorted(ks)} (current: {kind!r})"
                for n, ks in sorted(skipped_kinds.items())
                if n not in series]
    return baseline, refusals


def compare(old: Dict[str, float], new: Dict[str, float],
            threshold: float = 0.10, metrics=None,
            force_lower=(), force_higher=(),
            directions: Optional[Dict[str, str]] = None) -> List[dict]:
    """Rows for every metric present in BOTH files; ``regressed`` set
    when the bad-direction relative change exceeds the threshold.
    ``directions`` maps a base metric name to its declared direction
    (BenchRecord field) — consulted after the force lists, before name
    inference (detail metrics inherit their record's direction)."""
    rows = []
    directions = directions or {}
    for name in sorted(set(old) & set(new)):
        if metrics and not any(m in name for m in metrics):
            continue
        a, b = old[name], new[name]
        declared = directions.get(name) or directions.get(
            name.split(".", 1)[0])
        if any(m in name for m in force_lower):
            lower = True
        elif any(m in name for m in force_higher):
            lower = False
        elif declared in ("lower_better", "higher_better") \
                and "." not in name:
            # only the record's own value inherits the declared
            # direction; detail fields keep name inference (one record
            # mixes tok/s with ttft_ms details)
            lower = declared == "lower_better"
        else:
            lower = lower_is_better(name)
        if a == 0:
            # no baseline to be relative to: a counter that was 0 last
            # round (rollbacks, evictions, preemptions) going nonzero is
            # ordinary run-to-run jitter, not an unbounded regression —
            # report the move but never flag it
            change = 0.0 if b == 0 else float("inf") * (1 if b > 0 else -1)
            regressed = False
        else:
            change = (b - a) / abs(a)
            regressed = (change if lower else -change) > threshold
        rows.append({
            "metric": name, "old": a, "new": b,
            "change_pct": round(change * 100, 2),
            "direction": "lower_better" if lower else "higher_better",
            "regressed": regressed,
        })
    return rows


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench_compare",
        description="diff two bench JSON outputs (or gate one against "
                    "the BENCH/ ledger history), flag >threshold "
                    "regressions on named metrics")
    p.add_argument("baseline", nargs="?", default=None,
                   help="baseline file (omit with --history)")
    p.add_argument("current", nargs="?", default=None)
    p.add_argument("--history", default=None, metavar="LEDGER",
                   help="BENCH ledger JSONL: gate the single input file "
                        "against the rolling per-metric baseline "
                        "(median of the last --window same-device "
                        "records)")
    p.add_argument("--window", type=int, default=5,
                   help="history mode: rolling-baseline window "
                        "(default 5 records)")
    p.add_argument("--threshold", type=float, default=0.10,
                   help="bad-direction relative change that counts as a "
                        "regression (default 0.10 = 10%%)")
    p.add_argument("--metrics", default=None,
                   help="comma-separated substrings; only matching "
                        "metric names are compared")
    p.add_argument("--lower-better", default="",
                   help="comma-separated substrings forced lower-better")
    p.add_argument("--higher-better", default="",
                   help="comma-separated substrings forced higher-better")
    p.add_argument("-q", "--quiet", action="store_true",
                   help="print only regressions")
    args = p.parse_args(argv)
    if args.history:
        cur_path = args.current or args.baseline
        if cur_path is None or (args.current and args.baseline):
            print("bench_compare: --history takes exactly one input "
                  "file", file=sys.stderr)
            return 2
    elif args.baseline is None or args.current is None:
        print("bench_compare: need baseline and current files (or "
              "--history LEDGER current)", file=sys.stderr)
        return 2
    else:
        cur_path = args.current
    try:
        cur_records = load_records(cur_path)
        new = flatten_records(cur_records)
        cur_meta = records_meta(cur_records)
        cur_models = records_models(cur_records)
        directions = records_directions(cur_records)
        if args.history:
            hist_records = load_records(args.history)
            old, refusals = rolling_baseline(
                hist_records, cur_meta, cur_models, window=args.window)
            if refusals:
                print("bench_compare: refused (cross-device history):",
                      file=sys.stderr)
                for r in refusals:
                    print(f"  {r}", file=sys.stderr)
                return 2
            directions = {**records_directions(hist_records),
                          **directions}
            # the rolling baseline is already filtered to the current
            # device kind AND model shape — running the cross-model
            # guard over the raw ledger would spuriously refuse any
            # ledger that legitimately holds several model shapes
            base_models = {}
        else:
            base_records = load_records(args.baseline)
            old = flatten_records(base_records)
            conflict = meta_conflict(records_meta(base_records), cur_meta)
            if conflict:
                print(f"bench_compare: refused: {conflict}",
                      file=sys.stderr)
                return 2
            directions = {**records_directions(base_records),
                          **directions}
            base_models = records_models(base_records)
        shape_conflicts = model_conflicts(base_models, cur_models)
        if shape_conflicts:
            print("bench_compare: refused (model-shape mismatch):",
                  file=sys.stderr)
            for c in shape_conflicts:
                print(f"  {c}", file=sys.stderr)
            return 2
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"bench_compare: cannot load inputs: {e}", file=sys.stderr)
        return 2
    if not old or not new:
        print("bench_compare: no numeric metrics found", file=sys.stderr)
        return 2
    metrics = [m for m in (args.metrics or "").split(",") if m] or None
    rows = compare(old, new, threshold=args.threshold, metrics=metrics,
                   force_lower=[m for m in args.lower_better.split(",")
                                if m],
                   force_higher=[m for m in args.higher_better.split(",")
                                 if m],
                   directions=directions)
    if not rows:
        print("bench_compare: no common metrics to compare",
              file=sys.stderr)
        return 2
    regressions = [r for r in rows if r["regressed"]]
    width = max(len(r["metric"]) for r in rows)
    for r in rows:
        if args.quiet and not r["regressed"]:
            continue
        flag = "REGRESSED" if r["regressed"] else "ok"
        arrow = "↓ better" if r["direction"] == "lower_better" \
            else "↑ better"
        print(f"{r['metric']:<{width}}  {r['old']:>12.4g} -> "
              f"{r['new']:>12.4g}  {r['change_pct']:>+8.2f}%  "
              f"[{arrow}]  {flag}")
    mode = (f"rolling baseline over {args.history}" if args.history
            else "pairwise")
    print(f"\n{len(rows)} metrics compared ({mode}), {len(regressions)} "
          f"regression(s) past {args.threshold:.0%}")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
