"""GPT-NeoX-style decoder (Pythia / NeoX-20B family): LayerNorm with
biases, fused-QKV attention with PARTIAL rotary embeddings
(``rotary_pct`` of each head rotates, the rest passes through), biased
GELU MLP, and the parallel attention+MLP residual
(``use_parallel_residual``).

Reference capability: the gptneox kernel-injection container
(deepspeed/module_inject/containers/gptneox.py); here the architecture is
a native model so every engine feature (ZeRO, TP specs, offload,
compression) applies unchanged after ``neox_from_hf`` conversion.
"""
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.model import Model, qdot, resolve_size
from deepspeed_tpu.models.llama import rope
from deepspeed_tpu.ops.attention import causal_attention


@dataclass(frozen=True)
class NeoXConfig:
    vocab_size: int = 50432
    max_seq_len: int = 2048
    num_layers: int = 6
    num_heads: int = 8
    d_model: int = 512
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    layer_norm_eps: float = 1e-5
    use_parallel_residual: bool = True
    #: HF GPT-NeoX default hidden_act="gelu" is the EXACT erf GELU;
    #: gelu_new/gelu_fast variants map to the tanh approximation
    gelu_approximate: bool = False
    #: GPT-J variants (module_inject/containers/gptj.py capability): the
    #: rotate-every-two rotary pairing and the biased untied lm_head.
    #: GPT-J's single shared block LayerNorm converts as ln2 := ln1.
    rotary_interleaved: bool = False
    head_bias: bool = False
    dtype: str = "float32"
    remat: bool = False
    remat_policy: str = "nothing"
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads

    @property
    def d_mlp(self) -> int:
        return 4 * self.d_model

    @property
    def rotary_ndims(self) -> int:
        return int(self.head_dim * self.rotary_pct)


NEOX_SIZES = {
    "tiny": dict(vocab_size=256, max_seq_len=64, num_layers=2, num_heads=4,
                 d_model=32),
    "pythia-160m": dict(vocab_size=50304, max_seq_len=2048, num_layers=12,
                        num_heads=12, d_model=768),
    "20b": dict(vocab_size=50432, max_seq_len=2048, num_layers=44,
                num_heads=64, d_model=6144, rotary_pct=0.25),
}


def init_params(config: NeoXConfig, rng) -> dict:
    D, V, L, M = (config.d_model, config.vocab_size, config.num_layers,
                  config.d_mlp)
    k = iter(jax.random.split(rng, 10))
    std = 0.02
    norm = partial(jax.random.normal, dtype=jnp.float32)
    return {
        "wte": norm(next(k), (V, D)) * std,
        "blocks": {
            "ln1_scale": jnp.ones((L, D)), "ln1_bias": jnp.zeros((L, D)),
            "ln2_scale": jnp.ones((L, D)), "ln2_bias": jnp.zeros((L, D)),
            "qkv_w": norm(next(k), (L, D, 3 * D)) * std,
            "qkv_b": jnp.zeros((L, 3 * D)),
            "dense_w": norm(next(k), (L, D, D)) * std / (2 * L) ** 0.5,
            "dense_b": jnp.zeros((L, D)),
            "mlp_in_w": norm(next(k), (L, D, M)) * std,
            "mlp_in_b": jnp.zeros((L, M)),
            "mlp_out_w": norm(next(k), (L, M, D)) * std / (2 * L) ** 0.5,
            "mlp_out_b": jnp.zeros((L, D)),
        },
        "lnf_scale": jnp.ones((D,)), "lnf_bias": jnp.zeros((D,)),
        "embed_out": norm(next(k), (D, V)) * std,
        **({"embed_out_b": jnp.zeros((V,))} if config.head_bias else {}),
    }


def logical_specs(config: NeoXConfig) -> dict:
    head = {"embed_out": P(None, "model")}
    if config.head_bias:
        head["embed_out_b"] = P("model")
    return {
        "wte": P("model", None),
        "blocks": {
            "ln1_scale": P(), "ln1_bias": P(),
            "ln2_scale": P(), "ln2_bias": P(),
            "qkv_w": P(None, None, "model"), "qkv_b": P(None, "model"),
            "dense_w": P(None, "model", None), "dense_b": P(),
            "mlp_in_w": P(None, None, "model"), "mlp_in_b": P(None, "model"),
            "mlp_out_w": P(None, "model", None), "mlp_out_b": P(),
        },
        "lnf_scale": P(), "lnf_bias": P(),
        **head,
    }


def _ln(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + eps) * scale + bias).astype(x.dtype)


def _partial_rope(x, config: NeoXConfig, positions=None):
    """Rotate the first ``rotary_ndims`` of each head, pass the rest."""
    rot = config.rotary_ndims
    il = config.rotary_interleaved
    if rot >= x.shape[-1]:
        return rope(x, config.rope_theta, positions, interleaved=il)
    xr = rope(x[..., :rot], config.rope_theta, positions, interleaved=il)
    return jnp.concatenate([xr, x[..., rot:]], axis=-1)


def _block_qkv(x, layer, config: NeoXConfig, positions=None):
    """LN1 + fused QKV (head-major [q|k|v] packing) + partial rotary."""
    B, S, D = x.shape
    H, hd = config.num_heads, config.head_dim
    dt = x.dtype
    h1 = _ln(x, layer["ln1_scale"], layer["ln1_bias"],
             config.layer_norm_eps)
    qkv = qdot(h1, layer["qkv_w"]) + layer["qkv_b"].astype(dt)
    q, kk, v = jnp.split(qkv.reshape(B, S, H, 3 * hd), 3, axis=-1)
    q = _partial_rope(q, config, positions)
    kk = _partial_rope(kk, config, positions)
    return q, kk, v


def _block_finish(x, attn_flat, layer, config: NeoXConfig):
    """Output projection + MLP with the parallel/serial residual form."""
    dt = x.dtype
    attn_out = (qdot(attn_flat, layer["dense_w"])
                + layer["dense_b"].astype(dt))
    h2_in = x if config.use_parallel_residual else x + attn_out
    h2 = _ln(h2_in, layer["ln2_scale"], layer["ln2_bias"],
             config.layer_norm_eps)
    m = jax.nn.gelu(qdot(h2, layer["mlp_in_w"])
                    + layer["mlp_in_b"].astype(dt),
                    approximate=config.gelu_approximate)
    mlp_out = qdot(m, layer["mlp_out_w"]) + layer["mlp_out_b"].astype(dt)
    if config.use_parallel_residual:
        return x + attn_out + mlp_out       # gpt-j style parallel residual
    return h2_in + mlp_out


def _block(x, layer, config: NeoXConfig, rng=None, segment_ids=None):
    B, S, D = x.shape
    q, kk, v = _block_qkv(x, layer, config)
    attn = causal_attention(q, kk, v, impl=config.attention_impl,
                            segment_ids=segment_ids)
    return _block_finish(x, attn.reshape(B, S, D), layer, config)


def forward(params, batch, config: NeoXConfig, rng=None):
    tokens = batch["input_ids"]
    dtype = jnp.dtype(config.dtype)
    x = params["wte"].astype(dtype)[tokens]
    seg = batch.get("segment_ids") if isinstance(batch, dict) else None

    def block_fn(x, layer):
        from deepspeed_tpu.models.model import maybe_stream
        return _block(x, maybe_stream(layer), config, rng, seg)
    if config.remat:
        from deepspeed_tpu.models.gpt2 import remat_policy
        block_fn = jax.checkpoint(
            block_fn, policy=remat_policy(config.remat_policy))
    from deepspeed_tpu.models.model import scan_blocks
    x = scan_blocks(block_fn, x, params["blocks"], rng, batch,
                    config.num_layers, allow_ltd=seg is None)
    x = _ln(x, params["lnf_scale"], params["lnf_bias"],
            config.layer_norm_eps)
    logits = x @ params["embed_out"].astype(dtype)
    if config.head_bias:
        logits = logits + params["embed_out_b"].astype(dtype)
    return logits


def count_params(config: NeoXConfig) -> int:
    D, V, L, M = (config.d_model, config.vocab_size, config.num_layers,
                  config.d_mlp)
    per_layer = 4 * D + 3 * D * D + 3 * D + D * D + D + D * M + M + M * D + D
    return (V * D + L * per_layer + 2 * D + D * V
            + (V if config.head_bias else 0))


def _serving_fns(config: NeoXConfig):
    """KV-cache serving via the shared rotary scaffold (models/serving.py):
    NeoX contributes its fused-QKV partial-rotary projection and the
    parallel-residual finish."""
    from deepspeed_tpu.models import serving

    def embed_fn(params, tokens):
        return params["wte"].astype(jnp.dtype(config.dtype))[tokens]

    def qkv_fn(x, layer, positions):
        return _block_qkv(x, layer, config, positions)

    def finish_fn(x, attn_flat, layer):
        return _block_finish(x, attn_flat, layer, config)

    def head_fn(params, x):
        x = _ln(x, params["lnf_scale"], params["lnf_bias"],
                config.layer_norm_eps)
        logits = x @ params["embed_out"].astype(jnp.dtype(config.dtype))
        if config.head_bias:
            logits = logits + params["embed_out_b"].astype(
                jnp.dtype(config.dtype))
        return logits

    # fused per-layer megakernel wiring (ISSUE 12): head-major fused QKV
    # + partial rotary + parallel/serial residual in one Pallas call.
    # GPT-J-converted checkpoints (rotary_interleaved) keep the unfused
    # path — the spec reports itself unsupported
    from deepspeed_tpu.ops.pallas.fused_decode import FusedLayerSpec
    fused_spec = FusedLayerSpec(
        num_heads=config.num_heads, num_kv_heads=config.num_heads,
        head_dim=config.head_dim, d_model=config.d_model,
        norm="ln", eps=config.layer_norm_eps, qkv="headmajor",
        qkv_bias=True, out_bias=True,
        mlp="gelu_tanh" if config.gelu_approximate else "gelu_exact",
        mlp_bias=True,
        residual="parallel" if config.use_parallel_residual else "serial",
        rotary_dims=config.rotary_ndims, rope_theta=config.rope_theta,
        rotary_interleaved=config.rotary_interleaved)

    def fused_weights(layer):
        return {"n1_s": layer["ln1_scale"], "n1_b": layer["ln1_bias"],
                "wqkv": layer["qkv_w"], "bqkv": layer["qkv_b"],
                "wo": layer["dense_w"], "bo": layer["dense_b"],
                "n2_s": layer["ln2_scale"], "n2_b": layer["ln2_bias"],
                "w_in": layer["mlp_in_w"], "b_in": layer["mlp_in_b"],
                "w_out": layer["mlp_out_w"], "b_out": layer["mlp_out_b"]}

    def init_cache_fn(bs, max_len, dtype=None):
        return serving.init_cache(config.num_layers, config.num_heads,
                                  config.head_dim, bs, max_len, dtype,
                                  config.dtype)

    def prefill_fn(p, b, c):
        return serving.prefill(
            p, b, c, embed_fn=embed_fn, qkv_fn=qkv_fn, finish_fn=finish_fn,
            head_fn=head_fn, num_heads=config.num_heads,
            num_kv_heads=config.num_heads,
            attention_impl=config.attention_impl)

    def decode_fn(p, t, c, l):
        return serving.decode_step(
            p, t, c, l, embed_fn=embed_fn, qkv_fn=qkv_fn,
            finish_fn=finish_fn, head_fn=head_fn,
            num_heads=config.num_heads,
            fused_spec=fused_spec, fused_weights_fn=fused_weights)

    def verify_fn(p, t, c, l):
        return serving.verify_window(
            p, t, c, l, embed_fn=embed_fn, qkv_fn=qkv_fn,
            finish_fn=finish_fn, head_fn=head_fn,
            num_heads=config.num_heads,
            fused_spec=fused_spec, fused_weights_fn=fused_weights)

    return init_cache_fn, prefill_fn, decode_fn, verify_fn


def neox_model(size: str = "tiny", **overrides) -> Model:
    cfg_kwargs = resolve_size(NEOX_SIZES, size, "neox")
    cfg_kwargs.update(overrides)
    config = NeoXConfig(**cfg_kwargs)
    n_params = count_params(config)
    return Model(
        config=config,
        init_fn=partial(init_params, config),
        apply_fn=lambda p, b, rng=None: forward(p, b, config, rng),
        logical_specs=logical_specs(config),
        flops_per_token=6.0 * n_params,
        meta={"name": f"neox-{size}", "n_params": n_params,
              "supports_random_ltd": True, "supports_pld": True,
              "sparse_grad_params": {"wte": "input_ids"}},
        **dict(zip(("init_cache_fn", "prefill_fn", "decode_fn",
                    "verify_fn"),
                   _serving_fns(config))),
    )
