"""Torch-free reader for ``torch.save`` checkpoint files.

Reference capability: the checkpoint loaders under
/root/reference/deepspeed/checkpoint/ (deepspeed_checkpoint.py:33,
reshape_utils.py get_files) all call ``torch.load``; a TPU framework should
ingest existing DeepSpeed/Megatron checkpoints WITHOUT a torch runtime.

A modern ``.pt`` file (torch>=1.6) is a zip archive::

    archive_name/data.pkl        pickle stream (tensors as persistent ids)
    archive_name/data/<key>      raw little-endian storage bytes
    archive_name/version

The pickle stream references storages through ``persistent_id`` tuples
``('storage', <TypeStorage class>, key, location, numel)`` and rebuilds
tensors via ``torch._utils._rebuild_tensor_v2(storage, offset, size,
stride, ...)``.  This module supplies both hooks with numpy equivalents:
storages load as 1-D numpy arrays straight from the zip member, tensors
rebuild as (possibly strided) numpy views, copied to own their memory.

Unknown globals (Megatron args Namespaces, optimizer classes, ...) resolve
to inert stub objects — attribute bags that absorb REDUCE/BUILD without
running the named callable.  numpy globals are restricted to an explicit
allowlist of data reconstructors (``_NUMPY_ALLOWLIST``); a module-level
wildcard would hand out executing callables like
``numpy.testing._private.utils.runstring``.  This makes the loader far
safer than an unrestricted ``torch.load``, but it is a hardened surface,
not a proven sandbox: the pickle VM still drives the allowlisted
reconstructors and dict/list machinery, so treat checkpoints from
untrusted parties with the usual suspicion.
"""
import io
import pickle
import zipfile
from typing import Any, Dict

import numpy as np

try:
    import ml_dtypes
    _BF16 = np.dtype(ml_dtypes.bfloat16)
except Exception:                                    # pragma: no cover
    _BF16 = np.dtype(np.uint16)   # raw-bits fallback

_STORAGE_DTYPES = {
    "FloatStorage": np.dtype(np.float32),
    "DoubleStorage": np.dtype(np.float64),
    "HalfStorage": np.dtype(np.float16),
    "BFloat16Storage": _BF16,
    "LongStorage": np.dtype(np.int64),
    "IntStorage": np.dtype(np.int32),
    "ShortStorage": np.dtype(np.int16),
    "CharStorage": np.dtype(np.int8),
    "ByteStorage": np.dtype(np.uint8),
    "BoolStorage": np.dtype(np.bool_),
    "ComplexFloatStorage": np.dtype(np.complex64),
    "ComplexDoubleStorage": np.dtype(np.complex128),
    "UntypedStorage": np.dtype(np.uint8),
}


class _StubBase:
    """Inert stand-in for any global this reader does not model (argparse
    Namespaces, Megatron classes, torch dtypes...).  Construction absorbs
    any arguments; BUILD state lands in ``__dict__``; lookups of missing
    attributes return None so downstream ``getattr`` probing stays
    harmless.  No checkpoint-named callable body runs — construction and
    BUILD only fill ``__dict__`` (hardening, not a formal sandbox)."""

    def __new__(cls, *a, **kw):
        return object.__new__(cls)

    def __init__(self, *a, **kw):
        if a:
            self.__dict__["args"] = a
        if kw:
            self.__dict__.update(kw)

    def __setstate__(self, state):
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self.__dict__["_state"] = state

    def __getattr__(self, k):
        # dunders must miss honestly: returning None for __array__ &co
        # makes hasattr() duck-typing see capabilities the stub lacks
        # (np.asarray(stub) would raise instead of being skippable)
        if k.startswith("__") and k.endswith("__"):
            raise AttributeError(k)
        return None

    def __repr__(self):
        return f"<stub {type(self).__name__}>"


def _make_stub(qualname: str):
    # a real TYPE (NEWOBJ requires one), fresh per global so repr stays
    # informative
    return type(qualname.replace(".", "_"), (_StubBase,), {})


class _StorageType:
    def __init__(self, name):
        self.name = name
        self.dtype = _STORAGE_DTYPES.get(name)


def _rebuild_tensor(storage: np.ndarray, storage_offset, size, stride):
    itemsize = storage.dtype.itemsize
    if not size:
        return storage[storage_offset:storage_offset + 1].reshape(()).copy()
    flat = storage[storage_offset:]
    byte_strides = tuple(int(s) * itemsize for s in stride)
    arr = np.lib.stride_tricks.as_strided(flat, shape=tuple(size),
                                          strides=byte_strides)
    return arr.copy()


def _rebuild_tensor_v2(storage, storage_offset, size, stride,
                       requires_grad=False, backward_hooks=None,
                       metadata=None):
    return _rebuild_tensor(storage, storage_offset, size, stride)


def _rebuild_parameter(data, requires_grad=False, backward_hooks=None):
    return data


# The only numpy globals a tensor/ndarray/scalar pickle legitimately
# references (both the pre- and post-numpy-2.0 module paths).  Everything
# else under numpy.* resolves to an inert stub — numpy is full of
# callables that execute on REDUCE (numpy.testing._private.utils.runstring
# runs arbitrary code strings).
_NUMPY_ALLOWLIST = frozenset([
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "scalar"),
    # pickle protocol >= 5 ndarrays reconstruct through _frombuffer
    # (bytes -> array; data-only)
    ("numpy.core.numeric", "_frombuffer"),
    ("numpy._core.numeric", "_frombuffer"),
])


class _TorchUnpickler(pickle.Unpickler):
    def __init__(self, data_pkl: bytes, load_storage):
        super().__init__(io.BytesIO(data_pkl))
        self._load_storage = load_storage

    def find_class(self, module: str, name: str):
        if module == "torch._utils":
            if name == "_rebuild_tensor_v2":
                return _rebuild_tensor_v2
            if name == "_rebuild_tensor":
                return _rebuild_tensor
            if name == "_rebuild_parameter":
                return _rebuild_parameter
        if module in ("torch", "torch.storage") and name in _STORAGE_DTYPES:
            return _StorageType(name)
        if module == "torch" and name == "Size":
            # torch.Size pickles as GLOBAL('torch','Size') + REDUCE with a
            # tuple payload; real DeepSpeed param_shapes are torch.Size
            return lambda *a: tuple(a[0]) if a else ()
        if module == "collections" and name == "OrderedDict":
            import collections
            return collections.OrderedDict
        if module == "builtins" and name in ("dict", "list", "set",
                                             "tuple", "frozenset",
                                             "complex", "bytearray"):
            import builtins
            return getattr(builtins, name)
        if module == "_codecs" and name == "encode":
            # protocol-2 ndarray states carry their bytes as
            # latin-1-encoded str + _codecs.encode (a pure str->bytes
            # conversion; safe)
            import codecs
            return codecs.encode
        if (module, name) in _NUMPY_ALLOWLIST or (
                # numpy dtype classes (numpy.dtypes.Float32DType ...):
                # zero-arg reconstructors for dtype pickles, data only
                module == "numpy.dtypes" and name.endswith("DType")):
            import importlib
            try:
                return getattr(importlib.import_module(module), name)
            except (ImportError, AttributeError):
                pass  # allowlist miss falls through to an inert stub
        # torch dtype globals (torch.float32 ...), argparse.Namespace,
        # Megatron/DeepSpeed classes, and EVERYTHING else — including the
        # rest of numpy (numpy.testing._private.utils.runstring executes
        # arbitrary strings; a module wildcard would hand it out): inert
        # stubs
        return _make_stub(f"{module}.{name}")

    def persistent_load(self, pid):
        # ('storage', storage_type, key, location, numel)
        if not (isinstance(pid, tuple) and pid and pid[0] == "storage"):
            raise pickle.UnpicklingError(f"unknown persistent id {pid!r}")
        _, storage_type, key, _location, _numel = pid
        dtype = getattr(storage_type, "dtype", None)
        if dtype is None:
            # storage class resolved to a stub (unexpected torch version):
            # fall back to raw bytes so shapes still reconstruct
            dtype = np.dtype(np.uint8)
        return self._load_storage(str(key), dtype)


def load_pt(path: str) -> Any:
    """Read a ``torch.save`` .pt/.bin file without torch.  Tensors come
    back as numpy arrays (bfloat16 via ml_dtypes); unknown objects as
    inert stubs."""
    with zipfile.ZipFile(path) as zf:
        names = zf.namelist()
        pkl_name = next((n for n in names if n.endswith("/data.pkl")
                         or n == "data.pkl"), None)
        if pkl_name is None:
            raise ValueError(
                f"{path}: not a torch>=1.6 zip checkpoint (no data.pkl); "
                "legacy tar/pickle checkpoints are not supported — "
                "re-save with a modern torch")
        prefix = pkl_name[:-len("data.pkl")]
        data_pkl = zf.read(pkl_name)
        cache: Dict[str, np.ndarray] = {}

        def load_storage(key: str, dtype: np.dtype) -> np.ndarray:
            ck = f"{key}:{dtype}"
            if ck not in cache:
                raw = zf.read(f"{prefix}data/{key}")
                cache[ck] = np.frombuffer(raw, dtype=dtype)
            return cache[ck]

        return _TorchUnpickler(data_pkl, load_storage).load()
