"""Async I/O handle (reference: deepspeed/ops/aio over csrc/aio — the
``aio_handle`` pybind object with async pread/pwrite + wait).

ISSUE 14: every handle reports completed I/O windows through the
process-wide :class:`~deepspeed_tpu.telemetry.iostat.IoStat` when one
is installed (:func:`set_aio_iostat`) — per-request submit→completion
latency/bandwidth for the queue-depth paths, whole-drain windows for
batched ``wait()``.  With no sink installed the instrumentation is a
dict insert per submit (observability must not tax the I/O path)."""
import ctypes
import os
import time
from typing import Optional

import numpy as np

from op_builder import AsyncIOBuilder, load_op

#: process-wide I/O observation sink (telemetry/iostat.py installs it)
_IOSTAT = None


def set_aio_iostat(iostat) -> None:
    """Install (or clear, with None) the process-wide IoStat every
    AsyncIOHandle reports through."""
    global _IOSTAT
    _IOSTAT = iostat


class AsyncIOHandle:
    """Thread-pool async file reader/writer for numpy buffers.

    Mirrors the reference handle API: ``async_pread``/``async_pwrite`` submit
    and return immediately; ``wait()`` blocks until all in-flight requests
    complete and returns the number of failures.
    """

    def __init__(self, block_size: int = 1 << 20, queue_depth: int = 8,
                 single_submit: bool = False, overlap_events: bool = True,
                 thread_count: int = 4):
        self._lib = load_op(AsyncIOBuilder())
        self._lib.ds_aio_handle_new.restype = ctypes.c_void_p
        self._lib.ds_aio_wait.restype = ctypes.c_long
        self._lib.ds_aio_inflight.restype = ctypes.c_long
        self._lib.ds_aio_pread.restype = ctypes.c_int
        self._lib.ds_aio_pwrite.restype = ctypes.c_int
        self._lib.ds_aio_submit_pread.restype = ctypes.c_long
        self._lib.ds_aio_submit_pwrite.restype = ctypes.c_long
        self._lib.ds_aio_wait_req.restype = ctypes.c_int
        self._lib.ds_aio_wait_req_dur.restype = ctypes.c_int
        self._lib.ds_aio_backend.restype = ctypes.c_int
        self._h = ctypes.c_void_p(
            self._lib.ds_aio_handle_new(ctypes.c_int(thread_count)))
        self.block_size = block_size
        self.thread_count = thread_count
        # keep submitted buffers alive until wait(); per-request buffers
        # keyed by id so wait_req can release them individually
        self._pinned = []
        self._pinned_by_id = {}
        #: rid -> (t_submit, nbytes, op) for per-request windows; the
        #: batch path keeps (t_submit, nbytes, op) tuples until wait()
        self._io_meta = {}
        self._io_batch = []

    def _observe(self, op: str, nbytes: int, t0: float,
                 window: str = "op"):
        self._observe_dur(op, nbytes, time.perf_counter() - t0,
                          window=window)

    def _observe_dur(self, op: str, nbytes: int, duration_s: float,
                     window: str = "op"):
        sink = _IOSTAT
        if sink is None:
            return
        try:
            sink.observe(op, nbytes, duration_s, window=window)
        # dslint: disable=DSL005 -- observation is strictly best-effort:
        # a broken telemetry sink must never turn a completed I/O into
        # a failure (the bytes are already on disk / in the buffer)
        except Exception:
            pass

    def _buf_ptr(self, arr: np.ndarray):
        assert arr.flags.c_contiguous
        return arr.ctypes.data_as(ctypes.c_char_p)

    def async_pread(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self._lib.ds_aio_pread(
            self._h, filename.encode(), self._buf_ptr(buffer),
            ctypes.c_size_t(buffer.nbytes), ctypes.c_size_t(offset))
        if rc == 0:
            self._pinned.append(buffer)
            self._io_batch.append((time.perf_counter(), buffer.nbytes,
                                   "read"))
        return rc

    def async_pwrite(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self._lib.ds_aio_pwrite(
            self._h, filename.encode(), self._buf_ptr(buffer),
            ctypes.c_size_t(buffer.nbytes), ctypes.c_size_t(offset))
        if rc == 0:
            self._pinned.append(buffer)
            self._io_batch.append((time.perf_counter(), buffer.nbytes,
                                   "write"))
        return rc

    def submit_pread(self, buffer: np.ndarray, filename: str,
                     offset: int = 0) -> int:
        """Submit a read; returns a positive request id for wait_req, or
        raises on submit failure.  The buffer stays pinned until its
        wait_req (or a full wait())."""
        rid = self._lib.ds_aio_submit_pread(
            self._h, filename.encode(), self._buf_ptr(buffer),
            ctypes.c_size_t(buffer.nbytes), ctypes.c_size_t(offset))
        if rid <= 0:
            raise IOError(f"aio submit_pread failed for {filename}")
        self._pinned_by_id[rid] = buffer
        self._io_meta[rid] = (time.perf_counter(), buffer.nbytes, "read")
        return int(rid)

    def submit_pwrite(self, buffer: np.ndarray, filename: str,
                      offset: int = 0) -> int:
        """Submit a write; returns a positive request id for wait_req."""
        rid = self._lib.ds_aio_submit_pwrite(
            self._h, filename.encode(), self._buf_ptr(buffer),
            ctypes.c_size_t(buffer.nbytes), ctypes.c_size_t(offset))
        if rid <= 0:
            raise IOError(f"aio submit_pwrite failed for {filename}")
        self._pinned_by_id[rid] = buffer
        self._io_meta[rid] = (time.perf_counter(), buffer.nbytes, "write")
        return int(rid)

    def wait_req(self, rid: int) -> int:
        """Block until request ``rid`` completes (others may stay in
        flight — THE point of the queue-depth backend).  Returns 0 on
        success, -1 on I/O failure.  Each id may be waited once.

        Telemetry uses the BACKEND's submit→completion duration, not
        this call's submit→wait window: a fire-and-forget write is
        reaped a whole optimizer step later, and charging that step's
        compute to the device would collapse every bandwidth gauge."""
        dur = ctypes.c_double(0.0)
        err = self._lib.ds_aio_wait_req_dur(self._h, ctypes.c_long(rid),
                                            ctypes.byref(dur))
        self._pinned_by_id.pop(rid, None)
        meta = self._io_meta.pop(rid, None)
        if meta is not None and err == 0 and dur.value > 0:
            _, nbytes, op = meta
            self._observe_dur(op, nbytes, dur.value)
        return int(err)

    def backend(self) -> str:
        """"io_uring" (queue-depth kernel submission) or "threadpool"."""
        return ("io_uring" if self._lib.ds_aio_backend(self._h)
                else "threadpool")

    def sync_pread(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self.async_pread(buffer, filename, offset)
        if rc == 0:
            rc = -self.wait()
        return rc

    def sync_pwrite(self, buffer: np.ndarray, filename: str, offset: int = 0) -> int:
        rc = self.async_pwrite(buffer, filename, offset)
        if rc == 0:
            rc = -self.wait()
        return rc

    def wait(self) -> int:
        errors = self._lib.ds_aio_wait(self._h)
        self._pinned.clear()
        self._pinned_by_id.clear()
        # batched drain: one bandwidth sample per op over the window
        # from the oldest outstanding submit to completion.  Per-request
        # submits that were never wait_req'd fold into the same drain
        # sample (wait() completes them too).
        if errors == 0 and (self._io_batch or self._io_meta):
            pending = self._io_batch + list(self._io_meta.values())
            for op in ("read", "write"):
                rows = [(t0, n) for t0, n, o in pending if o == op]
                if rows:
                    self._observe(op, sum(n for _, n in rows),
                                  min(t0 for t0, _ in rows),
                                  window="drain")
        self._io_batch.clear()
        self._io_meta.clear()
        return int(errors)

    def inflight(self) -> int:
        return int(self._lib.ds_aio_inflight(self._h))

    def pending_requests(self) -> int:
        """Per-request submits not yet reaped by ``wait_req``/``wait``
        (the window a double-buffered caller gates on)."""
        return len(self._io_meta)

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            try:
                self._lib.ds_aio_handle_free(h)
            # dslint: disable=DSL005 -- interpreter-teardown __del__: the
            # shared lib may already be unloaded, and raising from __del__
            # only prints an unraisable-exception warning anyway
            except Exception:
                pass
            self._h = None
