// Async file I/O for the ZeRO-Infinity NVMe tier (reference capability:
// csrc/aio/ — libaio/O_DIRECT queue with a pthread pool behind the pybind
// `aio_handle`).  This environment ships no libaio/liburing headers, so the
// implementation is a std::thread worker pool issuing positional pread/pwrite
// (optionally O_DIRECT) — same async-handle semantics: submit returns
// immediately, `wait` drains completions.
#include <atomic>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <mutex>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Request {
  int op;            // 0 = read, 1 = write
  char* buf;
  size_t count;
  size_t offset;
  int fd;
  bool close_fd;
};

struct Handle {
  std::vector<std::thread> workers;
  std::deque<Request> queue;
  std::mutex mu;
  std::condition_variable cv;
  std::condition_variable done_cv;
  std::atomic<long> inflight{0};
  std::atomic<long> errors{0};
  bool stop = false;

  explicit Handle(int n_threads) {
    for (int i = 0; i < n_threads; ++i)
      workers.emplace_back([this] { run(); });
  }

  ~Handle() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv.notify_all();
    for (auto& t : workers) t.join();
  }

  void submit(Request r) {
    inflight.fetch_add(1);
    {
      std::lock_guard<std::mutex> lk(mu);
      queue.push_back(r);
    }
    cv.notify_one();
  }

  void run() {
    for (;;) {
      Request r;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv.wait(lk, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        r = queue.front();
        queue.pop_front();
      }
      ssize_t rc = 0;
      size_t done = 0;
      while (done < r.count) {
        if (r.op == 0)
          rc = pread(r.fd, r.buf + done, r.count - done, r.offset + done);
        else
          rc = pwrite(r.fd, r.buf + done, r.count - done, r.offset + done);
        if (rc <= 0) break;
        done += (size_t)rc;
      }
      if (done != r.count) errors.fetch_add(1);
      if (r.close_fd) close(r.fd);
      if (inflight.fetch_sub(1) == 1) done_cv.notify_all();
    }
  }

  long wait() {
    std::unique_lock<std::mutex> lk(mu);
    done_cv.wait(lk, [this] { return inflight.load() == 0; });
    return errors.exchange(0);
  }
};

}  // namespace

extern "C" {

void* ds_aio_handle_new(int n_threads) { return new Handle(n_threads); }

void ds_aio_handle_free(void* h) { delete (Handle*)h; }

// returns 0 on successful submit, -1 on open failure
int ds_aio_pread(void* h, const char* path, char* buf, size_t count,
                 size_t offset) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return -1;
  ((Handle*)h)->submit({0, buf, count, offset, fd, true});
  return 0;
}

int ds_aio_pwrite(void* h, const char* path, char* buf, size_t count,
                  size_t offset) {
  int fd = open(path, O_WRONLY | O_CREAT, 0644);
  if (fd < 0) return -1;
  ((Handle*)h)->submit({1, buf, count, offset, fd, true});
  return 0;
}

// drain all in-flight requests; returns number of failed requests since the
// previous wait
long ds_aio_wait(void* h) { return ((Handle*)h)->wait(); }

long ds_aio_inflight(void* h) { return ((Handle*)h)->inflight.load(); }

}  // extern "C"
