"""Inference engine (reference: deepspeed/inference/engine.py:37
``InferenceEngine``).

Capabilities mapped TPU-native:
- tensor-parallel serving — the model's logical PartitionSpecs over the
  ``model`` mesh axis (the reference's AutoTP / kernel-injection TP,
  inference/engine.py:217) with XLA inserting the all-reduces;
- compiled generate loop — ``lax.while_loop`` token loop compiled once
  (the reference's CUDA-graph capture/replay, engine.py:487, is subsumed by
  XLA compilation);
- greedy and temperature sampling with right-padded static shapes.

KV-cache fast path (default): prefill fills a static [L, B, S_max, KV, hd]
cache and each decode step runs the from-scratch Pallas decode-attention
kernel (ops/pallas/decode_attention.py — the ``ds_softmax_context``
equivalent, csrc/transformer/inference/csrc/pt_binding.cpp:434), so per-token
cost is O(S) cache streaming instead of O(S²) recompute.  Sampling: greedy /
temperature / top-k / top-p (inference/sampling.py) with EOS early-stop.
``use_cache=False`` keeps the O(S²) recompute loop as the numerics oracle.
"""
from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import MeshTopology, set_topology
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
from deepspeed_tpu.utils.logging import log_dist


def _tree_cast(tree, dtype):
    return jax.tree.map(
        lambda x: x.astype(dtype) if jnp.issubdtype(x.dtype, jnp.floating) else x,
        tree)


class InferenceEngine:
    def __init__(self, model, config: DeepSpeedInferenceConfig,
                 model_parameters=None, mesh=None, defer_params=False):
        """``defer_params=True`` skips parameter materialisation entirely —
        the caller binds ``self.params`` itself (the hybrid engine does:
        its fused view is already cast+sharded, and the default path would
        build a second full-size placed copy only to discard it)."""
        self.model = model
        self._config = config
        tp = config.tensor_parallel.tp_size if config.tensor_parallel.enabled else 1
        # EP-sharded MoE serving (reference inference/engine.py:230 expert
        # group creation): experts partition over the expert mesh axis
        ep = (int(config.moe.ep_size or 1)
              if getattr(config.moe, "enabled", True) else 1)
        kw = dict(model_parallel_size=tp, expert_parallel_size=ep)
        if mesh is not None:
            kw["devices"] = list(mesh.devices.flat)
        self.topology = MeshTopology(**kw)
        set_topology(self.topology)
        self.mesh = self.topology.mesh
        self.dtype = jnp.dtype(config.dtype)

        logical = getattr(model, "logical_specs", None)
        if defer_params:
            self.params = None
            self._generate_fns = {}
            self._forward = jax.jit(lambda p, batch: model.apply(p, batch))
            log_dist(f"InferenceEngine: tp={tp}, dtype={self.dtype} "
                     "(params deferred)", ranks=[0])
            return
        if model_parameters is None:
            params = model.init(jax.random.PRNGKey(0))
        else:
            params = model_parameters
        quant_blocks = None
        if config.quant.enabled:
            # weight-only int8 serving (reference: inference config `quant`
            # / MoQ): stacked block weights store as per-block int8 + fp32
            # scales; maybe_stream dequantizes each layer inside the scan.
            # HBM holds 1 byte/param for the blocks — 2x model capacity at
            # bf16 compute.  Quantization runs leaf-by-leaf with input
            # donation BEFORE the bulk placement, so peak device memory is
            # int8 totals + ONE full-precision leaf — the big-model load
            # path the feature exists for (checkpoint weights arrive as
            # host arrays).
            from deepspeed_tpu.utils.logging import warning_once
            if config.quant.bits != 8:
                warning_once(f"quant.bits={config.quant.bits}: only 8-bit "
                             "weight quantization is implemented; using 8")
            bk = getattr(model, "blocks_key", "blocks")
            if isinstance(params, dict) and bk in params:
                from deepspeed_tpu.models.model import QuantizedTensor
                from deepspeed_tpu.ops.pallas.quantization import (
                    BLOCK, block_quantize_int8)
                dt = str(jnp.dtype(self.dtype))
                blk_logical = (logical.get(bk)
                               if isinstance(logical, dict) else None)

                def _shard_for(spec, x, is_scales):
                    if spec is None:
                        return NamedSharding(self.mesh, P())
                    if is_scales:
                        # scales share the weight's layout when the grouped
                        # last dim still divides over its axis; otherwise
                        # replicate that dim (tiny tensor)
                        C = x.shape[-1]
                        nb = -(-C // BLOCK)
                        last = tuple(spec)[-1] if len(spec) else None
                        tp_n = (int(np.prod([self.mesh.shape[a] for a in
                                             ((last,) if isinstance(
                                                 last, str) else last)]))
                                if last else 1)
                        if nb % max(tp_n, 1) != 0:
                            spec = P(*tuple(spec)[:-1], None)
                    return NamedSharding(self.mesh, spec)

                import functools

                @functools.lru_cache(maxsize=None)
                def _packer(out_shardings):
                    # one trace per unique (shape→sharding) class: llama's
                    # wq/wk/wv etc. share a compiled quantization program
                    return jax.jit(
                        lambda v: block_quantize_int8(v.astype(self.dtype)),
                        donate_argnums=(0,), out_shardings=out_shardings)

                def pack_leaf(x, spec):
                    # >=3-dim floating = the stacked [L, in, out] weight
                    # mats (2-dim biases/norms stay full precision:
                    # negligible bytes, free accuracy).  q/s inherit the
                    # weight's TP layout so int8 serving composes with
                    # tensor parallelism.
                    if (isinstance(x, np.ndarray)
                            and np.issubdtype(x.dtype, np.floating)):
                        # host arrays cast to compute dtype ON HOST: the
                        # fp32->int8 donation cannot alias (different
                        # byte sizes), so an fp32 transfer doubles both
                        # the wire bytes and the device-side peak — at
                        # 7B the difference between fitting 16 GB or not
                        x = x.astype(self.dtype)
                    x = jnp.asarray(x)
                    if not jnp.issubdtype(x.dtype, jnp.floating):
                        return x        # non-float buffers pass through
                    if x.ndim >= 3:
                        fn = _packer((_shard_for(spec, x, False),
                                      _shard_for(spec, x, True)))
                        q, s = fn(x)
                        return QuantizedTensor(q, s, dt)
                    if spec is not None:
                        return jax.device_put(
                            x.astype(self.dtype),
                            NamedSharding(self.mesh, spec))
                    return x

                params = dict(params)
                blk = params.pop(bk)
                leaves, treedef = jax.tree_util.tree_flatten(blk)
                if blk_logical is not None:
                    spec_leaves = treedef.flatten_up_to(blk_logical)
                else:
                    spec_leaves = [None] * len(leaves)
                quant_blocks = jax.tree_util.tree_unflatten(
                    treedef, [pack_leaf(x, sp)
                              for x, sp in zip(leaves, spec_leaves)])
            else:
                warning_once(
                    f"quant.enabled: params tree has no {bk!r} subtree — "
                    "nothing to quantize, serving at full precision")
        params = _tree_cast(params, self.dtype)
        if logical is not None:
            shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), logical,
                is_leaf=lambda x: isinstance(x, P))
            if quant_blocks is not None and isinstance(shardings, dict):
                # quantized blocks were placed (TP-sharded) at pack time
                shardings = {k: v for k, v in shardings.items() if k != bk}
            params = jax.device_put(params, shardings)
        else:
            params = jax.device_put(
                params, NamedSharding(self.mesh, P()))
        if quant_blocks is not None:
            params = dict(params)
            params[bk] = quant_blocks
        self.params = params
        self._generate_fns = {}
        self._forward = jax.jit(
            lambda p, batch: model.apply(p, batch))
        log_dist(f"InferenceEngine: tp={tp}, dtype={self.dtype}", ranks=[0])

    @property
    def module(self):
        return self.model

    def __call__(self, batch):
        return self.forward(batch)

    def forward(self, batch):
        if isinstance(batch, (np.ndarray, jnp.ndarray)):
            batch = {"input_ids": batch}
        return self._forward(self.params, batch)

    # ------------------------------------------------------------------ generate
    def _build_generate(self, total_len: int, do_sample: bool, top_k: int,
                        top_p: float, eos_id: Optional[int]):
        """No-cache O(S²) recompute loop — the numerics oracle.  Supports the
        full sampling surface (greedy/temperature/top-k/top-p/EOS) so cached
        and uncached paths are comparable config-for-config."""
        from deepspeed_tpu.inference.sampling import sample
        model = self.model

        def gen(params, tokens, length, rng, temperature):
            """tokens: [B, total_len] right-padded; length: [B] prompt lens."""
            B = tokens.shape[0]

            def cond(state):
                # no all-done early exit: the loop keeps writing EOS so the
                # tail matches the cached path token-for-token (the oracle
                # contract); done rows cost almost nothing
                cur, _, _, done = state
                return cur < total_len

            def body(state):
                cur, toks, rng, done = state
                logits = model.apply(params, {"input_ids": toks})
                # next token for each row comes from its current last position
                idx = jnp.minimum(jnp.maximum(length, cur) - 1, total_len - 1)
                last = logits[jnp.arange(B), idx]          # [B, V]
                rng, sub = jax.random.split(rng)
                nxt = sample(last, sub, do_sample=do_sample,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p).astype(toks.dtype)
                if eos_id is not None:
                    nxt = jnp.where(done, jnp.asarray(eos_id, toks.dtype), nxt)
                # only write where cur >= prompt length (else keep prompt token)
                write = cur >= length
                cur_col = jax.lax.dynamic_slice(toks, (0, cur), (B, 1))[:, 0]
                new_col = jnp.where(write, nxt, cur_col)
                toks = jax.lax.dynamic_update_slice(
                    toks, new_col[:, None], (0, cur))
                if eos_id is not None:
                    done = jnp.logical_or(
                        done, jnp.logical_and(write, new_col == eos_id))
                return (cur + 1, toks, rng, done)

            start = jnp.min(length)
            done0 = jnp.zeros((B,), bool)
            _, toks, _, _ = jax.lax.while_loop(
                cond, body, (start, tokens, rng, done0))
            return toks

        return jax.jit(gen, static_argnames=())

    # ------------------------------------------------------------ cached path
    def _build_cached_generate(self, prompt_pad: int, max_new: int,
                               do_sample: bool, top_k: int, top_p: float,
                               eos_id: Optional[int]):
        """Prefill + lax.scan decode loop over the KV cache; one compiled
        program per (prompt_pad, max_new, sampling-config) bucket."""
        from deepspeed_tpu.inference.sampling import sample
        model = self.model
        dtype = self.dtype
        total = prompt_pad + max_new
        # the decode kernel streams the cache in S-blocks and pads unaligned
        # caches with a full HBM copy per call — size the cache buffer itself
        # to a 64 multiple (positions never exceed `total`; the tail is dead)
        cache_size = -(-total // 64) * 64

        cache_dtype = self._config.kv_cache_dtype or dtype

        def _cache_constraint(B):
            """Stable KV-cache layout for the whole generate program: the
            batch dim shards over as much of the dp group as divides it,
            every other dim left unconstrained (TP still shards the KV-head
            dim).  Without the pin, XLA picks per-while-loop layouts and
            falls back to replicate-then-repartition between them (SPMD
            'Involuntary full rematerialization')."""
            shape = dict(self.mesh.shape)
            axes = []
            rem = B
            for a in self.topology.data_parallel_axes:
                if shape[a] > 1 and rem % shape[a] == 0:
                    axes.append(a)
                    rem //= shape[a]
            if not axes:
                return lambda cache: cache
            def pin_leaf(c):
                spec = P(*([P.UNCONSTRAINED, tuple(axes)]
                           + [P.UNCONSTRAINED] * (c.ndim - 2)))
                return jax.lax.with_sharding_constraint(
                    c, NamedSharding(self.mesh, spec))
            return lambda cache: jax.tree.map(pin_leaf, cache)

        def gen(params, tokens_padded, lengths, rng, temperature):
            B = tokens_padded.shape[0]
            pin = _cache_constraint(B)
            cache = pin(model.init_cache_fn(B, cache_size, cache_dtype))
            logits, cache = model.prefill_fn(
                params, {"input_ids": tokens_padded}, cache)
            cache = pin(cache)
            last = logits[jnp.arange(B), lengths - 1]       # [B, V]
            if do_sample:
                rng, sub = jax.random.split(rng)
            else:
                sub = rng       # greedy ignores it; keep threefry out of
                                # the loop (it serializes ~0.1 ms/step)
            nxt = sample(last, sub, do_sample=do_sample,
                         temperature=temperature, top_k=top_k, top_p=top_p)
            done = (jnp.full((B,), False) if eos_id is None
                    else nxt == eos_id)

            # lax.scan with ys-emitted tokens: A/B'd against a fori_loop +
            # in-place token buffer on chip — the scan form is ~0.1 ms/token
            # FASTER (the per-step dynamic_update_slice into the output
            # buffer costs more than scan's ys stacking;
            # scripts/decode_profile.py engine_{scan,fori}_mimic)
            def body(carry, _):
                cache, tok, lens, rng, done = carry
                logits, cache = model.decode_fn(params, tok, cache, lens)
                cache = pin(cache)
                if do_sample:
                    rng, sub = jax.random.split(rng)
                else:
                    sub = rng       # greedy: keep threefry out of the loop
                new = sample(logits, sub, do_sample=do_sample,
                             temperature=temperature, top_k=top_k, top_p=top_p)
                if eos_id is not None:
                    new = jnp.where(done, jnp.int32(eos_id), new)
                    new_done = jnp.logical_or(done, new == eos_id)
                else:
                    new_done = done
                return (cache, new, lens + 1, rng, new_done), new

            # max_new-1 decode steps: the prefill already sampled token 0
            _, rest = jax.lax.scan(
                body, (cache, nxt, lengths, rng, done), None,
                length=max_new - 1)
            gen_tokens = jnp.concatenate(
                [nxt[:, None],
                 rest.T.astype(nxt.dtype)],
                axis=1)                                      # [B, max_new]
            # write generated tokens at each row's true positions
            out = jnp.zeros((B, total), jnp.int32)
            out = jax.lax.dynamic_update_slice(out, tokens_padded, (0, 0))
            idx = lengths[:, None] + jnp.arange(max_new)[None, :]
            out = out.at[jnp.arange(B)[:, None], idx].set(gen_tokens)
            return out, gen_tokens

        return jax.jit(gen)

    @staticmethod
    def _pad_bucket(n: int, quantum: int = 64) -> int:
        return max(quantum, -(-n // quantum) * quantum)

    def generate(self, input_ids, max_new_tokens: int = 32,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: int = 0, top_p: float = 1.0,
                 eos_token_id: Optional[int] = None,
                 rng: Optional[jax.Array] = None,
                 use_cache: bool = True, **kw):
        """Autoregressive generation (reference: InferenceEngine.generate guard,
        inference/engine.py:576 — here it is the real decode loop).

        With ``use_cache`` (default) the KV-cache fast path runs: prefill +
        per-token decode against the cache (O(S) per token).  ``use_cache=
        False`` keeps the O(S²) recompute loop (numerics oracle)."""
        input_ids = np.asarray(input_ids)
        if input_ids.ndim == 1:
            input_ids = input_ids[None]
        B, S = input_ids.shape
        max_ctx = getattr(self.model.config, "max_seq_len", S + max_new_tokens)
        if S + max_new_tokens > max_ctx:
            raise ValueError(
                f"generate: prompt {S} + max_new_tokens {max_new_tokens} "
                f"exceeds model context {max_ctx}")
        rng = rng if rng is not None else jax.random.PRNGKey(0)

        cached_ok = (use_cache and self.model.init_cache_fn is not None
                     and self.model.prefill_fn is not None
                     and self.model.decode_fn is not None)
        if cached_ok:
            prompt_pad = min(self._pad_bucket(S), max_ctx - max_new_tokens)
            if prompt_pad < S:
                prompt_pad = S
            tokens = np.zeros((B, prompt_pad), dtype=np.int32)
            tokens[:, :S] = input_ids
            length = np.full((B,), S, dtype=np.int32)
            key = ("cached", prompt_pad, max_new_tokens, do_sample,
                   int(top_k), float(top_p), eos_token_id)
            if key not in self._generate_fns:
                self._generate_fns[key] = self._build_cached_generate(
                    prompt_pad, max_new_tokens, do_sample, int(top_k),
                    float(top_p), eos_token_id)
            out, _ = self._generate_fns[key](
                self.params, jnp.asarray(tokens), jnp.asarray(length), rng,
                jnp.float32(temperature))
            out = np.asarray(out)
            # reference-compatible shape: [B, S + max_new]
            return out[:, :S + max_new_tokens]

        total = S + max_new_tokens
        tokens = np.zeros((B, total), dtype=np.int32)
        tokens[:, :S] = input_ids
        length = np.full((B,), S, dtype=np.int32)
        key = ("nocache", total, do_sample, int(top_k), float(top_p),
               eos_token_id)
        if key not in self._generate_fns:
            self._generate_fns[key] = self._build_generate(
                total, do_sample, int(top_k), float(top_p), eos_token_id)
        out = self._generate_fns[key](
            self.params, jnp.asarray(tokens), jnp.asarray(length), rng,
            jnp.float32(temperature))
        return np.asarray(out)
