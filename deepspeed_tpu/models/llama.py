"""Llama-2 / Llama-3-style decoder family, TPU-native: RMSNorm, rotary position
embeddings, grouped-query attention, SwiGLU MLP; scan-over-layers with stacked
params, Megatron-pattern TP specs.

Covers the BASELINE.md configs "Llama-2 13B ZeRO-3 + offload" and "Llama-2 7B
PP×ZeRO-1".  Architecture follows the public Llama papers; capability parity
target is the reference's HF-Llama support (module_inject/containers/llama.py).
"""
from dataclasses import dataclass
from functools import partial

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.models.model import Model, qdot, resolve_size
from deepspeed_tpu.ops.attention import causal_attention


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    max_seq_len: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32          # < num_heads → grouped-query attention
    d_model: int = 4096
    d_mlp: int = 11008
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    #: InternLM variant (module_inject/containers/internlm.py capability):
    #: biased q/k/v/o projections on the otherwise-llama block
    attn_bias: bool = False
    dtype: str = "bfloat16"
    remat: bool = False
    remat_policy: str = "nothing"
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.d_model // self.num_heads


LLAMA_SIZES = {
    "tiny": dict(vocab_size=256, max_seq_len=128, num_layers=2, num_heads=4,
                 num_kv_heads=2, d_model=32, d_mlp=64),
    "7b": dict(num_layers=32, num_heads=32, num_kv_heads=32, d_model=4096,
               d_mlp=11008),
    "13b": dict(num_layers=40, num_heads=40, num_kv_heads=40, d_model=5120,
                d_mlp=13824),
    "70b": dict(num_layers=80, num_heads=64, num_kv_heads=8, d_model=8192,
                d_mlp=28672),
}


def init_params(config: LlamaConfig, rng) -> dict:
    D, V, L, M = (config.d_model, config.vocab_size, config.num_layers,
                  config.d_mlp)
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    k = iter(jax.random.split(rng, 12))
    std = 0.02
    res_std = std / (2 * L) ** 0.5
    norm = partial(jax.random.normal, dtype=jnp.float32)
    blocks = {
        "attn_norm": jnp.ones((L, D)),
        "wq": norm(next(k), (L, D, H * hd)) * std,
        "wk": norm(next(k), (L, D, KV * hd)) * std,
        "wv": norm(next(k), (L, D, KV * hd)) * std,
        "wo": norm(next(k), (L, H * hd, D)) * res_std,
        "mlp_norm": jnp.ones((L, D)),
        "w_gate": norm(next(k), (L, D, M)) * std,
        "w_up": norm(next(k), (L, D, M)) * std,
        "w_down": norm(next(k), (L, M, D)) * res_std,
    }
    if config.attn_bias:
        blocks.update({"wq_b": jnp.zeros((L, H * hd)),
                       "wk_b": jnp.zeros((L, KV * hd)),
                       "wv_b": jnp.zeros((L, KV * hd)),
                       "wo_b": jnp.zeros((L, D))})
    return {
        "wte": norm(next(k), (V, D)) * std,
        "blocks": blocks,
        "final_norm": jnp.ones((D,)),
        "lm_head": norm(next(k), (D, V)) * std,
    }


def numpy_init_params(config: LlamaConfig, seed: int = 0) -> dict:
    """Host-side init mirroring ``init_params``'s distributions with numpy
    (the offload tier's fast init — see models/gpt2.py numpy_init_params)."""
    import numpy as np
    D, V, L, M = (config.d_model, config.vocab_size, config.num_layers,
                  config.d_mlp)
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    rng = np.random.default_rng(seed)
    std = 0.02
    res_std = std / (2 * L) ** 0.5

    def norm(shape, scale):
        return rng.standard_normal(shape, dtype=np.float32) * scale

    blocks = {
        "attn_norm": np.ones((L, D), np.float32),
        "wq": norm((L, D, H * hd), std),
        "wk": norm((L, D, KV * hd), std),
        "wv": norm((L, D, KV * hd), std),
        "wo": norm((L, H * hd, D), res_std),
        "mlp_norm": np.ones((L, D), np.float32),
        "w_gate": norm((L, D, M), std),
        "w_up": norm((L, D, M), std),
        "w_down": norm((L, M, D), res_std),
    }
    if config.attn_bias:
        blocks.update({"wq_b": np.zeros((L, H * hd), np.float32),
                       "wk_b": np.zeros((L, KV * hd), np.float32),
                       "wv_b": np.zeros((L, KV * hd), np.float32),
                       "wo_b": np.zeros((L, D), np.float32)})
    return {
        "wte": norm((V, D), std),
        "blocks": blocks,
        "final_norm": np.ones((D,), np.float32),
        "lm_head": norm((D, V), std),
    }


def logical_specs(config: LlamaConfig) -> dict:
    blocks = {
        "attn_norm": P(),
        "wq": P(None, None, "model"),
        "wk": P(None, None, "model"),
        "wv": P(None, None, "model"),
        "wo": P(None, "model", None),
        "mlp_norm": P(),
        "w_gate": P(None, None, "model"),
        "w_up": P(None, None, "model"),
        "w_down": P(None, "model", None),
    }
    if config.attn_bias:
        blocks.update({"wq_b": P(None, "model"), "wk_b": P(None, "model"),
                       "wv_b": P(None, "model"), "wo_b": P()})
    return {
        "wte": P("model", None),
        "blocks": blocks,
        "final_norm": P(),
        "lm_head": P(None, "model"),
    }


def _rms_norm(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps) * scale).astype(x.dtype)


def rope(x, theta: float, positions=None, interleaved: bool = False):
    """Rotary embeddings on [B, S, H, hd].  ``interleaved=False`` pairs
    dim i with i+hd/2 (llama/NeoX split-half convention);
    ``interleaved=True`` pairs dims (2i, 2i+1) (the GPT-J rotate_every_two
    convention — same frequencies, different lane pairing, so converted
    checkpoints must match their family's layout).  ``positions``: [S]
    (shared across batch) or [B, S] (per-row, decode)."""
    B, S, H, hd = x.shape
    if positions is None:
        positions = jnp.arange(S)
    freqs = theta ** (-jnp.arange(0, hd // 2) / (hd // 2))
    if positions.ndim == 1:
        angles = positions[:, None] * freqs[None, :]     # [S, hd/2]
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:
        angles = positions[:, :, None] * freqs[None, None, :]   # [B, S, hd/2]
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    xf = x.astype(jnp.float32)
    if interleaved:
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        r1, r2 = x1 * cos - x2 * sin, x1 * sin + x2 * cos
        out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    else:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                              axis=-1)
    return out.astype(x.dtype)


def _block_qkv(x, layer, config: LlamaConfig, positions=None, lora=None):
    """RMSNorm + QKV + rotary; x [B, S, D] -> q [B,S,H,hd], k/v [B,S,KV,hd]
    (kv heads NOT repeated — the caller decides, so caches stay compact).
    ``lora(name, h)`` adds per-row adapter deltas on the projection
    outputs BEFORE rope — rope is a position-dependent linear map on the
    projected vectors, so this is where the offline merge lands too
    (ISSUE 20)."""
    from deepspeed_tpu.models.serving import lora_add
    B, S, D = x.shape
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    h = _rms_norm(x, layer["attn_norm"], config.rms_norm_eps)
    dt = h.dtype
    q = lora_add(qdot(h, layer["wq"]), lora, "wq", h)
    kk = lora_add(qdot(h, layer["wk"]), lora, "wk", h)
    v = lora_add(qdot(h, layer["wv"]), lora, "wv", h)
    if config.attn_bias:
        q = q + layer["wq_b"].astype(dt)
        kk = kk + layer["wk_b"].astype(dt)
        v = v + layer["wv_b"].astype(dt)
    q = q.reshape(B, S, H, hd)
    kk = kk.reshape(B, S, KV, hd)
    v = v.reshape(B, S, KV, hd)
    q = rope(q, config.rope_theta, positions)
    kk = rope(kk, config.rope_theta, positions)
    return q, kk, v


def _block_finish(x, attn, layer, config: LlamaConfig, lora=None):
    from deepspeed_tpu.models.serving import lora_add
    dt = x.dtype
    attn_out = lora_add(qdot(attn, layer["wo"]), lora, "wo", attn)
    if config.attn_bias:
        attn_out = attn_out + layer["wo_b"].astype(dt)
    x = x + attn_out
    h = _rms_norm(x, layer["mlp_norm"], config.rms_norm_eps)
    gated = jax.nn.silu(lora_add(qdot(h, layer["w_gate"]), lora,
                                 "w_gate", h)) \
        * lora_add(qdot(h, layer["w_up"]), lora, "w_up", h)
    x = x + lora_add(qdot(gated, layer["w_down"]), lora, "w_down", gated)
    return x


def _block(x, layer, config: LlamaConfig, rng=None, segment_ids=None):
    B, S, D = x.shape
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    q, kk, v = _block_qkv(x, layer, config)
    # kv heads stay compact: the attention dispatch attends GQA natively
    # (from-scratch flash kernel) or repeats in the fallback paths
    attn = causal_attention(q, kk, v, impl=config.attention_impl,
                            segment_ids=segment_ids)
    attn = jax.ad_checkpoint.checkpoint_name(attn, "attn_out")
    return _block_finish(x, attn.reshape(B, S, H * hd), layer, config)


def forward(params, batch, config: LlamaConfig, rng=None):
    tokens = batch["input_ids"]
    dtype = jnp.dtype(config.dtype)
    x = params["wte"].astype(dtype)[tokens]
    # stream-inside-remat (see models/model.py maybe_stream): param-offload
    # transfers happen inside the remat boundary
    seg = batch.get("segment_ids") if isinstance(batch, dict) else None

    def block_fn(x, layer):
        from deepspeed_tpu.models.model import maybe_stream
        return _block(x, maybe_stream(layer), config, rng, seg)
    if config.remat:
        from deepspeed_tpu.models.gpt2 import remat_policy
        block_fn = jax.checkpoint(
            block_fn, policy=remat_policy(config.remat_policy))

    # layer scan with random-LTD + progressive-layer-drop hooks (see
    # models/model.py scan_blocks); packed batches skip LTD (a token
    # subset would misalign the closed-over segment ids)
    from deepspeed_tpu.models.model import scan_blocks
    x = scan_blocks(block_fn, x, params["blocks"], rng, batch,
                    config.num_layers, allow_ltd=seg is None)
    x = _rms_norm(x, params["final_norm"], config.rms_norm_eps)
    return x @ params["lm_head"].astype(dtype)


# --------------------------------------------------------------------- decode
def _serving_fns(config: LlamaConfig):
    """KV-cache serving via the shared rotary-GQA scaffold
    (models/serving.py) — llama contributes its QKV projection and dense
    SwiGLU finish."""
    from deepspeed_tpu.models import serving

    def embed_fn(params, tokens):
        return params["wte"].astype(jnp.dtype(config.dtype))[tokens]

    def qkv_fn(x, layer, positions, lora=None):
        return _block_qkv(x, layer, config, positions, lora=lora)

    def finish_fn(x, attn_flat, layer, lora=None):
        return _block_finish(x, attn_flat, layer, config, lora=lora)

    def head_fn(params, x):
        return head(params, x, config)

    # fused per-layer megakernel wiring (ISSUE 12): RMSNorm + split QKV
    # + full rotary + GQA decode attention + SwiGLU in one Pallas call
    from deepspeed_tpu.ops.pallas.fused_decode import FusedLayerSpec
    fused_spec = FusedLayerSpec(
        num_heads=config.num_heads, num_kv_heads=config.num_kv_heads,
        head_dim=config.head_dim, d_model=config.d_model,
        norm="rms", eps=config.rms_norm_eps, qkv="split",
        qkv_bias=config.attn_bias, out_bias=config.attn_bias,
        mlp="swiglu", mlp_bias=False, rotary_dims=config.head_dim,
        rope_theta=config.rope_theta)

    def fused_weights(layer):
        cw = {"n1_s": layer["attn_norm"], "wq": layer["wq"],
              "wk": layer["wk"], "wv": layer["wv"], "wo": layer["wo"],
              "n2_s": layer["mlp_norm"], "w_gate": layer["w_gate"],
              "w_up": layer["w_up"], "w_down": layer["w_down"]}
        if config.attn_bias:
            cw.update(bq=layer["wq_b"], bk=layer["wk_b"],
                      bv=layer["wv_b"], bo=layer["wo_b"])
        return cw

    def init_cache_fn(bs, max_len, dtype=None):
        return serving.init_cache(config.num_layers, config.num_kv_heads,
                                  config.head_dim, bs, max_len, dtype,
                                  config.dtype)

    def prefill_fn(p, b, c, lora=None):
        return serving.prefill(
            p, b, c, embed_fn=embed_fn, qkv_fn=qkv_fn, finish_fn=finish_fn,
            head_fn=head_fn, num_heads=config.num_heads,
            num_kv_heads=config.num_kv_heads,
            attention_impl=config.attention_impl, lora=lora)

    def decode_fn(p, t, c, l, lora=None):
        return serving.decode_step(
            p, t, c, l, embed_fn=embed_fn, qkv_fn=qkv_fn,
            finish_fn=finish_fn, head_fn=head_fn,
            num_heads=config.num_heads,
            fused_spec=fused_spec, fused_weights_fn=fused_weights,
            lora=lora)

    def verify_fn(p, t, c, l, lora=None):
        return serving.verify_window(
            p, t, c, l, embed_fn=embed_fn, qkv_fn=qkv_fn,
            finish_fn=finish_fn, head_fn=head_fn,
            num_heads=config.num_heads,
            fused_spec=fused_spec, fused_weights_fn=fused_weights,
            lora=lora)

    return init_cache_fn, prefill_fn, decode_fn, verify_fn


def count_params(config: LlamaConfig) -> int:
    D, V, L, M = (config.d_model, config.vocab_size, config.num_layers,
                  config.d_mlp)
    H, KV, hd = config.num_heads, config.num_kv_heads, config.head_dim
    per_layer = 2 * D + D * H * hd + 2 * D * KV * hd + H * hd * D + 3 * D * M
    return V * D + L * per_layer + D + D * V


def embed(params, batch, config: LlamaConfig):
    dtype = jnp.dtype(config.dtype)
    return params["wte"].astype(dtype)[batch["input_ids"]]


def head(params, x, config: LlamaConfig):
    x = _rms_norm(x, params["final_norm"], config.rms_norm_eps)
    return qdot(x, params["lm_head"])


def llama_model(size: str = "7b", **overrides) -> Model:
    cfg_kwargs = resolve_size(LLAMA_SIZES, size, "llama")
    cfg_kwargs.update(overrides)
    config = LlamaConfig(**cfg_kwargs)
    n_params = count_params(config)
    return Model(
        config=config,
        init_fn=partial(init_params, config),
        numpy_init_fn=partial(numpy_init_params, config),
        apply_fn=lambda p, b, rng=None: forward(p, b, config, rng),
        logical_specs=logical_specs(config),
        flops_per_token=6.0 * n_params,
        meta={"name": f"llama-{size}", "n_params": n_params,
              "supports_random_ltd": True, "supports_pld": True,
              "lora_serving": True,
              # wte grads come solely from input_ids lookups (untied
              # lm_head): eligible for the sparse_gradients exchange
              "sparse_grad_params": {"wte": "input_ids"}},
        embed_fn=lambda p, b: embed(p, b, config),
        block_fn=lambda lp, x: _block(x, lp, config),
        head_fn=lambda p, x: head(p, x, config),
        **dict(zip(("init_cache_fn", "prefill_fn", "decode_fn",
                    "verify_fn"),
                   _serving_fns(config))),
    )
