"""Roofline attribution over the cost model (ISSUE 13 tentpole).

``mfu.py`` answers "what fraction of peak FLOPs did we achieve";
this module answers the decode-regime question PERF.md has been
answering by hand: **what is the hardware floor for this program, and
how far above it are we running**.  A per-device HBM-bandwidth table
(same shape as ``PEAK_FLOPS_BY_KIND``) prices a program's
:class:`~deepspeed_tpu.telemetry.costmodel.CostReport` into

- ``floor_ms`` — ``max(flops/peak, hbm_bytes/bandwidth)`` per
  execution, the roofline lower bound;
- a compute-bound vs bandwidth-bound classification (which term won);
- ``achieved_vs_floor`` — measured wall clock over the floor, the
  "4-5x-over-floor" gap as a live gauge instead of a PERF.md table.

The comm observatory (ISSUE 19) adds the third roofline axis: an
interconnect (ICI) bandwidth table prices each program's per-axis
collective WIRE bytes into a comm floor beside the FLOP and HBM
floors, steps classify ``comm_bound`` when that term wins, and
``comm/achieved_vs_floor`` tracks the live gap.  ``DS_ICI_GBPS`` /
``DS_DCN_GBPS`` override the declared interconnect rates.

On CPU neither table resolves and every floor-dependent output is None
— **no fictitious floors**.  ``DS_HBM_GBPS`` overrides per device
(it is also how CPU tier-1 tests exercise the floor math).  Gauges
land in the shared metrics registry under ``perf/*`` labeled by
program, on both /metrics surfaces.
"""
import os
from typing import Any, Dict, Optional

from deepspeed_tpu.telemetry import costmodel as _cm
from deepspeed_tpu.telemetry.mfu import peak_flops_per_device

HBM_GBPS_ENV = "DS_HBM_GBPS"
ICI_GBPS_ENV = "DS_ICI_GBPS"
DCN_GBPS_ENV = "DS_DCN_GBPS"

#: HBM bandwidth per chip (GB/s) by device-kind substring (lowercase).
#: Sources: published TPU system specs (per-chip).
HBM_GBPS_BY_KIND = {
    "v5p": 2765.0,
    "v5e": 819.0,
    "v5litepod": 819.0,
    "v4": 1228.0,
    "v3": 900.0,
    "v2": 700.0,
}

#: inter-chip interconnect (ICI) bandwidth per chip (GB/s) by
#: device-kind substring.  Sources: published TPU system specs —
#: aggregate per-chip ICI link bandwidth (v2 496 Gbps, v3 656 Gbps,
#: v4 2400 Gbps, v5e 1600 Gbps, v5p 4800 Gbps), /8 to GB/s.
ICI_GBPS_BY_KIND = {
    "v5p": 600.0,
    "v5e": 200.0,
    "v5litepod": 200.0,
    "v4": 300.0,
    "v3": 82.0,
    "v2": 62.0,
}


def hbm_bytes_per_s(device=None, env: Optional[dict] = None
                    ) -> Optional[float]:
    """HBM bandwidth for one device in bytes/s: ``DS_HBM_GBPS`` env
    wins, then the device-kind table; None when unknown (CPU, exotic
    parts) — callers must skip floor math rather than report against a
    made-up bandwidth."""
    env = os.environ if env is None else env
    override = env.get(HBM_GBPS_ENV, "").strip()
    if override:
        return float(override) * 1e9
    if device is None:
        import jax
        device = jax.local_devices()[0]
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, gbps in HBM_GBPS_BY_KIND.items():
        if sub in kind:
            return gbps * 1e9
    return None


def ici_bytes_per_s(device=None, env: Optional[dict] = None
                    ) -> Optional[float]:
    """Inter-chip interconnect bandwidth for one device in bytes/s:
    ``DS_ICI_GBPS`` env wins, then the device-kind table; None when
    unknown (CPU, single-chip hosts) — a comm floor against a made-up
    link rate is worse than no floor."""
    env = os.environ if env is None else env
    override = env.get(ICI_GBPS_ENV, "").strip()
    if override:
        return float(override) * 1e9
    if device is None:
        import jax
        device = jax.local_devices()[0]
    kind = str(getattr(device, "device_kind", "")).lower()
    for sub, gbps in ICI_GBPS_BY_KIND.items():
        if sub in kind:
            return gbps * 1e9
    return None


def dcn_bytes_per_s(env: Optional[dict] = None) -> Optional[float]:
    """Data-center-network bandwidth in bytes/s — declaration-only
    (``DS_DCN_GBPS``): the DCN fabric between hosts has no device-kind
    table, so without an explicit declaration there is no rate."""
    env = os.environ if env is None else env
    override = env.get(DCN_GBPS_ENV, "").strip()
    if override:
        return float(override) * 1e9
    return None


def _comm_wire_bytes(report) -> int:
    """A program's per-execution interconnect wire bytes: the per-axis
    ring-accounted total when the costmodel attributed collectives,
    else the raw operand-byte aggregate as an upper bound."""
    wire = 0
    fn = getattr(report, "comm_wire_bytes", None)
    if callable(fn):
        wire = int(fn())
    if wire <= 0:
        wire = int(getattr(report, "collective_bytes", 0))
    return wire


def floor_seconds(report, peak_flops: Optional[float] = None,
                  hbm_bps: Optional[float] = None,
                  ici_bps: Optional[float] = None) -> Optional[float]:
    """Roofline lower bound for one execution: the slowest of the
    compute, HBM, and interconnect terms, over the terms whose
    hardware rate is known.  None when no rate resolves."""
    terms = []
    if peak_flops and peak_flops > 0 and report.flops > 0:
        terms.append(report.flops / peak_flops)
    if hbm_bps and hbm_bps > 0 and report.hbm_bytes > 0:
        terms.append(report.hbm_bytes / hbm_bps)
    wire = _comm_wire_bytes(report)
    if ici_bps and ici_bps > 0 and wire > 0:
        terms.append(wire / ici_bps)
    if not terms:
        return None
    return max(terms)


def comm_floor_seconds(report, ici_bps: Optional[float]
                       ) -> Optional[float]:
    """The interconnect term alone: wire bytes over the declared link
    rate; None without a rate or without comm bytes."""
    wire = _comm_wire_bytes(report)
    if not (ici_bps and ici_bps > 0 and wire > 0):
        return None
    return wire / ici_bps


def classify(report, peak_flops: Optional[float] = None,
             hbm_bps: Optional[float] = None,
             ici_bps: Optional[float] = None) -> Optional[str]:
    """"compute_bound" / "bandwidth_bound" / "comm_bound" by which
    roofline term dominates; None when the comparison needs a rate we
    don't have.  The comm term only competes when an interconnect rate
    is declared/known AND the program moves collective bytes."""
    if not (peak_flops and hbm_bps and report.flops > 0
            and report.hbm_bytes > 0):
        return None
    compute_s = report.flops / peak_flops
    memory_s = report.hbm_bytes / hbm_bps
    comm_s = comm_floor_seconds(report, ici_bps)
    if comm_s is not None and comm_s > max(compute_s, memory_s):
        return "comm_bound"
    return "compute_bound" if compute_s >= memory_s else "bandwidth_bound"


#: (DS_HBM_GBPS, DS_PEAK_FLOPS) env values -> resolved rates; the
#: device kind is constant per process, so rates only change when the
#: env overrides do — observe_achieved runs per decode step and must
#: not pay jax.local_devices + table walks every time
_RATES_CACHE: Dict[tuple, Dict[str, Optional[float]]] = {}


def device_rates(env: Optional[dict] = None) -> Dict[str, Optional[float]]:
    """(peak_flops, hbm_bps) for the first local device, None-safe on
    any backend (one place resolves both tables + envs).  Cached per
    (env-override) pair; pass an explicit ``env`` dict to bypass the
    cache (tests)."""
    from deepspeed_tpu.telemetry.mfu import PEAK_FLOPS_ENV
    cache_key = None
    if env is None:
        cache_key = (os.environ.get(HBM_GBPS_ENV, ""),
                     os.environ.get(PEAK_FLOPS_ENV, ""),
                     os.environ.get(ICI_GBPS_ENV, ""),
                     os.environ.get(DCN_GBPS_ENV, ""))
        hit = _RATES_CACHE.get(cache_key)
        if hit is not None:
            return hit
    try:
        import jax
        dev = jax.local_devices()[0]
    except Exception:
        dev = None
    try:
        peak = peak_flops_per_device(dev, env=env) if dev is not None \
            else None
    except Exception:
        peak = None
    try:
        bw = hbm_bytes_per_s(dev, env=env) if dev is not None else None
    except Exception:
        bw = None
    try:
        ici = ici_bytes_per_s(dev, env=env) if dev is not None else None
    except Exception:
        ici = None
    rates = {"peak_flops": peak, "hbm_bytes_per_s": bw,
             "ici_bytes_per_s": ici,
             "dcn_bytes_per_s": dcn_bytes_per_s(env=env),
             "device_kind": str(getattr(dev, "device_kind", "unknown"))}
    if cache_key is not None:
        _RATES_CACHE[cache_key] = rates
    return rates


def publish_report(registry, report):
    """Static cost gauges for one program family, labeled by program —
    rendered identically by ds_serve /metrics and the training
    endpoint.  Floor gauges appear only when a hardware rate resolves
    (no fictitious floors on CPU)."""
    _cm.register_report(report)
    name = report.name
    registry.set_gauge("perf/flops", float(report.flops), program=name)
    registry.set_gauge("perf/hbm_bytes", float(report.hbm_bytes),
                       program=name)
    registry.set_gauge("perf/pallas_launches",
                       float(report.pallas_launches), program=name)
    registry.set_gauge("perf/collective_bytes",
                       float(report.collective_bytes), program=name)
    wire = _comm_wire_bytes(report)
    if wire > 0:
        registry.set_gauge("comm/wire_bytes", float(wire), program=name)
    rates = device_rates()
    floor = floor_seconds(report, rates["peak_flops"],
                          rates["hbm_bytes_per_s"],
                          rates["ici_bytes_per_s"])
    if floor is not None:
        registry.set_gauge("perf/floor_ms", floor * 1e3, program=name)
    comm_floor = comm_floor_seconds(report, rates["ici_bytes_per_s"])
    if comm_floor is not None:
        registry.set_gauge("comm/floor_ms", comm_floor * 1e3,
                           program=name)


def observe_achieved(registry, name: str, duration_s: float):
    """One measured execution of a registered program: updates the
    lock-free achieved table and the ``perf/achieved_ms`` gauge, and —
    when the program's floor resolves — the ``perf/achieved_vs_floor``
    ratio (the live "N-x-over-floor" gap).  Programs whose comm floor
    resolves (wire bytes AND a declared/known interconnect rate — never
    fictitious on CPU) additionally publish ``comm/achieved_vs_floor``,
    the collapsing-link gauge."""
    _cm.record_achieved(name, duration_s)
    registry.set_gauge("perf/achieved_ms", duration_s * 1e3, program=name)
    report = _cm.get_report(name)
    if report is None:
        return
    rates = device_rates()
    floor = floor_seconds(report, rates["peak_flops"],
                          rates["hbm_bytes_per_s"],
                          rates["ici_bytes_per_s"])
    if floor and floor > 0:
        registry.set_gauge("perf/achieved_vs_floor",
                           duration_s / floor, program=name)
    comm_floor = comm_floor_seconds(report, rates["ici_bytes_per_s"])
    if comm_floor and comm_floor > 0:
        registry.set_gauge("comm/achieved_vs_floor",
                           duration_s / comm_floor, program=name)


def perf_table(env: Optional[dict] = None) -> Dict[str, Any]:
    """The ``/debug/perf`` body and the post-mortem ``perf.json``
    payload: device rates + one row per registered program (static
    cost, floor, classification, live achieved stats).  Lock-free with
    respect to every subsystem it reports on — safe to hit while a
    step is wedged."""
    rates = device_rates(env=env)
    peak, bw = rates["peak_flops"], rates["hbm_bytes_per_s"]
    ici = rates["ici_bytes_per_s"]
    achieved = _cm.get_achieved()
    programs = {}
    for name, report in sorted(_cm.get_reports().items()):
        row = report.to_dict()
        floor = floor_seconds(report, peak, bw, ici)
        row["floor_ms"] = None if floor is None else round(floor * 1e3, 6)
        row["bound"] = classify(report, peak, bw, ici)
        comm_floor = comm_floor_seconds(report, ici)
        row["comm_floor_ms"] = None if comm_floor is None else round(
            comm_floor * 1e3, 6)
        a = achieved.get(name)
        if a is not None:
            last_ms, count, total_ms = a
            row["achieved_ms"] = round(last_ms, 6)
            row["achieved_count"] = count
            # the first sample (compile + analysis trace) is excluded
            # from the total — the mean is over warm executions
            row["achieved_mean_ms"] = round(
                total_ms / (count - 1) if count > 1 else last_ms, 6)
            if floor and floor > 0:
                row["achieved_vs_floor"] = round(
                    (last_ms / 1e3) / floor, 4)
            if comm_floor and comm_floor > 0:
                row["comm_achieved_vs_floor"] = round(
                    (last_ms / 1e3) / comm_floor, 4)
        programs[name] = row
    return {
        "device_kind": rates["device_kind"],
        "peak_flops": peak,
        "hbm_gbps": None if bw is None else bw / 1e9,
        "ici_gbps": None if ici is None else ici / 1e9,
        "dcn_gbps": (None if rates["dcn_bytes_per_s"] is None
                     else rates["dcn_bytes_per_s"] / 1e9),
        "programs": programs,
    }
