"""Multi-tenant LoRA adapter serving (ISSUE 20).

- :class:`AdapterRegistry` — load/validate adapter weight trees keyed by
  ``adapter_id`` (rank/target manifest, crc-stamped).
- :class:`AdapterStore` — paged HBM residency: ref-counted slot stacks
  feeding the batched gather-LoRA pass, LRU demotion of refcount-0
  adapters through the SwapEngine to host RAM/NVMe.
- ``adapters_enabled`` — the ``serving.adapters.enabled`` /
  ``DS_ADAPTERS`` env-wins resolution.
"""
from deepspeed_tpu.serving.adapters.registry import (AdapterManifest,
                                                     AdapterRegistry,
                                                     load_adapter_file,
                                                     save_adapter)
from deepspeed_tpu.serving.adapters.store import (ADAPTERS_ENV,
                                                  AdapterStore,
                                                  adapters_enabled)

__all__ = ["AdapterManifest", "AdapterRegistry", "AdapterStore",
           "ADAPTERS_ENV", "adapters_enabled", "load_adapter_file",
           "save_adapter"]
