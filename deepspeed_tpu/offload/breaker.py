"""Per-tier circuit breaker for the offload substrate (ISSUE 18).

ZeRO-Infinity treats NVMe as fallible media; a drive that starts
failing every request must not turn each swap into a retry storm that
stalls the train/serve loop.  :class:`TierBreaker` is the classic
three-state machine over a rolling window of terminal I/O outcomes
(retries already happened — only post-retry verdicts feed it):

- **CLOSED** — healthy; every op admitted, outcomes recorded.
- **OPEN** — the rolling error rate crossed ``error_rate`` over at
  least ``min_ops`` outcomes: ops are refused (``allow()`` is False)
  so clients degrade *by policy* — NVMe demotions stop (host-only /
  evict waterfall), reads fail fast to the per-client degrade path
  (KV → re-prefill, params → master rebuild) — instead of timing out
  one at a time.  After ``cooldown_s`` the breaker moves to HALF_OPEN.
- **HALF_OPEN** — up to ``probes`` REAL ops are admitted; the first
  recorded failure reopens (fresh cooldown), ``probes`` consecutive
  successes close and reset the window.

Every transition sets the ``offload/breaker_state`` gauge (0=closed,
1=half_open, 2=open, labeled by tier) and records an
``offload/breaker`` flight event, so the CLOSED→OPEN→HALF_OPEN→CLOSED
lifecycle is observable end-to-end (``/debug/offload`` serves the live
snapshot; post-mortem bundles embed it).

Single-threaded by contract, like the SwapEngine that owns it.  The
clock is injectable for deterministic cooldown tests.
"""
import collections
import time
from typing import Callable, Optional

__all__ = ["TierBreaker", "STATE_CLOSED", "STATE_HALF_OPEN", "STATE_OPEN"]

STATE_CLOSED = "closed"
STATE_HALF_OPEN = "half_open"
STATE_OPEN = "open"

#: gauge encoding (docs/reference/registries.md): healthy sorts lowest
_STATE_GAUGE = {STATE_CLOSED: 0.0, STATE_HALF_OPEN: 1.0, STATE_OPEN: 2.0}


class TierBreaker:
    """Rolling error-rate circuit breaker for one storage tier."""

    def __init__(self, tier: str = "nvme", window: int = 16,
                 error_rate: float = 0.5, min_ops: int = 4,
                 cooldown_s: float = 30.0, probes: int = 1,
                 _now: Callable[[], float] = time.monotonic):
        self.tier = tier
        self.window = max(1, int(window))
        self.error_rate = float(error_rate)
        self.min_ops = max(1, int(min_ops))
        self.cooldown_s = float(cooldown_s)
        self.probes = max(1, int(probes))
        self._now = _now
        self.state = STATE_CLOSED
        self._outcomes = collections.deque(maxlen=self.window)  # True = ok
        self._opened_at: Optional[float] = None
        self._probes_admitted = 0
        self._probe_successes = 0
        # monotonic lifecycle counters (debug/postmortem snapshots)
        self.opens = 0
        self.closes = 0
        self.refused = 0
        self._publish_gauge()

    def _publish_gauge(self):
        """A breaker that never trips must still be scrapeable: publish
        the state gauge at construction, not only on transitions."""
        try:
            from deepspeed_tpu.telemetry import get_registry
            get_registry().set_gauge("offload/breaker_state",
                                     _STATE_GAUGE[self.state],
                                     tier=self.tier)
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"breaker gauge publish failed ({e})")

    # ------------------------------------------------------------ plumbing
    def _transition(self, new: str):
        if new == self.state:
            return
        old, self.state = self.state, new
        if new == STATE_OPEN:
            self.opens += 1
            self._opened_at = self._now()
        elif new == STATE_CLOSED:
            self.closes += 1
            self._outcomes.clear()
        if new != STATE_HALF_OPEN:
            self._probes_admitted = 0
            self._probe_successes = 0
        self._publish_gauge()
        try:
            from deepspeed_tpu.telemetry.flight_recorder import \
                get_flight_recorder
            get_flight_recorder().record("offload/breaker", tier=self.tier,
                                         **{"from": old, "to": new})
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"breaker telemetry failed ({e}); state machine "
                         "continues unobserved")

    def _error_fraction(self) -> float:
        if not self._outcomes:
            return 0.0
        return self._outcomes.count(False) / len(self._outcomes)

    # ------------------------------------------------------------- surface
    def allow(self) -> bool:
        """Gate one tier op.  CLOSED admits; OPEN refuses until the
        cooldown elapses (then flips to HALF_OPEN); HALF_OPEN admits up
        to ``probes`` in-flight probe ops — the probes ARE real traffic,
        their outcomes decide the next state."""
        if self.state == STATE_OPEN:
            if (self._opened_at is not None
                    and self._now() - self._opened_at >= self.cooldown_s):
                self._transition(STATE_HALF_OPEN)
            else:
                self.refused += 1
                return False
        if self.state == STATE_HALF_OPEN:
            if self._probes_admitted >= self.probes:
                self.refused += 1
                return False
            self._probes_admitted += 1
        return True

    def record(self, ok: bool):
        """Feed one TERMINAL op outcome (post-retry verdict)."""
        if self.state == STATE_HALF_OPEN:
            if not ok:
                self._transition(STATE_OPEN)
                return
            self._probe_successes += 1
            if self._probe_successes >= self.probes:
                self._transition(STATE_CLOSED)
            return
        self._outcomes.append(ok)
        if (self.state == STATE_CLOSED
                and len(self._outcomes) >= self.min_ops
                and self._error_fraction() >= self.error_rate):
            self._transition(STATE_OPEN)

    def snapshot(self) -> dict:
        """Live state for ``/debug/offload`` and post-mortem bundles."""
        return {"tier": self.tier, "state": self.state,
                "window": self.window, "error_rate": self.error_rate,
                "recent_error_fraction": round(self._error_fraction(), 4),
                "recent_ops": len(self._outcomes),
                "opens": self.opens, "closes": self.closes,
                "refused": self.refused,
                "cooldown_s": self.cooldown_s,
                "probes": self.probes,
                "probes_admitted": self._probes_admitted,
                "open_for_s": (round(self._now() - self._opened_at, 3)
                               if self.state == STATE_OPEN
                               and self._opened_at is not None else None)}
