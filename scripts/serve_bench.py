"""Serving benchmark: steady-state decode tokens/s through the
InferenceEngine (KV cache + Pallas decode kernel).

On-chip queue item (PERF.md): MoE int8-KV serving rate, plus rates for
the new serving families (NeoX/GPT-J/BLOOM/GPT-Neo).

    python scripts/serve_bench.py                          # gpt2 125m
    SERVE_MODEL=mixtral:1b-moe SERVE_KV=int8 python scripts/serve_bench.py
    SERVE_MODEL=bloom:560m SERVE_B=8 python scripts/serve_bench.py

Prints one JSON line: prefill ms + steady decode tokens/s.
Off-TPU this still runs (tiny default shapes) as a plumbing smoke.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax


def main():
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    spec = os.environ.get("SERVE_MODEL",
                          "gpt2:125m" if on_tpu else "gpt2:custom")
    B = int(os.environ.get("SERVE_B", 4))
    prompt_len = int(os.environ.get("SERVE_PROMPT", 128 if on_tpu else 8))
    new_tokens = int(os.environ.get("SERVE_TOKENS", 256 if on_tpu else 8))
    kv_dtype = os.environ.get("SERVE_KV") or None
    quant = bool(int(os.environ.get("SERVE_INT8_WEIGHTS", "0")))

    from deepspeed_tpu import models as M

    def _opt_model(size, **kw):
        # OPT serves through the gpt2-family scaffold (pre-LN + ReLU —
        # what opt_from_hf converts onto); this is the native-arch
        # equivalent for rate measurement
        return M.gpt2_model(size, activation="relu", **kw)

    def _internlm_model(size, **kw):
        # InternLM = llama block + biased q/k/v/o (llama_from_hf alias);
        # "1b" picks InternLM-1.8B-like dims (no in-tree llama preset
        # at this scale)
        if size in ("1b", ""):
            kw = dict(num_layers=16, num_heads=16, num_kv_heads=16,
                      d_model=2048, d_mlp=5504, vocab_size=50000, **kw)
            size = "custom"
        return M.llama_model(size, attn_bias=True, **kw)

    arch, _, size = spec.partition(":")
    registry = {"gpt2": M.gpt2_model, "llama": M.llama_model,
                "mixtral": M.mixtral_model, "neox": M.neox_model,
                "bloom": M.bloom_model, "gptneo": M.gptneo_model,
                "opt": _opt_model, "megatron": M.gpt2_model,
                "internlm": _internlm_model}
    if on_tpu:
        kwargs = {}
    elif arch in ("llama", "mixtral", "internlm"):
        # these archs have their own tiny presets with consistent
        # kv-heads/ffn dims — the generic tiny kwargs would not apply
        size = size or "tiny"
        kwargs = {}
    else:
        kwargs = dict(vocab_size=256, num_layers=2, num_heads=4,
                      d_model=32)
    model = registry[arch](size or "custom", dtype="bfloat16" if on_tpu
                           else "float32",
                           max_seq_len=max(2048 if on_tpu else 64,
                                           prompt_len + new_tokens),
                           **kwargs)

    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig
    from deepspeed_tpu.inference.engine import InferenceEngine
    cfg = DeepSpeedInferenceConfig(
        dtype="bfloat16" if on_tpu else "float32",
        quant={"enabled": quant},
        kv_cache_dtype=kv_dtype)
    params = None
    n_params = model.meta.get("n_params", 0)
    if quant and n_params * 2 > 8e9 and model.numpy_init_fn is not None:
        # int8 serving of models beyond HBM at full precision (the MoQ
        # big-model path): init on HOST, quantize leaf-by-leaf on device
        # — device-side init would materialize the full bf16 tree first
        print(f"# host-init {n_params/1e9:.1f}B params for int8 serving",
              file=sys.stderr)
        params = model.numpy_init_fn(seed=0)
    eng = InferenceEngine(model, cfg, model_parameters=params)

    rng = np.random.default_rng(0)
    prompts = rng.integers(1, model.config.vocab_size,
                           (B, prompt_len)).astype(np.int32)
    # decode rate = SLOPE between two generate lengths (min over repeats):
    # a one-shot (full - prefill) difference carries the axon tunnel's
    # ~100 ms fixed round-trip jitter twice and swings +-20% run to run;
    # the slope between two lengths measured min-of-3 cancels prefill and
    # every fixed cost
    small = max(1, new_tokens // 4)

    def timed(n, reps=3):
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            np.asarray(eng.generate(prompts, max_new_tokens=n,
                                    do_sample=False))
            best = min(best, time.time() - t0)
        return best

    # warmup/compile all program shapes
    np.asarray(eng.generate(prompts, max_new_tokens=1, do_sample=False))
    np.asarray(eng.generate(prompts, max_new_tokens=small, do_sample=False))
    np.asarray(eng.generate(prompts, max_new_tokens=new_tokens,
                            do_sample=False))
    t_prefill = timed(1)
    t_small = timed(small)
    t_full = timed(new_tokens)
    decode_s = t_full - t_small
    toks = B * (new_tokens - small)
    if decode_s <= 0:
        # timing noise swamped the marginal decode time (tiny smoke
        # shapes) — emit null rather than a garbage rate
        rate = None
    else:
        rate = round(toks / decode_s, 1)
    print(json.dumps({
        "metric": f"{spec}_serve"
                  + ("_int8kv" if kv_dtype == "int8" else "")
                  + ("_int8w" if quant else ""),
        "value": rate,
        "unit": "decode_tokens_per_sec",
        "detail": {"batch": B, "prompt_len": prompt_len,
                   "new_tokens": new_tokens,
                   "prefill_ms": round(t_prefill * 1e3, 2),
                   "total_s": round(t_full, 3)},
    }))


if __name__ == "__main__":
    main()
