"""Block-sparse attention (reference: deepspeed/ops/sparse_attention/ —
``SparsityConfig`` hierarchy in sparsity_config.py, ``SparseSelfAttention``,
Triton block matmul/softmax kernels).

The layouts (fixed / bigbird / bslongformer / variable) are faithful
reimplementations of the reference's mask construction.  Two compute
paths, selected by ``impl``:

* ``dense`` — block-masked dense attention: the [S, S] score tile is
  MXU-friendly and XLA folds the block mask into the softmax fusion; the
  right trade below ~16k tokens.
* ``pallas`` — the from-scratch block-skipping kernel
  (ops/pallas/block_sparse_attention.py): masked blocks are never DMA'd or
  multiplied, so cost scales with layout density — the long-sequence path.
"""
import random
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

NEG_INF = -1e30


class SparsityConfig:
    """Base layout builder (reference sparsity_config.py:22)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(
                f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=np.int64)

    def check_and_propagate_first_head_layout(self, layout: np.ndarray):
        if not self.different_layout_per_head:
            layout[1:] = layout[0]
        return layout

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError


class DenseSparsityConfig(SparsityConfig):
    """All blocks attended — dense baseline (reference :105)."""

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = 1
        return layout


class FixedSparsityConfig(SparsityConfig):
    """Local windows + fixed global columns (reference :135
    FixedSparsityConfig: num_local_blocks window, num_global_blocks summary
    columns chosen from each window's tail)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        for h in range(self.num_heads if self.different_layout_per_head
                       else 1):
            # local windows
            for start in range(0, n, self.num_local_blocks):
                end = min(start + self.num_local_blocks, n)
                layout[h, start:end, start:end] = 1
            # global columns: last num_global_blocks of each window
            for start in range(0, n, self.num_local_blocks):
                end = min(start + self.num_local_blocks, n)
                g0 = max(end - self.num_global_blocks, start)
                layout[h, :, g0:end] = 1
                if self.horizontal_global_attention:
                    layout[h, g0:end, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BigBirdSparsityConfig(SparsityConfig):
    """Random + sliding window + global blocks (reference :375)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1, num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1, attention: str = "bidirectional",
                 seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = np.random.default_rng(self.seed)
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_heads if self.different_layout_per_head
                       else 1):
            for i in range(n):
                lo, hi = max(0, i - w), min(n, i + w + 1)
                layout[h, i, lo:hi] = 1                       # sliding window
                choices = rng.choice(n, size=min(self.num_random_blocks, n),
                                     replace=False)
                layout[h, i, choices] = 1                     # random blocks
            g = min(self.num_global_blocks, n)
            layout[h, :g, :] = 1                              # global rows
            layout[h, :, :g] = 1                              # global cols
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class BSLongformerSparsityConfig(SparsityConfig):
    """Sliding window + selected global-attention block indices (reference
    :558)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for h in range(self.num_heads if self.different_layout_per_head
                       else 1):
            for i in range(n):
                lo, hi = max(0, i - w), min(n, i + w + 1)
                layout[h, i, lo:hi] = 1
            if self.global_block_end_indices is None:
                for idx in self.global_block_indices:
                    if idx < n:
                        layout[h, idx, :] = 1
                        layout[h, :, idx] = 1
            else:
                for s, e in zip(self.global_block_indices,
                                self.global_block_end_indices):
                    layout[h, s:e, :] = 1
                    layout[h, :, s:e] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


class VariableSparsityConfig(SparsityConfig):
    """Variable local window sizes + global blocks (reference :232)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 global_block_end_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False, seed: int = 0):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.global_block_end_indices = global_block_end_indices
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.seed = seed

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        rng = np.random.default_rng(self.seed)
        for h in range(self.num_heads if self.different_layout_per_head
                       else 1):
            start = 0
            wi = 0
            while start < n:
                w = self.local_window_blocks[
                    min(wi, len(self.local_window_blocks) - 1)]
                end = min(start + w, n)
                layout[h, start:end, start:end] = 1
                start = end
                wi += 1
            if self.num_random_blocks:
                for i in range(n):
                    choices = rng.choice(
                        n, size=min(self.num_random_blocks, n),
                        replace=False)
                    layout[h, i, choices] = 1
            if self.global_block_end_indices is None:
                for idx in self.global_block_indices:
                    if idx < n:
                        layout[h, :, idx] = 1
                        if self.horizontal_global_attention:
                            layout[h, idx, :] = 1
            else:
                for s, e in zip(self.global_block_indices,
                                self.global_block_end_indices):
                    layout[h, :, s:e] = 1
                    if self.horizontal_global_attention:
                        layout[h, s:e, :] = 1
        if self.attention == "unidirectional":
            layout = np.tril(layout)
        return self.check_and_propagate_first_head_layout(layout)


# ------------------------------------------------------------------- compute

def layout_to_mask(layout: np.ndarray, seq_len: int) -> jnp.ndarray:
    """[H, n, n] block layout -> [H, S, S] boolean attention mask."""
    block = seq_len // layout.shape[1]
    mask = np.repeat(np.repeat(layout, block, axis=1), block, axis=2)
    return jnp.asarray(mask.astype(bool))


def sparse_self_attention(q, k, v, sparsity_config: SparsityConfig,
                          causal: bool = False, sm_scale=None,
                          impl: str = "dense"):
    """q/k/v [B, S, H, hd] -> [B, S, H, hd] under the config's block layout
    (reference SparseSelfAttention.forward).

    ``impl="pallas"`` routes to the block-skipping Pallas kernels
    (ops/pallas/block_sparse_attention.py, fused forward AND backward):
    identical numerics and gradients, compute and HBM traffic scale with
    layout density instead of S² — the long-sequence path.  ``dense``
    keeps the block-masked XLA softmax fusion (the right trade below ~16k
    tokens)."""
    B, S, H, hd = q.shape
    scale = sm_scale if sm_scale is not None else hd ** -0.5
    layout = sparsity_config.make_layout(S)
    if impl == "pallas":
        from deepspeed_tpu.ops.pallas.block_sparse_attention import (
            block_sparse_attention_trainable)
        return block_sparse_attention_trainable(q, k, v, layout,
                                                causal=causal,
                                                sm_scale=sm_scale)
    mask = layout_to_mask(layout, S)                     # [H, S, S]
    if causal:
        mask = jnp.logical_and(mask, jnp.tril(jnp.ones((S, S), bool)))
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    s = jnp.where(mask[None], s, NEG_INF)
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    # fully-masked rows emit 0 (flash convention, shared with the Pallas
    # block-skipping kernel) — a uniform softmax over -1e30 scores would
    # leak masked V into the output
    row_any = mask.any(-1)                               # [H, S] (mask is
    out = jnp.where(row_any.T[None, :, :, None], out, 0.0)  # already causal)
    return out.astype(q.dtype)


class SparseSelfAttention:
    """Module shim mirroring the reference class."""

    def __init__(self, sparsity_config: SparsityConfig,
                 attn_mask_mode: str = "mul", impl: str = "dense"):
        self.sparsity_config = sparsity_config
        self.impl = impl

    def __call__(self, query, key, value, causal=False):
        return sparse_self_attention(query, key, value,
                                     self.sparsity_config, causal=causal,
                                     impl=self.impl)
