"""Draft proposers for speculative decoding (ISSUE 5).

A :class:`Proposer` supplies up to ``k`` draft tokens for a request's
next positions; the scheduler verifies the whole draft against the
target model in one weight pass and rolls rejected suffixes back through
the paged block tables.  Proposals must be DETERMINISTIC functions of
the request's token history: the verifier's rejection sampling treats
the proposal distribution as a point mass, which is exact only for
deterministic drafts (greedy draft-model decoding, n-gram lookup).

:class:`NgramProposer` is prompt-lookup decoding (Saxena, 2023): match
the last n-gram of the request's own prompt+output history against an
earlier occurrence and draft its continuation.  No second model, no
extra memory, pure host numpy — it wins on workloads whose outputs echo
their inputs (extraction, code edits, long-document QA) and on the
repetition loops greedy decoding falls into.  The draft-model proposer
lives in ``serving/spec/draft.py`` (it carries its own paged KV pool).
"""
from typing import Optional

import numpy as np


class Proposer:
    """Interface: the scheduler calls ``propose`` each iteration for
    each spec-eligible request and ``release`` when a request leaves the
    engine (finished, rejected, or evicted — eviction frees any
    per-request proposer state; the request may resume later and the
    proposer rebuilds from its token history)."""

    name = "base"

    def propose(self, req, k: int) -> np.ndarray:
        """Up to ``k`` drafted token ids (int32 [<=k]; empty = no
        proposal this round) continuing ``req.all_token_ids``."""
        raise NotImplementedError

    def release(self, request_id: int):
        """Drop any per-request state (no-op by default)."""


class NgramProposer(Proposer):
    """Prompt-lookup drafting: longest-suffix n-gram match over the
    request's own token history, most recent occurrence wins (recency
    tracks the repetition loops and local echo structure that make
    self-lookup profitable)."""

    name = "ngram"

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError(f"ngram sizes min={ngram_min} "
                             f"max={ngram_max}: need 1 <= min <= max")
        self.ngram_max = ngram_max
        self.ngram_min = ngram_min

    def _find(self, ctx: np.ndarray, n: int, k: int) -> Optional[np.ndarray]:
        L = ctx.size
        if L < n + 1:
            return None
        pat = ctx[-n:]
        # candidate starts 0..L-n-1: every length-n window EXCEPT the
        # suffix itself; a hit at i drafts the continuation ctx[i+n:]
        view = np.lib.stride_tricks.sliding_window_view(ctx, n)[:L - n]
        hits = np.nonzero((view == pat[None, :]).all(axis=1))[0]
        if hits.size == 0:
            return None
        # prefer the most RECENT hit that still has k continuation
        # tokens before the suffix; otherwise the EARLIEST hit (longest
        # continuation) — in a period-p repetition the latest hit sits
        # one period back and would draft only the run's tail otherwise
        full = hits[hits + n + k <= L]
        i = int(full[-1]) if full.size else int(hits[0])
        cont = ctx[i + n:i + n + k]
        return cont if cont.size else None

    def propose(self, req, k: int) -> np.ndarray:
        ctx = np.asarray(req.all_token_ids, np.int32)
        for n in range(self.ngram_max, self.ngram_min - 1, -1):
            cont = self._find(ctx, n, k)
            if cont is not None:
                return cont.astype(np.int32)
        return np.zeros((0,), np.int32)
