"""Llama model tests."""
import jax
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.models.llama import llama_model, rope
from tests.util import base_config


def _tiny():
    return llama_model("tiny", attention_impl="xla", dtype="float32")


def _batch(bs=8, seq=16, vocab=256, seed=0):
    rng = np.random.default_rng(seed)
    return {"input_ids": rng.integers(0, vocab, size=(bs, seq), dtype=np.int32)}


def test_forward_shape_and_loss():
    m = _tiny()
    params = m.init(jax.random.PRNGKey(0))
    logits = m.apply(params, _batch(2, 16))
    assert logits.shape == (2, 16, 256)
    loss = float(m.loss(params, _batch(4, 32)))
    assert abs(loss - np.log(256)) < 0.5


def test_causality():
    m = _tiny()
    params = m.init(jax.random.PRNGKey(0))
    b1 = _batch(1, 16, seed=1)
    b2 = {"input_ids": b1["input_ids"].copy()}
    b2["input_ids"][0, -1] = (b2["input_ids"][0, -1] + 1) % 256
    l1 = np.asarray(m.apply(params, b1))
    l2 = np.asarray(m.apply(params, b2))
    np.testing.assert_allclose(l1[0, :-1], l2[0, :-1], atol=1e-5)


def test_rope_relative():
    """RoPE preserves norms and depends only on relative offsets in q·k."""
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 8, 2, 16))
    r = rope(x, 10000.0)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(x), axis=-1),
                               np.linalg.norm(np.asarray(r), axis=-1),
                               rtol=1e-5)
    # dot(q_i, k_j) after rope equals dot at positions shifted by constant
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 12, 1, 16))
    kv = jax.random.normal(jax.random.PRNGKey(2), (1, 12, 1, 16))
    pos0 = np.arange(12)
    r1 = np.einsum("bshd,bthd->bst", np.asarray(rope(q, 1e4, pos0)),
                   np.asarray(rope(kv, 1e4, pos0)))
    pos5 = pos0 + 5
    r2 = np.einsum("bshd,bthd->bst", np.asarray(rope(q, 1e4, pos5)),
                   np.asarray(rope(kv, 1e4, pos5)))
    np.testing.assert_allclose(r1, r2, rtol=1e-3, atol=1e-4)


def test_gqa_kv_heads():
    m = llama_model("tiny", num_kv_heads=1, attention_impl="xla",
                    dtype="float32")
    params = m.init(jax.random.PRNGKey(0))
    assert params["blocks"]["wk"].shape[-1] == m.config.head_dim
    logits = m.apply(params, _batch(2, 8))
    assert np.isfinite(np.asarray(logits)).all()


def test_train_llama_engine(devices8):
    engine, *_ = deepspeed_tpu.initialize(
        model=_tiny(),
        config=base_config(zero_optimization={"stage": 3}))
    losses = []
    for i in range(3):
        losses.append(float(engine.train_batch(
            batch={"input_ids": _batch(8, 16, seed=i)["input_ids"][None]})))
    assert np.isfinite(losses).all()
