"""Attention dispatch: XLA einsum attention (always available) and the Pallas
flash-attention kernel on real TPU (reference capability: the fused attention in
csrc/transformer/*.cu and csrc/transformer/inference/csrc/softmax.cu, rebuilt as
TPU kernels rather than translated).
"""
from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _on_tpu() -> bool:
    try:
        d = jax.devices()[0]
        # experimental TPU platforms (e.g. axon tunnels) report their own
        # platform string but a TPU device kind
        return d.platform == "tpu" or "tpu" in str(d).lower()
    except Exception:
        return False


def xla_causal_attention(q, k, v, segment_ids=None):
    """Reference einsum attention with causal mask; [B, S, H, hd] layout.
    fp32 softmax accumulation for bf16 inputs.  ``segment_ids`` [B, S]
    restricts attention within packed segments."""
    B, S, H, hd = q.shape
    scale = hd ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    mask = jnp.tril(jnp.ones((S, S), dtype=bool))[None, None]
    if segment_ids is not None:
        mask = mask & (segment_ids[:, None, :, None]
                       == segment_ids[:, None, None, :])
    scores = jnp.where(mask, scores, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_causal_attention(q, k, v, segment_ids=None, fallback=True):
    """Pallas TPU flash attention (blockwise, never materialises the [S,S]
    scores in HBM).

    Kernel selection: the in-tree from-scratch FlashAttention-2 kernel
    (ops/pallas/ds_flash_attention) by DEFAULT — it beat the tuned stock
    wrapper 1.39x fwd+bwd at the 760M bench shape (B12 S1024 H16 hd96,
    3.92 ms vs 5.46 ms, PERF.md round-4 on-chip A/B) — with
    ``DS_FLASH_KERNEL=stock`` opting dense unpacked shapes back into the
    stock wrapper.  Packed batches (``segment_ids``) always need the
    from-scratch kernel (only it supports segments).  Dense shapes the
    kernel cannot take (VMEM budget, non-decomposing S) degrade to the
    stock wrapper, then to the exact XLA einsum; with ``fallback=False``
    (the explicit ``impl="flash"`` contract) they raise instead."""
    import os
    prefer_stock = os.environ.get(
        "DS_FLASH_KERNEL", "").lower() == "stock"
    if segment_ids is not None or not prefer_stock:
        from deepspeed_tpu.ops.pallas.ds_flash_attention import \
            ds_flash_attention
        vmem_ok = _ds_vmem_ok(q, segment_ids is not None)
        if not fallback and not vmem_ok:
            # explicit impl="flash" on a shape the VMEM heuristic rejects:
            # raise EAGERLY at trace time — under jit the Mosaic
            # scoped-VMEM failure happens at XLA compile time where no
            # except block here could wrap it, so a late opaque error is
            # the only alternative.  DS_FLASH_VMEM_MB is the escape hatch
            # for shapes the conservative margin mis-rejects.
            budget = int(os.environ.get("DS_FLASH_VMEM_MB", "12"))
            raise ValueError(
                f"impl='flash': q shape {tuple(q.shape)} ({q.dtype}) "
                f"exceeds the flash kernel's VMEM budget "
                f"(DS_FLASH_VMEM_MB={budget} MiB; the check holds a "
                f"safety margin — raise it if this shape is known to "
                f"compile). Shorten the sequence or use impl='auto' for "
                f"the XLA fallback.")
        if vmem_ok:    # the eager guard makes not-fallback imply vmem_ok
            try:
                return ds_flash_attention(q, k, v, segment_ids=segment_ids,
                                          causal=True)
            except Exception as e:
                # the eager guard above means not-fallback implies vmem_ok
                if not fallback:
                    if isinstance(e, ValueError):
                        raise   # genuine shape error, already actionable
                    budget = int(os.environ.get("DS_FLASH_VMEM_MB", "12"))
                    raise ValueError(
                        f"impl='flash': q shape {tuple(q.shape)} "
                        f"({q.dtype}) failed in the flash kernel despite "
                        f"passing the VMEM heuristic (budget "
                        f"DS_FLASH_VMEM_MB={budget} MiB). Lower the "
                        f"budget or use impl='auto' for the XLA "
                        f"fallback.") from e
                if not isinstance(e, ValueError):
                    raise       # fallback covers shape rejections only
        if segment_ids is not None:
            # only the ds kernel masks segments: exact XLA path
            return xla_causal_attention(q, k, v, segment_ids)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    try:
        return flash_attention(q, k, v, causal=True)
    except ValueError:
        if not fallback:
            raise
        # stock wrapper rejects the shape too: terminal exact einsum
        return xla_causal_attention(q, k, v)


def _ds_vmem_ok(q, packed=False) -> bool:
    """VMEM-budget check for the from-scratch kernel's whole-S staging; the
    eval_shape probe cannot see Mosaic VMEM exhaustion, so oversized shapes
    are routed to the XLA path here (loudly, once per shape class)."""
    from deepspeed_tpu.ops.pallas.ds_flash_attention import vmem_fits
    key = ("vmem", q.shape[1], q.shape[3], q.dtype.itemsize, packed)
    if key not in _FLASH_STATUS:
        _FLASH_STATUS[key] = vmem_fits(q.shape[1], q.shape[3],
                                       q.dtype.itemsize, packed=packed)
        if _FLASH_STATUS[key] is not True:
            from deepspeed_tpu.utils.logging import logger
            logger.warning(
                f"attention: ds flash kernel working set for S={q.shape[1]} "
                f"head_dim={q.shape[3]} {q.dtype} exceeds the VMEM budget — "
                "routing this shape away from the ds kernel (stock flash "
                "wrapper for dense batches, exact XLA einsum for packed) — "
                "raise DS_FLASH_VMEM_MB only if the target core has more "
                "VMEM")
    return _FLASH_STATUS[key] is True


_FLASH_STATUS = {}  # probe/guard result per shape-class key: True / message


def _flash_usable(q, fn=None, k=None, ds=False, packed=False) -> bool:
    """Probe the Pallas flash path once per shape class and remember the
    outcome.  A failure is logged loudly (never silently degraded — VERDICT
    round 1 flagged the silent except here) so a bench run on a slow fallback
    is visible in the logs.  ``ds=True`` marks fns that route to the
    from-scratch kernel, whose whole-S VMEM staging the eval_shape probe
    cannot vet — those get the budget check first."""
    from deepspeed_tpu.utils.logging import logger
    fn = fn or flash_causal_attention
    kv = q if k is None else k
    if ds and not _ds_vmem_ok(q, packed=packed):
        return False
    key = (q.shape[1], q.shape[3], kv.shape[2],
           getattr(fn, "__name__", "bidirectional"))
    if key not in _FLASH_STATUS:
        try:
            jax.eval_shape(fn, q, kv, kv)
            _FLASH_STATUS[key] = True
            logger.info(f"attention: Pallas flash selected for S={key[0]} "
                        f"head_dim={key[1]}")
        except Exception as e:  # trace-time failure: kernel unsupported here
            _FLASH_STATUS[key] = f"{type(e).__name__}: {e}"
            logger.warning(
                f"attention: Pallas flash UNAVAILABLE for S={key[0]} "
                f"head_dim={key[1]} — falling back to XLA einsum attention "
                f"(materialises [S,S] scores). Cause: {_FLASH_STATUS[key]}")
    return _FLASH_STATUS[key] is True


def _ds_gqa_causal(q, k, v):
    from deepspeed_tpu.ops.pallas.ds_flash_attention import \
        ds_flash_attention
    return ds_flash_attention(q, k, v, causal=True)


def _local_causal_attention(q, k, v, impl: str = "auto", segment_ids=None):
    gqa = k.shape[2] != q.shape[2]
    if segment_ids is not None:
        # packed sequences: only the from-scratch kernel (GQA-native,
        # segment-masked) or the exact einsum can honor the mask
        from deepspeed_tpu.ops.pallas.ds_flash_attention import \
            ds_flash_attention
        if impl == "flash":
            # explicit request: no fallback — surface the real error
            return ds_flash_attention(q, k, v, segment_ids=segment_ids,
                                      causal=True)
        if impl == "auto" and _on_tpu() and q.shape[1] >= 256 \
                and _ds_vmem_ok(q, packed=True):
            try:
                return ds_flash_attention(q, k, v,
                                          segment_ids=segment_ids,
                                          causal=True)
            except ValueError:
                from deepspeed_tpu.utils.logging import warning_once
                warning_once(
                    f"packed attention: S={q.shape[1]} does not "
                    "block-decompose for the flash kernel — exact einsum "
                    "fallback (materialises [S,S] scores)")
        if gqa:
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        return xla_causal_attention(q, k, v, segment_ids)
    if impl == "flash":
        # explicit request: no fallback — surface the real error
        if gqa:
            return _ds_gqa_causal(q, k, v)
        return flash_causal_attention(q, k, v, fallback=False)
    if impl == "auto" and _on_tpu() and q.shape[1] >= 256:
        if gqa and _flash_usable(q, fn=_ds_gqa_causal, k=k, ds=True):
            # grouped-query: the from-scratch kernel reads each KV head
            # once per group instead of attending repeated copies
            return _ds_gqa_causal(q, k, v)
        if gqa:
            # kernel unusable for this shape: repeat and try the tuned
            # stock wrapper before surrendering to the [S,S] einsum
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
            gqa = False
        if _flash_usable(q):
            return flash_causal_attention(q, k, v)
    if gqa:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return xla_causal_attention(q, k, v)


def xla_bidirectional_attention(q, k, v, pad_mask=None):
    """Encoder (BERT-style) attention; optional key padding mask [B, S]
    (1 = real token).  fp32 softmax accumulation."""
    B, S, H, hd = q.shape
    scale = hd ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if pad_mask is not None:
        scores = jnp.where(pad_mask[:, None, None, :].astype(bool), scores,
                           jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def bidirectional_attention(q, k, v, pad_mask=None, impl: str = "auto"):
    """q/k/v: [B, S, H, hd] -> [B, S, H, hd], no causal mask.

    Unpadded batches (``pad_mask=None``) ride the Pallas flash kernel on
    TPU at S>=256.  A padding mask maps onto the from-scratch kernel's
    segment ids (real tokens segment 1, pads segment 0 — pads only see
    pads, whose outputs are discarded), so padded encoder batches get the
    flash path too; sequence lengths that do not block-decompose fall back
    to the exact XLA path.
    """
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention
    noncausal = partial(flash_attention, causal=False)

    def flash_padded(a, b, c):
        from deepspeed_tpu.ops.pallas.ds_flash_attention import \
            ds_flash_attention
        return ds_flash_attention(a, b, c, segment_ids=pad_mask,
                                  causal=False)

    if impl == "flash":
        if pad_mask is not None:
            return flash_padded(q, k, v)
        # explicit request: no fallback — surface the real error
        return noncausal(q, k, v)
    if impl == "auto" and _on_tpu() and q.shape[1] >= 256:
        if pad_mask is None and _flash_usable(q, fn=noncausal):
            return noncausal(q, k, v)
        # padded: probe the segment-capable kernel the same (loudly
        # logged) way the unpadded path probes the stock wrapper
        if pad_mask is not None and _flash_usable(q, fn=flash_padded,
                                                  ds=True, packed=True):
            return flash_padded(q, k, v)
    return xla_bidirectional_attention(q, k, v, pad_mask)


def causal_attention(q, k, v, impl: str = "auto", segment_ids=None):
    """q [B, S, H, hd], k/v [B, S, KV, hd] -> [B, S, H, hd]; KV may divide
    H (GQA — the from-scratch flash kernel attends compact KV natively,
    other paths repeat).  ``segment_ids`` [B, S] restricts attention
    within packed segments (models thread ``batch["segment_ids"]`` here;
    the from-scratch kernel masks natively, the einsum path exactly).

    When the mesh has an active ``seq`` axis, attention runs under Ulysses
    sequence parallelism (head-scatter all-to-all; see sequence/layer.py) —
    models get SP transparently.  Packed segments compose with Ulysses
    (the head-scattered local product sees the full sequence) but not
    with ring CP (block-granular masks only — rejected loudly).
    """
    from deepspeed_tpu.comm.mesh import get_topology, SEQ_AXIS
    try:
        sp = get_topology().mesh.shape[SEQ_AXIS]
    except Exception:
        sp = 1
    if sp > 1 and getattr(get_topology(), "sequence_parallel_impl",
                          "ulysses") == "ring":
        if segment_ids is not None:
            raise NotImplementedError(
                "packed sequences (segment_ids) do not compose with ring "
                "context parallelism — use sequence_parallel_impl="
                "'ulysses' for packed batches")
        # ring CP (config mesh.sequence_parallel_impl="ring"): K/V blocks
        # rotate around the seq axis; the ring repeats compact KV itself
        # only in its dense fallback, but its shard_map spec expects
        # matching head counts — repeat here for GQA models
        if k.shape[2] != q.shape[2]:
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        from deepspeed_tpu.sequence.ring_attention import ring_attention
        # honor the caller's impl choice: "xla" means the exact einsum
        # path, which is the ring's "dense" chunk product
        return ring_attention(q, k, v, causal=True,
                              impl={"xla": "dense"}.get(impl, impl))
    if sp > 1:
        # Ulysses scatters heads over the seq axis: compact KV rides the
        # all-to-all whenever each (model-sharded) KV head shard divides
        # sp (1/group the wire bytes); otherwise repeat first
        try:
            tp = get_topology().mesh.shape["model"]
        except Exception:
            tp = 1
        if k.shape[2] != q.shape[2] and k.shape[2] % (sp * tp):
            rep = q.shape[2] // k.shape[2]
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        from deepspeed_tpu.sequence.layer import distributed_attention
        if segment_ids is not None:
            return distributed_attention(
                q, k, v,
                lambda a, b, c, seg: _local_causal_attention(
                    a, b, c, impl, seg),
                segment_ids=segment_ids)
        return distributed_attention(
            q, k, v, lambda a, b, c: _local_causal_attention(a, b, c, impl))
    return _local_causal_attention(q, k, v, impl, segment_ids)
