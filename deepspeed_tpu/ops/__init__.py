from deepspeed_tpu.ops.attention import causal_attention
