"""Sparse embedding gradients (reference: deepspeed/runtime/sparse_tensor.py
+ the engine's sparse-allreduce path, config key ``sparse_gradients``)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from deepspeed_tpu.utils.jax_compat import (HAS_PARTIAL_AUTO_SHARD_MAP,
                                            shard_map)
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import deepspeed_tpu
from deepspeed_tpu.runtime.sparse_tensor import sparse_embedding_allreduce
from deepspeed_tpu.models.llama import llama_model


def test_sparse_allreduce_matches_dense_mean(devices8):
    """(ids, rows) exchange reproduces the dense pmean exactly, duplicates
    included."""
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    rng = np.random.default_rng(0)
    V, D, T = 32, 8, 16
    ids = rng.integers(0, V, size=(8, T)).astype(np.int32)   # with dups
    # lookup-style local grads: rows non-zero only at local ids
    dense = np.zeros((8, V, D), np.float32)
    for d in range(8):
        for t in ids[d]:
            dense[d, t] += rng.normal(size=D)
    g_sh = jax.device_put(jnp.asarray(dense),
                          NamedSharding(mesh, P("dp", None, None)))
    i_sh = jax.device_put(jnp.asarray(ids), NamedSharding(mesh, P("dp", None)))

    def body(g, i):
        return sparse_embedding_allreduce(g[0], i[0], "dp", 8)[None]

    out = shard_map(body, mesh=mesh, in_specs=(P("dp", None, None),
                                               P("dp", None)),
                    out_specs=P(None, None, None), check_vma=False)(g_sh, i_sh)
    np.testing.assert_allclose(np.asarray(out)[0], dense.mean(0),
                               rtol=1e-5, atol=1e-6)


def test_sparse_gradients_training_matches_dense(devices8):
    """sparse_gradients=True trains identically to the dense path on an
    untied-embedding model (llama) over a pure-DP mesh."""
    def run(sparse):
        from deepspeed_tpu.comm import reset_topology
        reset_topology()
        engine, *_ = deepspeed_tpu.initialize(
            model=llama_model("tiny", attention_impl="xla", dtype="float32"),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "sparse_gradients": sparse,
                "steps_per_print": 0,
            })
        rng = np.random.default_rng(3)
        losses = []
        for _ in range(2):
            batch = {"input_ids": rng.integers(
                0, 256, size=(2, 8, 16), dtype=np.int32)}
            losses.append(float(engine.train_batch(batch=batch)))
        wte = np.asarray(jax.device_get(engine.state["params"]["wte"]))
        return losses, wte

    dense_losses, dense_wte = run(False)
    sparse_losses, sparse_wte = run(True)
    np.testing.assert_allclose(sparse_losses, dense_losses, rtol=2e-5)
    np.testing.assert_allclose(sparse_wte, dense_wte, rtol=1e-4, atol=1e-6)


@pytest.mark.skipif(
    not HAS_PARTIAL_AUTO_SHARD_MAP,
    reason="sparse-tier-on-wide-mesh needs partially-auto shard_map; "
           "this jax's lowering CHECK-aborts the process so the engine "
           "gates the tier off (env-blocked — same class as the qgZ "
           "skips, see tests/test_zeropp.py module note)")
def test_sparse_gradients_on_hybrid_tp_mesh(devices8):
    """sparse_gradients engages on a TP×DP mesh (round-2 VERDICT weak 1:
    no more single-axis pure-DP restriction) — the touched-rows exchange
    runs over the manual data axis while TP reductions stay automatic."""
    def run(sparse):
        from deepspeed_tpu.comm import reset_topology
        reset_topology()
        engine, *_ = deepspeed_tpu.initialize(
            model=llama_model("tiny", attention_impl="xla", dtype="float32"),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "Adam", "params": {"lr": 1e-3}},
                "sparse_gradients": sparse,
                "mesh": {"model_parallel_size": 2},
                "steps_per_print": 0,
            })
        if sparse:
            assert engine._get_qgz_plan() is not None, \
                "sparse tier did not engage on TP mesh"
        rng = np.random.default_rng(5)
        losses = []
        for _ in range(2):
            batch = {"input_ids": rng.integers(
                0, 256, size=(2, 8, 16), dtype=np.int32)}
            losses.append(float(engine.train_batch(batch=batch)))
        wte = np.asarray(jax.device_get(engine.state["params"]["wte"]))
        return losses, wte

    dense_losses, dense_wte = run(False)
    sparse_losses, sparse_wte = run(True)
    np.testing.assert_allclose(sparse_losses, dense_losses, rtol=2e-5)
    np.testing.assert_allclose(sparse_wte, dense_wte, rtol=1e-4, atol=1e-6)


def test_sparse_gradients_warns_on_tied_embedding(devices8):
    """GPT-2's tied wte must not engage the sparse path (no
    sparse_grad_params declared) — warn and fall back to dense."""
    import logging
    from tests.util import tiny_gpt2, base_config, random_batches
    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    handler = Capture()
    logging.getLogger("deepspeed_tpu").addHandler(handler)
    try:
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_gpt2(), config=base_config(sparse_gradients=True))
        b = random_batches(1, batch_size=8, seed=0)[0]
        loss = engine.train_batch(batch={"input_ids": b["input_ids"][None]})
    finally:
        logging.getLogger("deepspeed_tpu").removeHandler(handler)
    assert np.isfinite(float(loss))
    assert any("sparse_grad_params" in m for m in records), records
