"""LR schedule unit tests (reference: tests/unit/runtime/test_lr_schedulers.py)."""
import numpy as np
import pytest

from deepspeed_tpu.runtime.lr_schedules import (
    get_lr_schedule, VALID_LR_SCHEDULES, WARMUP_LR, WARMUP_DECAY_LR,
    WARMUP_COSINE_LR, ONE_CYCLE, LR_RANGE_TEST)


def test_warmup_ramps_then_flat():
    s = get_lr_schedule(WARMUP_LR, {"warmup_min_lr": 0.0,
                                    "warmup_max_lr": 0.01,
                                    "warmup_num_steps": 10})
    assert float(s(0)) < float(s(5)) < float(s(10))
    assert float(s(10)) == pytest.approx(0.01)
    assert float(s(100)) == pytest.approx(0.01)


def test_warmup_decay_hits_zero():
    s = get_lr_schedule(WARMUP_DECAY_LR, {"total_num_steps": 100,
                                          "warmup_max_lr": 0.01,
                                          "warmup_num_steps": 10})
    assert float(s(100)) == pytest.approx(0.0, abs=1e-8)
    assert float(s(55)) == pytest.approx(0.005, rel=0.01)


def test_warmup_cosine():
    s = get_lr_schedule(WARMUP_COSINE_LR, {"total_num_steps": 100,
                                           "warmup_num_steps": 10,
                                           "warmup_max_lr": 0.01})
    mid = float(s(55))
    assert 0 < float(s(99)) < mid < float(s(10))


def test_one_cycle_shape():
    s = get_lr_schedule(ONE_CYCLE, {"cycle_min_lr": 0.001,
                                    "cycle_max_lr": 0.01,
                                    "cycle_first_step_size": 10})
    assert float(s(0)) == pytest.approx(0.001)
    assert float(s(10)) == pytest.approx(0.01)
    assert float(s(20)) == pytest.approx(0.001)


def test_lr_range_test_monotone():
    s = get_lr_schedule(LR_RANGE_TEST, {"lr_range_test_min_lr": 1e-4,
                                        "lr_range_test_step_size": 10,
                                        "lr_range_test_step_rate": 1.0})
    vals = [float(s(i)) for i in range(0, 100, 10)]
    assert all(b > a for a, b in zip(vals, vals[1:]))


def test_unknown_raises():
    with pytest.raises(ValueError):
        get_lr_schedule("Nope", {})


def test_all_valid_instantiable():
    for name in VALID_LR_SCHEDULES:
        s = get_lr_schedule(name, {"total_num_steps": 10})
        assert np.isfinite(float(s(1)))
