"""deepspeed_tpu — a TPU-native large-model training framework.

Provides the capability surface of DeepSpeed (reference: deepspeed/__init__.py:64
``initialize`` and :269 ``init_inference``) re-designed for JAX/XLA on TPU:

- ``initialize()`` returns a :class:`~deepspeed_tpu.runtime.engine.DeepSpeedEngine`
  that compiles a pure train step under ``jax.jit`` with explicit shardings over a
  named device mesh instead of wrapping an ``nn.Module`` with autograd hooks.
- ZeRO stages 1/2/3 are sharding policies over the parameter/gradient/optimizer
  pytrees (XLA inserts the all-gather / reduce-scatter collectives the reference
  issues by hand).
- Pipeline/tensor/expert/sequence parallelism are mesh axes, not process groups.
"""

from deepspeed_tpu.version import __version__, __version_info__

# imported for its side effect as well as the shims: jax_compat flips
# jax_threefry_partitionable ON so RNG draws are sharding-invariant —
# it must happen before the first engine births params sharded, i.e.
# at package import, not at the first lazy shard_map use
from deepspeed_tpu.utils import jax_compat  # noqa: F401

from deepspeed_tpu.runtime.config import DeepSpeedConfig
from deepspeed_tpu.runtime.engine import DeepSpeedEngine
from deepspeed_tpu.accelerator import get_accelerator
from deepspeed_tpu import comm  # noqa: F401  (deepspeed.comm facade)
from deepspeed_tpu import zero  # noqa: F401  (deepspeed.zero API surface)


def initialize(args=None,
               model=None,
               optimizer=None,
               model_parameters=None,
               training_data=None,
               lr_scheduler=None,
               mesh=None,
               dist_init_required=None,
               collate_fn=None,
               config=None,
               config_params=None,
               mpu=None):
    """Create a training engine (reference: deepspeed/__init__.py:64).

    Args:
        args: optional namespace carrying ``deepspeed_config`` (CLI compat).
        model: a model description — either a :class:`deepspeed_tpu.models.Model`
            (apply/init pair) or anything exposing ``init(rng)`` / ``apply``.
        optimizer: optional optax gradient transformation overriding the config's
            ``optimizer`` section (reference lets a client torch optimizer through).
        model_parameters: optional pre-initialised parameter pytree.
        training_data: optional dataset for engine-built input pipeline.
        lr_scheduler: optional optax schedule overriding the config's ``scheduler``.
        mesh: optional ``jax.sharding.Mesh``; default mesh is built from the config's
            parallel-dimension keys and ``jax.devices()``.
        config: dict or path to a DeepSpeed-style JSON config.

    Returns:
        tuple of (engine, optimizer_handle, dataloader, lr_scheduler_handle) to
        mirror the reference's 4-tuple return.
    """
    from deepspeed_tpu.runtime.engine import DeepSpeedEngine

    if config is None and config_params is not None:
        config = config_params
    if config is None and args is not None:
        config = getattr(args, "deepspeed_config", None)
    if config is None:
        raise ValueError("deepspeed_tpu.initialize: a config dict or path is required")

    comm.init_distributed(dist_init_required=dist_init_required)

    engine = DeepSpeedEngine(
        config=config,
        model=model,
        optimizer=optimizer,
        model_parameters=model_parameters,
        training_data=training_data,
        lr_scheduler=lr_scheduler,
        mesh=mesh,
        collate_fn=collate_fn,
        mpu=mpu,
    )
    return engine, engine.optimizer, engine.training_dataloader, engine.lr_scheduler


def init_inference(model=None, config=None, **kwargs):
    """Create an inference engine (reference: deepspeed/__init__.py:269)."""
    from deepspeed_tpu.inference.engine import InferenceEngine
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    if config is None:
        config = kwargs
    elif kwargs:
        config = {**config, **kwargs}
    cfg = DeepSpeedInferenceConfig(**config) if isinstance(config, dict) else config
    return InferenceEngine(model, cfg)


def add_config_arguments(parser):
    """Add ``--deepspeed`` / ``--deepspeed_config`` CLI args (reference:
    deepspeed/__init__.py:205)."""
    group = parser.add_argument_group("DeepSpeed", "DeepSpeed-TPU configurations")
    group.add_argument("--deepspeed", default=False, action="store_true",
                       help="Enable DeepSpeed-TPU (helper flag, no-op)")
    group.add_argument("--deepspeed_config", default=None, type=str,
                       help="Path to DeepSpeed-style JSON config")
    group.add_argument("--deepscale", default=False, action="store_true",
                       help=argparse_suppress())
    return parser


def argparse_suppress():
    import argparse
    return argparse.SUPPRESS
