"""Shared bench timing helpers — the axon-tunnel measurement discipline
in ONE place (ISSUE 12 satellite).

PERF.md's round-4 lesson: the tunnel charges a fixed ~100 ms per
blocking round trip, ~1.8 GB/s to fetch any returned array, and —
crucially — ``jax.block_until_ready`` does NOT synchronize on the
tunnel: it waits on the local future, not the remote stream, so a
bench that "syncs" with it under-reports.  The only trustworthy sync
is FETCHING A VALUE; the only trustworthy timing is the SLOPE between
two on-device chained step counts, which cancels every fixed cost.

Every sweep/profile script imports these instead of growing its own
copy (decode_profile, serve_bench, qgemm_sweep, ggemm_sweep; the
original lives in scripts/flash_ab.py)."""
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def fetch(x):
    """Value-fetch synchronization: materialize ``x`` on the host and
    return it as numpy.  This is the ONE sync primitive benches should
    use — ``block_until_ready`` does not synchronize on the axon
    tunnel (PERF.md round 4)."""
    return np.asarray(x)


def timed_chain(step_fn, state0, n, warmup=2):
    """On-device loop slope: run ``m`` and ``5m`` chained ``step_fn``
    applications inside one jitted ``fori_loop`` (a data dependency
    chains them), sync by fetching a scalar, and report the per-step
    SLOPE in seconds — fixed dispatch/tunnel costs cancel between the
    two step counts.  ``state0`` is a tuple whose first element is an
    array (reduced to the fetched scalar)."""
    @jax.jit
    def run(state, m):
        state = lax.fori_loop(0, m, lambda i, s: step_fn(s), state)
        return jnp.sum(state[0].astype(jnp.float32))

    float(run(state0, warmup))          # compile + warm (value fetch syncs)

    def once(m):
        t0 = time.time()
        float(run(state0, m))
        return time.time() - t0

    t_small = min(once(n), once(n))
    t_big = min(once(5 * n), once(5 * n))
    return (t_big - t_small) / (4 * n)


def timed_chain_ms(step_fn, state0, n, warmup=3):
    """``timed_chain`` in milliseconds (decode_profile's historical
    unit)."""
    return timed_chain(step_fn, state0, n, warmup=warmup) * 1e3
