"""OnebitAdam (reference: deepspeed/runtime/fp16/onebit/adam.py:14).

Two-phase Adam: a fp32-comm *warmup* phase runs exact Adam while the
variance term settles; after ``freeze_step`` the variance (second moment)
freezes and gradients exchange through the 1-bit error-feedback compressed
all-reduce (runtime/comm/compressed.py) — 32x less gradient traffic.

Functional/optax formulation: ``onebit_adam`` returns a
``GradientTransformation`` whose state carries (m, v, error, step); the
caller provides already-reduced gradients during warmup and LOCAL gradients
plus an axis name afterwards (inside shard_map) — the engine-independent
pieces (compression + frozen-variance update) are what the reference's
class implements, and are unit-testable without a cluster.
"""
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
import optax

from deepspeed_tpu.runtime.comm.compressed import compressed_allreduce


class OnebitAdamState(NamedTuple):
    count: jnp.ndarray
    m: optax.Updates
    v: optax.Updates
    error: optax.Updates          # worker-side compression residual
    server_error: optax.Updates   # owned-chunk re-compression residual


def onebit_adam(learning_rate=1e-3, b1: float = 0.9,
                b2: float = 0.999, eps: float = 1e-8,
                weight_decay: float = 0.0,
                freeze_step: int = 100, axis_name=None,
                axis_size: int = 0):
    """1-bit Adam as an optax GradientTransformation.

    Before ``freeze_step``: exact Adam (grads assumed already reduced).
    After: v freezes; grads pass through the compressed all-reduce when
    ``axis_name`` is given (i.e. when running inside shard_map), with the
    error-feedback residual carried in the state.
    """

    def init_fn(params):
        z = lambda: jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32),
                                 params)
        # the error-feedback trees only exist when compression is engaged
        # (axis_name given); the engine's uncompressed path carries empty
        # pytrees instead of param-sized fp32 allocations
        if axis_name is not None:
            err = z()
            server = jax.tree.map(
                lambda p: jnp.zeros(
                    (p.size // axis_size,)
                    if axis_size and p.size % axis_size == 0 else (0,),
                    jnp.float32), params)
        else:
            err, server = (), ()
        return OnebitAdamState(jnp.zeros((), jnp.int32), z(), z(), err,
                               server)

    def update_fn(grads, state, params=None):
        count = state.count + 1
        in_warmup = count <= freeze_step

        if axis_name is None:
            g_red = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
            new_error = state.error
            new_server = state.server_error
        else:
            # lax.cond, not jnp.where: a select would compile BOTH
            # collectives into every step (XLA cannot DCE a collective
            # behind a predicate), paying fp32 traffic after the freeze
            def warm(g, err, srv):
                return (lax.pmean(g.astype(jnp.float32), axis_name),
                        jnp.zeros_like(err), jnp.zeros_like(srv))

            def frozen(g, err, srv):
                if srv.shape[0]:
                    return compressed_allreduce(g, err, axis_name,
                                                server_error=srv)
                red, ne = compressed_allreduce(g, err, axis_name)
                return red, ne, srv

            def reduce_leaf(g, err, srv):
                return lax.cond(in_warmup, warm, frozen, g, err, srv)

            reduced = jax.tree.map(
                lambda g, e, sv: reduce_leaf(g, e, sv),
                grads, state.error, state.server_error)
            is_t = lambda x: isinstance(x, tuple)
            g_red = jax.tree.map(lambda t: t[0], reduced, is_leaf=is_t)
            new_error = jax.tree.map(lambda t: t[1], reduced, is_leaf=is_t)
            new_server = jax.tree.map(lambda t: t[2], reduced, is_leaf=is_t)

        m = jax.tree.map(lambda mm, g: b1 * mm + (1 - b1) * g, state.m, g_red)
        # frozen variance after freeze_step (the 1-bit Adam invariant)
        v = jax.tree.map(
            lambda vv, g: jnp.where(in_warmup, b2 * vv + (1 - b2) * g * g,
                                    vv),
            state.v, g_red)
        c = count.astype(jnp.float32)
        lr = (learning_rate(count) if callable(learning_rate)
              else learning_rate)
        mhat = jax.tree.map(lambda mm: mm / (1 - b1 ** c), m)
        vhat = jax.tree.map(lambda vv: vv / (1 - b2 ** jnp.minimum(
            c, float(freeze_step))), v)
        if weight_decay > 0 and params is not None:
            updates = jax.tree.map(
                lambda mh, vh, p: -lr * (mh / (jnp.sqrt(vh) + eps)
                                         + weight_decay * p),
                mhat, vhat, params)
        else:
            updates = jax.tree.map(
                lambda mh, vh: -lr * mh / (jnp.sqrt(vh) + eps),
                mhat, vhat)
        return updates, OnebitAdamState(count, m, v, new_error, new_server)

    return optax.GradientTransformation(init_fn, update_fn)


class OnebitAdam:
    """Class shim with the reference's constructor surface."""

    def __init__(self, params=None, deepspeed=None, lr: float = 1e-3,
                 freeze_step: int = 100, betas=(0.9, 0.999), eps: float = 1e-8,
                 cuda_aware: bool = False, comm_backend_name: str = "jax",
                 **kw):
        self.transform = onebit_adam(learning_rate=lr, b1=betas[0],
                                     b2=betas[1], eps=eps,
                                     freeze_step=freeze_step)
