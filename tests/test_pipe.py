"""Pipeline parallelism tests (reference: tests/unit/runtime/pipe/test_pipe.py,
test_pipe_schedule.py, test_topology.py)."""
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.runtime.pipe.topology import (
    ProcessTopology, PipeDataParallelTopology, PipeModelDataParallelTopology,
    PipelineParallelGrid)
from deepspeed_tpu.runtime.pipe.schedule import (
    TrainSchedule, InferenceSchedule, ForwardPass, BackwardPass,
    OptimizerStep, LoadMicroBatch, RecvActivation, bubble_fraction)
from deepspeed_tpu.runtime.pipe.pipeline import pipeline_model
from tests.util import tiny_gpt2, base_config


# ---------------------------------------------------------------- topology
def test_topology_rank_coord_roundtrip():
    topo = ProcessTopology(axes=["pipe", "data"], dims=[2, 4])
    assert topo.world_size() == 8
    assert topo.get_rank(pipe=0, data=0) == 0
    assert topo.get_rank(pipe=1, data=3) == 7
    c = topo.get_coord(5)
    assert (c.pipe, c.data) == (1, 1)


def test_topology_axis_comm_lists():
    topo = PipeDataParallelTopology(num_pp=2, num_dp=2)
    pipe_lists = topo.get_axis_comm_lists("pipe")
    assert sorted(map(sorted, pipe_lists)) == [[0, 2], [1, 3]]
    data_lists = topo.get_axis_comm_lists("data")
    assert sorted(map(sorted, data_lists)) == [[0, 1], [2, 3]]


def test_topology_filter_match():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.world_size() == 8
    ranks = topo.filter_match(pipe=0)
    assert len(ranks) == 4


def test_grid():
    topo = PipeDataParallelTopology(num_pp=4, num_dp=2)
    grid = PipelineParallelGrid(topo, global_rank=6)
    assert grid.pipe_parallel_size == 4
    assert grid.get_stage_id() == 3
    assert grid.is_last_stage()
    assert grid.stage_to_global(0) == 0


def test_rank_repr():
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=1)
    assert "pipe_00" in topo.get_rank_repr(0)
    assert "model_01" in topo.get_rank_repr(1)


# ---------------------------------------------------------------- schedule
@pytest.mark.parametrize("micro,stages,stage", [(4, 2, 0), (4, 2, 1),
                                                (8, 4, 2), (4, 4, 3)])
def test_train_schedule_counts_and_order(micro, stages, stage):
    sched = TrainSchedule(micro_batches=micro, stages=stages, stage_id=stage)
    steps = sched.steps()
    fwd = [c for step in steps for c in step if isinstance(c, ForwardPass)]
    bwd = [c for step in steps for c in step if isinstance(c, BackwardPass)]
    assert len(fwd) == micro
    assert len(bwd) == micro
    # every backward's buffer was forwarded first
    seen_fwd = set()
    for step in steps:
        for c in step:
            if isinstance(c, ForwardPass):
                seen_fwd.add(c.buffer_id)
            if isinstance(c, BackwardPass):
                assert c.buffer_id in seen_fwd
    # exactly one OptimizerStep, at the end
    opts = [c for step in steps for c in step if isinstance(c, OptimizerStep)]
    assert len(opts) == 1
    assert any(isinstance(c, OptimizerStep) for c in steps[-1])


def test_first_stage_loads_last_stage_recvs():
    s0 = TrainSchedule(4, 2, 0).steps()
    assert any(isinstance(c, LoadMicroBatch) for step in s0 for c in step)
    s1 = TrainSchedule(4, 2, 1).steps()
    assert any(isinstance(c, RecvActivation) for step in s1 for c in step)
    assert not any(isinstance(c, LoadMicroBatch) for step in s1 for c in step)


def test_inference_schedule_fill_drain():
    sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=0)
    steps = sched.steps()
    assert len(steps) == 4       # M + S - 1
    fwd = [c for step in steps for c in step if isinstance(c, ForwardPass)]
    assert len(fwd) == 3


def test_bubble_fraction():
    assert bubble_fraction(8, 2) == pytest.approx(1 / 9)
    assert bubble_fraction(1, 1) == 0.0


# ---------------------------------------------------------------- execution
def test_pipeline_matches_sequential(devices8):
    """PP=2 training must match the unpipelined engine numerically
    (reference: test_pipe.py compares pipeline loss against a reference
    module)."""
    gas = 4
    cfg = base_config(train_micro_batch_size_per_gpu=2,
                      gradient_accumulation_steps=gas)
    rng = np.random.default_rng(5)
    batches = [{"input_ids": rng.integers(0, 128, size=(gas, 16, 16),
                                          dtype=np.int32)} for _ in range(2)]

    ref, *_ = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    pipe1_model = pipeline_model(tiny_gpt2(), num_stages=1)
    pipe1, *_ = deepspeed_tpu.initialize(model=pipe1_model, config=cfg)
    for b in batches:
        l_seq = float(ref.train_batch(batch=b))
        l_p1 = float(pipe1.train_batch(batch=b))
        assert abs(l_seq - l_p1) < 2e-4, f"{l_seq} vs {l_p1}"


def test_pipeline_2stage_exact_vs_1stage(devices8):
    """Same dp world (4): pp=2 vs pp=1-pipelined must match losses."""
    gas = 4
    mesh2 = {"pipe_parallel_size": 2, "data_parallel_size": 4}
    cfg2 = base_config(train_micro_batch_size_per_gpu=1,
                       gradient_accumulation_steps=gas, mesh=mesh2)
    m2 = pipeline_model(tiny_gpt2(), num_stages=2)
    e2, *_ = deepspeed_tpu.initialize(model=m2, config=cfg2)

    mesh1 = {"pipe_parallel_size": 1, "data_parallel_size": 4,
             "model_parallel_size": 2}
    cfg1 = base_config(train_micro_batch_size_per_gpu=1,
                       gradient_accumulation_steps=gas, mesh=mesh1)
    m1 = pipeline_model(tiny_gpt2(), num_stages=1)
    e1, *_ = deepspeed_tpu.initialize(model=m1, config=cfg1)

    rng = np.random.default_rng(11)
    for step in range(2):
        batch = {"input_ids": rng.integers(0, 128, size=(gas, 4, 16),
                                           dtype=np.int32)}
        l2 = float(e2.train_batch(batch=batch))
        l1 = float(e1.train_batch(batch=batch))
        assert abs(l1 - l2) < 2e-4, f"step {step}: {l1} vs {l2}"


def test_pipeline_with_zero1(devices8):
    """PP × ZeRO-1 hybrid (BASELINE config 4; reference engine.py:1445)."""
    gas = 2
    cfg = base_config(train_micro_batch_size_per_gpu=1,
                      gradient_accumulation_steps=gas,
                      zero_optimization={"stage": 1},
                      mesh={"pipe_parallel_size": 2})
    model = pipeline_model(tiny_gpt2(), num_stages=2)
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    rng = np.random.default_rng(0)
    losses = []
    for _ in range(3):
        batch = {"input_ids": rng.integers(0, 128, size=(gas, 4, 16),
                                           dtype=np.int32)}
        losses.append(float(engine.train_batch(batch=batch)))
    assert np.isfinite(losses).all()


def test_pipeline_requires_enough_microbatches(devices8):
    model = pipeline_model(tiny_gpt2(), num_stages=2)
    cfg = base_config(train_micro_batch_size_per_gpu=1,
                      gradient_accumulation_steps=1,
                      mesh={"pipe_parallel_size": 2})
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    batch = {"input_ids": np.zeros((1, 4, 16), dtype=np.int32)}
    with pytest.raises(AssertionError, match="microbatches"):
        engine.train_batch(batch=batch)


def test_pipeline_bounded_buffers_parity(devices8):
    """pp=4, M=8 with num_pipe_buffers=4 (the 1F1B memory bound,
    reference schedule.py:176) must match the all-live schedule's losses
    (VERDICT round-1 item 8)."""
    gas = 8
    mesh = {"pipe_parallel_size": 4, "data_parallel_size": 2}
    model4 = tiny_gpt2(num_layers=4)
    base = dict(train_micro_batch_size_per_gpu=1,
                gradient_accumulation_steps=gas, mesh=mesh)
    cfg_all = base_config(**base)
    cfg_bound = base_config(**base, pipeline={"num_pipe_buffers": 4})

    e_all, *_ = deepspeed_tpu.initialize(
        model=pipeline_model(tiny_gpt2(num_layers=4), num_stages=4),
        config=cfg_all)
    e_bound, *_ = deepspeed_tpu.initialize(
        model=pipeline_model(tiny_gpt2(num_layers=4), num_stages=4),
        config=cfg_bound)

    rng = np.random.default_rng(23)
    for step in range(2):
        batch = {"input_ids": rng.integers(0, 128, size=(gas, 8, 16),
                                           dtype=np.int32)}
        l_a = float(e_all.train_batch(batch=batch))
        l_b = float(e_bound.train_batch(batch=batch))
        assert abs(l_a - l_b) < 2e-4, f"step {step}: {l_a} vs {l_b}"


def test_pipeline_bounded_buffers_memory(devices8):
    """The bounded schedule's compiled step must allocate less temp memory
    than the all-live schedule (activations live per chunk, not per M)."""
    import jax
    gas = 8
    mesh = {"pipe_parallel_size": 4, "data_parallel_size": 2}
    base = dict(train_micro_batch_size_per_gpu=2,
                gradient_accumulation_steps=gas, mesh=mesh)
    rng = np.random.default_rng(3)
    batch = {"input_ids": rng.integers(0, 128, size=(gas, 16, 64),
                                       dtype=np.int32)}

    def temp_bytes(cfg):
        eng, *_ = deepspeed_tpu.initialize(
            model=pipeline_model(
                tiny_gpt2(num_layers=4, max_seq_len=64), num_stages=4),
            config=cfg)
        sharded = eng._shard_batch(batch, stacked=True)
        fn = eng._get_compiled("train_step")
        compiled = fn.lower(eng.state, sharded, eng._next_rng()).compile()
        mem = compiled.memory_analysis()
        return int(getattr(mem, "temp_size_in_bytes", 0))

    all_live = temp_bytes(base_config(**base))
    bounded = temp_bytes(base_config(**base,
                                     pipeline={"num_pipe_buffers": 4}))
    assert bounded < all_live, (bounded, all_live)


def test_pipeline_bad_buffer_count_warns_and_runs(devices8):
    gas = 4
    mesh = {"pipe_parallel_size": 2, "data_parallel_size": 4}
    cfg = base_config(train_micro_batch_size_per_gpu=1,
                      gradient_accumulation_steps=gas, mesh=mesh,
                      pipeline={"num_pipe_buffers": 3})   # does not divide 4
    eng, *_ = deepspeed_tpu.initialize(
        model=pipeline_model(tiny_gpt2(), num_stages=2), config=cfg)
    rng = np.random.default_rng(7)
    batch = {"input_ids": rng.integers(0, 128, size=(gas, 4, 16),
                                       dtype=np.int32)}
    assert np.isfinite(float(eng.train_batch(batch=batch)))


# ------------------------------------------------------------------- 1F1B

def test_pipeline_1f1b_parity(devices8):
    """pipeline.schedule='1f1b' (round-2 VERDICT item 7): the one-pass
    interleaved schedule matches the all-live GPipe losses."""
    gas = 8
    mesh = {"pipe_parallel_size": 4, "data_parallel_size": 2}
    base = dict(train_micro_batch_size_per_gpu=1,
                gradient_accumulation_steps=gas, mesh=mesh)
    e_all, *_ = deepspeed_tpu.initialize(
        model=pipeline_model(tiny_gpt2(num_layers=4), num_stages=4),
        config=base_config(**base))
    e_1f1b, *_ = deepspeed_tpu.initialize(
        model=pipeline_model(tiny_gpt2(num_layers=4), num_stages=4),
        config=base_config(**base, pipeline={"schedule": "1f1b"}))
    rng = np.random.default_rng(29)
    for step in range(2):
        batch = {"input_ids": rng.integers(0, 128, size=(gas, 8, 16),
                                           dtype=np.int32)}
        l_a = float(e_all.train_batch(batch=batch))
        l_b = float(e_1f1b.train_batch(batch=batch))
        assert abs(l_a - l_b) < 2e-4, f"step {step}: {l_a} vs {l_b}"


def test_pipeline_1f1b_memory_independent_of_microbatches(devices8):
    """1F1B's live activations are O(n_stages) ring-buffer slots: temp
    memory must beat the all-live schedule at large M and grow only
    marginally when M doubles (the all-live schedule's residuals double)."""
    import jax
    mesh = {"pipe_parallel_size": 4, "data_parallel_size": 2}
    rng = np.random.default_rng(3)

    def temp_bytes(gas, schedule):
        from deepspeed_tpu.comm import reset_topology
        reset_topology()
        extra = {"pipeline": {"schedule": schedule}} if schedule else {}
        eng, *_ = deepspeed_tpu.initialize(
            model=pipeline_model(
                tiny_gpt2(num_layers=4, max_seq_len=64), num_stages=4),
            config=base_config(
                train_micro_batch_size_per_gpu=2,
                gradient_accumulation_steps=gas, mesh=mesh, **extra))
        batch = {"input_ids": rng.integers(0, 128, size=(gas, 16, 64),
                                           dtype=np.int32)}
        sharded = eng._shard_batch(batch, stacked=True)
        fn = eng._get_compiled("train_step")
        compiled = fn.lower(eng.state, sharded, eng._next_rng()).compile()
        mem = compiled.memory_analysis()
        return int(getattr(mem, "temp_size_in_bytes", 0))

    all_live_16 = temp_bytes(16, None)
    f1b_16 = temp_bytes(16, "1f1b")
    assert f1b_16 < all_live_16, (f1b_16, all_live_16)
    # doubling M doubles the all-live residuals; 1F1B stays ~flat (ring
    # buffers sized by n_stages, not M)
    f1b_32 = temp_bytes(32, "1f1b")
    assert f1b_32 < 1.5 * f1b_16, (f1b_16, f1b_32)
