from op_builder.builder import (OpBuilder, CPUAdamBuilder, AsyncIOBuilder,
                                load_op)

ALL_OPS = {b.NAME: b for b in (CPUAdamBuilder, AsyncIOBuilder)}
