"""Generic async prefetch/swap engine (ISSUE 16 tentpole; ISSUE 18
storage integrity).

The reference's ZeRO-Infinity moves bytes through one shape
(PAPER.md §1 layers 0/5, ``zero/partitioned_param_swapper.py`` over
``csrc/aio``): a double-buffered swap pipeline that overlaps device
compute with tier I/O.  :class:`SwapEngine` is that shape made
model-agnostic: a key-addressed payload store with a **host-RAM tier**
(plain pinned numpy buffers — on TPU hosts all anonymous memory is
effectively pinned for the runtime's DMA path) in front of an **NVMe
tier** (one payload file per key through ``ops/aio`` — io_uring queue
depth when the kernel allows it, thread pool otherwise).

Clients and contracts:

- the first client is the serving side's tiered KV cache
  (``serving/kv_tiering.py`` — refcount-0 prefix blocks demote
  HBM→host→NVMe instead of evicting); param shards and optimizer state
  ride the SAME engine (``offload/param_store.py``).
- payloads are lists of numpy arrays (one per pytree leaf); NVMe
  serialization is the raw concatenated bytes with shapes/dtypes held
  host-side, so a swap round-trip is bit-exact by construction (int8
  KV included) — the tier-parity guarantee rests on this.
- reads and writes ride SEPARATE :class:`AsyncIOHandle` instances
  (separate rings/pools) for the same reason the tensor swapper does:
  a prefetch read must bypass the write backlog
  (``runtime/swap_tensor/swapper.py``).
- writes are fire-and-forget with per-key write→read ordering; reads
  are ``prefetch`` (submit) / ``fetch`` (complete), so the caller can
  overlap materialization with its own compute — the double-buffered
  in-flight window is capped at ``queue_depth`` outstanding requests
  per direction.
- every completed request reports its BACKEND-measured
  submit→completion window through the process-wide IoStat
  (``swap/*`` histograms, achieved bandwidth vs the ``DS_NVME_GBPS``
  floor) — the PR 14 observatory prices every byte this engine moves.
- tier bytes are ledger-exact: the engine owns one memory-ledger row
  per tier (``host``/``nvme``) and per owner label — ``put`` takes a
  per-key ``owner`` so a SHARED engine (param shards + optimizer
  state on one queue-depth budget, ISSUE 17) attributes each client's
  bytes separately.
- ``fetch(key, keep=True)`` is the read-only mode: the entry and its
  payload file stay valid, so a client holding a resident working set
  (the ParamStore's K layers) evicts clean copies for free.

Storage integrity (ISSUE 18) — NVMe is fallible media, and a
same-size bit-flip sails through the byte-count torn check:

- **checksums**: every payload's crc32 is computed at swap-out and
  stored host-side; ``fetch`` verifies it on BOTH tiers before any
  byte can reach a consumer.  A mismatch raises the typed
  :class:`CorruptPayloadError`, quarantines the key (the corrupt copy
  is dropped and can never re-attach; a fresh ``put`` of the key —
  e.g. the ParamStore's heal-back — clears the quarantine record),
  counts in ``offload/integrity_fail``, and records an
  ``offload/corrupt`` flight event.  ``verify_fetch=False`` is the
  hot-path escape hatch (checksums still stored, verification
  skipped) if the measured tax matters.
- **retry/backoff**: aio submission and reaping route through
  ``resilience/retry.retry_call`` — a transient backend error
  resubmits synchronously from a retained source with bounded
  backoff; only post-retry verdicts count as failures.
- **tier circuit breaker**: terminal I/O outcomes feed a per-tier
  :class:`~deepspeed_tpu.offload.breaker.TierBreaker`.  OPEN refuses
  new NVMe reads fast (the entry is RETAINED — the media may heal)
  and lets write-side clients stop demoting (``nvme_allowed()``);
  HALF_OPEN probes with real traffic.
- **retain-until-durable**: a fire-and-forget write's pristine
  serialized source is retained until the write reaps OK; a terminal
  write failure REVERTS the entry to the host tier from that source
  (``offload/write_reverts``) — a failed demotion can never have
  consumed the only copy.
- the ``swap.io`` fault site fires in the submit/reap paths (deny =
  backend I/O failure; corrupt = bit-flip between checksum and disk),
  so the whole ladder is chaos-testable without a failing drive.

The engine remains policy-free about *meaning* (no eviction
heuristics, no knowledge of what a key holds); integrity is mechanism,
and the per-client degrade policy (re-prefill vs master rebuild)
stays in the clients.
"""
import os
import tempfile
import time
import weakref
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import numpy as np

from deepspeed_tpu.offload.breaker import STATE_OPEN, TierBreaker
from deepspeed_tpu.resilience.faults import NULL_INJECTOR, flip_bytes

__all__ = ["SwapEngine", "TIERS", "CorruptPayloadError", "live_engines"]

#: engine tiers, warm to cold (the device tier stays with the client —
#: the engine only ever holds spilled copies)
TIERS = ("host", "nvme")

#: quarantine ring bound: corrupt-key forensics, not a second cache
_QUARANTINE_CAP = 64

#: live engines for the ``/debug/offload`` surface (weak: an engine
#: that closes or goes out of scope drops off the view)
_LIVE_ENGINES = weakref.WeakSet()


def live_engines() -> list:
    """Engines alive in this process, oldest construction first
    (best-effort ordering: WeakSet iteration order is arbitrary, so
    sort by the monotonic construction stamp)."""
    return sorted(_LIVE_ENGINES, key=lambda e: e._born)


class CorruptPayloadError(IOError):
    """A payload's stored checksum did not match the fetched bytes.

    Subclasses IOError so every existing client degrade path (KV →
    discard + re-prefill, params → synchronous master rebuild) already
    catches it; typed so tests and chaos cases can assert corruption
    was *detected*, not absorbed."""

    def __init__(self, key: str, tier: str, expected: int, actual: int):
        super().__init__(
            f"corrupt offload payload for {key} ({tier} tier): "
            f"crc32 {actual:#010x} != stored {expected:#010x} — "
            "quarantined, never attached")
        self.key = key
        self.tier = tier
        self.expected = expected
        self.actual = actual


class _Entry:
    """One key's residency: exactly one tier at a time."""
    __slots__ = ("tier", "meta", "arrays", "nbytes", "disk_nbytes",
                 "owner", "crc")

    def __init__(self, tier: str, meta, arrays, nbytes: int,
                 disk_nbytes: int = 0, owner: Optional[str] = None,
                 crc: Optional[int] = None):
        self.tier = tier
        self.meta = meta          # [(shape, dtype, nbytes), ...] per leaf
        self.arrays = arrays      # host tier: the payload; nvme: None
        self.nbytes = nbytes      # true payload bytes
        self.disk_nbytes = disk_nbytes   # bytes actually on disk (nvme)
        self.owner = owner        # ledger attribution for this key
        self.crc = crc            # crc32 of the true payload (or None)


class SwapEngine:
    """Key-addressed host-RAM + NVMe payload store with async swap I/O.

    Single-threaded by contract: callers (the serving scheduler, the
    offload runtime) already serialize access under their own lock, and
    the aio handles below carry per-request state that must not
    interleave.

    ``integrity`` is any object carrying the ``resilience.offload``
    config fields (``runtime/config.py OffloadIntegrityConfig``);
    ``None`` takes every default.  ``injector`` arms the ``swap.io``
    fault site inside the submit/reap paths.
    """

    def __init__(self, nvme_dir: Optional[str] = None, owner: str = "offload",
                 aio_threads: int = 2, queue_depth: int = 2,
                 injector=None, integrity=None):
        self._owned_dir = nvme_dir is None
        self.nvme_dir = nvme_dir or tempfile.mkdtemp(prefix="ds_offload_")
        os.makedirs(self.nvme_dir, exist_ok=True)
        self.owner = owner
        self.queue_depth = max(1, int(queue_depth))
        self._aio_threads = max(1, int(aio_threads))
        self.injector = injector or NULL_INJECTOR
        # --- integrity policy (ISSUE 18): checksum + retry + breaker
        self.checksums = bool(getattr(integrity, "checksums", True))
        self.verify_fetch = bool(getattr(integrity, "verify_fetch", True))
        self._retry_kw = dict(
            attempts=int(getattr(integrity, "retry_attempts", 3)),
            base_delay_s=float(getattr(integrity, "retry_base_delay_s",
                                       0.002)),
            max_delay_s=float(getattr(integrity, "retry_max_delay_s",
                                      0.05)),
            deadline_s=getattr(integrity, "retry_deadline_s", None))
        self._breaker = TierBreaker(
            "nvme",
            window=int(getattr(integrity, "breaker_window", 16)),
            error_rate=float(getattr(integrity, "breaker_error_rate", 0.5)),
            min_ops=int(getattr(integrity, "breaker_min_ops", 4)),
            cooldown_s=float(getattr(integrity, "breaker_cooldown_s",
                                     30.0)),
            probes=int(getattr(integrity, "breaker_probes", 1)))
        # lazy: host-only configurations never pay for the aio rings
        self._aio_r = None
        self._aio_w = None
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._inflight_reads: Dict[str, tuple] = {}   # key -> (rid, buf)
        self._inflight_writes: Dict[str, int] = {}    # key -> write id
        #: retain-until-durable (ISSUE 18): key -> the PRISTINE
        #: serialized payload of an in-flight write.  Released only
        #: when the write reaps OK; a terminal write failure reverts
        #: the entry to the host tier from this copy, so a failed
        #: fire-and-forget demotion never consumed the only copy.
        self._pending_writes: Dict[str, np.ndarray] = {}
        #: corrupt-key forensics ring: key -> {tier, reason, unix}.
        #: A quarantined key's payload was dropped before any consumer
        #: saw it; a fresh put() of the key (heal-back) clears the row.
        self._quarantine: "OrderedDict[str, dict]" = OrderedDict()
        self.integrity_failures = 0   # checksum mismatches detected
        self.write_reverts = 0        # failed writes reverted to host
        self.io_failures = 0          # terminal (post-retry) aio failures
        self._tier_bytes = {"host": 0, "nvme": 0}
        self._tier_count = {"host": 0, "nvme": 0}
        # per-(tier, owner) attribution: one SHARED engine can serve
        # several clients (param shards + optimizer state on one
        # queue-depth budget) with each client's bytes on its own
        # ledger row (the ISSUE 17 ``params_nvme`` contract)
        self._owner_bytes: Dict[tuple, int] = {}
        self._owner_count: Dict[tuple, int] = {}
        self._owners = {self.owner}
        self._born = time.monotonic()
        _LIVE_ENGINES.add(self)
        # arm the process-wide aio observation sink (idempotent)
        try:
            from deepspeed_tpu.telemetry.iostat import get_iostat
            get_iostat()
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"offload iostat arming failed ({e}); swapping "
                         "continues unobserved")

    # ------------------------------------------------------------ plumbing
    def _rings(self):
        if self._aio_r is None:
            from deepspeed_tpu.ops.aio import AsyncIOHandle
            # separate read/write handles: the prefetch read must not
            # queue behind a ring full of writeback-throttled writes
            self._aio_r = AsyncIOHandle(thread_count=self._aio_threads)
            self._aio_w = AsyncIOHandle(thread_count=self._aio_threads)
        return self._aio_r, self._aio_w

    def _path(self, key: str) -> str:
        return os.path.join(self.nvme_dir,
                            key.replace("/", "_") + ".pay")

    def _account(self):
        """Ledger tap: this engine's per-tier bytes, one row per owner
        label (best-effort — accounting never fails a swap)."""
        try:
            from deepspeed_tpu.telemetry.memory import (get_memory_ledger,
                                                        memory_enabled)
            if memory_enabled():
                led = get_memory_ledger()
                for owner in self._owners:
                    led.set_bytes(
                        "host", owner,
                        self._owner_bytes.get(("host", owner), 0),
                        entries=self._owner_count.get(("host", owner), 0))
                    led.set_bytes(
                        "nvme", owner,
                        self._owner_bytes.get(("nvme", owner), 0),
                        entries=self._owner_count.get(("nvme", owner), 0),
                        dir=self.nvme_dir)
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"offload ledger accounting failed ({e})")

    def _flight(self, kind: str, **fields):
        """Best-effort flight event through the process-wide recorder
        (the engine sits below the clients that carry one)."""
        try:
            from deepspeed_tpu.telemetry.flight_recorder import \
                get_flight_recorder
            get_flight_recorder().record(kind, **fields)
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"offload flight event failed ({e})")

    def _add(self, key: str, entry: _Entry):
        self._entries[key] = entry
        nbytes = (entry.disk_nbytes if entry.tier == "nvme"
                  else entry.nbytes)
        self._tier_count[entry.tier] += 1
        self._tier_bytes[entry.tier] += nbytes
        owner = entry.owner or self.owner
        self._owners.add(owner)
        ok = (entry.tier, owner)
        self._owner_count[ok] = self._owner_count.get(ok, 0) + 1
        self._owner_bytes[ok] = self._owner_bytes.get(ok, 0) + nbytes

    def _remove(self, key: str) -> Optional[_Entry]:
        entry = self._entries.pop(key, None)
        if entry is not None:
            nbytes = (entry.disk_nbytes if entry.tier == "nvme"
                      else entry.nbytes)
            self._tier_count[entry.tier] -= 1
            self._tier_bytes[entry.tier] -= nbytes
            ok = (entry.tier, entry.owner or self.owner)
            self._owner_count[ok] = self._owner_count.get(ok, 0) - 1
            self._owner_bytes[ok] = self._owner_bytes.get(ok, 0) - nbytes
        return entry

    # ----------------------------------------------------- integrity core
    def _record_io_failure(self, key: str, direction: str):
        self.io_failures += 1
        self._breaker.record(False)
        try:
            from deepspeed_tpu.telemetry import get_registry
            get_registry().inc("offload/io_failures", dir=direction)
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"offload io-failure telemetry failed ({e})")

    def _quarantine_key(self, key: str, entry: _Entry, actual: int):
        """Checksum mismatch: drop the corrupt copy (it can never
        re-attach), record the key in the bounded quarantine ring, and
        surface the typed error to the caller's degrade path."""
        self._remove(key)
        if entry.tier == "nvme":
            try:
                os.remove(self._path(key))
            except OSError:
                pass
        self._quarantine[key] = {"tier": entry.tier,
                                 "reason": "crc_mismatch",
                                 "unix": round(time.time(), 3)}
        while len(self._quarantine) > _QUARANTINE_CAP:
            self._quarantine.popitem(last=False)
        self.integrity_failures += 1
        try:
            from deepspeed_tpu.telemetry import get_registry
            get_registry().inc("offload/integrity_fail", tier=entry.tier)
            get_registry().set_gauge("offload/quarantined",
                                     float(len(self._quarantine)))
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"offload integrity telemetry failed ({e})")
        self._flight("offload/corrupt", key=key, tier=entry.tier,
                     owner=entry.owner or self.owner)
        self._account()
        raise CorruptPayloadError(key, entry.tier, entry.crc or 0, actual)

    @staticmethod
    def _crc_arrays(arrays: Sequence[np.ndarray]) -> int:
        crc = 0
        for a in arrays:
            crc = zlib.crc32(np.ascontiguousarray(a).view(np.uint8)
                             .reshape(-1), crc)
        return crc

    def _sync_write(self, buf: np.ndarray, path: str, key: str):
        """One synchronous write attempt (the retry body): submit +
        reap; an injected swap.io deny models a backend failure."""
        _, aio_w = self._rings()
        rid = aio_w.submit_pwrite(buf, path)
        if aio_w.wait_req(rid) == -1 or self.injector.deny("swap.io"):
            raise IOError(f"offload write retry failed for {key}")

    def _sync_read(self, buf: np.ndarray, key: str):
        """One synchronous read attempt (the retry body)."""
        aio_r, _ = self._rings()
        rid = aio_r.submit_pread(buf, self._path(key))
        if aio_r.wait_req(rid) == -1 or self.injector.deny("swap.io"):
            raise IOError(f"offload read retry failed for {key}")

    def _retry(self, fn, *args, describe: str):
        from deepspeed_tpu.resilience.retry import retry_call
        retry_call(fn, *args, retry_on=(OSError,), describe=describe,
                   **self._retry_kw)

    def _revert_to_host(self, key: str, entry: _Entry, src: np.ndarray):
        """Durability ordering: the write never became durable, but the
        pristine serialized source was retained — rebuild the host-tier
        entry from it.  The key survives; only the demotion failed."""
        self._remove(key)
        try:
            os.remove(self._path(key))
        except OSError:
            pass
        buf = src.copy()         # writable: host arrays may be stepped
        arrays, off = [], 0
        for shape, dtype, n in entry.meta:
            arrays.append(buf[off:off + n].view(dtype).reshape(shape))
            off += n
        self._add(key, _Entry("host", entry.meta, arrays, entry.nbytes,
                              owner=entry.owner, crc=entry.crc))
        self.write_reverts += 1
        try:
            from deepspeed_tpu.telemetry import get_registry
            get_registry().inc("offload/write_reverts")
        except Exception as e:
            from deepspeed_tpu.utils.logging import logger
            logger.debug(f"offload revert telemetry failed ({e})")
        self._flight("offload/write_revert", key=key,
                     owner=entry.owner or self.owner, bytes=entry.nbytes)
        self._account()

    # ------------------------------------------------------------- windows
    def _wait_write(self, key: str, revert: bool = True):
        """Reap one in-flight write.  A backend failure retries from
        the retained pristine source; a terminal failure feeds the
        breaker and — when the entry is still NVMe-resident — reverts
        it to the host tier instead of raising (the bytes survive).
        Raises IOError only when no source remains to recover from."""
        wid = self._inflight_writes.pop(key, None)
        if wid is None:
            return
        src = self._pending_writes.pop(key, None)
        _, aio_w = self._rings()
        failed = aio_w.wait_req(wid) == -1
        if self.injector.deny("swap.io"):
            failed = True
        if not failed:
            self._breaker.record(True)
            return
        if src is not None:
            try:
                self._retry(self._sync_write, src, self._path(key), key,
                            describe=f"offload write {key}")
                self._breaker.record(True)
                return
            except OSError:
                pass
        self._record_io_failure(key, "write")
        entry = self._entries.get(key)
        if revert and src is not None and entry is not None \
                and entry.tier == "nvme":
            self._revert_to_host(key, entry, src)
            return
        raise IOError(f"offload write failed for {key}")

    def _window_gate(self, inflight: Dict):
        """The double-buffering window: beyond ``queue_depth``
        outstanding requests in one direction, reap the oldest before
        submitting another (bounds pinned buffers AND keeps the ring a
        rolling window instead of an unbounded backlog).

        Read entries carry a sentinel rid after reaping: > 0 in flight,
        0 materialized OK (the buffer is just host cache now), -1 the
        backend reported failure (fetch must surface it, never the
        buffer)."""
        if inflight is self._inflight_writes:
            while len(inflight) >= self.queue_depth:
                self._wait_write(next(iter(inflight)))
            return
        while True:
            live = [k for k, (rid, _) in inflight.items() if rid > 0]
            if len(live) < self.queue_depth:
                return
            key = live[0]
            rid, buf = inflight.pop(key)
            aio_r, _ = self._rings()
            failed = aio_r.wait_req(rid) == -1
            if self.injector.deny("swap.io"):
                failed = True
            inflight[key] = (-1, None) if failed else (0, buf)

    def _write_nvme(self, key: str, arrays: Sequence[np.ndarray],
                    nbytes: int, truncate: Optional[int],
                    corrupt: Optional[int] = None,
                    crc: Optional[int] = None) -> tuple:
        """Serialize + submit the async write; returns (on-disk bytes,
        payload crc).  The crc is computed (or carried through on a
        tier move) BEFORE any injected damage: ``truncate``/``corrupt``
        model what bad media does to bytes already checksummed."""
        self._wait_write(key)            # same-key writes must not race
        self._window_gate(self._inflight_writes)
        payload = b"".join(np.ascontiguousarray(a).tobytes()
                           for a in arrays)
        if crc is None and self.checksums:
            crc = zlib.crc32(payload)
        src = np.frombuffer(payload, dtype=np.uint8)
        wbuf = src
        flips = max(corrupt or 0,
                    self.injector.corrupt_bytes("swap.io", nbytes) or 0)
        if flips:
            wbuf = src.copy()
            flip_bytes(wbuf, flips)
        disk = nbytes
        if truncate is not None and truncate < nbytes:
            wbuf = wbuf[:max(0, truncate)].copy()
            disk = int(wbuf.nbytes)
        path = self._path(key)
        # a shrinking rewrite must not leave stale tail bytes that make
        # a torn payload look whole
        if os.path.exists(path) and os.path.getsize(path) > disk:
            os.truncate(path, 0)
        if disk:
            _, aio_w = self._rings()
            from deepspeed_tpu.resilience.retry import retry_call
            self._inflight_writes[key] = retry_call(
                aio_w.submit_pwrite, wbuf, path, retry_on=(OSError,),
                describe=f"offload submit {key}", **self._retry_kw)
            # retained until the write reaps OK (pristine, full-length:
            # the revert source even under an injected torn write)
            self._pending_writes[key] = src
        else:
            open(path, "wb").close()
        return disk, crc

    # -------------------------------------------------------------- writes
    def put(self, key: str, arrays: Sequence[np.ndarray],
            tier: str = "host", truncate: Optional[int] = None,
            owner: Optional[str] = None,
            corrupt: Optional[int] = None) -> int:
        """Store a payload (replacing any tier's prior copy).  Host puts
        keep the arrays; nvme puts serialize and fire-and-forget the
        write.  ``truncate`` (fault injection) caps the bytes that reach
        disk — ``fetch`` of a torn payload fails cleanly.  ``corrupt``
        (fault injection) bit-flips that many payload bytes AFTER the
        checksum is computed — size-preserving damage only the checksum
        can see.  ``owner`` attributes THIS key's bytes to a ledger row
        other than the engine default (shared-engine clients).  A fresh
        put clears the key's quarantine record (the heal-back path
        stores known-good bytes).  Returns the payload's byte size."""
        assert tier in TIERS, tier
        self.discard(key)
        if self._quarantine.pop(key, None) is not None:
            try:
                from deepspeed_tpu.telemetry import get_registry
                get_registry().set_gauge("offload/quarantined",
                                         float(len(self._quarantine)))
            except Exception as e:
                from deepspeed_tpu.utils.logging import logger
                logger.debug(f"offload quarantine gauge failed ({e})")
        meta = [(a.shape, a.dtype, int(a.nbytes)) for a in arrays]
        nbytes = sum(m[2] for m in meta)
        if tier == "host":
            host = [np.ascontiguousarray(a) for a in arrays]
            crc = self._crc_arrays(host) if self.checksums else None
            if corrupt:
                # flip IN the stored copy (post-checksum, like media
                # damage): the host-tier fetch verify must catch it.
                # Callers hand live (often read-only) KV views — damage
                # a private copy, never the caller's buffer.
                for i, a in enumerate(host):
                    if a.nbytes:
                        damaged = a.copy()
                        flip_bytes(damaged.view(np.uint8).reshape(-1),
                                   corrupt)
                        host[i] = damaged
                        break
            self._add(key, _Entry("host", meta, host, nbytes,
                                  owner=owner, crc=crc))
        else:
            disk, crc = self._write_nvme(key, arrays, nbytes, truncate,
                                         corrupt=corrupt)
            self._add(key, _Entry("nvme", meta, None, nbytes,
                                  disk_nbytes=disk, owner=owner, crc=crc))
        self._account()
        return nbytes

    def demote(self, key: str, truncate: Optional[int] = None,
               corrupt: Optional[int] = None) -> int:
        """Move a host-tier payload to the NVMe tier (the host→NVMe leg
        of the spill waterfall).  The entry's stored crc travels with it
        (NOT recomputed: corruption picked up while host-resident must
        stay detectable after the tier move).  Returns the payload's
        byte size."""
        entry = self._entries.get(key)
        if entry is None or entry.tier != "host":
            raise KeyError(f"{key} is not host-resident")
        self._remove(key)
        disk, crc = self._write_nvme(key, entry.arrays, entry.nbytes,
                                     truncate, corrupt=corrupt,
                                     crc=entry.crc)
        self._add(key, _Entry("nvme", entry.meta, None, entry.nbytes,
                              disk_nbytes=disk, owner=entry.owner,
                              crc=crc))
        self._account()
        return entry.nbytes

    # --------------------------------------------------------------- reads
    def _submit_read(self, key: str, entry: _Entry):
        self._wait_write(key)            # write→read ordering, this key only
        self._window_gate(self._inflight_reads)
        buf = np.empty(entry.nbytes, dtype=np.uint8)
        aio_r, _ = self._rings()
        from deepspeed_tpu.resilience.retry import retry_call
        rid = retry_call(aio_r.submit_pread, buf, self._path(key),
                         retry_on=(OSError,),
                         describe=f"offload submit {key}",
                         **self._retry_kw)
        self._inflight_reads[key] = (rid, buf)

    def prefetch(self, key: str):
        """Submit the async read for an NVMe payload (no-op for host
        payloads, unknown keys, in-flight reads, torn payloads, and
        while the tier breaker is OPEN — fetch() is where failures and
        half-open probes surface)."""
        entry = self._entries.get(key)
        if (entry is None or entry.tier != "nvme"
                or key in self._inflight_reads
                or entry.disk_nbytes != entry.nbytes
                or self._breaker.state == STATE_OPEN):
            return
        self._submit_read(key, entry)

    def fetch(self, key: str, keep: bool = False) -> List[np.ndarray]:
        """Complete the swap-in.  By default the entry is CONSUMED (the
        caller now owns the only copy — a key is never resident in two
        tiers); with ``keep=True`` the entry AND its payload file stay
        valid, so a read-only caller (param shards, fp32 masters) can
        drop its copy later without a write-back.  Raises KeyError for
        unknown keys, IOError for torn payloads, failed reads, or a
        breaker-refused NVMe read (entry RETAINED — the media may
        heal), and :class:`CorruptPayloadError` for checksum
        mismatches (entry quarantined); on torn/failed/corrupt the
        entry is dropped even under ``keep`` so a degraded caller
        cannot re-attach bad bytes."""
        entry = self._entries.get(key)
        if entry is None:
            raise KeyError(f"{key} is not tier-resident")
        if entry.tier == "host":
            if self.checksums and self.verify_fetch \
                    and entry.crc is not None:
                actual = self._crc_arrays(entry.arrays)
                if actual != entry.crc:
                    self._quarantine_key(key, entry, actual)
            if keep:
                return [np.array(a, copy=True) for a in entry.arrays]
            self._remove(key)
            self._account()
            return entry.arrays
        if entry.disk_nbytes != entry.nbytes:
            self.discard(key)
            raise IOError(f"torn offload payload for {key} "
                          f"({entry.disk_nbytes}/{entry.nbytes} bytes)")
        if key not in self._inflight_reads:
            # new read traffic consults the breaker: OPEN fails fast
            # WITHOUT discarding (the on-disk bytes may be fine — the
            # tier is sick, not the payload); HALF_OPEN admits this
            # fetch as a real-traffic probe
            if not self._breaker.allow():
                raise IOError(f"nvme tier circuit {self._breaker.state}; "
                              f"offload read refused for {key}")
            self._submit_read(key, entry)
        rid, buf = self._inflight_reads.pop(key)
        failed = rid < 0
        if rid > 0:
            aio_r, _ = self._rings()
            failed = aio_r.wait_req(rid) == -1
            if self.injector.deny("swap.io"):
                failed = True
        if failed:
            if buf is None:
                buf = np.empty(entry.nbytes, dtype=np.uint8)
            try:
                self._retry(self._sync_read, buf, key,
                            describe=f"offload read {key}")
                failed = False
            except OSError:
                pass
        if failed:
            self._record_io_failure(key, "read")
            self.discard(key)
            raise IOError(f"offload read failed for {key}")
        self._breaker.record(True)
        flips = self.injector.corrupt_bytes("swap.io", entry.nbytes)
        if flips:
            # phase 1: a write+read corrupt storm must damage DIFFERENT
            # bytes, not XOR the write-side flips back off
            flip_bytes(buf, flips, phase=1)
        if self.checksums and self.verify_fetch and entry.crc is not None:
            actual = zlib.crc32(buf)
            if actual != entry.crc:
                self._quarantine_key(key, entry, actual)
        if not keep:
            self._remove(key)
            self._account()
            try:
                os.remove(self._path(key))
            except OSError:
                pass
        out, off = [], 0
        for shape, dtype, n in entry.meta:
            # writable zero-copy views of the read buffer (the buffer is
            # not retained): the host optimizer steps these in place
            out.append(buf[off:off + n].view(dtype).reshape(shape))
            off += n
        return out

    # ------------------------------------------------------------- readers
    def tier_of(self, key: str) -> Optional[str]:
        entry = self._entries.get(key)
        return entry.tier if entry is not None else None

    def nbytes_of(self, key: str) -> int:
        entry = self._entries.get(key)
        return entry.nbytes if entry is not None else 0

    def keys(self, tier: Optional[str] = None):
        """Keys in insertion (oldest-first) order, optionally one tier."""
        if tier is None:
            return list(self._entries)
        return [k for k, e in self._entries.items() if e.tier == tier]

    def tiers(self) -> Dict[str, str]:
        """key -> tier snapshot (the invariant / digest view)."""
        return {k: e.tier for k, e in self._entries.items()}

    def oldest(self, tier: str) -> Optional[str]:
        for k, e in self._entries.items():
            if e.tier == tier:
                return k
        return None

    def count(self, tier: str) -> int:
        return self._tier_count[tier]

    def bytes(self, tier: str) -> int:
        return self._tier_bytes[tier]

    def inflight_reads(self):
        return set(self._inflight_reads)

    def inflight(self) -> int:
        return len(self._inflight_reads) + len(self._inflight_writes)

    # --------------------------------------------------- integrity readers
    def nvme_allowed(self) -> bool:
        """Write-side breaker gate for policy clients: False while the
        NVMe tier's breaker refuses traffic — demotions should fall
        back to the host-only/evict waterfall.  In HALF_OPEN each True
        admits one real-traffic probe."""
        return self._breaker.allow()

    def breaker(self) -> TierBreaker:
        return self._breaker

    def quarantined(self) -> Dict[str, dict]:
        """Quarantine ring snapshot (key -> tier/reason/unix)."""
        return dict(self._quarantine)

    def snapshot(self) -> dict:
        """Live integrity + occupancy state for ``/debug/offload`` and
        post-mortem bundles (dict reads only — safe while wedged)."""
        return {
            "owner": self.owner,
            "nvme_dir": self.nvme_dir,
            "checksums": self.checksums,
            "verify_fetch": self.verify_fetch,
            "tiers": {t: {"entries": self._tier_count[t],
                          "bytes": self._tier_bytes[t]} for t in TIERS},
            "inflight_reads": len(self._inflight_reads),
            "inflight_writes": len(self._inflight_writes),
            "retained_write_sources": len(self._pending_writes),
            "integrity_failures": self.integrity_failures,
            "write_reverts": self.write_reverts,
            "io_failures": self.io_failures,
            "quarantine": dict(self._quarantine),
            "breaker": self._breaker.snapshot(),
        }

    # ------------------------------------------------------------ lifetime
    def discard(self, key: str):
        """Drop a key from whichever tier holds it (true eviction)."""
        if key in self._inflight_reads:
            rid, _ = self._inflight_reads.pop(key)
            if rid > 0:
                aio_r, _ = self._rings()
                aio_r.wait_req(rid)      # unpin; result irrelevant
        try:
            # no revert: the caller is dropping the key either way
            self._wait_write(key, revert=False)
        except IOError:
            pass                         # discarding anyway
        self._pending_writes.pop(key, None)
        entry = self._remove(key)
        if entry is not None:
            if entry.tier == "nvme":
                try:
                    os.remove(self._path(key))
                except OSError:
                    pass
            self._account()

    def drain(self):
        """Complete all in-flight I/O (one ``window=drain`` IoStat
        sample per direction); raises if any READ request failed.
        Writes reap individually first so a failed one still reverts
        its entry to the host tier instead of losing the only copy."""
        for key in list(self._inflight_writes):
            self._wait_write(key)
        self._inflight_reads.clear()
        self._pending_writes.clear()
        errors = 0
        if self._aio_r is not None:
            errors = self._aio_r.wait() + self._aio_w.wait()
        if errors:
            raise IOError(f"{errors} offload aio requests failed")

    def close(self):
        """Drain (best-effort) and delete this engine's payload files
        (and its temp dir when it created one)."""
        try:
            self.drain()
        except IOError:
            pass
        for key in list(self._entries):
            self._remove(key)
        self._account()
        _LIVE_ENGINES.discard(self)
        try:
            for name in os.listdir(self.nvme_dir):
                if name.endswith(".pay"):
                    os.remove(os.path.join(self.nvme_dir, name))
            if self._owned_dir:
                os.rmdir(self.nvme_dir)
        except OSError:
            pass

    def __del__(self):
        try:
            self.close()
        # dslint: disable=DSL005 -- interpreter-teardown __del__: the aio
        # lib may already be unloaded; leaking a temp file beats raising
        except Exception:
            pass
