"""ds_qgemm block-shape sweep (ISSUE 2 satellite) — the ds_flash_attention
tuning playbook applied to the fused-dequant int8 GEMM: on-chip A/B over
TPU-legal (bm, bk, bn) tile shapes at the serving-relevant GEMM shapes
(decode M = batch, K/N = the model's projection dims), slope-timed per the
PERF.md tunnel discipline (on-device fori_loop chains; only slopes between
step counts are trustworthy — a blocking round trip costs ~100 ms).

    python scripts/qgemm_sweep.py                     # gpt2-1.3b shapes
    QGEMM_M=8 QGEMM_SHAPES=4096x11008 python scripts/qgemm_sweep.py
    QGEMM_SWEEP_SMOKE=1 python scripts/qgemm_sweep.py # CPU plumbing smoke

Prints one JSON line per (shape, blocks) with the per-call slope in µs and
the achieved int8 weight-stream GB/s, then the winner per shape.  Off-TPU
(smoke) it runs tiny interpret-mode shapes — plumbing only, no timing
claims.
"""
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


from scripts.bench_util import timed_chain


def main():
    from deepspeed_tpu.ops.pallas.qgemm import ds_qgemm
    from deepspeed_tpu.ops.pallas.quantization import block_quantize_int8

    smoke = bool(int(os.environ.get("QGEMM_SWEEP_SMOKE", "0")))
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    if smoke or not on_tpu:
        shapes = [(64, 128)]
        M = 4
        grid = [(8, 64, 128)]
        steps = 2
        interpret = True
        dtype = jnp.float32
    else:
        # gpt2-1.3b decode GEMMs by default: QKV [2048, 6144], proj
        # [2048, 2048], MLP [2048, 8192] / [8192, 2048]
        env = os.environ.get("QGEMM_SHAPES",
                             "2048x6144,2048x2048,2048x8192,8192x2048")
        shapes = [tuple(int(v) for v in s.split("x"))
                  for s in env.split(",")]
        M = int(os.environ.get("QGEMM_M", 4))
        bms = [8, 16, 32, 128]
        bks = [256, 512, 1024]
        bns = [256, 512, 1024, 2048]
        grid = list(itertools.product(bms, bks, bns))
        steps = int(os.environ.get("QGEMM_STEPS", 20))
        interpret = False
        dtype = jnp.bfloat16

    rng = np.random.default_rng(0)
    for (K, N) in shapes:
        w = jnp.asarray(rng.standard_normal((K, N)), jnp.float32)
        q, s = block_quantize_int8(w)
        x0 = jnp.asarray(rng.standard_normal((M, K)), dtype)
        best = None
        seen_effective = set()
        for bm, bk, bn in grid:
            # dedup on the EFFECTIVE blocks: the wrapper clamps bm to
            # round_up(M, align), so at decode M several requested bm
            # values collapse to the same kernel — time it once and label
            # it by what actually ran
            m_align = 16 if dtype == jnp.bfloat16 else 8
            bm = min(bm, -(-M // m_align) * m_align)
            key = (bm, bk, bn)
            if key in seen_effective:
                continue
            seen_effective.add(key)

            def step(state, _bm=bm, _bk=bk, _bn=bn):
                x, acc = state
                y = ds_qgemm(x, q, s, block_m=_bm, block_k=_bk, block_n=_bn,
                             interpret=interpret)
                # data dependency so the chain cannot be elided: fold the
                # output back into a [M, K] carry
                carry = jnp.tanh(y[:, :1]) + x
                return (carry, acc + jnp.sum(y))

            try:
                # clamp at 0: sub-noise slopes (tiny smoke shapes) must
                # not report a negative time
                sec = max(timed_chain(step, (x0, jnp.float32(0)), steps),
                          0.0)
            except Exception as e:  # keep sweeping past illegal tilings
                print(json.dumps({"shape": f"{K}x{N}",
                                  "blocks": [bm, bk, bn],
                                  "error": str(e)[:200]}))
                continue
            gbs = (K * N) / sec / 1e9 if sec > 0 else None
            row = {"shape": f"{K}x{N}", "M": M, "blocks": [bm, bk, bn],
                   "us_per_call": round(sec * 1e6, 2),
                   "int8_stream_GBs": round(gbs, 1) if gbs else None}
            print(json.dumps(row))
            if sec > 0 and (best is None or sec < best[0]):
                best = (sec, row)
        if best:
            print(json.dumps({"shape": f"{K}x{N}", "winner": best[1]}))
            from scripts.bench_util import emit_ledger
            emit_ledger({"metric": f"qgemm_sweep_{K}x{N}",
                         "value": round(best[0] * 1e6, 2),
                         "unit": "us_per_call",
                         "direction": "lower_better",
                         "detail": {"blocks": str(best[1]["blocks"])}})


if __name__ == "__main__":
    main()
