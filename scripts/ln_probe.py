"""LayerNorm/residual bandwidth probe (VERDICT r4 item 2 precursor).

Measures what XLA's fusion already achieves for the LN+residual pattern
at the 760M training shape, fwd and fwd+bwd, against the HBM roofline —
decides whether a Pallas fused-LN kernel has headroom to win before one
is written (the flash-kernel A/B discipline).

    python scripts/ln_probe.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def timed_chain(step_fn, x0, n, warmup=3):
    @jax.jit
    def run(x, m):
        x = lax.fori_loop(0, m, lambda i, xx: step_fn(xx), x)
        return jnp.sum(x.astype(jnp.float32))

    jax.block_until_ready(run(x0, warmup))

    def once(m):
        t0 = time.time()
        jax.block_until_ready(run(x0, m))
        return time.time() - t0

    t_small = min(once(n), once(n))
    t_big = min(once(5 * n), once(5 * n))
    return (t_big - t_small) / (4 * n) * 1e3


def main():
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    B, S, D = (12, 1024, 1536) if on_tpu else (2, 64, 32)
    steps = int(os.environ.get("LN_STEPS", 50 if on_tpu else 2))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((B, S, D)), jnp.bfloat16)
    r = jnp.asarray(rng.standard_normal((B, S, D)), jnp.bfloat16)
    scale = jnp.ones((D,), jnp.float32)
    bias = jnp.zeros((D,), jnp.float32)

    from deepspeed_tpu.models.gpt2 import _layer_norm

    nbytes = x.size * 2
    peak = 819e9  # v5e HBM

    def ln_fwd(x):
        return _layer_norm(x, scale, bias, 1e-5)

    def resln_fwd(x):
        y = x + r
        return _layer_norm(y, scale, bias, 1e-5)

    g_ln = jax.grad(lambda x: jnp.sum(ln_fwd(x).astype(jnp.float32) ** 2))
    g_resln = jax.grad(
        lambda x: jnp.sum(resln_fwd(x).astype(jnp.float32) ** 2))

    cal = jnp.asarray(rng.standard_normal((2048, 2048)), jnp.bfloat16)
    mm_ms = timed_chain(lambda s: jnp.tanh(s @ cal), cal, steps)
    mm_tf = 2 * 2048 ** 3 / (mm_ms * 1e-3) / 1e12 if mm_ms > 0 else 0
    print(json.dumps({"calibration_tflops": round(mm_tf, 1),
                      "tensor_mb": round(nbytes / 1e6, 1),
                      "suspect": bool(on_tpu and (mm_tf <= 0 or mm_tf > 400))}))

    cases = {
        "ln_fwd": (ln_fwd, 2 * nbytes),             # read x, write y
        "resln_fwd": (resln_fwd, 3 * nbytes),       # read x,r, write y
        "ln_fwd_bwd": (lambda x: x + 1e-6 * g_ln(x).astype(x.dtype),
                       6 * nbytes),
        "resln_fwd_bwd": (lambda x: x + 1e-6 * g_resln(x).astype(x.dtype),
                          7 * nbytes),
    }
    for name, (fn, ideal_bytes) in cases.items():
        ms = timed_chain(fn, x, steps)
        ideal_ms = ideal_bytes / peak * 1e3
        print(json.dumps({
            "case": name, "ms": round(ms, 4),
            "ideal_ms": round(ideal_ms, 4),
            "xla_vs_roofline": round(ms / ideal_ms, 2) if ms > 0 else None,
        }))


if __name__ == "__main__":
    main()
