"""Tensor swapping to NVMe (reference: deepspeed/runtime/swap_tensor/
partitioned_optimizer_swapper.py + async_swapper.py:18 ``AsyncTensorSwapper``).

Each tensor gets a file under the swap directory; reads/writes go through the
async C++ I/O handle (ops/aio).  ``swap_out`` is fire-and-forget (drained
before the next access); ``swap_in`` supports prefetch-then-wait so the next
tensor's read overlaps the current tensor's compute — the reference's
double-buffered pipelined swapper (pipelined_optimizer_swapper.py).
"""
import os
from typing import Dict, Optional

import numpy as np

from deepspeed_tpu.ops.aio import AsyncIOHandle


class AsyncTensorSwapper:
    def __init__(self, swap_dir: str, aio_config=None):
        os.makedirs(swap_dir, exist_ok=True)
        self.swap_dir = swap_dir
        threads = getattr(aio_config, "thread_count", None) or 4
        self.aio = AsyncIOHandle(thread_count=threads)
        self._meta: Dict[str, tuple] = {}       # name -> (shape, dtype)
        self._inflight_reads: Dict[str, np.ndarray] = {}
        self._write_pending = False

    def _path(self, name: str) -> str:
        return os.path.join(self.swap_dir, name.replace("/", "_") + ".swp")

    def swap_out(self, name: str, array: np.ndarray):
        """Async write; buffer ownership passes to the swapper until drain."""
        self._meta[name] = (array.shape, array.dtype)
        arr = np.ascontiguousarray(array)
        rc = self.aio.async_pwrite(arr, self._path(name))
        if rc != 0:
            raise IOError(f"swap_out submit failed for {name}")
        self._write_pending = True

    def prefetch(self, name: str):
        """Start an async read; complete it with swap_in(name)."""
        if name in self._inflight_reads or name not in self._meta:
            return
        self._drain_writes()
        shape, dtype = self._meta[name]
        buf = np.empty(shape, dtype)
        rc = self.aio.async_pread(buf, self._path(name))
        if rc != 0:
            raise IOError(f"prefetch submit failed for {name}")
        self._inflight_reads[name] = buf

    def swap_in(self, name: str) -> np.ndarray:
        if name not in self._meta:
            raise KeyError(f"{name} was never swapped out")
        if name not in self._inflight_reads:
            self.prefetch(name)
        errors = self.aio.wait()
        if errors:
            raise IOError(f"{errors} aio requests failed")
        out = self._inflight_reads.pop(name)
        # other prefetches in flight were also drained by wait(); keep them
        return out

    def _drain_writes(self):
        if self._write_pending:
            errors = self.aio.wait()
            if errors:
                raise IOError(f"{errors} aio write requests failed")
            self._write_pending = False
            # wait() drains reads too; re-queue any lost prefetch buffers
            self._inflight_reads = dict(self._inflight_reads)

    def drain(self):
        errors = self.aio.wait()
        if errors:
            raise IOError(f"{errors} aio requests failed")
        self._write_pending = False
