"""Cartesian process topology (reference: deepspeed/runtime/pipe/topology.py:12
``ProcessTopology``, :232 ``PipeDataParallelTopology``, :244
``PipeModelDataParallelTopology``, :251 ``PipelineParallelGrid``).

Pure logic — on TPU the *execution* topology is the named mesh
(comm/mesh.py), but rank↔coordinate algebra is still needed by the launcher,
checkpoint naming, and grid-style user code, and is directly unit-testable.
"""
from collections import namedtuple
from itertools import product
from typing import Dict, List


class ProcessTopology:
    """Maps ranks <-> cartesian coordinates over named axes (row-major, first
    axis outermost)."""

    def __init__(self, axes: List[str], dims: List[int]):
        assert len(axes) == len(dims)
        self.axes = list(axes)
        self.dims = list(dims)
        self.ProcessCoord = namedtuple("ProcessCoord", axes)
        self.mapping: Dict = {}
        for coord in product(*[range(d) for d in dims]):
            key = dict(zip(axes, coord))
            self.mapping[self.ProcessCoord(**key)] = len(self.mapping)
        # O(1) reverse lookup (rank -> coord); world sizes reach 10^3-10^4
        # and per-rank naming (launcher, checkpoint paths) hits this per rank
        self._coords = list(self.mapping)

    def get_rank(self, **coord_kwargs) -> int:
        key = self.ProcessCoord(**coord_kwargs)
        assert key in self.mapping, f"invalid coord {coord_kwargs}"
        return self.mapping[key]

    def get_coord(self, rank: int):
        if 0 <= rank < len(self._coords):
            return self._coords[rank]
        raise ValueError(f"rank {rank} not in topology")

    def get_axis_names(self) -> List[str]:
        return self.axes

    def get_rank_repr(self, rank: int, omit_axes=("data",),
                      inner_sep: str = "_", outer_sep: str = "-") -> str:
        omit_axes = list(omit_axes)
        axes = [a for a in self.axes if a not in omit_axes]
        names = []
        coord = self.get_coord(rank)
        for ax in axes:
            names.append(f"{ax}{inner_sep}{getattr(coord, ax):02d}")
        return outer_sep.join(names)

    def get_dim(self, axis: str) -> int:
        if axis not in self.axes:
            return 0
        return self.dims[self.axes.index(axis)]

    def get_axis_comm_lists(self, axis: str) -> List[List[int]]:
        """Rank lists that vary only along ``axis`` (the reference's process
        groups for that axis)."""
        if axis not in self.axes:
            return []
        other_axes = [a for a in self.axes if a != axis]
        lists = []
        for other in product(*[range(self.get_dim(a)) for a in other_axes]):
            fixed = dict(zip(other_axes, other))
            ranks = [self.get_rank(**{axis: i, **fixed})
                     for i in range(self.get_dim(axis))]
            if len(ranks) > 1:
                lists.append(ranks)
        return lists

    def filter_match(self, **filter_kwargs) -> List[int]:
        def matches(coord):
            return all(getattr(coord, k) == v for k, v in filter_kwargs.items())
        return [self.get_rank(**coord._asdict())
                for coord in self.mapping if matches(coord)]

    def world_size(self) -> int:
        return len(self.mapping)

    def __str__(self):
        return str(self.mapping)


class PipeDataParallelTopology(ProcessTopology):
    """reference topology.py:232 — pipe × data."""

    def __init__(self, num_pp: int, num_dp: int):
        super().__init__(axes=["pipe", "data"], dims=[num_pp, num_dp])


class PipeModelDataParallelTopology(ProcessTopology):
    """reference topology.py:244 — pipe × data × model (3D)."""

    def __init__(self, num_pp: int, num_mp: int, num_dp: int):
        super().__init__(axes=["pipe", "data", "model"],
                         dims=[num_pp, num_dp, num_mp])


class PipelineParallelGrid:
    """reference topology.py:251 — axis sizes/ids for a given rank over a
    topology."""

    def __init__(self, topology: ProcessTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.world_size = topology.world_size()
        self.pipe_parallel_size = topology.get_dim("pipe") or 1
        self.data_parallel_size = topology.get_dim("data") or 1
        self.model_parallel_size = topology.get_dim("model") or 1
        coord = topology.get_coord(global_rank)
        self.stage_id = getattr(coord, "pipe", 0)
        self.data_parallel_id = getattr(coord, "data", 0)
        self.model_parallel_id = getattr(coord, "model", 0)

    def get_stage_id(self) -> int:
        return self.stage_id

    def get_data_parallel_id(self) -> int:
        return self.data_parallel_id

    def get_pipe_parallel_rank(self) -> int:
        return self.stage_id

    def get_data_parallel_rank(self) -> int:
        return self.data_parallel_id

    def get_model_parallel_rank(self) -> int:
        return self.model_parallel_id

    def is_first_stage(self) -> bool:
        return self.stage_id == 0

    def is_last_stage(self) -> bool:
        return self.stage_id == self.pipe_parallel_size - 1

    def stage_to_global(self, stage_id: int) -> int:
        coord = self._topo.get_coord(self.global_rank)
        kwargs = coord._asdict()
        kwargs["pipe"] = stage_id
        return self._topo.get_rank(**kwargs)
