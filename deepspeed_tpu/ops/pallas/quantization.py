"""Int8 block quantization kernels — the ZeRO++ quantization layer
(reference: csrc/quantization/quantize.cu + swizzled_quantize.cu, consumed by
qwZ quantized-weight all-gather and qgZ quantized gradient reduction,
partition_parameters.py:1488 / docs/_tutorials/zeropp.md:13-17).

Symmetric per-block quantization over the last dimension: each BLOCK-sized
group of lanes shares one fp32 scale (amax / 127).  The Pallas kernel tiles
rows into VMEM and emits q + scales in one pass; a jnp reference path serves
CPU meshes, odd shapes, and numeric tests.
"""
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

BLOCK = 256


def _ref_quantize(x, block=BLOCK):
    """Symmetric per-block quantization over the last dim.  When ``block``
    does not divide ``C`` the row splits into ``nb = ceil(C/block)``
    near-equal groups of width ``ceil(C/nb)`` (last group ragged) — the
    SAME shape contract as the exact-multiple path, so every consumer can
    recover the group width as ``ceil(C / scales.shape[-1])`` (see
    ``block_dequantize_int8``; the pre-fix fallback collapsed to ONE
    whole-row group, which both coarsened the scales and made the group
    width unrecoverable from the shapes)."""
    *lead, C = x.shape
    nb = -(-C // block)
    gw = -(-C // nb)                    # effective group width
    pad = nb * gw - C
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * len(lead) + [(0, pad)])
    xb = xf.reshape(*lead, nb, gw)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xb / scale), -127, 127).astype(jnp.int8)
    return (q.reshape(*lead, nb * gw)[..., :C],
            scale[..., 0].reshape(*lead, nb))


def _ref_dequantize(q, scales):
    *lead, C = q.shape
    nb = scales.shape[-1]
    gw = -(-C // nb)                    # group width (last may be ragged)
    pad = nb * gw - C
    qf = q.astype(jnp.float32)
    if pad:
        qf = jnp.pad(qf, [(0, 0)] * len(lead) + [(0, pad)])
    qb = qf.reshape(*lead, nb, gw)
    return (qb * scales.reshape(*lead, nb, 1)).reshape(
        *lead, nb * gw)[..., :C]


def _quant_kernel(x_ref, q_ref, s_ref, *, block):
    x = x_ref[...].astype(jnp.float32)              # [rows, C]
    rows, C = x.shape
    nb = C // block
    xb = x.reshape(rows, nb, block)
    amax = jnp.max(jnp.abs(xb), axis=-1)            # [rows, nb]
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(xb / scale[..., None]), -127, 127)
    q_ref[...] = q.reshape(rows, C).astype(jnp.int8)
    s_ref[...] = scale


def _pallas_quantize_2d(x, block=BLOCK, row_tile=256):
    """x [R, C] with C % block == 0, R % row_tile == 0."""
    from jax.experimental import pallas as pl
    R, C = x.shape
    nb = C // block
    kernel = partial(_quant_kernel, block=block)
    return pl.pallas_call(
        kernel,
        grid=(R // row_tile,),
        in_specs=[pl.BlockSpec((row_tile, C), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((row_tile, C), lambda i: (i, 0)),
                   pl.BlockSpec((row_tile, nb), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((R, C), jnp.int8),
                   jax.ShapeDtypeStruct((R, nb), jnp.float32)],
    )(x)


def block_quantize_int8(x, block=BLOCK):
    """x [..., C] -> (q int8 [..., C], scales fp32 [..., C//block])."""
    C = x.shape[-1]
    if C % block != 0:
        # ragged fallback: ceil(C/block) near-equal groups — same scales
        # shape contract as the main path (see _ref_quantize)
        return _ref_quantize(x, block=block)
    # the Pallas kernel serves eager / op-level calls; inside a traced
    # (possibly SPMD-partitioned) program the jnp reference path is used —
    # GSPMD has no partitioning rule for the pallas custom call, and XLA
    # fuses the reference elementwise chain just as well there
    traced = isinstance(x, jax.core.Tracer)
    on_tpu = jax.devices()[0].platform == "tpu"
    lead = x.shape[:-1]
    R = int(np.prod(lead)) if lead else 1
    row_tile = 256
    if on_tpu and not traced and R % row_tile == 0:
        q, s = _pallas_quantize_2d(x.reshape(R, C), block, row_tile)
        return q.reshape(*lead, C), s.reshape(*lead, C // block)
    return _ref_quantize(x, block)


def block_dequantize_int8(q, scales):
    """Inverse of ``block_quantize_int8``.  The group width is recovered
    from the shapes as ``ceil(C / nb)`` — exact for the multiple-of-block
    layout and, by construction, for the ragged fallback layout too (no
    ``block`` parameter: a caller-supplied width that disagreed with the
    layout would silently dequantize wrong)."""
    return _ref_dequantize(q, scales)
