"""Block-sparse attention kernel (Pallas/TPU, from scratch).

The TPU-native equivalent of the reference's Triton block-sparse attention
(deepspeed/ops/sparse_attention/matmul.py ``_sparse_matmul`` SDD/DSD modes +
softmax.py, driven by the `SparsityConfig` block layouts).  The reference
compiles a per-layout Triton lookup table; here the static layout becomes
**scalar-prefetched active-block index lists**, and the kernel runs a
flash-style online-softmax sweep that only ever DMAs and multiplies the
live KV blocks — masked blocks cost zero FLOPs and zero HBM traffic, so
compute scales with layout density, not S².

Layout semantics match ops/sparse_attention.py's dense block-masked path
(NEG_INF = -1e30 additive masking) — the two implementations are
numerically interchangeable, which the tests assert.

Grid: (B, H, n_q_blocks, max_active) with the KV step innermost; the KV
BlockSpec's index map reads the prefetched index list, so inactive steps
clamp to the last live block (DMA'd but skipped by ``pl.when``).
"""
import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _plan(layout: np.ndarray, causal: bool):
    """[H, nq, nk] 0/1 block layout -> (kv_idx [H, nq, max_active] int32,
    kv_cnt [H, nq] int32).  Static (numpy) — the layout is config, not data."""
    if causal:
        layout = np.tril(layout)
    H, nq, nk = layout.shape
    cnt = layout.sum(-1).astype(np.int32)                    # [H, nq]
    max_active = max(int(cnt.max()), 1)
    idx = np.zeros((H, nq, max_active), np.int32)
    for h in range(H):
        for q in range(nq):
            active = np.nonzero(layout[h, q])[0]
            idx[h, q, :len(active)] = active
            if len(active):                                   # clamp target
                idx[h, q, len(active):] = active[-1]
    return idx, cnt, max_active


def _plan_transpose(layout: np.ndarray, causal: bool):
    """Column-wise plan: for each KV block, which q blocks attend it —
    exactly ``_plan`` of the (tril'd) transposed layout.
    -> (q_idx [H, nk, max_q] int32, q_cnt [H, nk] int32)."""
    layout = np.asarray(layout)
    if causal:
        layout = np.tril(layout)
    return _plan(layout.transpose(0, 2, 1), causal=False)


def _block_scores(q_ref, k_ref, qi, kb, *, scale, causal, block):
    """Scaled (+causally masked) [BQ, BK] score tile — shared by the
    forward and both backward kernels so mask semantics cannot drift."""
    qv = q_ref[0, 0].astype(jnp.float32)
    kv = k_ref[0, 0].astype(jnp.float32)
    scores = jax.lax.dot_general(
        qv, kv, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = qi * block + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 0)
        k_pos = kb * block + jax.lax.broadcasted_iota(
            jnp.int32, scores.shape, 1)
        scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
    return qv, kv, scores


def _kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, o_ref, *rest, scale,
            causal, block, max_active, out_dtype, with_lse):
    if with_lse:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        lse_ref, (acc_ref, m_ref, l_ref) = None, rest
    import jax.experimental.pallas as pl

    h = pl.program_id(1)
    qi = pl.program_id(2)
    s = pl.program_id(3)

    @pl.when(s == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(s < cnt_ref[h, qi])
    def _step():
        kb = idx_ref[h, qi, s]
        qv, kv, scores = _block_scores(q_ref, k_ref, qi, kb, scale=scale,
                                       causal=causal, block=block)
        m_prev, l_prev = m_ref[:], l_ref[:]
        m_new = jnp.maximum(m_prev, scores.max(-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(scores - m_new)
        l_ref[:] = l_prev * alpha + p.sum(-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v_ref[0, 0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    @pl.when(s == max_active - 1)
    def _emit():
        # rows with no live blocks (fully masked) emit 0 — the flash
        # convention, shared with the dense path's row_any guard
        l = l_ref[:]
        o_ref[0, 0] = jnp.where(
            l > 0, acc_ref[:] / jnp.maximum(l, 1e-30),
            0.0).astype(out_dtype)
        if with_lse:
            # logsumexp residual for the fused backward; +inf on empty rows
            # so exp(scores - lse) = 0 and their gradients vanish
            lse_ref[0, 0] = jnp.where(
                l > 0, m_ref[:] + jnp.log(jnp.maximum(l, 1e-30)),
                jnp.inf).astype(jnp.float32)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block", "sm_scale",
                                    "interpret", "with_lse"))
def _call(q, k, v, kv_idx, kv_cnt, causal, block, sm_scale, interpret,
          with_lse=False):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    nq = S // block
    max_active = kv_idx.shape[-1]
    scale = sm_scale if sm_scale is not None else hd ** -0.5

    # _plan pads every idx row to max_active with its last live block (or 0
    # for empty rows), so the raw entry is always a safe DMA target
    kv_spec = pl.BlockSpec(
        (1, 1, block, hd),
        lambda b, h, qi, s, idx, cnt: (b, h, idx[h, qi, s], 0))
    out_specs = [pl.BlockSpec((1, 1, block, hd),
                              lambda b, h, qi, s, idx, cnt: (b, h, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct((B, H, S, hd), q.dtype)]
    if with_lse:   # residual for the fused backward; skipped inference-only
        out_specs.append(
            pl.BlockSpec((1, 1, block, 1),
                         lambda b, h, qi, s, idx, cnt: (b, h, qi, 0)))
        out_shape.append(jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nq, max_active),
        in_specs=[
            pl.BlockSpec((1, 1, block, hd),
                         lambda b, h, qi, s, idx, cnt: (b, h, qi, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((block, hd), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
            pltpu.VMEM((block, 1), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block=block,
        max_active=max_active, out_dtype=q.dtype, with_lse=with_lse)
    res = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
    )(kv_idx, kv_cnt, q, k, v)
    return res if with_lse else (res[0], None)


def _dq_kernel(idx_ref, cnt_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
               d_ref, dq_ref, acc_ref, *, scale, causal, block, max_active):
    import jax.experimental.pallas as pl

    h = pl.program_id(1)
    qi = pl.program_id(2)
    s = pl.program_id(3)

    @pl.when(s == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(s < cnt_ref[h, qi])
    def _step():
        kb = idx_ref[h, qi, s]
        qv, kv, scores = _block_scores(q_ref, k_ref, qi, kb, scale=scale,
                                       causal=causal, block=block)
        p = jnp.exp(scores - lse_ref[0, 0])                   # [BQ, BK]
        dp = jax.lax.dot_general(
            do_ref[0, 0].astype(jnp.float32), v_ref[0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - d_ref[0, 0])
        acc_ref[:] = acc_ref[:] + jax.lax.dot_general(
            ds, kv, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(s == max_active - 1)
    def _emit():
        dq_ref[0, 0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(idx_ref, cnt_ref, k_ref, v_ref, q_ref, do_ref, lse_ref,
                d_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                block, max_q):
    import jax.experimental.pallas as pl

    h = pl.program_id(1)
    kb = pl.program_id(2)
    s = pl.program_id(3)

    @pl.when(s == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(s < cnt_ref[h, kb])
    def _step():
        qi = idx_ref[h, kb, s]
        qv, kv, scores = _block_scores(q_ref, k_ref, qi, kb, scale=scale,
                                       causal=causal, block=block)
        p = jnp.exp(scores - lse_ref[0, 0])                   # [BQ, BK]
        dov = do_ref[0, 0].astype(jnp.float32)                # [BQ, hd]
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, dov, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BK, hd]
        dp = jax.lax.dot_general(
            dov, v_ref[0, 0].astype(jnp.float32),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32)
        ds = p * (dp - d_ref[0, 0])                           # [BQ, BK]
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, qv, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [BK, hd]

    @pl.when(s == max_q - 1)
    def _emit():
        dk_ref[0, 0] = (dk_acc[:] * scale).astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[:].astype(dv_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("causal", "block", "sm_scale",
                                    "interpret"))
def _bwd_call(q, k, v, do, lse, dsum, kv_idx, kv_cnt, q_idx, q_cnt,
              causal, block, sm_scale, interpret):
    """Fused backward: dQ over the forward plan, dK/dV over the transpose
    plan.  All shapes [B, H, S, hd]; lse/dsum [B, H, S, 1]."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, hd = q.shape
    nq = S // block
    max_active = kv_idx.shape[-1]
    max_q = q_idx.shape[-1]
    scale = sm_scale if sm_scale is not None else hd ** -0.5

    row_spec = pl.BlockSpec((1, 1, block, hd),
                            lambda b, h, qi, s, idx, cnt: (b, h, qi, 0))
    row1_spec = pl.BlockSpec((1, 1, block, 1),
                             lambda b, h, qi, s, idx, cnt: (b, h, qi, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, block, hd),
        lambda b, h, qi, s, idx, cnt: (b, h, idx[h, qi, s], 0))
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block=block, max_active=max_active),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nq, max_active),
            in_specs=[row_spec, kv_spec, kv_spec, row_spec, row1_spec,
                      row1_spec],
            out_specs=row_spec,
            scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
        interpret=interpret,
    )(kv_idx, kv_cnt, q, k, v, do, lse, dsum)

    # transpose plan: rows of q/do/lse/dsum come from the visited q block
    col_spec = pl.BlockSpec((1, 1, block, hd),
                            lambda b, h, kb, s, idx, cnt: (b, h, kb, 0))
    qrow_spec = pl.BlockSpec(
        (1, 1, block, hd),
        lambda b, h, kb, s, idx, cnt: (b, h, idx[h, kb, s], 0))
    qrow1_spec = pl.BlockSpec(
        (1, 1, block, 1),
        lambda b, h, kb, s, idx, cnt: (b, h, idx[h, kb, s], 0))
    nk = S // block
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block=block, max_q=max_q),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, H, nk, max_q),
            in_specs=[col_spec, col_spec, qrow_spec, qrow_spec, qrow1_spec,
                      qrow1_spec],
            out_specs=[col_spec, col_spec],
            scratch_shapes=[pltpu.VMEM((block, hd), jnp.float32),
                            pltpu.VMEM((block, hd), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((B, H, S, hd), k.dtype),
                   jax.ShapeDtypeStruct((B, H, S, hd), v.dtype)],
        interpret=interpret,
    )(q_idx, q_cnt, k, v, q, do, lse, dsum)
    return dq, dk, dv


def block_sparse_attention_trainable(q, k, v, layout: np.ndarray,
                                     causal: bool = False,
                                     sm_scale: Optional[float] = None,
                                     interpret: Optional[bool] = None):
    """Differentiable block-sparse attention: forward AND backward run the
    block-skipping Pallas kernels (flash-style — the backward recomputes
    per-block scores from the saved logsumexp instead of materialising
    [S, S] probabilities; dK/dV sweep a transposed column-wise block
    plan).  Gradients match the dense block-masked path, which the tests
    assert."""
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    interpret = bool(interpret)
    layout = np.asarray(layout)
    kv_idx, kv_cnt, _ = _plan(layout, causal)
    q_idx, q_cnt, _ = _plan_transpose(layout, causal)
    kv_idx, kv_cnt = jnp.asarray(kv_idx), jnp.asarray(kv_cnt)
    q_idx, q_cnt = jnp.asarray(q_idx), jnp.asarray(q_cnt)
    S = q.shape[1]                             # q is [B, S, H, hd] here
    assert S % layout.shape[1] == 0, (S, layout.shape)
    block = S // layout.shape[1]

    @jax.custom_vjp
    def f(q, k, v):
        out, _ = _call(q, k, v, kv_idx, kv_cnt, causal=causal, block=block,
                       sm_scale=sm_scale, interpret=interpret)
        return out

    def fwd(q, k, v):
        out, lse = _call(q, k, v, kv_idx, kv_cnt, causal=causal,
                         block=block, sm_scale=sm_scale,
                         interpret=interpret, with_lse=True)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        q, k, v, out, lse = res
        dsum = (g.astype(jnp.float32) * out.astype(jnp.float32)
                ).sum(-1, keepdims=True)
        dq, dk, dv = _bwd_call(q, k, v, g.astype(q.dtype), lse, dsum,
                               kv_idx, kv_cnt, q_idx, q_cnt, causal=causal,
                               block=block, sm_scale=sm_scale,
                               interpret=interpret)
        return dq, dk, dv

    f.defvjp(fwd, bwd)
    # kernels run in [B, H, S, hd]; the transposes sit OUTSIDE the
    # custom_vjp so their gradients are handled by jax
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    return f(qt, kt, vt).transpose(0, 2, 1, 3)


def block_sparse_attention(q, k, v, layout: np.ndarray, causal: bool = False,
                           sm_scale: Optional[float] = None,
                           interpret: Optional[bool] = None):
    """q/k/v [B, S, H, hd], layout [H, S//block, S//block] (0/1 numpy) ->
    [B, S, H, hd].  Skipped blocks are never loaded or multiplied.

    ``interpret`` defaults to True off-TPU (CPU tests run the kernel through
    the Pallas interpreter).
    """
    B, S, H, hd = q.shape
    nq = layout.shape[1]
    block = S // nq
    assert S % nq == 0, (S, nq)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    kv_idx, kv_cnt, _ = _plan(np.asarray(layout), causal)
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out, _ = _call(qt, kt, vt, jnp.asarray(kv_idx), jnp.asarray(kv_cnt),
                   causal=causal, block=block, sm_scale=sm_scale,
                   interpret=bool(interpret))
    return out.transpose(0, 2, 1, 3)
