from deepspeed_tpu.runtime.zero.policy import ZeroShardingPolicy, add_zero_axes_to_spec
