"""Process-wide metrics registry (ISSUE 4 tentpole).

One registry holds every counter, gauge, and bucketed histogram the
framework emits — train-side (step latency, MFU, checkpoint durations,
retry counts) and serve-side (TTFT, per-token decode latency, queue
wait, batch occupancy).  Two render paths share it:

- :meth:`MetricsRegistry.render_prometheus` — the single Prometheus-text
  exposition function behind ``ds_serve /metrics`` and the opt-in
  training metrics endpoint (``telemetry.metrics_port``);
- :meth:`MetricsRegistry.to_events` — the bridge that drains the
  registry into the existing ``monitor/monitor.py`` sinks per step.

Histograms keep (a) cumulative Prometheus buckets — cheap, mergeable,
what a scraper wants — and (b) a bounded reservoir of recent samples so
``quantile()`` reports exact p50/p90/p99 over the observation window
(vLLM-style serving histograms; PAPERS.md) rather than bucket-edge
estimates.

Everything is guarded by one lock per registry; observation is a
bisect + two increments — safe for the serving loop's hot path.
"""
import bisect
import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

Event = Tuple[str, float, int]     # monitor/monitor.py event triple

#: latency buckets (seconds): 0.5 ms .. 60 s, roughly 2.5x spacing —
#: covers per-token decode (~ms) through checkpoint saves (~tens of s)
DEFAULT_LATENCY_BUCKETS_S = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0)

#: occupancy / utilization buckets (fractions of capacity)
OCCUPANCY_BUCKETS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)

#: token-count buckets (prefill batch sizes, queue depths)
COUNT_BUCKETS = (1, 4, 16, 64, 256, 1024, 4096)

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Prometheus metric name: [a-zA-Z_:][a-zA-Z0-9_:]*."""
    out = _NAME_SANITIZE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_escape(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"')


def _prom_labels(labels: Tuple[Tuple[str, str], ...],
                 extra: Optional[Tuple[Tuple[str, str], ...]] = None) -> str:
    items = list(labels) + list(extra or ())
    if not items:
        return ""
    body = ",".join(f'{_prom_name(k)}="{_prom_escape(v)}"'
                    for k, v in items)
    return "{" + body + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class Histogram:
    """Bucketed histogram + bounded exact-quantile reservoir.

    ``counts[i]`` is the number of observations <= ``buckets[i]``
    (non-cumulative storage; rendering accumulates into the Prometheus
    ``le`` convention).  The reservoir is a ring buffer of the most
    recent ``reservoir_size`` raw samples."""

    def __init__(self, name: str,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                 reservoir_size: int = 4096):
        self.name = name
        self.buckets: Tuple[float, ...] = tuple(sorted(float(b)
                                                       for b in buckets))
        if not self.buckets:
            raise ValueError(f"histogram {name!r}: needs >= 1 bucket bound")
        self.counts = [0] * (len(self.buckets) + 1)   # +1 = +Inf
        self.sum = 0.0
        self.count = 0
        self._ring: List[float] = []
        self._ring_idx = 0
        self._ring_cap = int(reservoir_size)
        self._lock = threading.Lock()

    def observe(self, value: float):
        v = float(value)
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[i] += 1
            self.sum += v
            self.count += 1
            if len(self._ring) < self._ring_cap:
                self._ring.append(v)
            else:
                self._ring[self._ring_idx] = v
                self._ring_idx = (self._ring_idx + 1) % self._ring_cap

    @staticmethod
    def _interp(data: List[float], q: float) -> float:
        pos = (q / 100.0) * (len(data) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(data) - 1)
        frac = pos - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    def quantile(self, q: float) -> Optional[float]:
        """Exact quantile over the recent-sample window (None = empty).
        ``q`` in [0, 100] (percentile convention, matching np)."""
        out = self.quantiles((q,))
        return out[0] if out else None

    def quantiles(self, qs: Sequence[float]) -> Optional[List[float]]:
        """All requested quantiles from ONE sort of the reservoir — the
        snapshot/render paths ask for p50/p90/p99 together, and a
        per-quantile sort would triple the work on every scrape."""
        with self._lock:
            data = sorted(self._ring)
        if not data:
            return None
        return [self._interp(data, q) for q in qs]

    def cumulative_counts(self) -> List[Tuple[float, int]]:
        """[(le_bound, cumulative_count), ...] ending with (+inf, count)."""
        out = []
        acc = 0
        with self._lock:
            for bound, c in zip(self.buckets, self.counts):
                acc += c
                out.append((bound, acc))
            out.append((float("inf"), acc + self.counts[-1]))
        return out


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Labeled counters + gauges + histograms with one exposition path."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (name, labelkey) -> float
        self._counters: Dict[Tuple[str, tuple], float] = {}
        self._gauges: Dict[Tuple[str, tuple], float] = {}
        #: (name, labelkey) -> Histogram
        self._histograms: Dict[Tuple[str, tuple], Histogram] = {}

    # ------------------------------------------------------------ writers
    def inc(self, name: str, value: float = 1.0, **labels):
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + float(value)

    def set_counter(self, name: str, value: float, **labels):
        """Absolute set for counters maintained elsewhere (the serving
        scheduler's ``collections.Counter`` syncs through here at render
        time).  Still rendered with the counter TYPE."""
        with self._lock:
            self._counters[(name, _label_key(labels))] = float(value)

    def set_gauge(self, name: str, value: float, **labels):
        with self._lock:
            self._gauges[(name, _label_key(labels))] = float(value)

    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S,
                  **labels) -> Histogram:
        """Get-or-create; an existing histogram's buckets win (one bucket
        layout per metric name — the Prometheus contract)."""
        key = (name, _label_key(labels))
        with self._lock:
            h = self._histograms.get(key)
            if h is None:
                h = Histogram(name, buckets=buckets)
                self._histograms[key] = h
            return h

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()

    # ------------------------------------------------------------ readers
    def get_counter(self, name: str, **labels) -> float:
        with self._lock:
            return self._counters.get((name, _label_key(labels)), 0.0)

    def get_gauge(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get((name, _label_key(labels)))

    def snapshot(self) -> Dict[str, float]:
        """Flat name -> value view (labels folded into the name); used by
        tests and the monitor bridge.  Histograms contribute _count,
        _sum, and exact-window p50/p90/p99."""
        out = {}
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._histograms)

        def flat(name, labelkey):
            if not labelkey:
                return name
            return name + "{" + ",".join(f"{k}={v}"
                                         for k, v in labelkey) + "}"

        for (name, lk), v in counters.items():
            out[flat(name, lk)] = v
        for (name, lk), v in gauges.items():
            out[flat(name, lk)] = v
        for (name, lk), h in hists.items():
            base = flat(name, lk)
            out[base + "_count"] = float(h.count)
            out[base + "_sum"] = h.sum
            vals = h.quantiles((50, 90, 99))
            if vals is not None:
                for tag, val in zip(("p50", "p90", "p99"), vals):
                    out[f"{base}_{tag}"] = val
        return out

    # --------------------------------------------------------- exposition
    def render_prometheus(self, extra_labels: Optional[Dict[str, str]]
                          = None) -> str:
        """THE text exposition function: Prometheus 0.0.4 text format,
        rendered identically by ``ds_serve /metrics`` and the training
        metrics endpoint.  ``extra_labels`` are appended to every sample
        line — the fleet front-end (ISSUE 11) renders each replica's
        isolated registry with ``{"replica": "<id>"}`` and merges the
        texts into one exposition."""
        extra = tuple(sorted((str(k), str(v))
                             for k, v in (extra_labels or {}).items()))
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._histograms.items())
        lines: List[str] = []
        seen_type = set()

        def type_line(name, kind):
            if name not in seen_type:
                seen_type.add(name)
                lines.append(f"# TYPE {name} {kind}")

        for (name, lk), v in counters:
            n = _prom_name(name)
            type_line(n, "counter")
            lines.append(f"{n}{_prom_labels(lk, extra)} {_fmt(v)}")
        for (name, lk), v in gauges:
            n = _prom_name(name)
            type_line(n, "gauge")
            lines.append(f"{n}{_prom_labels(lk, extra)} {_fmt(v)}")
        for (name, lk), h in hists:
            n = _prom_name(name)
            type_line(n, "histogram")
            for bound, acc in h.cumulative_counts():
                le = "+Inf" if bound == float("inf") else _fmt(bound)
                lines.append(
                    f"{n}_bucket"
                    f"{_prom_labels(lk, extra + (('le', le),))} {acc}")
            lines.append(f"{n}_sum{_prom_labels(lk, extra)} {_fmt(h.sum)}")
            lines.append(f"{n}_count{_prom_labels(lk, extra)} {h.count}")
        return "\n".join(lines) + "\n"

    # ------------------------------------------------------ monitor bridge
    def to_events(self, step: int) -> List[Event]:
        """Drain view for ``monitor/monitor.py`` sinks: every metric as a
        (name, value, step) event.  Counters report their running total;
        histograms report count/sum/quantiles — exactly the snapshot()
        keys, so CSV/TensorBoard series stay stably named."""
        return [(name, float(value), int(step))
                for name, value in sorted(self.snapshot().items())]


# ----------------------------------------------------------- process-wide
_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[MetricsRegistry] = None


def get_registry() -> MetricsRegistry:
    """The process-wide registry (created on first use).  Subsystems that
    want isolation (tests, multiple schedulers in one process) construct
    their own ``MetricsRegistry`` and pass it down instead."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsRegistry()
        return _GLOBAL
