"""ds_fused_layer cache-stream block sweep (ISSUE 12 satellite) — the
qgemm_sweep playbook applied to the decode megakernel: on-chip A/B over
``block_s`` (the KV-cache stream block, DS_FUSED_DECODE_BLOCKS) at the
serving-relevant layer shapes, slope-timed per the PERF.md tunnel
discipline (on-device fori_loop chains; value-fetch sync — see
scripts/bench_util.py).

    python scripts/fused_sweep.py                     # gpt2-125m layer
    FUSED_SHAPES=2048x16x128 FUSED_S=4096 python scripts/fused_sweep.py
    FUSED_SWEEP_SMOKE=1 python scripts/fused_sweep.py # CPU interpret smoke

Kinds swept per shape: ``decode`` (W=1 float cache), ``window`` (W=8 —
the spec-verify / chunk surface), ``int8kv`` (W=1 int8 cache), and
``int8w`` (W=1 int8 weights) — the float and quantized optima differ
(the int8 paths add in-kernel scale expansions), so a winner prints PER
KIND.  Off-TPU (smoke) it runs a tiny interpret-mode shape — plumbing
only, no timing claims.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp

from scripts.bench_util import timed_chain


def _mk_weights(rng, D, H, hd, M, dtype, int8w):
    mk = lambda shape: jnp.asarray(rng.standard_normal(shape), dtype) * 0.2
    cw = {"n1_s": jnp.ones((D,), dtype), "n1_b": jnp.zeros((D,), dtype),
          "wqkv": mk((D, 3 * D)), "bqkv": jnp.zeros((3 * D,), dtype),
          "wo": mk((D, D)), "bo": jnp.zeros((D,), dtype),
          "n2_s": jnp.ones((D,), dtype), "n2_b": jnp.zeros((D,), dtype),
          "w_in": mk((D, M)), "b_in": jnp.zeros((M,), dtype),
          "w_out": mk((M, D)), "b_out": jnp.zeros((D,), dtype)}
    if int8w:
        from deepspeed_tpu.models.model import QuantizedTensor
        from deepspeed_tpu.ops.pallas.quantization import \
            block_quantize_int8
        for k in ("wqkv", "wo", "w_in", "w_out"):
            q, s = block_quantize_int8(np.asarray(cw[k], np.float32))
            cw[k] = QuantizedTensor(jnp.asarray(q), jnp.asarray(s),
                                    str(dtype))
    return cw


def main():
    from deepspeed_tpu.ops.pallas.fused_decode import (FusedLayerSpec,
                                                       ds_fused_layer)
    from deepspeed_tpu.ops.pallas.decode_attention import quantize_kv

    smoke = bool(int(os.environ.get("FUSED_SWEEP_SMOKE", "0")))
    on_tpu = "tpu" in str(jax.devices()[0]).lower()
    if smoke or not on_tpu:
        shapes = [(32, 4, 8)]               # D x H x hd
        S = 64
        B = 2
        blocks = [32, 64]
        steps = 2
        interpret = True
        dtype = jnp.float32
        kinds = ["decode", "window", "int8kv", "int8w"]
    else:
        env = os.environ.get("FUSED_SHAPES", "768x12x64,2048x16x128")
        shapes = [tuple(int(v) for v in s.split("x"))
                  for s in env.split(",")]
        S = int(os.environ.get("FUSED_S", 2048))
        B = int(os.environ.get("FUSED_B", 8))
        blocks = [128, 256, 512, 1024, 2048]
        steps = int(os.environ.get("FUSED_STEPS", 20))
        interpret = False
        dtype = jnp.bfloat16
        kinds = ["decode", "window", "int8kv", "int8w"]

    rng = np.random.default_rng(0)
    for (D, H, hd) in shapes:
        M = 4 * D
        spec = FusedLayerSpec(num_heads=H, num_kv_heads=H, head_dim=hd,
                              d_model=D, norm="ln", qkv="fused",
                              mlp="gelu_tanh")
        lengths = jnp.asarray(rng.integers(S // 2, S - 9, (B,)), jnp.int32)
        k_f = jnp.asarray(rng.standard_normal((B, S, H, hd)), dtype)
        v_f = jnp.asarray(rng.standard_normal((B, S, H, hd)), dtype)
        kq, ks = quantize_kv(k_f)
        vq, vs = quantize_kv(v_f)
        cw = _mk_weights(rng, D, H, hd, M, dtype, int8w=False)
        cwq = _mk_weights(rng, D, H, hd, M, dtype, int8w=True)
        best = {}
        for kind in kinds:
            W = 8 if kind == "window" else 1
            weights = cwq if kind == "int8w" else cw
            quant = kind == "int8kv"
            x0 = jnp.asarray(rng.standard_normal((B, W, D)), dtype)
            for bs in blocks:
                if bs > S:
                    continue

                def step(state, _bs=bs, _w=weights, _q=quant):
                    x, acc = state
                    out = ds_fused_layer(
                        x, _w, kq if _q else k_f, vq if _q else v_f,
                        lengths, spec,
                        ks_l=ks if _q else None, vs_l=vs if _q else None,
                        block_s=_bs, interpret=interpret)
                    return (jnp.tanh(out[0]) + x, acc + jnp.sum(out[0]))

                try:
                    sec = max(timed_chain(step, (x0, jnp.float32(0)),
                                          steps), 0.0)
                except Exception as e:  # keep sweeping past bad tilings
                    print(json.dumps({"shape": f"{D}x{H}x{hd}",
                                      "kind": kind, "block_s": bs,
                                      "error": str(e)[:200]}))
                    continue
                row = {"shape": f"{D}x{H}x{hd}", "kind": kind, "W": W,
                       "S": S, "B": B, "block_s": bs,
                       "us_per_layer": round(sec * 1e6, 2)}
                print(json.dumps(row))
                if sec > 0 and (kind not in best or sec < best[kind][0]):
                    best[kind] = (sec, row)
        # winner PER KIND: float/int8 optima differ (scale expansions)
        for kind, (sec_w, row) in sorted(best.items()):
            print(json.dumps({"shape": f"{D}x{H}x{hd}", "kind": kind,
                              "winner": row}))
            from scripts.bench_util import emit_ledger
            emit_ledger({"metric": f"fused_sweep_{kind}_{D}x{H}x{hd}",
                         "value": row["us_per_layer"],
                         "unit": "us_per_layer",
                         "direction": "lower_better",
                         "detail": {"block_s": row["block_s"]}})


if __name__ == "__main__":
    main()
