"""Engine end-to-end tests on the simulated 8-device mesh (reference:
tests/unit/runtime/test_ds_initialize.py + runtime/zero/test_zero.py —
correctness across ZeRO stages vs the stage-0 baseline)."""
import numpy as np
import pytest

import deepspeed_tpu
from tests.util import tiny_gpt2, random_batch, random_batches, base_config


def _make_engine(config_overrides=None, model=None, **mesh):
    cfg = base_config(**(config_overrides or {}))
    if mesh:
        cfg["mesh"] = mesh
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model or tiny_gpt2(), config=cfg)
    return engine


def _train(engine, steps=3, batch_size=8, seed=0):
    losses = []
    gas = engine.gradient_accumulation_steps()
    for i in range(steps):
        batches = iter(random_batches(gas, batch_size=batch_size,
                                      seed=seed + i * gas))
        losses.append(float(engine.train_batch(batches)))
    return losses


def test_initialize_returns_tuple(devices8):
    cfg = base_config()
    out = deepspeed_tpu.initialize(model=tiny_gpt2(), config=cfg)
    assert len(out) == 4
    engine = out[0]
    assert engine.train_batch_size() == 8      # micro 1 × gas 1 × dp 8


def test_train_loss_decreases_stage0(devices8):
    engine = _make_engine({"optimizer": {"type": "Adam",
                                         "params": {"lr": 1e-2}}})
    losses = _train(engine, steps=8, seed=42)
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stages_match_stage0(devices8, stage):
    """ZeRO stages must be numerically equivalent to plain DP (reference
    test_zero.py compares against torch DDP)."""
    ref = _make_engine()
    got = _make_engine({"zero_optimization": {"stage": stage}})
    ref_losses = _train(ref, steps=3, seed=7)
    got_losses = _train(got, steps=3, seed=7)
    np.testing.assert_allclose(got_losses, ref_losses, rtol=2e-4, atol=2e-5)


def test_gradient_accumulation_equivalence(devices8):
    """gas=2 with half micro-batch ≈ gas=1 with full batch (same total)."""
    e1 = _make_engine({"train_micro_batch_size_per_gpu": 1,
                       "gradient_accumulation_steps": 2})
    e2 = _make_engine({"train_micro_batch_size_per_gpu": 2,
                       "gradient_accumulation_steps": 1})
    b = random_batch(batch_size=16, seed=3)
    # e1: two micro-batches of 8; e2: one batch of 16
    stacked = {"input_ids": b["input_ids"].reshape(2, 8, -1)}
    l1 = float(e1.train_batch(batch=stacked))   # mean over micro-batches
    l2 = float(e2.train_batch(batch={"input_ids":
                                     b["input_ids"][None]}))
    assert abs(l1 - l2) < 1e-4


def test_forward_backward_step_api(devices8):
    """Micro-step API parity (reference engine.forward/backward/step)."""
    engine = _make_engine({"gradient_accumulation_steps": 2,
                           "train_micro_batch_size_per_gpu": 1})
    fast = _make_engine({"gradient_accumulation_steps": 2,
                         "train_micro_batch_size_per_gpu": 1})
    batches = random_batches(2, batch_size=8, seed=11)
    for mb in batches:
        loss = engine.forward(mb)
        engine.backward(loss)
        engine.step()
    assert engine.global_steps == 1
    stacked = {"input_ids": np.stack([b["input_ids"] for b in batches])}
    fast.train_batch(batch=stacked)
    p1 = engine.state["params"]["blocks"]["qkv_w"]
    p2 = fast.state["params"]["blocks"]["qkv_w"]
    np.testing.assert_allclose(np.asarray(p1), np.asarray(p2),
                               rtol=2e-4, atol=2e-5)


def test_bf16_training(devices8):
    engine = _make_engine({"bf16": {"enabled": True}})
    losses = _train(engine, steps=3)
    assert np.isfinite(losses).all()


def test_fp16_dynamic_loss_scale(devices8):
    engine = _make_engine({"fp16": {"enabled": True,
                                    "initial_scale_power": 8}})
    assert engine.loss_scale == 2 ** 8
    losses = _train(engine, steps=3)
    assert np.isfinite(losses).all()


def test_gradient_clipping(devices8):
    engine = _make_engine({"gradient_clipping": 0.001,
                           "optimizer": {"type": "SGD", "params": {"lr": 1.0}}})
    before = np.asarray(engine.state["params"]["blocks"]["qkv_w"]).copy()
    _train(engine, steps=1)
    after = np.asarray(engine.state["params"]["blocks"]["qkv_w"])
    # update magnitude bounded by lr * clip
    assert np.abs(after - before).max() <= 0.001 + 1e-6


def test_tp_matches_dp(devices8):
    """Tensor-parallel run must match the pure-DP run."""
    ref = _make_engine()
    tp = _make_engine(model=tiny_gpt2(), model_parallel_size=2)
    ref_losses = _train(ref, steps=2, seed=5)
    tp_losses = _train(tp, steps=2, seed=5)
    np.testing.assert_allclose(tp_losses, ref_losses, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("stage", [0, 2])
def test_checkpoint_roundtrip(devices8, tmp_path, stage):
    """(reference: tests/unit/checkpoint/test_zero_optimizer.py)"""
    engine = _make_engine({"zero_optimization": {"stage": stage}})
    _train(engine, steps=2, seed=1)
    engine.save_checkpoint(str(tmp_path), client_state={"foo": 1})
    loss_before = _train(engine, steps=1, seed=9)[0]

    engine2 = _make_engine({"zero_optimization": {"stage": stage}})
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert path is not None
    assert client == {"foo": 1}
    assert engine2.global_steps == 2
    loss_after = _train(engine2, steps=1, seed=9)[0]
    assert abs(loss_before - loss_after) < 1e-5


def test_async_checkpoint_overlaps_training(devices8, tmp_path):
    """Async engine (reference nebula_checkpoint_engine.py capability):
    save_checkpoint returns with the save in flight, training continues
    and mutates the live state, the commit barrier publishes `latest`,
    and the restored state is the SAVE-TIME snapshot — not the
    post-save-mutated one."""
    import os
    from deepspeed_tpu.runtime.checkpoint_engine.engine import (
        AsyncOrbaxCheckpointEngine)
    engine = _make_engine({"zero_optimization": {"stage": 2},
                           "checkpoint": {"async_save": True}})
    _train(engine, steps=2, seed=1)
    at_save = np.asarray(
        engine.state["params"]["blocks"]["qkv_w"]).copy()
    engine.save_checkpoint(str(tmp_path), client_state={"bar": 2})
    assert isinstance(engine.checkpoint_engine, AsyncOrbaxCheckpointEngine)
    # commit deferred: `latest` is not published while the save is in
    # flight, and training keeps going meanwhile
    assert not os.path.exists(os.path.join(str(tmp_path), "latest"))
    _train(engine, steps=2, seed=21)
    mutated = np.asarray(engine.state["params"]["blocks"]["qkv_w"])
    assert np.abs(mutated - at_save).max() > 0
    engine.wait_pending_checkpoint()
    assert os.path.exists(os.path.join(str(tmp_path), "latest"))

    engine2 = _make_engine({"zero_optimization": {"stage": 2},
                            "checkpoint": {"async_save": True}})
    path, client = engine2.load_checkpoint(str(tmp_path))
    assert path is not None and client == {"bar": 2}
    assert engine2.global_steps == 2
    restored = np.asarray(engine2.state["params"]["blocks"]["qkv_w"])
    np.testing.assert_array_equal(restored, at_save)
    # a second async save auto-commits any pending one at entry
    engine.save_checkpoint(str(tmp_path), tag="second")
    engine.save_checkpoint(str(tmp_path), tag="third")
    engine.wait_pending_checkpoint()
    assert open(os.path.join(str(tmp_path), "latest")).read() == "third"


def test_checkpoint_reshape_across_stages(devices8, tmp_path):
    """Universal-checkpoint property: save under stage 0, load under stage 3
    (reference: checkpoint/universal_checkpoint.py capability)."""
    e0 = _make_engine()
    _train(e0, steps=1, seed=2)
    e0.save_checkpoint(str(tmp_path))
    e3 = _make_engine({"zero_optimization": {"stage": 3}})
    e3.load_checkpoint(str(tmp_path))
    l0 = _train(e0, steps=1, seed=13)[0]
    l3 = _train(e3, steps=1, seed=13)[0]
    assert abs(l0 - l3) < 2e-4


def test_frozen_params_not_updated(devices8):
    """Frozen-parameter coverage (reference SimpleFrozenModel,
    tests/unit/runtime/zero/test_zero.py): a trainable_mask freezing the
    embedding leaves it bit-identical under ZeRO-2 + AdamW weight decay
    while the rest of the model trains."""
    import dataclasses
    import jax
    base = tiny_gpt2()
    shapes = jax.eval_shape(base.init, jax.random.PRNGKey(0))
    mask = jax.tree.map(lambda _: True, shapes)
    mask["wte"] = False
    model = dataclasses.replace(base, trainable_mask=mask)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=base_config(
            zero_optimization={"stage": 2},
            optimizer={"type": "AdamW",
                       "params": {"lr": 1e-2, "weight_decay": 0.1}}))
    wte_before = np.asarray(engine.state["params"]["wte"]).copy()
    qkv_before = np.asarray(
        engine.state["params"]["blocks"]["qkv_w"]).copy()
    _train(engine, steps=3, seed=2)
    np.testing.assert_array_equal(
        np.asarray(engine.state["params"]["wte"]), wte_before)
    assert np.abs(np.asarray(engine.state["params"]["blocks"]["qkv_w"])
                  - qkv_before).max() > 0


def test_unused_parameters_train(devices8):
    """UnusedParametersModel coverage (reference simple_model.py:
    a param no forward path touches must not break the step — the
    reference's hook-driven ZeRO needed special handling; here zero
    grads flow naturally and Adam leaves the leaf untouched)."""
    import dataclasses
    import jax
    base = tiny_gpt2()
    orig_init, orig_loss = base.init_fn, base.loss_fn

    def init_fn(rng):
        p = orig_init(rng)
        p["unused_w"] = jax.numpy.ones((8, 8))
        return p

    def loss_fn(params, batch, rng=None):
        rest = {k: v for k, v in params.items() if k != "unused_w"}
        return orig_loss(rest, batch, rng)

    from jax.sharding import PartitionSpec as P
    model = dataclasses.replace(
        base, init_fn=init_fn, loss_fn=loss_fn,
        logical_specs={**base.logical_specs, "unused_w": P()})
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=base_config(zero_optimization={"stage": 2}))
    losses = _train(engine, steps=2, seed=6)
    assert np.isfinite(losses).all()
    np.testing.assert_array_equal(
        np.asarray(engine.state["params"]["unused_w"]), np.ones((8, 8)))


def test_lr_scheduler_wired(devices8):
    engine = _make_engine({
        "optimizer": {"type": "Adam", "params": {"lr": 1e-2}},
        "scheduler": {"type": "WarmupLR",
                      "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2,
                                 "warmup_num_steps": 100}}})
    lr0 = engine.get_lr()[0]
    _train(engine, steps=2)
    lr2 = engine.get_lr()[0]
    assert lr2 > lr0


def test_eval_batch(devices8):
    engine = _make_engine()
    loss = float(engine.eval_batch(random_batch(batch_size=8)))
    assert np.isfinite(loss)


def test_engine_introspection_api(devices8):
    """Reference engine accessors (engine.py:2243-2259): get_lr/get_type/
    get_mom/get_pld_theta."""
    engine = _make_engine({"optimizer": {
        "type": "AdamW", "params": {"lr": 2e-3, "betas": (0.8, 0.95)}}})
    assert engine.get_lr() == [2e-3]
    assert engine.get_type() == ["adamw"]
    assert engine.get_mom() == [(0.8, 0.95)]
    assert engine.get_pld_theta() is None
    sgd = _make_engine({"optimizer": {"type": "SGD",
                                      "params": {"lr": 0.1,
                                                 "momentum": 0.9}}})
    assert sgd.get_mom() == [0.9]
