"""Compiled pipeline parallelism (reference: deepspeed/runtime/pipe/engine.py:54
``PipelineEngine`` executing a 1F1B instruction stream with p2p send/recv,
p2p.py:50).

TPU-native formulation — the whole schedule is ONE XLA program:

- layer params stay stacked ``[L, ...]`` and are viewed as
  ``[n_stages, L/n_stages, ...]`` with the stage dim sharded over the ``pipe``
  mesh axis;
- a ``vmap`` over the stage dim applies every stage to its activation slot in
  parallel (each device computes only its stage — the weights are local);
- shifting the activation buffer one slot along the stage dim lowers to an XLA
  ``CollectivePermute`` over ICI — the reference's send/recv pairs;
- a ``lax.scan`` over M + S - 1 ticks runs the GPipe fill/steady/drain; the
  backward pass through the scan is the reversed pipeline (XLA schedules it —
  no hand-written 1F1B instruction interleave needed).

Bubble fraction is (S-1)/(M+S-1), identical to the reference's schedule.
Everything stays inside the automatic SPMD partitioner, so ZeRO/TP/SP compose
with pipelining without manual collectives.
"""
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from deepspeed_tpu.comm.mesh import get_topology, PIPE_AXIS


def stage_params_view(blocks_params, n_stages: int):
    """[L, ...] stacked layer params -> [n_stages, L/S, ...], stage dim
    constrained to the pipe axis."""
    mesh = get_topology().mesh

    def reshape(p):
        L = p.shape[0]
        assert L % n_stages == 0, (
            f"num_layers {L} must divide evenly into {n_stages} stages")
        v = p.reshape(n_stages, L // n_stages, *p.shape[1:])
        return lax.with_sharding_constraint(
            v, NamedSharding(mesh, P(PIPE_AXIS)))

    return jax.tree.map(reshape, blocks_params)


def pipeline_blocks(block_fn: Callable, blocks_params, x_micro, n_stages: int):
    """Run stacked transformer blocks as an n_stages pipeline.

    Args:
        block_fn: (x, layer_params) -> x, one layer.
        blocks_params: stacked [L, ...] pytree.
        x_micro: [n_micro, B_micro, S, D] microbatched activations.
    Returns:
        [n_micro, B_micro, S, D] outputs after all L layers.
    """
    if n_stages == 1:
        def body(c, lp):
            return block_fn(c, lp), None

        def run_one(x):
            return lax.scan(body, x, blocks_params)[0]
        return jax.vmap(run_one)(x_micro) if x_micro.ndim > 3 else run_one(x_micro)

    n_micro = x_micro.shape[0]
    assert n_micro >= n_stages, (
        f"need >= {n_stages} microbatches to fill the pipeline, got {n_micro} "
        f"(set gradient_accumulation_steps >= pipe_parallel_size)")
    staged = stage_params_view(blocks_params, n_stages)
    mesh = get_topology().mesh
    state_spec = NamedSharding(mesh, P(PIPE_AXIS))

    def stage_apply(stage_params, x):
        def body(c, lp):
            return block_fn(c, lp), None
        return lax.scan(body, x, stage_params)[0]

    vstages = jax.vmap(stage_apply)

    state = jnp.zeros((n_stages,) + x_micro.shape[1:], x_micro.dtype)
    state = lax.with_sharding_constraint(state, state_spec)
    outputs = jnp.zeros_like(x_micro)
    n_ticks = n_micro + n_stages - 1

    def tick(carry, t):
        state, outputs = carry
        # ingest microbatch t at stage 0 (clamped after the last microbatch —
        # those ticks only drain the tail stages)
        inp = lax.dynamic_index_in_dim(
            x_micro, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
        state = lax.dynamic_update_index_in_dim(state, inp, 0, axis=0)
        state = lax.with_sharding_constraint(state, state_spec)
        state = vstages(staged, state)
        state = lax.with_sharding_constraint(state, state_spec)
        # microbatch t-(S-1) finishes at the last stage this tick
        out_t = t - (n_stages - 1)
        finished = lax.dynamic_index_in_dim(
            state, n_stages - 1, axis=0, keepdims=False)
        updated = lax.dynamic_update_index_in_dim(
            outputs, finished, jnp.maximum(out_t, 0), axis=0)
        outputs = jnp.where(out_t >= 0, updated, outputs)
        # shift: stage i's output becomes stage i+1's input (CollectivePermute)
        state = jnp.roll(state, shift=1, axis=0)
        state = lax.with_sharding_constraint(state, state_spec)
        return (state, outputs), None

    (state, outputs), _ = lax.scan(
        tick, (state, outputs), jnp.arange(n_ticks))
    return outputs


def pipeline_model(model, num_stages: int):
    """Wrap a Model exposing (embed_fn, block_fn, head_fn) into a pipelined
    Model (reference: PipelineModule, runtime/pipe/module.py:86; tied
    embeddings live outside the pipelined region — the reference's
    TiedLayerSpec replication, module.py:421 — so no tied-grad all-reduce is
    needed: the embedding computes on every stage and XLA keeps one copy per
    non-pipe mesh position)."""
    from deepspeed_tpu.models.model import Model
    import optax

    assert model.embed_fn is not None and model.block_fn is not None \
        and model.head_fn is not None, \
        "model must expose embed_fn/block_fn/head_fn for pipelining"

    def pipelined_apply_micro(params, stacked_batch, rng=None):
        """stacked_batch leaves: [n_micro, B_micro, ...] -> logits
        [n_micro, B_micro, S, V]."""
        x = jax.vmap(lambda b: model.embed_fn(params, b))(stacked_batch)
        x = pipeline_blocks(
            lambda h, lp: model.block_fn(lp, h),
            params[model.blocks_key], x, num_stages)
        return jax.vmap(lambda h: model.head_fn(params, h))(x)

    def loss_fn(params, stacked_batch, rng=None):
        logits = pipelined_apply_micro(params, stacked_batch, rng)
        tokens = stacked_batch["input_ids"]
        ce = optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :, :-1].astype(jnp.float32), tokens[:, :, 1:])
        return ce.mean()

    def apply_fn(params, batch, rng=None):
        # single (non-micro) batch: run as one microbatch group of size S
        return model.apply_fn(params, batch, rng)

    # storage layout: the stacked layer dim of every blocks leaf is sharded
    # over the pipe axis (stage-major), so the [n_stages, L/S, ...] view in
    # pipeline_blocks is a local reshape
    specs = model.logical_specs
    if specs is not None:
        def add_pipe(spec):
            entries = list(tuple(spec)) or [None]
            assert entries[0] is None, \
                f"blocks leaf dim0 (layers) already sharded: {spec}"
            entries[0] = PIPE_AXIS
            return P(*entries)

        specs = dict(specs)
        specs[model.blocks_key] = jax.tree.map(
            add_pipe, specs[model.blocks_key],
            is_leaf=lambda x: isinstance(x, P))

    m = Model(
        config=model.config,
        init_fn=model.init_fn,
        apply_fn=apply_fn,
        loss_fn=loss_fn,
        logical_specs=specs,
        flops_per_token=model.flops_per_token,
        meta={**model.meta, "pipeline": True, "num_stages": num_stages},
    )
    m.embed_fn = model.embed_fn
    m.block_fn = model.block_fn
    m.head_fn = model.head_fn
    m.blocks_key = model.blocks_key
    return m
