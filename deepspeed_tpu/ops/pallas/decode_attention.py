"""From-scratch Pallas TPU decode-attention kernel (KV-cache attention).

The serving-side equivalent of the reference's fused ``ds_softmax_context``
(csrc/transformer/inference/csrc/pt_binding.cpp:434, softmax.cu): one query
token per row attends to a KV cache of ``cache_len[b]`` valid positions.
Decode attention is HBM-bandwidth-bound — the work IS streaming the cache —
so the kernel:

- keeps the cache **packed** as [S_max, KV*hd] (a [*, hd] trailing dim with
  hd=64 would pad to 128 lanes in HBM, doubling cache bytes and bandwidth);
- streams it through VMEM in S-blocks with an online-softmax accumulator, so
  nothing [S, S]-shaped ever exists and arbitrarily long caches fit;
- skips entire S-blocks past the longest row's ``cache_len`` (predicated
  execution: the DMA for skipped blocks still lands but the FLOPs don't);
- computes all heads' scores in ONE [bs, KV*hd] x [KV*hd, KV] matmul per
  group by materialising the query as a block-diagonal weight (full 128-lane
  contraction depth even though hd=64 — a per-head formulation would waste
  half the MXU).

Grouped-query attention folds in by iterating ``rep = H // KV`` query groups;
each group maps 1:1 onto the KV heads, so the same block-diagonal trick
applies per group.

Layouts (packed, group-major):
  q:        [B, rep, KV*hd]   (q[b, r, kvh*hd+d] = query head kvh*rep+r)
  k/v:      [B, S_max, KV*hd]
  cache_len:[B] int32 — number of valid cache positions per row
  out:      [B, rep, KV*hd]
"""
from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, *rest, block_s, kv_heads,
                   head_dim, rep, sm_scale, precision, quantized, alibi,
                   windowed):
    """Grid: (B, num_s_blocks); S is the minor (sequential) dimension so the
    online-softmax state in scratch carries across S-blocks of one row.

    ``quantized``: k/v blocks are int8 with per-(position, kv-head) fp32
    scales (two extra inputs) — the cache stream halves its HBM bytes and
    dequantizes on the VPU in VMEM.  ``alibi``: one extra [rep, KV] fp32
    input of group-major per-head slopes; scores get the BLOOM additive
    bias ``slope * key_position`` before the online softmax.
    ``windowed``: one extra [B] int32 SMEM input of per-row window floors
    — positions below it are masked (sliding-window / GPT-Neo local
    attention)."""
    rest = list(rest)
    ks_ref = vs_ref = sl_ref = min_ref = None
    if quantized:
        ks_ref, vs_ref = rest[0], rest[1]
        rest = rest[2:]
    if alibi:
        sl_ref = rest[0]
        rest = rest[1:]
    if windowed:
        min_ref = rest[0]
        rest = rest[1:]
    o_ref, m_ref, l_ref, acc_ref = rest
    s_idx = pl.program_id(1)
    n_s = pl.num_programs(1)
    cache_len = len_ref[pl.program_id(0)]
    min_pos = min_ref[pl.program_id(0)] if windowed else None
    Dk = kv_heads * head_dim

    @pl.when(s_idx == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    s_start = s_idx * block_s
    # entire block beyond this row's cache: skip the compute
    @pl.when(s_start < cache_len)
    def _compute():
        # block-diagonal expansion mask (built once per block; VPU-cheap):
        # the ONE source of the lane-packing layout — scale expansion and
        # prob expansion both derive from it
        row_group = jax.lax.broadcasted_iota(
            jnp.int32, (Dk, kv_heads), 0) // head_dim       # [Dk, KV]
        col_head = jax.lax.broadcasted_iota(
            jnp.int32, (Dk, kv_heads), 1)                   # [Dk, KV]
        blockdiag = (row_group == col_head)                 # [Dk, KV] bool

        if quantized:
            # expand per-kv-head scales onto the packed lanes with one
            # [bs, KV] x [KV, Dk] matmul
            expand = blockdiag.astype(jnp.float32).T        # [KV, Dk]
            k_sc = jax.lax.dot(ks_ref[:], expand,
                               preferred_element_type=jnp.float32)
            v_sc = jax.lax.dot(vs_ref[:], expand,
                               preferred_element_type=jnp.float32)
            k = k_ref[:].astype(jnp.float32) * k_sc          # [bs, Dk]
            v = v_ref[:].astype(jnp.float32) * v_sc
        else:
            k = k_ref[:]                           # [bs, KV*hd]
            v = v_ref[:]
        # validity mask for positions inside this block
        pos = s_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_s, kv_heads), 0)     # [bs, KV]
        valid = pos < cache_len
        if windowed:
            valid &= pos >= min_pos

        for r in range(rep):
            # minor-dim insertion on bf16 vectors is unsupported by Mosaic;
            # widen to f32 for the [Dk] -> [Dk, 1] reshape
            q_r = q_ref[r, :].astype(jnp.float32)           # [Dk]
            w = jnp.where(blockdiag, q_r[:, None], 0.0).astype(k.dtype)
            scores = jax.lax.dot(
                k, w, preferred_element_type=jnp.float32,
                precision=precision) * sm_scale
            if alibi:
                scores = scores + (sl_ref[r, :][None, :]
                                   * pos.astype(jnp.float32))
            scores = jnp.where(valid, scores, NEG_INF)      # [bs, KV]

            m_prev = m_ref[r, :]                            # [KV]
            l_prev = l_ref[r, :]
            m_cur = jnp.max(scores, axis=0)                 # [KV]
            m_new = jnp.maximum(m_prev, m_cur)
            corr = jnp.exp(m_prev - m_new)                  # [KV]
            p = jnp.exp(scores - m_new[None, :])            # [bs, KV]
            p = jnp.where(valid, p, 0.0)
            l_ref[r, :] = l_prev * corr + jnp.sum(p, axis=0)
            m_ref[r, :] = m_new

            # expand per-head probs to the packed lane layout and reduce
            # over the block's positions:  acc[kvh*hd+d] += Σ_s p[s,kvh]·v[s,kvh*hd+d]
            p_exp = jax.lax.dot(
                p.astype(v.dtype), blockdiag.astype(v.dtype).T,
                preferred_element_type=jnp.float32,
                precision=precision)                         # [bs, Dk]
            acc_ref[r, :] = acc_ref[r, :] * jnp.where(
                blockdiag, corr[None, :], 0.0).sum(axis=1) + jnp.sum(
                p_exp * v.astype(jnp.float32), axis=0)

    @pl.when(s_idx == n_s - 1)
    def _finalize():
        # expand l (per kv head) onto the packed lanes
        row_group = jax.lax.broadcasted_iota(
            jnp.int32, (Dk, kv_heads), 0) // head_dim
        col_head = jax.lax.broadcasted_iota(
            jnp.int32, (Dk, kv_heads), 1)
        blockdiag = (row_group == col_head)
        for r in range(rep):
            # VPU select-sum (a matmul here would round l through bf16)
            l_exp = jnp.where(blockdiag, l_ref[r, :][None, :], 0.0).sum(axis=1)
            o_ref[r, :] = (acc_ref[r, :] /
                           jnp.maximum(l_exp, 1e-30)).astype(o_ref.dtype)


def quantize_kv(x):
    """[..., KV, hd] -> (int8 [..., KV, hd], fp32 scales [..., KV]): one
    symmetric scale per cached head-vector (the int8 KV-cache layout)."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def dequantize_kv(q, scale):
    return q.astype(jnp.float32) * scale[..., None]


def quantize_prefill_into_cache(cache, ks, vs):
    """Quantize a prefill's stacked K/V ([L, B, S, KV, hd]) and write them
    into the int8 cache dict (shared by every KV-cache model)."""
    kq, ksc = quantize_kv(ks)
    vq, vsc = quantize_kv(vs)
    return {
        "k": jax.lax.dynamic_update_slice(cache["k"], kq, (0, 0, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(cache["v"], vq, (0, 0, 0, 0, 0)),
        "k_s": jax.lax.dynamic_update_slice(cache["k_s"], ksc,
                                            (0, 0, 0, 0)),
        "v_s": jax.lax.dynamic_update_slice(cache["v_s"], vsc,
                                            (0, 0, 0, 0)),
    }


def decode_attention_pallas(q, k_cache, v_cache, cache_len,
                            sm_scale=None, block_s: int = 1024,
                            k_scale=None, v_scale=None, alibi_slopes=None,
                            min_pos=None):
    """q: [B, H, hd]; k/v_cache: [B, S_max, KV, hd]; cache_len: [B] int32.
    int8 caches pass their per-vector fp32 ``k_scale``/``v_scale``
    [B, S_max, KV].  ``alibi_slopes`` [H] adds the BLOOM positional bias.
    Returns [B, H, hd]."""
    B, H, hd = q.shape
    _, S_max, KV, _ = k_cache.shape
    rep = H // KV
    quantized = k_scale is not None
    if sm_scale is None:
        sm_scale = hd ** -0.5
    # Pick the LARGEST tile-aligned divisor of S_max under the VMEM budget:
    # decode is launch-bound at short caches (each extra grid cell costs
    # more than the bytes it streams — a 384-cache at block 128 ran 0.26 ms
    # slower per 12-layer step than at block 384, scripts/decode_profile.py),
    # so fewer S-blocks beats finer block-skipping.  ``block_s`` acts as an
    # upper cap; the VMEM cap keeps k+v double-buffered blocks in budget.
    Dk_bytes = KV * hd * (1 if quantized else jnp.dtype(q.dtype).itemsize)
    vmem_cap = max(64, (6 << 20) // max(1, 4 * Dk_bytes) // 8 * 8)
    cap = min(block_s, vmem_cap, S_max)
    best = 0
    for cand in range(8, cap + 1, 8):
        if S_max % cand == 0:
            best = cand
    if best:
        block_s = best
    else:
        pad = -S_max % 128
        k_cache = jnp.pad(k_cache, ((0, 0), (0, pad), (0, 0), (0, 0)))
        v_cache = jnp.pad(v_cache, ((0, 0), (0, pad), (0, 0), (0, 0)))
        if quantized:
            k_scale = jnp.pad(k_scale, ((0, 0), (0, pad), (0, 0)))
            v_scale = jnp.pad(v_scale, ((0, 0), (0, pad), (0, 0)))
        S_max += pad
        block_s = min(block_s, S_max)
        while S_max % block_s:
            block_s //= 2
    Dk = KV * hd

    # group-major packing: [B, KV, rep, hd] -> [B, rep, KV*hd]
    qp = q.reshape(B, KV, rep, hd).transpose(0, 2, 1, 3).reshape(B, rep, Dk)
    kp = k_cache.reshape(B, S_max, Dk)
    vp = v_cache.reshape(B, S_max, Dk)

    # fp32 inputs need full-precision MXU passes (the default lowering runs
    # bf16-grade multiplies even for f32 operands); bf16 inputs keep the
    # default single pass
    precision = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32
                 else None)
    kernel = partial(_decode_kernel, block_s=block_s, kv_heads=KV,
                     head_dim=hd, rep=rep, sm_scale=sm_scale,
                     precision=precision, quantized=quantized,
                     alibi=alibi_slopes is not None,
                     windowed=min_pos is not None)
    cache_spec = pl.BlockSpec((None, block_s, Dk), lambda b, s: (b, s, 0),
                              memory_space=pltpu.VMEM)
    in_specs = [
        # whole cache_len vector in SMEM (TPU lowering rejects 1-element
        # rank-1 blocks); the kernel indexes it by program_id
        pl.BlockSpec((B,), lambda b, s: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((None, rep, Dk), lambda b, s: (b, 0, 0),
                     memory_space=pltpu.VMEM),
        cache_spec,
        cache_spec,
    ]
    args = [cache_len.astype(jnp.int32), qp, kp, vp]
    if quantized:
        scale_spec = pl.BlockSpec((None, block_s, KV),
                                  lambda b, s: (b, s, 0),
                                  memory_space=pltpu.VMEM)
        in_specs += [scale_spec, scale_spec]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    if alibi_slopes is not None:
        # group-major slope table matching the packed query layout
        sl_rk = jnp.asarray(alibi_slopes, jnp.float32).reshape(
            KV, rep).transpose(1, 0)
        in_specs += [pl.BlockSpec((rep, KV), lambda b, s: (0, 0),
                                  memory_space=pltpu.VMEM)]
        args += [sl_rk]
    if min_pos is not None:
        in_specs += [pl.BlockSpec((B,), lambda b, s: (0,),
                                  memory_space=pltpu.SMEM)]
        args += [min_pos.astype(jnp.int32)]
    out = pl.pallas_call(
        kernel,
        grid=(B, S_max // block_s),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((None, rep, Dk), lambda b, s: (b, 0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((B, rep, Dk), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, KV), jnp.float32),   # m
            pltpu.VMEM((rep, KV), jnp.float32),   # l
            pltpu.VMEM((rep, Dk), jnp.float32),   # acc
        ],
    )(*args)
    # unpack group-major -> head-major
    return out.reshape(B, rep, KV, hd).transpose(0, 2, 1, 3).reshape(B, H, hd)


def decode_attention_xla(q, k_cache, v_cache, cache_len, sm_scale=None,
                         k_scale=None, v_scale=None, alibi_slopes=None,
                         min_pos=None):
    """Reference/fallback implementation (CPU meshes, numeric tests).
    Same signature as the Pallas kernel."""
    if k_scale is not None:
        k_cache = dequantize_kv(k_cache, k_scale).astype(q.dtype)
        v_cache = dequantize_kv(v_cache, v_scale).astype(q.dtype)
    B, H, hd = q.shape
    _, S_max, KV, _ = k_cache.shape
    if sm_scale is None:
        sm_scale = hd ** -0.5
    if KV != H:
        rep = H // KV
        k_cache = jnp.repeat(k_cache, rep, axis=2)
        v_cache = jnp.repeat(v_cache, rep, axis=2)
    prec = (jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else None)
    scores = jnp.einsum("bhd,bshd->bhs", q, k_cache,
                        precision=prec).astype(jnp.float32)
    scores = scores * sm_scale
    if alibi_slopes is not None:
        scores = scores + (jnp.asarray(alibi_slopes, jnp.float32)[None, :, None]
                           * jnp.arange(S_max)[None, None, :])
    valid = jnp.arange(S_max)[None, None, :] < cache_len[:, None, None]
    if min_pos is not None:
        valid &= jnp.arange(S_max)[None, None, :] >= min_pos[:, None, None]
    scores = jnp.where(valid, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhs,bshd->bhd", probs, v_cache, precision=prec)


def decode_attention(q, k_cache, v_cache, cache_len, sm_scale=None,
                     k_scale=None, v_scale=None, alibi_slopes=None,
                     min_pos=None):
    """Dispatch: Pallas kernel on TPU, XLA reference elsewhere.  int8
    caches pass per-vector fp32 scales (see ``quantize_kv``);
    ``alibi_slopes`` [H] selects the BLOOM positional-bias form;
    ``min_pos`` [B] masks positions below a per-row floor
    (sliding-window attention)."""
    from deepspeed_tpu.ops.attention import _on_tpu
    if _on_tpu():
        return decode_attention_pallas(q, k_cache, v_cache, cache_len,
                                       sm_scale=sm_scale, k_scale=k_scale,
                                       v_scale=v_scale,
                                       alibi_slopes=alibi_slopes,
                                       min_pos=min_pos)
    return decode_attention_xla(q, k_cache, v_cache, cache_len,
                                sm_scale=sm_scale, k_scale=k_scale,
                                v_scale=v_scale, alibi_slopes=alibi_slopes,
                                min_pos=min_pos)
